"""Wasm binary decoder: bytes → `module.Module`.

Follows the core-spec binary grammar (sections 1–11, LEB128 integers).
Instruction bodies are decoded eagerly into flat (opcode, imm) lists —
the same representation `ModuleBuilder` emits — so validation and
execution never re-touch raw bytes.  Unknown opcodes, truncated
sections, and malformed LEB encodings raise `WasmFormatError`
deterministically (a hostile module must fail identically on every
node; reference analogue: Wasmi's parse errors surfacing as
SCE_WASM_VM errors via rust/src/contract.rs).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from .module import (BLOCK, BLOCK_EMPTY, BR, BR_IF, BR_TABLE, CALL,
                     CALL_INDIRECT, Code, DATA_DROP, ELSE, END, F32, F64,
                     F32_CONST, F64_CONST, FC_PREFIX, FUNCREF, FuncType,
                     GLOBAL_GET, GLOBAL_SET, Global, I32, I32_CONST, I64,
                     I64_CONST, IF, Import, Export, LOCAL_GET, LOCAL_SET,
                     LOCAL_TEE, LOOP, MEMARG_OPS, MEMORY_COPY, MEMORY_FILL,
                     MEMORY_GROW, MEMORY_INIT, MEMORY_SIZE, Module,
                     WasmFormatError)

_KNOWN_OPS = set()
_KNOWN_OPS.update([0x00, 0x01, 0x02, 0x03, 0x04, 0x05, 0x0B, 0x0C, 0x0D,
                   0x0E, 0x0F, 0x10, 0x11, 0x1A, 0x1B])
_KNOWN_OPS.update(range(0x20, 0x25))         # variable
_KNOWN_OPS.update(range(0x28, 0x41))         # memory + size/grow
_KNOWN_OPS.update(range(0x41, 0x45))         # consts
_KNOWN_OPS.update(range(0x45, 0xC5))         # numeric + conversions + extN
_KNOWN_OPS.add(FC_PREFIX)                    # bulk-memory / trunc_sat


class Reader:
    __slots__ = ("buf", "pos", "end")

    def __init__(self, buf: bytes, pos: int = 0, end: Optional[int] = None):
        self.buf = buf
        self.pos = pos
        self.end = len(buf) if end is None else end

    def eof(self) -> bool:
        return self.pos >= self.end

    def byte(self) -> int:
        if self.pos >= self.end:
            raise WasmFormatError("unexpected end of section")
        b = self.buf[self.pos]
        self.pos += 1
        return b

    def bytes(self, n: int) -> bytes:
        if self.pos + n > self.end:
            raise WasmFormatError("unexpected end of section")
        b = self.buf[self.pos:self.pos + n]
        self.pos += n
        return b

    def u32(self) -> int:
        return self._leb_u(32)

    def u64(self) -> int:
        return self._leb_u(64)

    def _leb_u(self, bits: int) -> int:
        result = shift = 0
        while True:
            b = self.byte()
            result |= (b & 0x7F) << shift
            shift += 7
            if not (b & 0x80):
                break
            if shift >= bits + 7:
                raise WasmFormatError("LEB128 too long")
        if result >= 1 << bits:
            raise WasmFormatError("LEB128 out of range")
        return result

    def s_leb(self, bits: int) -> int:
        result = shift = 0
        while True:
            b = self.byte()
            result |= (b & 0x7F) << shift
            shift += 7
            if not (b & 0x80):
                if b & 0x40 and shift < bits + 7:
                    result -= 1 << shift
                break
            if shift >= bits + 7:
                raise WasmFormatError("LEB128 too long")
        if not (-(1 << (bits - 1)) <= result < 1 << bits):
            # s33 blocktype / i32 / i64 ranges checked by caller context
            raise WasmFormatError("signed LEB128 out of range")
        return result

    def name(self) -> str:
        n = self.u32()
        try:
            return self.bytes(n).decode("utf-8")
        except UnicodeDecodeError as e:
            raise WasmFormatError(f"bad utf-8 name: {e}")

    def valtype(self) -> int:
        t = self.byte()
        if t not in (I32, I64, F32, F64):
            raise WasmFormatError(f"bad value type 0x{t:02x}")
        return t

    def limits(self) -> Tuple[int, Optional[int]]:
        flag = self.byte()
        if flag == 0x00:
            return self.u32(), None
        if flag == 0x01:
            mn = self.u32()
            mx = self.u32()
            if mx < mn:
                raise WasmFormatError("limits max < min")
            return mn, mx
        raise WasmFormatError(f"bad limits flag 0x{flag:02x}")


def _decode_blocktype(r: Reader) -> int:
    """Empty (0x40), valtype, or s33 type index."""
    if r.pos >= r.end:
        raise WasmFormatError("unexpected end of section")
    b = r.buf[r.pos]
    if b in (BLOCK_EMPTY, I32, I64, F32, F64):
        r.pos += 1
        return b
    idx = r.s_leb(33)
    if idx < 0:
        raise WasmFormatError("bad block type")
    return idx


def decode_expr(r: Reader, stop_at_else: bool = False
                ) -> List[Tuple[int, object]]:
    """Decode instructions until the matching END (depth-tracked)."""
    instrs: List[Tuple[int, object]] = []
    depth = 0
    while True:
        op = r.byte()
        if op not in _KNOWN_OPS:
            raise WasmFormatError(f"unknown opcode 0x{op:02x}")
        imm: object = None
        if op in (BLOCK, LOOP, IF):
            imm = _decode_blocktype(r)
            depth += 1
        elif op == ELSE:
            pass
        elif op == END:
            if depth == 0:
                instrs.append((op, None))
                return instrs
            depth -= 1
        elif op in (BR, BR_IF, CALL, LOCAL_GET, LOCAL_SET, LOCAL_TEE,
                    GLOBAL_GET, GLOBAL_SET):
            imm = r.u32()
        elif op == CALL_INDIRECT:
            imm = r.u32()
            if r.byte() != 0x00:
                raise WasmFormatError("call_indirect: table index must be 0")
        elif op == BR_TABLE:
            n = r.u32()
            targets = [r.u32() for _ in range(n)]
            imm = (targets, r.u32())
        elif op in MEMARG_OPS:
            imm = (r.u32(), r.u32())        # align, offset
        elif op in (MEMORY_SIZE, MEMORY_GROW):
            if r.byte() != 0x00:
                raise WasmFormatError("memory index must be 0")
        elif op == I32_CONST:
            imm = r.s_leb(32) & 0xFFFFFFFF
        elif op == I64_CONST:
            imm = r.s_leb(64) & 0xFFFFFFFFFFFFFFFF
        elif op == F32_CONST:
            imm = r.bytes(4)
        elif op == F64_CONST:
            imm = r.bytes(8)
        elif op == FC_PREFIX:
            sub = r.u32()
            if sub > 0x0B:      # OR-ing larger subs would alias onto
                raise WasmFormatError(   # valid opcodes (e.g. 0x408)
                    f"unknown 0xFC opcode {sub}")
            op = 0xFC00 | sub
            if sub <= 7:                     # trunc_sat: float family;
                imm = None                   # validator rejects it
            elif op == MEMORY_INIT:
                imm = r.u32()                # data segment index
                if r.byte() != 0x00:
                    raise WasmFormatError("memory.init: memidx must be 0")
            elif op == DATA_DROP:
                imm = r.u32()
            elif op == MEMORY_COPY:
                if r.byte() != 0x00 or r.byte() != 0x00:
                    raise WasmFormatError("memory.copy: memidx must be 0")
            elif op == MEMORY_FILL:
                if r.byte() != 0x00:
                    raise WasmFormatError("memory.fill: memidx must be 0")
            else:
                raise WasmFormatError(f"unknown 0xFC opcode {sub}")
        instrs.append((op, imm))


def _decode_const_expr(r: Reader, want: int = I32) -> int:
    """Constant initializer: a single iNN.const (of type `want`) + END."""
    op = r.byte()
    if op == I32_CONST:
        v = r.s_leb(32) & 0xFFFFFFFF
    elif op == I64_CONST:
        v = r.s_leb(64) & 0xFFFFFFFFFFFFFFFF
    else:
        raise WasmFormatError(
            f"unsupported constant initializer opcode 0x{op:02x}")
    if r.byte() != END:
        raise WasmFormatError("constant expression must be a single const")
    if (I32_CONST if want == I32 else I64_CONST) != op:
        raise WasmFormatError("constant initializer type mismatch")
    return v


def decode_module(data: bytes) -> Module:
    if data[:4] != b"\x00asm":
        raise WasmFormatError("bad magic")
    if data[4:8] != b"\x01\x00\x00\x00":
        raise WasmFormatError("unsupported wasm version")
    m = Module()
    r = Reader(data, 8)
    last_sid = 0
    func_count = 0
    while not r.eof():
        sid = r.byte()
        size = r.u32()
        body = Reader(data, r.pos, r.pos + size)
        if body.end > len(data):
            raise WasmFormatError("section extends past end of module")
        r.pos += size
        if sid == 0:                       # custom section: skipped
            continue
        if sid > 12:
            raise WasmFormatError(f"unknown section id {sid}")
        # bulk-memory's data-count section (12) sorts between element (9)
        # and code (10) in the spec's required ordering
        order = sid if sid != 12 else 9.5
        last_order = last_sid if last_sid != 12 else 9.5
        if order <= last_order:
            raise WasmFormatError(f"out-of-order section id {sid}")
        last_sid = sid

        if sid == 1:
            for _ in range(body.u32()):
                if body.byte() != 0x60:
                    raise WasmFormatError("bad functype tag")
                params = [body.valtype() for _ in range(body.u32())]
                results = [body.valtype() for _ in range(body.u32())]
                m.types.append(FuncType(params, results))
        elif sid == 2:
            for _ in range(body.u32()):
                mod = body.name()
                name = body.name()
                kind = body.byte()
                if kind == 0x00:
                    desc: object = body.u32()
                elif kind == 0x01:
                    if body.byte() != FUNCREF:
                        raise WasmFormatError("bad table elemtype")
                    desc = body.limits()
                elif kind == 0x02:
                    desc = body.limits()
                elif kind == 0x03:
                    desc = (body.valtype(), body.byte() == 1)
                else:
                    raise WasmFormatError(f"bad import kind {kind}")
                m.imports.append(Import(mod, name, kind, desc))
        elif sid == 3:
            func_count = body.u32()
            m.funcs = [body.u32() for _ in range(func_count)]
        elif sid == 4:
            n = body.u32()
            if n > 1:
                raise WasmFormatError("at most one table")
            if n:
                if body.byte() != FUNCREF:
                    raise WasmFormatError("bad table elemtype")
                m.table_limits = body.limits()
        elif sid == 5:
            n = body.u32()
            if n > 1:
                raise WasmFormatError("at most one memory")
            if n:
                m.mem_limits = body.limits()
        elif sid == 6:
            for _ in range(body.u32()):
                vt = body.valtype()
                mut = body.byte() == 1
                init = _decode_const_expr(body, want=vt)
                m.globals.append(Global(vt, mut, init))
        elif sid == 7:
            seen = set()
            for _ in range(body.u32()):
                name = body.name()
                if name in seen:
                    raise WasmFormatError(f"duplicate export {name!r}")
                seen.add(name)
                kind = body.byte()
                if kind > 3:
                    raise WasmFormatError(f"bad export kind {kind}")
                m.exports.append(Export(name, kind, body.u32()))
        elif sid == 8:
            m.start = body.u32()
        elif sid == 9:
            for _ in range(body.u32()):
                if body.u32() != 0:
                    raise WasmFormatError("table index must be 0")
                off = _decode_const_expr(body)
                m.elements.append(
                    (off, [body.u32() for _ in range(body.u32())]))
        elif sid == 10:
            n = body.u32()
            if n != func_count:
                raise WasmFormatError(
                    "code section count != function section count")
            for _ in range(n):
                sz = body.u32()
                fr = Reader(data, body.pos, body.pos + sz)
                body.pos += sz
                locals_: List[int] = []
                for _ in range(fr.u32()):
                    cnt = fr.u32()
                    vt = fr.valtype()
                    if cnt > 100_000 or len(locals_) + cnt > 100_000:
                        raise WasmFormatError("too many locals")
                    locals_.extend([vt] * cnt)
                instrs = decode_expr(fr)
                if not fr.eof():
                    raise WasmFormatError("trailing bytes in function body")
                m.codes.append(Code(locals_, instrs))
        elif sid == 11:
            for _ in range(body.u32()):
                flag = body.u32()
                if flag == 0:              # active, memory 0
                    off: Optional[int] = _decode_const_expr(body)
                elif flag == 1:            # passive (bulk-memory)
                    off = None
                elif flag == 2:            # active with explicit memidx
                    if body.u32() != 0:
                        raise WasmFormatError("memory index must be 0")
                    off = _decode_const_expr(body)
                else:
                    raise WasmFormatError(f"bad data segment flag {flag}")
                payload = body.bytes(body.u32())
                m.data.append((off, payload))
        elif sid == 12:
            m.data_count = body.u32()
        if not body.eof():
            raise WasmFormatError(f"trailing bytes in section {sid}")
    if func_count and len(m.codes) != func_count:
        raise WasmFormatError("missing code section")
    if m.data_count is not None and len(m.data) != m.data_count:
        raise WasmFormatError(
            "data count section disagrees with data section")
    return m
