"""Metered wasm interpreter.

Execution model: each function body (already decoded to flat
(opcode, imm) lists) gets a one-time jump-map pass resolving
block/loop/if→else/end targets; the run loop then uses a label stack
(target pc, arity, operand-stack height) for branches — the standard
structured-control interpretation, no bytecode re-scanning at branch
time.

Determinism & metering: every instruction consumes one fuel unit
against a `meter` (the Soroban budget adapter); fuel is reconciled at
host-call boundaries so the budget observes instruction costs and host
costs in program order.  Exhaustion, div-by-zero, OOB memory access,
indirect-call mismatch, unreachable, and call-depth overflow all raise
`WasmTrap` with a stable kind string — hostile or buggy contract code
must fail identically on every node (reference analogue: Wasmi traps
mapped to SCE_WASM_VM / SCE_BUDGET in soroban-env-host).

Values are Python ints held in unsigned canonical form (i32 in
[0,2^32), i64 in [0,2^64)); signed operators reinterpret at use.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

from .module import (BLOCK, BR, BR_IF, BR_TABLE, CALL, CALL_INDIRECT,
                     Code, DATA_DROP, DROP, ELSE, END, GLOBAL_GET,
                     GLOBAL_SET, I32, I32_CONST, I64, I64_CONST, IF,
                     LOCAL_GET, LOCAL_SET, LOCAL_TEE, LOOP, MEMORY_COPY,
                     MEMORY_FILL, MEMORY_GROW, MEMORY_INIT, MEMORY_SIZE,
                     Module, NOP, PAGE_SIZE, RETURN, SELECT, UNREACHABLE,
                     FuncType)
from .validate import MAX_MEMORY_PAGES

M32 = 0xFFFFFFFF
M64 = 0xFFFFFFFFFFFFFFFF


class WasmTrap(Exception):
    """Deterministic runtime trap."""

    def __init__(self, kind: str, msg: str = ""):
        super().__init__(f"wasm trap: {kind}" + (f" ({msg})" if msg else ""))
        self.kind = kind


class HostFunc:
    """An imported function provided by the embedder."""
    __slots__ = ("params", "results", "fn")

    def __init__(self, params: List[int], results: List[int], fn: Callable):
        self.params = list(params)
        self.results = list(results)
        self.fn = fn

    @property
    def type(self) -> FuncType:
        return FuncType(self.params, self.results)


class _NullMeter:
    def flush(self, executed: int) -> int:
        return 1 << 30


def _s32(v: int) -> int:
    return v - 0x100000000 if v & 0x80000000 else v


def _s64(v: int) -> int:
    return v - 0x10000000000000000 if v & 0x8000000000000000 else v


def _clz(v: int, bits: int) -> int:
    return bits - v.bit_length() if v else bits


def _ctz(v: int, bits: int) -> int:
    return (v & -v).bit_length() - 1 if v else bits


def _jump_map(code: Code) -> Dict[int, Tuple[Optional[int], int]]:
    """instr index of BLOCK/LOOP/IF → (else_idx or None, end_idx)."""
    jumps: Dict[int, Tuple[Optional[int], int]] = {}
    stack: List[int] = []
    elses: Dict[int, int] = {}
    for i, (op, _imm) in enumerate(code.instrs):
        if op in (BLOCK, LOOP, IF):
            stack.append(i)
        elif op == ELSE:
            elses[stack[-1]] = i
        elif op == END and stack:
            start = stack.pop()
            jumps[start] = (elses.get(start), i)
    return jumps


class _Label:
    __slots__ = ("target", "arity", "height", "is_loop")

    def __init__(self, target: int, arity: int, height: int, is_loop: bool):
        self.target = target
        self.arity = arity
        self.height = height
        self.is_loop = is_loop


class Instance:
    """One instantiated module.

    imports: {(module, name): HostFunc}; only function imports are
    supported (memory/table/global imports are outside the profile —
    contracts own their memory, as in the reference host).
    meter: object with flush(executed:int) -> remaining:int; called with
    the instruction count executed since the previous flush, returns how
    many more instructions may run (0 → out-of-fuel trap).
    """

    def __init__(self, module: Module,
                 imports: Optional[Dict[Tuple[str, str], HostFunc]] = None,
                 meter=None, max_call_depth: int = 64):
        self.m = module
        self.meter = meter or _NullMeter()
        self.max_call_depth = max_call_depth
        self._depth = 0
        self._allow = 0          # instructions allowed before next flush
        self._pending = 0        # instructions executed since last flush

        self.host_funcs: List[HostFunc] = []
        imports = imports or {}
        for im in module.imports:
            if im.kind != 0:
                raise WasmTrap("link", f"unsupported import kind {im.kind}")
            hf = imports.get((im.module, im.name))
            if hf is None:
                raise WasmTrap(
                    "link", f"missing import {im.module}.{im.name}")
            if hf.type != module.types[im.desc]:
                raise WasmTrap(
                    "link", f"import type mismatch {im.module}.{im.name}")
            self.host_funcs.append(hf)

        self.memory = bytearray()
        self.mem_max = 0
        if module.mem_limits is not None:
            mn, mx = module.mem_limits
            self.memory = bytearray(mn * PAGE_SIZE)
            self.mem_max = min(mx if mx is not None else MAX_MEMORY_PAGES,
                               MAX_MEMORY_PAGES)
        # active segments initialize memory then drop; passive segments
        # stay live for memory.init until data.drop empties them
        self.data_segs: List[bytes] = []
        for off, payload in module.data:
            if off is None:
                self.data_segs.append(payload)
                continue
            if off + len(payload) > len(self.memory):
                raise WasmTrap("oob", "data segment out of bounds")
            self.memory[off:off + len(payload)] = payload
            self.data_segs.append(b"")

        self.globals: List[int] = [g.init for g in module.globals]

        self.table: List[Optional[int]] = []
        if module.table_limits is not None:
            self.table = [None] * module.table_limits[0]
        for off, idxs in module.elements:
            if off + len(idxs) > len(self.table):
                raise WasmTrap("oob", "element segment out of bounds")
            for j, fidx in enumerate(idxs):
                self.table[off + j] = fidx

        for c in module.codes:        # resolved once, cached on the Module
            if c.jumps is None:
                c.jumps = _jump_map(c)
        self._jumps: List[Dict[int, Tuple[Optional[int], int]]] = [
            c.jumps for c in module.codes]
        self._exports = module.export_map()

        if module.start is not None:
            self._enter()
            try:
                self._call(module.start, [])
            finally:
                self._exit()

    # ------------------------------------------------------------- metering --
    def _enter(self):
        self._allow = self.meter.flush(0)
        self._pending = 0

    def _exit(self):
        self.meter.flush(self._pending)
        self._pending = 0

    def _refuel(self):
        self._allow = self.meter.flush(self._pending)
        self._pending = 0
        if self._allow <= 0:
            raise WasmTrap("fuel", "instruction budget exhausted")

    # -------------------------------------------------------------- invoke --
    def invoke(self, name: str, args: List[int]) -> List[int]:
        exp = self._exports.get(name)
        if exp is None or exp.kind != 0:
            raise WasmTrap("link", f"no exported function {name!r}")
        ft = self.m.func_type(exp.index)
        if len(args) != len(ft.params):
            raise WasmTrap("type", f"{name} expects {len(ft.params)} args")
        self._enter()
        try:
            return self._call(exp.index, list(args))
        finally:
            self._exit()

    # ---------------------------------------------------------- the engine --
    def _call(self, funcidx: int, args: List[int]) -> List[int]:
        nimp = len(self.host_funcs)
        if funcidx < nimp:
            hf = self.host_funcs[funcidx]
            # reconcile fuel so the budget sees costs in program order
            self.meter.flush(self._pending)
            self._pending = 0
            res = hf.fn(self, *args)
            self._allow = self.meter.flush(0)
            if self._allow <= 0:
                raise WasmTrap("fuel", "instruction budget exhausted")
            if not hf.results:
                return []
            return [res & (M32 if hf.results[0] == I32 else M64)]

        self._depth += 1
        if self._depth > self.max_call_depth:
            self._depth -= 1
            raise WasmTrap("stack", "call depth exceeded")
        try:
            lidx = funcidx - nimp
            code = self.m.codes[lidx]
            ft = self.m.types[self.m.funcs[lidx]]
            locals_ = args + [0] * len(code.locals)
            return self._run(code, self._jumps[lidx], locals_,
                             len(ft.results))
        finally:
            self._depth -= 1

    def _run(self, code: Code, jumps, locals_: List[int],
             result_arity: int) -> List[int]:
        instrs = code.instrs
        n = len(instrs)
        stack: List[int] = []
        labels: List[_Label] = [_Label(n, result_arity, 0, False)]
        allow = self._allow
        pending = self._pending
        mem = self.memory
        pc = 0
        while pc < n:
            if pending >= allow:
                self._pending = pending
                self._refuel()
                allow = self._allow
                pending = 0
            pending += 1

            op, imm = instrs[pc]
            pc += 1

            if op == LOCAL_GET:
                stack.append(locals_[imm])
            elif op == I32_CONST or op == I64_CONST:
                stack.append(imm)
            elif op == LOCAL_SET:
                locals_[imm] = stack.pop()
            elif op == LOCAL_TEE:
                locals_[imm] = stack[-1]
            elif 0x45 <= op <= 0xC4:
                try:
                    self._numeric(op, stack)
                except WasmTrap:
                    # in-frame trap: charge the instructions executed in
                    # this stretch before propagating (callee frames and
                    # _refuel account for themselves)
                    self._allow, self._pending = allow, pending
                    raise
            elif op == BLOCK or op == LOOP:
                arity = self._block_arity(imm, op == LOOP)
                _else, endi = jumps[pc - 1]
                if op == LOOP:
                    labels.append(_Label(pc, arity, len(stack), True))
                else:
                    labels.append(_Label(endi + 1, arity,
                                         len(stack), False))
            elif op == IF:
                cond = stack.pop()
                arity = self._block_arity(imm, False)
                elsei, endi = jumps[pc - 1]
                labels.append(_Label(endi + 1, arity, len(stack), False))
                if not cond:
                    pc = (elsei + 1) if elsei is not None else endi
                    if elsei is None:
                        pass  # run END: pops the label
            elif op == ELSE:
                # end of the taken then-branch: jump to the matching END
                lab = labels[-1]
                pc = lab.target - 1        # the END instruction
            elif op == END:
                labels.pop()
            elif op == BR or op == BR_IF or op == BR_TABLE:
                if op == BR_IF:
                    if not stack.pop():
                        continue
                    depth = imm
                elif op == BR:
                    depth = imm
                else:
                    targets, default = imm
                    i = stack.pop()
                    depth = targets[i] if i < len(targets) else default
                idx = len(labels) - 1 - depth
                lab = labels[idx]
                if lab.arity:
                    vals = stack[-lab.arity:]
                    del stack[lab.height:]
                    stack.extend(vals)
                else:
                    del stack[lab.height:]
                if lab.is_loop:
                    del labels[idx + 1:]
                else:
                    del labels[idx:]
                pc = lab.target
            elif op == RETURN:
                break
            elif op == CALL:
                self._allow, self._pending = allow, pending
                ft = self.m.func_type(imm)
                nargs = len(ft.params)
                args = stack[len(stack) - nargs:] if nargs else []
                if nargs:
                    del stack[len(stack) - nargs:]
                stack.extend(self._call(imm, args))
                allow, pending = self._allow, self._pending
                mem = self.memory
            elif op == CALL_INDIRECT:
                self._allow, self._pending = allow, pending
                elem = stack.pop()
                if elem >= len(self.table) or self.table[elem] is None:
                    raise WasmTrap("indirect", "undefined table element")
                fidx = self.table[elem]
                if self.m.func_type(fidx) != self.m.types[imm]:
                    raise WasmTrap("indirect", "signature mismatch")
                ft = self.m.types[imm]
                nargs = len(ft.params)
                args = stack[len(stack) - nargs:] if nargs else []
                if nargs:
                    del stack[len(stack) - nargs:]
                stack.extend(self._call(fidx, args))
                allow, pending = self._allow, self._pending
                mem = self.memory
            elif op == DROP:
                stack.pop()
            elif op == SELECT:
                c = stack.pop()
                b = stack.pop()
                a = stack.pop()
                stack.append(a if c else b)
            elif op == GLOBAL_GET:
                stack.append(self.globals[imm])
            elif op == GLOBAL_SET:
                self.globals[imm] = stack.pop()
            elif 0x28 <= op <= 0x3E:
                try:
                    self._memop(op, imm, stack, mem)
                except WasmTrap:
                    self._allow, self._pending = allow, pending
                    raise
            elif op == MEMORY_SIZE:
                stack.append(len(mem) // PAGE_SIZE)
            elif op == MEMORY_GROW:
                delta = stack.pop()
                cur = len(mem) // PAGE_SIZE
                if delta > self.mem_max or cur + delta > self.mem_max:
                    stack.append(M32)
                else:
                    self.memory.extend(bytes(delta * PAGE_SIZE))
                    mem = self.memory
                    stack.append(cur)
            elif op == NOP:
                pass
            elif op >= 0xFC00:               # bulk-memory family
                cnt = 0                      # byte count (top of stack)
                if op != DATA_DROP:
                    cnt = stack.pop()
                if op == MEMORY_COPY:
                    s = stack.pop()
                    d = stack.pop()
                    if d + cnt > len(mem) or s + cnt > len(mem):
                        self._allow, self._pending = allow, pending
                        raise WasmTrap("oob", "memory.copy")
                    if cnt:
                        # snapshot source: memmove semantics on overlap
                        mem[d:d + cnt] = bytes(mem[s:s + cnt])
                elif op == MEMORY_FILL:
                    v = stack.pop()
                    d = stack.pop()
                    if d + cnt > len(mem):
                        self._allow, self._pending = allow, pending
                        raise WasmTrap("oob", "memory.fill")
                    if cnt:
                        mem[d:d + cnt] = bytes((v & 0xFF,)) * cnt
                elif op == MEMORY_INIT:
                    s = stack.pop()
                    d = stack.pop()
                    seg = self.data_segs[imm]
                    if s + cnt > len(seg) or d + cnt > len(mem):
                        self._allow, self._pending = allow, pending
                        raise WasmTrap("oob", "memory.init")
                    if cnt:
                        mem[d:d + cnt] = seg[s:s + cnt]
                elif op == DATA_DROP:
                    self.data_segs[imm] = b""
                else:   # pragma: no cover - validator excludes the rest
                    self._allow, self._pending = allow, pending
                    raise WasmTrap("type", f"unexecutable 0x{op:04x}")
                # bulk ops move cnt bytes for one opcode: meter the work
                pending += cnt >> 3
            elif op == UNREACHABLE:
                self._allow, self._pending = allow, pending
                raise WasmTrap("unreachable")
            else:  # pragma: no cover - validator excludes anything else
                self._allow, self._pending = allow, pending
                raise WasmTrap("type", f"unexecutable opcode 0x{op:02x}")

        self._allow, self._pending = allow, pending
        if result_arity:
            return stack[-result_arity:]
        return []

    def _block_arity(self, bt, is_loop: bool) -> int:
        if bt == 0x40:
            return 0
        if bt in (I32, I64):
            return 0 if is_loop else 1
        ft = self.m.types[bt]
        return len(ft.params) if is_loop else len(ft.results)

    # ------------------------------------------------------------- numeric --
    def _numeric(self, op: int, stack: List[int]) -> None:
        if op == 0x45:                       # i32.eqz
            stack[-1] = 1 if stack[-1] == 0 else 0
            return
        if op == 0x50:                       # i64.eqz
            stack[-1] = 1 if stack[-1] == 0 else 0
            return
        if 0x46 <= op <= 0x4F:               # i32 comparisons
            b = stack.pop()
            a = stack[-1]
            if op == 0x46:
                r = a == b
            elif op == 0x47:
                r = a != b
            elif op == 0x48:
                r = _s32(a) < _s32(b)
            elif op == 0x49:
                r = a < b
            elif op == 0x4A:
                r = _s32(a) > _s32(b)
            elif op == 0x4B:
                r = a > b
            elif op == 0x4C:
                r = _s32(a) <= _s32(b)
            elif op == 0x4D:
                r = a <= b
            elif op == 0x4E:
                r = _s32(a) >= _s32(b)
            else:
                r = a >= b
            stack[-1] = 1 if r else 0
            return
        if 0x51 <= op <= 0x5A:               # i64 comparisons
            b = stack.pop()
            a = stack[-1]
            if op == 0x51:
                r = a == b
            elif op == 0x52:
                r = a != b
            elif op == 0x53:
                r = _s64(a) < _s64(b)
            elif op == 0x54:
                r = a < b
            elif op == 0x55:
                r = _s64(a) > _s64(b)
            elif op == 0x56:
                r = a > b
            elif op == 0x57:
                r = _s64(a) <= _s64(b)
            elif op == 0x58:
                r = a <= b
            elif op == 0x59:
                r = _s64(a) >= _s64(b)
            else:
                r = a >= b
            stack[-1] = 1 if r else 0
            return
        if 0x67 <= op <= 0x78:               # i32 arithmetic
            if op == 0x67:
                stack[-1] = _clz(stack[-1], 32)
                return
            if op == 0x68:
                stack[-1] = _ctz(stack[-1], 32)
                return
            if op == 0x69:
                stack[-1] = bin(stack[-1]).count("1")
                return
            b = stack.pop()
            a = stack[-1]
            if op == 0x6A:
                r = (a + b) & M32
            elif op == 0x6B:
                r = (a - b) & M32
            elif op == 0x6C:
                r = (a * b) & M32
            elif op == 0x6D:                 # div_s
                if b == 0:
                    raise WasmTrap("div0", "i32.div_s")
                sa, sb = _s32(a), _s32(b)
                q = abs(sa) // abs(sb)
                if (sa < 0) != (sb < 0):
                    q = -q
                if q > 0x7FFFFFFF:
                    raise WasmTrap("overflow", "i32.div_s")
                r = q & M32
            elif op == 0x6E:                 # div_u
                if b == 0:
                    raise WasmTrap("div0", "i32.div_u")
                r = a // b
            elif op == 0x6F:                 # rem_s
                if b == 0:
                    raise WasmTrap("div0", "i32.rem_s")
                sa, sb = _s32(a), _s32(b)
                r = (abs(sa) % abs(sb))
                if sa < 0:
                    r = -r
                r &= M32
            elif op == 0x70:                 # rem_u
                if b == 0:
                    raise WasmTrap("div0", "i32.rem_u")
                r = a % b
            elif op == 0x71:
                r = a & b
            elif op == 0x72:
                r = a | b
            elif op == 0x73:
                r = a ^ b
            elif op == 0x74:
                r = (a << (b % 32)) & M32
            elif op == 0x75:
                r = (_s32(a) >> (b % 32)) & M32
            elif op == 0x76:
                r = a >> (b % 32)
            elif op == 0x77:
                k = b % 32
                r = ((a << k) | (a >> (32 - k))) & M32 if k else a
            else:                            # rotr
                k = b % 32
                r = ((a >> k) | (a << (32 - k))) & M32 if k else a
            stack[-1] = r
            return
        if 0x79 <= op <= 0x8A:               # i64 arithmetic
            if op == 0x79:
                stack[-1] = _clz(stack[-1], 64)
                return
            if op == 0x7A:
                stack[-1] = _ctz(stack[-1], 64)
                return
            if op == 0x7B:
                stack[-1] = bin(stack[-1]).count("1")
                return
            b = stack.pop()
            a = stack[-1]
            if op == 0x7C:
                r = (a + b) & M64
            elif op == 0x7D:
                r = (a - b) & M64
            elif op == 0x7E:
                r = (a * b) & M64
            elif op == 0x7F:                 # div_s
                if b == 0:
                    raise WasmTrap("div0", "i64.div_s")
                sa, sb = _s64(a), _s64(b)
                q = abs(sa) // abs(sb)
                if (sa < 0) != (sb < 0):
                    q = -q
                if q > 0x7FFFFFFFFFFFFFFF:
                    raise WasmTrap("overflow", "i64.div_s")
                r = q & M64
            elif op == 0x80:
                if b == 0:
                    raise WasmTrap("div0", "i64.div_u")
                r = a // b
            elif op == 0x81:
                if b == 0:
                    raise WasmTrap("div0", "i64.rem_s")
                sa, sb = _s64(a), _s64(b)
                r = (abs(sa) % abs(sb))
                if sa < 0:
                    r = -r
                r &= M64
            elif op == 0x82:
                if b == 0:
                    raise WasmTrap("div0", "i64.rem_u")
                r = a % b
            elif op == 0x83:
                r = a & b
            elif op == 0x84:
                r = a | b
            elif op == 0x85:
                r = a ^ b
            elif op == 0x86:
                r = (a << (b % 64)) & M64
            elif op == 0x87:
                r = (_s64(a) >> (b % 64)) & M64
            elif op == 0x88:
                r = a >> (b % 64)
            elif op == 0x89:
                k = b % 64
                r = ((a << k) | (a >> (64 - k))) & M64 if k else a
            else:
                k = b % 64
                r = ((a >> k) | (a << (64 - k))) & M64 if k else a
            stack[-1] = r
            return
        if op == 0xA7:                       # i32.wrap_i64
            stack[-1] &= M32
            return
        if op == 0xAC:                       # i64.extend_i32_s
            stack[-1] = _s32(stack[-1]) & M64
            return
        if op == 0xAD:                       # i64.extend_i32_u
            return
        if op == 0xC0:                       # i32.extend8_s
            v = stack[-1] & 0xFF
            stack[-1] = (v - 0x100 if v & 0x80 else v) & M32
            return
        if op == 0xC1:
            v = stack[-1] & 0xFFFF
            stack[-1] = (v - 0x10000 if v & 0x8000 else v) & M32
            return
        if op == 0xC2:
            v = stack[-1] & 0xFF
            stack[-1] = (v - 0x100 if v & 0x80 else v) & M64
            return
        if op == 0xC3:
            v = stack[-1] & 0xFFFF
            stack[-1] = (v - 0x10000 if v & 0x8000 else v) & M64
            return
        if op == 0xC4:
            v = stack[-1] & M32
            stack[-1] = (v - 0x100000000 if v & 0x80000000 else v) & M64
            return
        raise WasmTrap("type", f"unexecutable opcode 0x{op:02x}")

    # -------------------------------------------------------------- memory --
    def _memop(self, op: int, imm, stack: List[int], mem: bytearray) -> None:
        offset = imm[1]
        if 0x28 <= op <= 0x35:               # loads
            addr = stack.pop() + offset
            if op == 0x28:
                w, signed, mask = 4, False, M32
            elif op == 0x29:
                w, signed, mask = 8, False, M64
            elif op == 0x2C:
                w, signed, mask = 1, True, M32
            elif op == 0x2D:
                w, signed, mask = 1, False, M32
            elif op == 0x2E:
                w, signed, mask = 2, True, M32
            elif op == 0x2F:
                w, signed, mask = 2, False, M32
            elif op == 0x30:
                w, signed, mask = 1, True, M64
            elif op == 0x31:
                w, signed, mask = 1, False, M64
            elif op == 0x32:
                w, signed, mask = 2, True, M64
            elif op == 0x33:
                w, signed, mask = 2, False, M64
            elif op == 0x34:
                w, signed, mask = 4, True, M64
            else:
                w, signed, mask = 4, False, M64
            if addr + w > len(mem):
                raise WasmTrap("oob", "memory load")
            v = int.from_bytes(mem[addr:addr + w], "little")
            if signed and v & (1 << (w * 8 - 1)):
                v -= 1 << (w * 8)
            stack.append(v & mask)
        else:                                # stores
            v = stack.pop()
            addr = stack.pop() + offset
            if op == 0x36:
                w = 4
            elif op == 0x37:
                w = 8
            elif op == 0x3A:
                w = 1
            elif op == 0x3B:
                w = 2
            elif op == 0x3C:
                w = 1
            elif op == 0x3D:
                w = 2
            else:
                w = 4 if op == 0x3E else 8
            if addr + w > len(mem):
                raise WasmTrap("oob", "memory store")
            mem[addr:addr + w] = (v & ((1 << (w * 8)) - 1)).to_bytes(
                w, "little")
