"""Wasm validation: the core-spec type-checking algorithm (appendix
"Validation Algorithm": value/control stacks with unreachable
polymorphism), restricted to the deterministic integer profile.

Rejections beyond the spec (profile restrictions, mirroring the
reference host's determinism requirements — soroban-env rejects float
code the same way):
  - any float value type or opcode (F32/F64);
  - memory/table limits above hard caps (hostile-module resource guard);
  - multi-value block/function results (MVP arity).
"""

from __future__ import annotations

from typing import List, Optional

from .module import (BLOCK, BLOCK_EMPTY, BR, BR_IF, BR_TABLE, CALL,
                     CALL_INDIRECT, DROP, ELSE, END, F32, F64, FLOAT_OPS,
                     FuncType, GLOBAL_GET, GLOBAL_SET, I32, I32_CONST,
                     I32_EQZ, I32_EXTEND8_S, I32_EXTEND16_S, I32_WRAP_I64,
                     I32_ARITH, I32_CMP, I64, I64_CONST, I64_EQZ,
                     I64_EXTEND_I32_S, I64_EXTEND_I32_U, I64_EXTEND8_S,
                     I64_EXTEND16_S, I64_EXTEND32_S, I64_ARITH, I64_CMP,
                     IF, LOCAL_GET, LOCAL_SET, LOCAL_TEE, LOOP,
                     DATA_DROP, MEMORY_COPY, MEMORY_FILL, MEMORY_INIT,
                     MEMORY_GROW, MEMORY_SIZE, Module, NOP, RETURN,
                     SELECT, UNREACHABLE,
                     I32_LOAD, I64_LOAD, I32_LOAD8_S, I32_LOAD8_U,
                     I32_LOAD16_S, I32_LOAD16_U, I64_LOAD8_S, I64_LOAD8_U,
                     I64_LOAD16_S, I64_LOAD16_U, I64_LOAD32_S,
                     I64_LOAD32_U, I32_STORE, I64_STORE, I32_STORE8,
                     I32_STORE16, I64_STORE8, I64_STORE16, I64_STORE32)

MAX_MEMORY_PAGES = 64          # 4 MiB — contract-scale cap
MAX_TABLE_SIZE = 4096
MAX_CALL_PARAMS = 32

UNKNOWN = 0  # bottom type for unreachable polymorphism


class WasmValidationError(Exception):
    pass


_LOADS = {
    I32_LOAD: (I32, 4), I64_LOAD: (I64, 8),
    I32_LOAD8_S: (I32, 1), I32_LOAD8_U: (I32, 1),
    I32_LOAD16_S: (I32, 2), I32_LOAD16_U: (I32, 2),
    I64_LOAD8_S: (I64, 1), I64_LOAD8_U: (I64, 1),
    I64_LOAD16_S: (I64, 2), I64_LOAD16_U: (I64, 2),
    I64_LOAD32_S: (I64, 4), I64_LOAD32_U: (I64, 4),
}
_STORES = {
    I32_STORE: (I32, 4), I64_STORE: (I64, 8),
    I32_STORE8: (I32, 1), I32_STORE16: (I32, 2),
    I64_STORE8: (I64, 1), I64_STORE16: (I64, 2), I64_STORE32: (I64, 4),
}


class _Ctrl:
    __slots__ = ("opcode", "start_types", "end_types", "height",
                 "unreachable")

    def __init__(self, opcode, start_types, end_types, height):
        self.opcode = opcode
        self.start_types = start_types
        self.end_types = end_types
        self.height = height
        self.unreachable = False


class _Checker:
    def __init__(self, module: Module, func_type: FuncType,
                 locals_: List[int]):
        self.m = module
        self.ft = func_type
        self.locals = list(func_type.params) + list(locals_)
        self.vals: List[int] = []
        self.ctrls: List[_Ctrl] = []

    # --- stack ops (spec algorithm) --------------------------------------
    def push(self, t: int):
        self.vals.append(t)

    def pop(self, expect: Optional[int] = None) -> int:
        frame = self.ctrls[-1]
        if len(self.vals) == frame.height:
            if frame.unreachable:
                return expect if expect is not None else UNKNOWN
            raise WasmValidationError("value stack underflow")
        t = self.vals.pop()
        if expect is not None and t != UNKNOWN and t != expect:
            raise WasmValidationError(
                f"type mismatch: expected {expect:#x} got {t:#x}")
        return t

    def push_ctrl(self, opcode: int, start, end):
        self.ctrls.append(_Ctrl(opcode, start, end, len(self.vals)))
        for t in start:
            self.push(t)

    def pop_ctrl(self) -> _Ctrl:
        if not self.ctrls:
            raise WasmValidationError("control stack underflow")
        frame = self.ctrls[-1]
        for t in reversed(frame.end_types):
            self.pop(t)
        if len(self.vals) != frame.height:
            raise WasmValidationError("values left on stack at block end")
        self.ctrls.pop()
        return frame

    def label_types(self, frame: _Ctrl):
        return frame.start_types if frame.opcode == LOOP else frame.end_types

    def unreachable_(self):
        frame = self.ctrls[-1]
        del self.vals[frame.height:]
        frame.unreachable = True

    # --- block types ------------------------------------------------------
    def blocktype(self, bt) -> FuncType:
        if bt == BLOCK_EMPTY:
            return FuncType([], [])
        if bt in (I32, I64):
            return FuncType([], [bt])
        if bt in (F32, F64):
            raise WasmValidationError("float block type")
        if not isinstance(bt, int) or bt >= len(self.m.types):
            raise WasmValidationError("bad block type index")
        ft = self.m.types[bt]
        if ft.params:
            # MVP arity: blocks take no parameters (the interpreter's
            # label-height model assumes it; multi-value is post-MVP)
            raise WasmValidationError("block parameters not supported")
        return ft

    # --- main loop --------------------------------------------------------
    def check(self, instrs) -> None:
        self.push_ctrl(BLOCK, [], list(self.ft.results))
        for op, imm in instrs:
            self.instr(op, imm)
        if self.ctrls:
            raise WasmValidationError("unterminated control structure")

    def instr(self, op: int, imm) -> None:
        if op in FLOAT_OPS:
            raise WasmValidationError(
                f"float opcode 0x{op:02x} rejected (deterministic profile)")
        if op == UNREACHABLE:
            self.unreachable_()
        elif op == NOP:
            pass
        elif op in (BLOCK, LOOP):
            ft = self.blocktype(imm)
            for t in reversed(ft.params):
                self.pop(t)
            self.push_ctrl(op, list(ft.params), list(ft.results))
        elif op == IF:
            ft = self.blocktype(imm)
            self.pop(I32)
            for t in reversed(ft.params):
                self.pop(t)
            self.push_ctrl(IF, list(ft.params), list(ft.results))
        elif op == ELSE:
            frame = self.pop_ctrl()
            if frame.opcode != IF:
                raise WasmValidationError("else without if")
            self.push_ctrl(ELSE, frame.start_types, frame.end_types)
        elif op == END:
            frame = self.pop_ctrl()
            if frame.opcode == IF and frame.start_types != frame.end_types:
                raise WasmValidationError(
                    "if without else must have matching param/result types")
            for t in frame.end_types:
                self.push(t)
        elif op == BR:
            frame = self._label(imm)
            for t in reversed(self.label_types(frame)):
                self.pop(t)
            self.unreachable_()
        elif op == BR_IF:
            frame = self._label(imm)
            self.pop(I32)
            lts = self.label_types(frame)
            for t in reversed(lts):
                self.pop(t)
            for t in lts:
                self.push(t)
        elif op == BR_TABLE:
            targets, default = imm
            self.pop(I32)
            dts = self.label_types(self._label(default))
            for d in targets:
                ts = self.label_types(self._label(d))
                if len(ts) != len(dts):
                    raise WasmValidationError("br_table arity mismatch")
            for t in reversed(dts):
                self.pop(t)
            self.unreachable_()
        elif op == RETURN:
            for t in reversed(self.ft.results):
                self.pop(t)
            self.unreachable_()
        elif op == CALL:
            nfuncs = self.m.num_imported_funcs() + len(self.m.funcs)
            if imm >= nfuncs:
                raise WasmValidationError(f"call to unknown function {imm}")
            ft = self.m.func_type(imm)
            for t in reversed(ft.params):
                self.pop(t)
            for t in ft.results:
                self.push(t)
        elif op == CALL_INDIRECT:
            if self.m.table_limits is None:
                raise WasmValidationError("call_indirect without a table")
            if imm >= len(self.m.types):
                raise WasmValidationError("call_indirect: bad type index")
            ft = self.m.types[imm]
            self.pop(I32)
            for t in reversed(ft.params):
                self.pop(t)
            for t in ft.results:
                self.push(t)
        elif op == DROP:
            self.pop()
        elif op == SELECT:
            self.pop(I32)
            t1 = self.pop()
            t2 = self.pop()
            if t1 != UNKNOWN and t2 != UNKNOWN and t1 != t2:
                raise WasmValidationError("select operand type mismatch")
            self.push(t1 if t1 != UNKNOWN else t2)
        elif op in (LOCAL_GET, LOCAL_SET, LOCAL_TEE):
            if imm >= len(self.locals):
                raise WasmValidationError(f"unknown local {imm}")
            t = self.locals[imm]
            if op == LOCAL_GET:
                self.push(t)
            elif op == LOCAL_SET:
                self.pop(t)
            else:
                self.pop(t)
                self.push(t)
        elif op in (GLOBAL_GET, GLOBAL_SET):
            g = self._global(imm)
            if op == GLOBAL_GET:
                self.push(g[0])
            else:
                if not g[1]:
                    raise WasmValidationError(
                        f"global {imm} is immutable")
                self.pop(g[0])
        elif op in _LOADS:
            self._need_memory()
            t, width = _LOADS[op]
            self._check_align(imm, width)
            self.pop(I32)
            self.push(t)
        elif op in _STORES:
            self._need_memory()
            t, width = _STORES[op]
            self._check_align(imm, width)
            self.pop(t)
            self.pop(I32)
        elif op == MEMORY_SIZE:
            self._need_memory()
            self.push(I32)
        elif op == MEMORY_GROW:
            self._need_memory()
            self.pop(I32)
            self.push(I32)
        elif op == I32_CONST:
            self.push(I32)
        elif op == I64_CONST:
            self.push(I64)
        elif op == I32_EQZ:
            self.pop(I32)
            self.push(I32)
        elif op == I64_EQZ:
            self.pop(I64)
            self.push(I32)
        elif op in I32_CMP:
            self.pop(I32)
            self.pop(I32)
            self.push(I32)
        elif op in I64_CMP:
            self.pop(I64)
            self.pop(I64)
            self.push(I32)
        elif op in I32_ARITH:
            if op in range(0x67, 0x6A):          # clz/ctz/popcnt: unary
                self.pop(I32)
            else:
                self.pop(I32)
                self.pop(I32)
            self.push(I32)
        elif op in I64_ARITH:
            if op in range(0x79, 0x7C):
                self.pop(I64)
            else:
                self.pop(I64)
                self.pop(I64)
            self.push(I64)
        elif op == I32_WRAP_I64:
            self.pop(I64)
            self.push(I32)
        elif op in (I64_EXTEND_I32_S, I64_EXTEND_I32_U):
            self.pop(I32)
            self.push(I64)
        elif op in (I32_EXTEND8_S, I32_EXTEND16_S):
            self.pop(I32)
            self.push(I32)
        elif op in (I64_EXTEND8_S, I64_EXTEND16_S, I64_EXTEND32_S):
            self.pop(I64)
            self.push(I64)
        elif op in (MEMORY_COPY, MEMORY_FILL):
            self._need_memory()
            self.pop(I32)
            self.pop(I32)
            self.pop(I32)
        elif op in (MEMORY_INIT, DATA_DROP):
            # spec: these require the data-count section so single-pass
            # validators can bound the data index space
            if self.m.data_count is None:
                raise WasmValidationError(
                    "memory.init/data.drop without data count section")
            if imm >= self.m.data_count:
                raise WasmValidationError(
                    f"data segment index {imm} out of range")
            if op == MEMORY_INIT:
                self._need_memory()
                self.pop(I32)
                self.pop(I32)
                self.pop(I32)
        else:
            raise WasmValidationError(f"unsupported opcode 0x{op:02x}")

    def _label(self, depth: int) -> _Ctrl:
        if depth >= len(self.ctrls):
            raise WasmValidationError(f"branch depth {depth} out of range")
        return self.ctrls[-1 - depth]

    def _global(self, idx: int):
        gi = [im.desc for im in self.m.imports if im.kind == 3]
        n_imported = len(gi)
        if idx < n_imported:
            return gi[idx]
        idx -= n_imported
        if idx >= len(self.m.globals):
            raise WasmValidationError("unknown global")
        g = self.m.globals[idx]
        return (g.valtype, g.mutable)

    def _need_memory(self):
        has_mem = self.m.mem_limits is not None or any(
            im.kind == 2 for im in self.m.imports)
        if not has_mem:
            raise WasmValidationError("memory instruction without memory")

    @staticmethod
    def _check_align(memarg, width: int):
        align, _offset = memarg
        # compare exponents — never materialize 1 << attacker_align
        if align > width.bit_length() - 1:
            raise WasmValidationError("alignment larger than natural")


def validate_module(m: Module) -> None:
    """Whole-module validation; raises WasmValidationError."""
    # types: reject floats anywhere
    for ft in m.types:
        for t in list(ft.params) + list(ft.results):
            if t in (F32, F64):
                raise WasmValidationError(
                    "float value type rejected (deterministic profile)")
        if len(ft.results) > 1:
            raise WasmValidationError("multi-value results not supported")
        if len(ft.params) > MAX_CALL_PARAMS:
            raise WasmValidationError("too many parameters")
    for im in m.imports:
        if im.kind == 0 and im.desc >= len(m.types):
            raise WasmValidationError("import type index out of range")
        if im.kind == 3 and im.desc[0] in (F32, F64):
            raise WasmValidationError("float global rejected")
    for t in m.funcs:
        if t >= len(m.types):
            raise WasmValidationError("function type index out of range")
    if len(m.codes) != len(m.funcs):
        raise WasmValidationError("code/function section size mismatch")
    if m.mem_limits is not None:
        mn, mx = m.mem_limits
        if mn > MAX_MEMORY_PAGES or (mx or 0) > MAX_MEMORY_PAGES:
            raise WasmValidationError(
                f"memory limits exceed cap of {MAX_MEMORY_PAGES} pages")
    if m.table_limits is not None:
        mn, mx = m.table_limits
        if mn > MAX_TABLE_SIZE or (mx or 0) > MAX_TABLE_SIZE:
            raise WasmValidationError("table limits exceed cap")
    for g in m.globals:
        if g.valtype in (F32, F64):
            raise WasmValidationError("float global rejected")
    nfuncs = m.num_imported_funcs() + len(m.funcs)
    for e in m.exports:
        if e.kind == 0 and e.index >= nfuncs:
            raise WasmValidationError(f"export {e.name!r}: bad func index")
        if e.kind == 2 and m.mem_limits is None and not any(
                im.kind == 2 for im in m.imports):
            raise WasmValidationError("export of missing memory")
        if e.kind == 3 and e.index >= len(m.globals) + sum(
                1 for im in m.imports if im.kind == 3):
            raise WasmValidationError("export of missing global")
    if m.start is not None:
        if m.start >= nfuncs:
            raise WasmValidationError("start function index out of range")
        ft = m.func_type(m.start)
        if ft.params or ft.results:
            raise WasmValidationError("start function must be [] -> []")
    for _off, idxs in m.elements:
        if m.table_limits is None:
            raise WasmValidationError("element segment without table")
        for i in idxs:
            if i >= nfuncs:
                raise WasmValidationError("element func index out of range")
    if any(off is not None for off, _ in m.data) \
            and m.mem_limits is None and not any(
            im.kind == 2 for im in m.imports):
        raise WasmValidationError("data segment without memory")
    # function bodies
    for i, code in enumerate(m.codes):
        for vt in code.locals:
            if vt in (F32, F64):
                raise WasmValidationError("float local rejected")
        ft = m.types[m.funcs[i]]
        _Checker(m, ft, code.locals).check(code.instrs)
