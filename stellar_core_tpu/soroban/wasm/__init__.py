"""Deterministic metered WebAssembly (MVP integer profile) for the
Soroban execution seam.

Reference: the reference node executes contracts through soroban-env-host's
Wasmi interpreter (src/rust/src/contract.rs:261-340, rust/Cargo.toml:27-56).
This package is a native re-implementation of that role: a wasm binary
decoder (`decode`), a spec-shaped validator (`validate`), and a
budget-metered interpreter (`interp`), plus an in-repo module builder /
assembler (`module.ModuleBuilder`) used by tests and by the scvm→wasm
compiler.

Profile: wasm core MVP restricted to the deterministic integer subset —
i32/i64 values, full control flow, linear memory, tables/call_indirect,
globals, plus the sign-extension operators. Floating point types and
opcodes are rejected at validation, exactly as the reference's host
rejects floats for consensus determinism.
"""

from .module import (I32, I64, FuncType, Module, ModuleBuilder,
                     WasmFormatError)
from .decode import decode_module
from .validate import validate_module, WasmValidationError
from .interp import Instance, WasmTrap, HostFunc

__all__ = [
    "I32", "I64", "FuncType", "Module", "ModuleBuilder",
    "WasmFormatError", "decode_module", "validate_module",
    "WasmValidationError", "Instance", "WasmTrap", "HostFunc",
]
