"""In-repo hand-assembled contract against the REAL soroban-env ABI.

This is the deliverable VERDICT r02 #2 asks for: a contract that uses
the actual host interface SDK-built binaries use (single-letter import
modules, positional short names, tagged i64 Vals — see env_abi.py for
the recovered ground truth) rather than the bespoke long-name module,
assembled instruction-by-instruction with the in-repo ModuleBuilder.
It mirrors the counter scenario matrix the scvm/wasm twins run
(tests/test_soroban.py) — increment / get_count / auth_bump / boom —
and adds bulk-memory coverage (passive data segment + memory.init /
memory.fill / memory.copy / data.drop, the 0xFC opcodes real SDK
output emits).

Reference behavior anchors: example_add_i32.wasm's tag-check/trap
idioms (decode = ``v & 15`` / ``v >> 4``; overflow → ``unreachable``)
and example_contract_data.wasm's put/del flow returning ``i64.const 5``.
"""

from __future__ import annotations

from .env_abi import TAG_MASK, TAG_U32, VAL_VOID, symbol_to_val
from .wasm.module import (BLOCK_EMPTY, I32, I64, ModuleBuilder)

# opcodes used below (spec byte values)
I64_EQ, I64_NE, I64_EQZ = 0x51, 0x52, 0x50
I64_ADD, I64_AND, I64_OR = 0x7C, 0x83, 0x84
I64_SHL, I64_SHR_U = 0x86, 0x88
I32_EQZ = 0x45

KEY_COUNT = symbol_to_val(b"count")
KEY_HASH = symbol_to_val(b"hash")
SYM_BUMPED = symbol_to_val(b"bumped")


def u32val(n: int) -> int:
    return (n << 4) | TAG_U32


def build_env_counter() -> bytes:
    b = ModuleBuilder()
    # imports — every one resolves in env_abi.env_host_table
    put_ = b.import_func("l", "_", [I64, I64], [I64])
    has_ = b.import_func("l", "0", [I64], [I64])
    get_ = b.import_func("l", "1", [I64], [I64])
    b.import_func("l", "2", [I64], [I64])            # del (unused, linked)
    event_ = b.import_func("x", "0", [I64, I64], [I64])
    fail_ = b.import_func("x", "3", [I64], [I64])
    vec_new_ = b.import_func("v", "_", [], [I64])
    vec_push_ = b.import_func("v", "0", [I64, I64], [I64])
    auth_ = b.import_func("a", "_", [I64], [I64])
    bytes_new_ = b.import_func("b", "_", [I64, I64], [I64])
    sha256_ = b.import_func("c", "_", [I64], [I64])

    b.add_memory(1)
    seg = b.add_passive_data(b"hello-soroban")       # 13 bytes

    from .env_abi import VAL_TRUE

    # increment() -> U32Val — same semantics as the twins' counter
    fi, f = b.add_func([], [I64], locals_=[I64])
    (f.i64_const(KEY_COUNT).call(has_)
      .i64_const(VAL_TRUE).op(I64_EQ)
      .if_(I64)
      .i64_const(KEY_COUNT).call(get_)
      .else_()
      .i64_const(u32val(0))
      .end()
      .local_set(0)
      # tag must be U32 (the reference contracts' `v & 15` idiom)
      .local_get(0).i64_const(TAG_MASK).op(I64_AND)
      .i64_const(TAG_U32).op(I64_NE)
      .if_(BLOCK_EMPTY).unreachable().end()
      # new = payload + 1; overflow past u32 traps (add_i32 idiom)
      .local_get(0).i64_const(4).op(I64_SHR_U)
      .i64_const(1).op(I64_ADD).local_set(0)
      .local_get(0).i64_const(32).op(I64_SHR_U).op(I64_EQZ)
      .op(I32_EQZ).if_(BLOCK_EMPTY).unreachable().end()
      # re-tag, store, return
      .local_get(0).i64_const(4).op(I64_SHL)
      .i64_const(TAG_U32).op(I64_OR).local_set(0)
      .i64_const(KEY_COUNT).local_get(0).call(put_).drop()
      .local_get(0))
    b.export_func("increment", fi)

    # get_count() -> stored Val (host errors if missing)
    fi, f = b.add_func([], [I64])
    f.i64_const(KEY_COUNT).call(get_)
    b.export_func("get_count", fi)

    # auth_bump(addr) -> Void: require_auth + event (twins' scenario)
    fi, f = b.add_func([I64], [I64])
    (f.local_get(0).call(auth_).drop()
      .call(vec_new_)
      .i64_const(SYM_BUMPED).call(vec_push_)
      .i64_const(u32val(1))
      .call(event_).drop()
      .i64_const(VAL_VOID))
    b.export_func("auth_bump", fi)

    # boom() -> trap through fail_with_error
    fi, f = b.add_func([], [I64])
    f.i64_const(u32val(0)).call(fail_)
    b.export_func("boom", fi)

    # copy_hash() -> Void: bulk-memory exercise. memory.init the
    # passive segment, memory.fill 3 bytes of 'a', memory.copy to
    # double the buffer, hash the 32 bytes, store under symbol "hash"
    # (stored so the test can assert through the ledger).
    fi, f = b.add_func([], [I64])
    (f.i32_const(0).i32_const(0).i32_const(13).memory_init(seg)
      .i32_const(13).i32_const(0x61).i32_const(3).memory_fill()
      .i32_const(16).i32_const(0).i32_const(16).memory_copy()
      .i64_const(KEY_HASH)
      .i64_const(u32val(0)).i64_const(u32val(32)).call(bytes_new_)
      .call(sha256_)
      .call(put_).drop()
      .i64_const(VAL_VOID))
    b.export_func("copy_hash", fi)

    # drop_then_init() — data.drop empties the segment; the following
    # memory.init must trap out-of-bounds
    fi, f = b.add_func([], [I64])
    (f.data_drop(seg)
      .i32_const(0).i32_const(0).i32_const(1).memory_init(seg)
      .i64_const(VAL_VOID))
    b.export_func("drop_then_init", fi)

    # SDK-style interface marker
    fi, f = b.add_func([], [])
    f.nop()
    b.export_func("_", fi)

    return b.encode()


# what copy_hash() hashes: segment + 3×'a', duplicated
COPY_HASH_PREIMAGE = (b"hello-soroban" + b"aaa") * 2


def build_env_toolkit() -> bytes:
    """Second env-ABI contract: exercises the extended host surface —
    maps (sorted, immutable), i128 pieces, strings from linear memory,
    and verify_sig_ed25519 — end-to-end through hand-assembled wasm.
    Every assertion the contract makes uses the reference binaries'
    trap idiom (condition → unreachable)."""
    b = ModuleBuilder()
    map_new_ = b.import_func("m", "_", [], [I64])
    map_put_ = b.import_func("m", "0", [I64, I64, I64], [I64])
    map_get_ = b.import_func("m", "1", [I64, I64], [I64])
    map_has_ = b.import_func("m", "2", [I64, I64], [I64])
    map_del_ = b.import_func("m", "3", [I64, I64], [I64])
    map_len_ = b.import_func("m", "4", [I64], [I64])
    from_i128_ = b.import_func("i", "3", [I64, I64], [I64])
    i128_lo_ = b.import_func("i", "4", [I64], [I64])
    i128_hi_ = b.import_func("i", "5", [I64], [I64])
    str_new_ = b.import_func("s", "_", [I64, I64], [I64])
    str_len_ = b.import_func("s", "0", [I64], [I64])
    verify_ = b.import_func("c", "0", [I64, I64, I64], [I64])

    b.add_memory(1)
    seg = b.add_passive_data(b"toolkit")             # 7 bytes

    from .env_abi import VAL_TRUE, VAL_VOID as _VOID

    sym_a = symbol_to_val(b"a")
    sym_b = symbol_to_val(b"b")

    # map_demo() -> U32Val: put a=1, b=2, a=9 (replace), check has(b),
    # del b, check get(a)==9, return len (==1)
    fi, f = b.add_func([], [I64], locals_=[I64])
    (f.call(map_new_)
      .i64_const(sym_a).i64_const(u32val(1)).call(map_put_)
      .i64_const(sym_b).i64_const(u32val(2)).call(map_put_)
      .i64_const(sym_a).i64_const(u32val(9)).call(map_put_)
      .local_set(0)
      .local_get(0).i64_const(sym_b).call(map_has_)
      .i64_const(VAL_TRUE).op(I64_NE)
      .if_(BLOCK_EMPTY).unreachable().end()
      .local_get(0).i64_const(sym_b).call(map_del_).local_set(0)
      .local_get(0).i64_const(sym_a).call(map_get_)
      .i64_const(u32val(9)).op(I64_NE)
      .if_(BLOCK_EMPTY).unreachable().end()
      .local_get(0).call(map_len_))
    b.export_func("map_demo", fi)

    # i128_demo() -> U32Val(42): pieces (hi=1, lo=42) roundtrip
    fi, f = b.add_func([], [I64], locals_=[I64])
    (f.i64_const(1).i64_const(42).call(from_i128_).local_set(0)
      .local_get(0).call(i128_hi_)
      .i64_const(1).op(I64_NE)
      .if_(BLOCK_EMPTY).unreachable().end()
      .local_get(0).call(i128_lo_)
      .i64_const(4).op(I64_SHL).i64_const(TAG_U32).op(I64_OR))
    b.export_func("i128_demo", fi)

    # str_demo() -> U32Val(7): string from linear memory, length
    fi, f = b.add_func([], [I64])
    (f.i32_const(0).i32_const(0).i32_const(7).memory_init(seg)
      .i64_const(u32val(0)).i64_const(u32val(7)).call(str_new_)
      .call(str_len_))
    b.export_func("str_demo", fi)

    # sig_demo(pub, msg, sig) -> Void; host traps on a bad signature
    fi, f = b.add_func([I64, I64, I64], [I64])
    (f.local_get(0).local_get(1).local_get(2).call(verify_).drop()
      .i64_const(_VOID))
    b.export_func("sig_demo", fi)

    # SDK-style interface marker
    fi, f = b.add_func([], [])
    f.nop()
    b.export_func("_", fi)

    return b.encode()


def build_env_u256() -> bytes:
    """Third env-ABI contract: computes with the 256-bit host families
    end-to-end (VERDICT r04 #5). `u256_demo` returns a Vec of
    [((1,2,3,4)+(0,0,0,5)) << 7  as U256,  (-2^255) >> 3  as I256];
    `div_zero` must trap through the host's checked division."""
    b = ModuleBuilder()
    from_u256_ = b.import_func("i", "B", [I64] * 4, [I64])
    u256_add_ = b.import_func("i", "P", [I64, I64], [I64])
    u256_div_ = b.import_func("i", "S", [I64, I64], [I64])
    u256_shl_ = b.import_func("i", "V", [I64, I64], [I64])
    from_i256_ = b.import_func("i", "I", [I64] * 4, [I64])
    i256_shr_ = b.import_func("i", "e", [I64, I64], [I64])
    vec_new_ = b.import_func("v", "_", [], [I64])
    vec_push_ = b.import_func("v", "0", [I64, I64], [I64])

    fi, f = b.add_func([], [I64], locals_=[I64])
    (f.i64_const(1).i64_const(2).i64_const(3).i64_const(4)
      .call(from_u256_)
      .i64_const(0).i64_const(0).i64_const(0).i64_const(5)
      .call(from_u256_)
      .call(u256_add_)
      .i64_const(u32val(7)).call(u256_shl_)
      .local_set(0)
      .call(vec_new_)
      .local_get(0).call(vec_push_)
      .i64_const(-(1 << 63)).i64_const(0).i64_const(0).i64_const(0)
      .call(from_i256_)
      .i64_const(u32val(3)).call(i256_shr_)
      .call(vec_push_))
    b.export_func("u256_demo", fi)

    fi, f = b.add_func([], [I64])
    (f.i64_const(0).i64_const(0).i64_const(0).i64_const(9)
      .call(from_u256_)
      .i64_const(0).i64_const(0).i64_const(0).i64_const(0)
      .call(from_u256_)
      .call(u256_div_))
    b.export_func("div_zero", fi)

    fi, f = b.add_func([], [])
    f.nop()
    b.export_func("_", fi)
    return b.encode()


def build_write_bytes() -> bytes:
    """The settings-upgrade helper contract (reference:
    scripts/soroban-settings' write_upgrade_bytes contract): `write(b)`
    stores b as a TEMPORARY contract-data entry keyed by
    Bytes(sha256(b)) — exactly the shape ConfigUpgradeSetFrame looks up
    when a LEDGER_UPGRADE_CONFIG key is voted."""
    b = ModuleBuilder()
    put_t_ = b.import_func("l", "5", [I64, I64, I64], [I64])
    sha256_ = b.import_func("c", "_", [I64], [I64])

    fi, f = b.add_func([I64], [I64])
    (f.local_get(0).call(sha256_)       # key = Bytes(sha256(v))
      .local_get(0)                     # value = v
      .i64_const(u32val(0))             # StorageType 0 = TEMPORARY
      .call(put_t_).drop()
      .i64_const(VAL_VOID))
    b.export_func("write", fi)

    fi, f = b.add_func([], [])
    f.nop()
    b.export_func("_", fi)
    return b.encode()
