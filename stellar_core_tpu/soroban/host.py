"""The smart-contract host: storage, budget, auth, and execution.

Reference: the `e2e_invoke::invoke_host_function` surface of
soroban-env-host used by the reference node (rust/src/contract.rs:261-456
adapts it; transactions/InvokeHostFunctionOpFrame.cpp:364 drives it).
This is a native re-implementation of that surface: footprint-gated
storage over LedgerTxn, deterministic instruction budgeting, TTL
liveness, nonce-consuming address authorization (signatures routed
through the node's verifier seam — north-star config #4), contract
events, and host-function dispatch.

Execution is pluggable through `VM_REGISTRY`: production wasm engines
register by code prefix. The built-in `SCVM` interpreter executes a
deterministic SCVal-encoded expression language (each exported function
is one metered expression tree) — it exists so every protocol mechanism
around execution (footprints, rent, TTL, auth, events, budget, fees) is
fully exercised end-to-end; swapping in a wasm engine touches only this
seam.
"""

from __future__ import annotations

import struct
from typing import Callable, Dict, List, Optional, Tuple

from ..crypto.sha import sha256
from ..util.logging import get_logger
from ..xdr.contract import (ContractCodeEntry, ContractDataDurability,
                            ContractDataEntry, ContractEvent,
                            ContractExecutable, ContractExecutableType,
                            ContractIDPreimageType, HostFunction,
                            HostFunctionType, LedgerFootprint, SCAddress,
                            SCAddressType, SCContractInstance, SCError,
                            SCErrorCode, SCErrorType, SCMapEntry,
                            SCNonceKey, SCVal, SCValType, TTLEntry,
                            _ContractEventBody, _ContractEventV0)
from ..xdr.ledger_entries import (LedgerEntry, LedgerEntryType, LedgerKey,
                                  _LedgerEntryData, _LedgerEntryExt)
from ..xdr.types import EnvelopeType, ExtensionPoint, PublicKey

log = get_logger("Tx")


class HostError(Exception):
    def __init__(self, error_type: SCErrorType, code_or_msg="", code=None):
        super().__init__(f"{error_type.name}: {code_or_msg}")
        self.error_type = error_type
        self.code = code


class BudgetExceeded(HostError):
    def __init__(self):
        super().__init__(SCErrorType.SCE_BUDGET, "instruction limit")


class Budget:
    """Deterministic instruction metering (reference: soroban budget)."""

    def __init__(self, instruction_limit: int):
        self.limit = instruction_limit
        self.used = 0

    def charge(self, n: int) -> None:
        self.used += n
        if self.used > self.limit:
            raise BudgetExceeded()


# cost constants (deterministic; roughly scaled to the reference's
# per-operation cost types). Module-level values are the CURRENT
# protocol's calibration; the host classes carry them as class
# attributes so the protocol-prev host can override (see
# host_for_protocol below).
COST_BASE_INSTRUCTION = 100
COST_STORAGE_OP = 5000
COST_PER_BYTE = 10
COST_CALL = 10000
COST_VERIFY_SIG = 400_000


def contract_id_from_preimage(network_id: bytes, preimage) -> bytes:
    """SHA256(HashIDPreimage ENVELOPE_TYPE_CONTRACT_ID) (reference:
    Stellar-transaction.x HashIDPreimage)."""
    return sha256(network_id
                  + struct.pack(">i", EnvelopeType.ENVELOPE_TYPE_CONTRACT_ID)
                  + preimage.to_bytes())


def soroban_auth_payload(network_id: bytes, nonce: int,
                         expiration: int, invocation) -> bytes:
    """Signature payload for address credentials (reference:
    HashIDPreimage ENVELOPE_TYPE_SOROBAN_AUTHORIZATION)."""
    return sha256(
        network_id
        + struct.pack(">i",
                      EnvelopeType.ENVELOPE_TYPE_SOROBAN_AUTHORIZATION)
        + struct.pack(">q", nonce) + struct.pack(">I", expiration)
        + invocation.to_bytes())


def instance_key(contract: SCAddress) -> LedgerKey:
    return LedgerKey.contract_data(
        contract, SCVal(SCValType.SCV_LEDGER_KEY_CONTRACT_INSTANCE),
        ContractDataDurability.PERSISTENT)


def ttl_key_for(key: LedgerKey) -> LedgerKey:
    return LedgerKey.ttl(sha256(key.to_bytes()))


# --- pluggable execution -----------------------------------------------------

# code-prefix -> callable(host, contract_addr, code, fn_name, args) -> SCVal
VM_REGISTRY: Dict[bytes, Callable] = {}


def register_vm(prefix: bytes):
    def deco(fn):
        VM_REGISTRY[prefix] = fn
        return fn
    return deco


class SorobanHost:
    # current-protocol cost calibration (class attrs: the prev host
    # overrides — reference analogue: two complete soroban-env-host
    # versions linked side by side, rust/Cargo.toml:27-56)
    COST_BASE_INSTRUCTION = COST_BASE_INSTRUCTION
    COST_STORAGE_OP = COST_STORAGE_OP
    COST_PER_BYTE = COST_PER_BYTE
    COST_CALL = COST_CALL
    COST_VERIFY_SIG = COST_VERIFY_SIG

    def __init__(self, ltx, header, config, footprint: LedgerFootprint,
                 budget: Budget, network_id: bytes,
                 source_account: PublicKey, verify=None):
        self.ltx = ltx
        self.header = header
        self.config = config
        self.budget = budget
        self.network_id = network_id
        self.source_account = source_account
        self.verify = verify
        self.events: List[ContractEvent] = []
        self.diagnostics: List[tuple] = []   # (msg bytes, [SCVal]) from log
        self.read_bytes = 0
        self.write_bytes = 0
        self.rent_changes: List[dict] = []
        self._ro = {k.to_bytes() for k in footprint.readOnly}
        self._rw = {k.to_bytes() for k in footprint.readWrite}
        self._auth_entries: List = []
        self._authorized_addrs: List[bytes] = []
        self._call_depth = 0
        self._frame_stack: List[bytes] = []   # executing contract addrs
        self._prng_frames = 0

    # ------------------------------------------------------------- storage --
    def _check_footprint(self, key: LedgerKey, write: bool) -> None:
        kb = key.to_bytes()
        if write:
            if kb not in self._rw:
                raise HostError(SCErrorType.SCE_STORAGE,
                                "write outside footprint")
        elif kb not in self._ro and kb not in self._rw:
            raise HostError(SCErrorType.SCE_STORAGE,
                            "read outside footprint")

    def _is_live(self, key: LedgerKey) -> bool:
        ttl_le = self.ltx.load_without_record(ttl_key_for(key))
        if ttl_le is None:
            return False
        return ttl_le.data.value.liveUntilLedgerSeq >= self.header.ledgerSeq

    def load_entry(self, key: LedgerKey,
                   need_live: bool = True) -> Optional[LedgerEntry]:
        self.budget.charge(self.COST_STORAGE_OP)
        self._check_footprint(key, write=False)
        le = self.ltx.load_without_record(key)
        if le is None:
            return None
        size = len(le.to_bytes())
        self.budget.charge(size * self.COST_PER_BYTE)
        self.read_bytes += size
        if need_live and key.disc in (LedgerEntryType.CONTRACT_DATA,
                                      LedgerEntryType.CONTRACT_CODE) \
                and not self._is_live(key):
            raise HostError(SCErrorType.SCE_STORAGE, "entry archived")
        return le

    def put_entry(self, key: LedgerKey, entry: LedgerEntry,
                  durability=ContractDataDurability.PERSISTENT) -> None:
        self.budget.charge(self.COST_STORAGE_OP)
        self._check_footprint(key, write=True)
        size = len(entry.to_bytes())
        self.budget.charge(size * self.COST_PER_BYTE)
        self.write_bytes += size
        entry.lastModifiedLedgerSeq = self.header.ledgerSeq
        old = self.ltx.load(key)
        if old is not None:
            old_size = len(old.to_bytes())
            self.ltx.erase(key)
            self.ltx.create(entry)
        else:
            old_size = 0
            self.ltx.create(entry)
        self._ensure_ttl(key, durability, old_size, size)

    def erase_entry(self, key: LedgerKey) -> None:
        self.budget.charge(self.COST_STORAGE_OP)
        self._check_footprint(key, write=True)
        if self.ltx.load(key) is not None:
            self.ltx.erase(key)
            ttlk = ttl_key_for(key)
            if self.ltx.load(ttlk) is not None:
                self.ltx.erase(ttlk)

    def _ensure_ttl(self, key: LedgerKey, durability, old_size: int,
                    new_size: int) -> None:
        sa = self.config.state_archival
        is_persistent = durability == ContractDataDurability.PERSISTENT
        min_ttl = sa.minPersistentTTL if is_persistent \
            else sa.minTemporaryTTL
        ttlk = ttl_key_for(key)
        ttl_le = self.ltx.load(ttlk)
        target = self.header.ledgerSeq + min_ttl - 1
        if ttl_le is None:
            self.ltx.create(LedgerEntry(
                lastModifiedLedgerSeq=self.header.ledgerSeq,
                data=_LedgerEntryData(
                    LedgerEntryType.TTL,
                    TTLEntry(keyHash=sha256(key.to_bytes()),
                             liveUntilLedgerSeq=target)),
                ext=_LedgerEntryExt(0)))
            self.rent_changes.append({
                "is_persistent": is_persistent,
                "old_size_bytes": old_size, "new_size_bytes": new_size,
                "old_live_until": 0, "new_live_until": target})
        else:
            old_until = ttl_le.data.value.liveUntilLedgerSeq
            if new_size > old_size:
                self.rent_changes.append({
                    "is_persistent": is_persistent,
                    "old_size_bytes": old_size,
                    "new_size_bytes": new_size,
                    "old_live_until": old_until,
                    "new_live_until": old_until})

    def set_ttl(self, key: LedgerKey, live_until: int) -> None:
        """Pin an entry's liveUntil to an exact ledger (clamped to
        maxEntryTTL) — used where the TTL itself carries protocol
        meaning, e.g. SAC allowance expirations and auth nonces.
        Extensions are rent-charged like any other TTL change and the
        entry must sit in the write footprint like any other write."""
        self.budget.charge(self.COST_STORAGE_OP)
        self._check_footprint(key, write=True)
        ttl_le = self.ltx.load(ttl_key_for(key))
        if ttl_le is None:
            raise HostError(SCErrorType.SCE_STORAGE, "no TTL entry",
                            SCErrorCode.SCEC_MISSING_VALUE)
        sa = self.config.state_archival
        cur = ttl_le.data.value.liveUntilLedgerSeq
        new_until = min(live_until, self.header.ledgerSeq + sa.maxEntryTTL)
        if new_until == cur:
            return
        ttl_le.data.value.liveUntilLedgerSeq = new_until
        if new_until > cur:     # extensions pay rent; shrinks refund none
            le = self.ltx.load_without_record(key)
            size = len(le.to_bytes()) if le is not None else 0
            is_persistent = key.disc == LedgerEntryType.CONTRACT_CODE or \
                key.value.durability == ContractDataDurability.PERSISTENT
            self.rent_changes.append({
                "is_persistent": is_persistent,
                "old_size_bytes": size, "new_size_bytes": size,
                "old_live_until": cur, "new_live_until": new_until})

    def extend_entry_ttl(self, key: LedgerKey, threshold: int,
                         extend_to: int) -> None:
        """Host-function TTL extension (reference: the env's
        extend_contract_data_ttl / extend_current_contract_instance...
        host fns; op-level analogue ExtendFootprintTTLOpFrame above):
        when the entry's remaining TTL is <= threshold, raise its
        liveUntil to ledgerSeq + extend_to (clamped to maxEntryTTL);
        no-op when already above the threshold. Archived entries error
        (they need RestoreFootprint)."""
        if threshold > extend_to:
            raise HostError(SCErrorType.SCE_STORAGE,
                            "threshold > extend_to",
                            SCErrorCode.SCEC_INVALID_INPUT)
        self.budget.charge(self.COST_STORAGE_OP)
        self._check_footprint(key, write=False)
        le = self.ltx.load_without_record(key)
        ttlk = ttl_key_for(key)
        # decide on the UNRECORDED snapshot: a recorded load stamps
        # lastModifiedLedgerSeq into the delta, so a no-op extension
        # would still rewrite the TTL entry at commit and diverge the
        # ledger hash from nodes that never saw the attempt
        ttl_snap = self.ltx.load_without_record(ttlk)
        if le is None or ttl_snap is None or \
                ttl_snap.data.value.liveUntilLedgerSeq < self.header.ledgerSeq:
            raise HostError(SCErrorType.SCE_STORAGE,
                            "missing or archived entry",
                            SCErrorCode.SCEC_MISSING_VALUE)
        size = len(le.to_bytes())
        self.budget.charge(size * self.COST_PER_BYTE)
        cur = ttl_snap.data.value.liveUntilLedgerSeq
        if cur - self.header.ledgerSeq > threshold:
            return
        sa = self.config.state_archival
        new_until = self.header.ledgerSeq + min(extend_to, sa.maxEntryTTL)
        if new_until <= cur:
            return
        is_persistent = key.disc == LedgerEntryType.CONTRACT_CODE or \
            key.value.durability == ContractDataDurability.PERSISTENT
        ttl_le = self.ltx.load(ttlk)            # now we really write
        ttl_le.data.value.liveUntilLedgerSeq = new_until
        self.rent_changes.append({
            "is_persistent": is_persistent,
            "old_size_bytes": size, "new_size_bytes": size,
            "old_live_until": cur, "new_live_until": new_until})

    def log_diagnostic(self, msg: bytes, vals) -> None:
        """Diagnostic log sink (reference: the env's
        log_from_linear_memory emits DIAGNOSTIC contract events);
        recorded off the consensus state — never hashed."""
        self.budget.charge(len(msg) + 8 * len(vals))
        self.diagnostics.append((bytes(msg), list(vals)))

    def get_verify(self):
        """The signature-verifier seam shared by address-credential auth
        and the env's verify_sig_ed25519 host fn: the injected verifier
        (prevalidated-batch routing in catchup/herder) or the sync
        default."""
        if self.verify is not None:
            return self.verify
        from ..tx.signature_checker import default_verify
        return default_verify

    def prng_frame_seed(self, contract_bytes: bytes) -> bytes:
        """Per-invocation-frame prng seed: every validator derives the
        identical stream for a given frame, but two frames — a repeated
        cross-contract call in one tx, or two txs in one ledger — get
        distinct streams (the real env subseeds each frame from a base
        prng; same determinism contract)."""
        self._prng_frames += 1
        return sha256(self.network_id +
                      int(self.header.ledgerSeq).to_bytes(4, "big") +
                      contract_bytes +
                      self.source_account.to_bytes() +
                      self._prng_frames.to_bytes(8, "big"))

    # ---------------------------------------------------------------- auth --
    def set_auth_entries(self, entries) -> None:
        self._auth_entries = list(entries)

    def require_auth(self, address: SCAddress) -> None:
        """reference: host's require_auth — source-account credentials
        authorize the tx source implicitly; address credentials carry a
        signature over the nonce'd invocation payload."""
        ab = address.to_bytes()
        if ab in self._authorized_addrs:
            return
        # invoker authorization (reference: the host treats the DIRECT
        # calling contract as authorized for its own address — contract
        # C calling token.transfer(from=C, ..) needs no auth entry)
        if len(self._frame_stack) >= 2 and self._frame_stack[-2] == ab:
            return
        from ..xdr.contract import SorobanCredentialsType
        for entry in self._auth_entries:
            cred = entry.credentials
            if cred.disc == \
                    SorobanCredentialsType.SOROBAN_CREDENTIALS_SOURCE_ACCOUNT:
                if address.disc == SCAddressType.SC_ADDRESS_TYPE_ACCOUNT \
                        and address.value.to_bytes() == \
                        self.source_account.to_bytes():
                    self._authorized_addrs.append(ab)
                    return
            else:
                ac = cred.value
                if ac.address.to_bytes() != ab:
                    continue
                self._verify_address_credentials(entry, ac)
                self._authorized_addrs.append(ab)
                return
        raise HostError(SCErrorType.SCE_AUTH, "no authorization",
                        SCErrorCode.SCEC_INVALID_ACTION)

    def _verify_address_credentials(self, entry, ac) -> None:
        if ac.signatureExpirationLedger < self.header.ledgerSeq:
            raise HostError(SCErrorType.SCE_AUTH, "signature expired")
        if ac.address.disc != SCAddressType.SC_ADDRESS_TYPE_ACCOUNT:
            raise HostError(SCErrorType.SCE_AUTH,
                            "contract-address auth requires __check_auth")
        payload = soroban_auth_payload(
            self.network_id, ac.nonce, ac.signatureExpirationLedger,
            entry.rootInvocation)
        account_raw = bytes(ac.address.value.value)
        sigs = self._extract_signatures(ac.signature)
        if not sigs:
            raise HostError(SCErrorType.SCE_AUTH, "missing signature")
        self.budget.charge(self.COST_VERIFY_SIG * len(sigs))
        verify = self.get_verify()
        for pub, sig in sigs:
            if pub != account_raw:
                raise HostError(SCErrorType.SCE_AUTH,
                                "signer is not the address")
            if not verify(pub, sig, payload):
                raise HostError(SCErrorType.SCE_AUTH, "bad signature")
        self._consume_nonce(ac)

    @staticmethod
    def _extract_signatures(sig_val: SCVal) -> List[Tuple[bytes, bytes]]:
        """Signature SCVal: vec of maps {public_key, signature}
        (reference: the account contract's signature format)."""
        out = []
        vals = []
        if sig_val.disc == SCValType.SCV_VEC and sig_val.value:
            vals = list(sig_val.value)
        elif sig_val.disc == SCValType.SCV_MAP:
            vals = [sig_val]
        for v in vals:
            if v.disc != SCValType.SCV_MAP or not v.value:
                continue
            entry = {}
            for me in v.value:
                if me.key.disc == SCValType.SCV_SYMBOL:
                    entry[bytes(me.key.value)] = me.val
            pk = entry.get(b"public_key")
            sg = entry.get(b"signature")
            # only well-typed byte payloads count; anything else is a
            # malformed signature map and is skipped (the caller then
            # raises the auth error) — never a crash, since this also
            # runs in the untrusted validation path
            if pk is not None and sg is not None \
                    and pk.disc == SCValType.SCV_BYTES \
                    and sg.disc == SCValType.SCV_BYTES:
                out.append((bytes(pk.value), bytes(sg.value)))
        return out

    def _consume_nonce(self, ac) -> None:
        """Replay protection: the nonce entry must not exist yet
        (reference: nonce consumption in soroban auth)."""
        key = LedgerKey.contract_data(
            ac.address,
            SCVal(SCValType.SCV_LEDGER_KEY_NONCE,
                  SCNonceKey(nonce=ac.nonce)),
            ContractDataDurability.TEMPORARY)
        if self.ltx.load_without_record(key) is not None:
            raise HostError(SCErrorType.SCE_AUTH, "nonce already used")
        self.ltx.create(LedgerEntry(
            lastModifiedLedgerSeq=self.header.ledgerSeq,
            data=_LedgerEntryData(
                LedgerEntryType.CONTRACT_DATA,
                ContractDataEntry(
                    ext=ExtensionPoint(0), contract=ac.address,
                    key=SCVal(SCValType.SCV_LEDGER_KEY_NONCE,
                              SCNonceKey(nonce=ac.nonce)),
                    durability=ContractDataDurability.TEMPORARY,
                    val=SCVal(SCValType.SCV_VOID))),
            ext=_LedgerEntryExt(0)))
        ttlk = ttl_key_for(key)
        sa = self.config.state_archival
        self.ltx.create(LedgerEntry(
            lastModifiedLedgerSeq=self.header.ledgerSeq,
            data=_LedgerEntryData(
                LedgerEntryType.TTL,
                TTLEntry(keyHash=sha256(key.to_bytes()),
                         liveUntilLedgerSeq=min(
                             ac.signatureExpirationLedger,
                             self.header.ledgerSeq + sa.maxEntryTTL))),
            ext=_LedgerEntryExt(0)))

    # --------------------------------------------------------------- events --
    def emit_event(self, contract_id: Optional[bytes], topics: List[SCVal],
                   data: SCVal) -> None:
        from ..xdr.contract import ContractEventType
        self.events.append(ContractEvent(
            ext=ExtensionPoint(0), contractID=contract_id,
            type=ContractEventType.CONTRACT,
            body=_ContractEventBody(0, _ContractEventV0(
                topics=topics, data=data))))

    def events_size_bytes(self) -> int:
        return sum(len(e.to_bytes()) for e in self.events)

    # ------------------------------------------------------------- dispatch --
    def invoke_host_function(self, host_fn: HostFunction, auth) -> SCVal:
        self.set_auth_entries(auth)
        t = host_fn.disc
        if t == HostFunctionType.HOST_FUNCTION_TYPE_UPLOAD_CONTRACT_WASM:
            return self._upload_wasm(bytes(host_fn.value))
        if t == HostFunctionType.HOST_FUNCTION_TYPE_CREATE_CONTRACT:
            return self._create_contract(host_fn.value)
        return self._invoke_contract(host_fn.value)

    def _upload_wasm(self, code: bytes) -> SCVal:
        if len(code) > self.config.max_contract_size:
            raise HostError(SCErrorType.SCE_BUDGET, "code too large",
                            SCErrorCode.SCEC_EXCEEDED_LIMIT)
        code_hash = sha256(code)
        key = LedgerKey.contract_code(code_hash)
        existing = self.ltx.load_without_record(key)
        if existing is None:
            self._check_footprint(key, write=True)
            self.budget.charge(self.COST_STORAGE_OP
                               + len(code) * self.COST_PER_BYTE)
            self.write_bytes += len(code)
            self.ltx.create(LedgerEntry(
                lastModifiedLedgerSeq=self.header.ledgerSeq,
                data=_LedgerEntryData(
                    LedgerEntryType.CONTRACT_CODE,
                    ContractCodeEntry(ext=ExtensionPoint(0),
                                      hash=code_hash, code=code)),
                ext=_LedgerEntryExt(0)))
            self._ensure_ttl(key, ContractDataDurability.PERSISTENT, 0,
                             len(code))
        return SCVal(SCValType.SCV_BYTES, code_hash)

    def _create_contract(self, args) -> SCVal:
        preimage = args.contractIDPreimage
        from_asset = preimage.disc == \
            ContractIDPreimageType.CONTRACT_ID_PREIMAGE_FROM_ASSET
        is_sac = args.executable.disc == \
            ContractExecutableType.CONTRACT_EXECUTABLE_STELLAR_ASSET
        # the executable kind is bound to the preimage kind (reference:
        # only the host itself instantiates the SAC, and only for an
        # asset preimage; a wasm executable needs an address preimage)
        if from_asset != is_sac:
            raise HostError(SCErrorType.SCE_CONTEXT,
                            "executable does not match preimage kind",
                            SCErrorCode.SCEC_INVALID_INPUT)
        if not from_asset:
            # creating from an address requires that address's auth;
            # anyone may deploy the SAC for an existing asset. A factory
            # contract deploying from its OWN address needs no auth
            # entry (reference: the host skips require_auth when the
            # deployer address is the currently executing contract)
            addr = preimage.value.address
            if not (self._frame_stack and
                    self._frame_stack[-1] == addr.to_bytes()):
                self.require_auth(addr)
        contract_id = contract_id_from_preimage(self.network_id, preimage)
        addr = SCAddress(SCAddressType.SC_ADDRESS_TYPE_CONTRACT,
                         contract_id)
        key = instance_key(addr)
        if self.ltx.load_without_record(key) is not None:
            raise HostError(SCErrorType.SCE_STORAGE,
                            "contract already exists",
                            SCErrorCode.SCEC_EXISTING_VALUE)
        storage = None
        if is_sac:
            storage = self._sac_instance_storage(preimage.value)
        elif args.executable.disc == \
                ContractExecutableType.CONTRACT_EXECUTABLE_WASM:
            code_key = LedgerKey.contract_code(
                bytes(args.executable.value))
            if self.ltx.load_without_record(code_key) is None:
                raise HostError(SCErrorType.SCE_STORAGE,
                                "wasm not uploaded",
                                SCErrorCode.SCEC_MISSING_VALUE)
        inst = ContractDataEntry(
            ext=ExtensionPoint(0), contract=addr,
            key=SCVal(SCValType.SCV_LEDGER_KEY_CONTRACT_INSTANCE),
            durability=ContractDataDurability.PERSISTENT,
            val=SCVal(SCValType.SCV_CONTRACT_INSTANCE,
                      SCContractInstance(executable=args.executable,
                                         storage=storage)))
        self.put_entry(key, LedgerEntry(
            lastModifiedLedgerSeq=self.header.ledgerSeq,
            data=_LedgerEntryData(LedgerEntryType.CONTRACT_DATA, inst),
            ext=_LedgerEntryExt(0)))
        return SCVal(SCValType.SCV_ADDRESS, addr)

    def _invoke_contract(self, args) -> SCVal:
        return self.call_contract(args.contractAddress,
                                  bytes(args.functionName),
                                  list(args.args))

    def call_contract(self, contract: SCAddress, fn: bytes,
                      args: List[SCVal]) -> SCVal:
        self.budget.charge(self.COST_CALL)
        self._call_depth += 1
        self._frame_stack.append(contract.to_bytes())
        if self._call_depth > 10:
            raise HostError(SCErrorType.SCE_CONTEXT, "call depth")
        try:
            inst_le = self.load_entry(instance_key(contract))
            if inst_le is None:
                raise HostError(SCErrorType.SCE_STORAGE,
                                "no such contract",
                                SCErrorCode.SCEC_MISSING_VALUE)
            inst = inst_le.data.value.val.value
            if inst.executable.disc == \
                    ContractExecutableType.CONTRACT_EXECUTABLE_STELLAR_ASSET:
                return self._invoke_sac(contract, inst, fn, args)
            code_key = LedgerKey.contract_code(
                bytes(inst.executable.value))
            code_le = self.load_entry(code_key)
            if code_le is None:
                raise HostError(SCErrorType.SCE_STORAGE, "missing code",
                                SCErrorCode.SCEC_MISSING_VALUE)
            code = bytes(code_le.data.value.code)
            for prefix, vm in VM_REGISTRY.items():
                if code.startswith(prefix):
                    return vm(self, contract, code, fn, args)
            raise HostError(SCErrorType.SCE_WASM_VM,
                            "no VM for code format")
        finally:
            self._call_depth -= 1
            self._frame_stack.pop()

    # ------------------------------------------- built-in stellar asset SAC --
    def _sac_instance_storage(self, asset):
        """Instance storage for a freshly deployed SAC: the asset it
        wraps and (for issued assets) the admin, initially the issuer."""
        from ..xdr.ledger_entries import AssetType
        entries = [SCMapEntry(
            key=SCVal(SCValType.SCV_SYMBOL, b"Asset"),
            val=SCVal(SCValType.SCV_BYTES, asset.to_bytes()))]
        if asset.disc != AssetType.ASSET_TYPE_NATIVE:
            issuer_addr = SCAddress(SCAddressType.SC_ADDRESS_TYPE_ACCOUNT,
                                    asset.value.issuer)
            entries.append(SCMapEntry(
                key=SCVal(SCValType.SCV_SYMBOL, b"Admin"),
                val=SCVal(SCValType.SCV_ADDRESS, issuer_addr)))
        return entries

    @staticmethod
    def _sac_storage_get(inst, key: bytes):
        for me in (inst.storage or []):
            if me.key.disc == SCValType.SCV_SYMBOL and \
                    bytes(me.key.value) == key:
                return me.val
        return None

    def _invoke_sac(self, contract: SCAddress, inst, fn: bytes,
                    args: List[SCVal]) -> SCVal:
        from ..xdr.ledger_entries import Asset
        from .sac import StellarAssetContract
        asset_val = self._sac_storage_get(inst, b"Asset")
        if asset_val is None:
            raise HostError(SCErrorType.SCE_STORAGE,
                            "SAC instance missing asset",
                            SCErrorCode.SCEC_INTERNAL_ERROR)
        asset = Asset.from_bytes(bytes(asset_val.value))
        admin_val = self._sac_storage_get(inst, b"Admin")
        admin = admin_val.value if admin_val is not None else None
        return StellarAssetContract(self, contract, asset,
                                    admin).invoke(fn, args)

    def sac_set_admin(self, contract: SCAddress,
                      new_admin: SCAddress) -> None:
        """Rewrite the SAC instance's Admin entry (set_admin)."""
        key = instance_key(contract)
        le = self.load_entry(key)
        inst = le.data.value.val.value
        entries = [me for me in (inst.storage or [])
                   if not (me.key.disc == SCValType.SCV_SYMBOL and
                           bytes(me.key.value) == b"Admin")]
        entries.append(SCMapEntry(
            key=SCVal(SCValType.SCV_SYMBOL, b"Admin"),
            val=SCVal(SCValType.SCV_ADDRESS, new_admin)))
        inst.storage = entries
        self.put_entry(key, le)


# --- protocol-keyed host dispatch (curr/prev) -------------------------------

# First protocol whose host uses the CURRENT (recalibrated, cheaper)
# cost model. Reference analogue: the node links two complete host
# versions — soroban-env-host-curr always, -prev feature-gated — and
# routes invocations by the ledger protocol so transition-boundary
# replay is bit-exact (rust/Cargo.toml:27-56, contract.rs dual paths).
FIRST_RECALIBRATED_PROTOCOL = 21


class SorobanHostPrev(SorobanHost):
    """The protocol-20 host: identical semantics, original (pre-
    recalibration) cost model. A borderline instruction budget can
    therefore succeed under the current host and exhaust under this
    one — the real, state-visible divergence catchup must reproduce
    when replaying across the upgrade boundary (the protocol-21 story
    in the reference was exactly a cost recalibration)."""

    COST_STORAGE_OP = 2 * COST_STORAGE_OP
    COST_PER_BYTE = 2 * COST_PER_BYTE
    COST_CALL = 2 * COST_CALL


def host_for_protocol(ledger_version: int):
    """The host implementation for a ledger protocol (reference:
    rust_bridge::invoke_host_function routing between the curr and prev
    soroban-env-host builds by protocol)."""
    if ledger_version < FIRST_RECALIBRATED_PROTOCOL:
        return SorobanHostPrev
    return SorobanHost
