"""Built-in deterministic contract interpreter (the execution seam's
reference implementation).

Contract code = b"SCVM" ‖ XDR(SCVal map: symbol → expression). Each
exported function is one expression tree; expressions are SCVal vecs
whose head is an opcode symbol. Every node charges the budget, so
resource-limit semantics are exercised exactly like a metered wasm VM.

Opcodes:
  (lit v)                    literal
  (arg i)                    i-th invocation argument
  (seq e...)                 evaluate in order, yield last
  (add|sub|mul a b)          u64 arithmetic (traps on over/underflow)
  (eq a b) (lt a b)          comparisons → bool
  (if c t e)                 conditional
  (get k dur) (put k v dur) (del k dur)   contract storage
  (self)                     this contract's address
  (ledger_seq)               current ledger → u32
  (require_auth a)           host auth check
  (event topic data)         emit contract event
  (call c fn a...)           cross-contract call
  (fail)                     trap with a contract error
"""

from __future__ import annotations

from typing import List

from ..xdr.contract import (ContractDataDurability, ContractDataEntry,
                            SCError, SCErrorCode, SCErrorType, SCVal,
                            SCValType)
from ..xdr.ledger_entries import (LedgerEntry, LedgerEntryType, LedgerKey,
                                  _LedgerEntryData, _LedgerEntryExt)
from ..xdr.types import ExtensionPoint
from .host import (COST_BASE_INSTRUCTION, HostError, SorobanHost,
                   register_vm)

SCVM_MAGIC = b"SCVM"

U64_MAX = 2**64 - 1


def make_code(functions: dict) -> bytes:
    """Assemble {name: expression SCVal} into deployable code bytes."""
    entries = [
        {"key": SCVal(SCValType.SCV_SYMBOL, name.encode()
                      if isinstance(name, str) else name),
         "val": expr}
        for name, expr in sorted(functions.items())
    ]
    from ..xdr.contract import SCMapEntry
    m = SCVal(SCValType.SCV_MAP,
              [SCMapEntry(key=e["key"], val=e["val"]) for e in entries])
    return SCVM_MAGIC + m.to_bytes()


def sym(s: str) -> SCVal:
    return SCVal(SCValType.SCV_SYMBOL, s.encode())


def u64(v: int) -> SCVal:
    return SCVal(SCValType.SCV_U64, v)


def op(*parts) -> SCVal:
    return SCVal(SCValType.SCV_VEC, list(parts))


def _durability(v: SCVal) -> ContractDataDurability:
    if v.disc == SCValType.SCV_SYMBOL and bytes(v.value) == b"temp":
        return ContractDataDurability.TEMPORARY
    return ContractDataDurability.PERSISTENT


class _Frame:
    def __init__(self, host: SorobanHost, contract, functions: dict,
                 args: List[SCVal]):
        self.host = host
        self.contract = contract
        self.functions = functions
        self.args = args


def _eval(fr: _Frame, expr: SCVal) -> SCVal:
    host = fr.host
    host.budget.charge(host.COST_BASE_INSTRUCTION)
    if expr.disc != SCValType.SCV_VEC or not expr.value:
        return expr  # self-evaluating
    items = list(expr.value)
    head = items[0]
    if head.disc != SCValType.SCV_SYMBOL:
        return expr
    opname = bytes(head.value)
    a = items[1:]

    if opname == b"lit":
        return a[0]
    if opname == b"arg":
        i = _eval(fr, a[0]).value
        if i >= len(fr.args):
            raise HostError(SCErrorType.SCE_VALUE, "missing argument",
                            SCErrorCode.SCEC_INDEX_BOUNDS)
        return fr.args[i]
    if opname == b"seq":
        out = SCVal(SCValType.SCV_VOID)
        for e in a:
            out = _eval(fr, e)
        return out
    if opname in (b"add", b"sub", b"mul"):
        x = _eval(fr, a[0]).value
        y = _eval(fr, a[1]).value
        if opname == b"add":
            r = x + y
        elif opname == b"sub":
            r = x - y
        else:
            r = x * y
        if r < 0 or r > U64_MAX:
            raise HostError(SCErrorType.SCE_VALUE, "u64 overflow",
                            SCErrorCode.SCEC_ARITH_DOMAIN)
        return u64(r)
    if opname == b"eq":
        return SCVal(SCValType.SCV_BOOL,
                     _eval(fr, a[0]) == _eval(fr, a[1]))
    if opname == b"lt":
        return SCVal(SCValType.SCV_BOOL,
                     _eval(fr, a[0]).value < _eval(fr, a[1]).value)
    if opname == b"if":
        cond = _eval(fr, a[0])
        truthy = bool(cond.value) if cond.disc == SCValType.SCV_BOOL \
            else cond.disc != SCValType.SCV_VOID
        return _eval(fr, a[1] if truthy else a[2])
    if opname == b"get":
        key = _eval(fr, a[0])
        dur = _durability(a[1]) if len(a) > 1 else \
            ContractDataDurability.PERSISTENT
        lk = LedgerKey.contract_data(fr.contract, key, dur)
        le = host.load_entry(lk)
        if le is None:
            return SCVal(SCValType.SCV_VOID)
        return le.data.value.val
    if opname == b"put":
        key = _eval(fr, a[0])
        val = _eval(fr, a[1])
        dur = _durability(a[2]) if len(a) > 2 else \
            ContractDataDurability.PERSISTENT
        lk = LedgerKey.contract_data(fr.contract, key, dur)
        host.put_entry(lk, LedgerEntry(
            lastModifiedLedgerSeq=host.header.ledgerSeq,
            data=_LedgerEntryData(
                LedgerEntryType.CONTRACT_DATA,
                ContractDataEntry(ext=ExtensionPoint(0),
                                  contract=fr.contract, key=key,
                                  durability=dur, val=val)),
            ext=_LedgerEntryExt(0)), durability=dur)
        return SCVal(SCValType.SCV_VOID)
    if opname == b"del":
        key = _eval(fr, a[0])
        dur = _durability(a[1]) if len(a) > 1 else \
            ContractDataDurability.PERSISTENT
        host.erase_entry(LedgerKey.contract_data(fr.contract, key, dur))
        return SCVal(SCValType.SCV_VOID)
    if opname == b"self":
        return SCVal(SCValType.SCV_ADDRESS, fr.contract)
    if opname == b"ledger_seq":
        return SCVal(SCValType.SCV_U32, host.header.ledgerSeq)
    if opname == b"require_auth":
        addr = _eval(fr, a[0])
        host.require_auth(addr.value)
        return SCVal(SCValType.SCV_VOID)
    if opname == b"log":
        msg = _eval(fr, a[0])
        if msg.disc not in (SCValType.SCV_SYMBOL, SCValType.SCV_STRING,
                            SCValType.SCV_BYTES):
            raise HostError(SCErrorType.SCE_VALUE,
                            "log expects a bytes-like value")
        host.log_diagnostic(bytes(msg.value), [])
        return SCVal(SCValType.SCV_VOID)
    if opname == b"event":
        topic = _eval(fr, a[0])
        data = _eval(fr, a[1])
        host.emit_event(bytes(fr.contract.value), [topic], data)
        return SCVal(SCValType.SCV_VOID)
    if opname == b"call":
        target = _eval(fr, a[0])
        fname = _eval(fr, a[1])
        call_args = [_eval(fr, x) for x in a[2:]]
        return host.call_contract(target.value, bytes(fname.value),
                                  call_args)
    if opname == b"fail":
        raise HostError(SCErrorType.SCE_CONTRACT, "contract trap")
    raise HostError(SCErrorType.SCE_WASM_VM,
                    f"unknown opcode {opname!r}")


@register_vm(SCVM_MAGIC)
def run_scvm(host: SorobanHost, contract, code: bytes, fn: bytes,
             args: List[SCVal]):
    table = SCVal.from_bytes(code[len(SCVM_MAGIC):])
    functions = {}
    if table.value:
        for me in table.value:
            functions[bytes(me.key.value)] = me.val
    expr = functions.get(fn)
    if expr is None:
        raise HostError(SCErrorType.SCE_CONTEXT,
                        f"no function {fn!r}",
                        SCErrorCode.SCEC_MISSING_VALUE)
    return _eval(_Frame(host, contract, functions, args), expr)
