"""Ledger entry types (reference: Stellar-ledger-entries.x; consumed by
src/ledger/LedgerTxn* and the per-type SQL backends).

Classic entry types are complete; Soroban entry types (CONTRACT_DATA,
CONTRACT_CODE, CONFIG_SETTING, TTL) are wired in by the soroban layer
(build-plan SURVEY.md §7 step 8 — classic protocol first).
"""

from __future__ import annotations

from enum import IntEnum

from .runtime import (
    Array, Bool, Int32, Int64, Lazy, Opaque, Optional, Struct, Uint32,
    Uint64, Union, VarArray, VarOpaque, XdrString,
)
from .types import AccountID, ExtensionPoint, Hash, PublicKey, SignerKey, Uint256

Thresholds = Opaque(4)
String32 = XdrString(32)
String64 = XdrString(64)
DataValue = VarOpaque(64)
PoolID = Hash  # opaque[32]

AssetCode4 = Opaque(4)
AssetCode12 = Opaque(12)

MAX_SIGNERS = 20
LIQUIDITY_POOL_FEE_V18 = 30

MASK_ACCOUNT_FLAGS = 0x7
MASK_ACCOUNT_FLAGS_V17 = 0xF
MASK_TRUSTLINE_FLAGS = 1
MASK_TRUSTLINE_FLAGS_V13 = 3
MASK_TRUSTLINE_FLAGS_V17 = 7
MASK_OFFERENTRY_FLAGS = 1
MASK_CLAIMABLE_BALANCE_FLAGS = 0x1
MASK_LEDGER_HEADER_FLAGS = 0x7


class AssetType(IntEnum):
    ASSET_TYPE_NATIVE = 0
    ASSET_TYPE_CREDIT_ALPHANUM4 = 1
    ASSET_TYPE_CREDIT_ALPHANUM12 = 2
    ASSET_TYPE_POOL_SHARE = 3


class AssetCode(Union):
    SWITCH = AssetType
    ARMS = {
        AssetType.ASSET_TYPE_CREDIT_ALPHANUM4: ("assetCode4", AssetCode4),
        AssetType.ASSET_TYPE_CREDIT_ALPHANUM12: ("assetCode12", AssetCode12),
    }

    def __init__(self, disc=AssetType.ASSET_TYPE_CREDIT_ALPHANUM4, value=b"\x00" * 4, **kw):
        super().__init__(disc, value, **kw)


class AlphaNum4(Struct):
    FIELDS = [("assetCode", AssetCode4), ("issuer", AccountID)]


class AlphaNum12(Struct):
    FIELDS = [("assetCode", AssetCode12), ("issuer", AccountID)]


class Asset(Union):
    SWITCH = AssetType
    ARMS = {
        AssetType.ASSET_TYPE_NATIVE: None,
        AssetType.ASSET_TYPE_CREDIT_ALPHANUM4: ("alphaNum4", AlphaNum4),
        AssetType.ASSET_TYPE_CREDIT_ALPHANUM12: ("alphaNum12", AlphaNum12),
    }

    @classmethod
    def native(cls) -> "Asset":
        return cls(AssetType.ASSET_TYPE_NATIVE)

    @classmethod
    def credit(cls, code: bytes, issuer) -> "Asset":
        """Alphanum4/12 credit asset from a short code (zero-padded)."""
        if len(code) <= 4:
            return cls(AssetType.ASSET_TYPE_CREDIT_ALPHANUM4,
                       AlphaNum4(assetCode=code.ljust(4, b"\x00"),
                                 issuer=issuer))
        return cls(AssetType.ASSET_TYPE_CREDIT_ALPHANUM12,
                   AlphaNum12(assetCode=code.ljust(12, b"\x00"),
                              issuer=issuer))


class Price(Struct):
    FIELDS = [("n", Int32), ("d", Int32)]


class Liabilities(Struct):
    FIELDS = [("buying", Int64), ("selling", Int64)]


class ThresholdIndexes(IntEnum):
    THRESHOLD_MASTER_WEIGHT = 0
    THRESHOLD_LOW = 1
    THRESHOLD_MED = 2
    THRESHOLD_HIGH = 3


class LedgerEntryType(IntEnum):
    ACCOUNT = 0
    TRUSTLINE = 1
    OFFER = 2
    DATA = 3
    CLAIMABLE_BALANCE = 4
    LIQUIDITY_POOL = 5
    CONTRACT_DATA = 6
    CONTRACT_CODE = 7
    CONFIG_SETTING = 8
    TTL = 9


class Signer(Struct):
    FIELDS = [("key", SignerKey), ("weight", Uint32)]


class AccountFlags(IntEnum):
    AUTH_REQUIRED_FLAG = 0x1
    AUTH_REVOCABLE_FLAG = 0x2
    AUTH_IMMUTABLE_FLAG = 0x4
    AUTH_CLAWBACK_ENABLED_FLAG = 0x8


SponsorshipDescriptor = Optional(AccountID)


class AccountEntryExtensionV3(Struct):
    FIELDS = [
        ("ext", ExtensionPoint),
        ("seqLedger", Uint32),
        ("seqTime", Uint64),
    ]


class _AccountEntryExtV2Ext(Union):
    SWITCH = Int32
    ARMS = {0: None, 3: ("v3", AccountEntryExtensionV3)}


class AccountEntryExtensionV2(Struct):
    FIELDS = [
        ("numSponsored", Uint32),
        ("numSponsoring", Uint32),
        ("signerSponsoringIDs", VarArray(SponsorshipDescriptor, MAX_SIGNERS)),
        ("ext", _AccountEntryExtV2Ext),
    ]


class _AccountEntryExtV1Ext(Union):
    SWITCH = Int32
    ARMS = {0: None, 2: ("v2", AccountEntryExtensionV2)}


class AccountEntryExtensionV1(Struct):
    FIELDS = [
        ("liabilities", Liabilities),
        ("ext", _AccountEntryExtV1Ext),
    ]


class _AccountEntryExt(Union):
    SWITCH = Int32
    ARMS = {0: None, 1: ("v1", AccountEntryExtensionV1)}


class AccountEntry(Struct):
    FIELDS = [
        ("accountID", AccountID),
        ("balance", Int64),
        ("seqNum", Int64),
        ("numSubEntries", Uint32),
        ("inflationDest", Optional(AccountID)),
        ("flags", Uint32),
        ("homeDomain", String32),
        ("thresholds", Thresholds),
        ("signers", VarArray(Signer, MAX_SIGNERS)),
        ("ext", _AccountEntryExt),
    ]


class TrustLineFlags(IntEnum):
    AUTHORIZED_FLAG = 1
    AUTHORIZED_TO_MAINTAIN_LIABILITIES_FLAG = 2
    TRUSTLINE_CLAWBACK_ENABLED_FLAG = 4


class LiquidityPoolType(IntEnum):
    LIQUIDITY_POOL_CONSTANT_PRODUCT = 0


class TrustLineAsset(Union):
    SWITCH = AssetType
    ARMS = {
        AssetType.ASSET_TYPE_NATIVE: None,
        AssetType.ASSET_TYPE_CREDIT_ALPHANUM4: ("alphaNum4", AlphaNum4),
        AssetType.ASSET_TYPE_CREDIT_ALPHANUM12: ("alphaNum12", AlphaNum12),
        AssetType.ASSET_TYPE_POOL_SHARE: ("liquidityPoolID", PoolID),
    }

    @classmethod
    def from_asset(cls, asset: "Asset") -> "TrustLineAsset":
        if asset.disc == AssetType.ASSET_TYPE_NATIVE:
            return cls(AssetType.ASSET_TYPE_NATIVE)
        return cls(asset.disc, asset.value)


class TrustLineEntryExtensionV2(Struct):
    FIELDS = [
        ("liquidityPoolUseCount", Int32),
        ("ext", ExtensionPoint),
    ]


class _TrustLineEntryExtV1Ext(Union):
    SWITCH = Int32
    ARMS = {0: None, 2: ("v2", TrustLineEntryExtensionV2)}


class TrustLineEntryV1(Struct):
    FIELDS = [
        ("liabilities", Liabilities),
        ("ext", _TrustLineEntryExtV1Ext),
    ]


class _TrustLineEntryExt(Union):
    SWITCH = Int32
    ARMS = {0: None, 1: ("v1", TrustLineEntryV1)}


class TrustLineEntry(Struct):
    FIELDS = [
        ("accountID", AccountID),
        ("asset", TrustLineAsset),
        ("balance", Int64),
        ("limit", Int64),
        ("flags", Uint32),
        ("ext", _TrustLineEntryExt),
    ]


class OfferEntryFlags(IntEnum):
    PASSIVE_FLAG = 1


class OfferEntry(Struct):
    FIELDS = [
        ("sellerID", AccountID),
        ("offerID", Int64),
        ("selling", Asset),
        ("buying", Asset),
        ("amount", Int64),
        ("price", Price),
        ("flags", Uint32),
        ("ext", ExtensionPoint),
    ]


class DataEntry(Struct):
    FIELDS = [
        ("accountID", AccountID),
        ("dataName", String64),
        ("dataValue", DataValue),
        ("ext", ExtensionPoint),
    ]


class ClaimPredicateType(IntEnum):
    CLAIM_PREDICATE_UNCONDITIONAL = 0
    CLAIM_PREDICATE_AND = 1
    CLAIM_PREDICATE_OR = 2
    CLAIM_PREDICATE_NOT = 3
    CLAIM_PREDICATE_BEFORE_ABSOLUTE_TIME = 4
    CLAIM_PREDICATE_BEFORE_RELATIVE_TIME = 5


class ClaimPredicate(Union):
    SWITCH = ClaimPredicateType
    ARMS = {
        ClaimPredicateType.CLAIM_PREDICATE_UNCONDITIONAL: None,
        ClaimPredicateType.CLAIM_PREDICATE_AND:
            ("andPredicates", VarArray(Lazy(lambda: ClaimPredicate), 2)),
        ClaimPredicateType.CLAIM_PREDICATE_OR:
            ("orPredicates", VarArray(Lazy(lambda: ClaimPredicate), 2)),
        ClaimPredicateType.CLAIM_PREDICATE_NOT:
            ("notPredicate", Optional(Lazy(lambda: ClaimPredicate))),
        ClaimPredicateType.CLAIM_PREDICATE_BEFORE_ABSOLUTE_TIME:
            ("absBefore", Int64),
        ClaimPredicateType.CLAIM_PREDICATE_BEFORE_RELATIVE_TIME:
            ("relBefore", Int64),
    }


class ClaimantType(IntEnum):
    CLAIMANT_TYPE_V0 = 0


class ClaimantV0(Struct):
    FIELDS = [("destination", AccountID), ("predicate", ClaimPredicate)]


class Claimant(Union):
    SWITCH = ClaimantType
    ARMS = {ClaimantType.CLAIMANT_TYPE_V0: ("v0", ClaimantV0)}


class ClaimableBalanceIDType(IntEnum):
    CLAIMABLE_BALANCE_ID_TYPE_V0 = 0


class ClaimableBalanceID(Union):
    SWITCH = ClaimableBalanceIDType
    ARMS = {ClaimableBalanceIDType.CLAIMABLE_BALANCE_ID_TYPE_V0: ("v0", Hash)}


class ClaimableBalanceFlags(IntEnum):
    CLAIMABLE_BALANCE_CLAWBACK_ENABLED_FLAG = 0x1


class ClaimableBalanceEntryExtensionV1(Struct):
    FIELDS = [("ext", ExtensionPoint), ("flags", Uint32)]


class _ClaimableBalanceEntryExt(Union):
    SWITCH = Int32
    ARMS = {0: None, 1: ("v1", ClaimableBalanceEntryExtensionV1)}


class ClaimableBalanceEntry(Struct):
    FIELDS = [
        ("balanceID", ClaimableBalanceID),
        ("claimants", VarArray(Claimant, 10)),
        ("asset", Asset),
        ("amount", Int64),
        ("ext", _ClaimableBalanceEntryExt),
    ]


class LiquidityPoolConstantProductParameters(Struct):
    FIELDS = [("assetA", Asset), ("assetB", Asset), ("fee", Int32)]


class _LPConstantProduct(Struct):
    FIELDS = [
        ("params", LiquidityPoolConstantProductParameters),
        ("reserveA", Int64),
        ("reserveB", Int64),
        ("totalPoolShares", Int64),
        ("poolSharesTrustLineCount", Int64),
    ]


class _LiquidityPoolBody(Union):
    SWITCH = LiquidityPoolType
    ARMS = {
        LiquidityPoolType.LIQUIDITY_POOL_CONSTANT_PRODUCT:
            ("constantProduct", _LPConstantProduct),
    }


class LiquidityPoolEntry(Struct):
    FIELDS = [
        ("liquidityPoolID", PoolID),
        ("body", _LiquidityPoolBody),
    ]


class _LedgerEntryData(Union):
    SWITCH = LedgerEntryType
    ARMS = {
        LedgerEntryType.ACCOUNT: ("account", AccountEntry),
        LedgerEntryType.TRUSTLINE: ("trustLine", TrustLineEntry),
        LedgerEntryType.OFFER: ("offer", OfferEntry),
        LedgerEntryType.DATA: ("data", DataEntry),
        LedgerEntryType.CLAIMABLE_BALANCE:
            ("claimableBalance", ClaimableBalanceEntry),
        LedgerEntryType.LIQUIDITY_POOL: ("liquidityPool", LiquidityPoolEntry),
    }


class LedgerEntryExtensionV1(Struct):
    FIELDS = [
        ("sponsoringID", SponsorshipDescriptor),
        ("ext", ExtensionPoint),
    ]


class _LedgerEntryExt(Union):
    SWITCH = Int32
    ARMS = {0: None, 1: ("v1", LedgerEntryExtensionV1)}


class LedgerEntry(Struct):
    FIELDS = [
        ("lastModifiedLedgerSeq", Uint32),
        ("data", _LedgerEntryData),
        ("ext", _LedgerEntryExt),
    ]


# --- LedgerKey -------------------------------------------------------------

class _LedgerKeyAccount(Struct):
    FIELDS = [("accountID", AccountID)]


class _LedgerKeyTrustLine(Struct):
    FIELDS = [("accountID", AccountID), ("asset", TrustLineAsset)]


class _LedgerKeyOffer(Struct):
    FIELDS = [("sellerID", AccountID), ("offerID", Int64)]


class _LedgerKeyData(Struct):
    FIELDS = [("accountID", AccountID), ("dataName", String64)]


class _LedgerKeyClaimableBalance(Struct):
    FIELDS = [("balanceID", ClaimableBalanceID)]


class _LedgerKeyLiquidityPool(Struct):
    FIELDS = [("liquidityPoolID", PoolID)]


class LedgerKey(Union):
    SWITCH = LedgerEntryType
    ARMS = {
        LedgerEntryType.ACCOUNT: ("account", _LedgerKeyAccount),
        LedgerEntryType.TRUSTLINE: ("trustLine", _LedgerKeyTrustLine),
        LedgerEntryType.OFFER: ("offer", _LedgerKeyOffer),
        LedgerEntryType.DATA: ("data", _LedgerKeyData),
        LedgerEntryType.CLAIMABLE_BALANCE:
            ("claimableBalance", _LedgerKeyClaimableBalance),
        LedgerEntryType.LIQUIDITY_POOL:
            ("liquidityPool", _LedgerKeyLiquidityPool),
    }

    # LedgerKeys are immutable by convention: they are constructed,
    # serialized, compared, and discarded.  The serialized form is
    # memoized per instance, and account keys (the hottest kind — every
    # fee/seqnum/signature/op phase re-loads source accounts) are
    # interned by raw public key.
    _ACCOUNT_KEYS: dict = {}

    def to_bytes(self) -> bytes:
        b = self.__dict__.get("_kb")
        if b is None:
            b = self.__dict__["_kb"] = Union.to_bytes(self)
        return b

    @classmethod
    def account(cls, account_id: PublicKey) -> "LedgerKey":
        raw = bytes(account_id.value)
        k = cls._ACCOUNT_KEYS.get(raw)
        if k is None:
            if len(cls._ACCOUNT_KEYS) > 65536:
                cls._ACCOUNT_KEYS.clear()
            k = cls(LedgerEntryType.ACCOUNT,
                    _LedgerKeyAccount(accountID=account_id))
            cls._ACCOUNT_KEYS[raw] = k
        return k

    @classmethod
    def trust_line(cls, account_id: PublicKey, asset: TrustLineAsset) -> "LedgerKey":
        return cls(LedgerEntryType.TRUSTLINE,
                   _LedgerKeyTrustLine(accountID=account_id, asset=asset))

    @classmethod
    def offer(cls, seller_id: PublicKey, offer_id: int) -> "LedgerKey":
        return cls(LedgerEntryType.OFFER,
                   _LedgerKeyOffer(sellerID=seller_id, offerID=offer_id))

    @classmethod
    def data(cls, account_id: PublicKey, name: bytes) -> "LedgerKey":
        return cls(LedgerEntryType.DATA,
                   _LedgerKeyData(accountID=account_id, dataName=name))

    @classmethod
    def claimable_balance(cls, balance_id: ClaimableBalanceID) -> "LedgerKey":
        return cls(LedgerEntryType.CLAIMABLE_BALANCE,
                   _LedgerKeyClaimableBalance(balanceID=balance_id))

    @classmethod
    def liquidity_pool(cls, pool_id: bytes) -> "LedgerKey":
        return cls(LedgerEntryType.LIQUIDITY_POOL,
                   _LedgerKeyLiquidityPool(liquidityPoolID=pool_id))


def ledger_entry_key(entry: LedgerEntry) -> LedgerKey:
    """LedgerKey for a LedgerEntry (reference: ledger/LedgerHashUtils usage,
    LedgerEntryKey in ledger/InternalLedgerEntry.cpp)."""
    t = entry.data.disc
    d = entry.data.value
    if t == LedgerEntryType.ACCOUNT:
        return LedgerKey.account(d.accountID)
    if t == LedgerEntryType.TRUSTLINE:
        return LedgerKey.trust_line(d.accountID, d.asset)
    if t == LedgerEntryType.OFFER:
        return LedgerKey.offer(d.sellerID, d.offerID)
    if t == LedgerEntryType.DATA:
        return LedgerKey.data(d.accountID, d.dataName)
    if t == LedgerEntryType.CLAIMABLE_BALANCE:
        return LedgerKey.claimable_balance(d.balanceID)
    if t == LedgerEntryType.LIQUIDITY_POOL:
        return LedgerKey.liquidity_pool(d.liquidityPoolID)
    # Soroban entry types: key helpers are registered by xdr.contract
    if t == LedgerEntryType.CONTRACT_DATA:
        return LedgerKey.contract_data(d.contract, d.key, d.durability)
    if t == LedgerEntryType.CONTRACT_CODE:
        return LedgerKey.contract_code(bytes(d.hash))
    if t == LedgerEntryType.CONFIG_SETTING:
        return LedgerKey.config_setting(d.disc)
    if t == LedgerEntryType.TTL:
        return LedgerKey.ttl(bytes(d.keyHash))
    raise ValueError(f"unsupported entry type {t}")
