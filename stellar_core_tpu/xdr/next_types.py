"""Protocol-next structural deltas — the second XDR type set.

Reference: `src/protocol-next/` carries the in-development protocol's
.x changes as a complete parallel tree (Makefile.am:46-51); builds
against curr and next must both compile and be hash-distinguishable.

The deltas below model the actual in-flight next-protocol change to the
bucket format (hot-archive bucket lists: BucketMetadata.ext v1 carries
a BucketListType discriminator).  They are STRUCTURAL — a new union
arm and enum — which the version-gate mechanism inside one merged tree
cannot represent; this namespace can.

Types here are standalone classes (not mutations of the curr classes),
so the curr build's wire language is untouched; `schema.next_namespace`
overlays them by name.
"""

from __future__ import annotations

from enum import IntEnum

from .runtime import Int32, Struct, Uint32, Union


class BucketListType(IntEnum):
    """next: which bucket list a bucket belongs to (live vs the
    hot-archive list introduced for state archival)."""
    LIVE = 0
    HOT_ARCHIVE = 1


# plain int-discriminated ext (v: 0 = void, 1 = bucketListType)
class _BucketMetadataExt(Union):
    SWITCH = Int32
    ARMS = {0: None, 1: ("bucketListType", BucketListType)}


class BucketMetadata(Struct):
    """next-protocol BucketMetadata: ext arm 1 discriminates the
    bucket-list kind."""
    FIELDS = [("ledgerVersion", Uint32), ("ext", _BucketMetadataExt)]


# the overlay consumed by schema.next_namespace(); keys replace the
# same-named curr types
NEXT_TYPES = {
    "BucketListType": BucketListType,
    "BucketMetadata": BucketMetadata,
    "_BucketMetadataExt": _BucketMetadataExt,
}
