"""Protocol-next structural deltas — the second XDR type set.

Reference: `src/protocol-next/` carries the in-development protocol's
.x changes as a complete parallel tree (Makefile.am:46-51); builds
against curr and next must both compile and be hash-distinguishable.

The deltas below model the actual in-flight next-protocol change to the
bucket format (hot-archive bucket lists: BucketMetadata.ext v1 carries
a BucketListType discriminator).  They are STRUCTURAL — a new union
arm and enum — which the version-gate mechanism inside one merged tree
cannot represent; this namespace can.

Types here are standalone classes (not mutations of the curr classes),
so the curr build's wire language is untouched; `schema.next_namespace`
overlays them by name.
"""

from __future__ import annotations

from enum import IntEnum

from .ledger_entries import LedgerEntry, LedgerKey
from .runtime import Int32, Struct, Uint32, Union


class BucketListType(IntEnum):
    """next: which bucket list a bucket belongs to (live vs the
    hot-archive list introduced for state archival)."""
    LIVE = 0
    HOT_ARCHIVE = 1


# plain int-discriminated ext (v: 0 = void, 1 = bucketListType)
class _BucketMetadataExt(Union):
    SWITCH = Int32
    ARMS = {0: None, 1: ("bucketListType", BucketListType)}


class BucketMetadata(Struct):
    """next-protocol BucketMetadata: ext arm 1 discriminates the
    bucket-list kind."""
    FIELDS = [("ledgerVersion", Uint32), ("ext", _BucketMetadataExt)]


# --------------------------------------------------------------------------
# hot-archive bucket entries: the next protocol's second bucket list
# (state archival). Entry kinds mirror the in-development tree's shape:
# ARCHIVED carries the full evicted entry, LIVE marks an archived entry
# as restored (a hot-archive tombstone), DELETED records that the entry
# was deleted while archived; METAENTRY heads every bucket with the
# next BucketMetadata whose ext discriminates the list kind.
# Reference mechanism: src/protocol-next built+tested alongside curr
# (Makefile.am:46-51); the content here is this framework's next tree.
# --------------------------------------------------------------------------

class HotArchiveBucketEntryType(IntEnum):
    HOT_ARCHIVE_METAENTRY = -1
    HOT_ARCHIVE_ARCHIVED = 0
    HOT_ARCHIVE_LIVE = 1
    HOT_ARCHIVE_DELETED = 2


class HotArchiveBucketEntry(Union):
    SWITCH = HotArchiveBucketEntryType
    ARMS = {
        HotArchiveBucketEntryType.HOT_ARCHIVE_METAENTRY:
            ("metaEntry", BucketMetadata),
        HotArchiveBucketEntryType.HOT_ARCHIVE_ARCHIVED:
            ("archivedEntry", LedgerEntry),
        HotArchiveBucketEntryType.HOT_ARCHIVE_LIVE: ("key", LedgerKey),
        HotArchiveBucketEntryType.HOT_ARCHIVE_DELETED: ("key", LedgerKey),
    }


# the overlay consumed by schema.next_namespace(); keys replace the
# same-named curr types (new names extend the namespace)
NEXT_TYPES = {
    "BucketListType": BucketListType,
    "BucketMetadata": BucketMetadata,
    "_BucketMetadataExt": _BucketMetadataExt,
    "HotArchiveBucketEntryType": HotArchiveBucketEntryType,
    "HotArchiveBucketEntry": HotArchiveBucketEntry,
}
