"""Base protocol types (reference: Stellar-types.x via xdrpp codegen;
usage cited throughout src/crypto and src/overlay)."""

from __future__ import annotations

from enum import IntEnum

from .runtime import (
    Array, Bool, Int32, Int64, Opaque, Optional, Struct, Uint32, Uint64,
    Union, VarArray, VarOpaque, XdrString,
)

# opaque[32] aliases
Hash = Opaque(32)
Uint256 = Opaque(32)

Signature = VarOpaque(64)
SignatureHint = Opaque(4)


class CryptoKeyType(IntEnum):
    KEY_TYPE_ED25519 = 0
    KEY_TYPE_PRE_AUTH_TX = 1
    KEY_TYPE_HASH_X = 2
    KEY_TYPE_ED25519_SIGNED_PAYLOAD = 3
    KEY_TYPE_MUXED_ED25519 = 0x100


class PublicKeyType(IntEnum):
    PUBLIC_KEY_TYPE_ED25519 = 0


class SignerKeyType(IntEnum):
    SIGNER_KEY_TYPE_ED25519 = 0
    SIGNER_KEY_TYPE_PRE_AUTH_TX = 1
    SIGNER_KEY_TYPE_HASH_X = 2
    SIGNER_KEY_TYPE_ED25519_SIGNED_PAYLOAD = 3


class PublicKey(Union):
    SWITCH = PublicKeyType
    ARMS = {PublicKeyType.PUBLIC_KEY_TYPE_ED25519: ("ed25519", Uint256)}

    @classmethod
    def ed25519(cls, raw: bytes) -> "PublicKey":
        return cls(PublicKeyType.PUBLIC_KEY_TYPE_ED25519, raw)


# NodeID and AccountID are PublicKey aliases in the reference XDR
NodeID = PublicKey
AccountID = PublicKey


class Ed25519SignedPayload(Struct):
    FIELDS = [("ed25519", Uint256), ("payload", VarOpaque(64))]


class SignerKey(Union):
    SWITCH = SignerKeyType
    ARMS = {
        SignerKeyType.SIGNER_KEY_TYPE_ED25519: ("ed25519", Uint256),
        SignerKeyType.SIGNER_KEY_TYPE_PRE_AUTH_TX: ("preAuthTx", Uint256),
        SignerKeyType.SIGNER_KEY_TYPE_HASH_X: ("hashX", Uint256),
        SignerKeyType.SIGNER_KEY_TYPE_ED25519_SIGNED_PAYLOAD:
            ("ed25519SignedPayload", Ed25519SignedPayload),
    }


class Curve25519Secret(Struct):
    FIELDS = [("key", Opaque(32))]


class Curve25519Public(Struct):
    FIELDS = [("key", Opaque(32))]


class HmacSha256Key(Struct):
    FIELDS = [("key", Opaque(32))]


class HmacSha256Mac(Struct):
    FIELDS = [("mac", Opaque(32))]


class ExtensionPoint(Union):
    """Reserved extension point — only case 0 (void) exists."""
    SWITCH = Int32
    ARMS = {0: None}


class EnvelopeType(IntEnum):
    ENVELOPE_TYPE_TX_V0 = 0
    ENVELOPE_TYPE_SCP = 1
    ENVELOPE_TYPE_TX = 2
    ENVELOPE_TYPE_AUTH = 3
    ENVELOPE_TYPE_SCPVALUE = 4
    ENVELOPE_TYPE_TX_FEE_BUMP = 5
    ENVELOPE_TYPE_OP_ID = 6
    ENVELOPE_TYPE_POOL_REVOKE_OP_ID = 7
    ENVELOPE_TYPE_CONTRACT_ID = 8
    ENVELOPE_TYPE_SOROBAN_AUTHORIZATION = 9
