"""Type-aware XDR → JSON-able conversion (reference: xdr_to_string /
cereal JSON output used by dump-ledger and print-xdr; union
discriminants render as their enum names, keys as strkey, opaques as
hex)."""

from __future__ import annotations

from enum import IntEnum
from typing import Any

from ..util.xdrquery import XDRQueryError, _leaf_value, _norm
from .runtime import (Optional as XdrOptional, Struct, Union, Array,
                      VarArray)


def to_jsonable(value: Any, t: Any = None) -> Any:
    """Convert an XDR value to plain dict/list/str/int for json.dumps.
    Leaves render exactly as xdrquery resolves them, so a value copied
    out of a dump matches the same entry via --filter-query."""
    if t is None:
        t = type(value)
    t = _norm(t)

    if isinstance(t, XdrOptional):
        if value is None:
            return None
        return to_jsonable(value, t.elem)
    if isinstance(t, (Array, VarArray)):
        return [to_jsonable(v, t.elem) for v in value]
    try:
        return _leaf_value(value, t)  # PublicKey/enum/str/opaque/int/bool
    except XDRQueryError:
        pass
    if isinstance(t, type) and issubclass(t, Struct):
        return {fn: to_jsonable(getattr(value, fn), ft)
                for fn, ft in t._FIELDS}
    if isinstance(t, type) and issubclass(t, Union):
        disc = value.disc
        disc_repr = disc.name if isinstance(disc, IntEnum) else int(disc)
        arm = t._ARMS.get(disc, t._DEFAULT_ARM
                          if t._DEFAULT_ARM != "_missing_" else None)
        if arm is None or arm[1] is None:
            return {"type": disc_repr}
        return {"type": disc_repr,
                arm[0]: to_jsonable(value.value, arm[1])}
    if isinstance(value, IntEnum):
        return value.name
    if isinstance(value, (bytes, bytearray)):
        return bytes(value).hex()
    if isinstance(value, (list, tuple)):
        return [to_jsonable(v) for v in value]
    return value
