"""Ledger-level types (reference: Stellar-ledger.x; consumed by
src/ledger/LedgerManagerImpl, src/herder/TxSetFrame, src/bucket/Bucket)."""

from __future__ import annotations

from enum import IntEnum

from .runtime import (
    Lazy,
    Array, Int32, Int64, Opaque, Optional, Struct, Uint32, Uint64, Union,
    VarArray, VarOpaque,
)
from .types import (
    ExtensionPoint, Hash, NodeID, PublicKey, Signature, Uint256,
)
from .ledger_entries import LedgerEntry, LedgerKey
from .transaction import TransactionEnvelope
from .results import TransactionResultPair, TransactionResultSet
from .scp import SCPHistoryEntry

UpgradeType = VarOpaque(128)

MAX_TX_SET_ALLOWANCE = 0xFFFFFFFF


class StellarValueType(IntEnum):
    STELLAR_VALUE_BASIC = 0
    STELLAR_VALUE_SIGNED = 1


class LedgerCloseValueSignature(Struct):
    FIELDS = [("nodeID", NodeID), ("signature", Signature)]


class _StellarValueExt(Union):
    SWITCH = StellarValueType
    ARMS = {
        StellarValueType.STELLAR_VALUE_BASIC: None,
        StellarValueType.STELLAR_VALUE_SIGNED:
            ("lcValueSignature", LedgerCloseValueSignature),
    }


class StellarValue(Struct):
    """The value SCP agrees on per ledger (reference: Stellar-ledger.x
    StellarValue; built in herder/HerderImpl::triggerNextLedger)."""
    FIELDS = [
        ("txSetHash", Hash),
        ("closeTime", Uint64),
        ("upgrades", VarArray(UpgradeType, 6)),
        ("ext", _StellarValueExt),
    ]


class LedgerHeaderFlags(IntEnum):
    DISABLE_LIQUIDITY_POOL_TRADING_FLAG = 0x1
    DISABLE_LIQUIDITY_POOL_DEPOSIT_FLAG = 0x2
    DISABLE_LIQUIDITY_POOL_WITHDRAWAL_FLAG = 0x4


class LedgerHeaderExtensionV1(Struct):
    FIELDS = [("flags", Uint32), ("ext", ExtensionPoint)]


class _LedgerHeaderExt(Union):
    SWITCH = Int32
    ARMS = {0: None, 1: ("v1", LedgerHeaderExtensionV1)}


class LedgerHeader(Struct):
    FIELDS = [
        ("ledgerVersion", Uint32),
        ("previousLedgerHash", Hash),
        ("scpValue", StellarValue),
        ("txSetResultHash", Hash),
        ("bucketListHash", Hash),
        ("ledgerSeq", Uint32),
        ("totalCoins", Int64),
        ("feePool", Int64),
        ("inflationSeq", Uint32),
        ("idPool", Uint64),
        ("baseFee", Uint32),
        ("baseReserve", Uint32),
        ("maxTxSetSize", Uint32),
        ("skipList", Array(Hash, 4)),
        ("ext", _LedgerHeaderExt),
    ]


class LedgerUpgradeType(IntEnum):
    LEDGER_UPGRADE_VERSION = 1
    LEDGER_UPGRADE_BASE_FEE = 2
    LEDGER_UPGRADE_MAX_TX_SET_SIZE = 3
    LEDGER_UPGRADE_BASE_RESERVE = 4
    LEDGER_UPGRADE_FLAGS = 5
    LEDGER_UPGRADE_CONFIG = 6
    LEDGER_UPGRADE_MAX_SOROBAN_TX_SET_SIZE = 7


def _config_upgrade_set_key():
    from .contract import ConfigUpgradeSetKey
    return ConfigUpgradeSetKey


class LedgerUpgrade(Union):
    SWITCH = LedgerUpgradeType
    ARMS = {
        LedgerUpgradeType.LEDGER_UPGRADE_VERSION: ("newLedgerVersion", Uint32),
        LedgerUpgradeType.LEDGER_UPGRADE_BASE_FEE: ("newBaseFee", Uint32),
        LedgerUpgradeType.LEDGER_UPGRADE_MAX_TX_SET_SIZE:
            ("newMaxTxSetSize", Uint32),
        LedgerUpgradeType.LEDGER_UPGRADE_BASE_RESERVE:
            ("newBaseReserve", Uint32),
        LedgerUpgradeType.LEDGER_UPGRADE_FLAGS: ("newFlags", Uint32),
        LedgerUpgradeType.LEDGER_UPGRADE_CONFIG:
            ("newConfig", Lazy(lambda: _config_upgrade_set_key())),
        LedgerUpgradeType.LEDGER_UPGRADE_MAX_SOROBAN_TX_SET_SIZE:
            ("newMaxSorobanTxSetSize", Uint32),
    }


# --- Transaction sets ------------------------------------------------------

class TransactionSet(Struct):
    """Legacy (pre-protocol-20 wire) tx set (reference: herder/TxSetFrame)."""
    FIELDS = [
        ("previousLedgerHash", Hash),
        ("txs", VarArray(TransactionEnvelope)),
    ]


class _TxSetComponentTxsMaybeDiscountedFee(Struct):
    FIELDS = [
        ("baseFee", Optional(Int64)),
        ("txs", VarArray(TransactionEnvelope)),
    ]


class TxSetComponentType(IntEnum):
    TXSET_COMP_TXS_MAYBE_DISCOUNTED_FEE = 0


class TxSetComponent(Union):
    SWITCH = TxSetComponentType
    ARMS = {
        TxSetComponentType.TXSET_COMP_TXS_MAYBE_DISCOUNTED_FEE:
            ("txsMaybeDiscountedFee", _TxSetComponentTxsMaybeDiscountedFee),
    }


class TransactionPhase(Union):
    SWITCH = Int32
    ARMS = {0: ("v0Components", VarArray(TxSetComponent))}


class _TransactionSetV1(Struct):
    FIELDS = [
        ("previousLedgerHash", Hash),
        ("phases", VarArray(TransactionPhase)),
    ]


class GeneralizedTransactionSet(Union):
    """Protocol-20+ two-phase tx set (reference: herder/TxSetFrame.h:28-33 —
    phases CLASSIC and SOROBAN)."""
    SWITCH = Int32
    ARMS = {1: ("v1TxSet", _TransactionSetV1)}

    def __init__(self, disc=1, value=None, **kw):
        if value is None and not kw:
            value = _TransactionSetV1()
        super().__init__(disc, value, **kw)


TransactionSetV1 = _TransactionSetV1


# --- History entries -------------------------------------------------------

class _TxHistoryEntryExt(Union):
    SWITCH = Int32
    ARMS = {0: None, 1: ("generalizedTxSet", GeneralizedTransactionSet)}


class TransactionHistoryEntry(Struct):
    FIELDS = [
        ("ledgerSeq", Uint32),
        ("txSet", TransactionSet),
        ("ext", _TxHistoryEntryExt),
    ]


class TransactionHistoryResultEntry(Struct):
    FIELDS = [
        ("ledgerSeq", Uint32),
        ("txResultSet", TransactionResultSet),
        ("ext", ExtensionPoint),
    ]


class LedgerHeaderHistoryEntry(Struct):
    FIELDS = [
        ("hash", Hash),
        ("header", LedgerHeader),
        ("ext", ExtensionPoint),
    ]


# --- Ledger close meta -----------------------------------------------------

class LedgerEntryChangeType(IntEnum):
    LEDGER_ENTRY_CREATED = 0
    LEDGER_ENTRY_UPDATED = 1
    LEDGER_ENTRY_REMOVED = 2
    LEDGER_ENTRY_STATE = 3


class LedgerEntryChange(Union):
    SWITCH = LedgerEntryChangeType
    ARMS = {
        LedgerEntryChangeType.LEDGER_ENTRY_CREATED: ("created", LedgerEntry),
        LedgerEntryChangeType.LEDGER_ENTRY_UPDATED: ("updated", LedgerEntry),
        LedgerEntryChangeType.LEDGER_ENTRY_REMOVED: ("removed", LedgerKey),
        LedgerEntryChangeType.LEDGER_ENTRY_STATE: ("state", LedgerEntry),
    }


LedgerEntryChanges = VarArray(LedgerEntryChange)


class OperationMeta(Struct):
    FIELDS = [("changes", LedgerEntryChanges)]


class TransactionMetaV1(Struct):
    FIELDS = [
        ("txChanges", LedgerEntryChanges),
        ("operations", VarArray(OperationMeta)),
    ]


class TransactionMetaV2(Struct):
    FIELDS = [
        ("txChangesBefore", LedgerEntryChanges),
        ("operations", VarArray(OperationMeta)),
        ("txChangesAfter", LedgerEntryChanges),
    ]


class DiagnosticEvent(Struct):
    # reference: Stellar-ledger.x DiagnosticEvent
    FIELDS = [
        ("inSuccessfulContractCall", Lazy(lambda: _Bool())),
        ("event", Lazy(lambda: _contract().ContractEvent)),
    ]


class SorobanTransactionMeta(Struct):
    # reference: Stellar-ledger.x SorobanTransactionMeta — the soroban
    # leg of V3 meta: contract events, the host-fn return value, and
    # (off-consensus) diagnostic events
    FIELDS = [
        ("ext", ExtensionPoint),
        ("events", Lazy(lambda: VarArray(_contract().ContractEvent))),
        ("returnValue", Lazy(lambda: _contract().SCVal)),
        ("diagnosticEvents", VarArray(DiagnosticEvent)),
    ]


def _contract():
    from . import contract
    return contract


def _Bool():
    from .runtime import Bool
    return Bool


class TransactionMetaV3(Struct):
    # reference: Stellar-ledger.x TransactionMetaV3 (protocol 20+)
    FIELDS = [
        ("ext", ExtensionPoint),
        ("txChangesBefore", LedgerEntryChanges),
        ("operations", VarArray(OperationMeta)),
        ("txChangesAfter", LedgerEntryChanges),
        ("sorobanMeta", Optional(SorobanTransactionMeta)),
    ]


class TransactionMeta(Union):
    SWITCH = Int32
    ARMS = {
        0: ("operations", VarArray(OperationMeta)),
        1: ("v1", TransactionMetaV1),
        2: ("v2", TransactionMetaV2),
        3: ("v3", TransactionMetaV3),
    }


class TransactionResultMeta(Struct):
    FIELDS = [
        ("result", TransactionResultPair),
        ("feeProcessing", LedgerEntryChanges),
        ("txApplyProcessing", TransactionMeta),
    ]


class UpgradeEntryMeta(Struct):
    FIELDS = [
        ("upgrade", UpgradeType),
        ("changes", LedgerEntryChanges),
    ]


class LedgerCloseMetaV0(Struct):
    FIELDS = [
        ("ledgerHeader", LedgerHeaderHistoryEntry),
        ("txSet", TransactionSet),
        ("txProcessing", VarArray(TransactionResultMeta)),
        ("upgradesProcessing", VarArray(UpgradeEntryMeta)),
        ("scpInfo", VarArray(SCPHistoryEntry)),
    ]


class LedgerCloseMetaV1(Struct):
    """Protocol-20+ meta: generalized tx set + Soroban eviction info
    (reference: Stellar-ledger.x LedgerCloseMetaV1)."""
    FIELDS = [
        ("ext", ExtensionPoint),
        ("ledgerHeader", LedgerHeaderHistoryEntry),
        ("txSet", GeneralizedTransactionSet),
        ("txProcessing", VarArray(TransactionResultMeta)),
        ("upgradesProcessing", VarArray(UpgradeEntryMeta)),
        ("scpInfo", VarArray(SCPHistoryEntry)),
        ("totalByteSizeOfBucketList", Uint64),
        ("evictedTemporaryLedgerKeys", VarArray(LedgerKey)),
        ("evictedPersistentLedgerEntries", VarArray(LedgerEntry)),
    ]


class LedgerCloseMeta(Union):
    SWITCH = Int32
    ARMS = {0: ("v0", LedgerCloseMetaV0), 1: ("v1", LedgerCloseMetaV1)}


# --- Bucket entries --------------------------------------------------------

class BucketEntryType(IntEnum):
    METAENTRY = -1
    LIVEENTRY = 0
    DEADENTRY = 1
    INITENTRY = 2


class BucketMetadata(Struct):
    """First entry of every bucket from protocol 11 on (reference:
    bucket/Bucket.cpp METAENTRY handling, LedgerCmp.h)."""
    FIELDS = [("ledgerVersion", Uint32), ("ext", ExtensionPoint)]


class BucketEntry(Union):
    SWITCH = BucketEntryType
    ARMS = {
        BucketEntryType.LIVEENTRY: ("liveEntry", LedgerEntry),
        BucketEntryType.INITENTRY: ("liveEntry", LedgerEntry),
        BucketEntryType.DEADENTRY: ("deadEntry", LedgerKey),
        BucketEntryType.METAENTRY: ("metaEntry", BucketMetadata),
    }
