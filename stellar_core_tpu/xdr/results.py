"""Operation and transaction result types (reference: Stellar-transaction.x
result section; produced by src/transactions/*OpFrame::doApply and consumed by
history's TransactionHistoryResultEntry)."""

from __future__ import annotations

from enum import IntEnum

from .runtime import (
    Int32, Int64, Struct, Uint32, Uint64, Union, VarArray,
)
from .types import AccountID, ExtensionPoint, Hash, Uint256
from .ledger_entries import (
    Asset, ClaimableBalanceID, OfferEntry, PoolID,
)
from .transaction import OperationType


class ClaimAtomType(IntEnum):
    CLAIM_ATOM_TYPE_V0 = 0
    CLAIM_ATOM_TYPE_ORDER_BOOK = 1
    CLAIM_ATOM_TYPE_LIQUIDITY_POOL = 2


class ClaimOfferAtomV0(Struct):
    FIELDS = [
        ("sellerEd25519", Uint256),
        ("offerID", Int64),
        ("assetSold", Asset),
        ("amountSold", Int64),
        ("assetBought", Asset),
        ("amountBought", Int64),
    ]


class ClaimOfferAtom(Struct):
    FIELDS = [
        ("sellerID", AccountID),
        ("offerID", Int64),
        ("assetSold", Asset),
        ("amountSold", Int64),
        ("assetBought", Asset),
        ("amountBought", Int64),
    ]


class ClaimLiquidityAtom(Struct):
    FIELDS = [
        ("liquidityPoolID", PoolID),
        ("assetSold", Asset),
        ("amountSold", Int64),
        ("assetBought", Asset),
        ("amountBought", Int64),
    ]


class ClaimAtom(Union):
    SWITCH = ClaimAtomType
    ARMS = {
        ClaimAtomType.CLAIM_ATOM_TYPE_V0: ("v0", ClaimOfferAtomV0),
        ClaimAtomType.CLAIM_ATOM_TYPE_ORDER_BOOK:
            ("orderBook", ClaimOfferAtom),
        ClaimAtomType.CLAIM_ATOM_TYPE_LIQUIDITY_POOL:
            ("liquidityPool", ClaimLiquidityAtom),
    }


# --- per-operation result codes -------------------------------------------

class CreateAccountResultCode(IntEnum):
    CREATE_ACCOUNT_SUCCESS = 0
    CREATE_ACCOUNT_MALFORMED = -1
    CREATE_ACCOUNT_UNDERFUNDED = -2
    CREATE_ACCOUNT_LOW_RESERVE = -3
    CREATE_ACCOUNT_ALREADY_EXIST = -4


class CreateAccountResult(Union):
    SWITCH = CreateAccountResultCode
    ARMS = {CreateAccountResultCode.CREATE_ACCOUNT_SUCCESS: None}
    DEFAULT_ARM = None


class PaymentResultCode(IntEnum):
    PAYMENT_SUCCESS = 0
    PAYMENT_MALFORMED = -1
    PAYMENT_UNDERFUNDED = -2
    PAYMENT_SRC_NO_TRUST = -3
    PAYMENT_SRC_NOT_AUTHORIZED = -4
    PAYMENT_NO_DESTINATION = -5
    PAYMENT_NO_TRUST = -6
    PAYMENT_NOT_AUTHORIZED = -7
    PAYMENT_LINE_FULL = -8
    PAYMENT_NO_ISSUER = -9


class PaymentResult(Union):
    SWITCH = PaymentResultCode
    ARMS = {PaymentResultCode.PAYMENT_SUCCESS: None}
    DEFAULT_ARM = None


class SimplePaymentResult(Struct):
    FIELDS = [
        ("destination", AccountID),
        ("asset", Asset),
        ("amount", Int64),
    ]


class PathPaymentStrictReceiveResultCode(IntEnum):
    PATH_PAYMENT_STRICT_RECEIVE_SUCCESS = 0
    PATH_PAYMENT_STRICT_RECEIVE_MALFORMED = -1
    PATH_PAYMENT_STRICT_RECEIVE_UNDERFUNDED = -2
    PATH_PAYMENT_STRICT_RECEIVE_SRC_NO_TRUST = -3
    PATH_PAYMENT_STRICT_RECEIVE_SRC_NOT_AUTHORIZED = -4
    PATH_PAYMENT_STRICT_RECEIVE_NO_DESTINATION = -5
    PATH_PAYMENT_STRICT_RECEIVE_NO_TRUST = -6
    PATH_PAYMENT_STRICT_RECEIVE_NOT_AUTHORIZED = -7
    PATH_PAYMENT_STRICT_RECEIVE_LINE_FULL = -8
    PATH_PAYMENT_STRICT_RECEIVE_NO_ISSUER = -9
    PATH_PAYMENT_STRICT_RECEIVE_TOO_FEW_OFFERS = -10
    PATH_PAYMENT_STRICT_RECEIVE_OFFER_CROSS_SELF = -11
    PATH_PAYMENT_STRICT_RECEIVE_OVER_SENDMAX = -12


class _PathPaymentStrictReceiveSuccess(Struct):
    FIELDS = [
        ("offers", VarArray(ClaimAtom)),
        ("last", SimplePaymentResult),
    ]


class PathPaymentStrictReceiveResult(Union):
    SWITCH = PathPaymentStrictReceiveResultCode
    ARMS = {
        PathPaymentStrictReceiveResultCode.PATH_PAYMENT_STRICT_RECEIVE_SUCCESS:
            ("success", _PathPaymentStrictReceiveSuccess),
        PathPaymentStrictReceiveResultCode.PATH_PAYMENT_STRICT_RECEIVE_NO_ISSUER:
            ("noIssuer", Asset),
    }
    DEFAULT_ARM = None


class PathPaymentStrictSendResultCode(IntEnum):
    PATH_PAYMENT_STRICT_SEND_SUCCESS = 0
    PATH_PAYMENT_STRICT_SEND_MALFORMED = -1
    PATH_PAYMENT_STRICT_SEND_UNDERFUNDED = -2
    PATH_PAYMENT_STRICT_SEND_SRC_NO_TRUST = -3
    PATH_PAYMENT_STRICT_SEND_SRC_NOT_AUTHORIZED = -4
    PATH_PAYMENT_STRICT_SEND_NO_DESTINATION = -5
    PATH_PAYMENT_STRICT_SEND_NO_TRUST = -6
    PATH_PAYMENT_STRICT_SEND_NOT_AUTHORIZED = -7
    PATH_PAYMENT_STRICT_SEND_LINE_FULL = -8
    PATH_PAYMENT_STRICT_SEND_NO_ISSUER = -9
    PATH_PAYMENT_STRICT_SEND_TOO_FEW_OFFERS = -10
    PATH_PAYMENT_STRICT_SEND_OFFER_CROSS_SELF = -11
    PATH_PAYMENT_STRICT_SEND_UNDER_DESTMIN = -12


class _PathPaymentStrictSendSuccess(Struct):
    FIELDS = [
        ("offers", VarArray(ClaimAtom)),
        ("last", SimplePaymentResult),
    ]


class PathPaymentStrictSendResult(Union):
    SWITCH = PathPaymentStrictSendResultCode
    ARMS = {
        PathPaymentStrictSendResultCode.PATH_PAYMENT_STRICT_SEND_SUCCESS:
            ("success", _PathPaymentStrictSendSuccess),
        PathPaymentStrictSendResultCode.PATH_PAYMENT_STRICT_SEND_NO_ISSUER:
            ("noIssuer", Asset),
    }
    DEFAULT_ARM = None


class ManageSellOfferResultCode(IntEnum):
    MANAGE_SELL_OFFER_SUCCESS = 0
    MANAGE_SELL_OFFER_MALFORMED = -1
    MANAGE_SELL_OFFER_SELL_NO_TRUST = -2
    MANAGE_SELL_OFFER_BUY_NO_TRUST = -3
    MANAGE_SELL_OFFER_SELL_NOT_AUTHORIZED = -4
    MANAGE_SELL_OFFER_BUY_NOT_AUTHORIZED = -5
    MANAGE_SELL_OFFER_LINE_FULL = -6
    MANAGE_SELL_OFFER_UNDERFUNDED = -7
    MANAGE_SELL_OFFER_CROSS_SELF = -8
    MANAGE_SELL_OFFER_SELL_NO_ISSUER = -9
    MANAGE_SELL_OFFER_BUY_NO_ISSUER = -10
    MANAGE_SELL_OFFER_NOT_FOUND = -11
    MANAGE_SELL_OFFER_LOW_RESERVE = -12


class ManageOfferEffect(IntEnum):
    MANAGE_OFFER_CREATED = 0
    MANAGE_OFFER_UPDATED = 1
    MANAGE_OFFER_DELETED = 2


class _ManageOfferEffectUnion(Union):
    SWITCH = ManageOfferEffect
    ARMS = {
        ManageOfferEffect.MANAGE_OFFER_CREATED: ("offer", OfferEntry),
        ManageOfferEffect.MANAGE_OFFER_UPDATED: ("offer", OfferEntry),
    }
    DEFAULT_ARM = None


class ManageOfferSuccessResult(Struct):
    FIELDS = [
        ("offersClaimed", VarArray(ClaimAtom)),
        ("offer", _ManageOfferEffectUnion),
    ]


class ManageSellOfferResult(Union):
    SWITCH = ManageSellOfferResultCode
    ARMS = {
        ManageSellOfferResultCode.MANAGE_SELL_OFFER_SUCCESS:
            ("success", ManageOfferSuccessResult),
    }
    DEFAULT_ARM = None


class ManageBuyOfferResultCode(IntEnum):
    MANAGE_BUY_OFFER_SUCCESS = 0
    MANAGE_BUY_OFFER_MALFORMED = -1
    MANAGE_BUY_OFFER_SELL_NO_TRUST = -2
    MANAGE_BUY_OFFER_BUY_NO_TRUST = -3
    MANAGE_BUY_OFFER_SELL_NOT_AUTHORIZED = -4
    MANAGE_BUY_OFFER_BUY_NOT_AUTHORIZED = -5
    MANAGE_BUY_OFFER_LINE_FULL = -6
    MANAGE_BUY_OFFER_UNDERFUNDED = -7
    MANAGE_BUY_OFFER_CROSS_SELF = -8
    MANAGE_BUY_OFFER_SELL_NO_ISSUER = -9
    MANAGE_BUY_OFFER_BUY_NO_ISSUER = -10
    MANAGE_BUY_OFFER_NOT_FOUND = -11
    MANAGE_BUY_OFFER_LOW_RESERVE = -12


class ManageBuyOfferResult(Union):
    SWITCH = ManageBuyOfferResultCode
    ARMS = {
        ManageBuyOfferResultCode.MANAGE_BUY_OFFER_SUCCESS:
            ("success", ManageOfferSuccessResult),
    }
    DEFAULT_ARM = None


class SetOptionsResultCode(IntEnum):
    SET_OPTIONS_SUCCESS = 0
    SET_OPTIONS_LOW_RESERVE = -1
    SET_OPTIONS_TOO_MANY_SIGNERS = -2
    SET_OPTIONS_BAD_FLAGS = -3
    SET_OPTIONS_INVALID_INFLATION = -4
    SET_OPTIONS_CANT_CHANGE = -5
    SET_OPTIONS_UNKNOWN_FLAG = -6
    SET_OPTIONS_THRESHOLD_OUT_OF_RANGE = -7
    SET_OPTIONS_BAD_SIGNER = -8
    SET_OPTIONS_INVALID_HOME_DOMAIN = -9
    SET_OPTIONS_AUTH_REVOCABLE_REQUIRED = -10


class SetOptionsResult(Union):
    SWITCH = SetOptionsResultCode
    ARMS = {SetOptionsResultCode.SET_OPTIONS_SUCCESS: None}
    DEFAULT_ARM = None


class ChangeTrustResultCode(IntEnum):
    CHANGE_TRUST_SUCCESS = 0
    CHANGE_TRUST_MALFORMED = -1
    CHANGE_TRUST_NO_ISSUER = -2
    CHANGE_TRUST_INVALID_LIMIT = -3
    CHANGE_TRUST_LOW_RESERVE = -4
    CHANGE_TRUST_SELF_NOT_ALLOWED = -5
    CHANGE_TRUST_TRUST_LINE_MISSING = -6
    CHANGE_TRUST_CANNOT_DELETE = -7
    CHANGE_TRUST_NOT_AUTH_MAINTAIN_LIABILITIES = -8


class ChangeTrustResult(Union):
    SWITCH = ChangeTrustResultCode
    ARMS = {ChangeTrustResultCode.CHANGE_TRUST_SUCCESS: None}
    DEFAULT_ARM = None


class AllowTrustResultCode(IntEnum):
    ALLOW_TRUST_SUCCESS = 0
    ALLOW_TRUST_MALFORMED = -1
    ALLOW_TRUST_NO_TRUST_LINE = -2
    ALLOW_TRUST_TRUST_NOT_REQUIRED = -3
    ALLOW_TRUST_CANT_REVOKE = -4
    ALLOW_TRUST_SELF_NOT_ALLOWED = -5
    ALLOW_TRUST_LOW_RESERVE = -6


class AllowTrustResult(Union):
    SWITCH = AllowTrustResultCode
    ARMS = {AllowTrustResultCode.ALLOW_TRUST_SUCCESS: None}
    DEFAULT_ARM = None


class AccountMergeResultCode(IntEnum):
    ACCOUNT_MERGE_SUCCESS = 0
    ACCOUNT_MERGE_MALFORMED = -1
    ACCOUNT_MERGE_NO_ACCOUNT = -2
    ACCOUNT_MERGE_IMMUTABLE_SET = -3
    ACCOUNT_MERGE_HAS_SUB_ENTRIES = -4
    ACCOUNT_MERGE_SEQNUM_TOO_FAR = -5
    ACCOUNT_MERGE_DEST_FULL = -6
    ACCOUNT_MERGE_IS_SPONSOR = -7


class AccountMergeResult(Union):
    SWITCH = AccountMergeResultCode
    ARMS = {
        AccountMergeResultCode.ACCOUNT_MERGE_SUCCESS:
            ("sourceAccountBalance", Int64),
    }
    DEFAULT_ARM = None


class InflationResultCode(IntEnum):
    INFLATION_SUCCESS = 0
    INFLATION_NOT_TIME = -1


class InflationPayout(Struct):
    FIELDS = [("destination", AccountID), ("amount", Int64)]


class InflationResult(Union):
    SWITCH = InflationResultCode
    ARMS = {
        InflationResultCode.INFLATION_SUCCESS:
            ("payouts", VarArray(InflationPayout)),
    }
    DEFAULT_ARM = None


class ManageDataResultCode(IntEnum):
    MANAGE_DATA_SUCCESS = 0
    MANAGE_DATA_NOT_SUPPORTED_YET = -1
    MANAGE_DATA_NAME_NOT_FOUND = -2
    MANAGE_DATA_LOW_RESERVE = -3
    MANAGE_DATA_INVALID_NAME = -4


class ManageDataResult(Union):
    SWITCH = ManageDataResultCode
    ARMS = {ManageDataResultCode.MANAGE_DATA_SUCCESS: None}
    DEFAULT_ARM = None


class BumpSequenceResultCode(IntEnum):
    BUMP_SEQUENCE_SUCCESS = 0
    BUMP_SEQUENCE_BAD_SEQ = -1


class BumpSequenceResult(Union):
    SWITCH = BumpSequenceResultCode
    ARMS = {BumpSequenceResultCode.BUMP_SEQUENCE_SUCCESS: None}
    DEFAULT_ARM = None


class CreateClaimableBalanceResultCode(IntEnum):
    CREATE_CLAIMABLE_BALANCE_SUCCESS = 0
    CREATE_CLAIMABLE_BALANCE_MALFORMED = -1
    CREATE_CLAIMABLE_BALANCE_LOW_RESERVE = -2
    CREATE_CLAIMABLE_BALANCE_NO_TRUST = -3
    CREATE_CLAIMABLE_BALANCE_NOT_AUTHORIZED = -4
    CREATE_CLAIMABLE_BALANCE_UNDERFUNDED = -5


class CreateClaimableBalanceResult(Union):
    SWITCH = CreateClaimableBalanceResultCode
    ARMS = {
        CreateClaimableBalanceResultCode.CREATE_CLAIMABLE_BALANCE_SUCCESS:
            ("balanceID", ClaimableBalanceID),
    }
    DEFAULT_ARM = None


class ClaimClaimableBalanceResultCode(IntEnum):
    CLAIM_CLAIMABLE_BALANCE_SUCCESS = 0
    CLAIM_CLAIMABLE_BALANCE_DOES_NOT_EXIST = -1
    CLAIM_CLAIMABLE_BALANCE_CANNOT_CLAIM = -2
    CLAIM_CLAIMABLE_BALANCE_LINE_FULL = -3
    CLAIM_CLAIMABLE_BALANCE_NO_TRUST = -4
    CLAIM_CLAIMABLE_BALANCE_NOT_AUTHORIZED = -5


class ClaimClaimableBalanceResult(Union):
    SWITCH = ClaimClaimableBalanceResultCode
    ARMS = {
        ClaimClaimableBalanceResultCode.CLAIM_CLAIMABLE_BALANCE_SUCCESS: None,
    }
    DEFAULT_ARM = None


class BeginSponsoringFutureReservesResultCode(IntEnum):
    BEGIN_SPONSORING_FUTURE_RESERVES_SUCCESS = 0
    BEGIN_SPONSORING_FUTURE_RESERVES_MALFORMED = -1
    BEGIN_SPONSORING_FUTURE_RESERVES_ALREADY_SPONSORED = -2
    BEGIN_SPONSORING_FUTURE_RESERVES_RECURSIVE = -3


class BeginSponsoringFutureReservesResult(Union):
    SWITCH = BeginSponsoringFutureReservesResultCode
    ARMS = {
        BeginSponsoringFutureReservesResultCode
        .BEGIN_SPONSORING_FUTURE_RESERVES_SUCCESS: None,
    }
    DEFAULT_ARM = None


class EndSponsoringFutureReservesResultCode(IntEnum):
    END_SPONSORING_FUTURE_RESERVES_SUCCESS = 0
    END_SPONSORING_FUTURE_RESERVES_NOT_SPONSORED = -1


class EndSponsoringFutureReservesResult(Union):
    SWITCH = EndSponsoringFutureReservesResultCode
    ARMS = {
        EndSponsoringFutureReservesResultCode
        .END_SPONSORING_FUTURE_RESERVES_SUCCESS: None,
    }
    DEFAULT_ARM = None


class RevokeSponsorshipResultCode(IntEnum):
    REVOKE_SPONSORSHIP_SUCCESS = 0
    REVOKE_SPONSORSHIP_DOES_NOT_EXIST = -1
    REVOKE_SPONSORSHIP_NOT_SPONSOR = -2
    REVOKE_SPONSORSHIP_LOW_RESERVE = -3
    REVOKE_SPONSORSHIP_ONLY_TRANSFERABLE = -4
    REVOKE_SPONSORSHIP_MALFORMED = -5


class RevokeSponsorshipResult(Union):
    SWITCH = RevokeSponsorshipResultCode
    ARMS = {RevokeSponsorshipResultCode.REVOKE_SPONSORSHIP_SUCCESS: None}
    DEFAULT_ARM = None


class ClawbackResultCode(IntEnum):
    CLAWBACK_SUCCESS = 0
    CLAWBACK_MALFORMED = -1
    CLAWBACK_NOT_CLAWBACK_ENABLED = -2
    CLAWBACK_NO_TRUST = -3
    CLAWBACK_UNDERFUNDED = -4


class ClawbackResult(Union):
    SWITCH = ClawbackResultCode
    ARMS = {ClawbackResultCode.CLAWBACK_SUCCESS: None}
    DEFAULT_ARM = None


class ClawbackClaimableBalanceResultCode(IntEnum):
    CLAWBACK_CLAIMABLE_BALANCE_SUCCESS = 0
    CLAWBACK_CLAIMABLE_BALANCE_DOES_NOT_EXIST = -1
    CLAWBACK_CLAIMABLE_BALANCE_NOT_ISSUER = -2
    CLAWBACK_CLAIMABLE_BALANCE_NOT_CLAWBACK_ENABLED = -3


class ClawbackClaimableBalanceResult(Union):
    SWITCH = ClawbackClaimableBalanceResultCode
    ARMS = {
        ClawbackClaimableBalanceResultCode
        .CLAWBACK_CLAIMABLE_BALANCE_SUCCESS: None,
    }
    DEFAULT_ARM = None


class SetTrustLineFlagsResultCode(IntEnum):
    SET_TRUST_LINE_FLAGS_SUCCESS = 0
    SET_TRUST_LINE_FLAGS_MALFORMED = -1
    SET_TRUST_LINE_FLAGS_NO_TRUST_LINE = -2
    SET_TRUST_LINE_FLAGS_CANT_REVOKE = -3
    SET_TRUST_LINE_FLAGS_INVALID_STATE = -4
    SET_TRUST_LINE_FLAGS_LOW_RESERVE = -5


class SetTrustLineFlagsResult(Union):
    SWITCH = SetTrustLineFlagsResultCode
    ARMS = {SetTrustLineFlagsResultCode.SET_TRUST_LINE_FLAGS_SUCCESS: None}
    DEFAULT_ARM = None


class LiquidityPoolDepositResultCode(IntEnum):
    LIQUIDITY_POOL_DEPOSIT_SUCCESS = 0
    LIQUIDITY_POOL_DEPOSIT_MALFORMED = -1
    LIQUIDITY_POOL_DEPOSIT_NO_TRUST = -2
    LIQUIDITY_POOL_DEPOSIT_NOT_AUTHORIZED = -3
    LIQUIDITY_POOL_DEPOSIT_UNDERFUNDED = -4
    LIQUIDITY_POOL_DEPOSIT_LINE_FULL = -5
    LIQUIDITY_POOL_DEPOSIT_BAD_PRICE = -6
    LIQUIDITY_POOL_DEPOSIT_POOL_FULL = -7


class LiquidityPoolDepositResult(Union):
    SWITCH = LiquidityPoolDepositResultCode
    ARMS = {
        LiquidityPoolDepositResultCode.LIQUIDITY_POOL_DEPOSIT_SUCCESS: None,
    }
    DEFAULT_ARM = None


class LiquidityPoolWithdrawResultCode(IntEnum):
    LIQUIDITY_POOL_WITHDRAW_SUCCESS = 0
    LIQUIDITY_POOL_WITHDRAW_MALFORMED = -1
    LIQUIDITY_POOL_WITHDRAW_NO_TRUST = -2
    LIQUIDITY_POOL_WITHDRAW_UNDERFUNDED = -3
    LIQUIDITY_POOL_WITHDRAW_LINE_FULL = -4
    LIQUIDITY_POOL_WITHDRAW_UNDER_MINIMUM = -5


class LiquidityPoolWithdrawResult(Union):
    SWITCH = LiquidityPoolWithdrawResultCode
    ARMS = {
        LiquidityPoolWithdrawResultCode.LIQUIDITY_POOL_WITHDRAW_SUCCESS: None,
    }
    DEFAULT_ARM = None


# --- OperationResult -------------------------------------------------------

class OperationResultCode(IntEnum):
    opINNER = 0
    opBAD_AUTH = -1
    opNO_ACCOUNT = -2
    opNOT_SUPPORTED = -3
    opTOO_MANY_SUBENTRIES = -4
    opEXCEEDED_WORK_LIMIT = -5
    opTOO_MANY_SPONSORING = -6


class _OperationResultTr(Union):
    SWITCH = OperationType
    ARMS = {
        OperationType.CREATE_ACCOUNT:
            ("createAccountResult", CreateAccountResult),
        OperationType.PAYMENT: ("paymentResult", PaymentResult),
        OperationType.PATH_PAYMENT_STRICT_RECEIVE:
            ("pathPaymentStrictReceiveResult", PathPaymentStrictReceiveResult),
        OperationType.MANAGE_SELL_OFFER:
            ("manageSellOfferResult", ManageSellOfferResult),
        OperationType.CREATE_PASSIVE_SELL_OFFER:
            ("createPassiveSellOfferResult", ManageSellOfferResult),
        OperationType.SET_OPTIONS: ("setOptionsResult", SetOptionsResult),
        OperationType.CHANGE_TRUST: ("changeTrustResult", ChangeTrustResult),
        OperationType.ALLOW_TRUST: ("allowTrustResult", AllowTrustResult),
        OperationType.ACCOUNT_MERGE:
            ("accountMergeResult", AccountMergeResult),
        OperationType.INFLATION: ("inflationResult", InflationResult),
        OperationType.MANAGE_DATA: ("manageDataResult", ManageDataResult),
        OperationType.BUMP_SEQUENCE:
            ("bumpSeqResult", BumpSequenceResult),
        OperationType.MANAGE_BUY_OFFER:
            ("manageBuyOfferResult", ManageBuyOfferResult),
        OperationType.PATH_PAYMENT_STRICT_SEND:
            ("pathPaymentStrictSendResult", PathPaymentStrictSendResult),
        OperationType.CREATE_CLAIMABLE_BALANCE:
            ("createClaimableBalanceResult", CreateClaimableBalanceResult),
        OperationType.CLAIM_CLAIMABLE_BALANCE:
            ("claimClaimableBalanceResult", ClaimClaimableBalanceResult),
        OperationType.BEGIN_SPONSORING_FUTURE_RESERVES:
            ("beginSponsoringFutureReservesResult",
             BeginSponsoringFutureReservesResult),
        OperationType.END_SPONSORING_FUTURE_RESERVES:
            ("endSponsoringFutureReservesResult",
             EndSponsoringFutureReservesResult),
        OperationType.REVOKE_SPONSORSHIP:
            ("revokeSponsorshipResult", RevokeSponsorshipResult),
        OperationType.CLAWBACK: ("clawbackResult", ClawbackResult),
        OperationType.CLAWBACK_CLAIMABLE_BALANCE:
            ("clawbackClaimableBalanceResult", ClawbackClaimableBalanceResult),
        OperationType.SET_TRUST_LINE_FLAGS:
            ("setTrustLineFlagsResult", SetTrustLineFlagsResult),
        OperationType.LIQUIDITY_POOL_DEPOSIT:
            ("liquidityPoolDepositResult", LiquidityPoolDepositResult),
        OperationType.LIQUIDITY_POOL_WITHDRAW:
            ("liquidityPoolWithdrawResult", LiquidityPoolWithdrawResult),
    }


class OperationResult(Union):
    SWITCH = OperationResultCode
    ARMS = {OperationResultCode.opINNER: ("tr", _OperationResultTr)}
    DEFAULT_ARM = None


# --- TransactionResult -----------------------------------------------------

class TransactionResultCode(IntEnum):
    txFEE_BUMP_INNER_SUCCESS = 1
    txSUCCESS = 0
    txFAILED = -1
    txTOO_EARLY = -2
    txTOO_LATE = -3
    txMISSING_OPERATION = -4
    txBAD_SEQ = -5
    txBAD_AUTH = -6
    txINSUFFICIENT_BALANCE = -7
    txNO_ACCOUNT = -8
    txINSUFFICIENT_FEE = -9
    txBAD_AUTH_EXTRA = -10
    txINTERNAL_ERROR = -11
    txNOT_SUPPORTED = -12
    txFEE_BUMP_INNER_FAILED = -13
    txBAD_SPONSORSHIP = -14
    txBAD_MIN_SEQ_AGE_OR_GAP = -15
    txMALFORMED = -16
    txSOROBAN_INVALID = -17


class _InnerTxResultResult(Union):
    # The reference XDR enumerates every non-fee-bump code and has no
    # default, so txFEE_BUMP_INNER_SUCCESS/FAILED must fail strict decode
    # inside an inner result (Stellar-transaction.x InnerTransactionResult).
    SWITCH = TransactionResultCode
    ARMS = {
        TransactionResultCode.txSUCCESS:
            ("results", VarArray(OperationResult)),
        TransactionResultCode.txFAILED:
            ("results", VarArray(OperationResult)),
        **{code: None for code in TransactionResultCode
           if code not in (TransactionResultCode.txSUCCESS,
                           TransactionResultCode.txFAILED,
                           TransactionResultCode.txFEE_BUMP_INNER_SUCCESS,
                           TransactionResultCode.txFEE_BUMP_INNER_FAILED)},
    }


class InnerTransactionResult(Struct):
    FIELDS = [
        ("feeCharged", Int64),
        ("result", _InnerTxResultResult),
        ("ext", ExtensionPoint),
    ]


class InnerTransactionResultPair(Struct):
    FIELDS = [
        ("transactionHash", Hash),
        ("result", InnerTransactionResult),
    ]


class _TxResultResult(Union):
    SWITCH = TransactionResultCode
    ARMS = {
        TransactionResultCode.txFEE_BUMP_INNER_SUCCESS:
            ("innerResultPair", InnerTransactionResultPair),
        TransactionResultCode.txFEE_BUMP_INNER_FAILED:
            ("innerResultPair", InnerTransactionResultPair),
        TransactionResultCode.txSUCCESS:
            ("results", VarArray(OperationResult)),
        TransactionResultCode.txFAILED:
            ("results", VarArray(OperationResult)),
    }
    DEFAULT_ARM = None


class TransactionResult(Struct):
    FIELDS = [
        ("feeCharged", Int64),
        ("result", _TxResultResult),
        ("ext", ExtensionPoint),
    ]


class TransactionResultPair(Struct):
    FIELDS = [("transactionHash", Hash), ("result", TransactionResult)]


class TransactionResultSet(Struct):
    FIELDS = [("results", VarArray(TransactionResultPair))]
