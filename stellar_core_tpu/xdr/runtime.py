"""XDR (RFC 4506) runtime: declarative types with canonical serialization.

The reference builds on xdrpp codegen from `.x` files (reference:
src/Makefile.am:46-51, docs/architecture.md:50-52 — "single, standard XDR for
canonical (hashed) format, history, and inter-node messaging").  Our build
replaces codegen with a small declarative runtime: types are described once as
Python class declarations and get canonical pack/unpack, equality, ordering,
repr and deep-copy for free.  The canonical byte encoding is exactly XDR:
big-endian 4-byte words, length-prefixed variable data, 4-byte padding.

Design notes (TPU-first framework):
- Canonical bytes are the hash domain (ledger hashes, tx hashes, bucket
  hashes) so serialization must be total and deterministic — no floats, no
  maps, no implicit defaults in the encoding.
- Hot-path hashing feeds the batch signature verifier; `xdr_to_bytes` is kept
  allocation-light (single bytearray writer).
"""

from __future__ import annotations

import struct
from enum import IntEnum
from typing import Any, Dict, List, Optional as Opt, Sequence, Tuple, Type


class XdrError(Exception):
    """Raised on malformed XDR input or out-of-range values."""


# ---------------------------------------------------------------------------
# Native codec hookup (see native_codec.py / native/src/pyext/xdr_codec.cpp)
# ---------------------------------------------------------------------------

# every concrete Struct/Union class, in creation order; the native codec
# compiles this world into a C schema program
_XDR_REGISTRY: List[type] = []
# bumped on class creation and register_arm so the native program recompiles
_XDR_GEN = [0]
_NC: List[Any] = [None]   # None = not loaded, False = disabled/unavailable


def _nc():
    """The native codec state if usable for the current schema
    generation, else None (callers then take the Python path)."""
    ns = _NC[0]
    if ns is None:
        try:
            from . import native_codec
            ns = native_codec.state()
        except Exception:
            ns = None
        if ns is None:
            _NC[0] = False
            return None
        _NC[0] = ns
    elif ns is False:
        return None
    if ns.gen != _XDR_GEN[0]:
        ns.refresh()
    return ns if ns.ok else None


# ---------------------------------------------------------------------------
# Reader / writer
# ---------------------------------------------------------------------------

class Writer:
    __slots__ = ("buf",)

    def __init__(self) -> None:
        self.buf = bytearray()

    def u32(self, v: int) -> None:
        if not 0 <= v <= 0xFFFFFFFF:
            raise XdrError(f"uint32 out of range: {v}")
        self.buf += v.to_bytes(4, "big")

    def i32(self, v: int) -> None:
        if not -(2**31) <= v < 2**31:
            raise XdrError(f"int32 out of range: {v}")
        self.buf += struct.pack(">i", v)

    def u64(self, v: int) -> None:
        if not 0 <= v <= 0xFFFFFFFFFFFFFFFF:
            raise XdrError(f"uint64 out of range: {v}")
        self.buf += v.to_bytes(8, "big")

    def i64(self, v: int) -> None:
        if not -(2**63) <= v < 2**63:
            raise XdrError(f"int64 out of range: {v}")
        self.buf += struct.pack(">q", v)

    def raw(self, b: bytes) -> None:
        self.buf += b

    def opaque(self, b: bytes) -> None:
        self.buf += b
        pad = (-len(b)) % 4
        if pad:
            self.buf += b"\x00" * pad


class Reader:
    __slots__ = ("data", "pos")

    def __init__(self, data: bytes) -> None:
        self.data = data
        self.pos = 0

    def _take(self, n: int) -> bytes:
        if self.pos + n > len(self.data):
            raise XdrError("unexpected end of XDR input")
        b = self.data[self.pos:self.pos + n]
        self.pos += n
        return b

    def u32(self) -> int:
        return int.from_bytes(self._take(4), "big")

    def i32(self) -> int:
        return struct.unpack(">i", self._take(4))[0]

    def u64(self) -> int:
        return int.from_bytes(self._take(8), "big")

    def i64(self) -> int:
        return struct.unpack(">q", self._take(8))[0]

    def opaque(self, n: int) -> bytes:
        b = self._take(n)
        pad = (-n) % 4
        if pad:
            p = self._take(pad)
            if p != b"\x00" * pad:
                raise XdrError("non-zero XDR padding")
        return b

    def done(self) -> bool:
        return self.pos == len(self.data)


# ---------------------------------------------------------------------------
# Type descriptors
# ---------------------------------------------------------------------------

class XdrType:
    """A type descriptor: knows how to pack/unpack/validate one value."""

    def pack(self, w: Writer, v: Any) -> None:
        raise NotImplementedError

    def unpack(self, r: Reader) -> Any:
        raise NotImplementedError

    def default(self) -> Any:
        raise NotImplementedError


class _Int32(XdrType):
    def pack(self, w: Writer, v: Any) -> None:
        w.i32(int(v))

    def unpack(self, r: Reader) -> int:
        return r.i32()

    def default(self) -> int:
        return 0


class _Uint32(XdrType):
    def pack(self, w: Writer, v: Any) -> None:
        w.u32(int(v))

    def unpack(self, r: Reader) -> int:
        return r.u32()

    def default(self) -> int:
        return 0


class _Int64(XdrType):
    def pack(self, w: Writer, v: Any) -> None:
        w.i64(int(v))

    def unpack(self, r: Reader) -> int:
        return r.i64()

    def default(self) -> int:
        return 0


class _Uint64(XdrType):
    def pack(self, w: Writer, v: Any) -> None:
        w.u64(int(v))

    def unpack(self, r: Reader) -> int:
        return r.u64()

    def default(self) -> int:
        return 0


class _Bool(XdrType):
    def pack(self, w: Writer, v: Any) -> None:
        w.u32(1 if v else 0)

    def unpack(self, r: Reader) -> bool:
        v = r.u32()
        if v not in (0, 1):
            raise XdrError(f"invalid bool encoding {v}")
        return bool(v)

    def default(self) -> bool:
        return False


Int32 = _Int32()
Uint32 = _Uint32()
Int64 = _Int64()
Uint64 = _Uint64()
Bool = _Bool()


class Opaque(XdrType):
    """Fixed-length opaque bytes."""

    def __init__(self, n: int) -> None:
        self.n = n

    def pack(self, w: Writer, v: Any) -> None:
        b = bytes(v)
        if len(b) != self.n:
            raise XdrError(f"opaque[{self.n}] got {len(b)} bytes")
        w.opaque(b)

    def unpack(self, r: Reader) -> bytes:
        return r.opaque(self.n)

    def default(self) -> bytes:
        return b"\x00" * self.n


class VarOpaque(XdrType):
    """Variable-length opaque bytes with a max size."""

    def __init__(self, max_len: int = 0xFFFFFFFF) -> None:
        self.max_len = max_len

    def pack(self, w: Writer, v: Any) -> None:
        b = bytes(v)
        if len(b) > self.max_len:
            raise XdrError(f"opaque<{self.max_len}> got {len(b)} bytes")
        w.u32(len(b))
        w.opaque(b)

    def unpack(self, r: Reader) -> bytes:
        n = r.u32()
        if n > self.max_len:
            raise XdrError(f"opaque<{self.max_len}> got {n} bytes")
        return r.opaque(n)

    def default(self) -> bytes:
        return b""


class XdrString(VarOpaque):
    """XDR string — same wire format as VarOpaque; value kept as bytes
    (the reference keeps strings as raw bytes too; validation is the
    application's job, e.g. manage-data names)."""


class Array(XdrType):
    """Fixed-length array of an element type."""

    def __init__(self, elem: Any, n: int) -> None:
        self.elem = _resolve(elem)
        self.n = n

    def pack(self, w: Writer, v: Any) -> None:
        if len(v) != self.n:
            raise XdrError(f"array[{self.n}] got {len(v)} elements")
        for e in v:
            self.elem.pack(w, e)

    def unpack(self, r: Reader) -> list:
        return [self.elem.unpack(r) for _ in range(self.n)]

    def default(self) -> list:
        return [self.elem.default() for _ in range(self.n)]


class VarArray(XdrType):
    """Variable-length array with a max size."""

    def __init__(self, elem: Any, max_len: int = 0xFFFFFFFF) -> None:
        self.elem = _resolve(elem)
        self.max_len = max_len

    def pack(self, w: Writer, v: Any) -> None:
        if len(v) > self.max_len:
            raise XdrError(f"array<{self.max_len}> got {len(v)} elements")
        w.u32(len(v))
        for e in v:
            self.elem.pack(w, e)

    def unpack(self, r: Reader) -> list:
        n = r.u32()
        if n > self.max_len:
            raise XdrError(f"array<{self.max_len}> got {n} elements")
        return [self.elem.unpack(r) for _ in range(n)]

    def default(self) -> list:
        return []


class Optional(XdrType):
    """XDR optional (`*T`): bool presence flag then the value."""

    def __init__(self, elem: Any) -> None:
        self.elem = _resolve(elem)

    def pack(self, w: Writer, v: Any) -> None:
        if v is None:
            w.u32(0)
        else:
            w.u32(1)
            self.elem.pack(w, v)

    def unpack(self, r: Reader) -> Any:
        flag = r.u32()
        if flag == 0:
            return None
        if flag != 1:
            raise XdrError(f"invalid optional flag {flag}")
        return self.elem.unpack(r)

    def default(self) -> None:
        return None


class EnumType(XdrType):
    """Wraps a Python IntEnum as an XDR enum (strict: unknown values reject)."""

    def __init__(self, enum_cls: Type[IntEnum]) -> None:
        self.enum_cls = enum_cls
        self._members = enum_cls._value2member_map_

    def pack(self, w: Writer, v: Any) -> None:
        if v.__class__ is self.enum_cls:        # hot path: already typed
            w.i32(v._value_)
            return
        try:
            w.i32(int(self.enum_cls(v)))
        except ValueError:
            raise XdrError(
                f"invalid {self.enum_cls.__name__} value {v!r}") from None

    def unpack(self, r: Reader) -> IntEnum:
        raw = r.i32()
        m = self._members.get(raw)
        if m is None:
            raise XdrError(
                f"invalid {self.enum_cls.__name__} value {raw}")
        return m

    def default(self) -> IntEnum:
        return next(iter(self.enum_cls))


class Lazy(XdrType):
    """Deferred type reference for recursive XDR types (e.g. ClaimPredicate,
    SCPQuorumSet). Takes a zero-arg callable resolved on first use."""

    def __init__(self, thunk) -> None:
        self._thunk = thunk
        self._t: Opt[XdrType] = None

    def _get(self) -> XdrType:
        if self._t is None:
            self._t = _resolve(self._thunk())
        return self._t

    def pack(self, w: Writer, v: Any) -> None:
        self._get().pack(w, v)

    def unpack(self, r: Reader) -> Any:
        return self._get().unpack(r)

    def default(self) -> Any:
        return self._get().default()


_ENUM_TYPES: Dict[type, EnumType] = {}


def _resolve(t: Any) -> XdrType:
    """Accept XdrType instances, Struct/Union classes, and IntEnum classes."""
    if isinstance(t, XdrType):
        return t
    if isinstance(t, type) and issubclass(t, (Struct, Union)):
        return _Composite(t)
    if isinstance(t, type) and issubclass(t, IntEnum):
        et = _ENUM_TYPES.get(t)
        if et is None:
            et = _ENUM_TYPES[t] = EnumType(t)
        return et
    raise TypeError(f"not an XDR type: {t!r}")


class _Composite(XdrType):
    """Adapter: a Struct/Union class used as a field type."""

    def __init__(self, cls: type) -> None:
        self.cls = cls

    def pack(self, w: Writer, v: Any) -> None:
        if not isinstance(v, self.cls):
            raise XdrError(f"expected {self.cls.__name__}, got {type(v).__name__}")
        v._pack(w)

    def unpack(self, r: Reader) -> Any:
        return self.cls._unpack(r)

    def default(self) -> Any:
        return self.cls()


# ---------------------------------------------------------------------------
# Struct
# ---------------------------------------------------------------------------

def _emit_pack(ft, expr: str, ns: dict, uid: List[int],
               indent: str) -> List[str]:
    """Specialized pack statements for one value of type `ft` (falls
    back to the type's bound pack method when no specialization
    applies).  Scalar writes inline onto the Writer; composites call
    `._pack` directly, skipping the _Composite isinstance adapter."""
    i = uid[0]
    uid[0] += 1
    if isinstance(ft, _Int32):
        return [f"{indent}w.i32({expr})"]
    if isinstance(ft, _Uint32):
        return [f"{indent}w.u32({expr})"]
    if isinstance(ft, _Int64):
        return [f"{indent}w.i64({expr})"]
    if isinstance(ft, _Uint64):
        return [f"{indent}w.u64({expr})"]
    if isinstance(ft, _Bool):
        return [f"{indent}w.u32(1 if {expr} else 0)"]
    if isinstance(ft, _Composite):
        return [f"{indent}{expr}._pack(w)"]
    if isinstance(ft, Optional):
        tmp = f"_t{i}"
        inner = _emit_pack(ft.elem, tmp, ns, uid, indent + "    ")
        return ([f"{indent}{tmp} = {expr}",
                 f"{indent}if {tmp} is None:",
                 f"{indent}    w.u32(0)",
                 f"{indent}else:",
                 f"{indent}    w.u32(1)"] + inner)
    if isinstance(ft, VarArray):
        tmp = f"_t{i}"
        x = f"_x{i}"
        inner = _emit_pack(ft.elem, x, ns, uid, indent + "    ")
        out = [f"{indent}{tmp} = {expr}"]
        if ft.max_len < 0xFFFFFFFF:
            ns.setdefault("_XdrError", XdrError)
            out += [f"{indent}if len({tmp}) > {ft.max_len}:",
                    f"{indent}    raise _XdrError('array too long')"]
        out += [f"{indent}w.u32(len({tmp}))",
                f"{indent}for {x} in {tmp}:"] + inner
        return out
    # Opaque/VarOpaque/XdrString/EnumType/Array/Lazy: bound method
    ns[f"_p{i}"] = ft.pack
    return [f"{indent}_p{i}(w, {expr})"]


def _gen_struct_codecs(cls):
    """exec-specialized _pack/_unpack for one Struct type: straight-line
    per-field statements with scalar writes inlined — removes the
    generic loop/getattr/adapter overhead from the serialization hot
    path (hashing, DB writes, meta streams all funnel through here).
    On errors the generic slow path re-runs to produce the
    field-attributed message (the output buffer is abandoned by the
    raise either way)."""
    fields = cls._FIELDS
    pack_ns: dict = {}
    uid = [0]
    body: List[str] = []
    for fn, ft in fields:
        body += _emit_pack(ft, f"self.{fn}", pack_ns, uid, "    ")
    src = ["def _fast_pack(self, w):"] + (body or ["    pass"])
    exec("\n".join(src), pack_ns)          # noqa: S102 — trusted codegen
    fast_pack = pack_ns["_fast_pack"]

    def _pack(self, w):
        try:
            fast_pack(self, w)
        except (XdrError, AttributeError, TypeError):
            Struct._generic_pack(self, w)  # re-raise with field context
            raise                           # pragma: no cover (safety)

    unpack_ns = {("_u%d" % i): ft.unpack for i, (_, ft) in
                 enumerate(fields)}
    src = (["def _fast_unpack(cls, r):",
            "    obj = cls.__new__(cls)",
            "    d = obj.__dict__"] +
           ["    d['%s'] = _u%d(r)" % (fn, i)
            for i, (fn, _) in enumerate(fields)] +
           ["    return obj"])
    exec("\n".join(src), unpack_ns)        # noqa: S102 — trusted codegen
    return _pack, unpack_ns["_fast_unpack"]


def _clone_value(v: Any) -> Any:
    """Deep-copy an XDR field value (generic path for fields whose
    static type doesn't allow specialization — Lazy, nested optionals).
    Immutables (ints, bytes, str, None, enums, bools) are shared;
    Struct/Union recurse; sequences rebuild; mutable byte buffers
    snapshot to bytes."""
    cl = getattr(v, "clone", None)
    if cl is not None:
        return cl()
    t = v.__class__
    if t is list:
        return [_clone_value(x) for x in v]
    if t is tuple:
        return tuple(_clone_value(x) for x in v)
    if t is bytearray or t is memoryview:
        return bytes(v)
    return v


# clone modes: how to deep-copy a field of a given XDR type without
# generic dispatch (0: immutable leaf, 1: .clone(), 2: generic
# _clone_value, 3: bytes-ish, 4: list of leaves, 5: list of composites,
# 6: optional composite)
def _clone_mode(ft) -> int:
    if isinstance(ft, (_Int32, _Uint32, _Int64, _Uint64, _Bool, EnumType)):
        return 0
    if isinstance(ft, (Opaque, VarOpaque)):
        return 3
    if isinstance(ft, _Composite):
        return 1
    if isinstance(ft, (Array, VarArray)):
        em = _clone_mode(ft.elem)
        if em == 0:
            return 4
        if em == 1:
            return 5
        return 2
    if isinstance(ft, Optional):
        em = _clone_mode(ft.elem)
        if em == 0:
            return 0
        if em == 1:
            return 6
        return 2
    return 2


_CLONE_STMTS = {
    0: "    d['{f}'] = s['{f}']",
    1: "    d['{f}'] = s['{f}'].clone()",
    2: "    d['{f}'] = _cv(s['{f}'])",
    3: ("    _t = s['{f}']\n"
        "    d['{f}'] = _t if _t.__class__ is bytes else bytes(_t)"),
    4: "    d['{f}'] = list(s['{f}'])",
    5: "    d['{f}'] = [_x.clone() for _x in s['{f}']]",
    6: ("    _t = s['{f}']\n"
        "    d['{f}'] = None if _t is None else _t.clone()"),
}


def _gen_struct_clone(cls):
    """exec-specialized structural deep copy: straight-line per-field
    code chosen from the field's static XDR type — the LedgerTxn
    load/commit hot path runs this instead of generic recursion."""
    src = ["def _fast_clone(self):",
           "    obj = _new(_cls)",
           "    d = obj.__dict__",
           "    s = self.__dict__"]
    for fn, ft in cls._FIELDS:
        src.append(_CLONE_STMTS[_clone_mode(ft)].format(f=fn))
    src.append("    return obj")
    ns = {"_cls": cls, "_new": cls.__new__, "_cv": _clone_value}
    exec("\n".join(src), ns)               # noqa: S102 — trusted codegen
    return ns["_fast_clone"]


class _StructMeta(type):
    def __new__(mcls, name, bases, ns):
        cls = super().__new__(mcls, name, bases, ns)
        fields = ns.get("FIELDS")
        if fields is not None:
            cls._FIELDS = [(fn, _resolve(ft)) for fn, ft in fields]
            cls._FIELD_NAMES = tuple(fn for fn, _ in fields)
            pack, unpack = _gen_struct_codecs(cls)
            cls._pack = pack
            cls._unpack = classmethod(unpack)
            cls._py_clone = _gen_struct_clone(cls)
            _XDR_REGISTRY.append(cls)
            _XDR_GEN[0] += 1
        return cls


class Struct(metaclass=_StructMeta):
    """Declarative XDR struct.

    Subclasses set ``FIELDS = [("name", Type), ...]``; instances take keyword
    arguments (missing fields get XDR zero-defaults).
    """

    FIELDS: Sequence[Tuple[str, Any]] = []
    _FIELDS: List[Tuple[str, XdrType]] = []
    _FIELD_NAMES: Tuple[str, ...] = ()

    def __init__(self, **kw: Any) -> None:
        for fn, ft in self._FIELDS:
            if fn in kw:
                setattr(self, fn, kw.pop(fn))
            else:
                setattr(self, fn, ft.default())
        if kw:
            raise TypeError(
                f"{type(self).__name__}: unknown fields {sorted(kw)}")

    def _generic_pack(self, w: Writer) -> None:
        """Slow path kept for field-attributed error messages; the
        metaclass installs an exec-specialized _pack per subclass."""
        for fn, ft in self._FIELDS:
            try:
                ft.pack(w, getattr(self, fn))
            except XdrError as e:
                raise XdrError(f"{type(self).__name__}.{fn}: {e}") from None

    _pack = _generic_pack

    @classmethod
    def _unpack(cls, r: Reader) -> "Struct":
        obj = cls.__new__(cls)
        for fn, ft in cls._FIELDS:
            setattr(obj, fn, ft.unpack(r))
        return obj

    def to_bytes(self) -> bytes:
        nc = _nc()
        if nc is not None:
            try:
                return nc.pack(nc.cap, self.__class__._nidx, self)
            except Exception:
                pass   # Python path below re-raises with field context
        w = Writer()
        self._pack(w)
        return bytes(w.buf)

    @classmethod
    def from_bytes(cls, data: bytes) -> "Struct":
        nc = _nc()
        if nc is not None:
            try:
                return nc.unpack(nc.cap, cls._nidx, data)
            except Exception:
                pass   # Python path below re-raises with context
        r = Reader(data)
        obj = cls._unpack(r)
        if not r.done():
            raise XdrError(f"{cls.__name__}: {len(data) - r.pos} trailing bytes")
        return obj

    def clone(self) -> "Struct":
        """Structural deep copy — no serialize/parse roundtrip (the
        LedgerTxn aliasing-protection hot path). The native-codec check
        is inlined rather than routed through _nc(): clone is the
        single hottest XDR call in ledger replay (16.5k calls per 64
        ledgers, scripts/profile_catchup.py) and the extra function
        call + refresh bookkeeping measured ~60% overhead on top of
        the native clone itself."""
        cls = self.__class__
        ns = _NC[0]
        if ns is not None and ns is not False and ns.gen == _XDR_GEN[0] \
                and ns.ok:
            try:
                return ns.clone(ns.cap, cls._nidx, self)
            except Exception:
                pass
        elif (nc := _nc()) is not None:
            try:
                return nc.clone(nc.cap, cls._nidx, self)
            except Exception:
                pass
        pc = getattr(cls, "_py_clone", None)
        if pc is not None:
            return pc(self)
        obj = cls.__new__(cls)
        for fn in self._FIELD_NAMES:
            obj.__dict__[fn] = _clone_value(self.__dict__[fn])
        return obj

    def __eq__(self, other: Any) -> bool:
        if type(self) is not type(other):
            return NotImplemented
        return all(getattr(self, f) == getattr(other, f)
                   for f in self._FIELD_NAMES)

    def __hash__(self) -> int:
        return hash(self.to_bytes())

    def __lt__(self, other: Any) -> bool:
        # canonical-bytes ordering, matching xdrpp's operator< on serialized
        # form where the reference sorts XDR values
        return self.to_bytes() < other.to_bytes()

    def __repr__(self) -> str:
        parts = ", ".join(
            f"{f}={getattr(self, f)!r}" for f in self._FIELD_NAMES)
        return f"{type(self).__name__}({parts})"

    def copy(self) -> "Struct":
        return self.clone()


# ---------------------------------------------------------------------------
# Union
# ---------------------------------------------------------------------------

class _UnionMeta(type):
    def __new__(mcls, name, bases, ns):
        cls = super().__new__(mcls, name, bases, ns)
        arms = ns.get("ARMS")
        if arms:
            switch = ns.get("SWITCH")
            if switch is None:
                for b in bases:
                    switch = getattr(b, "SWITCH", None)
                    if switch is not None:
                        break
            cls._SWITCH = _resolve(switch)
            resolved: Dict[Any, Opt[Tuple[str, Opt[XdrType]]]] = {}
            for disc, arm in arms.items():
                if arm is None:
                    resolved[disc] = None  # void arm
                else:
                    an, at = arm
                    resolved[disc] = (an, _resolve(at) if at is not None else None)
            cls._ARMS = resolved
            default = ns.get("DEFAULT_ARM",
                             getattr(cls, "DEFAULT_ARM", "_missing_"))
            if default not in ("_missing_", None):
                an, at = default
                default = (an, _resolve(at) if at is not None else None)
            cls._DEFAULT_ARM = default
            # per-arm clone modes (see _clone_mode): void arms and leaf
            # payloads share, composites .clone(), anything else generic
            modes: Dict[Any, int] = {}
            for disc, arm in cls._ARMS.items():
                if arm is None or arm[1] is None:
                    modes[disc] = 0
                else:
                    modes[disc] = _clone_mode(arm[1])
            if default not in ("_missing_", None) and default[1] is not None:
                cls._DEFAULT_CLONE_MODE = _clone_mode(default[1])
            else:
                cls._DEFAULT_CLONE_MODE = 0 if default is None else 2
            cls._ARM_CLONE_MODES = modes
            # per-arm pack/unpack tables: one dict hit replaces the
            # _arm_for lookup + adapter dispatch on the (hot) wire path
            cls._ARM_PACKERS = {
                disc: (None if arm is None or arm[1] is None
                       else _arm_packer(arm[1]))
                for disc, arm in cls._ARMS.items()}
            cls._ARM_UNPACKERS = {
                disc: (arm[0] if arm is not None else None,
                       arm[1].unpack if arm is not None
                       and arm[1] is not None else None)
                for disc, arm in cls._ARMS.items()}
            if default == "_missing_":
                cls._DEFAULT_PACKER = "_missing_"
                cls._DEFAULT_UNPACKER = ("_missing_", None)
            elif default is None:               # void default arm
                cls._DEFAULT_PACKER = None
                cls._DEFAULT_UNPACKER = (None, None)
            else:
                cls._DEFAULT_PACKER = (None if default[1] is None
                                       else _arm_packer(default[1]))
                cls._DEFAULT_UNPACKER = (
                    default[0],
                    default[1].unpack if default[1] is not None else None)
            _XDR_REGISTRY.append(cls)
            _XDR_GEN[0] += 1
        return cls


def _pack_composite(w: Writer, v: Any) -> None:
    v._pack(w)


def _arm_packer(at: XdrType):
    """Direct packer for a union arm, skipping the adapter layer for
    composites (the dominant arm kind in the protocol)."""
    if isinstance(at, _Composite):
        return _pack_composite
    return at.pack


_UNSET = object()


class Union(metaclass=_UnionMeta):
    """Declarative XDR union.

    Subclasses set ``SWITCH`` (an enum class or integer XdrType) and
    ``ARMS = {disc_value: ("arm_name", ArmType) | ("arm_name", None) | None}``.
    ``None`` as the whole arm means void.  ``DEFAULT_ARM`` (same shapes) covers
    unlisted discriminants.  Construct as ``U(disc)`` for void arms or
    ``U(disc, value)`` / ``U(disc, arm_name=value)``.
    """

    SWITCH: Any = None
    ARMS: Dict[Any, Any] = {}
    _SWITCH: XdrType
    _ARMS: Dict[Any, Opt[Tuple[str, Opt[XdrType]]]]
    _DEFAULT_ARM: Any = "_missing_"

    def __init__(self, disc: Any = _UNSET, value: Any = _UNSET, **kw: Any) -> None:
        if disc is _UNSET:
            disc = self._SWITCH.default()
        self.disc = disc
        # inline the overwhelmingly common listed-arm hit; _arm_for
        # handles default arms and invalid discriminants
        arm = self._ARMS.get(disc, _UNSET)
        if arm is _UNSET:
            arm = self._arm_for(disc)
        if arm is None:
            if value is not _UNSET or kw:
                raise TypeError(f"{type(self).__name__}({disc!r}) is a void arm")
            self.arm_name = None
            self.value = None
            return
        an, at = arm
        self.arm_name = an
        if kw:
            if value is not _UNSET or list(kw) != [an]:
                raise TypeError(
                    f"{type(self).__name__}: expected keyword {an!r}")
            value = kw[an]
        if value is _UNSET:
            value = at.default() if at is not None else None
        self.value = value

    @classmethod
    def register_arm(cls, disc: Any, arm_name: Opt[str],
                     arm_type: Any) -> None:
        """Extend a union with a new arm after class creation (the
        protocol-extension hook used by xdr/contract.py) — keeps the
        precomputed pack/unpack/clone tables in sync with _ARMS."""
        if arm_name is None:
            cls.ARMS[disc] = None
            cls._ARMS[disc] = None
            cls._ARM_PACKERS[disc] = None
            cls._ARM_UNPACKERS[disc] = (None, None)
            cls._ARM_CLONE_MODES[disc] = 0
            return
        at = _resolve(arm_type) if arm_type is not None else None
        cls.ARMS[disc] = (arm_name, arm_type)
        cls._ARMS[disc] = (arm_name, at)
        cls._ARM_PACKERS[disc] = None if at is None else _arm_packer(at)
        cls._ARM_UNPACKERS[disc] = (
            arm_name, at.unpack if at is not None else None)
        cls._ARM_CLONE_MODES[disc] = 0 if at is None else _clone_mode(at)
        _XDR_GEN[0] += 1   # recompile the native schema program

    @classmethod
    def _arm_for(cls, disc: Any) -> Opt[Tuple[str, Opt[XdrType]]]:
        if disc in cls._ARMS:
            return cls._ARMS[disc]
        if cls._DEFAULT_ARM != "_missing_":
            return cls._DEFAULT_ARM
        raise XdrError(
            f"{cls.__name__}: invalid discriminant {disc!r}")

    def _pack(self, w: Writer) -> None:
        cls = self.__class__
        d = self.disc
        cls._SWITCH.pack(w, d)
        try:
            p = cls._ARM_PACKERS[d]
        except KeyError:
            p = cls._DEFAULT_PACKER
            if p == "_missing_":
                raise XdrError(
                    f"{cls.__name__}: invalid discriminant {d!r}") from None
        if p is not None:
            try:
                p(w, self.value)
            except (XdrError, AttributeError, TypeError) as e:
                an = (self.arm_name or "?")
                raise XdrError(
                    f"{cls.__name__}.{an}: {e}") from None

    @classmethod
    def _unpack(cls, r: Reader) -> "Union":
        disc = cls._SWITCH.unpack(r)
        obj = cls.__new__(cls)
        obj.disc = disc
        try:
            an, u = cls._ARM_UNPACKERS[disc]
        except KeyError:
            an, u = cls._DEFAULT_UNPACKER
            if an == "_missing_":
                raise XdrError(
                    f"{cls.__name__}: invalid discriminant {disc!r}") \
                    from None
        obj.arm_name = an
        obj.value = u(r) if u is not None else None
        return obj

    def to_bytes(self) -> bytes:
        nc = _nc()
        if nc is not None:
            try:
                return nc.pack(nc.cap, self.__class__._nidx, self)
            except Exception:
                pass   # Python path below re-raises with arm context
        w = Writer()
        self._pack(w)
        return bytes(w.buf)

    @classmethod
    def from_bytes(cls, data: bytes) -> "Union":
        nc = _nc()
        if nc is not None:
            try:
                return nc.unpack(nc.cap, cls._nidx, data)
            except Exception:
                pass   # Python path below re-raises with context
        r = Reader(data)
        obj = cls._unpack(r)
        if not r.done():
            raise XdrError(f"{cls.__name__}: {len(data) - r.pos} trailing bytes")
        return obj

    def clone(self) -> "Union":
        """Structural deep copy (see Struct.clone); arm payloads are
        copied per the statically computed per-arm clone mode. Native
        check inlined as in Struct.clone (hot path)."""
        cls = self.__class__
        ns = _NC[0]
        if ns is not None and ns is not False and ns.gen == _XDR_GEN[0] \
                and ns.ok:
            try:
                return ns.clone(ns.cap, cls._nidx, self)
            except Exception:
                pass
        elif (nc := _nc()) is not None:
            try:
                return nc.clone(nc.cap, cls._nidx, self)
            except Exception:
                pass
        obj = cls.__new__(cls)
        obj.disc = d = self.disc
        obj.arm_name = self.arm_name
        v = self.value
        m = cls._ARM_CLONE_MODES.get(d, cls._DEFAULT_CLONE_MODE)
        if m == 0:
            obj.value = v
        elif m == 1:
            obj.value = v.clone()
        elif m == 3:
            obj.value = v if v.__class__ is bytes else bytes(v)
        elif m == 4:
            obj.value = list(v)
        elif m == 5:
            obj.value = [x.clone() for x in v]
        elif m == 6:
            obj.value = None if v is None else v.clone()
        else:
            obj.value = _clone_value(v)
        return obj

    def __eq__(self, other: Any) -> bool:
        if type(self) is not type(other):
            return NotImplemented
        return self.disc == other.disc and self.value == other.value

    def __hash__(self) -> int:
        return hash(self.to_bytes())

    def __lt__(self, other: Any) -> bool:
        return self.to_bytes() < other.to_bytes()

    def __repr__(self) -> str:
        if self.arm_name is None:
            return f"{type(self).__name__}({self.disc!r})"
        return f"{type(self).__name__}({self.disc!r}, {self.value!r})"

    def copy(self) -> "Union":
        return self.clone()


# ---------------------------------------------------------------------------
# Helpers
# ---------------------------------------------------------------------------

def xdr_to_bytes(v: Any) -> bytes:
    """Serialize any XDR value (struct/union instance)."""
    return v.to_bytes()


def xdr_from_bytes(cls: type, data: bytes) -> Any:
    return cls.from_bytes(data)
