"""Compile the declarative XDR type world into the _scxdr C codec.

The runtime (xdr/runtime.py) stays the semantic oracle; this module
walks every registered Struct/Union class, flattens the type graph into
a node program, and hands it to the C extension
(native/src/pyext/xdr_codec.cpp).  runtime.py dispatches
to_bytes/from_bytes/clone through here when the extension is available,
falling back to the Python path on any error so messages and edge-case
behavior are unchanged (reference equivalent: xdrpp's generated C++
codecs, src/Makefile.am:46-51).

Disable with SC_XDR_NATIVE=0 (tests exercise both paths).
"""

from __future__ import annotations

import importlib.util
import os
import subprocess
import sys
import sysconfig
import threading

# node kind codes — must match enum Kind in xdr_codec.cpp
K_I32, K_U32, K_I64, K_U64, K_BOOL = 0, 1, 2, 3, 4
K_OPAQUE, K_VAROPAQUE, K_ARRAY, K_VARARRAY, K_OPT = 5, 6, 7, 8, 9
K_ENUM, K_STRUCT, K_UNION = 10, 11, 12

_PKG = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SRC = os.path.join(_PKG, "native", "src", "pyext", "xdr_codec.cpp")
_BUILD = os.path.join(_PKG, "native", "build")
_SO = os.path.join(_BUILD, "_scxdr.so")


def build_ext(force: bool = False) -> str:
    os.makedirs(_BUILD, exist_ok=True)
    # >= : a fresh checkout gives source and prebuilt .so near-identical
    # mtimes; treat that as up to date rather than demanding a toolchain
    if (not force and os.path.exists(_SO)
            and os.path.getmtime(_SO) >= os.path.getmtime(_SRC)):
        return _SO
    inc = sysconfig.get_paths()["include"]
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC",
           "-fvisibility=hidden", f"-I{inc}", "-o", _SO, _SRC]
    try:
        subprocess.run(cmd, check=True, capture_output=True)
    except Exception:
        if os.path.exists(_SO):   # stale beats none: the differential
            return _SO            # tests gate correctness either way
        raise
    return _SO


def _load_ext():
    spec = importlib.util.spec_from_file_location("_scxdr", build_ext())
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class NativeCodec:
    """Holds the loaded extension + the compiled program for the current
    schema generation.  runtime._nc() refreshes on generation bumps
    (class creation, register_arm)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.ext = None
        self.cap = None
        self.gen = -1
        self.ok = False
        self.pack = None
        self.unpack = None
        self.clone = None
        self._failed = False

    def refresh(self) -> None:
        from . import runtime
        with self._lock:
            if self.gen == runtime._XDR_GEN[0]:
                return
            if self._failed:
                self.gen = runtime._XDR_GEN[0]
                return
            try:
                if self.ext is None:
                    self.ext = _load_ext()
                self.cap = self._compile(runtime)
                self.pack = self.ext.pack
                self.unpack = self.ext.unpack
                self.clone = self.ext.clone
                self.gen = runtime._XDR_GEN[0]
                self.ok = True
            except Exception:
                # no native toolchain / build failure: permanent Python
                # fallback for this process
                self._failed = True
                self.ok = False
                self.gen = runtime._XDR_GEN[0]

    def _compile(self, runtime):
        nodes: list = []
        memo_t: dict = {}
        memo_c: dict = {}
        keep: list = []   # keep XdrType instances alive for id() keys

        def t_idx(t) -> int:
            while isinstance(t, runtime.Lazy):
                t = t._get()
            if isinstance(t, runtime._Composite):
                return c_idx(t.cls)
            k = id(t)
            got = memo_t.get(k)
            if got is not None:
                return got
            keep.append(t)
            if isinstance(t, runtime._Int32):
                node = (K_I32,)
            elif isinstance(t, runtime._Uint32):
                node = (K_U32,)
            elif isinstance(t, runtime._Int64):
                node = (K_I64,)
            elif isinstance(t, runtime._Uint64):
                node = (K_U64,)
            elif isinstance(t, runtime._Bool):
                node = (K_BOOL,)
            elif isinstance(t, runtime.Opaque):
                node = (K_OPAQUE, t.n)
            elif isinstance(t, runtime.VarOpaque):   # incl. XdrString
                node = (K_VAROPAQUE, t.max_len)
            elif isinstance(t, runtime.EnumType):
                vmap = {int(v): m
                        for v, m in t.enum_cls._value2member_map_.items()}
                node = (K_ENUM, t.enum_cls, vmap)
            elif isinstance(t, (runtime.Array, runtime.VarArray)):
                # reserve slot first: element may cycle back
                i = len(nodes)
                nodes.append(None)
                memo_t[k] = i
                kind = (K_ARRAY if isinstance(t, runtime.Array)
                        else K_VARARRAY)
                lim = t.n if kind == K_ARRAY else t.max_len
                nodes[i] = (kind, lim, t_idx(t.elem))
                return i
            elif isinstance(t, runtime.Optional):
                i = len(nodes)
                nodes.append(None)
                memo_t[k] = i
                nodes[i] = (K_OPT, t_idx(t.elem))
                return i
            else:
                raise TypeError(f"uncompilable XDR type {t!r}")
            i = len(nodes)
            nodes.append(node)
            memo_t[k] = i
            return i

        def c_idx(cls) -> int:
            got = memo_c.get(cls)
            if got is not None:
                return got
            i = len(nodes)
            nodes.append(None)
            memo_c[cls] = i
            if issubclass(cls, runtime.Struct):
                names = []
                idxs = []
                for fn, ft in cls._FIELDS:
                    names.append(sys.intern(fn))
                    idxs.append(t_idx(ft))
                nodes[i] = (K_STRUCT, cls, tuple(names), tuple(idxs))
            else:
                sw = t_idx(cls._SWITCH)
                arms = {}
                for disc, arm in cls._ARMS.items():
                    if arm is None:
                        arms[int(disc)] = (None, -1)
                    else:
                        an, at = arm
                        arms[int(disc)] = (
                            sys.intern(an),
                            t_idx(at) if at is not None else -1)
                d = cls._DEFAULT_ARM
                if d == "_missing_":
                    dd: object = 0          # int = "missing" marker
                elif d is None:
                    dd = None               # void default arm
                else:
                    an, at = d
                    dd = (sys.intern(an) if an is not None else None,
                          t_idx(at) if at is not None else -1)
                nodes[i] = (K_UNION, cls, sw, arms, dd)
            return i

        for cls in list(runtime._XDR_REGISTRY):
            cls._nidx = c_idx(cls)
        cap = self.ext.build(nodes, runtime.XdrError)
        self._keep = (nodes, keep)
        return cap


_STATE: NativeCodec | None = None
_DISABLED = os.environ.get("SC_XDR_NATIVE", "1") == "0"


def state() -> NativeCodec | None:
    global _STATE
    if _DISABLED:
        return None
    if _STATE is None:
        _STATE = NativeCodec()
    return _STATE
