"""Transaction types (reference: Stellar-transaction.x; consumed by
src/transactions/TransactionFrame* and the 24 operation frames).

Classic operations are complete. Soroban op bodies (INVOKE_HOST_FUNCTION,
EXTEND_FOOTPRINT_TTL, RESTORE_FOOTPRINT) arrive with the soroban layer
(SURVEY.md §7 step 8).
"""

from __future__ import annotations

from enum import IntEnum

from .runtime import (
    Array, Bool, Int32, Int64, Opaque, Optional, Struct, Uint32, Uint64,
    Union, VarArray, VarOpaque, XdrString,
)
from .types import (
    AccountID, CryptoKeyType, EnvelopeType, ExtensionPoint, Hash, PublicKey,
    Signature, SignatureHint, SignerKey, Uint256,
)
from .ledger_entries import (
    AlphaNum4, AlphaNum12, Asset, AssetCode, AssetType, ClaimableBalanceID,
    Claimant, LedgerKey, LiquidityPoolConstantProductParameters,
    LiquidityPoolType, OfferEntry, PoolID, Price, Signer, String32, String64,
    DataValue, TrustLineAsset,
)

MAX_OPS_PER_TX = 100
MAX_PATH_LENGTH = 5

class LiquidityPoolParameters(Union):
    SWITCH = LiquidityPoolType
    ARMS = {
        LiquidityPoolType.LIQUIDITY_POOL_CONSTANT_PRODUCT:
            ("constantProduct", LiquidityPoolConstantProductParameters),
    }


_LPParams = LiquidityPoolParameters


class _MuxedAccountMed25519(Struct):
    FIELDS = [("id", Uint64), ("ed25519", Uint256)]


class MuxedAccount(Union):
    SWITCH = CryptoKeyType
    ARMS = {
        CryptoKeyType.KEY_TYPE_ED25519: ("ed25519", Uint256),
        CryptoKeyType.KEY_TYPE_MUXED_ED25519:
            ("med25519", _MuxedAccountMed25519),
    }

    @classmethod
    def from_ed25519(cls, raw: bytes) -> "MuxedAccount":
        return cls(CryptoKeyType.KEY_TYPE_ED25519, raw)

    def account_id(self) -> PublicKey:
        """Strip the mux (reference: transactions/TransactionUtils
        toAccountID). Memoized: the apply path asks ~18x per tx and the
        result is only ever read (entries that embed it clone first)."""
        memo = getattr(self, "_acct_memo", None)
        if memo is None:
            if self.disc == CryptoKeyType.KEY_TYPE_ED25519:
                memo = PublicKey.ed25519(self.value)
            else:
                memo = PublicKey.ed25519(self.value.ed25519)
            self._acct_memo = memo
        return memo


class DecoratedSignature(Struct):
    FIELDS = [("hint", SignatureHint), ("signature", Signature)]


class OperationType(IntEnum):
    CREATE_ACCOUNT = 0
    PAYMENT = 1
    PATH_PAYMENT_STRICT_RECEIVE = 2
    MANAGE_SELL_OFFER = 3
    CREATE_PASSIVE_SELL_OFFER = 4
    SET_OPTIONS = 5
    CHANGE_TRUST = 6
    ALLOW_TRUST = 7
    ACCOUNT_MERGE = 8
    INFLATION = 9
    MANAGE_DATA = 10
    BUMP_SEQUENCE = 11
    MANAGE_BUY_OFFER = 12
    PATH_PAYMENT_STRICT_SEND = 13
    CREATE_CLAIMABLE_BALANCE = 14
    CLAIM_CLAIMABLE_BALANCE = 15
    BEGIN_SPONSORING_FUTURE_RESERVES = 16
    END_SPONSORING_FUTURE_RESERVES = 17
    REVOKE_SPONSORSHIP = 18
    CLAWBACK = 19
    CLAWBACK_CLAIMABLE_BALANCE = 20
    SET_TRUST_LINE_FLAGS = 21
    LIQUIDITY_POOL_DEPOSIT = 22
    LIQUIDITY_POOL_WITHDRAW = 23
    INVOKE_HOST_FUNCTION = 24
    EXTEND_FOOTPRINT_TTL = 25
    RESTORE_FOOTPRINT = 26


class CreateAccountOp(Struct):
    FIELDS = [("destination", AccountID), ("startingBalance", Int64)]


class PaymentOp(Struct):
    FIELDS = [
        ("destination", MuxedAccount),
        ("asset", Asset),
        ("amount", Int64),
    ]


class PathPaymentStrictReceiveOp(Struct):
    FIELDS = [
        ("sendAsset", Asset),
        ("sendMax", Int64),
        ("destination", MuxedAccount),
        ("destAsset", Asset),
        ("destAmount", Int64),
        ("path", VarArray(Asset, MAX_PATH_LENGTH)),
    ]


class PathPaymentStrictSendOp(Struct):
    FIELDS = [
        ("sendAsset", Asset),
        ("sendAmount", Int64),
        ("destination", MuxedAccount),
        ("destAsset", Asset),
        ("destMin", Int64),
        ("path", VarArray(Asset, MAX_PATH_LENGTH)),
    ]


class ManageSellOfferOp(Struct):
    FIELDS = [
        ("selling", Asset),
        ("buying", Asset),
        ("amount", Int64),
        ("price", Price),
        ("offerID", Int64),
    ]


class ManageBuyOfferOp(Struct):
    FIELDS = [
        ("selling", Asset),
        ("buying", Asset),
        ("buyAmount", Int64),
        ("price", Price),
        ("offerID", Int64),
    ]


class CreatePassiveSellOfferOp(Struct):
    FIELDS = [
        ("selling", Asset),
        ("buying", Asset),
        ("amount", Int64),
        ("price", Price),
    ]


class SetOptionsOp(Struct):
    FIELDS = [
        ("inflationDest", Optional(AccountID)),
        ("clearFlags", Optional(Uint32)),
        ("setFlags", Optional(Uint32)),
        ("masterWeight", Optional(Uint32)),
        ("lowThreshold", Optional(Uint32)),
        ("medThreshold", Optional(Uint32)),
        ("highThreshold", Optional(Uint32)),
        ("homeDomain", Optional(String32)),
        ("signer", Optional(Signer)),
    ]


class ChangeTrustAsset(Union):
    SWITCH = AssetType
    ARMS = {
        AssetType.ASSET_TYPE_NATIVE: None,
        AssetType.ASSET_TYPE_CREDIT_ALPHANUM4: ("alphaNum4", AlphaNum4),
        AssetType.ASSET_TYPE_CREDIT_ALPHANUM12: ("alphaNum12", AlphaNum12),
        AssetType.ASSET_TYPE_POOL_SHARE: ("liquidityPool", _LPParams),
    }


class ChangeTrustOp(Struct):
    FIELDS = [("line", ChangeTrustAsset), ("limit", Int64)]


class AllowTrustOp(Struct):
    FIELDS = [
        ("trustor", AccountID),
        ("asset", AssetCode),
        ("authorize", Uint32),
    ]


class ManageDataOp(Struct):
    FIELDS = [("dataName", String64), ("dataValue", Optional(DataValue))]


class BumpSequenceOp(Struct):
    FIELDS = [("bumpTo", Int64)]


class CreateClaimableBalanceOp(Struct):
    FIELDS = [
        ("asset", Asset),
        ("amount", Int64),
        ("claimants", VarArray(Claimant, 10)),
    ]


class ClaimClaimableBalanceOp(Struct):
    FIELDS = [("balanceID", ClaimableBalanceID)]


class BeginSponsoringFutureReservesOp(Struct):
    FIELDS = [("sponsoredID", AccountID)]


class RevokeSponsorshipType(IntEnum):
    REVOKE_SPONSORSHIP_LEDGER_ENTRY = 0
    REVOKE_SPONSORSHIP_SIGNER = 1


class _RevokeSponsorshipSigner(Struct):
    FIELDS = [("accountID", AccountID), ("signerKey", SignerKey)]


class RevokeSponsorshipOp(Union):
    SWITCH = RevokeSponsorshipType
    ARMS = {
        RevokeSponsorshipType.REVOKE_SPONSORSHIP_LEDGER_ENTRY:
            ("ledgerKey", LedgerKey),
        RevokeSponsorshipType.REVOKE_SPONSORSHIP_SIGNER:
            ("signer", _RevokeSponsorshipSigner),
    }


class ClawbackOp(Struct):
    FIELDS = [
        ("asset", Asset),
        ("from_", MuxedAccount),
        ("amount", Int64),
    ]


class ClawbackClaimableBalanceOp(Struct):
    FIELDS = [("balanceID", ClaimableBalanceID)]


class SetTrustLineFlagsOp(Struct):
    FIELDS = [
        ("trustor", AccountID),
        ("asset", Asset),
        ("clearFlags", Uint32),
        ("setFlags", Uint32),
    ]


class LiquidityPoolDepositOp(Struct):
    FIELDS = [
        ("liquidityPoolID", PoolID),
        ("maxAmountA", Int64),
        ("maxAmountB", Int64),
        ("minPrice", Price),
        ("maxPrice", Price),
    ]


class LiquidityPoolWithdrawOp(Struct):
    FIELDS = [
        ("liquidityPoolID", PoolID),
        ("amount", Int64),
        ("minAmountA", Int64),
        ("minAmountB", Int64),
    ]


class _OperationBody(Union):
    SWITCH = OperationType
    ARMS = {
        OperationType.CREATE_ACCOUNT: ("createAccountOp", CreateAccountOp),
        OperationType.PAYMENT: ("paymentOp", PaymentOp),
        OperationType.PATH_PAYMENT_STRICT_RECEIVE:
            ("pathPaymentStrictReceiveOp", PathPaymentStrictReceiveOp),
        OperationType.MANAGE_SELL_OFFER:
            ("manageSellOfferOp", ManageSellOfferOp),
        OperationType.CREATE_PASSIVE_SELL_OFFER:
            ("createPassiveSellOfferOp", CreatePassiveSellOfferOp),
        OperationType.SET_OPTIONS: ("setOptionsOp", SetOptionsOp),
        OperationType.CHANGE_TRUST: ("changeTrustOp", ChangeTrustOp),
        OperationType.ALLOW_TRUST: ("allowTrustOp", AllowTrustOp),
        OperationType.ACCOUNT_MERGE: ("destination", MuxedAccount),
        OperationType.INFLATION: None,
        OperationType.MANAGE_DATA: ("manageDataOp", ManageDataOp),
        OperationType.BUMP_SEQUENCE: ("bumpSequenceOp", BumpSequenceOp),
        OperationType.MANAGE_BUY_OFFER:
            ("manageBuyOfferOp", ManageBuyOfferOp),
        OperationType.PATH_PAYMENT_STRICT_SEND:
            ("pathPaymentStrictSendOp", PathPaymentStrictSendOp),
        OperationType.CREATE_CLAIMABLE_BALANCE:
            ("createClaimableBalanceOp", CreateClaimableBalanceOp),
        OperationType.CLAIM_CLAIMABLE_BALANCE:
            ("claimClaimableBalanceOp", ClaimClaimableBalanceOp),
        OperationType.BEGIN_SPONSORING_FUTURE_RESERVES:
            ("beginSponsoringFutureReservesOp",
             BeginSponsoringFutureReservesOp),
        OperationType.END_SPONSORING_FUTURE_RESERVES: None,
        OperationType.REVOKE_SPONSORSHIP:
            ("revokeSponsorshipOp", RevokeSponsorshipOp),
        OperationType.CLAWBACK: ("clawbackOp", ClawbackOp),
        OperationType.CLAWBACK_CLAIMABLE_BALANCE:
            ("clawbackClaimableBalanceOp", ClawbackClaimableBalanceOp),
        OperationType.SET_TRUST_LINE_FLAGS:
            ("setTrustLineFlagsOp", SetTrustLineFlagsOp),
        OperationType.LIQUIDITY_POOL_DEPOSIT:
            ("liquidityPoolDepositOp", LiquidityPoolDepositOp),
        OperationType.LIQUIDITY_POOL_WITHDRAW:
            ("liquidityPoolWithdrawOp", LiquidityPoolWithdrawOp),
    }


class Operation(Struct):
    FIELDS = [
        ("sourceAccount", Optional(MuxedAccount)),
        ("body", _OperationBody),
    ]


class HashIDPreimageOperationID(Struct):
    FIELDS = [
        ("sourceAccount", AccountID),
        ("seqNum", Int64),
        ("opNum", Uint32),
    ]


class HashIDPreimageRevokeID(Struct):
    FIELDS = [
        ("sourceAccount", AccountID),
        ("seqNum", Int64),
        ("opNum", Uint32),
        ("liquidityPoolID", PoolID),
        ("asset", Asset),
    ]


class HashIDPreimage(Union):
    """Preimages for hash-derived ids (reference: Stellar-transaction.x
    HashIDPreimage; used for claimable-balance ids and pool-revoke ids)."""
    SWITCH = EnvelopeType
    ARMS = {
        EnvelopeType.ENVELOPE_TYPE_OP_ID:
            ("operationID", HashIDPreimageOperationID),
        EnvelopeType.ENVELOPE_TYPE_POOL_REVOKE_OP_ID:
            ("revokeID", HashIDPreimageRevokeID),
    }


class MemoType(IntEnum):
    MEMO_NONE = 0
    MEMO_TEXT = 1
    MEMO_ID = 2
    MEMO_HASH = 3
    MEMO_RETURN = 4


class Memo(Union):
    SWITCH = MemoType
    ARMS = {
        MemoType.MEMO_NONE: None,
        MemoType.MEMO_TEXT: ("text", XdrString(28)),
        MemoType.MEMO_ID: ("id", Uint64),
        MemoType.MEMO_HASH: ("hash", Hash),
        MemoType.MEMO_RETURN: ("retHash", Hash),
    }


class TimeBounds(Struct):
    FIELDS = [("minTime", Uint64), ("maxTime", Uint64)]


class LedgerBounds(Struct):
    FIELDS = [("minLedger", Uint32), ("maxLedger", Uint32)]


class PreconditionsV2(Struct):
    FIELDS = [
        ("timeBounds", Optional(TimeBounds)),
        ("ledgerBounds", Optional(LedgerBounds)),
        ("minSeqNum", Optional(Int64)),
        ("minSeqAge", Uint64),
        ("minSeqLedgerGap", Uint32),
        ("extraSigners", VarArray(SignerKey, 2)),
    ]


class PreconditionType(IntEnum):
    PRECOND_NONE = 0
    PRECOND_TIME = 1
    PRECOND_V2 = 2


class Preconditions(Union):
    SWITCH = PreconditionType
    ARMS = {
        PreconditionType.PRECOND_NONE: None,
        PreconditionType.PRECOND_TIME: ("timeBounds", TimeBounds),
        PreconditionType.PRECOND_V2: ("v2", PreconditionsV2),
    }


class _TxExt(Union):
    SWITCH = Int32
    ARMS = {0: None}


class Transaction(Struct):
    FIELDS = [
        ("sourceAccount", MuxedAccount),
        ("fee", Uint32),
        ("seqNum", Int64),
        ("cond", Preconditions),
        ("memo", Memo),
        ("operations", VarArray(Operation, MAX_OPS_PER_TX)),
        ("ext", _TxExt),
    ]


class TransactionV0(Struct):
    """Legacy pre-protocol-13 envelope body (reference: Stellar-transaction.x
    TransactionV0; still accepted on the wire, hashed as ENVELOPE_TYPE_TX with
    upgraded source account)."""
    FIELDS = [
        ("sourceAccountEd25519", Uint256),
        ("fee", Uint32),
        ("seqNum", Int64),
        ("timeBounds", Optional(TimeBounds)),
        ("memo", Memo),
        ("operations", VarArray(Operation, MAX_OPS_PER_TX)),
        ("ext", _TxExt),
    ]


class TransactionV0Envelope(Struct):
    FIELDS = [
        ("tx", TransactionV0),
        ("signatures", VarArray(DecoratedSignature, 20)),
    ]


class TransactionV1Envelope(Struct):
    FIELDS = [
        ("tx", Transaction),
        ("signatures", VarArray(DecoratedSignature, 20)),
    ]


class _FeeBumpInnerTx(Union):
    SWITCH = EnvelopeType
    ARMS = {EnvelopeType.ENVELOPE_TYPE_TX: ("v1", TransactionV1Envelope)}


class FeeBumpTransaction(Struct):
    FIELDS = [
        ("feeSource", MuxedAccount),
        ("fee", Int64),
        ("innerTx", _FeeBumpInnerTx),
        ("ext", _TxExt),
    ]


class FeeBumpTransactionEnvelope(Struct):
    FIELDS = [
        ("tx", FeeBumpTransaction),
        ("signatures", VarArray(DecoratedSignature, 20)),
    ]


class TransactionEnvelope(Union):
    SWITCH = EnvelopeType
    ARMS = {
        EnvelopeType.ENVELOPE_TYPE_TX_V0: ("v0", TransactionV0Envelope),
        EnvelopeType.ENVELOPE_TYPE_TX: ("v1", TransactionV1Envelope),
        EnvelopeType.ENVELOPE_TYPE_TX_FEE_BUMP:
            ("feeBump", FeeBumpTransactionEnvelope),
    }


class _TaggedTransaction(Union):
    SWITCH = EnvelopeType
    ARMS = {
        EnvelopeType.ENVELOPE_TYPE_TX: ("tx", Transaction),
        EnvelopeType.ENVELOPE_TYPE_TX_FEE_BUMP:
            ("feeBump", FeeBumpTransaction),
    }


class TransactionSignaturePayload(Struct):
    """The signed bytes: SHA256(networkId ‖ taggedTransaction) is what
    DecoratedSignatures sign (reference:
    transactions/TransactionFrame.cpp:99-107)."""
    FIELDS = [
        ("networkId", Hash),
        ("taggedTransaction", _TaggedTransaction),
    ]
