"""SCP consensus message types (reference: Stellar-SCP.x; consumed by
src/scp — the freestanding consensus kernel, scp/readme.md:3-12)."""

from __future__ import annotations

from enum import IntEnum

from .runtime import (
    Int32, Lazy, Optional, Struct, Uint32, Uint64, Union, VarArray, VarOpaque,
)
from .types import Hash, NodeID, Signature

Value = VarOpaque()


class SCPBallot(Struct):
    FIELDS = [("counter", Uint32), ("value", Value)]


class SCPStatementType(IntEnum):
    SCP_ST_PREPARE = 0
    SCP_ST_CONFIRM = 1
    SCP_ST_EXTERNALIZE = 2
    SCP_ST_NOMINATE = 3


class SCPNomination(Struct):
    FIELDS = [
        ("quorumSetHash", Hash),
        ("votes", VarArray(Value)),
        ("accepted", VarArray(Value)),
    ]


class SCPStatementPrepare(Struct):
    FIELDS = [
        ("quorumSetHash", Hash),
        ("ballot", SCPBallot),
        ("prepared", Optional(SCPBallot)),
        ("preparedPrime", Optional(SCPBallot)),
        ("nC", Uint32),
        ("nH", Uint32),
    ]


class SCPStatementConfirm(Struct):
    FIELDS = [
        ("ballot", SCPBallot),
        ("nPrepared", Uint32),
        ("nCommit", Uint32),
        ("nH", Uint32),
        ("quorumSetHash", Hash),
    ]


class SCPStatementExternalize(Struct):
    FIELDS = [
        ("commit", SCPBallot),
        ("nH", Uint32),
        ("commitQuorumSetHash", Hash),
    ]


class _SCPStatementPledges(Union):
    SWITCH = SCPStatementType
    ARMS = {
        SCPStatementType.SCP_ST_PREPARE: ("prepare", SCPStatementPrepare),
        SCPStatementType.SCP_ST_CONFIRM: ("confirm", SCPStatementConfirm),
        SCPStatementType.SCP_ST_EXTERNALIZE:
            ("externalize", SCPStatementExternalize),
        SCPStatementType.SCP_ST_NOMINATE: ("nominate", SCPNomination),
    }


class SCPStatement(Struct):
    FIELDS = [
        ("nodeID", NodeID),
        ("slotIndex", Uint64),
        ("pledges", _SCPStatementPledges),
    ]


class SCPEnvelope(Struct):
    FIELDS = [("statement", SCPStatement), ("signature", Signature)]


class SCPQuorumSet(Struct):
    """Recursive quorum-set tree (reference: scp/LocalNode isQuorumSlice;
    sanity rules in scp/QuorumSetUtils.cpp)."""
    FIELDS = [
        ("threshold", Uint32),
        ("validators", VarArray(NodeID)),
        ("innerSets", VarArray(Lazy(lambda: SCPQuorumSet))),
    ]


class LedgerSCPMessages(Struct):
    """SCP messages externalizing one ledger (reference: Stellar-ledger.x
    LedgerSCPMessages; written by herder/HerderPersistence)."""
    FIELDS = [
        ("ledgerSeq", Uint32),
        ("messages", VarArray(SCPEnvelope)),
    ]


class SCPHistoryEntryV0(Struct):
    FIELDS = [
        ("quorumSets", VarArray(SCPQuorumSet)),
        ("ledgerMessages", LedgerSCPMessages),
    ]


class SCPHistoryEntry(Union):
    SWITCH = Int32
    ARMS = {0: ("v0", SCPHistoryEntryV0)}
