"""Smart-contract protocol types.

Reference: Stellar-contract.x, Stellar-contract-config-setting.x, and the
Soroban parts of Stellar-ledger-entries.x / Stellar-transaction.x
(consumed by transactions/InvokeHostFunctionOpFrame.cpp and the host in
src/rust/src/contract.rs). This is the wire-faithful subset the host
layer executes: SCVal's common arms, contract data/code/TTL entries,
resource declarations, host functions, and authorization entries.
"""

from __future__ import annotations

from enum import IntEnum

from .runtime import (
    Array, Bool, Int32, Int64, Lazy, Opaque, Optional, Struct, Uint32,
    Uint64, Union, VarArray, VarOpaque, XdrString,
)
from .types import AccountID, ExtensionPoint, Hash, PublicKey, Uint256
from .ledger_entries import LedgerEntryType, LedgerKey


# --- SCVal ------------------------------------------------------------------

class SCValType(IntEnum):
    SCV_BOOL = 0
    SCV_VOID = 1
    SCV_ERROR = 2
    SCV_U32 = 3
    SCV_I32 = 4
    SCV_U64 = 5
    SCV_I64 = 6
    SCV_TIMEPOINT = 7
    SCV_DURATION = 8
    SCV_U128 = 9
    SCV_I128 = 10
    SCV_U256 = 11
    SCV_I256 = 12
    SCV_BYTES = 13
    SCV_STRING = 14
    SCV_SYMBOL = 15
    SCV_VEC = 16
    SCV_MAP = 17
    SCV_ADDRESS = 18
    SCV_CONTRACT_INSTANCE = 19
    SCV_LEDGER_KEY_CONTRACT_INSTANCE = 20
    SCV_LEDGER_KEY_NONCE = 21


class SCErrorType(IntEnum):
    SCE_CONTRACT = 0
    SCE_WASM_VM = 1
    SCE_CONTEXT = 2
    SCE_STORAGE = 3
    SCE_OBJECT = 4
    SCE_CRYPTO = 5
    SCE_EVENTS = 6
    SCE_BUDGET = 7
    SCE_VALUE = 8
    SCE_AUTH = 9


class SCErrorCode(IntEnum):
    SCEC_ARITH_DOMAIN = 0
    SCEC_INDEX_BOUNDS = 1
    SCEC_INVALID_INPUT = 2
    SCEC_MISSING_VALUE = 3
    SCEC_EXISTING_VALUE = 4
    SCEC_EXCEEDED_LIMIT = 5
    SCEC_INVALID_ACTION = 6
    SCEC_INTERNAL_ERROR = 7
    SCEC_UNEXPECTED_TYPE = 8
    SCEC_UNEXPECTED_SIZE = 9


class SCError(Union):
    SWITCH = SCErrorType
    ARMS = {
        SCErrorType.SCE_CONTRACT: ("contractCode", Uint32),
        SCErrorType.SCE_WASM_VM: None,
        SCErrorType.SCE_CONTEXT: None,
        SCErrorType.SCE_STORAGE: None,
        SCErrorType.SCE_OBJECT: None,
        SCErrorType.SCE_CRYPTO: None,
        SCErrorType.SCE_EVENTS: None,
        SCErrorType.SCE_BUDGET: None,
        SCErrorType.SCE_VALUE: None,
        SCErrorType.SCE_AUTH: ("code", SCErrorCode),
    }


class SCAddressType(IntEnum):
    SC_ADDRESS_TYPE_ACCOUNT = 0
    SC_ADDRESS_TYPE_CONTRACT = 1


class SCAddress(Union):
    SWITCH = SCAddressType
    ARMS = {
        SCAddressType.SC_ADDRESS_TYPE_ACCOUNT: ("accountId", AccountID),
        SCAddressType.SC_ADDRESS_TYPE_CONTRACT: ("contractId", Hash),
    }


class UInt128Parts(Struct):
    FIELDS = [("hi", Uint64), ("lo", Uint64)]


class Int128Parts(Struct):
    FIELDS = [("hi", Int64), ("lo", Uint64)]


class UInt256Parts(Struct):
    FIELDS = [("hi_hi", Uint64), ("hi_lo", Uint64),
              ("lo_hi", Uint64), ("lo_lo", Uint64)]


class Int256Parts(Struct):
    FIELDS = [("hi_hi", Int64), ("hi_lo", Uint64),
              ("lo_hi", Uint64), ("lo_lo", Uint64)]


SCSymbol = XdrString(32)
SCString = XdrString()
SCBytes = VarOpaque()


class SCNonceKey(Struct):
    FIELDS = [("nonce", Int64)]


class SCMapEntry(Struct):
    FIELDS = [("key", Lazy(lambda: SCVal)), ("val", Lazy(lambda: SCVal))]


class SCContractInstance(Struct):
    FIELDS = [
        ("executable", Lazy(lambda: ContractExecutable)),
        ("storage", Optional(VarArray(SCMapEntry))),
    ]


class SCVal(Union):
    SWITCH = SCValType
    ARMS = {
        SCValType.SCV_BOOL: ("b", Bool),
        SCValType.SCV_VOID: None,
        SCValType.SCV_ERROR: ("error", SCError),
        SCValType.SCV_U32: ("u32", Uint32),
        SCValType.SCV_I32: ("i32", Int32),
        SCValType.SCV_U64: ("u64", Uint64),
        SCValType.SCV_I64: ("i64", Int64),
        SCValType.SCV_TIMEPOINT: ("timepoint", Uint64),
        SCValType.SCV_DURATION: ("duration", Uint64),
        SCValType.SCV_U128: ("u128", UInt128Parts),
        SCValType.SCV_I128: ("i128", Int128Parts),
        SCValType.SCV_U256: ("u256", UInt256Parts),
        SCValType.SCV_I256: ("i256", Int256Parts),
        SCValType.SCV_BYTES: ("bytes", SCBytes),
        SCValType.SCV_STRING: ("str", SCString),
        SCValType.SCV_SYMBOL: ("sym", SCSymbol),
        SCValType.SCV_VEC: ("vec", Optional(VarArray(Lazy(lambda: SCVal)))),
        SCValType.SCV_MAP: ("map", Optional(VarArray(SCMapEntry))),
        SCValType.SCV_ADDRESS: ("address", SCAddress),
        SCValType.SCV_CONTRACT_INSTANCE: ("instance", SCContractInstance),
        SCValType.SCV_LEDGER_KEY_CONTRACT_INSTANCE: None,
        SCValType.SCV_LEDGER_KEY_NONCE: ("nonce_key", SCNonceKey),
    }


# --- Contract entries -------------------------------------------------------

class ContractExecutableType(IntEnum):
    CONTRACT_EXECUTABLE_WASM = 0
    CONTRACT_EXECUTABLE_STELLAR_ASSET = 1


class ContractExecutable(Union):
    SWITCH = ContractExecutableType
    ARMS = {
        ContractExecutableType.CONTRACT_EXECUTABLE_WASM:
            ("wasm_hash", Hash),
        ContractExecutableType.CONTRACT_EXECUTABLE_STELLAR_ASSET: None,
    }


class ContractDataDurability(IntEnum):
    TEMPORARY = 0
    PERSISTENT = 1


class ContractDataEntry(Struct):
    FIELDS = [
        ("ext", ExtensionPoint),
        ("contract", SCAddress),
        ("key", SCVal),
        ("durability", ContractDataDurability),
        ("val", SCVal),
    ]


class ContractCodeEntry(Struct):
    FIELDS = [
        ("ext", ExtensionPoint),
        ("hash", Hash),
        ("code", VarOpaque()),
    ]


class TTLEntry(Struct):
    # keyHash = SHA256(LedgerKey of the extended entry)
    FIELDS = [
        ("keyHash", Hash),
        ("liveUntilLedgerSeq", Uint32),
    ]


# --- Ledger keys for contract entries (joined into LedgerKey by the
# soroban layer registering these arms) ------------------------------------

class LedgerKeyContractData(Struct):
    FIELDS = [
        ("contract", SCAddress),
        ("key", SCVal),
        ("durability", ContractDataDurability),
    ]


class LedgerKeyContractCode(Struct):
    FIELDS = [("hash", Hash)]


class LedgerKeyTtl(Struct):
    FIELDS = [("keyHash", Hash)]


# --- Soroban tx resources ---------------------------------------------------

class LedgerFootprint(Struct):
    FIELDS = [
        ("readOnly", VarArray(LedgerKey)),
        ("readWrite", VarArray(LedgerKey)),
    ]


class SorobanResources(Struct):
    FIELDS = [
        ("footprint", LedgerFootprint),
        ("instructions", Uint32),
        ("readBytes", Uint32),
        ("writeBytes", Uint32),
    ]


class SorobanTransactionData(Struct):
    FIELDS = [
        ("ext", ExtensionPoint),
        ("resources", SorobanResources),
        ("resourceFee", Int64),
    ]


# --- Host functions ---------------------------------------------------------

class ContractIDPreimageType(IntEnum):
    CONTRACT_ID_PREIMAGE_FROM_ADDRESS = 0
    CONTRACT_ID_PREIMAGE_FROM_ASSET = 1


class _ContractIDPreimageFromAddress(Struct):
    FIELDS = [("address", SCAddress), ("salt", Uint256)]


class ContractIDPreimage(Union):
    SWITCH = ContractIDPreimageType
    ARMS = {
        ContractIDPreimageType.CONTRACT_ID_PREIMAGE_FROM_ADDRESS:
            ("fromAddress", _ContractIDPreimageFromAddress),
        ContractIDPreimageType.CONTRACT_ID_PREIMAGE_FROM_ASSET:
            ("fromAsset", Lazy(lambda: _asset_type())),
    }


def _asset_type():
    from .ledger_entries import Asset
    return Asset


class CreateContractArgs(Struct):
    FIELDS = [
        ("contractIDPreimage", ContractIDPreimage),
        ("executable", ContractExecutable),
    ]


class InvokeContractArgs(Struct):
    FIELDS = [
        ("contractAddress", SCAddress),
        ("functionName", SCSymbol),
        ("args", VarArray(SCVal)),
    ]


class HostFunctionType(IntEnum):
    HOST_FUNCTION_TYPE_INVOKE_CONTRACT = 0
    HOST_FUNCTION_TYPE_CREATE_CONTRACT = 1
    HOST_FUNCTION_TYPE_UPLOAD_CONTRACT_WASM = 2


class HostFunction(Union):
    SWITCH = HostFunctionType
    ARMS = {
        HostFunctionType.HOST_FUNCTION_TYPE_INVOKE_CONTRACT:
            ("invokeContract", InvokeContractArgs),
        HostFunctionType.HOST_FUNCTION_TYPE_CREATE_CONTRACT:
            ("createContract", CreateContractArgs),
        HostFunctionType.HOST_FUNCTION_TYPE_UPLOAD_CONTRACT_WASM:
            ("wasm", VarOpaque()),
    }


# --- Authorization ----------------------------------------------------------

class SorobanAuthorizedFunctionType(IntEnum):
    SOROBAN_AUTHORIZED_FUNCTION_TYPE_CONTRACT_FN = 0
    SOROBAN_AUTHORIZED_FUNCTION_TYPE_CREATE_CONTRACT_HOST_FN = 1


class SorobanAuthorizedFunction(Union):
    SWITCH = SorobanAuthorizedFunctionType
    ARMS = {
        SorobanAuthorizedFunctionType
        .SOROBAN_AUTHORIZED_FUNCTION_TYPE_CONTRACT_FN:
            ("contractFn", InvokeContractArgs),
        SorobanAuthorizedFunctionType
        .SOROBAN_AUTHORIZED_FUNCTION_TYPE_CREATE_CONTRACT_HOST_FN:
            ("createContractHostFn", CreateContractArgs),
    }


class SorobanAuthorizedInvocation(Struct):
    FIELDS = [
        ("function", SorobanAuthorizedFunction),
        ("subInvocations",
         VarArray(Lazy(lambda: SorobanAuthorizedInvocation))),
    ]


class SorobanAddressCredentials(Struct):
    FIELDS = [
        ("address", SCAddress),
        ("nonce", Int64),
        ("signatureExpirationLedger", Uint32),
        ("signature", SCVal),
    ]


class SorobanCredentialsType(IntEnum):
    SOROBAN_CREDENTIALS_SOURCE_ACCOUNT = 0
    SOROBAN_CREDENTIALS_ADDRESS = 1


class SorobanCredentials(Union):
    SWITCH = SorobanCredentialsType
    ARMS = {
        SorobanCredentialsType.SOROBAN_CREDENTIALS_SOURCE_ACCOUNT: None,
        SorobanCredentialsType.SOROBAN_CREDENTIALS_ADDRESS:
            ("address", SorobanAddressCredentials),
    }


class SorobanAuthorizationEntry(Struct):
    FIELDS = [
        ("credentials", SorobanCredentials),
        ("rootInvocation", SorobanAuthorizedInvocation),
    ]


# --- Operations -------------------------------------------------------------

class InvokeHostFunctionOp(Struct):
    FIELDS = [
        ("hostFunction", HostFunction),
        ("auth", VarArray(SorobanAuthorizationEntry)),
    ]


class ExtendFootprintTTLOp(Struct):
    FIELDS = [
        ("ext", ExtensionPoint),
        ("extendTo", Uint32),
    ]


class RestoreFootprintOp(Struct):
    FIELDS = [("ext", ExtensionPoint)]


# --- Results ----------------------------------------------------------------

class InvokeHostFunctionResultCode(IntEnum):
    INVOKE_HOST_FUNCTION_SUCCESS = 0
    INVOKE_HOST_FUNCTION_MALFORMED = -1
    INVOKE_HOST_FUNCTION_TRAPPED = -2
    INVOKE_HOST_FUNCTION_RESOURCE_LIMIT_EXCEEDED = -3
    INVOKE_HOST_FUNCTION_ENTRY_ARCHIVED = -4
    INVOKE_HOST_FUNCTION_INSUFFICIENT_REFUNDABLE_FEE = -5


class InvokeHostFunctionResult(Union):
    SWITCH = InvokeHostFunctionResultCode
    ARMS = {
        InvokeHostFunctionResultCode.INVOKE_HOST_FUNCTION_SUCCESS:
            ("success", Hash),
        InvokeHostFunctionResultCode.INVOKE_HOST_FUNCTION_MALFORMED: None,
        InvokeHostFunctionResultCode.INVOKE_HOST_FUNCTION_TRAPPED: None,
        InvokeHostFunctionResultCode
        .INVOKE_HOST_FUNCTION_RESOURCE_LIMIT_EXCEEDED: None,
        InvokeHostFunctionResultCode
        .INVOKE_HOST_FUNCTION_ENTRY_ARCHIVED: None,
        InvokeHostFunctionResultCode
        .INVOKE_HOST_FUNCTION_INSUFFICIENT_REFUNDABLE_FEE: None,
    }


class ExtendFootprintTTLResultCode(IntEnum):
    EXTEND_FOOTPRINT_TTL_SUCCESS = 0
    EXTEND_FOOTPRINT_TTL_MALFORMED = -1
    EXTEND_FOOTPRINT_TTL_RESOURCE_LIMIT_EXCEEDED = -2
    EXTEND_FOOTPRINT_TTL_INSUFFICIENT_REFUNDABLE_FEE = -3


class ExtendFootprintTTLResult(Union):
    SWITCH = ExtendFootprintTTLResultCode
    ARMS = {
        ExtendFootprintTTLResultCode.EXTEND_FOOTPRINT_TTL_SUCCESS: None,
        ExtendFootprintTTLResultCode.EXTEND_FOOTPRINT_TTL_MALFORMED: None,
        ExtendFootprintTTLResultCode
        .EXTEND_FOOTPRINT_TTL_RESOURCE_LIMIT_EXCEEDED: None,
        ExtendFootprintTTLResultCode
        .EXTEND_FOOTPRINT_TTL_INSUFFICIENT_REFUNDABLE_FEE: None,
    }


class RestoreFootprintResultCode(IntEnum):
    RESTORE_FOOTPRINT_SUCCESS = 0
    RESTORE_FOOTPRINT_MALFORMED = -1
    RESTORE_FOOTPRINT_RESOURCE_LIMIT_EXCEEDED = -2
    RESTORE_FOOTPRINT_INSUFFICIENT_REFUNDABLE_FEE = -3


class RestoreFootprintResult(Union):
    SWITCH = RestoreFootprintResultCode
    ARMS = {
        RestoreFootprintResultCode.RESTORE_FOOTPRINT_SUCCESS: None,
        RestoreFootprintResultCode.RESTORE_FOOTPRINT_MALFORMED: None,
        RestoreFootprintResultCode
        .RESTORE_FOOTPRINT_RESOURCE_LIMIT_EXCEEDED: None,
        RestoreFootprintResultCode
        .RESTORE_FOOTPRINT_INSUFFICIENT_REFUNDABLE_FEE: None,
    }


# --- Events (diagnostic subset) --------------------------------------------

class ContractEventType(IntEnum):
    SYSTEM = 0
    CONTRACT = 1
    DIAGNOSTIC = 2


class _ContractEventV0(Struct):
    FIELDS = [
        ("topics", VarArray(SCVal)),
        ("data", SCVal),
    ]


class _ContractEventBody(Union):
    SWITCH = Int32
    ARMS = {0: ("v0", _ContractEventV0)}


class ContractEvent(Struct):
    FIELDS = [
        ("ext", ExtensionPoint),
        ("contractID", Optional(Hash)),
        ("type", ContractEventType),
        ("body", _ContractEventBody),
    ]


# --- Network config settings (reference: Stellar-contract-config-setting.x) --

class ConfigSettingID(IntEnum):
    CONFIG_SETTING_CONTRACT_MAX_SIZE_BYTES = 0
    CONFIG_SETTING_CONTRACT_COMPUTE_V0 = 1
    CONFIG_SETTING_CONTRACT_LEDGER_COST_V0 = 2
    CONFIG_SETTING_CONTRACT_HISTORICAL_DATA_V0 = 3
    CONFIG_SETTING_CONTRACT_EVENTS_V0 = 4
    CONFIG_SETTING_CONTRACT_BANDWIDTH_V0 = 5
    CONFIG_SETTING_CONTRACT_COST_PARAMS_CPU_INSTRUCTIONS = 6
    CONFIG_SETTING_CONTRACT_COST_PARAMS_MEMORY_BYTES = 7
    CONFIG_SETTING_CONTRACT_DATA_KEY_SIZE_BYTES = 8
    CONFIG_SETTING_CONTRACT_DATA_ENTRY_SIZE_BYTES = 9
    CONFIG_SETTING_STATE_ARCHIVAL = 10
    CONFIG_SETTING_CONTRACT_EXECUTION_LANES = 11
    CONFIG_SETTING_BUCKETLIST_SIZE_WINDOW = 12
    CONFIG_SETTING_EVICTION_ITERATOR = 13


class ConfigSettingContractComputeV0(Struct):
    FIELDS = [
        ("ledgerMaxInstructions", Int64),
        ("txMaxInstructions", Int64),
        ("feeRatePerInstructionsIncrement", Int64),
        ("txMemoryLimit", Uint32),
    ]


class ConfigSettingContractLedgerCostV0(Struct):
    FIELDS = [
        ("ledgerMaxReadLedgerEntries", Uint32),
        ("ledgerMaxReadBytes", Uint32),
        ("ledgerMaxWriteLedgerEntries", Uint32),
        ("ledgerMaxWriteBytes", Uint32),
        ("txMaxReadLedgerEntries", Uint32),
        ("txMaxReadBytes", Uint32),
        ("txMaxWriteLedgerEntries", Uint32),
        ("txMaxWriteBytes", Uint32),
        ("feeReadLedgerEntry", Int64),
        ("feeWriteLedgerEntry", Int64),
        ("feeRead1KB", Int64),
        ("bucketListTargetSizeBytes", Int64),
        ("writeFee1KBBucketListLow", Int64),
        ("writeFee1KBBucketListHigh", Int64),
        ("bucketListWriteFeeGrowthFactor", Uint32),
    ]


class ConfigSettingContractHistoricalDataV0(Struct):
    FIELDS = [("feeHistorical1KB", Int64)]


class ConfigSettingContractEventsV0(Struct):
    FIELDS = [
        ("txMaxContractEventsSizeBytes", Uint32),
        ("feeContractEvents1KB", Int64),
    ]


class ConfigSettingContractBandwidthV0(Struct):
    FIELDS = [
        ("ledgerMaxTxsSizeBytes", Uint32),
        ("txMaxSizeBytes", Uint32),
        ("feeTxSize1KB", Int64),
    ]


class ContractCostParamEntry(Struct):
    FIELDS = [
        ("ext", ExtensionPoint),
        ("constTerm", Int64),
        ("linearTerm", Int64),
    ]


class StateArchivalSettings(Struct):
    FIELDS = [
        ("maxEntryTTL", Uint32),
        ("minTemporaryTTL", Uint32),
        ("minPersistentTTL", Uint32),
        ("persistentRentRateDenominator", Int64),
        ("tempRentRateDenominator", Int64),
        ("maxEntriesToArchive", Uint32),
        ("bucketListSizeWindowSampleSize", Uint32),
        ("bucketListWindowSamplePeriod", Uint32),
        ("evictionScanSize", Uint32),
        ("startingEvictionScanLevel", Uint32),
    ]


class ConfigSettingContractExecutionLanesV0(Struct):
    FIELDS = [("ledgerMaxTxCount", Uint32)]


class EvictionIterator(Struct):
    FIELDS = [
        ("bucketListLevel", Uint32),
        ("isCurrBucket", Bool),
        ("bucketFileOffset", Uint64),
    ]


class ConfigSettingEntry(Union):
    SWITCH = ConfigSettingID
    ARMS = {
        ConfigSettingID.CONFIG_SETTING_CONTRACT_MAX_SIZE_BYTES:
            ("contractMaxSizeBytes", Uint32),
        ConfigSettingID.CONFIG_SETTING_CONTRACT_COMPUTE_V0:
            ("contractCompute", ConfigSettingContractComputeV0),
        ConfigSettingID.CONFIG_SETTING_CONTRACT_LEDGER_COST_V0:
            ("contractLedgerCost", ConfigSettingContractLedgerCostV0),
        ConfigSettingID.CONFIG_SETTING_CONTRACT_HISTORICAL_DATA_V0:
            ("contractHistoricalData",
             ConfigSettingContractHistoricalDataV0),
        ConfigSettingID.CONFIG_SETTING_CONTRACT_EVENTS_V0:
            ("contractEvents", ConfigSettingContractEventsV0),
        ConfigSettingID.CONFIG_SETTING_CONTRACT_BANDWIDTH_V0:
            ("contractBandwidth", ConfigSettingContractBandwidthV0),
        ConfigSettingID.CONFIG_SETTING_CONTRACT_COST_PARAMS_CPU_INSTRUCTIONS:
            ("contractCostParamsCpuInsns",
             VarArray(ContractCostParamEntry)),
        ConfigSettingID.CONFIG_SETTING_CONTRACT_COST_PARAMS_MEMORY_BYTES:
            ("contractCostParamsMemBytes",
             VarArray(ContractCostParamEntry)),
        ConfigSettingID.CONFIG_SETTING_CONTRACT_DATA_KEY_SIZE_BYTES:
            ("contractDataKeySizeBytes", Uint32),
        ConfigSettingID.CONFIG_SETTING_CONTRACT_DATA_ENTRY_SIZE_BYTES:
            ("contractDataEntrySizeBytes", Uint32),
        ConfigSettingID.CONFIG_SETTING_STATE_ARCHIVAL:
            ("stateArchivalSettings", StateArchivalSettings),
        ConfigSettingID.CONFIG_SETTING_CONTRACT_EXECUTION_LANES:
            ("contractExecutionLanes",
             ConfigSettingContractExecutionLanesV0),
        ConfigSettingID.CONFIG_SETTING_BUCKETLIST_SIZE_WINDOW:
            ("bucketListSizeWindow", VarArray(Uint64)),
        ConfigSettingID.CONFIG_SETTING_EVICTION_ITERATOR:
            ("evictionIterator", EvictionIterator),
    }

class ConfigUpgradeSetKey(Struct):
    """reference: Stellar-ledger.x ConfigUpgradeSetKey — points at a
    TEMPORARY contract-data entry holding the serialized upgrade set."""
    FIELDS = [("contractID", Hash), ("contentHash", Hash)]


class ConfigUpgradeSet(Struct):
    """reference: Stellar-contract-config-setting.x ConfigUpgradeSet."""
    FIELDS = [("updatedEntry", VarArray(ConfigSettingEntry))]



class LedgerKeyConfigSetting(Struct):
    FIELDS = [("configSettingID", ConfigSettingID)]


# --- Join contract arms into the core LedgerEntry/LedgerKey unions ----------

def register_soroban_ledger_arms() -> None:
    """Extend _LedgerEntryData and LedgerKey with the Soroban arms
    (ledger_entries.py defers these to this layer — SURVEY.md §7 step 8:
    classic first, contracts join the same unions when loaded)."""
    from .ledger_entries import _LedgerEntryData


    data_arms = {
        LedgerEntryType.CONTRACT_DATA: ("contractData", ContractDataEntry),
        LedgerEntryType.CONTRACT_CODE: ("contractCode", ContractCodeEntry),
        LedgerEntryType.CONFIG_SETTING:
            ("configSetting", ConfigSettingEntry),
        LedgerEntryType.TTL: ("ttl", TTLEntry),
    }
    key_arms = {
        LedgerEntryType.CONTRACT_DATA:
            ("contractData", LedgerKeyContractData),
        LedgerEntryType.CONTRACT_CODE:
            ("contractCode", LedgerKeyContractCode),
        LedgerEntryType.CONFIG_SETTING:
            ("configSetting", LedgerKeyConfigSetting),
        LedgerEntryType.TTL: ("ttl", LedgerKeyTtl),
    }
    for disc, (an, at) in data_arms.items():
        if disc not in _LedgerEntryData._ARMS:
            _LedgerEntryData.register_arm(disc, an, at)
    for disc, (an, at) in key_arms.items():
        if disc not in LedgerKey._ARMS:
            LedgerKey.register_arm(disc, an, at)

    if not hasattr(LedgerKey, "contract_data"):
        def contract_data(cls, contract: SCAddress, key: SCVal,
                          durability) -> "LedgerKey":
            return cls(LedgerEntryType.CONTRACT_DATA,
                       LedgerKeyContractData(contract=contract, key=key,
                                             durability=durability))

        def contract_code(cls, wasm_hash: bytes) -> "LedgerKey":
            return cls(LedgerEntryType.CONTRACT_CODE,
                       LedgerKeyContractCode(hash=wasm_hash))

        def ttl(cls, key_hash: bytes) -> "LedgerKey":
            return cls(LedgerEntryType.TTL, LedgerKeyTtl(keyHash=key_hash))

        def config_setting(cls, setting_id) -> "LedgerKey":
            return cls(LedgerEntryType.CONFIG_SETTING,
                       LedgerKeyConfigSetting(configSettingID=setting_id))

        LedgerKey.contract_data = classmethod(contract_data)
        LedgerKey.contract_code = classmethod(contract_code)
        LedgerKey.ttl = classmethod(ttl)
        LedgerKey.config_setting = classmethod(config_setting)


register_soroban_ledger_arms()


def register_soroban_tx_arms() -> None:
    """Extend the operation-body, operation-result, and tx-ext unions
    with the Soroban arms (reference: Stellar-transaction.x protocol 20
    additions)."""

    from .transaction import OperationType, _OperationBody, _TxExt
    from .results import _OperationResultTr

    body_arms = {
        OperationType.INVOKE_HOST_FUNCTION:
            ("invokeHostFunctionOp", InvokeHostFunctionOp),
        OperationType.EXTEND_FOOTPRINT_TTL:
            ("extendFootprintTTLOp", ExtendFootprintTTLOp),
        OperationType.RESTORE_FOOTPRINT:
            ("restoreFootprintOp", RestoreFootprintOp),
    }
    result_arms = {
        OperationType.INVOKE_HOST_FUNCTION:
            ("invokeHostFunctionResult", InvokeHostFunctionResult),
        OperationType.EXTEND_FOOTPRINT_TTL:
            ("extendFootprintTTLResult", ExtendFootprintTTLResult),
        OperationType.RESTORE_FOOTPRINT:
            ("restoreFootprintResult", RestoreFootprintResult),
    }
    for disc, (an, at) in body_arms.items():
        if disc not in _OperationBody._ARMS:
            _OperationBody.register_arm(disc, an, at)
    for disc, (an, at) in result_arms.items():
        if disc not in _OperationResultTr._ARMS:
            _OperationResultTr.register_arm(disc, an, at)
    # Transaction.ext arm 1 = SorobanTransactionData (protocol 20)
    if 1 not in _TxExt._ARMS:
        _TxExt.register_arm(1, "sorobanData", SorobanTransactionData)


register_soroban_tx_arms()
