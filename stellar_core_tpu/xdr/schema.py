"""XDR schema identity and the protocol-curr / protocol-next split.

Reference mechanisms being reproduced:
  - `src/protocol-curr/` vs `src/protocol-next/`: two complete XDR type
    trees built side by side so a *structural* next-protocol change is
    representable before it activates (Makefile.am:46-51).
  - XDR identity hashing: the reference hashes its .x definitions into
    the binary and cross-checks them against the Rust host's XDR
    (Makefile.am:28-32, rust/src/lib.rs:631) so two builds can prove
    they speak the same wire language.

This build's types are declarative Python classes, so a "type set" is a
NAMESPACE {name: class}.  `curr_namespace()` collects every XDR type
the node registered at import; `next_namespace()` overlays the
structural deltas declared in `next_types.py`.  `schema_hash()` renders
a canonical descriptor of every type (fields, arm tables, enum values —
the wire-relevant structure, nothing else) and hashes it; equal hashes
⟺ identical wire language.  The node reports both hashes in `info` /
`version` so operators can compare builds the way the reference
compares its embedded .x hashes.
"""

from __future__ import annotations

import hashlib
from enum import IntEnum
from typing import Dict

from . import runtime as rt


def _type_name(ft) -> str:
    """Canonical name for a field-type descriptor — structure only."""
    if isinstance(ft, rt.Opaque):
        return f"opaque[{ft.n}]"
    if isinstance(ft, rt.XdrString):
        return f"string<{ft.max_len}>"
    if isinstance(ft, rt.VarOpaque):
        return f"opaque<{ft.max_len}>"
    if isinstance(ft, rt.Array):
        return f"{_type_name(ft.elem)}[{ft.n}]"
    if isinstance(ft, rt.VarArray):
        return f"{_type_name(ft.elem)}<{ft.max_len}>"
    if isinstance(ft, rt.Optional):
        return f"*{_type_name(ft.elem)}"
    if isinstance(ft, rt.Lazy):
        return _type_name(ft._get())
    if isinstance(ft, rt.EnumType):
        return ft.enum_cls.__name__
    if isinstance(ft, rt._Composite):
        return ft.cls.__name__
    for name, singleton in (("int32", rt.Int32), ("uint32", rt.Uint32),
                            ("int64", rt.Int64), ("uint64", rt.Uint64),
                            ("bool", rt.Bool)):
        if ft is singleton:
            return name
    return type(ft).__name__


def describe_type(cls) -> str:
    """One-line canonical descriptor of a Struct/Union/IntEnum."""
    if isinstance(cls, type) and issubclass(cls, IntEnum):
        vals = ",".join(f"{m.name}={m.value}" for m in cls)
        return f"enum {cls.__name__} {{{vals}}}"
    if isinstance(cls, type) and issubclass(cls, rt.Struct):
        fields = ",".join(f"{fn}:{_type_name(ft)}"
                          for fn, ft in cls._FIELDS)
        return f"struct {cls.__name__} {{{fields}}}"
    if isinstance(cls, type) and issubclass(cls, rt.Union):
        sw = _type_name(cls._SWITCH)
        arms = []
        for disc in sorted(cls._ARMS, key=lambda d: int(d)):
            arm = cls._ARMS[disc]
            if arm is None:
                arms.append(f"{int(disc)}:void")
            else:
                an, at = arm
                arms.append(f"{int(disc)}:{an}:"
                            f"{_type_name(at) if at else 'void'}")
        d = cls._DEFAULT_ARM
        if d != "_missing_":
            if d is None:
                arms.append("default:void")
            else:
                arms.append(f"default:{d[0]}:"
                            f"{_type_name(d[1]) if d[1] else 'void'}")
        return f"union {cls.__name__} switch({sw}) {{{','.join(arms)}}}"
    raise TypeError(f"not an XDR type: {cls!r}")


_XDR_MODULES = ("types", "ledger_entries", "ledger", "transaction",
                "results", "scp", "overlay", "contract")


def curr_namespace() -> Dict[str, type]:
    """Every XDR type of the current-protocol build."""
    import importlib
    ns: Dict[str, type] = {}
    for mod_name in _XDR_MODULES:
        mod = importlib.import_module(f"{__package__}.{mod_name}")
        for name, obj in vars(mod).items():
            if not isinstance(obj, type):
                continue
            if issubclass(obj, (rt.Struct, rt.Union)) and \
                    obj not in (rt.Struct, rt.Union):
                ns.setdefault(name, obj)
            elif issubclass(obj, IntEnum) and obj is not IntEnum:
                ns.setdefault(name, obj)
    return ns


def next_namespace() -> Dict[str, type]:
    """The protocol-next type set: curr overlaid with the structural
    deltas (next_types.NEXT_TYPES)."""
    from . import next_types
    ns = dict(curr_namespace())
    ns.update(next_types.NEXT_TYPES)
    return ns


def schema_hash(ns: Dict[str, type]) -> bytes:
    lines = sorted(describe_type(cls) for cls in set(ns.values()))
    h = hashlib.sha256()
    for line in lines:
        h.update(line.encode())
        h.update(b"\n")
    return h.digest()


def identity() -> Dict[str, str]:
    """Both builds' schema hashes (the `info`/`version` surface)."""
    return {"curr": schema_hash(curr_namespace()).hex(),
            "next": schema_hash(next_namespace()).hex()}
