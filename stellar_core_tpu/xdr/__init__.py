"""XDR protocol layer — canonical wire/hash/history format.

Reference: src/protocol-curr/xdr compiled by xdrpp (src/Makefile.am:46-51);
"single, standard XDR for canonical (hashed) format, history, and inter-node
messaging" (docs/architecture.md:50-52).
"""

from .runtime import (  # noqa: F401
    Array, Bool, Int32, Int64, Lazy, Opaque, Optional, Reader, Struct,
    Uint32, Uint64, Union, VarArray, VarOpaque, Writer, XdrError, XdrString,
    xdr_from_bytes, xdr_to_bytes,
)
from . import (types, ledger_entries, contract, transaction, results,
               ledger, scp, overlay)  # noqa: F401
# `contract` must load with the package: importing it joins the Soroban
# arms (CONTRACT_DATA/CONTRACT_CODE/CONFIG_SETTING/TTL) into LedgerKey
# and LedgerEntry's unions


def xdr_sha256(value) -> bytes:
    """SHA256 of the canonical XDR encoding — the ubiquitous object hash
    (reference: crypto/XDRHasher.h, xdrSha256 in crypto/SHA.h)."""
    import hashlib

    return hashlib.sha256(value.to_bytes()).digest()
