"""Transaction frames: validity, fee/sequence processing, apply.

Reference: transactions/TransactionFrame.{h,cpp} and
FeeBumpTransactionFrame.{h,cpp}. The lifecycle mirrors the reference's
modern-protocol path (>= 13):

  queue admission / txset validation:
      check_valid = commonValid(applying=False) + per-op checkValid
                    + checkAllSignaturesUsed            (:1398-1455)
  ledger close:
      process_fee_seq_num   — charge min(fee, baseFee*numOps) into the
                              fee pool, clamped to balance (:processFeeSeqNum)
      apply                 — commonValid(applying=True) + processSeqNum
                              + processSignatures, then per-op apply in
                              nested LedgerTxns (:applyOperations)

Signature verification funnels through the injected VerifyFn — the TPU
batch-verifier seam (SURVEY.md §3.2 hot path).
"""

from __future__ import annotations

import hashlib
from enum import IntEnum
from typing import List, Optional, Sequence, Tuple

from ..crypto.sha import sha256
from ..util.checks import releaseAssert
from ..xdr.ledger_entries import LedgerKey, ThresholdIndexes
from ..xdr.transaction import (
    DecoratedSignature, MuxedAccount, Preconditions, PreconditionType,
    Transaction, TransactionEnvelope, TransactionSignaturePayload,
    _TaggedTransaction, _TxExt,
)
from ..xdr.results import (
    InnerTransactionResult, InnerTransactionResultPair, OperationResult,
    OperationResultCode, TransactionResult, TransactionResultCode,
    _InnerTxResultResult, _TxResultResult,
)
from ..xdr.types import EnvelopeType, ExtensionPoint, SignerKey, SignerKeyType
from ..ledger.ledger_txn import LedgerTxn
from . import tx_utils
from .operation_frame import OperationFrame, make_operation_frame
from .signature_checker import SignatureChecker, VerifyFn, default_verify
from .sponsorship import (ApplyContext, account_seq_ledger, account_seq_time,
                          ensure_account_ext_v3)

INT64_MAX = 2**63 - 1
MIN_PROTOCOL = 13  # this build replays modern-protocol ledgers only


class ValidationType(IntEnum):
    kInvalid = 0
    kInvalidUpdateSeqNum = 1
    kInvalidPostAuth = 2
    kMaybeValid = 3


def make_frame(envelope: TransactionEnvelope,
               network_id: bytes) -> "TransactionFrame":
    if envelope.disc == EnvelopeType.ENVELOPE_TYPE_TX_FEE_BUMP:
        return FeeBumpTransactionFrame(envelope, network_id)
    return TransactionFrame(envelope, network_id)


def _v0_to_v1_tx(v0tx) -> Transaction:
    """Upgrade a legacy TransactionV0 body for hashing/validation
    (reference: txbridge convertForV13)."""
    cond = Preconditions(PreconditionType.PRECOND_TIME, v0tx.timeBounds) \
        if v0tx.timeBounds is not None \
        else Preconditions(PreconditionType.PRECOND_NONE)
    return Transaction(
        sourceAccount=MuxedAccount.from_ed25519(v0tx.sourceAccountEd25519),
        fee=v0tx.fee, seqNum=v0tx.seqNum, cond=cond, memo=v0tx.memo,
        operations=v0tx.operations, ext=_TxExt(0))


class TransactionFrame:
    def __init__(self, envelope: TransactionEnvelope, network_id: bytes):
        releaseAssert(
            envelope.disc != EnvelopeType.ENVELOPE_TYPE_TX_FEE_BUMP,
            "use FeeBumpTransactionFrame")
        self.envelope = envelope
        self.network_id = network_id
        if envelope.disc == EnvelopeType.ENVELOPE_TYPE_TX_V0:
            self.tx: Transaction = _v0_to_v1_tx(envelope.value.tx)
        else:
            self.tx = envelope.value.tx
        self.signatures: Sequence[DecoratedSignature] = \
            envelope.value.signatures
        self._contents_hash: Optional[bytes] = None
        self._full_hash: Optional[bytes] = None
        self._envelope_bytes: Optional[bytes] = None
        self.result: Optional[TransactionResult] = None
        self.op_frames: List[OperationFrame] = [
            make_operation_frame(op, self.tx.sourceAccount, i)
            for i, op in enumerate(self.tx.operations)]

    # ------------------------------------------------------------- identity --
    def contents_hash(self) -> bytes:
        """SHA256(networkID ‖ ENVELOPE_TYPE_TX ‖ tx) — the signed bytes
        (reference: TransactionFrame.cpp:99-107)."""
        if self._contents_hash is None:
            payload = TransactionSignaturePayload(
                networkId=self.network_id,
                taggedTransaction=_TaggedTransaction(
                    EnvelopeType.ENVELOPE_TYPE_TX, self.tx))
            self._contents_hash = sha256(payload.to_bytes())
        return self._contents_hash

    def envelope_bytes(self) -> bytes:
        """Serialized envelope, cached — valid once the envelope is
        fully signed (apply/store paths; submission signing happens
        before the first call)."""
        if self._envelope_bytes is None:
            self._envelope_bytes = self.envelope.to_bytes()
        return self._envelope_bytes

    def full_hash(self) -> bytes:
        """SHA256 of the whole envelope incl. signatures (apply-order
        tiebreak key, reference: TxSetFrame.cpp:550-599)."""
        if self._full_hash is None:
            self._full_hash = sha256(self.envelope_bytes())
        return self._full_hash

    @property
    def source_id(self):
        sid = getattr(self, "_source_id_memo", None)
        if sid is None:
            sid = self.tx.sourceAccount.account_id()
            self._source_id_memo = sid
        return sid

    @property
    def fee_source_id(self):
        return self.source_id

    @property
    def seq_num(self) -> int:
        return self.tx.seqNum

    def full_fee(self) -> int:
        return self.tx.fee

    def inclusion_fee(self) -> int:
        # Soroban txs bid inclusion separately from the resource fee
        # (reference: TransactionFrame::getInclusionFee)
        sd = self.soroban_data()
        if sd is not None:
            return self.tx.fee - sd.resourceFee
        return self.tx.fee

    def is_soroban(self) -> bool:
        """reference: isSoroban() — any of the 3 contract op types.
        Memoized: ops never change after construction, and the queue/
        fee/apply paths ask several times per tx (the un-memoized walk
        profiled at 6% of the TPSMT leg)."""
        memo = getattr(self, "_is_soroban_memo", None)
        if memo is None:
            from ..xdr.transaction import OperationType
            memo = any(
                op.body.disc in (OperationType.INVOKE_HOST_FUNCTION,
                                 OperationType.EXTEND_FOOTPRINT_TTL,
                                 OperationType.RESTORE_FOOTPRINT)
                for op in self.tx.operations)
            self._is_soroban_memo = memo
        return memo

    def soroban_data(self):
        """The declared SorobanTransactionData, or None."""
        if getattr(self.tx.ext, "disc", 0) == 1:
            return self.tx.ext.value
        return None

    def num_operations(self) -> int:
        return len(self.tx.operations)

    def is_fee_bump(self) -> bool:
        return False

    # --------------------------------------------------------- preconditions --
    def time_bounds(self):
        c = self.tx.cond
        if c.disc == PreconditionType.PRECOND_TIME:
            return c.value
        if c.disc == PreconditionType.PRECOND_V2:
            return c.value.timeBounds
        return None

    def ledger_bounds(self):
        c = self.tx.cond
        if c.disc == PreconditionType.PRECOND_V2:
            return c.value.ledgerBounds
        return None

    def min_seq_num(self):
        c = self.tx.cond
        if c.disc == PreconditionType.PRECOND_V2:
            return c.value.minSeqNum
        return None

    def min_seq_age(self) -> int:
        c = self.tx.cond
        return c.value.minSeqAge if c.disc == PreconditionType.PRECOND_V2 \
            else 0

    def min_seq_ledger_gap(self) -> int:
        c = self.tx.cond
        return c.value.minSeqLedgerGap \
            if c.disc == PreconditionType.PRECOND_V2 else 0

    def extra_signers(self):
        c = self.tx.cond
        return list(c.value.extraSigners) \
            if c.disc == PreconditionType.PRECOND_V2 else []

    # -------------------------------------------------------------- results --
    def _fee_for(self, header, base_fee: Optional[int],
                 applying: bool) -> int:
        """reference: TransactionFrame::getFee (modern branch)"""
        if base_fee is None:
            return self.full_fee()
        adjusted = base_fee * max(1, self.num_operations())
        if applying:
            return min(self.inclusion_fee(), adjusted)
        return adjusted

    def _reset_result(self, header, base_fee: Optional[int],
                      applying: bool) -> None:
        # a REPLACE, never a mutation: a result frozen by a closed
        # ledger's TransactionResultPair stays untouched, the frame
        # starts the new validation pass on a fresh mutable object
        self.result = TransactionResult(
            feeCharged=self._fee_for(header, base_fee, applying),
            result=_TxResultResult(TransactionResultCode.txSUCCESS, []),
            ext=ExtensionPoint(0))

    def _assert_result_mutable(self) -> None:
        # closeLedger freezes the result when it adopts it into the
        # stored TransactionResultPair; mutating it afterwards would
        # silently corrupt committed history / held-back delay-meta.
        # releaseAssert: the guard must survive `python -O`
        releaseAssert(
            not getattr(self.result, "_frozen", False),
            "mutating a TransactionResult adopted by a closed ledger")

    def set_error(self, code: TransactionResultCode) -> None:
        self._assert_result_mutable()
        self.result.result = _TxResultResult(code)

    def _collect_op_results(self) -> List[OperationResult]:
        return [op.result if op.result is not None
                else OperationResult(OperationResultCode.opBAD_AUTH)
                for op in self.op_frames]

    def mark_result_failed(self) -> None:
        self._assert_result_mutable()
        self.result.result = _TxResultResult(
            TransactionResultCode.txFAILED, self._collect_op_results())

    def _mark_result_success_ops(self) -> None:
        self._assert_result_mutable()
        self.result.result = _TxResultResult(
            TransactionResultCode.txSUCCESS, self._collect_op_results())

    # ------------------------------------------------------------- validity --
    def _is_too_early(self, header, lb_offset: int) -> bool:
        tb = self.time_bounds()
        if tb and tb.minTime and \
                tb.minTime > header.scpValue.closeTime + lb_offset:
            return True
        lb = self.ledger_bounds()
        return bool(lb and lb.minLedger > header.ledgerSeq)

    def _is_too_late(self, header, ub_offset: int) -> bool:
        tb = self.time_bounds()
        if tb and tb.maxTime and \
                tb.maxTime < header.scpValue.closeTime + ub_offset:
            return True
        lb = self.ledger_bounds()
        return bool(lb and lb.maxLedger != 0
                    and lb.maxLedger <= header.ledgerSeq)

    def _is_too_early_for_account(self, header, source_acc,
                                  lb_offset: int) -> bool:
        """minSeqAge / minSeqLedgerGap checks (protocol 19 preconditions,
        reference: isTooEarlyForAccount)."""
        if header.ledgerVersion < 19:
            return False
        min_age = self.min_seq_age()
        if min_age:
            acc_time = account_seq_time(source_acc)
            if header.scpValue.closeTime + lb_offset < acc_time + min_age:
                return True
        min_gap = self.min_seq_ledger_gap()
        if min_gap:
            acc_ledger = account_seq_ledger(source_acc)
            if header.ledgerSeq < acc_ledger + min_gap:
                return True
        return False

    def _is_bad_seq(self, header, current: int) -> bool:
        if self.seq_num == tx_utils.starting_sequence_number(
                header.ledgerSeq):
            return True
        if header.ledgerVersion >= 19:
            msn = self.min_seq_num()
            if msn is not None:
                return current < msn or current >= self.seq_num
        return current == INT64_MAX or current + 1 != self.seq_num

    def _common_valid_pre_seqnum(self, ltx, charge_fee: bool,
                                 lb_offset: int, ub_offset: int,
                                 base_fee: Optional[int]) -> bool:
        header = ltx.get_header()
        if header.ledgerVersion < MIN_PROTOCOL and \
                self.envelope.disc == EnvelopeType.ENVELOPE_TYPE_TX:
            self.set_error(TransactionResultCode.txNOT_SUPPORTED)
            return False
        extra = self.extra_signers()
        if extra:
            if len(extra) == 2 and extra[0] == extra[1]:
                self.set_error(TransactionResultCode.txMALFORMED)
                return False
            for sk in extra:
                if sk.disc == SignerKeyType.\
                        SIGNER_KEY_TYPE_ED25519_SIGNED_PAYLOAD and \
                        len(sk.value.payload) == 0:
                    self.set_error(TransactionResultCode.txMALFORMED)
                    return False
        if self.num_operations() == 0:
            self.set_error(TransactionResultCode.txMISSING_OPERATION)
            return False
        # Soroban structural rules (reference: checkSorobanResourceAndSetLedgerCost
        # + isTooManyOperations): exactly one op, sorobanData required
        if self.is_soroban():
            if self.num_operations() != 1 or self.soroban_data() is None \
                    or self.soroban_data().resourceFee < 0 \
                    or self.soroban_data().resourceFee > self.tx.fee:
                self.set_error(TransactionResultCode.txMALFORMED)
                return False
        if self._is_too_early(header, lb_offset):
            self.set_error(TransactionResultCode.txTOO_EARLY)
            return False
        if self._is_too_late(header, ub_offset):
            self.set_error(TransactionResultCode.txTOO_LATE)
            return False
        if charge_fee and self.inclusion_fee() < \
                header.baseFee * max(1, self.num_operations()):
            self.set_error(TransactionResultCode.txINSUFFICIENT_FEE)
            return False
        if not charge_fee and self.inclusion_fee() < 0:
            self.set_error(TransactionResultCode.txMALFORMED)
            return False
        if not ltx.entry_exists(LedgerKey.account(self.source_id)):
            self.set_error(TransactionResultCode.txNO_ACCOUNT)
            return False
        return True

    def check_signature_low(self, checker: SignatureChecker, acc) -> bool:
        signers = tx_utils.get_signers_with_master(acc)
        needed = acc.thresholds[ThresholdIndexes.THRESHOLD_LOW]
        return checker.check_signature(signers, needed)

    def _check_extra_signers(self, checker: SignatureChecker) -> bool:
        extra = self.extra_signers()
        if not extra:
            return True
        return checker.check_signature([(sk, 1) for sk in extra],
                                       len(extra))

    def common_valid(self, checker: SignatureChecker, ltx_outer,
                     current: int, applying: bool, charge_fee: bool,
                     lb_offset: int, ub_offset: int,
                     base_fee: Optional[int] = None) -> ValidationType:
        # every access below is a READ: the reference's nested
        # LedgerTxn here is rolled back unconditionally, so shared
        # snapshots through ltx_outer are equivalent — and skip a
        # LedgerTxn + a recording clone per validated tx
        res = ValidationType.kInvalid
        releaseAssert(not (applying and (lb_offset or ub_offset)),
                      "applying with non-current closeTime")
        if not self._common_valid_pre_seqnum(
                ltx_outer, charge_fee, lb_offset, ub_offset, base_fee):
            return res
        header = ltx_outer.get_header()
        source_le = ltx_outer.load_without_record(
            LedgerKey.account(self.source_id))
        acc = source_le.data.value

        if current == 0:
            current = acc.seqNum
        if self._is_bad_seq(header, current):
            self.set_error(TransactionResultCode.txBAD_SEQ)
            return res
        res = ValidationType.kInvalidUpdateSeqNum

        if self._is_too_early_for_account(header, acc, lb_offset):
            self.set_error(TransactionResultCode.
                           txBAD_MIN_SEQ_AGE_OR_GAP)
            return res
        if not self.check_signature_low(checker, acc):
            self.set_error(TransactionResultCode.txBAD_AUTH)
            return res
        if header.ledgerVersion >= 19 and \
                not self._check_extra_signers(checker):
            self.set_error(TransactionResultCode.txBAD_AUTH)
            return res
        res = ValidationType.kInvalidPostAuth

        # fee was already deducted when applying
        fee_to_pay = 0 if applying else self.full_fee()
        if charge_fee and tx_utils.available_balance(
                header, acc) < fee_to_pay:
            self.set_error(TransactionResultCode.txINSUFFICIENT_BALANCE)
            return res
        return ValidationType.kMaybeValid

    # -------------------------------------------------- queue/txset validity --
    def check_valid(self, ltx_outer, current: int = 0,
                    lb_offset: int = 0, ub_offset: int = 0,
                    charge_fee: bool = True,
                    verify: VerifyFn = default_verify) -> bool:
        """Non-mutating full validity (reference:
        checkValidWithOptionallyChargedFee)."""
        header = ltx_outer.get_header()
        self._reset_result(header, None, False)
        checker = SignatureChecker(self.contents_hash(), self.signatures,
                                   verify)
        with LedgerTxn(ltx_outer) as ltx:
            cv = self.common_valid(checker, ltx, current, False, charge_fee,
                                   lb_offset, ub_offset)
            if cv != ValidationType.kMaybeValid:
                return False
            ok = True
            for op in self.op_frames:
                if not op.check_valid(checker, ltx, False):
                    ok = False
            if not ok:
                self.mark_result_failed()
                return False
            if not checker.check_all_signatures_used():
                self.set_error(TransactionResultCode.txBAD_AUTH_EXTRA)
                return False
        return True

    # ------------------------------------------------------------ fee stage --
    def process_fee_seq_num(self, ltx_outer,
                            base_fee: Optional[int]) -> TransactionResult:
        """Charge the fee into the fee pool (reference:
        processFeeSeqNum; seqnum consumption happens in apply for
        protocol >= 10)."""
        with LedgerTxn(ltx_outer) as ltx:
            header = ltx.load_header()
            self._reset_result(header, base_fee, True)
            source_le = ltx.load(LedgerKey.account(self.fee_source_id))
            releaseAssert(source_le is not None,
                          "fee source account must exist")
            acc = source_le.data.value
            fee = self.result.feeCharged
            if fee > 0:
                fee = min(acc.balance, fee)
                self.result.feeCharged = fee
                acc.balance -= fee
                header.feePool += fee
            ltx.commit()
        return self.result

    def process_fee_seq_num_lean(self, ltx, base_fee: Optional[int]):
        """Fee phase without a nested LedgerTxn per tx: loads through
        the shared phase txn and builds the per-tx LedgerEntryChanges
        [STATE(prev), UPDATED(post)] directly — byte-identical to the
        nested shape (the golden tx-meta baselines pin this)."""
        from ..xdr.ledger import LedgerEntryChange, LedgerEntryChangeType
        header = ltx.load_header()
        self._reset_result(header, base_fee, True)
        source_le, prev = ltx.load_with_state_snapshot(
            LedgerKey.account(self.fee_source_id))
        releaseAssert(source_le is not None,
                      "fee source account must exist")
        acc = source_le.data.value
        fee = self.result.feeCharged
        if fee > 0:
            fee = min(acc.balance, fee)
            self.result.feeCharged = fee
            acc.balance -= fee
            header.feePool += fee
        return [
            LedgerEntryChange(
                LedgerEntryChangeType.LEDGER_ENTRY_STATE, prev),
            LedgerEntryChange(
                LedgerEntryChangeType.LEDGER_ENTRY_UPDATED,
                source_le.clone()),
        ]

    # ----------------------------------------------------------- apply stage --
    def _process_seq_num(self, ltx) -> None:
        header = ltx.load_header()
        source_le = ltx.load(LedgerKey.account(self.source_id))
        acc = source_le.data.value
        releaseAssert(acc.seqNum <= self.seq_num,
                      "unexpected sequence number")
        acc.seqNum = self.seq_num
        if header.ledgerVersion >= 19 and (
                self.min_seq_age() or self.min_seq_ledger_gap()
                or header.ledgerVersion >= 20):
            # v3 ext records when the seqnum moved (CAP-21); the reference
            # materializes it lazily the same way
            v3 = ensure_account_ext_v3(acc)
            v3.seqLedger = header.ledgerSeq
            v3.seqTime = header.scpValue.closeTime

    def _remove_one_time_signer_from(self, ltx, acc_id) -> None:
        le = ltx.load_without_record(LedgerKey.account(acc_id))
        if le is None:
            return
        acc = le.data.value
        hit = any(s.key.disc == SignerKeyType.SIGNER_KEY_TYPE_PRE_AUTH_TX
                  and s.key.value == self.contents_hash()
                  for s in acc.signers)
        if not hit:
            return
        le = ltx.load(LedgerKey.account(acc_id))
        acc = le.data.value
        for i in range(len(acc.signers) - 1, -1, -1):
            s = acc.signers[i]
            if s.key.disc == SignerKeyType.SIGNER_KEY_TYPE_PRE_AUTH_TX \
                    and s.key.value == self.contents_hash():
                from .sponsorship import remove_signer_sponsorship
                remove_signer_sponsorship(ltx, le, i)
                acc.signers.pop(i)
                if acc.ext.disc == 1 and acc.ext.value.ext.disc == 2:
                    sids = acc.ext.value.ext.value.signerSponsoringIDs
                    if i < len(sids):
                        sids.pop(i)

    def _remove_one_time_signers(self, ltx) -> None:
        """Drop PRE_AUTH_TX signers matching this tx from every source
        account (reference: removeOneTimeSignerFromAllSourceAccounts)."""
        ids = {self.source_id.to_bytes(): self.source_id}
        for op in self.op_frames:
            ids[op.source_id.to_bytes()] = op.source_id
        for acc_id in ids.values():
            self._remove_one_time_signer_from(ltx, acc_id)

    def _process_signatures(self, cv: ValidationType,
                            checker: SignatureChecker, ltx) -> bool:
        maybe_valid = cv == ValidationType.kMaybeValid
        if not maybe_valid:
            self._remove_one_time_signers(ltx)
            return False
        all_ops_valid = True
        with LedgerTxn(ltx) as ltx_inner:
            for op in self.op_frames:
                if not op.check_signature(checker, ltx_inner, False):
                    all_ops_valid = False
        self._remove_one_time_signers(ltx)
        if not all_ops_valid:
            self.mark_result_failed()
            return False
        if not checker.check_all_signatures_used():
            self.set_error(TransactionResultCode.txBAD_AUTH_EXTRA)
            return False
        return True

    def _apply_operations(self, checker: SignatureChecker, ltx,
                          meta_ops: Optional[list],
                          invariants=None,
                          meta: Optional[dict] = None) -> bool:
        from ..invariant.manager import (InvariantDoesNotHold,
                                         OperationDelta)
        success = True
        with LedgerTxn(ltx) as ltx_tx:
            ctx = ApplyContext(self.network_id, self.source_id, self.seq_num)
            ctx.soroban_data = self.soroban_data()
            ctx.fee_source_id = self.fee_source_id
            ctx.tx_size_bytes = len(self.envelope_bytes())
            op_metas = []
            for op in self.op_frames:
                with LedgerTxn(ltx_tx) as ltx_op:
                    try:
                        ok = op.apply(checker, ltx_op, ctx)
                        if ok and invariants is not None:
                            # reference: InvariantManager::
                            # checkOnOperationApply called from
                            # TransactionFrame.cpp:1557; a violation
                            # escapes apply entirely (crash semantics)
                            invariants.check_on_operation_apply(
                                op, op.result,
                                OperationDelta.from_ledger_txn(ltx_op))
                    except InvariantDoesNotHold:
                        raise
                    except Exception:
                        self.set_error(
                            TransactionResultCode.txINTERNAL_ERROR)
                        return False
                    if not ok:
                        success = False
                    if success:
                        op_metas.append(ltx_op.get_changes())
                    # reference commits ltxOp unconditionally — a failed
                    # op's mutations stay visible to later ops of the
                    # (ultimately rolled-back) tx
                    ltx_op.commit()
            if success:
                if ctx.active_sponsorships:
                    self.set_error(TransactionResultCode.txBAD_SPONSORSHIP)
                    return False
                ltx_tx.commit()
                if meta_ops is not None:
                    meta_ops.extend(op_metas)
                if meta is not None and self.is_soroban():
                    # soroban leg of V3 meta (reference:
                    # SorobanTransactionMeta — events + return value +
                    # optional off-consensus diagnostics)
                    meta["soroban"] = {
                        "events": list(ctx.soroban_events),
                        "return_value": ctx.soroban_return_value,
                        "diagnostics":
                            list(ctx.soroban_diagnostic_events),
                        "in_success": True,
                    }
                self._mark_result_success_ops()
                return True
            if meta is not None and self.is_soroban() and \
                    ctx.soroban_diagnostic_events:
                # failed invocation: no contract events in meta, but
                # diagnostics ARE emitted (reference: diagnostics with
                # inSuccessfulContractCall=false — the case operators
                # need them most)
                meta["soroban"] = {
                    "events": [],
                    "return_value": None,
                    "diagnostics": list(ctx.soroban_diagnostic_events),
                    "in_success": False,
                }
        self.mark_result_failed()
        return False

    def apply(self, ltx_outer, base_fee: Optional[int] = None,
              verify: VerifyFn = default_verify,
              meta: Optional[dict] = None, invariants=None) -> bool:
        """Full apply (fee must have been processed already); returns
        success and leaves the TransactionResult in self.result
        (reference: TransactionFrame::apply :1703)."""
        header = ltx_outer.get_header()
        self._reset_result(header, base_fee, True)
        checker = SignatureChecker(self.contents_hash(), self.signatures,
                                   verify)
        with LedgerTxn(ltx_outer) as ltx_tx:
            cv = self.common_valid(checker, ltx_tx, 0, True, True, 0, 0)
            if cv >= ValidationType.kInvalidUpdateSeqNum:
                self._process_seq_num(ltx_tx)
            signatures_valid = self._process_signatures(cv, checker, ltx_tx)
            if meta is not None:
                meta["tx_changes_before"] = ltx_tx.get_changes()
            ltx_tx.commit()
        if not (signatures_valid and cv == ValidationType.kMaybeValid):
            return False
        meta_ops = [] if meta is not None else None
        ok = self._apply_operations(checker, ltx_outer, meta_ops, invariants,
                                    meta=meta)
        if meta is not None:
            meta["operations"] = meta_ops or []
        return ok


class FeeBumpTransactionFrame(TransactionFrame):
    """reference: transactions/FeeBumpTransactionFrame.cpp — wraps an
    inner v1 tx; outer fee source pays, inner executes; outer result
    embeds the inner result pair."""

    def __init__(self, envelope: TransactionEnvelope, network_id: bytes):
        releaseAssert(
            envelope.disc == EnvelopeType.ENVELOPE_TYPE_TX_FEE_BUMP,
            "fee-bump envelope required")
        self.envelope = envelope
        self.network_id = network_id
        self.fee_bump_tx = envelope.value.tx
        inner_env = TransactionEnvelope(
            EnvelopeType.ENVELOPE_TYPE_TX, self.fee_bump_tx.innerTx.value)
        self.inner = TransactionFrame(inner_env, network_id)
        self.tx = self.inner.tx
        self.signatures = envelope.value.signatures
        self._contents_hash = None
        self._full_hash = None
        self._envelope_bytes = None
        self.result: Optional[TransactionResult] = None
        self.op_frames = self.inner.op_frames

    def contents_hash(self) -> bytes:
        if self._contents_hash is None:
            payload = TransactionSignaturePayload(
                networkId=self.network_id,
                taggedTransaction=_TaggedTransaction(
                    EnvelopeType.ENVELOPE_TYPE_TX_FEE_BUMP,
                    self.fee_bump_tx))
            self._contents_hash = sha256(payload.to_bytes())
        return self._contents_hash

    def is_fee_bump(self) -> bool:
        return True

    @property
    def fee_source_id(self):
        return self.fee_bump_tx.feeSource.account_id()

    def full_fee(self) -> int:
        return self.fee_bump_tx.fee

    def inclusion_fee(self) -> int:
        return self.fee_bump_tx.fee

    def num_operations(self) -> int:
        return self.inner.num_operations() + 1

    def _inner_result_pair(self) -> InnerTransactionResultPair:
        inner_res = self.inner.result
        code = inner_res.result.disc
        value = inner_res.result.value
        inner = _InnerTxResultResult(code, value) \
            if _InnerTxResultResult.ARMS.get(code) is not None \
            else _InnerTxResultResult(code)
        return InnerTransactionResultPair(
            transactionHash=self.inner.contents_hash(),
            result=InnerTransactionResult(
                feeCharged=inner_res.feeCharged,
                result=inner,
                ext=ExtensionPoint(0)))

    def check_valid(self, ltx_outer, current: int = 0,
                    lb_offset: int = 0, ub_offset: int = 0,
                    charge_fee: bool = True,
                    verify: VerifyFn = default_verify) -> bool:
        header = ltx_outer.get_header()
        self._reset_result(header, None, False)
        if header.ledgerVersion < 13:
            self.set_error(TransactionResultCode.txNOT_SUPPORTED)
            return False
        min_fee = header.baseFee * self.num_operations()
        if self.full_fee() < min_fee:
            self.set_error(TransactionResultCode.txINSUFFICIENT_FEE)
            return False
        # fee-per-op of the bump must beat the inner fee bid
        # (reference: FeeBumpTransactionFrame::checkValid feeSource rules)
        inner_bid = self.inner.inclusion_fee()
        inner_ops = max(1, self.inner.num_operations())
        if self.full_fee() * inner_ops < inner_bid * self.num_operations():
            self.set_error(TransactionResultCode.txINSUFFICIENT_FEE)
            return False
        checker = SignatureChecker(self.contents_hash(), self.signatures,
                                   verify)
        with LedgerTxn(ltx_outer) as ltx:
            if not self._fee_source_valid(checker, ltx):
                return False
            if not checker.check_all_signatures_used():
                self.set_error(TransactionResultCode.txBAD_AUTH_EXTRA)
                return False
            inner_ok = self.inner.check_valid(
                ltx, current, lb_offset, ub_offset, charge_fee=False,
                verify=verify)
        if not inner_ok:
            self.result = TransactionResult(
                feeCharged=self.result.feeCharged,
                result=_TxResultResult(
                    TransactionResultCode.txFEE_BUMP_INNER_FAILED,
                    self._inner_result_pair()),
                ext=ExtensionPoint(0))
            return False
        return True

    def _fee_source_valid(self, checker: SignatureChecker, ltx) -> bool:
        header = ltx.get_header()
        source_le = ltx.load_without_record(
            LedgerKey.account(self.fee_source_id))
        if source_le is None:
            self.set_error(TransactionResultCode.txNO_ACCOUNT)
            return False
        acc = source_le.data.value
        if not self.check_signature_low(checker, acc):
            self.set_error(TransactionResultCode.txBAD_AUTH)
            return False
        if tx_utils.available_balance(header, acc) < self.full_fee():
            self.set_error(TransactionResultCode.txINSUFFICIENT_BALANCE)
            return False
        return True

    def apply(self, ltx_outer, base_fee: Optional[int] = None,
              verify: VerifyFn = default_verify,
              meta: Optional[dict] = None, invariants=None) -> bool:
        header = ltx_outer.get_header()
        self._reset_result(header, base_fee, True)
        checker = SignatureChecker(self.contents_hash(), self.signatures,
                                   verify)
        with LedgerTxn(ltx_outer) as ltx:
            fee_auth_ok = self._fee_source_valid_applying(checker, ltx)
            # the fee-bump's own PRE_AUTH_TX signer comes off the fee
            # source whether or not auth succeeded (reference:
            # removeOneTimeSignerKeyFromFeeSource)
            self._remove_one_time_signer_from(ltx, self.fee_source_id)
            if fee_auth_ok and not checker.check_all_signatures_used():
                self.set_error(TransactionResultCode.txBAD_AUTH_EXTRA)
                fee_auth_ok = False
            ltx.commit()
            if not fee_auth_ok:
                return False
        inner_ok = self.inner.apply(ltx_outer, base_fee=None, verify=verify,
                                    meta=meta, invariants=invariants)
        code = TransactionResultCode.txFEE_BUMP_INNER_SUCCESS if inner_ok \
            else TransactionResultCode.txFEE_BUMP_INNER_FAILED
        self.result = TransactionResult(
            feeCharged=self.result.feeCharged,
            result=_TxResultResult(code, self._inner_result_pair()),
            ext=ExtensionPoint(0))
        return inner_ok

    def _fee_source_valid_applying(self, checker: SignatureChecker,
                                   ltx) -> bool:
        source_le = ltx.load_without_record(
            LedgerKey.account(self.fee_source_id))
        if source_le is None:
            self.set_error(TransactionResultCode.txNO_ACCOUNT)
            return False
        if not self.check_signature_low(checker, source_le.data.value):
            self.set_error(TransactionResultCode.txBAD_AUTH)
            return False
        return True
