"""Static touched-key footprints for conflict-staged parallel apply.

Reference: the parallel apply phases of Lokhava et al. (SOSP 2019 §6)
partition a ledger's transactions by the ledger entries they touch; the
Soroban half of that design makes footprints explicit in the envelope
(SorobanTransactionData.resources.footprint), while classic operations
need them derived from the operation bodies.

`extract_footprint` computes, per transaction frame, the set of ledger
keys (canonical key bytes) the tx MAY touch during apply, plus a
`precise` verdict:

- ``precise=True``: the key set is a guaranteed superset of every entry
  the apply path loads, creates or erases (including signature-check
  reads of the op source accounts).  Only these txs are eligible for
  concurrent application; anything else acts as a conflict barrier.
- ``precise=False``: the op set contains something whose touched keys
  cannot be named from the envelope alone — order-book walks (offers,
  path payments), sponsorship releases whose sponsor lives in ledger
  state, ID-pool allocation (header mutation), Soroban host calls.  The
  keys collected so far are still returned: they remain useful for the
  close-prepare prefetch, just not for conflict partitioning.

The staged-apply engine (ledger/parallel_apply.py) re-verifies the
claim at merge time — a worker whose recorded delta/read set escapes
its declared footprint forces the stage back onto the sequential path —
so a classification bug here degrades parallelism, never correctness.
"""

from __future__ import annotations

from typing import List, Set

from ..xdr.ledger_entries import AssetType, LedgerKey, TrustLineAsset
from ..xdr.transaction import OperationType
from . import tx_utils


class TxFootprint:
    """Touched-key claim of one transaction frame."""

    __slots__ = ("keys", "precise")

    def __init__(self, keys: Set[bytes], precise: bool):
        self.keys = keys
        self.precise = precise


def _acct_kb(account_id) -> bytes:
    return LedgerKey.account(account_id).to_bytes()


def extract_footprint(tx) -> "TxFootprint":
    """Footprint of one TransactionFrame / FeeBumpTransactionFrame."""
    keys: Set[bytes] = set()
    keys.add(_acct_kb(tx.source_id))
    keys.add(_acct_kb(tx.fee_source_id))
    precise = True

    from .frame import FeeBumpTransactionFrame
    if isinstance(tx, FeeBumpTransactionFrame):
        # the outer frame's signature bookkeeping and the inner frame's
        # result plumbing interleave; rare enough to stay sequential
        precise = False

    if tx.is_soroban():
        # declared footprint keys still feed the prefetch, but host
        # calls mutate the header (fee refunds) and TTL entries beyond
        # the declaration, so Soroban txs apply inline
        precise = False
        sd = tx.soroban_data()
        if sd is not None:
            for key in list(sd.resources.footprint.readOnly) + \
                    list(sd.resources.footprint.readWrite):
                keys.add(key.to_bytes())

    tx_source = tx.tx.sourceAccount
    for op in tx.tx.operations:
        src = (op.sourceAccount if op.sourceAccount is not None
               else tx_source).account_id()
        # signature threshold checks + one-time-signer removal read the
        # op source account even when the op itself never loads it
        keys.add(_acct_kb(src))
        if not _op_keys(op, src, keys):
            precise = False
    return TxFootprint(keys, precise)


def _op_keys(op, src, keys: Set[bytes]) -> bool:
    """Add `op`'s touched keys to `keys`; True iff the set is a
    guaranteed superset of what the op's do_apply touches."""
    d = op.body.disc
    b = op.body.value
    if d == OperationType.PAYMENT:
        dest = b.destination.account_id()
        keys.add(_acct_kb(dest))
        if b.asset.disc != AssetType.ASSET_TYPE_NATIVE:
            issuer = tx_utils.asset_issuer(b.asset)
            keys.add(_acct_kb(issuer))
            tla = TrustLineAsset.from_asset(b.asset)
            keys.add(LedgerKey.trust_line(src, tla).to_bytes())
            keys.add(LedgerKey.trust_line(dest, tla).to_bytes())
        return True
    if d == OperationType.CREATE_ACCOUNT:
        keys.add(_acct_kb(b.destination))
        return True
    if d == OperationType.MANAGE_DATA:
        keys.add(LedgerKey.data(src, b.dataName).to_bytes())
        # deleting a data entry may release a sponsorship whose sponsor
        # is named only in the stored entry, not the envelope
        return b.dataValue is not None
    if d == OperationType.BUMP_SEQUENCE:
        return True
    if d == OperationType.SET_OPTIONS:
        if b.inflationDest is not None:
            keys.add(_acct_kb(b.inflationDest))
        # signer removal may release a ledger-state sponsorship
        return b.signer is None
    if d == OperationType.ACCOUNT_MERGE:
        # body IS the destination MuxedAccount; the source's signers may
        # carry sponsorships held by accounts named only in ledger state
        keys.add(_acct_kb(b.account_id()))
        return False
    # offers / path payments walk the order book and allocate from the
    # header ID pool; sponsorship ops rewrite ctx-external state;
    # everything unrecognized stays sequential by construction
    return False


def extract_footprints(txs) -> List[TxFootprint]:
    return [extract_footprint(tx) for tx in txs]
