"""Offer liability bookkeeping.

Reference: transactions/TransactionUtils.cpp acquireLiabilities /
releaseLiabilities (:460-520) — every resting offer reserves selling
liabilities on the line of the asset it sells and buying liabilities on
the line of the asset it buys; native liabilities live on the account
entry (ext v1), credit liabilities on the trustline (ext v1). Removing an
offer releases both sides; `remove_offers_by_account_and_asset` is the
auth-revocation path (TrustFlagsOpFrameBase::removeOffers).
"""

from __future__ import annotations

from typing import Optional

from ..util.checks import releaseAssert
from ..xdr.ledger_entries import (AccountEntry, AssetType, LedgerEntry,
                                  LedgerKey, TrustLineEntry,
                                  TrustLineEntryV1, Liabilities)
from ..xdr.types import AccountID
from . import offer_math, tx_utils
from .sponsorship import ensure_account_ext_v1, remove_entry_with_possible_sponsorship

INT64_MAX = 2**63 - 1


def ensure_trustline_ext_v1(tl: TrustLineEntry) -> TrustLineEntryV1:
    if tl.ext.disc == 0:
        tl.ext = type(tl.ext)(1, TrustLineEntryV1(
            liabilities=Liabilities(buying=0, selling=0)))
    return tl.ext.value


def add_account_buying_liabilities(header, acc: AccountEntry,
                                   delta: int) -> bool:
    v1 = ensure_account_ext_v1(acc)
    new = v1.liabilities.buying + delta
    if new < 0 or acc.balance > INT64_MAX - new:
        return False
    v1.liabilities.buying = new
    return True


def add_account_selling_liabilities(header, acc: AccountEntry,
                                    delta: int) -> bool:
    v1 = ensure_account_ext_v1(acc)
    new = v1.liabilities.selling + delta
    if new < 0 or new > acc.balance - tx_utils.min_balance(header, acc):
        return False
    v1.liabilities.selling = new
    return True


def add_trustline_buying_liabilities(tl: TrustLineEntry, delta: int) -> bool:
    v1 = ensure_trustline_ext_v1(tl)
    new = v1.liabilities.buying + delta
    if new < 0 or tl.balance > tl.limit - new:
        return False
    v1.liabilities.buying = new
    return True


def add_trustline_selling_liabilities(tl: TrustLineEntry,
                                      delta: int) -> bool:
    v1 = ensure_trustline_ext_v1(tl)
    new = v1.liabilities.selling + delta
    if new < 0 or new > tl.balance:
        return False
    v1.liabilities.selling = new
    return True


def _adjust_asset_liabilities(ltx, header, account_le: LedgerEntry,
                              asset, selling_delta: int,
                              buying_delta: int) -> bool:
    """Apply liability deltas for one asset leg of an offer owned by
    account_le's account. The issuer of an asset holds no trustline and
    carries no liabilities for it (reference: TrustLineWrapper issuer)."""
    acc: AccountEntry = account_le.data.value
    if asset.disc == AssetType.ASSET_TYPE_NATIVE:
        ok = True
        if selling_delta:
            ok = ok and add_account_selling_liabilities(
                header, acc, selling_delta)
        if buying_delta:
            ok = ok and add_account_buying_liabilities(
                header, acc, buying_delta)
        return ok
    issuer = tx_utils.asset_issuer(asset)
    if issuer.to_bytes() == acc.accountID.to_bytes():
        return True
    tl_le = tx_utils.load_trustline(ltx, acc.accountID, asset)
    if tl_le is None:
        return False
    tl = tl_le.data.value
    ok = True
    if selling_delta:
        ok = ok and add_trustline_selling_liabilities(tl, selling_delta)
    if buying_delta:
        ok = ok and add_trustline_buying_liabilities(tl, buying_delta)
    return ok


def acquire_liabilities(ltx, header, offer_le: LedgerEntry) -> bool:
    return _apply_offer_liabilities(ltx, header, offer_le, acquire=True)


def release_liabilities(ltx, header, offer_le: LedgerEntry) -> None:
    ok = _apply_offer_liabilities(ltx, header, offer_le, acquire=False)
    releaseAssert(ok, "releasing liabilities cannot fail")


def _apply_offer_liabilities(ltx, header, offer_le: LedgerEntry,
                             acquire: bool) -> bool:
    offer = offer_le.data.value
    sell_liab = offer_math.offer_selling_liabilities(offer)
    buy_liab = offer_math.offer_buying_liabilities(offer)
    sign = 1 if acquire else -1
    acct_le = ltx.load(LedgerKey.account(offer.sellerID))
    releaseAssert(acct_le is not None, "offer owner must exist")
    ok = _adjust_asset_liabilities(
        ltx, header, acct_le, offer.selling, sign * sell_liab, 0)
    ok = ok and _adjust_asset_liabilities(
        ltx, header, acct_le, offer.buying, 0, sign * buy_liab)
    return ok


def erase_offer(ltx, header, offer_le: LedgerEntry) -> None:
    """Release liabilities, refund the reserve accounting, erase.
    (reference: eraseOfferWithPossibleSponsorship)"""
    offer = offer_le.data.value
    release_liabilities(ltx, header, offer_le)
    owner_le = ltx.load(LedgerKey.account(offer.sellerID))
    remove_entry_with_possible_sponsorship(ltx, header, offer_le, owner_le)
    ltx.erase(LedgerKey.offer(offer.sellerID, offer.offerID))


def remove_offers_by_account_and_asset(ltx, header, account_id: AccountID,
                                       asset) -> None:
    """Delete every offer owned by account_id buying or selling `asset`
    (reference: removeOffersByAccountAndAsset, the auth-revocation
    path)."""
    for offer_le in list(ltx.load_offers_by_account(account_id)):
        offer = offer_le.data.value
        if offer.selling == asset or offer.buying == asset:
            erase_offer(ltx, header, offer_le)
