"""Transaction subsystem (reference: src/transactions/).

- signature_checker: hint-prefiltered threshold signature accounting with
  a pluggable verifier — the seam the TPU batch backend slots behind
  (transactions/SignatureChecker.cpp, SURVEY.md §3.2)
- tx_utils: account/trustline/balance/reserve helpers
  (transactions/TransactionUtils.cpp)
- frame: TransactionFrame / FeeBumpTransactionFrame lifecycle
  (transactions/TransactionFrame.cpp)
- operations/: one OperationFrame per operation type
"""

from .frame import TransactionFrame, FeeBumpTransactionFrame, make_frame
from .signature_checker import SignatureChecker
from . import operations  # registers every OperationFrame

__all__ = ["TransactionFrame", "FeeBumpTransactionFrame", "make_frame",
           "SignatureChecker"]
