"""Threshold signature accounting.

Reference: transactions/SignatureChecker.{h,cpp} — given the tx contents
hash and the envelope's DecoratedSignatures, `check_signature(signers,
needed_weight)` consumes signatures (each may be used once), matching by
the 4-byte hint before any crypto, and sums signer weights until the
threshold is met. `check_all_signatures_used` enforces the reference's
txBAD_AUTH_EXTRA rule.

The verify callable is the TPU seam: by default PubKeyUtils.verify_sig
(cached libsodium-semantics path, crypto/SecretKey.cpp:427); the batch
apply paths can inject a `PrevalidatedVerifier` built from one TPU batch
verify over a whole txset/checkpoint (SURVEY.md §3.3).
"""

from __future__ import annotations

import hashlib
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..crypto.keys import PubKeyUtils
from ..xdr.types import SignerKey, SignerKeyType
from ..xdr.transaction import DecoratedSignature

VerifyFn = Callable[[bytes, bytes, bytes], bool]  # (pub, sig, msg) -> ok


def default_verify(pub: bytes, sig: bytes, msg: bytes) -> bool:
    return PubKeyUtils.verify_sig(pub, sig, msg)


class PrevalidatedVerifier:
    """Lookup table of (pub, sig, msg) -> bool filled by one TPU batch
    verify; falls back to the sync path on miss (stragglers keep exact
    semantics, SURVEY.md §7 'latency vs batch')."""

    def __init__(self, fallback: VerifyFn = default_verify):
        self._results: Dict[bytes, bool] = {}
        self._fallback = fallback
        self.hits = 0
        self.misses = 0

    @staticmethod
    def _key(pub: bytes, sig: bytes, msg: bytes) -> bytes:
        return hashlib.blake2b(pub + sig + msg, digest_size=32).digest()

    def add_results(self, tuples: Sequence[Tuple[bytes, bytes, bytes]],
                    results: Sequence[bool]) -> None:
        for (p, s, m), ok in zip(tuples, results):
            self._results[self._key(p, s, m)] = bool(ok)

    def __call__(self, pub: bytes, sig: bytes, msg: bytes) -> bool:
        r = self._results.get(self._key(pub, sig, msg))
        if r is not None:
            self.hits += 1
            return r
        self.misses += 1
        return self._fallback(pub, sig, msg)


def signed_payload_hint(pubkey_raw: bytes, payload: bytes) -> bytes:
    """Hint for an ed25519-signed-payload signature: pubkey tail XOR
    the zero-right-padded payload tail (reference:
    SignatureUtils::getSignedPayloadHint)."""
    tail = payload[-4:] if len(payload) >= 4 else payload.ljust(4, b"\x00")
    return bytes(a ^ b for a, b in zip(pubkey_raw[28:], tail))


class SignatureChecker:
    def __init__(self, contents_hash: bytes,
                 signatures: Sequence[DecoratedSignature],
                 verify: VerifyFn = default_verify):
        self.contents_hash = contents_hash
        self.signatures = list(signatures)
        self.used = [False] * len(self.signatures)
        self._verify = verify

    def check_signature(self, signers: List[Tuple[SignerKey, int]],
                        needed_weight: int) -> bool:
        """signers: (signer key, weight). Matches the reference
        SignatureChecker::checkSignature exactly: signatures are marked
        used for txBAD_AUTH_EXTRA bookkeeping but remain matchable by
        LATER checkSignature calls (the same master signature covers both
        the tx-low check and each op-threshold check); within one call a
        matched signer is dropped so it can't double-count; weights clamp
        to 255; PRE_AUTH_TX signers count without consuming a
        signature."""
        # fast path: one ed25519 signer (the overwhelmingly common
        # master-key case) — same semantics as the general loop below,
        # without the per-type group scaffolding
        if len(signers) == 1 and \
                signers[0][0].disc == SignerKeyType.SIGNER_KEY_TYPE_ED25519:
            signer, weight = signers[0]
            for i, ds in enumerate(self.signatures):
                if self._match_ed25519(ds, signer):
                    self.used[i] = True
                    return min(weight, 255) >= needed_weight
            return False

        total = 0
        pending: List[Tuple[SignerKey, int]] = []
        for signer, weight in signers:
            w = min(weight, 255)
            if signer.disc == SignerKeyType.SIGNER_KEY_TYPE_PRE_AUTH_TX:
                if signer.value == self.contents_hash:
                    total += w
                    if total >= needed_weight:
                        return True
            else:
                pending.append((signer, w))

        # reference order: HASH_X pass, then ED25519, then SIGNED_PAYLOAD
        for want_type, match in (
                (SignerKeyType.SIGNER_KEY_TYPE_HASH_X, self._match_hash_x),
                (SignerKeyType.SIGNER_KEY_TYPE_ED25519, self._match_ed25519),
                (SignerKeyType.SIGNER_KEY_TYPE_ED25519_SIGNED_PAYLOAD,
                 self._match_signed_payload)):
            group = [(s, w) for (s, w) in pending if s.disc == want_type]
            for i, ds in enumerate(self.signatures):
                for j, (signer, w) in enumerate(group):
                    if match(ds, signer):
                        self.used[i] = True
                        total += w
                        if total >= needed_weight:
                            return True
                        group.pop(j)
                        break
        # no early return ⇒ threshold never reached; note a call with
        # needed_weight 0 still requires at least one match (reference
        # returns false at the end unconditionally)
        return False

    def _match_ed25519(self, ds: DecoratedSignature,
                       signer: SignerKey) -> bool:
        pub = signer.value
        if ds.hint != pub[28:]:
            return False
        return self._verify(pub, ds.signature, self.contents_hash)

    def _match_signed_payload(self, ds: DecoratedSignature,
                              signer: SignerKey) -> bool:
        sp = signer.value
        if ds.hint != signed_payload_hint(bytes(sp.ed25519),
                                          bytes(sp.payload)):
            return False
        return self._verify(sp.ed25519, ds.signature, sp.payload)

    def _match_hash_x(self, ds: DecoratedSignature,
                      signer: SignerKey) -> bool:
        hash_x = signer.value
        preimage = ds.signature
        if len(preimage) > 64:
            return False
        if hashlib.sha256(preimage).digest() != hash_x:
            return False
        return ds.hint == hash_x[28:]

    def check_all_signatures_used(self) -> bool:
        return all(self.used)


def collect_signature_tuples(frames, network_id=None):
    """(pub, sig, msg) candidates for a batch verify: each decorated
    signature paired with the tx's hint-matching source key, and — when
    `network_id` is provided — every Soroban address-credential
    auth-entry signature with its deterministic auth payload (BASELINE.md
    config #4: contract-heavy ledgers). Signatures from extra signers
    miss the cache and fall back to the sync path, preserving exact
    semantics (SURVEY.md §7 'latency vs batch'). Shared by the herder's
    txset validation and catchup's checkpoint prevalidation (SURVEY.md
    §3.2/§3.3 collection points)."""
    tuples = []
    for frame in frames:
        src_raw = bytes(frame.source_id.value)  # 32-byte ed25519 key
        h = frame.contents_hash()
        for ds in frame.signatures:
            if bytes(ds.hint) == src_raw[-4:]:
                tuples.append((src_raw, bytes(ds.signature), h))
        if network_id is not None:
            tuples.extend(_soroban_auth_tuples(frame, network_id))
    return tuples


def _soroban_auth_tuples(frame, network_id: bytes):
    """Address-credential auth signatures of a tx's InvokeHostFunction
    ops: the payload is deterministic from the envelope alone, so these
    batch ahead of apply exactly like tx signatures."""
    from ..xdr.contract import (SCAddressType, SorobanCredentialsType)
    from ..xdr.transaction import OperationType
    out = []
    for op in frame.tx.operations:      # fee bump shares the inner .tx
        if op.body.disc != OperationType.INVOKE_HOST_FUNCTION:
            continue
        for entry in op.body.value.auth:
            cred = entry.credentials
            if cred.disc != \
                    SorobanCredentialsType.SOROBAN_CREDENTIALS_ADDRESS:
                continue
            ac = cred.value
            if ac.address.disc != SCAddressType.SC_ADDRESS_TYPE_ACCOUNT:
                continue
            from ..soroban.host import SorobanHost, soroban_auth_payload
            payload = soroban_auth_payload(
                network_id, ac.nonce, ac.signatureExpirationLedger,
                entry.rootInvocation)
            for pub, sig in SorobanHost._extract_signatures(ac.signature):
                out.append((pub, sig, payload))
    return out
