"""Threshold signature accounting.

Reference: transactions/SignatureChecker.{h,cpp} — given the tx contents
hash and the envelope's DecoratedSignatures, `check_signature(signers,
needed_weight)` consumes signatures (each may be used once), matching by
the 4-byte hint before any crypto, and sums signer weights until the
threshold is met. `check_all_signatures_used` enforces the reference's
txBAD_AUTH_EXTRA rule.

The verify callable is the TPU seam: by default PubKeyUtils.verify_sig
(cached libsodium-semantics path, crypto/SecretKey.cpp:427); the batch
apply paths can inject a `PrevalidatedVerifier` built from one TPU batch
verify over a whole txset/checkpoint (SURVEY.md §3.3).
"""

from __future__ import annotations

import hashlib
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..crypto.keys import PubKeyUtils
from ..xdr.types import SignerKey, SignerKeyType
from ..xdr.transaction import DecoratedSignature

VerifyFn = Callable[[bytes, bytes, bytes], bool]  # (pub, sig, msg) -> ok


def default_verify(pub: bytes, sig: bytes, msg: bytes) -> bool:
    return PubKeyUtils.verify_sig(pub, sig, msg)


class PrevalidatedVerifier:
    """Lookup table of (pub, sig, msg) -> bool filled by one TPU batch
    verify; falls back to the sync path on miss (stragglers keep exact
    semantics, SURVEY.md §7 'latency vs batch')."""

    def __init__(self, fallback: VerifyFn = default_verify):
        self._results: Dict[bytes, bool] = {}
        self._fallback = fallback
        self.hits = 0
        self.misses = 0

    @staticmethod
    def _key(pub: bytes, sig: bytes, msg: bytes) -> bytes:
        return hashlib.blake2b(pub + sig + msg, digest_size=32).digest()

    def add_results(self, tuples: Sequence[Tuple[bytes, bytes, bytes]],
                    results: Sequence[bool]) -> None:
        for (p, s, m), ok in zip(tuples, results):
            self._results[self._key(p, s, m)] = bool(ok)

    def __call__(self, pub: bytes, sig: bytes, msg: bytes) -> bool:
        r = self._results.get(self._key(pub, sig, msg))
        if r is not None:
            self.hits += 1
            return r
        self.misses += 1
        return self._fallback(pub, sig, msg)


class SignatureChecker:
    def __init__(self, contents_hash: bytes,
                 signatures: Sequence[DecoratedSignature],
                 verify: VerifyFn = default_verify):
        self.contents_hash = contents_hash
        self.signatures = list(signatures)
        self.used = [False] * len(self.signatures)
        self._verify = verify

    def check_signature(self, signers: List[Tuple[SignerKey, int]],
                        needed_weight: int) -> bool:
        """signers: (signer key, weight); weight sum of distinct matched
        signers must reach needed_weight. needed_weight==0 succeeds
        immediately (reference semantics for PreAuth-covered ops)."""
        total = 0
        for signer, weight in signers:
            if weight <= 0:
                continue
            if self._signer_matched(signer):
                total += weight
                if total >= needed_weight:
                    break
        return total >= needed_weight or needed_weight == 0

    def _signer_matched(self, signer: SignerKey) -> bool:
        t = signer.disc
        if t == SignerKeyType.SIGNER_KEY_TYPE_ED25519:
            return self._match_ed25519(signer.value, self.contents_hash)
        if t == SignerKeyType.SIGNER_KEY_TYPE_PRE_AUTH_TX:
            # the signer IS the tx hash: no signature object consumed
            return signer.value == self.contents_hash
        if t == SignerKeyType.SIGNER_KEY_TYPE_HASH_X:
            return self._match_hash_x(signer.value)
        if t == SignerKeyType.SIGNER_KEY_TYPE_ED25519_SIGNED_PAYLOAD:
            sp = signer.value
            return self._match_ed25519(sp.ed25519, sp.payload)
        return False

    def _match_ed25519(self, pub: bytes, msg: bytes) -> bool:
        hint = pub[28:]
        for i, ds in enumerate(self.signatures):
            if self.used[i] or ds.hint != hint:
                continue
            if self._verify(pub, ds.signature, msg):
                self.used[i] = True
                return True
        return False

    def _match_hash_x(self, hash_x: bytes) -> bool:
        for i, ds in enumerate(self.signatures):
            if self.used[i]:
                continue
            preimage = ds.signature
            if len(preimage) > 64:
                continue
            if hashlib.sha256(preimage).digest() == hash_x:
                if ds.hint == hash_x[28:]:
                    self.used[i] = True
                    return True
        return False

    def check_all_signatures_used(self) -> bool:
        return all(self.used)
