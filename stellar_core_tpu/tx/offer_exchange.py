"""Order-book / liquidity-pool conversion engine.

Reference: transactions/OfferExchange.cpp — `convert_with_offers_and_pools`
walks the best-offer chain (crossOfferV10 per resting offer) or swaps
against the constant-product pool, choosing whichever gives the taker the
strictly better price (maybeConvertWithOffers/shouldConvertWithOffers).

Terminology follows the reference: the taker sends "sheep" and receives
"wheat"; resting offers sell wheat for sheep.
"""

from __future__ import annotations

from enum import IntEnum
from typing import Callable, List, Optional, Tuple

from ..util.checks import releaseAssert
from ..xdr.ledger import LedgerHeaderFlags
from ..xdr.ledger_entries import (AssetType, LedgerEntry, LedgerKey,
                                  OfferEntry, Price)
from ..xdr.results import (ClaimAtom, ClaimAtomType, ClaimOfferAtom,
                           ClaimLiquidityAtom)
from ..xdr.types import AccountID
from . import liabilities as liab
from . import offer_math, tx_utils
from .offer_math import Rounding, RoundingType, exchange_v10
from .pool_trust import LIQUIDITY_POOL_FEE_V18, pool_id_for_assets
from .sponsorship import remove_entry_with_possible_sponsorship
from ..ledger.ledger_txn import LedgerTxn

INT64_MAX = 2**63 - 1
MAX_OFFERS_TO_CROSS = 1000


class ConvertResult(IntEnum):
    eOK = 0
    ePartial = 1
    eFilterStopBadPrice = 2
    eFilterStopCrossSelf = 3
    eCrossedTooMany = 4


class OfferFilterResult(IntEnum):
    eKeep = 0
    eStopBadPrice = 1
    eStopCrossSelf = 2


class CrossOfferResult(IntEnum):
    eOfferTaken = 0
    eOfferPartial = 1
    eOfferCantConvert = 2


# ---------------------------------------------------------- capacity limits --

def _load_tl(ltx, account_id: AccountID, asset):
    return tx_utils.load_trustline(ltx, account_id, asset)


def can_sell_at_most(ltx, header, account_id: AccountID, asset) -> int:
    """reference: OfferExchange canSellAtMost"""
    if asset.disc == AssetType.ASSET_TYPE_NATIVE:
        le = ltx.load_without_record(LedgerKey.account(account_id))
        return max(tx_utils.available_balance(header, le.data.value), 0)
    if tx_utils.asset_issuer(asset).to_bytes() == account_id.to_bytes():
        return INT64_MAX
    tl_le = _load_tl(ltx, account_id, asset)
    if tl_le is not None and tx_utils.is_authorized_to_maintain_liabilities(
            tl_le.data.value):
        tl = tl_le.data.value
        return max(tl.balance - tx_utils._tl_selling_liabilities(tl), 0)
    return 0


def can_buy_at_most(ltx, header, account_id: AccountID, asset) -> int:
    """reference: OfferExchange canBuyAtMost"""
    if asset.disc == AssetType.ASSET_TYPE_NATIVE:
        le = ltx.load_without_record(LedgerKey.account(account_id))
        acc = le.data.value
        return max(INT64_MAX - acc.balance -
                   tx_utils.buying_liabilities_account(acc), 0)
    if tx_utils.asset_issuer(asset).to_bytes() == account_id.to_bytes():
        return INT64_MAX
    tl_le = _load_tl(ltx, account_id, asset)
    if tl_le is None:
        return 0
    return max(tx_utils.max_receive_trustline(tl_le.data.value), 0)


def _add_asset_balance(ltx, header, account_id: AccountID, asset,
                       delta: int) -> bool:
    """Move `delta` of `asset` on the account's line; issuers mint/burn."""
    if asset.disc == AssetType.ASSET_TYPE_NATIVE:
        le = ltx.load(LedgerKey.account(account_id))
        return tx_utils.add_balance_account(header, le.data.value, delta)
    if tx_utils.asset_issuer(asset).to_bytes() == account_id.to_bytes():
        return True
    tl_le = _load_tl(ltx, account_id, asset)
    if tl_le is None:
        return False
    return tx_utils.add_balance_trustline(tl_le.data.value, delta)


# --------------------------------------------------------------- crossing ---

def _adjust_offer_in_place(ltx, header, offer_le: LedgerEntry) -> None:
    offer: OfferEntry = offer_le.data.value
    max_wheat = min(offer.amount, can_sell_at_most(
        ltx, header, offer.sellerID, offer.selling))
    max_sheep_recv = can_buy_at_most(ltx, header, offer.sellerID,
                                     offer.buying)
    offer.amount = offer_math.adjust_offer_amount(
        offer.price, max_wheat, max_sheep_recv)


def cross_offer_v10(ltx, offer_le: LedgerEntry, max_wheat_received: int,
                    max_sheep_send: int, round_type: RoundingType,
                    offer_trail: List[ClaimAtom]
                    ) -> Tuple[CrossOfferResult, int, int, bool]:
    """Cross one resting wheat-selling offer (reference: crossOfferV10).
    Returns (result, num_wheat_received, num_sheep_send, wheat_stays)."""
    releaseAssert(max_wheat_received > 0 and max_sheep_send > 0,
                  "crossOfferV10 with nothing to exchange")
    header = ltx.load_header()
    offer: OfferEntry = offer_le.data.value
    sheep, wheat = offer.buying, offer.selling
    account_b, offer_id = offer.sellerID, offer.offerID

    liab.release_liabilities(ltx, header, offer_le)
    _adjust_offer_in_place(ltx, header, offer_le)

    max_wheat_send = min(offer.amount, can_sell_at_most(
        ltx, header, account_b, wheat))
    max_sheep_receive = can_buy_at_most(ltx, header, account_b, sheep)
    ex = exchange_v10(offer.price, max_wheat_send, max_wheat_received,
                      max_sheep_send, max_sheep_receive, round_type)
    wheat_received, sheep_send = ex.num_wheat_received, ex.num_sheep_send

    if sheep_send:
        releaseAssert(_add_asset_balance(ltx, header, account_b, sheep,
                                         sheep_send),
                      "overflowed sheep balance")
    if wheat_received:
        releaseAssert(_add_asset_balance(ltx, header, account_b, wheat,
                                         -wheat_received),
                      "overflowed wheat balance")

    if ex.wheat_stays:
        offer.amount -= wheat_received
        _adjust_offer_in_place(ltx, header, offer_le)
    else:
        offer.amount = 0

    res = CrossOfferResult.eOfferTaken if offer.amount == 0 \
        else CrossOfferResult.eOfferPartial
    if res == CrossOfferResult.eOfferTaken:
        owner_le = ltx.load(LedgerKey.account(account_b))
        remove_entry_with_possible_sponsorship(ltx, header, offer_le,
                                               owner_le)
        ltx.erase(LedgerKey.offer(account_b, offer_id))
    else:
        ok = liab.acquire_liabilities(ltx, header, offer_le)
        releaseAssert(ok, "could not re-acquire offer liabilities")

    offer_trail.append(ClaimAtom(
        ClaimAtomType.CLAIM_ATOM_TYPE_ORDER_BOOK,
        ClaimOfferAtom(sellerID=account_b, offerID=offer_id,
                       assetSold=wheat, amountSold=wheat_received,
                       assetBought=sheep, amountBought=sheep_send)))
    return res, wheat_received, sheep_send, ex.wheat_stays


FilterFn = Callable[[LedgerEntry], OfferFilterResult]


def convert_with_offers(ltx_outer, sheep, max_sheep_send: int, wheat,
                        max_wheat_receive: int, round_type: RoundingType,
                        offer_filter: Optional[FilterFn],
                        offer_trail: List[ClaimAtom],
                        max_offers_to_cross: int
                        ) -> Tuple[ConvertResult, int, int]:
    """Walk the book best-offer-first (reference: convertWithOffers).
    Returns (result, sheep_send, wheat_received)."""
    releaseAssert(not offer_trail, "offerTrail must start empty")
    sheep_send = 0
    wheat_received = 0
    need_more = max_wheat_receive > 0 and max_sheep_send > 0
    # zero-budget fast-fail only from protocol 18 (the reference's
    # convertWithOffers pairs the check with V_18; earlier protocols walk
    # the book and report ePartial/filter results instead)
    if need_more and max_offers_to_cross <= 0 and \
            ltx_outer.get_header().ledgerVersion >= 18:
        return ConvertResult.eCrossedTooMany, 0, 0

    while need_more:
        with LedgerTxn(ltx_outer) as ltx:
            # resting offers SELL wheat and BUY sheep
            offer_le = ltx.load_best_offer(wheat, sheep)
            if offer_le is None:
                break
            if offer_filter:
                f = offer_filter(offer_le)
                if f == OfferFilterResult.eStopBadPrice:
                    return (ConvertResult.eFilterStopBadPrice, sheep_send,
                            wheat_received)
                if f == OfferFilterResult.eStopCrossSelf:
                    return (ConvertResult.eFilterStopCrossSelf, sheep_send,
                            wheat_received)
            if len(offer_trail) >= max_offers_to_cross:
                return (ConvertResult.eCrossedTooMany, sheep_send,
                        wheat_received)
            cor, num_wheat, num_sheep, wheat_stays = cross_offer_v10(
                ltx, offer_le, max_wheat_receive, max_sheep_send,
                round_type, offer_trail)
            need_more = not wheat_stays
            releaseAssert(0 <= num_sheep <= max_sheep_send,
                          "sheepSend out of range")
            releaseAssert(0 <= num_wheat <= max_wheat_receive,
                          "wheatReceived out of range")
            if cor == CrossOfferResult.eOfferCantConvert:
                return ConvertResult.ePartial, sheep_send, wheat_received
            ltx.commit()
        sheep_send += num_sheep
        max_sheep_send -= num_sheep
        wheat_received += num_wheat
        max_wheat_receive -= num_wheat
        need_more = need_more and max_wheat_receive > 0 and \
            max_sheep_send > 0
        if not need_more:
            return ConvertResult.eOK, sheep_send, wheat_received
        if cor == CrossOfferResult.eOfferPartial:
            return ConvertResult.ePartial, sheep_send, wheat_received
    # loop left: either the book ran out of offers, or there was nothing
    # to exchange in the first place
    if not need_more:
        return ConvertResult.eOK, sheep_send, wheat_received
    return ConvertResult.ePartial, sheep_send, wheat_received


# ------------------------------------------------------------ pool exchange --

def exchange_with_pool_amounts(reserves_to_pool: int, max_send_to_pool: int,
                               reserves_from_pool: int,
                               max_receive_from_pool: int, fee_bps: int,
                               round_type: RoundingType
                               ) -> Optional[Tuple[int, int]]:
    """Pure constant-product swap math (reference: exchangeWithPool int64
    overload). Returns (to_pool, from_pool) or None."""
    max_bps = 10_000
    releaseAssert(0 <= fee_bps < max_bps, "pool fee out of range")
    releaseAssert(reserves_to_pool > 0 and reserves_from_pool > 0,
                  "non-positive reserve")
    if round_type == RoundingType.PATH_PAYMENT_STRICT_SEND:
        releaseAssert(max_receive_from_pool == INT64_MAX,
                      "strict send with bounded receive")
        max_receive_from_pool = reserves_from_pool
        if max_send_to_pool > INT64_MAX - reserves_to_pool:
            return None
        to_pool = max_send_to_pool
        denom = max_bps * reserves_to_pool + (max_bps - fee_bps) * to_pool
        from_pool = ((max_bps - fee_bps) * reserves_from_pool * to_pool
                     ) // denom
        if from_pool > INT64_MAX:
            return None
        releaseAssert(0 <= from_pool <= max_receive_from_pool,
                      "pool payout out of range")
        if from_pool == 0:
            return None
        return to_pool, from_pool
    if round_type == RoundingType.PATH_PAYMENT_STRICT_RECEIVE:
        releaseAssert(max_send_to_pool == INT64_MAX,
                      "strict receive with bounded send")
        max_send_to_pool = INT64_MAX - reserves_to_pool
        if max_receive_from_pool >= reserves_from_pool:
            return None
        from_pool = max_receive_from_pool
        num = max_bps * reserves_to_pool * from_pool
        denom = (reserves_from_pool - from_pool) * (max_bps - fee_bps)
        to_pool = (num + denom - 1) // denom
        if to_pool > INT64_MAX:
            return None
        releaseAssert(to_pool >= 0, "toPool negative")
        if to_pool > max_send_to_pool:
            return None
        return to_pool, from_pool
    releaseAssert(False, "invalid rounding type for pool exchange")


def exchange_with_pool(ltx_outer, to_pool_asset, max_send_to_pool: int,
                       from_pool_asset, max_receive_from_pool: int,
                       round_type: RoundingType, max_offers_to_cross: int
                       ) -> Optional[Tuple[int, int]]:
    """Swap against the live pool entry; mutates reserves; returns
    (to_pool, from_pool) or None (reference: exchangeWithPool ltx
    overload). The protocol-18 gate and the voted
    DISABLE_LIQUIDITY_POOL_TRADING_FLAG live HERE, inside the shared
    primitive, so every caller inherits them (reference:
    OfferExchange isPoolTradingDisabled + the pre-V18 early-out)."""
    if round_type == RoundingType.NORMAL:
        return None
    if max_offers_to_cross <= 0:
        return None
    header = ltx_outer.get_header()
    if header.ledgerVersion < 18:
        return None
    if tx_utils.header_flags(header) & \
            LedgerHeaderFlags.DISABLE_LIQUIDITY_POOL_TRADING_FLAG:
        return None
    with LedgerTxn(ltx_outer) as ltx:
        pool_id = pool_id_for_assets(to_pool_asset, from_pool_asset)
        pool_le = ltx.load(LedgerKey.liquidity_pool(pool_id))
        if pool_le is None:
            return None
        cp = pool_le.data.value.body.value
        if cp.reserveA <= 0 or cp.reserveB <= 0:
            return None
        if to_pool_asset == cp.params.assetA and \
                from_pool_asset == cp.params.assetB:
            r = exchange_with_pool_amounts(
                cp.reserveA, max_send_to_pool, cp.reserveB,
                max_receive_from_pool, LIQUIDITY_POOL_FEE_V18, round_type)
            if r is None:
                return None
            to_pool, from_pool = r
            cp.reserveA += to_pool
            cp.reserveB -= from_pool
        elif from_pool_asset == cp.params.assetA and \
                to_pool_asset == cp.params.assetB:
            r = exchange_with_pool_amounts(
                cp.reserveB, max_send_to_pool, cp.reserveA,
                max_receive_from_pool, LIQUIDITY_POOL_FEE_V18, round_type)
            if r is None:
                return None
            to_pool, from_pool = r
            cp.reserveB += to_pool
            cp.reserveA -= from_pool
        else:
            releaseAssert(False, "pool does not match assets")
        releaseAssert(cp.reserveA >= 0 and cp.reserveB >= 0,
                      "negative pool reserve")
        ltx.commit()
        return to_pool, from_pool


def convert_with_offers_and_pools(
        ltx_outer, sheep, max_sheep_send: int, wheat,
        max_wheat_receive: int, round_type: RoundingType,
        offer_filter: Optional[FilterFn], offer_trail: List[ClaimAtom],
        max_offers_to_cross: int) -> Tuple[ConvertResult, int, int]:
    """Book vs pool, best taker price wins (reference:
    convertWithOffersAndPools + maybeConvertWithOffers)."""
    releaseAssert(not offer_trail, "offerTrail must start empty")

    # probe the pool without committing
    pool_quote: Optional[Tuple[int, int]] = None
    with LedgerTxn(ltx_outer) as probe:
        pool_quote = exchange_with_pool(
            probe, sheep, max_sheep_send, wheat, max_wheat_receive,
            round_type, max_offers_to_cross)
        # probe rolls back

    with LedgerTxn(ltx_outer) as book_ltx:
        trail: List[ClaimAtom] = []
        res, sheep_send, wheat_received = convert_with_offers(
            book_ltx, sheep, max_sheep_send, wheat, max_wheat_receive,
            round_type, offer_filter, trail, max_offers_to_cross)
        use_book = True
        if pool_quote is not None:
            if res != ConvertResult.eOK:
                use_book = False
            else:
                # book wins only on a strictly better price:
                # book.wR/book.sS > pool.fP/pool.tP
                use_book = (pool_quote[0] * wheat_received >
                            pool_quote[1] * sheep_send)
        if use_book:
            offer_trail.extend(trail)
            book_ltx.commit()
            return res, sheep_send, wheat_received

    # execute for real against the pool
    r = exchange_with_pool(ltx_outer, sheep, max_sheep_send, wheat,
                           max_wheat_receive, round_type,
                           max_offers_to_cross)
    releaseAssert(r is not None, "pool exchange vanished")
    to_pool, from_pool = r
    offer_trail.append(ClaimAtom(
        ClaimAtomType.CLAIM_ATOM_TYPE_LIQUIDITY_POOL,
        ClaimLiquidityAtom(
            liquidityPoolID=pool_id_for_assets(sheep, wheat),
            assetSold=wheat, amountSold=from_pool,
            assetBought=sheep, amountBought=to_pool)))
    return ConvertResult.eOK, to_pool, from_pool
