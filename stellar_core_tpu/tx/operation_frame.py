"""Operation frame base and registry.

Reference: transactions/OperationFrame.{h,cpp} — one frame per
OperationType, each with `doCheckValid` (stateless validity),
`doApply` (ledger mutation inside the op's own LedgerTxn), a threshold
level (LOW/MEDIUM/HIGH, OperationFrame.cpp:167-169 default MEDIUM), and
shared signature/account plumbing: the op's source (op override or tx
source), opNO_ACCOUNT when the source vanished, opBAD_AUTH when the
source account's signers don't reach the needed threshold.
"""

from __future__ import annotations

from enum import IntEnum
from typing import Callable, Dict, Optional, Type

from ..util.checks import releaseAssert
from ..xdr.ledger_entries import LedgerKey, ThresholdIndexes
from ..xdr.transaction import MuxedAccount, Operation, OperationType
from ..xdr.results import OperationResult, OperationResultCode, \
    _OperationResultTr
from ..xdr.types import AccountID
from . import tx_utils
from .signature_checker import SignatureChecker
from .sponsorship import ApplyContext


class ThresholdLevel(IntEnum):
    LOW = 0
    MEDIUM = 1
    HIGH = 2


_THRESHOLD_INDEX = {
    ThresholdLevel.LOW: ThresholdIndexes.THRESHOLD_LOW,
    ThresholdLevel.MEDIUM: ThresholdIndexes.THRESHOLD_MED,
    ThresholdLevel.HIGH: ThresholdIndexes.THRESHOLD_HIGH,
}

_REGISTRY: Dict[OperationType, Type["OperationFrame"]] = {}


def register_op(op_type: OperationType):
    def deco(cls):
        cls.OP_TYPE = op_type
        _REGISTRY[op_type] = cls
        return cls
    return deco


def make_operation_frame(op: Operation, tx_source: MuxedAccount,
                         op_index: int) -> "OperationFrame":
    cls = _REGISTRY.get(op.body.disc)
    releaseAssert(cls is not None,
                  f"no operation frame registered for {op.body.disc!r}")
    return cls(op, tx_source, op_index)


class OperationFrame:
    OP_TYPE: OperationType = None

    def __init__(self, op: Operation, tx_source: MuxedAccount,
                 op_index: int):
        self.op = op
        self.tx_source = tx_source
        self.op_index = op_index
        self.result: Optional[OperationResult] = None

    # ----------------------------------------------------------- identities --
    @property
    def source(self) -> MuxedAccount:
        return self.op.sourceAccount if self.op.sourceAccount is not None \
            else self.tx_source

    @property
    def source_id(self) -> AccountID:
        return self.source.account_id()

    @property
    def body(self):
        return self.op.body.value

    # -------------------------------------------------------------- results --
    def _inner_result_type(self):
        arm = _OperationResultTr.ARMS[self.OP_TYPE]
        return arm[1] if arm else None

    def set_inner_result(self, code: IntEnum, value=None) -> None:
        """result = opINNER/tr/<this op's result union>(code, value)."""
        rt = self._inner_result_type()
        if rt is None:
            inner = None
        elif value is None and rt.ARMS.get(code, None) is None:
            inner = rt(code)  # void arm
        else:
            inner = rt(code, value)
        self.result = OperationResult(
            OperationResultCode.opINNER,
            _OperationResultTr(self.OP_TYPE, inner))

    def set_outer_result(self, code: OperationResultCode) -> None:
        releaseAssert(code != OperationResultCode.opINNER,
                      "opINNER is set via set_inner_result")
        self.result = OperationResult(code)

    def inner_code(self) -> Optional[int]:
        if self.result is not None and \
                self.result.disc == OperationResultCode.opINNER:
            return self.result.value.value.disc
        return None

    # ------------------------------------------------------------ overrides --
    def threshold_level(self) -> ThresholdLevel:
        return ThresholdLevel.MEDIUM

    def is_op_supported(self, header, ledger_version: int) -> bool:
        """Version/flag gate (reference: OperationFrame::isOpSupported —
        overloads take the LedgerHeader so voted header flags can
        disable ops, e.g. the liquidity-pool bits)."""
        return True

    def do_check_valid(self, header, ledger_version: int) -> bool:
        """Stateless validity; set a result and return False on failure."""
        raise NotImplementedError

    def do_apply(self, ltx, header, ctx: ApplyContext) -> bool:
        raise NotImplementedError

    # ------------------------------------------------------------- plumbing --
    def check_signature(self, checker: SignatureChecker, ltx,
                        forapply: bool) -> bool:
        """Reference: OperationFrame::checkSignature — the op source's
        signers must reach the op threshold; missing-account fallback only
        for validation of ops with an explicit source override."""
        source_le = ltx.load_without_record(LedgerKey.account(self.source_id))
        if source_le is not None:
            acc = source_le.data.value
            needed = acc.thresholds[_THRESHOLD_INDEX[self.threshold_level()]]
            signers = tx_utils.get_signers_with_master(acc)
            if not checker.check_signature(signers, needed):
                self.set_outer_result(OperationResultCode.opBAD_AUTH)
                return False
        else:
            if forapply or self.op.sourceAccount is None:
                self.set_outer_result(OperationResultCode.opNO_ACCOUNT)
                return False
            # validation-time with missing account: master key at weight 1
            # (reference: TransactionFrame::checkSignatureNoAccount)
            from ..xdr.types import SignerKey, SignerKeyType
            signers = [(SignerKey(SignerKeyType.SIGNER_KEY_TYPE_ED25519,
                                  self.source_id.value), 1)]
            if not checker.check_signature(signers, 0):
                self.set_outer_result(OperationResultCode.opBAD_AUTH)
                return False
        return True

    def check_valid(self, checker: SignatureChecker, ltx,
                    forapply: bool) -> bool:
        """Reference: OperationFrame::checkValid — version gate, then
        signature check at validation time (apply-time signatures were
        settled in processSignatures, only existence is re-checked), then
        doCheckValid. Never mutates the caller's ltx."""
        header = ltx.get_header()
        ledger_version = header.ledgerVersion
        if not self.is_op_supported(header, ledger_version):
            self.set_outer_result(OperationResultCode.opNOT_SUPPORTED)
            return False
        if not forapply:
            if not self.check_signature(checker, ltx, False):
                return False
        else:
            if ltx.load_without_record(
                    LedgerKey.account(self.source_id)) is None:
                self.set_outer_result(OperationResultCode.opNO_ACCOUNT)
                return False
        return self.do_check_valid(header, ledger_version)

    def apply(self, checker: SignatureChecker, ltx,
              ctx: ApplyContext) -> bool:
        """Reference: OperationFrame::apply = checkValid(apply-mode) +
        doApply (caller wraps in a per-op LedgerTxn)."""
        if not self.check_valid(checker, ltx, True):
            return False
        ctx.op_index = self.op_index
        return self.do_apply(ltx, ltx.load_header(), ctx)

    # ------------------------------------------------------------- helpers --
    def load_source_account(self, ltx):
        return ltx.load(LedgerKey.account(self.source_id))
