"""Liquidity-pool lifecycle tied to pool-share trustlines.

Reference: transactions/ChangeTrustOpFrame.cpp
(tryManagePoolOnNewTrustLine / managePoolOnDeletedTrustLine /
tryIncrementPoolUseCount) and OfferExchange.cpp getPoolID:1371-1378 —
the pool LedgerEntry exists exactly while >=1 pool-share trustline
references it; each constituent credit-asset trustline tracks how many
pools use it via liquidityPoolUseCount (blocks deletion).
"""

from __future__ import annotations

from typing import Optional

from ..crypto.sha import sha256
from ..util.checks import releaseAssert
from ..xdr.ledger_entries import (AssetType, LedgerEntry, LedgerEntryType,
                                  LedgerKey, LiquidityPoolEntry,
                                  LiquidityPoolType, TrustLineAsset,
                                  TrustLineEntry, TrustLineEntryV1,
                                  TrustLineEntryExtensionV2, Liabilities,
                                  _LedgerEntryData)
from ..xdr.results import ChangeTrustResultCode
from . import tx_utils

LIQUIDITY_POOL_FEE_V18 = 30
INT32_MAX = 2**31 - 1
INT64_MAX = 2**63 - 1


def pool_id_for_params(cp_params) -> bytes:
    """PoolID = SHA256(xdr(LiquidityPoolParameters)) (reference:
    getPoolID, OfferExchange.cpp:1371; xdrSha256 of the params union)."""
    from ..xdr.transaction import LiquidityPoolParameters
    lpp = LiquidityPoolParameters(
        LiquidityPoolType.LIQUIDITY_POOL_CONSTANT_PRODUCT, cp_params)
    return sha256(lpp.to_bytes())


def pool_id_for_assets(asset_a, asset_b,
                       fee: int = LIQUIDITY_POOL_FEE_V18) -> bytes:
    from ..xdr.ledger_entries import LiquidityPoolConstantProductParameters
    a, b = sorted([asset_a, asset_b], key=lambda x: x.to_bytes())
    return pool_id_for_params(LiquidityPoolConstantProductParameters(
        assetA=a, assetB=b, fee=fee))


def pool_params_valid(lpp) -> bool:
    """assetA < assetB strictly, both valid, canonical fee (reference:
    isAssetValid for ASSET_TYPE_POOL_SHARE in TransactionUtils)."""
    if lpp.disc != LiquidityPoolType.LIQUIDITY_POOL_CONSTANT_PRODUCT:
        return False
    cp = lpp.value
    if cp.fee != LIQUIDITY_POOL_FEE_V18:
        return False
    for a in (cp.assetA, cp.assetB):
        if not tx_utils.is_asset_valid(a):
            return False
    return cp.assetA.to_bytes() < cp.assetB.to_bytes()


def prepare_trustline_ext_v2(tl: TrustLineEntry) -> TrustLineEntryExtensionV2:
    if tl.ext.disc == 0:
        tl.ext = type(tl.ext)(1, TrustLineEntryV1(
            liabilities=Liabilities(buying=0, selling=0)))
    v1 = tl.ext.value
    if v1.ext.disc == 0:
        v1.ext = type(v1.ext)(2, TrustLineEntryExtensionV2(
            liquidityPoolUseCount=0))
    return v1.ext.value


def load_pool(ltx, pool_id: bytes) -> Optional[LedgerEntry]:
    return ltx.load(LedgerKey.liquidity_pool(pool_id))


def _try_increment_use_count(op_frame, ltx, asset) -> bool:
    src = op_frame.source_id
    if asset.disc == AssetType.ASSET_TYPE_NATIVE:
        return True
    if tx_utils.asset_issuer(asset).to_bytes() == src.to_bytes():
        return True
    tl_le = tx_utils.load_trustline(ltx, src, asset)
    if tl_le is None:
        op_frame.set_inner_result(
            ChangeTrustResultCode.CHANGE_TRUST_TRUST_LINE_MISSING)
        return False
    tl = tl_le.data.value
    if not tx_utils.is_authorized_to_maintain_liabilities(tl):
        op_frame.set_inner_result(
            ChangeTrustResultCode.CHANGE_TRUST_NOT_AUTH_MAINTAIN_LIABILITIES)
        return False
    v2 = prepare_trustline_ext_v2(tl)
    releaseAssert(v2.liquidityPoolUseCount < INT32_MAX,
                  "liquidityPoolUseCount overflow")
    v2.liquidityPoolUseCount += 1
    return True


def _decrement_use_count(ltx, asset, account_id) -> None:
    if asset.disc == AssetType.ASSET_TYPE_NATIVE:
        return
    if tx_utils.asset_issuer(asset).to_bytes() == account_id.to_bytes():
        return
    tl_le = tx_utils.load_trustline(ltx, account_id, asset)
    if tl_le is None:
        return
    tl = tl_le.data.value
    if tl.ext.disc == 1 and tl.ext.value.ext.disc == 2:
        v2 = tl.ext.value.ext.value
        v2.liquidityPoolUseCount = max(0, v2.liquidityPoolUseCount - 1)


def try_manage_pool_on_new_trustline(op_frame, ltx, header, line,
                                     tla: TrustLineAsset) -> bool:
    """Create or ref-count the pool entry for a new pool-share trustline;
    sets the op result and returns False on failure."""
    if tla.disc != AssetType.ASSET_TYPE_POOL_SHARE:
        return True
    cp = line.value.value  # LiquidityPoolParameters -> constantProduct
    if not _try_increment_use_count(op_frame, ltx, cp.assetA):
        return False
    if not _try_increment_use_count(op_frame, ltx, cp.assetB):
        return False
    pool_le = load_pool(ltx, tla.value)
    if pool_le is not None:
        body = pool_le.data.value.body.value
        releaseAssert(body.poolSharesTrustLineCount < INT64_MAX,
                      "poolSharesTrustLineCount overflow")
        body.poolSharesTrustLineCount += 1
    else:
        from ..xdr.ledger_entries import (_LiquidityPoolBody,
                                          _LPConstantProduct)
        lp = LiquidityPoolEntry(
            liquidityPoolID=tla.value,
            body=_LiquidityPoolBody(
                LiquidityPoolType.LIQUIDITY_POOL_CONSTANT_PRODUCT,
                _LPConstantProduct(
                    params=cp, reserveA=0, reserveB=0, totalPoolShares=0,
                    poolSharesTrustLineCount=1)))
        ltx.create(LedgerEntry(
            lastModifiedLedgerSeq=header.ledgerSeq,
            data=_LedgerEntryData(LedgerEntryType.LIQUIDITY_POOL, lp)))
    return True


def manage_pool_on_deleted_trustline(ltx, tla: TrustLineAsset,
                                     cp_params=None, account_id=None) -> None:
    """Deref the pool when a pool-share trustline is deleted; erases the
    pool entry when the last trustline goes."""
    if tla.disc != AssetType.ASSET_TYPE_POOL_SHARE:
        return
    pool_le = load_pool(ltx, tla.value)
    releaseAssert(pool_le is not None, "liquidity pool is missing")
    body = pool_le.data.value.body.value
    if cp_params is None:
        cp_params = body.params
    if account_id is not None:
        _decrement_use_count(ltx, cp_params.assetA, account_id)
        _decrement_use_count(ltx, cp_params.assetB, account_id)
    body.poolSharesTrustLineCount -= 1
    if body.poolSharesTrustLineCount == 0:
        ltx.erase(LedgerKey.liquidity_pool(tla.value))
