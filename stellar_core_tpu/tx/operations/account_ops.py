"""Account lifecycle operations: CreateAccount, AccountMerge, SetOptions,
BumpSequence.

Reference: transactions/CreateAccountOpFrame.cpp, MergeOpFrame.cpp,
SetOptionsOpFrame.cpp, BumpSequenceOpFrame.cpp. Behavior targets the
current protocol (>= 19); legacy-version branches the reference keeps for
replay of ancient ledgers are documented where omitted.
"""

from __future__ import annotations

from ...xdr.ledger_entries import (AccountFlags, LedgerEntry, LedgerKey,
                                   LedgerEntryType, Signer, ThresholdIndexes)
from ...xdr.transaction import OperationType
from ...xdr.results import (
    AccountMergeResult, AccountMergeResultCode, BumpSequenceResult,
    BumpSequenceResultCode, CreateAccountResult, CreateAccountResultCode,
    SetOptionsResult, SetOptionsResultCode,
)
from ...xdr.types import SignerKey, SignerKeyType
from .. import tx_utils
from ..operation_frame import (OperationFrame, ThresholdLevel, register_op)
from ..sponsorship import (
    ApplyContext, SponsorshipResult, account_seq_ledger, account_seq_time,
    create_entry_with_possible_sponsorship,
    create_signer_with_possible_sponsorship, ensure_account_ext_v2,
    ensure_account_ext_v3, num_sponsoring, remove_signer_sponsorship,
)

MAX_SIGNERS = 20
ALL_ACCOUNT_FLAGS = (AccountFlags.AUTH_REQUIRED_FLAG
                     | AccountFlags.AUTH_REVOCABLE_FLAG
                     | AccountFlags.AUTH_IMMUTABLE_FLAG
                     | AccountFlags.AUTH_CLAWBACK_ENABLED_FLAG)


@register_op(OperationType.CREATE_ACCOUNT)
class CreateAccountOpFrame(OperationFrame):
    """reference: transactions/CreateAccountOpFrame.cpp"""

    def do_check_valid(self, header, ledger_version: int) -> bool:
        b = self.body
        if b.startingBalance < 0:
            self.set_inner_result(CreateAccountResultCode.
                                  CREATE_ACCOUNT_MALFORMED)
            return False
        # startingBalance == 0 allowed from protocol 14 (sponsored creation)
        if b.startingBalance == 0 and ledger_version < 14:
            self.set_inner_result(CreateAccountResultCode.
                                  CREATE_ACCOUNT_MALFORMED)
            return False
        if b.destination.to_bytes() == self.source_id.to_bytes():
            self.set_inner_result(CreateAccountResultCode.
                                  CREATE_ACCOUNT_MALFORMED)
            return False
        return True

    def do_apply(self, ltx, header, ctx: ApplyContext) -> bool:
        b = self.body
        if ltx.entry_exists(LedgerKey.account(b.destination)):
            self.set_inner_result(CreateAccountResultCode.
                                  CREATE_ACCOUNT_ALREADY_EXIST)
            return False
        source_le = self.load_source_account(ltx)
        source = source_le.data.value
        if tx_utils.available_balance(header, source) < b.startingBalance:
            self.set_inner_result(CreateAccountResultCode.
                                  CREATE_ACCOUNT_UNDERFUNDED)
            return False

        new_le = tx_utils.make_account_ledger_entry(
            b.destination, b.startingBalance,
            tx_utils.starting_sequence_number(header.ledgerSeq))
        new_le.lastModifiedLedgerSeq = header.ledgerSeq

        sres = create_entry_with_possible_sponsorship(
            ltx, header, new_le, source_le, ctx)
        if sres != SponsorshipResult.SUCCESS:
            self.set_inner_result(CreateAccountResultCode.
                                  CREATE_ACCOUNT_LOW_RESERVE)
            return False
        # unsponsored accounts must fund their own 2-reserve minimum
        from ..sponsorship import is_sponsored
        if not is_sponsored(new_le) and \
                b.startingBalance < 2 * header.baseReserve:
            self.set_inner_result(CreateAccountResultCode.
                                  CREATE_ACCOUNT_LOW_RESERVE)
            return False
        ok = tx_utils.add_balance_account(header, source, -b.startingBalance)
        if not ok:
            self.set_inner_result(CreateAccountResultCode.
                                  CREATE_ACCOUNT_UNDERFUNDED)
            return False
        ltx.create(new_le)
        self.set_inner_result(CreateAccountResultCode.CREATE_ACCOUNT_SUCCESS)
        return True


@register_op(OperationType.ACCOUNT_MERGE)
class MergeOpFrame(OperationFrame):
    """reference: transactions/MergeOpFrame.cpp (threshold HIGH :30-32)"""

    def threshold_level(self) -> ThresholdLevel:
        return ThresholdLevel.HIGH

    def do_check_valid(self, header, ledger_version: int) -> bool:
        if self.body.account_id().to_bytes() == self.source_id.to_bytes():
            self.set_inner_result(AccountMergeResultCode.
                                  ACCOUNT_MERGE_MALFORMED)
            return False
        return True

    def do_apply(self, ltx, header, ctx: ApplyContext) -> bool:
        dest_id = self.body.account_id()
        dest_le = ltx.load(LedgerKey.account(dest_id))
        if dest_le is None:
            self.set_inner_result(AccountMergeResultCode.
                                  ACCOUNT_MERGE_NO_ACCOUNT)
            return False
        source_le = self.load_source_account(ltx)
        source = source_le.data.value

        if source.flags & AccountFlags.AUTH_IMMUTABLE_FLAG:
            self.set_inner_result(AccountMergeResultCode.
                                  ACCOUNT_MERGE_IMMUTABLE_SET)
            return False
        if source.numSubEntries != 0:
            self.set_inner_result(AccountMergeResultCode.
                                  ACCOUNT_MERGE_HAS_SUB_ENTRIES)
            return False
        if num_sponsoring(source) != 0:
            self.set_inner_result(AccountMergeResultCode.
                                  ACCOUNT_MERGE_IS_SPONSOR)
            return False
        # seqnum must not be reusable after re-creation (reference:
        # MergeOpFrame::doApply, protocol >= 10: maxSeq =
        # getStartingSequenceNumber(header) = ledgerSeq << 32)
        max_seq = tx_utils.starting_sequence_number(header.ledgerSeq)
        if source.seqNum >= max_seq:
            self.set_inner_result(AccountMergeResultCode.
                                  ACCOUNT_MERGE_SEQNUM_TOO_FAR)
            return False

        balance = source.balance
        dest = dest_le.data.value
        if not tx_utils.add_balance_account(header, dest, balance):
            self.set_inner_result(AccountMergeResultCode.
                                  ACCOUNT_MERGE_DEST_FULL)
            return False
        # release sponsorships on the account's signers before the account
        # itself (reference: MergeOpFrame removeSignersWithSponsorship)
        for i in range(len(source.signers) - 1, -1, -1):
            remove_signer_sponsorship(ltx, source_le, i)
        from ..sponsorship import remove_entry_with_possible_sponsorship
        remove_entry_with_possible_sponsorship(ltx, header, source_le, None)
        ltx.erase(LedgerKey.account(self.source_id))
        self.set_inner_result(AccountMergeResultCode.ACCOUNT_MERGE_SUCCESS,
                              balance)
        return True


@register_op(OperationType.SET_OPTIONS)
class SetOptionsOpFrame(OperationFrame):
    """reference: transactions/SetOptionsOpFrame.cpp (threshold HIGH when
    touching signers/weights/thresholds :33-42)"""

    def threshold_level(self) -> ThresholdLevel:
        b = self.body
        if (b.masterWeight is not None or b.lowThreshold is not None
                or b.medThreshold is not None or b.highThreshold is not None
                or b.signer is not None):
            return ThresholdLevel.HIGH
        return ThresholdLevel.MEDIUM

    def do_check_valid(self, header, ledger_version: int) -> bool:
        b = self.body
        set_f = b.setFlags or 0
        clear_f = b.clearFlags or 0
        if set_f & clear_f:
            self.set_inner_result(SetOptionsResultCode.SET_OPTIONS_BAD_FLAGS)
            return False
        allowed = ALL_ACCOUNT_FLAGS if ledger_version >= 17 else (
            AccountFlags.AUTH_REQUIRED_FLAG | AccountFlags.AUTH_REVOCABLE_FLAG
            | AccountFlags.AUTH_IMMUTABLE_FLAG)
        if (set_f | clear_f) & ~allowed:
            self.set_inner_result(SetOptionsResultCode.
                                  SET_OPTIONS_UNKNOWN_FLAG)
            return False
        for v in (b.masterWeight, b.lowThreshold, b.medThreshold,
                  b.highThreshold):
            if v is not None and v > 255:
                self.set_inner_result(SetOptionsResultCode.
                                      SET_OPTIONS_THRESHOLD_OUT_OF_RANGE)
                return False
        if b.signer is not None:
            sk: SignerKey = b.signer.key
            if sk.disc == SignerKeyType.SIGNER_KEY_TYPE_ED25519 and \
                    sk.value == self.source_id.value:
                self.set_inner_result(SetOptionsResultCode.
                                      SET_OPTIONS_BAD_SIGNER)
                return False
            if sk.disc == SignerKeyType.SIGNER_KEY_TYPE_ED25519_SIGNED_PAYLOAD \
                    and len(sk.value.payload) == 0:
                self.set_inner_result(SetOptionsResultCode.
                                      SET_OPTIONS_BAD_SIGNER)
                return False
            if ledger_version >= 10 and b.signer.weight > 255:
                self.set_inner_result(SetOptionsResultCode.
                                      SET_OPTIONS_BAD_SIGNER)
                return False
        if b.homeDomain is not None and not _valid_string32(b.homeDomain):
            self.set_inner_result(SetOptionsResultCode.
                                  SET_OPTIONS_INVALID_HOME_DOMAIN)
            return False
        return True

    def do_apply(self, ltx, header, ctx: ApplyContext) -> bool:
        b = self.body
        source_le = self.load_source_account(ltx)
        acc = source_le.data.value

        if b.inflationDest is not None:
            if not ltx.entry_exists(LedgerKey.account(b.inflationDest)):
                self.set_inner_result(SetOptionsResultCode.
                                      SET_OPTIONS_INVALID_INFLATION)
                return False
            acc.inflationDest = b.inflationDest

        # reference SetOptionsOpFrame: all auth flags (REQUIRED, REVOCABLE,
        # IMMUTABLE) are frozen once AUTH_IMMUTABLE is set
        all_auth = (AccountFlags.AUTH_REQUIRED_FLAG
                    | AccountFlags.AUTH_REVOCABLE_FLAG
                    | AccountFlags.AUTH_IMMUTABLE_FLAG)
        if b.clearFlags:
            if (b.clearFlags & all_auth) and \
                    (acc.flags & AccountFlags.AUTH_IMMUTABLE_FLAG):
                self.set_inner_result(SetOptionsResultCode.
                                      SET_OPTIONS_CANT_CHANGE)
                return False
            acc.flags &= ~b.clearFlags
        if b.setFlags:
            if (b.setFlags & all_auth) and \
                    (acc.flags & AccountFlags.AUTH_IMMUTABLE_FLAG):
                self.set_inner_result(SetOptionsResultCode.
                                      SET_OPTIONS_CANT_CHANGE)
                return False
            acc.flags |= b.setFlags
        # AUTH_REVOCABLE is required while AUTH_CLAWBACK_ENABLED is set
        if (acc.flags & AccountFlags.AUTH_CLAWBACK_ENABLED_FLAG) and \
                not (acc.flags & AccountFlags.AUTH_REVOCABLE_FLAG):
            self.set_inner_result(
                SetOptionsResultCode.SET_OPTIONS_AUTH_REVOCABLE_REQUIRED)
            return False

        th = bytearray(acc.thresholds)
        if b.masterWeight is not None:
            th[ThresholdIndexes.THRESHOLD_MASTER_WEIGHT] = b.masterWeight
        if b.lowThreshold is not None:
            th[ThresholdIndexes.THRESHOLD_LOW] = b.lowThreshold
        if b.medThreshold is not None:
            th[ThresholdIndexes.THRESHOLD_MED] = b.medThreshold
        if b.highThreshold is not None:
            th[ThresholdIndexes.THRESHOLD_HIGH] = b.highThreshold
        acc.thresholds = bytes(th)

        if b.homeDomain is not None:
            acc.homeDomain = b.homeDomain

        if b.signer is not None:
            if not self._apply_signer(ltx, header, source_le, b.signer, ctx):
                return False

        self.set_inner_result(SetOptionsResultCode.SET_OPTIONS_SUCCESS)
        return True

    def _apply_signer(self, ltx, header, source_le: LedgerEntry,
                      signer: Signer, ctx: ApplyContext) -> bool:
        acc = source_le.data.value
        weight = min(signer.weight, 255)
        idx = next((i for i, s in enumerate(acc.signers)
                    if s.key == signer.key), None)
        if weight == 0:
            if idx is None:
                self.set_inner_result(SetOptionsResultCode.
                                      SET_OPTIONS_BAD_SIGNER)
                return False
            remove_signer_sponsorship(ltx, source_le, idx)
            acc.signers.pop(idx)
            if acc.ext.disc == 1 and acc.ext.value.ext.disc == 2:
                ids = acc.ext.value.ext.value.signerSponsoringIDs
                if idx < len(ids):
                    ids.pop(idx)
            return True
        if idx is not None:
            acc.signers[idx].weight = weight
            return True
        if len(acc.signers) >= MAX_SIGNERS:
            self.set_inner_result(SetOptionsResultCode.
                                  SET_OPTIONS_TOO_MANY_SIGNERS)
            return False
        sres = create_signer_with_possible_sponsorship(
            ltx, header, source_le, ctx)
        if sres == SponsorshipResult.LOW_RESERVE:
            self.set_inner_result(SetOptionsResultCode.
                                  SET_OPTIONS_LOW_RESERVE)
            return False
        if sres != SponsorshipResult.SUCCESS:
            self.set_inner_result(SetOptionsResultCode.
                                  SET_OPTIONS_TOO_MANY_SIGNERS)
            return False
        # signers stay sorted by key bytes (reference: account entry
        # invariant enforced in SetOptionsOpFrame)
        new_signer = Signer(key=signer.key, weight=weight)
        sponsor = ctx.sponsor_for(acc.accountID) if ctx else None
        insert_at = len(acc.signers)
        for i, s in enumerate(acc.signers):
            if signer.key.to_bytes() < s.key.to_bytes():
                insert_at = i
                break
        acc.signers.insert(insert_at, new_signer)
        if sponsor is not None or (
                acc.ext.disc == 1 and acc.ext.value.ext.disc == 2):
            v2 = ensure_account_ext_v2(acc)
            # ensure_account_ext_v2 appended a slot; place it correctly
            v2.signerSponsoringIDs.pop()
            v2.signerSponsoringIDs.insert(insert_at, sponsor)
        return True


@register_op(OperationType.BUMP_SEQUENCE)
class BumpSequenceOpFrame(OperationFrame):
    """reference: transactions/BumpSequenceOpFrame.cpp (LOW threshold,
    supported from protocol 10)"""

    def threshold_level(self) -> ThresholdLevel:
        return ThresholdLevel.LOW

    def is_op_supported(self, header, ledger_version: int) -> bool:
        return ledger_version >= 10

    def do_check_valid(self, header, ledger_version: int) -> bool:
        if self.body.bumpTo < 0:
            self.set_inner_result(BumpSequenceResultCode.
                                  BUMP_SEQUENCE_BAD_SEQ)
            return False
        return True

    def do_apply(self, ltx, header, ctx: ApplyContext) -> bool:
        source_le = self.load_source_account(ltx)
        acc = source_le.data.value
        if self.body.bumpTo > acc.seqNum:
            acc.seqNum = self.body.bumpTo
            if header.ledgerVersion >= 19:
                v3 = ensure_account_ext_v3(acc)
                v3.seqLedger = header.ledgerSeq
                v3.seqTime = header.scpValue.closeTime
        self.set_inner_result(BumpSequenceResultCode.BUMP_SEQUENCE_SUCCESS)
        return True


def _valid_string32(s: bytes) -> bool:
    return len(s) <= 32 and tx_utils.is_string_valid(s)
