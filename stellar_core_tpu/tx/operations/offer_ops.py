"""Order-book operations: ManageSellOffer, ManageBuyOffer,
CreatePassiveSellOffer.

Reference: transactions/ManageOfferOpFrameBase.cpp (apply at :214 —
release old liabilities / pre-establish reserve, cross the book through
convertWithOffersAndPools with passive/self filters, settle balances,
adjust + recreate the residual offer, acquire liabilities),
ManageSellOfferOpFrame.cpp, ManageBuyOfferOpFrame.cpp (buy amount and
inverted price mapped onto the sell machinery),
CreatePassiveSellOfferOpFrame.cpp.
"""

from __future__ import annotations

from typing import List, Optional

from ...xdr.ledger_entries import (Asset, AssetType, LedgerEntry,
                                   LedgerEntryType, LedgerKey, OfferEntry,
                                   OfferEntryFlags, Price,
                                   _LedgerEntryData, _LedgerEntryExt)
from ...xdr.results import (ClaimAtom, ManageBuyOfferResultCode,
                            ManageOfferEffect, ManageOfferSuccessResult,
                            ManageSellOfferResultCode, OperationResultCode,
                            _ManageOfferEffectUnion)
from ...xdr.transaction import OperationType
from ...xdr.types import ExtensionPoint
from ...ledger.ledger_txn import LedgerTxn
from ..operation_frame import OperationFrame, register_op
from ..offer_exchange import (ConvertResult, OfferFilterResult,
                              can_buy_at_most, can_sell_at_most,
                              convert_with_offers)
from ..offer_math import (Rounding, RoundingType, adjust_offer_amount,
                          big_divide, exchange_v10_without_price_error_thresholds)
from .. import liabilities as liab
from .. import tx_utils
from ..sponsorship import (SponsorshipResult,
                           create_entry_with_possible_sponsorship,
                           remove_entry_with_possible_sponsorship)

INT64_MAX = 2**63 - 1
# reference: getMaxOffersToCross / MAX_OFFERS_TO_CROSS
MAX_OFFERS_TO_CROSS = 1000


def _price_cmp(a: Price, b: Price) -> int:
    """a.n/a.d vs b.n/b.d in exact integer math."""
    lhs = a.n * b.d
    rhs = b.n * a.d
    return (lhs > rhs) - (lhs < rhs)


class ManageOfferOpFrameBase(OperationFrame):
    """Shared apply machinery; subclasses define the (sheep, wheat,
    amount, price, offerID, passive) view and result codes."""

    RC = ManageSellOfferResultCode
    PREFIX = "MANAGE_SELL_OFFER"

    # ---- subclass view ----
    def sheep(self) -> Asset:
        return self.body.selling

    def wheat(self) -> Asset:
        return self.body.buying

    def offer_id(self) -> int:
        return self.body.offerID

    def sell_price(self) -> Price:
        return self.body.price

    def is_delete(self) -> bool:
        return self.body.amount == 0

    def set_passive_on_create(self) -> bool:
        return False

    def apply_operation_specific_limits(self, sheep_send_limit: int,
                                        sheep_sent: int,
                                        wheat_receive_limit: int,
                                        wheat_received: int) -> tuple:
        limit = min(sheep_send_limit, self.body.amount - sheep_sent)
        return limit, wheat_receive_limit


    # ---- result helpers ----
    def _rc(self, name: str):
        return getattr(self.RC, f"{self.PREFIX}_{name}")

    def _fail(self, name: str) -> bool:
        self.set_inner_result(self._rc(name))
        return False

    def _success(self) -> ManageOfferSuccessResult:
        self.set_inner_result(self._rc("SUCCESS"),
                              ManageOfferSuccessResult(
                                  offersClaimed=[],
                                  offer=_ManageOfferEffectUnion(
                                      ManageOfferEffect
                                      .MANAGE_OFFER_DELETED)))
        return self.result.value.value.value

    # ---- validity ----
    def do_check_valid(self, header, ledger_version: int) -> bool:
        sheep, wheat = self.sheep(), self.wheat()
        price = self.sell_price()
        if not tx_utils.is_asset_valid(sheep) or \
                not tx_utils.is_asset_valid(wheat):
            return self._fail("MALFORMED")
        if sheep.to_bytes() == wheat.to_bytes():
            return self._fail("MALFORMED")
        if self._raw_amount() < 0 or price.d <= 0 or price.n <= 0:
            return self._fail("MALFORMED")
        if self.offer_id() < 0:
            return self._fail("MALFORMED")
        if self.offer_id() == 0 and self.is_delete():
            return self._fail("NOT_FOUND")
        return True

    def _raw_amount(self) -> int:
        return self.body.amount

    # ---- apply ----
    def _check_offer_valid(self, ltx_outer, header) -> bool:
        """reference: checkOfferValid — rolled-back probe."""
        if self.is_delete():
            return True
        with LedgerTxn(ltx_outer) as ltx:
            if True:
                sheep, wheat = self.sheep(), self.wheat()
                if sheep.disc != AssetType.ASSET_TYPE_NATIVE and \
                        tx_utils.asset_issuer(sheep).to_bytes() != \
                        self.source_id.to_bytes():
                    tl = tx_utils.load_trustline(ltx, self.source_id, sheep)
                    if tl is None:
                        return self._fail("SELL_NO_TRUST")
                    if tl.data.value.balance == 0:
                        return self._fail("UNDERFUNDED")
                    if not tx_utils.is_authorized(tl.data.value):
                        return self._fail("SELL_NOT_AUTHORIZED")
                if wheat.disc != AssetType.ASSET_TYPE_NATIVE and \
                        tx_utils.asset_issuer(wheat).to_bytes() != \
                        self.source_id.to_bytes():
                    tl = tx_utils.load_trustline(ltx, self.source_id, wheat)
                    if tl is None:
                        return self._fail("BUY_NO_TRUST")
                    if not tx_utils.is_authorized(tl.data.value):
                        return self._fail("BUY_NOT_AUTHORIZED")
                return True  # with-exit rolls the probe back

    def _build_offer(self, amount: int, flags: int, ext) -> LedgerEntry:
        return LedgerEntry(
            lastModifiedLedgerSeq=0,
            data=_LedgerEntryData(LedgerEntryType.OFFER, OfferEntry(
                sellerID=self.source_id, offerID=self.offer_id(),
                selling=self.sheep(), buying=self.wheat(),
                amount=amount, price=self.sell_price(), flags=flags,
                ext=ExtensionPoint(0))),
            ext=ext)

    def _offer_buying_liabilities(self) -> int:
        ex = exchange_v10_without_price_error_thresholds(
            self.sell_price(), self._raw_amount(), INT64_MAX, INT64_MAX,
            INT64_MAX, RoundingType.NORMAL)
        return ex.num_sheep_send

    def _offer_selling_liabilities(self) -> int:
        ex = exchange_v10_without_price_error_thresholds(
            self.sell_price(), self._raw_amount(), INT64_MAX, INT64_MAX,
            INT64_MAX, RoundingType.NORMAL)
        return ex.num_wheat_received

    def do_apply(self, ltx_outer, header_outer, ctx) -> bool:
        with LedgerTxn(ltx_outer) as ltx:
            ok = self._do_apply_inner(ltx, ctx)
            if ok:
                ltx.commit()
            else:
                ltx.rollback()
            return ok

    def _do_apply_inner(self, ltx, ctx) -> bool:
        header = ltx.load_header()
        if not self._check_offer_valid(ltx, header):
            return False

        creating = False
        passive = False
        flags = 0
        extension = _LedgerEntryExt(0)

        if self.offer_id():
            offer_le = ltx.load(LedgerKey.offer(self.source_id,
                                                self.offer_id()))
            if offer_le is None:
                return self._fail("NOT_FOUND")
            liab.release_liabilities(ltx, header, offer_le)
            flags = offer_le.data.value.flags
            passive = bool(flags & OfferEntryFlags.PASSIVE_FLAG)
            extension = offer_le.ext
            ltx.erase(LedgerKey.offer(self.source_id, self.offer_id()))
        else:
            creating = True
            passive = self.set_passive_on_create()
            flags = OfferEntryFlags.PASSIVE_FLAG if passive else 0
            le = self._build_offer(0, 0, _LedgerEntryExt(0))
            source_le = ltx.load(LedgerKey.account(self.source_id))
            res = create_entry_with_possible_sponsorship(
                ltx, header, le, source_le, ctx)
            if res == SponsorshipResult.LOW_RESERVE:
                return self._fail("LOW_RESERVE")
            if res == SponsorshipResult.TOO_MANY_SUBENTRIES:
                self.set_outer_result(
                    OperationResultCode.opTOO_MANY_SUBENTRIES)
                return False
            if res == SponsorshipResult.TOO_MANY_SPONSORING:
                self.set_outer_result(
                    OperationResultCode.opTOO_MANY_SPONSORING)
                return False
            if res != SponsorshipResult.SUCCESS:
                raise RuntimeError("unexpected sponsorship result")
            extension = le.ext

        success = self._success()
        amount = 0
        sheep, wheat = self.sheep(), self.wheat()

        if not self.is_delete():
            # compute exchange caps on a rolled-back probe
            with LedgerTxn(ltx) as probe:
                ph = probe.load_header()
                max_wheat_receive = can_buy_at_most(
                    probe, ph, self.source_id, wheat)
                max_sheep_send = can_sell_at_most(
                    probe, ph, self.source_id, sheep)
                # liabilities must fit (reference: LINE_FULL /
                # UNDERFUNDED checks against available limit/balance)
                if max_wheat_receive < self._offer_buying_liabilities():
                    return self._fail("LINE_FULL")
                if max_sheep_send < self._offer_selling_liabilities():
                    return self._fail("UNDERFUNDED")
            if max_wheat_receive == 0:
                return self._fail("LINE_FULL")

            # reference: applyOperationSpecificLimits(maxSheepSend, 0,
            # maxWheatReceive, 0) — same virtual caps the crossing
            max_sheep_send, max_wheat_receive = \
                self.apply_operation_specific_limits(
                    max_sheep_send, 0, max_wheat_receive, 0)

            max_price = Price(n=self.sell_price().d,
                              d=self.sell_price().n)

            def offer_filter(entry):
                o = entry.data.value
                if o.offerID == self.offer_id():
                    raise RuntimeError("crossing own replaced offer")
                cmp = _price_cmp(o.price, max_price)
                if (passive and cmp >= 0) or cmp > 0:
                    return OfferFilterResult.eStopBadPrice
                if o.sellerID.to_bytes() == self.source_id.to_bytes():
                    return OfferFilterResult.eStopCrossSelf
                return OfferFilterResult.eKeep

            offer_trail: List[ClaimAtom] = []
            r, sheep_sent, wheat_received = convert_with_offers(
                ltx, sheep, max_sheep_send, wheat, max_wheat_receive,
                RoundingType.NORMAL, offer_filter, offer_trail,
                MAX_OFFERS_TO_CROSS)

            if r == ConvertResult.eFilterStopCrossSelf:
                return self._fail("CROSS_SELF")
            if r == ConvertResult.eCrossedTooMany:
                self.set_outer_result(
                    OperationResultCode.opEXCEEDED_WORK_LIMIT)
                return False
            sheep_stays = r in (ConvertResult.ePartial,
                                ConvertResult.eFilterStopBadPrice)

            success.offersClaimed = offer_trail
            header = ltx.load_header()
            if wheat_received > 0:
                from ..offer_exchange import _add_asset_balance
                if not _add_asset_balance(ltx, header, self.source_id,
                                          wheat, wheat_received):
                    raise RuntimeError("offer claimed over limit")
                if not _add_asset_balance(ltx, header, self.source_id,
                                          sheep, -sheep_sent):
                    raise RuntimeError("offer sold more than balance")

            if sheep_stays:
                sheep_send_limit = min(
                    can_sell_at_most(ltx, header, self.source_id, sheep),
                    INT64_MAX)
                wheat_receive_limit = can_buy_at_most(
                    ltx, header, self.source_id, wheat)
                sheep_send_limit, wheat_receive_limit = \
                    self.apply_operation_specific_limits(
                        sheep_send_limit, sheep_sent,
                        wheat_receive_limit, wheat_received)
                amount = adjust_offer_amount(
                    self.sell_price(), sheep_send_limit,
                    wheat_receive_limit)
            else:
                amount = 0

        header = ltx.load_header()
        if amount > 0:
            new_offer = self._build_offer(amount, flags, extension)
            if creating:
                header.idPool += 1
                new_offer.data.value.offerID = header.idPool
                success.offer = _ManageOfferEffectUnion(
                    ManageOfferEffect.MANAGE_OFFER_CREATED,
                    new_offer.data.value)
            else:
                success.offer = _ManageOfferEffectUnion(
                    ManageOfferEffect.MANAGE_OFFER_UPDATED,
                    new_offer.data.value)
            new_offer.lastModifiedLedgerSeq = header.ledgerSeq
            ltx.create(new_offer)
            offer_le = ltx.load(LedgerKey.offer(
                self.source_id, new_offer.data.value.offerID))
            if not liab.acquire_liabilities(ltx, header, offer_le):
                raise RuntimeError("could not acquire offer liabilities")
        else:
            success.offer = _ManageOfferEffectUnion(
                ManageOfferEffect.MANAGE_OFFER_DELETED)
            source_le = ltx.load(LedgerKey.account(self.source_id))
            le = self._build_offer(0, 0, extension)
            remove_entry_with_possible_sponsorship(
                ltx, header, le, source_le)
        return True


@register_op(OperationType.MANAGE_SELL_OFFER)
class ManageSellOfferOpFrame(ManageOfferOpFrameBase):
    RC = ManageSellOfferResultCode
    PREFIX = "MANAGE_SELL_OFFER"


@register_op(OperationType.CREATE_PASSIVE_SELL_OFFER)
class CreatePassiveSellOfferOpFrame(ManageOfferOpFrameBase):
    """reference: CreatePassiveSellOfferOpFrame — always creates, sets
    the passive flag; result shares the sell-offer shape."""
    RC = ManageSellOfferResultCode
    PREFIX = "MANAGE_SELL_OFFER"

    def offer_id(self) -> int:
        return 0

    def is_delete(self) -> bool:
        return False

    def set_passive_on_create(self) -> bool:
        return True

    def do_check_valid(self, header, ledger_version: int) -> bool:
        sheep, wheat = self.sheep(), self.wheat()
        price = self.sell_price()
        if not tx_utils.is_asset_valid(sheep) or \
                not tx_utils.is_asset_valid(wheat) or \
                sheep.to_bytes() == wheat.to_bytes() or \
                self.body.amount <= 0 or price.d <= 0 or price.n <= 0:
            return self._fail("MALFORMED")
        return True


@register_op(OperationType.MANAGE_BUY_OFFER)
class ManageBuyOfferOpFrame(ManageOfferOpFrameBase):
    """Buy semantics on the sell machinery: price inverted, the cap is
    on wheat received (reference: ManageBuyOfferOpFrame)."""
    RC = ManageBuyOfferResultCode
    PREFIX = "MANAGE_BUY_OFFER"

    def sheep(self) -> Asset:
        return self.body.selling

    def wheat(self) -> Asset:
        return self.body.buying

    def sell_price(self) -> Price:
        return Price(n=self.body.price.d, d=self.body.price.n)

    def is_delete(self) -> bool:
        return self.body.buyAmount == 0

    def _raw_amount(self) -> int:
        return self.body.buyAmount

    def _build_offer(self, amount: int, flags: int, ext) -> LedgerEntry:
        le = super()._build_offer(amount, flags, ext)
        # stored offers always carry the sell-side price of the
        # *original* buy price (reference: buildOffer in ManageBuyOffer)
        return le

    def _offer_buying_liabilities(self) -> int:
        # reference: exchangeV10WithoutPriceErrorThresholds(invPrice,
        # INT64_MAX, INT64_MAX, INT64_MAX, buyAmount, NORMAL)
        ex = exchange_v10_without_price_error_thresholds(
            self.sell_price(), INT64_MAX, INT64_MAX, INT64_MAX,
            self.body.buyAmount, RoundingType.NORMAL)
        return ex.num_sheep_send

    def _offer_selling_liabilities(self) -> int:
        ex = exchange_v10_without_price_error_thresholds(
            self.sell_price(), INT64_MAX, INT64_MAX, INT64_MAX,
            self.body.buyAmount, RoundingType.NORMAL)
        return ex.num_wheat_received

    def apply_operation_specific_limits(self, sheep_send_limit: int,
                                        sheep_sent: int,
                                        wheat_receive_limit: int,
                                        wheat_received: int) -> tuple:
        limit = min(wheat_receive_limit,
                    self.body.buyAmount - wheat_received)
        return sheep_send_limit, limit


    def do_check_valid(self, header, ledger_version: int) -> bool:
        sheep, wheat = self.sheep(), self.wheat()
        price = self.body.price
        if not tx_utils.is_asset_valid(sheep) or \
                not tx_utils.is_asset_valid(wheat) or \
                sheep.to_bytes() == wheat.to_bytes() or \
                self.body.buyAmount < 0 or price.d <= 0 or price.n <= 0 \
                or self.body.offerID < 0:
            return self._fail("MALFORMED")
        if self.body.offerID == 0 and self.is_delete():
            return self._fail("NOT_FOUND")
        return True
