"""Operation frames, one module per group; importing this package
registers every frame with the operation_frame registry (reference:
src/transactions/*OpFrame.cpp, dispatch at OperationFrame.cpp:31-120)."""

from . import account_ops          # noqa: F401
from . import payment_ops          # noqa: F401
from . import trust_ops            # noqa: F401
from . import misc_ops             # noqa: F401
from . import offer_ops            # noqa: F401
from . import path_payment_ops     # noqa: F401
from . import claimable_balance_ops  # noqa: F401
from . import sponsorship_ops      # noqa: F401
from . import clawback_ops         # noqa: F401
from . import liquidity_pool_ops   # noqa: F401
from ... import soroban as _soroban   # noqa: F401  (registers contract ops)
