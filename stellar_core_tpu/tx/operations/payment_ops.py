"""Payment operation (direct credit/native transfer).

Reference: transactions/PaymentOpFrame.cpp — the reference routes
payment through a synthesized PathPaymentStrictReceive with an empty
path and rewrites result codes; since an empty-path payment never touches
the order book, this build implements the transfer directly with the same
semantics (self-payment instant success, issuer mint/burn, trustline
authorization and limit checks, protocol>=13 no-issuer-existence rule).
Path payments (with real paths) live in offer_ops alongside OfferExchange.
"""

from __future__ import annotations

from ...xdr.ledger_entries import AssetType, LedgerKey, TrustLineAsset
from ...xdr.transaction import OperationType
from ...xdr.results import PaymentResultCode
from .. import tx_utils
from ..operation_frame import OperationFrame, register_op
from ..sponsorship import ApplyContext


@register_op(OperationType.PAYMENT)
class PaymentOpFrame(OperationFrame):

    def do_check_valid(self, header, ledger_version: int) -> bool:
        b = self.body
        if b.amount <= 0:
            self.set_inner_result(PaymentResultCode.PAYMENT_MALFORMED)
            return False
        if not tx_utils.is_asset_valid(b.asset):
            self.set_inner_result(PaymentResultCode.PAYMENT_MALFORMED)
            return False
        return True

    def do_apply(self, ltx, header, ctx: ApplyContext) -> bool:
        b = self.body
        dest_id = b.destination.account_id()
        src_id = self.source_id
        native = b.asset.disc == AssetType.ASSET_TYPE_NATIVE

        # ed25519 raws compare directly — both are stripped PublicKeys
        if native and bytes(dest_id.value) == bytes(src_id.value):
            self.set_inner_result(PaymentResultCode.PAYMENT_SUCCESS)
            return True

        issuer = tx_utils.asset_issuer(b.asset)
        if not native and header.ledgerVersion < 13:
            if not ltx.entry_exists(LedgerKey.account(issuer)):
                self.set_inner_result(PaymentResultCode.PAYMENT_NO_ISSUER)
                return False

        # destination is credited BEFORE the source is debited (reference
        # routes through PathPaymentStrictReceive: updateDestBalance first)
        # so dest-side errors win and self-payments over one trustline work
        if native:
            # existence check folds into the (recording) load
            dest_le = ltx.load(LedgerKey.account(dest_id))
            if dest_le is None:
                self.set_inner_result(
                    PaymentResultCode.PAYMENT_NO_DESTINATION)
                return False
            if not tx_utils.add_balance_account(
                    header, dest_le.data.value, b.amount):
                self.set_inner_result(PaymentResultCode.PAYMENT_LINE_FULL)
                return False
        elif issuer.to_bytes() == dest_id.to_bytes():
            pass  # issuer burns: no destination trustline
        elif not ltx.entry_exists(LedgerKey.account(dest_id)):
            self.set_inner_result(PaymentResultCode.PAYMENT_NO_DESTINATION)
            return False
        else:
            tl_le = tx_utils.load_trustline(ltx, dest_id, b.asset)
            if tl_le is None:
                self.set_inner_result(PaymentResultCode.PAYMENT_NO_TRUST)
                return False
            tl = tl_le.data.value
            if not tx_utils.is_authorized(tl):
                self.set_inner_result(PaymentResultCode.
                                      PAYMENT_NOT_AUTHORIZED)
                return False
            if not tx_utils.add_balance_trustline(tl, b.amount):
                self.set_inner_result(PaymentResultCode.PAYMENT_LINE_FULL)
                return False

        # ---- debit the source ----
        if native:
            src_le = self.load_source_account(ltx)
            if not tx_utils.add_balance_account(
                    header, src_le.data.value, -b.amount):
                self.set_inner_result(PaymentResultCode.PAYMENT_UNDERFUNDED)
                return False
        elif issuer.to_bytes() == src_id.to_bytes():
            pass  # issuer mints: no source trustline
        else:
            tl_le = tx_utils.load_trustline(ltx, src_id, b.asset)
            if tl_le is None:
                self.set_inner_result(PaymentResultCode.PAYMENT_SRC_NO_TRUST)
                return False
            tl = tl_le.data.value
            if not tx_utils.is_authorized(tl):
                self.set_inner_result(PaymentResultCode.
                                      PAYMENT_SRC_NOT_AUTHORIZED)
                return False
            if not tx_utils.add_balance_trustline(tl, -b.amount):
                self.set_inner_result(PaymentResultCode.PAYMENT_UNDERFUNDED)
                return False

        self.set_inner_result(PaymentResultCode.PAYMENT_SUCCESS)
        return True
