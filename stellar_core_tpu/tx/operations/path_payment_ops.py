"""Path payments: strict receive + strict send.

Reference: transactions/PathPaymentOpFrameBase.cpp (shared dest/source
balance updates + convert filter), PathPaymentStrictReceiveOpFrame.cpp
(fixed destination amount, hops walked backwards computing what must be
sent), PathPaymentStrictSendOpFrame.cpp (fixed send amount, hops walked
forwards computing what arrives).
"""

from __future__ import annotations

from typing import List

from ...xdr.ledger_entries import Asset, AssetType, LedgerKey
from ...xdr.results import (ClaimAtom, PathPaymentStrictReceiveResultCode,
                            PathPaymentStrictSendResultCode,
                            SimplePaymentResult,
                            _PathPaymentStrictReceiveSuccess,
                            _PathPaymentStrictSendSuccess)
from ...xdr.transaction import OperationType
from ...ledger.ledger_txn import LedgerTxn
from .. import tx_utils
from ..offer_exchange import (ConvertResult, OfferFilterResult,
                              convert_with_offers_and_pools)
from ..offer_math import RoundingType
from ..operation_frame import OperationFrame, register_op
from .offer_ops import MAX_OFFERS_TO_CROSS

INT64_MAX = 2**63 - 1


class PathPaymentOpFrameBase(OperationFrame):
    RC = PathPaymentStrictReceiveResultCode
    PREFIX = "PATH_PAYMENT_STRICT_RECEIVE"

    def _rc(self, name: str):
        return getattr(self.RC, f"{self.PREFIX}_{name}")

    def _fail(self, name: str) -> bool:
        self.set_inner_result(self._rc(name))
        return False

    # ------------------------------------------------------------ balances --
    def _credit_dest(self, ltx, header, dest_id, asset, amount) -> bool:
        native = asset.disc == AssetType.ASSET_TYPE_NATIVE
        issuer = None if native else tx_utils.asset_issuer(asset)
        if not native and issuer.to_bytes() == dest_id.to_bytes():
            return True  # burn at the issuer
        if not ltx.entry_exists(LedgerKey.account(dest_id)):
            return self._fail("NO_DESTINATION")
        if native:
            dest_le = ltx.load(LedgerKey.account(dest_id))
            if not tx_utils.add_balance_account(header, dest_le.data.value,
                                                amount):
                return self._fail("LINE_FULL")
            return True
        tl_le = tx_utils.load_trustline(ltx, dest_id, asset)
        if tl_le is None:
            return self._fail("NO_TRUST")
        tl = tl_le.data.value
        if not tx_utils.is_authorized(tl):
            return self._fail("NOT_AUTHORIZED")
        if not tx_utils.add_balance_trustline(tl, amount):
            return self._fail("LINE_FULL")
        return True

    def _debit_source(self, ltx, header, asset, amount) -> bool:
        native = asset.disc == AssetType.ASSET_TYPE_NATIVE
        src_id = self.source_id
        if native:
            src_le = ltx.load(LedgerKey.account(src_id))
            if not tx_utils.add_balance_account(header, src_le.data.value,
                                                -amount):
                return self._fail("UNDERFUNDED")
            return True
        issuer = tx_utils.asset_issuer(asset)
        if issuer.to_bytes() == src_id.to_bytes():
            return True  # mint at the issuer
        tl_le = tx_utils.load_trustline(ltx, src_id, asset)
        if tl_le is None:
            return self._fail("SRC_NO_TRUST")
        tl = tl_le.data.value
        if not tx_utils.is_authorized(tl):
            return self._fail("SRC_NOT_AUTHORIZED")
        if not tx_utils.add_balance_trustline(tl, -amount):
            return self._fail("UNDERFUNDED")
        return True

    def _convert(self, ltx, sheep: Asset, max_sheep: int, wheat: Asset,
                 max_wheat: int, round_type, trail: List[ClaimAtom]):
        """One hop through book AND pool — whichever gives the taker the
        strictly better price wins (reference:
        PathPaymentOpFrameBase::convert → convertWithOffersAndPools;
        the protocol-18 gate + the pool-trading-disabled header flag
        live inside exchange_with_pool, so pre-18 ledgers cross offers
        only). The source crossing its own offer aborts the whole
        payment (OFFER_CROSS_SELF). The 1000-offer work limit is
        PER OPERATION: each hop gets only the remaining budget
        (reference passes getMaxOffersToCross() - offersCrossed)."""

        def offer_filter(entry):
            o = entry.data.value
            if o.sellerID.to_bytes() == self.source_id.to_bytes():
                return OfferFilterResult.eStopCrossSelf
            return OfferFilterResult.eKeep

        hop: List[ClaimAtom] = []
        # the 1000-offer work limit exists from protocol 11
        # (FIRST_PROTOCOL_SUPPORTING_OPERATION_LIMITS); it is PER
        # OPERATION, so each hop gets only the remaining budget
        # (reference passes getMaxOffersToCross() - offersCrossed)
        budget = MAX_OFFERS_TO_CROSS - len(trail) \
            if ltx.get_header().ledgerVersion >= 11 else INT64_MAX
        r, sheep_sent, wheat_received = convert_with_offers_and_pools(
            ltx, sheep, max_sheep, wheat, max_wheat, round_type,
            offer_filter, hop, budget)
        trail.extend(hop)
        return r, sheep_sent, wheat_received

    def _map_convert_error(self, r) -> bool:
        """Shared terminal ConvertResult mapping (reference:
        PathPaymentOpFrameBase::convert switch); True = result set."""
        if r == ConvertResult.eFilterStopCrossSelf:
            self._fail("OFFER_CROSS_SELF")
            return True
        if r == ConvertResult.eCrossedTooMany:
            from ...xdr.results import OperationResultCode
            self.set_outer_result(
                OperationResultCode.opEXCEEDED_WORK_LIMIT)
            return True
        return False

    # ------------------------------------------------------------ validity --
    def _check_common(self, send_asset, dest_asset, path,
                      amounts) -> bool:
        if any(a <= 0 for a in amounts):
            return self._fail("MALFORMED")
        if not tx_utils.is_asset_valid(send_asset) or \
                not tx_utils.is_asset_valid(dest_asset):
            return self._fail("MALFORMED")
        if any(not tx_utils.is_asset_valid(a) for a in path):
            return self._fail("MALFORMED")
        return True


@register_op(OperationType.PATH_PAYMENT_STRICT_RECEIVE)
class PathPaymentStrictReceiveOpFrame(PathPaymentOpFrameBase):
    RC = PathPaymentStrictReceiveResultCode
    PREFIX = "PATH_PAYMENT_STRICT_RECEIVE"

    def do_check_valid(self, header, ledger_version: int) -> bool:
        b = self.body
        return self._check_common(b.sendAsset, b.destAsset, list(b.path),
                                  [b.sendMax, b.destAmount])

    def do_apply(self, ltx_outer, header_outer, ctx) -> bool:
        b = self.body
        dest_id = b.destination.account_id()
        with LedgerTxn(ltx_outer) as ltx:
            header = ltx.load_header()
            if not self._credit_dest(ltx, header, dest_id, b.destAsset,
                                     b.destAmount):
                return False
            offer_trail: List[ClaimAtom] = []
            cur_amount = b.destAmount
            cur_asset = b.destAsset
            full_path = [b.sendAsset] + list(b.path)
            for asset in reversed(full_path):
                if asset.to_bytes() == cur_asset.to_bytes():
                    continue
                r, sheep_sent, wheat_received = self._convert(
                    ltx, asset, INT64_MAX, cur_asset, cur_amount,
                    RoundingType.PATH_PAYMENT_STRICT_RECEIVE, offer_trail)
                if self._map_convert_error(r):
                    return False
                if r != ConvertResult.eOK or wheat_received != cur_amount:
                    return self._fail("TOO_FEW_OFFERS")
                cur_amount = sheep_sent
                cur_asset = asset
            if cur_amount > b.sendMax:
                return self._fail("OVER_SENDMAX")
            if not self._debit_source(ltx, header, b.sendAsset,
                                      cur_amount):
                return False
            self.set_inner_result(
                self._rc("SUCCESS"),
                _PathPaymentStrictReceiveSuccess(
                    offers=offer_trail,
                    last=SimplePaymentResult(
                        destination=dest_id, asset=b.destAsset,
                        amount=b.destAmount)))
            ltx.commit()
            return True


@register_op(OperationType.PATH_PAYMENT_STRICT_SEND)
class PathPaymentStrictSendOpFrame(PathPaymentOpFrameBase):
    RC = PathPaymentStrictSendResultCode
    PREFIX = "PATH_PAYMENT_STRICT_SEND"

    def do_check_valid(self, header, ledger_version: int) -> bool:
        b = self.body
        return self._check_common(b.sendAsset, b.destAsset, list(b.path),
                                  [b.sendAmount, b.destMin])

    def do_apply(self, ltx_outer, header_outer, ctx) -> bool:
        b = self.body
        dest_id = b.destination.account_id()
        with LedgerTxn(ltx_outer) as ltx:
            header = ltx.load_header()
            if not self._debit_source(ltx, header, b.sendAsset,
                                      b.sendAmount):
                return False
            offer_trail: List[ClaimAtom] = []
            cur_amount = b.sendAmount
            cur_asset = b.sendAsset
            full_path = list(b.path) + [b.destAsset]
            for asset in full_path:
                if asset.to_bytes() == cur_asset.to_bytes():
                    continue
                r, sheep_sent, wheat_received = self._convert(
                    ltx, cur_asset, cur_amount, asset, INT64_MAX,
                    RoundingType.PATH_PAYMENT_STRICT_SEND, offer_trail)
                if self._map_convert_error(r):
                    return False
                if r != ConvertResult.eOK or sheep_sent != cur_amount:
                    return self._fail("TOO_FEW_OFFERS")
                cur_amount = wheat_received
                cur_asset = asset
            if cur_amount < b.destMin:
                return self._fail("UNDER_DESTMIN")
            if not self._credit_dest(ltx, header, dest_id, b.destAsset,
                                     cur_amount):
                return False
            self.set_inner_result(
                self._rc("SUCCESS"),
                _PathPaymentStrictSendSuccess(
                    offers=offer_trail,
                    last=SimplePaymentResult(
                        destination=dest_id, asset=b.destAsset,
                        amount=cur_amount)))
            ltx.commit()
            return True
