"""Liquidity pool deposit/withdraw (constant-product AMM).

Reference: transactions/LiquidityPoolDepositOpFrame.cpp (empty-pool
bootstrap from maxAmountA/B with price bounds, proportional deposit
against reserves otherwise, shares = min over both axes),
LiquidityPoolWithdrawOpFrame.cpp (pro-rata redemption with minimums).
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

from ...xdr.ledger_entries import (AssetType, LedgerKey, Price,
                                   TrustLineAsset)
from ...xdr.results import (LiquidityPoolDepositResultCode,
                            LiquidityPoolWithdrawResultCode)
from ...xdr.transaction import OperationType
from ...ledger.ledger_txn import LedgerTxn
from .. import tx_utils
from ..offer_math import Rounding, big_divide
from ..operation_frame import OperationFrame, register_op
from ..pool_trust import load_pool

INT64_MAX = 2**63 - 1


def _pool_share_tl(ltx, account_id, pool_id: bytes):
    key = LedgerKey.trust_line(
        account_id, TrustLineAsset(AssetType.ASSET_TYPE_POOL_SHARE,
                                   pool_id))
    return ltx.load(key)


def _asset_balance_available(ltx, header, account_id, asset) -> int:
    from ..offer_exchange import can_sell_at_most
    return can_sell_at_most(ltx, header, account_id, asset)


def _credit(ltx, header, account_id, asset, amount) -> bool:
    from ..offer_exchange import _add_asset_balance
    return _add_asset_balance(ltx, header, account_id, asset, amount)


@register_op(OperationType.LIQUIDITY_POOL_DEPOSIT)
class LiquidityPoolDepositOpFrame(OperationFrame):

    def do_check_valid(self, header, ledger_version: int) -> bool:
        b = self.body
        rc = LiquidityPoolDepositResultCode
        if b.maxAmountA <= 0 or b.maxAmountB <= 0 or \
                b.minPrice.n <= 0 or b.minPrice.d <= 0 or \
                b.maxPrice.n <= 0 or b.maxPrice.d <= 0:
            self.set_inner_result(rc.LIQUIDITY_POOL_DEPOSIT_MALFORMED)
            return False
        if b.minPrice.n * b.maxPrice.d > b.maxPrice.n * b.minPrice.d:
            self.set_inner_result(rc.LIQUIDITY_POOL_DEPOSIT_MALFORMED)
            return False
        return True

    def is_op_supported(self, header, ledger_version: int) -> bool:
        # reference: LiquidityPoolDepositOpFrame::isOpSupported —
        # protocol 18+ AND the voted disable flag is clear
        from ...xdr.ledger import LedgerHeaderFlags
        return ledger_version >= 18 and not (
            tx_utils.header_flags(header) &
            LedgerHeaderFlags.DISABLE_LIQUIDITY_POOL_DEPOSIT_FLAG)

    def do_apply(self, ltx_outer, header_outer, ctx) -> bool:
        b = self.body
        rc = LiquidityPoolDepositResultCode
        with LedgerTxn(ltx_outer) as ltx:
            header = ltx.load_header()
            pool_id = bytes(b.liquidityPoolID)
            ps_tl_le = _pool_share_tl(ltx, self.source_id, pool_id)
            if ps_tl_le is None:
                self.set_inner_result(rc.LIQUIDITY_POOL_DEPOSIT_NO_TRUST)
                return False
            pool_le = load_pool(ltx, pool_id)
            if pool_le is None:
                self.set_inner_result(rc.LIQUIDITY_POOL_DEPOSIT_NO_TRUST)
                return False
            cp = pool_le.data.value.body.value
            asset_a, asset_b = cp.params.assetA, cp.params.assetB

            # trustlines/auth for both assets (issuer accounts exempt)
            for asset in (asset_a, asset_b):
                if asset.disc == AssetType.ASSET_TYPE_NATIVE:
                    continue
                if tx_utils.asset_issuer(asset).to_bytes() == \
                        self.source_id.to_bytes():
                    continue
                tl = tx_utils.load_trustline(ltx, self.source_id, asset)
                if tl is None:
                    self.set_inner_result(
                        rc.LIQUIDITY_POOL_DEPOSIT_NO_TRUST)
                    return False
                if not tx_utils.is_authorized(tl.data.value):
                    self.set_inner_result(
                        rc.LIQUIDITY_POOL_DEPOSIT_NOT_AUTHORIZED)
                    return False

            if cp.totalPoolShares == 0:
                amount_a, amount_b = b.maxAmountA, b.maxAmountB
                # price = A/B must be within bounds
                if amount_a * b.minPrice.d < b.minPrice.n * amount_b or \
                        amount_a * b.maxPrice.d > b.maxPrice.n * amount_b:
                    self.set_inner_result(
                        rc.LIQUIDITY_POOL_DEPOSIT_BAD_PRICE)
                    return False
                total_shares = math.isqrt(amount_a * amount_b)
            else:
                # proportional to reserves (reference: bigDivide ROUND_DOWN
                # on each axis, pick the binding one)
                amount_b = big_divide(b.maxAmountA, cp.reserveB,
                                      cp.reserveA, Rounding.ROUND_UP)
                if amount_b <= b.maxAmountB:
                    amount_a = b.maxAmountA
                else:
                    amount_b = b.maxAmountB
                    amount_a = big_divide(b.maxAmountB, cp.reserveA,
                                          cp.reserveB, Rounding.ROUND_UP)
                    if amount_a > b.maxAmountA:
                        self.set_inner_result(
                            rc.LIQUIDITY_POOL_DEPOSIT_BAD_PRICE)
                        return False
                if amount_a <= 0 or amount_b <= 0:
                    self.set_inner_result(
                        rc.LIQUIDITY_POOL_DEPOSIT_BAD_PRICE)
                    return False
                # price bounds on the actual deposit ratio
                if amount_a * b.minPrice.d < b.minPrice.n * amount_b or \
                        amount_a * b.maxPrice.d > b.maxPrice.n * amount_b:
                    self.set_inner_result(
                        rc.LIQUIDITY_POOL_DEPOSIT_BAD_PRICE)
                    return False
                shares_a = big_divide(cp.totalPoolShares, amount_a,
                                      cp.reserveA, Rounding.ROUND_DOWN)
                shares_b = big_divide(cp.totalPoolShares, amount_b,
                                      cp.reserveB, Rounding.ROUND_DOWN)
                total_shares = min(shares_a, shares_b)

            if total_shares <= 0:
                self.set_inner_result(rc.LIQUIDITY_POOL_DEPOSIT_BAD_PRICE)
                return False
            if cp.totalPoolShares > INT64_MAX - total_shares or \
                    cp.reserveA > INT64_MAX - amount_a or \
                    cp.reserveB > INT64_MAX - amount_b:
                self.set_inner_result(rc.LIQUIDITY_POOL_DEPOSIT_POOL_FULL)
                return False

            # funding
            for asset, amount in ((asset_a, amount_a),
                                  (asset_b, amount_b)):
                if _asset_balance_available(ltx, header, self.source_id,
                                            asset) < amount:
                    self.set_inner_result(
                        rc.LIQUIDITY_POOL_DEPOSIT_UNDERFUNDED)
                    return False
            ps_tl = ps_tl_le.data.value
            if tx_utils.max_receive_trustline(ps_tl) < total_shares:
                self.set_inner_result(rc.LIQUIDITY_POOL_DEPOSIT_LINE_FULL)
                return False

            for asset, amount in ((asset_a, amount_a),
                                  (asset_b, amount_b)):
                if not _credit(ltx, header, self.source_id, asset,
                               -amount):
                    self.set_inner_result(
                        rc.LIQUIDITY_POOL_DEPOSIT_UNDERFUNDED)
                    return False
            cp.reserveA += amount_a
            cp.reserveB += amount_b
            cp.totalPoolShares += total_shares
            ps_tl.balance += total_shares
            self.set_inner_result(rc.LIQUIDITY_POOL_DEPOSIT_SUCCESS)
            ltx.commit()
            return True


@register_op(OperationType.LIQUIDITY_POOL_WITHDRAW)
class LiquidityPoolWithdrawOpFrame(OperationFrame):

    def do_check_valid(self, header, ledger_version: int) -> bool:
        b = self.body
        rc = LiquidityPoolWithdrawResultCode
        if b.amount <= 0 or b.minAmountA < 0 or b.minAmountB < 0:
            self.set_inner_result(rc.LIQUIDITY_POOL_WITHDRAW_MALFORMED)
            return False
        return True

    def is_op_supported(self, header, ledger_version: int) -> bool:
        from ...xdr.ledger import LedgerHeaderFlags
        return ledger_version >= 18 and not (
            tx_utils.header_flags(header) &
            LedgerHeaderFlags.DISABLE_LIQUIDITY_POOL_WITHDRAWAL_FLAG)

    def do_apply(self, ltx_outer, header_outer, ctx) -> bool:
        b = self.body
        rc = LiquidityPoolWithdrawResultCode
        with LedgerTxn(ltx_outer) as ltx:
            header = ltx.load_header()
            pool_id = bytes(b.liquidityPoolID)
            ps_tl_le = _pool_share_tl(ltx, self.source_id, pool_id)
            if ps_tl_le is None:
                self.set_inner_result(rc.LIQUIDITY_POOL_WITHDRAW_NO_TRUST)
                return False
            ps_tl = ps_tl_le.data.value
            if ps_tl.balance < b.amount:
                self.set_inner_result(
                    rc.LIQUIDITY_POOL_WITHDRAW_UNDERFUNDED)
                return False
            pool_le = load_pool(ltx, pool_id)
            if pool_le is None:
                self.set_inner_result(rc.LIQUIDITY_POOL_WITHDRAW_NO_TRUST)
                return False
            cp = pool_le.data.value.body.value

            amount_a = big_divide(cp.reserveA, b.amount,
                                  cp.totalPoolShares, Rounding.ROUND_DOWN)
            amount_b = big_divide(cp.reserveB, b.amount,
                                  cp.totalPoolShares, Rounding.ROUND_DOWN)
            if amount_a < b.minAmountA or amount_b < b.minAmountB:
                self.set_inner_result(
                    rc.LIQUIDITY_POOL_WITHDRAW_UNDER_MINIMUM)
                return False

            for asset, amount in ((cp.params.assetA, amount_a),
                                  (cp.params.assetB, amount_b)):
                if amount == 0:
                    continue
                if not _credit(ltx, header, self.source_id, asset,
                               amount):
                    self.set_inner_result(
                        rc.LIQUIDITY_POOL_WITHDRAW_LINE_FULL)
                    return False
            cp.reserveA -= amount_a
            cp.reserveB -= amount_b
            cp.totalPoolShares -= b.amount
            ps_tl.balance -= b.amount
            self.set_inner_result(rc.LIQUIDITY_POOL_WITHDRAW_SUCCESS)
            ltx.commit()
            return True
