"""Trustline operations: ChangeTrust, AllowTrust, SetTrustLineFlags.

Reference: transactions/ChangeTrustOpFrame.cpp,
AllowTrustOpFrame.cpp, SetTrustLineFlagsOpFrame.cpp and the shared
TrustFlagsOpFrameBase.cpp (LOW threshold :22-25; auth-revocation pulls
the trustor's offers, :28-45). Pool-share trustlines are wired through
`pool_trust` hooks (liquidity-pool wave).
"""

from __future__ import annotations

from ...xdr.ledger_entries import (AccountFlags, AssetType, LedgerEntry,
                                   LedgerEntryType, LedgerKey,
                                   TrustLineAsset, TrustLineEntry,
                                   TrustLineFlags, _LedgerEntryData)
from ...xdr.transaction import OperationType
from ...xdr.results import (
    AllowTrustResultCode, ChangeTrustResultCode, OperationResultCode,
    SetTrustLineFlagsResultCode,
)
from .. import liabilities, tx_utils
from ..operation_frame import OperationFrame, ThresholdLevel, register_op
from ..sponsorship import (ApplyContext, SponsorshipResult,
                           create_entry_with_possible_sponsorship,
                           remove_entry_with_possible_sponsorship)

INT64_MAX = 2**63 - 1

TRUSTLINE_AUTH_FLAGS = (TrustLineFlags.AUTHORIZED_FLAG |
                        TrustLineFlags.AUTHORIZED_TO_MAINTAIN_LIABILITIES_FLAG)
ALL_TRUSTLINE_FLAGS = (TRUSTLINE_AUTH_FLAGS |
                       TrustLineFlags.TRUSTLINE_CLAWBACK_ENABLED_FLAG)


def trustline_flag_is_valid(flags: int, ledger_version: int) -> bool:
    """No unknown bits and not both auth levels at once (reference:
    TransactionUtils trustLineFlagIsValid/trustLineFlagAuthIsValid)."""
    mask = ALL_TRUSTLINE_FLAGS if ledger_version >= 17 else \
        TRUSTLINE_AUTH_FLAGS
    if flags & ~mask:
        return False
    both = (TrustLineFlags.AUTHORIZED_FLAG |
            TrustLineFlags.AUTHORIZED_TO_MAINTAIN_LIABILITIES_FLAG)
    return (flags & both) != both


def _change_trust_asset_to_tla(line) -> TrustLineAsset:
    if line.disc == AssetType.ASSET_TYPE_POOL_SHARE:
        from ..pool_trust import pool_id_for_params
        return TrustLineAsset(AssetType.ASSET_TYPE_POOL_SHARE,
                              pool_id_for_params(line.value.value))
    return TrustLineAsset(line.disc, line.value)


def _is_issuer_of(source_id, line) -> bool:
    if line.disc in (AssetType.ASSET_TYPE_NATIVE,
                     AssetType.ASSET_TYPE_POOL_SHARE):
        return False
    return line.value.issuer.to_bytes() == source_id.to_bytes()


@register_op(OperationType.CHANGE_TRUST)
class ChangeTrustOpFrame(OperationFrame):

    def do_check_valid(self, header, ledger_version: int) -> bool:
        b = self.body
        if b.limit < 0:
            self.set_inner_result(ChangeTrustResultCode.CHANGE_TRUST_MALFORMED)
            return False
        if not self._line_asset_valid(b.line, ledger_version):
            self.set_inner_result(ChangeTrustResultCode.CHANGE_TRUST_MALFORMED)
            return False
        if b.line.disc == AssetType.ASSET_TYPE_NATIVE:
            self.set_inner_result(ChangeTrustResultCode.CHANGE_TRUST_MALFORMED)
            return False
        if ledger_version >= 16 and _is_issuer_of(self.source_id, b.line):
            self.set_inner_result(ChangeTrustResultCode.CHANGE_TRUST_MALFORMED)
            return False
        return True

    @staticmethod
    def _line_asset_valid(line, ledger_version: int) -> bool:
        if line.disc == AssetType.ASSET_TYPE_POOL_SHARE:
            if ledger_version < 18:
                return False
            from ..pool_trust import pool_params_valid
            return pool_params_valid(line.value)
        from ...xdr.ledger_entries import Asset
        return tx_utils.is_asset_valid(
            Asset(line.disc, line.value)
            if line.disc != AssetType.ASSET_TYPE_NATIVE else Asset(line.disc))

    def do_apply(self, ltx, header, ctx: ApplyContext) -> bool:
        b = self.body
        if _is_issuer_of(self.source_id, b.line):
            self.set_inner_result(ChangeTrustResultCode.
                                  CHANGE_TRUST_SELF_NOT_ALLOWED)
            return False

        is_pool = b.line.disc == AssetType.ASSET_TYPE_POOL_SHARE
        tla = _change_trust_asset_to_tla(b.line)
        key = LedgerKey.trust_line(self.source_id, tla)
        tl_le = ltx.load(key)

        if tl_le is not None:
            tl: TrustLineEntry = tl_le.data.value
            min_limit = tl.balance + tx_utils._tl_buying_liabilities(tl)
            if b.limit < min_limit:
                self.set_inner_result(ChangeTrustResultCode.
                                      CHANGE_TRUST_INVALID_LIMIT)
                return False
            if b.limit == 0:
                if not is_pool and _pool_use_count(tl) != 0:
                    self.set_inner_result(ChangeTrustResultCode.
                                          CHANGE_TRUST_CANNOT_DELETE)
                    return False
                source_le = self.load_source_account(ltx)
                remove_entry_with_possible_sponsorship(
                    ltx, header, tl_le, source_le)
                ltx.erase(key)
                if is_pool:
                    from ..pool_trust import manage_pool_on_deleted_trustline
                    manage_pool_on_deleted_trustline(
                        ltx, tla, cp_params=b.line.value.value,
                        account_id=self.source_id)
            else:
                if not is_pool:
                    issuer = b.line.value.issuer
                    if not ltx.entry_exists(LedgerKey.account(issuer)):
                        self.set_inner_result(ChangeTrustResultCode.
                                              CHANGE_TRUST_NO_ISSUER)
                        return False
                tl.limit = b.limit
            self.set_inner_result(ChangeTrustResultCode.CHANGE_TRUST_SUCCESS)
            return True

        # --- new trustline ---
        if b.limit == 0:
            self.set_inner_result(ChangeTrustResultCode.
                                  CHANGE_TRUST_INVALID_LIMIT)
            return False
        flags = 0
        if not is_pool:
            issuer_le = ltx.load_without_record(
                LedgerKey.account(b.line.value.issuer))
            if issuer_le is None:
                self.set_inner_result(ChangeTrustResultCode.
                                      CHANGE_TRUST_NO_ISSUER)
                return False
            issuer_acc = issuer_le.data.value
            if not (issuer_acc.flags & AccountFlags.AUTH_REQUIRED_FLAG):
                flags = TrustLineFlags.AUTHORIZED_FLAG
            if issuer_acc.flags & AccountFlags.AUTH_CLAWBACK_ENABLED_FLAG:
                flags |= TrustLineFlags.TRUSTLINE_CLAWBACK_ENABLED_FLAG
        tl = TrustLineEntry(accountID=self.source_id, asset=tla,
                            balance=0, limit=b.limit, flags=flags)
        new_le = LedgerEntry(
            lastModifiedLedgerSeq=header.ledgerSeq,
            data=_LedgerEntryData(LedgerEntryType.TRUSTLINE, tl))
        if is_pool:
            from ..pool_trust import try_manage_pool_on_new_trustline
            if not try_manage_pool_on_new_trustline(self, ltx, header,
                                                    b.line, tla):
                return False
        source_le = self.load_source_account(ltx)
        sres = create_entry_with_possible_sponsorship(
            ltx, header, new_le, source_le, ctx)
        if sres == SponsorshipResult.LOW_RESERVE:
            self.set_inner_result(ChangeTrustResultCode.
                                  CHANGE_TRUST_LOW_RESERVE)
            return False
        if sres == SponsorshipResult.TOO_MANY_SUBENTRIES:
            self.set_outer_result(OperationResultCode.opTOO_MANY_SUBENTRIES)
            return False
        if sres == SponsorshipResult.TOO_MANY_SPONSORING:
            self.set_outer_result(OperationResultCode.opTOO_MANY_SPONSORING)
            return False
        ltx.create(new_le)
        self.set_inner_result(ChangeTrustResultCode.CHANGE_TRUST_SUCCESS)
        return True


def _pool_use_count(tl: TrustLineEntry) -> int:
    if tl.ext.disc == 1 and tl.ext.value.ext.disc == 2:
        return tl.ext.value.ext.value.liquidityPoolUseCount
    return 0


class _TrustFlagsOpFrameBase(OperationFrame):
    """Shared auth-flag machinery (reference:
    TrustFlagsOpFrameBase.cpp)."""

    def threshold_level(self) -> ThresholdLevel:
        return ThresholdLevel.LOW

    # subclass hooks -------------------------------------------------------
    def trustor(self):
        raise NotImplementedError

    def op_asset(self):
        raise NotImplementedError

    def expected_flag_value(self, tl: TrustLineEntry):
        """new flags value, or None + result already set on failure"""
        raise NotImplementedError

    def set_success(self):
        raise NotImplementedError

    def set_no_trust_line(self):
        raise NotImplementedError

    def set_cant_revoke(self):
        raise NotImplementedError

    def set_self_not_allowed(self):
        raise NotImplementedError

    # shared apply ---------------------------------------------------------
    def do_apply(self, ltx, header, ctx: ApplyContext) -> bool:
        if self.trustor().to_bytes() == self.source_id.to_bytes():
            self.set_self_not_allowed()
            return False
        source_le = self.load_source_account(ltx)
        auth_revocable = bool(source_le.data.value.flags &
                              AccountFlags.AUTH_REVOCABLE_FLAG)

        asset = self.op_asset()
        tla = TrustLineAsset.from_asset(asset)
        key = LedgerKey.trust_line(self.trustor(), tla)
        tl_le = ltx.load(key)
        if tl_le is None:
            self.set_no_trust_line()
            return False
        tl: TrustLineEntry = tl_le.data.value
        expected = self.expected_flag_value(tl)
        if expected is None:
            return False

        was_auth = tx_utils.is_authorized(tl)
        was_maintain = tx_utils.is_authorized_to_maintain_liabilities(tl)
        now_auth = bool(expected & TrustLineFlags.AUTHORIZED_FLAG)
        now_maintain = bool(expected & TRUSTLINE_AUTH_FLAGS)

        # any downgrade of authorization requires AUTH_REVOCABLE
        if (was_auth and not now_auth) or (was_maintain and not now_maintain):
            if not auth_revocable:
                self.set_cant_revoke()
                return False

        if was_maintain and not now_maintain:
            # full revocation pulls the trustor's offers in this asset
            liabilities.remove_offers_by_account_and_asset(
                ltx, header, self.trustor(), asset)
            tl_le = ltx.load(key)  # offers removal may have touched it
            tl = tl_le.data.value

        tl.flags = expected
        self.set_success()
        return True


@register_op(OperationType.ALLOW_TRUST)
class AllowTrustOpFrame(_TrustFlagsOpFrameBase):

    def trustor(self):
        return self.body.trustor

    def op_asset(self):
        from ...xdr.ledger_entries import Asset, AlphaNum4, AlphaNum12
        code = self.body.asset
        if code.disc == AssetType.ASSET_TYPE_CREDIT_ALPHANUM4:
            return Asset(AssetType.ASSET_TYPE_CREDIT_ALPHANUM4,
                         AlphaNum4(assetCode=code.value,
                                   issuer=self.source_id))
        return Asset(AssetType.ASSET_TYPE_CREDIT_ALPHANUM12,
                     AlphaNum12(assetCode=code.value, issuer=self.source_id))

    def do_check_valid(self, header, ledger_version: int) -> bool:
        b = self.body
        if b.asset.disc == AssetType.ASSET_TYPE_NATIVE:
            self.set_inner_result(AllowTrustResultCode.ALLOW_TRUST_MALFORMED)
            return False
        if b.authorize > TrustLineFlags.\
                AUTHORIZED_TO_MAINTAIN_LIABILITIES_FLAG or \
                not trustline_flag_is_valid(b.authorize, ledger_version):
            self.set_inner_result(AllowTrustResultCode.ALLOW_TRUST_MALFORMED)
            return False
        if not tx_utils.is_asset_valid(self.op_asset()):
            self.set_inner_result(AllowTrustResultCode.ALLOW_TRUST_MALFORMED)
            return False
        if ledger_version >= 16 and \
                b.trustor.to_bytes() == self.source_id.to_bytes():
            self.set_inner_result(AllowTrustResultCode.ALLOW_TRUST_MALFORMED)
            return False
        return True

    def expected_flag_value(self, tl: TrustLineEntry):
        return (tl.flags & ~TRUSTLINE_AUTH_FLAGS) | self.body.authorize

    def set_success(self):
        self.set_inner_result(AllowTrustResultCode.ALLOW_TRUST_SUCCESS)

    def set_no_trust_line(self):
        self.set_inner_result(AllowTrustResultCode.ALLOW_TRUST_NO_TRUST_LINE)

    def set_cant_revoke(self):
        self.set_inner_result(AllowTrustResultCode.ALLOW_TRUST_CANT_REVOKE)

    def set_self_not_allowed(self):
        self.set_inner_result(AllowTrustResultCode.
                              ALLOW_TRUST_SELF_NOT_ALLOWED)


@register_op(OperationType.SET_TRUST_LINE_FLAGS)
class SetTrustLineFlagsOpFrame(_TrustFlagsOpFrameBase):

    def is_op_supported(self, header, ledger_version: int) -> bool:
        return ledger_version >= 17

    def trustor(self):
        return self.body.trustor

    def op_asset(self):
        return self.body.asset

    def do_check_valid(self, header, ledger_version: int) -> bool:
        b = self.body
        bad = SetTrustLineFlagsResultCode.SET_TRUST_LINE_FLAGS_MALFORMED
        if b.asset.disc == AssetType.ASSET_TYPE_NATIVE or \
                not tx_utils.is_asset_valid(b.asset):
            self.set_inner_result(bad)
            return False
        issuer = tx_utils.asset_issuer(b.asset)
        if issuer.to_bytes() != self.source_id.to_bytes():
            self.set_inner_result(bad)
            return False
        if b.trustor.to_bytes() == self.source_id.to_bytes():
            self.set_inner_result(bad)
            return False
        if b.setFlags & b.clearFlags:
            self.set_inner_result(bad)
            return False
        if not trustline_flag_is_valid(b.setFlags, ledger_version) or \
                (b.setFlags & TrustLineFlags.TRUSTLINE_CLAWBACK_ENABLED_FLAG):
            self.set_inner_result(bad)
            return False
        if b.clearFlags & ~ALL_TRUSTLINE_FLAGS:
            self.set_inner_result(bad)
            return False
        return True

    def expected_flag_value(self, tl: TrustLineEntry):
        expected = (tl.flags & ~self.body.clearFlags) | self.body.setFlags
        if not trustline_flag_is_valid(expected, 17):
            self.set_inner_result(SetTrustLineFlagsResultCode.
                                  SET_TRUST_LINE_FLAGS_INVALID_STATE)
            return None
        return expected

    def set_success(self):
        self.set_inner_result(SetTrustLineFlagsResultCode.
                              SET_TRUST_LINE_FLAGS_SUCCESS)

    def set_no_trust_line(self):
        self.set_inner_result(SetTrustLineFlagsResultCode.
                              SET_TRUST_LINE_FLAGS_NO_TRUST_LINE)

    def set_cant_revoke(self):
        self.set_inner_result(SetTrustLineFlagsResultCode.
                              SET_TRUST_LINE_FLAGS_CANT_REVOKE)

    def set_self_not_allowed(self):
        # unreachable: doCheckValid rejects trustor == source
        self.set_inner_result(SetTrustLineFlagsResultCode.
                              SET_TRUST_LINE_FLAGS_MALFORMED)
