"""ManageData and Inflation operations.

Reference: transactions/ManageDataOpFrame.cpp,
InflationOpFrame.cpp (LOW threshold :133-135; unsupported from
protocol 12, :127-130 — the pre-12 vote-tally payout logic is
deliberately not carried into this modern-protocol build).
"""

from __future__ import annotations

from ...xdr.ledger_entries import (DataEntry, LedgerEntry, LedgerEntryType,
                                   LedgerKey, _LedgerEntryData)
from ...xdr.transaction import OperationType
from ...xdr.results import (InflationResultCode, ManageDataResultCode,
                            OperationResultCode)
from ..operation_frame import OperationFrame, ThresholdLevel, register_op
from ..sponsorship import (ApplyContext, SponsorshipResult,
                           create_entry_with_possible_sponsorship,
                           remove_entry_with_possible_sponsorship)


from ..tx_utils import is_string_valid


@register_op(OperationType.MANAGE_DATA)
class ManageDataOpFrame(OperationFrame):

    def do_check_valid(self, header, ledger_version: int) -> bool:
        b = self.body
        if ledger_version < 2:
            self.set_inner_result(ManageDataResultCode.
                                  MANAGE_DATA_NOT_SUPPORTED_YET)
            return False
        if len(b.dataName) < 1 or not is_string_valid(b.dataName):
            self.set_inner_result(ManageDataResultCode.
                                  MANAGE_DATA_INVALID_NAME)
            return False
        return True

    def do_apply(self, ltx, header, ctx: ApplyContext) -> bool:
        b = self.body
        key = LedgerKey.data(self.source_id, b.dataName)
        data_le = ltx.load(key)
        if b.dataValue is not None:
            if data_le is None:
                de = DataEntry(accountID=self.source_id,
                               dataName=b.dataName, dataValue=b.dataValue)
                new_le = LedgerEntry(
                    lastModifiedLedgerSeq=header.ledgerSeq,
                    data=_LedgerEntryData(LedgerEntryType.DATA, de))
                source_le = self.load_source_account(ltx)
                sres = create_entry_with_possible_sponsorship(
                    ltx, header, new_le, source_le, ctx)
                if sres == SponsorshipResult.LOW_RESERVE:
                    self.set_inner_result(ManageDataResultCode.
                                          MANAGE_DATA_LOW_RESERVE)
                    return False
                if sres == SponsorshipResult.TOO_MANY_SUBENTRIES:
                    self.set_outer_result(OperationResultCode.
                                          opTOO_MANY_SUBENTRIES)
                    return False
                if sres == SponsorshipResult.TOO_MANY_SPONSORING:
                    self.set_outer_result(OperationResultCode.
                                          opTOO_MANY_SPONSORING)
                    return False
                ltx.create(new_le)
            else:
                data_le.data.value.dataValue = b.dataValue
        else:
            if data_le is None:
                self.set_inner_result(ManageDataResultCode.
                                      MANAGE_DATA_NAME_NOT_FOUND)
                return False
            source_le = self.load_source_account(ltx)
            remove_entry_with_possible_sponsorship(
                ltx, header, data_le, source_le)
            ltx.erase(key)
        self.set_inner_result(ManageDataResultCode.MANAGE_DATA_SUCCESS)
        return True


@register_op(OperationType.INFLATION)
class InflationOpFrame(OperationFrame):

    def threshold_level(self) -> ThresholdLevel:
        return ThresholdLevel.LOW

    def is_op_supported(self, header, ledger_version: int) -> bool:
        return ledger_version < 12

    def do_check_valid(self, header, ledger_version: int) -> bool:
        return True

    def do_apply(self, ltx, header, ctx: ApplyContext) -> bool:
        # Unreachable in this modern-protocol build (is_op_supported gates
        # anything >= v12); kept for result-code shape parity.
        self.set_inner_result(InflationResultCode.INFLATION_NOT_TIME)
        return False
