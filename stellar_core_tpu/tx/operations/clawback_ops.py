"""Clawback operation.

Reference: transactions/ClawbackOpFrame.cpp — the asset issuer pulls
back `amount` from `from`'s trust line; requires the trust line's
TRUSTLINE_CLAWBACK_ENABLED flag; the clawed-back amount must fit the
line's available balance (balance minus selling liabilities).
"""

from __future__ import annotations

from ...xdr.ledger_entries import AssetType, LedgerKey, TrustLineFlags
from ...xdr.results import ClawbackResultCode
from ...xdr.transaction import OperationType
from .. import tx_utils
from ..operation_frame import OperationFrame, register_op


@register_op(OperationType.CLAWBACK)
class ClawbackOpFrame(OperationFrame):

    def do_check_valid(self, header, ledger_version: int) -> bool:
        b = self.body
        rc = ClawbackResultCode
        if b.amount <= 0 or not tx_utils.is_asset_valid(b.asset) or \
                b.asset.disc == AssetType.ASSET_TYPE_NATIVE:
            self.set_inner_result(rc.CLAWBACK_MALFORMED)
            return False
        issuer = tx_utils.asset_issuer(b.asset)
        if issuer.to_bytes() != self.source_id.to_bytes():
            self.set_inner_result(rc.CLAWBACK_MALFORMED)
            return False
        if b.from_.account_id().to_bytes() == self.source_id.to_bytes():
            self.set_inner_result(rc.CLAWBACK_MALFORMED)
            return False
        return True

    def do_apply(self, ltx, header, ctx) -> bool:
        b = self.body
        rc = ClawbackResultCode
        from_id = b.from_.account_id()
        tl_le = tx_utils.load_trustline(ltx, from_id, b.asset)
        if tl_le is None:
            self.set_inner_result(rc.CLAWBACK_NO_TRUST)
            return False
        tl = tl_le.data.value
        if not (tl.flags &
                TrustLineFlags.TRUSTLINE_CLAWBACK_ENABLED_FLAG):
            self.set_inner_result(rc.CLAWBACK_NOT_CLAWBACK_ENABLED)
            return False
        available = tl.balance - tx_utils._tl_selling_liabilities(tl)
        if available < b.amount:
            self.set_inner_result(rc.CLAWBACK_UNDERFUNDED)
            return False
        tl.balance -= b.amount
        self.set_inner_result(rc.CLAWBACK_SUCCESS)
        return True
