"""Sponsorship operations: begin/end sponsoring future reserves, revoke.

Reference: transactions/BeginSponsoringFutureReservesOpFrame.cpp
(records the ephemeral sponsorship scope; RECURSIVE if chains would
form), EndSponsoringFutureReservesOpFrame.cpp (the *sponsored* account
ends its scope), RevokeSponsorshipOpFrame.cpp (transfer or remove the
sponsorship of one entry/signer, updating counters and checking
reserves).
"""

from __future__ import annotations

from typing import Optional

from ...xdr.ledger_entries import (AccountEntry, LedgerEntry,
                                   LedgerEntryType, LedgerKey,
                                   _LedgerEntryExt, LedgerEntryExtensionV1)
from ...xdr.results import (BeginSponsoringFutureReservesResultCode,
                            EndSponsoringFutureReservesResultCode,
                            OperationResultCode,
                            RevokeSponsorshipResultCode)
from ...xdr.transaction import OperationType, RevokeSponsorshipType
from ...xdr.types import ExtensionPoint
from ...ledger.ledger_txn import LedgerTxn
from .. import tx_utils
from ..operation_frame import OperationFrame, register_op
from ..sponsorship import (ensure_account_ext_v2, get_sponsoring_id,
                           num_sponsored, num_sponsoring,
                           reserve_multiplier, set_sponsoring_id,
                           _available_for_reserve)


@register_op(OperationType.BEGIN_SPONSORING_FUTURE_RESERVES)
class BeginSponsoringFutureReservesOpFrame(OperationFrame):

    def do_check_valid(self, header, ledger_version: int) -> bool:
        rc = BeginSponsoringFutureReservesResultCode
        if self.body.sponsoredID.to_bytes() == self.source_id.to_bytes():
            self.set_inner_result(
                rc.BEGIN_SPONSORING_FUTURE_RESERVES_MALFORMED)
            return False
        return True

    def do_apply(self, ltx, header, ctx) -> bool:
        rc = BeginSponsoringFutureReservesResultCode
        sponsored = self.body.sponsoredID.to_bytes()
        source = self.source_id.to_bytes()
        if sponsored in ctx.active_sponsorships:
            self.set_inner_result(
                rc.BEGIN_SPONSORING_FUTURE_RESERVES_ALREADY_SPONSORED)
            return False
        # no chains: our sponsor-to-be can't itself be sponsored, and the
        # sponsored account can't be sponsoring anyone (reference:
        # RECURSIVE checks)
        if source in ctx.active_sponsorships or any(
                sp.to_bytes() == sponsored
                for sp in ctx.active_sponsorships.values()):
            self.set_inner_result(
                rc.BEGIN_SPONSORING_FUTURE_RESERVES_RECURSIVE)
            return False
        ctx.active_sponsorships[sponsored] = self.source_id
        self.set_inner_result(
            rc.BEGIN_SPONSORING_FUTURE_RESERVES_SUCCESS)
        return True


@register_op(OperationType.END_SPONSORING_FUTURE_RESERVES)
class EndSponsoringFutureReservesOpFrame(OperationFrame):

    def do_check_valid(self, header, ledger_version: int) -> bool:
        return True

    def do_apply(self, ltx, header, ctx) -> bool:
        rc = EndSponsoringFutureReservesResultCode
        source = self.source_id.to_bytes()
        if source not in ctx.active_sponsorships:
            self.set_inner_result(
                rc.END_SPONSORING_FUTURE_RESERVES_NOT_SPONSORED)
            return False
        del ctx.active_sponsorships[source]
        self.set_inner_result(
            rc.END_SPONSORING_FUTURE_RESERVES_SUCCESS)
        return True


def _entry_owner_id(key: LedgerKey):
    t = key.disc
    if t == LedgerEntryType.ACCOUNT:
        return key.value.accountID
    if t == LedgerEntryType.TRUSTLINE:
        return key.value.accountID
    if t == LedgerEntryType.OFFER:
        return key.value.sellerID
    if t == LedgerEntryType.DATA:
        return key.value.accountID
    return None  # claimable balances have no owner


@register_op(OperationType.REVOKE_SPONSORSHIP)
class RevokeSponsorshipOpFrame(OperationFrame):

    def do_check_valid(self, header, ledger_version: int) -> bool:
        return True

    def do_apply(self, ltx_outer, header_outer, ctx) -> bool:
        with LedgerTxn(ltx_outer) as ltx:
            if self.body.disc == \
                    RevokeSponsorshipType.REVOKE_SPONSORSHIP_LEDGER_ENTRY:
                ok = self._revoke_entry(ltx, ctx)
            else:
                ok = self._revoke_signer(ltx, ctx)
            if ok:
                ltx.commit()
            return ok

    # ------------------------------------------------------------- entries --
    def _revoke_entry(self, ltx, ctx) -> bool:
        rc = RevokeSponsorshipResultCode
        key = self.body.value
        header = ltx.load_header()
        le = ltx.load(key)
        if le is None:
            self.set_inner_result(rc.REVOKE_SPONSORSHIP_DOES_NOT_EXIST)
            return False
        owner_id = _entry_owner_id(key)
        old_sponsor = get_sponsoring_id(le)
        was_sponsored = old_sponsor is not None
        mult = reserve_multiplier(le)

        # permission (reference: source must be the current payer)
        if was_sponsored:
            if old_sponsor.to_bytes() != self.source_id.to_bytes():
                self.set_inner_result(rc.REVOKE_SPONSORSHIP_NOT_SPONSOR)
                return False
        else:
            if owner_id is None or \
                    owner_id.to_bytes() != self.source_id.to_bytes():
                self.set_inner_result(rc.REVOKE_SPONSORSHIP_NOT_SPONSOR)
                return False

        new_sponsor = None
        if owner_id is not None:
            new_sponsor = ctx.sponsor_for(owner_id)
        elif key.disc == LedgerEntryType.CLAIMABLE_BALANCE:
            # CBs can only be transferred to another sponsor
            new_sponsor = ctx.active_sponsorships.get(
                self.source_id.to_bytes())
            if new_sponsor is None:
                self.set_inner_result(
                    rc.REVOKE_SPONSORSHIP_ONLY_TRANSFERABLE)
                return False

        # release the old payer
        if was_sponsored:
            sp_le = ltx.load(LedgerKey.account(old_sponsor))
            if sp_le is not None:
                v2 = ensure_account_ext_v2(sp_le.data.value)
                v2.numSponsoring = max(0, v2.numSponsoring - mult)
            if owner_id is not None:
                own_le = ltx.load(LedgerKey.account(owner_id))
                if own_le is not None:
                    v2 = ensure_account_ext_v2(own_le.data.value)
                    v2.numSponsored = max(0, v2.numSponsored - mult)

        if new_sponsor is not None:
            # transfer: the new sponsor pays
            sp_le = ltx.load(LedgerKey.account(new_sponsor))
            if sp_le is None or not _available_for_reserve(
                    header, sp_le.data.value, mult):
                self.set_inner_result(rc.REVOKE_SPONSORSHIP_LOW_RESERVE)
                return False
            v2 = ensure_account_ext_v2(sp_le.data.value)
            v2.numSponsoring += mult
            if owner_id is not None:
                own_le = ltx.load(LedgerKey.account(owner_id))
                ov2 = ensure_account_ext_v2(own_le.data.value)
                ov2.numSponsored += mult
            set_sponsoring_id(le, new_sponsor)
        else:
            # remove: the owner pays its own reserve again
            own_le = ltx.load(LedgerKey.account(owner_id))
            if own_le is None or not _available_for_reserve(
                    header, own_le.data.value, mult):
                self.set_inner_result(rc.REVOKE_SPONSORSHIP_LOW_RESERVE)
                return False
            set_sponsoring_id(le, None)
        self.set_inner_result(rc.REVOKE_SPONSORSHIP_SUCCESS)
        return True

    # ------------------------------------------------------------- signers --
    def _revoke_signer(self, ltx, ctx) -> bool:
        rc = RevokeSponsorshipResultCode
        header = ltx.load_header()
        target = self.body.value
        acc_le = ltx.load(LedgerKey.account(target.accountID))
        if acc_le is None:
            self.set_inner_result(rc.REVOKE_SPONSORSHIP_DOES_NOT_EXIST)
            return False
        acc: AccountEntry = acc_le.data.value
        idx = None
        for i, s in enumerate(acc.signers):
            if s.key.to_bytes() == target.signerKey.to_bytes():
                idx = i
                break
        if idx is None:
            self.set_inner_result(rc.REVOKE_SPONSORSHIP_DOES_NOT_EXIST)
            return False
        from ..sponsorship import ensure_account_ext_v2 as _v2
        v2 = _v2(acc)
        sponsors = v2.ext.value.signerSponsoringIDs \
            if v2.ext.disc == 2 else None
        old_sponsor = sponsors[idx] if sponsors is not None else None

        if old_sponsor is not None:
            if old_sponsor.to_bytes() != self.source_id.to_bytes():
                self.set_inner_result(rc.REVOKE_SPONSORSHIP_NOT_SPONSOR)
                return False
        else:
            if target.accountID.to_bytes() != self.source_id.to_bytes():
                self.set_inner_result(rc.REVOKE_SPONSORSHIP_NOT_SPONSOR)
                return False

        new_sponsor = ctx.sponsor_for(target.accountID)
        if old_sponsor is not None:
            sp_le = ltx.load(LedgerKey.account(old_sponsor))
            if sp_le is not None:
                sv2 = _v2(sp_le.data.value)
                sv2.numSponsoring = max(0, sv2.numSponsoring - 1)
            v2.numSponsored = max(0, v2.numSponsored - 1)
        if new_sponsor is not None:
            sp_le = ltx.load(LedgerKey.account(new_sponsor))
            if sp_le is None or not _available_for_reserve(
                    header, sp_le.data.value, 1):
                self.set_inner_result(rc.REVOKE_SPONSORSHIP_LOW_RESERVE)
                return False
            sv2 = _v2(sp_le.data.value)
            sv2.numSponsoring += 1
            v2.numSponsored += 1
            if sponsors is not None:
                sponsors[idx] = new_sponsor
        else:
            if not _available_for_reserve(header, acc, 1):
                self.set_inner_result(rc.REVOKE_SPONSORSHIP_LOW_RESERVE)
                return False
            if sponsors is not None:
                sponsors[idx] = None
        self.set_inner_result(rc.REVOKE_SPONSORSHIP_SUCCESS)
        return True
