"""Claimable balances: create, claim, clawback.

Reference: transactions/CreateClaimableBalanceOpFrame.cpp (balance id =
SHA256 of the ENVELOPE_TYPE_OP_ID preimage, relative predicates rebased
to absolute close time, clawback flag inherited from the source trust
line), ClaimClaimableBalanceOpFrame.cpp (predicate evaluation against
close time), ClawbackClaimableBalanceOpFrame.cpp.
"""

from __future__ import annotations

import struct
from typing import List, Optional

from ...crypto.sha import sha256
from ...xdr.ledger_entries import (AccountFlags, Asset, AssetType, Claimant,
                                   ClaimantType, ClaimantV0,
                                   ClaimPredicate, ClaimPredicateType,
                                   ClaimableBalanceEntry,
                                   ClaimableBalanceEntryExtensionV1,
                                   ClaimableBalanceID,
                                   ClaimableBalanceIDType,
                                   LedgerEntry, LedgerEntryType, LedgerKey,
                                   TrustLineFlags, _ClaimableBalanceEntryExt,
                                   _LedgerEntryData, _LedgerEntryExt)
from ...xdr.results import (ClaimClaimableBalanceResultCode,
                            ClawbackClaimableBalanceResultCode,
                            CreateClaimableBalanceResultCode)
from ...xdr.transaction import OperationType
from ...xdr.types import EnvelopeType, ExtensionPoint
from ...ledger.ledger_txn import LedgerTxn
from .. import tx_utils
from ..operation_frame import OperationFrame, register_op
from ..sponsorship import (SponsorshipResult,
                           create_entry_with_possible_sponsorship,
                           remove_entry_with_possible_sponsorship)
from ...xdr.results import OperationResultCode

# reference: ClaimableBalanceEntry v1 flags
CLAIMABLE_BALANCE_CLAWBACK_ENABLED_FLAG = 0x1

MAX_PREDICATE_DEPTH = 4


def operation_id(ctx, op_index: int) -> bytes:
    """SHA256(HashIDPreimage ENVELOPE_TYPE_OP_ID {sourceAccount, seqNum,
    opNum}) (reference: getBalanceID / HashIDPreimage)."""
    return sha256(
        struct.pack(">i", EnvelopeType.ENVELOPE_TYPE_OP_ID)
        + ctx.tx_source_id.to_bytes()
        + struct.pack(">q", ctx.tx_seq_num)
        + struct.pack(">I", op_index))


def validate_predicate(pred: ClaimPredicate, depth: int = 1) -> bool:
    """reference: validatePredicate — depth cap, arity, non-negative
    relative times."""
    if depth > MAX_PREDICATE_DEPTH:
        return False
    t = pred.disc
    if t == ClaimPredicateType.CLAIM_PREDICATE_UNCONDITIONAL:
        return True
    if t == ClaimPredicateType.CLAIM_PREDICATE_AND or \
            t == ClaimPredicateType.CLAIM_PREDICATE_OR:
        arms = list(pred.value)
        if len(arms) != 2:
            return False
        return all(validate_predicate(p, depth + 1) for p in arms)
    if t == ClaimPredicateType.CLAIM_PREDICATE_NOT:
        if pred.value is None:
            return False
        return validate_predicate(pred.value, depth + 1)
    if t == ClaimPredicateType.CLAIM_PREDICATE_BEFORE_ABSOLUTE_TIME:
        return pred.value >= 0
    if t == ClaimPredicateType.CLAIM_PREDICATE_BEFORE_RELATIVE_TIME:
        return pred.value >= 0
    return False


def rebase_predicate(pred: ClaimPredicate,
                     close_time: int) -> ClaimPredicate:
    """BEFORE_RELATIVE_TIME → BEFORE_ABSOLUTE_TIME(closeTime + rel)
    (reference: updatePredicatesForApply)."""
    t = pred.disc
    if t in (ClaimPredicateType.CLAIM_PREDICATE_AND,
             ClaimPredicateType.CLAIM_PREDICATE_OR):
        return ClaimPredicate(t, [rebase_predicate(p, close_time)
                                  for p in pred.value])
    if t == ClaimPredicateType.CLAIM_PREDICATE_NOT:
        return ClaimPredicate(t, rebase_predicate(pred.value, close_time))
    if t == ClaimPredicateType.CLAIM_PREDICATE_BEFORE_RELATIVE_TIME:
        when = min(close_time + pred.value, 2**63 - 1)
        return ClaimPredicate(
            ClaimPredicateType.CLAIM_PREDICATE_BEFORE_ABSOLUTE_TIME, when)
    return pred


def test_predicate(pred: ClaimPredicate, close_time: int) -> bool:
    """reference: evaluatePredicate at claim time."""
    t = pred.disc
    if t == ClaimPredicateType.CLAIM_PREDICATE_UNCONDITIONAL:
        return True
    if t == ClaimPredicateType.CLAIM_PREDICATE_AND:
        return all(test_predicate(p, close_time) for p in pred.value)
    if t == ClaimPredicateType.CLAIM_PREDICATE_OR:
        return any(test_predicate(p, close_time) for p in pred.value)
    if t == ClaimPredicateType.CLAIM_PREDICATE_NOT:
        return not test_predicate(pred.value, close_time)
    if t == ClaimPredicateType.CLAIM_PREDICATE_BEFORE_ABSOLUTE_TIME:
        return close_time < pred.value
    return False


@register_op(OperationType.CREATE_CLAIMABLE_BALANCE)
class CreateClaimableBalanceOpFrame(OperationFrame):

    def do_check_valid(self, header, ledger_version: int) -> bool:
        b = self.body
        rc = CreateClaimableBalanceResultCode
        if b.amount <= 0 or not tx_utils.is_asset_valid(b.asset) or \
                not b.claimants:
            self.set_inner_result(rc.CREATE_CLAIMABLE_BALANCE_MALFORMED)
            return False
        dests = set()
        for c in b.claimants:
            dest = c.value.destination.to_bytes()
            if dest in dests or not validate_predicate(c.value.predicate):
                self.set_inner_result(
                    rc.CREATE_CLAIMABLE_BALANCE_MALFORMED)
                return False
            dests.add(dest)
        return True

    def do_apply(self, ltx_outer, header_outer, ctx) -> bool:
        b = self.body
        rc = CreateClaimableBalanceResultCode
        with LedgerTxn(ltx_outer) as ltx:
            header = ltx.load_header()
            close_time = header.scpValue.closeTime

            # debit the source (reference: underfunded / trust checks)
            native = b.asset.disc == AssetType.ASSET_TYPE_NATIVE
            clawback = False
            if native:
                src_le = ltx.load(LedgerKey.account(self.source_id))
                if not tx_utils.add_balance_account(
                        header, src_le.data.value, -b.amount):
                    self.set_inner_result(
                        rc.CREATE_CLAIMABLE_BALANCE_UNDERFUNDED)
                    return False
            else:
                issuer = tx_utils.asset_issuer(b.asset)
                if issuer.to_bytes() == self.source_id.to_bytes():
                    # issuer mints; clawback follows the account flag
                    src_le = ltx.load(LedgerKey.account(self.source_id))
                    clawback = bool(src_le.data.value.flags &
                                    AccountFlags.AUTH_CLAWBACK_ENABLED_FLAG)
                else:
                    tl_le = tx_utils.load_trustline(ltx, self.source_id,
                                                    b.asset)
                    if tl_le is None:
                        self.set_inner_result(
                            rc.CREATE_CLAIMABLE_BALANCE_NO_TRUST)
                        return False
                    tl = tl_le.data.value
                    if not tx_utils.is_authorized(tl):
                        self.set_inner_result(
                            rc.CREATE_CLAIMABLE_BALANCE_NOT_AUTHORIZED)
                        return False
                    if not tx_utils.add_balance_trustline(tl, -b.amount):
                        self.set_inner_result(
                            rc.CREATE_CLAIMABLE_BALANCE_UNDERFUNDED)
                        return False
                    clawback = bool(
                        tl.flags &
                        TrustLineFlags.TRUSTLINE_CLAWBACK_ENABLED_FLAG)

            balance_id = ClaimableBalanceID(
                ClaimableBalanceIDType.CLAIMABLE_BALANCE_ID_TYPE_V0,
                operation_id(ctx, self.op_index))
            claimants = [
                Claimant(ClaimantType.CLAIMANT_TYPE_V0, ClaimantV0(
                    destination=c.value.destination,
                    predicate=rebase_predicate(c.value.predicate,
                                               close_time)))
                for c in b.claimants]
            ext = _ClaimableBalanceEntryExt(0)
            if clawback:
                ext = _ClaimableBalanceEntryExt(
                    1, ClaimableBalanceEntryExtensionV1(
                        ext=ExtensionPoint(0),
                        flags=CLAIMABLE_BALANCE_CLAWBACK_ENABLED_FLAG))
            entry = LedgerEntry(
                lastModifiedLedgerSeq=header.ledgerSeq,
                data=_LedgerEntryData(
                    LedgerEntryType.CLAIMABLE_BALANCE,
                    ClaimableBalanceEntry(
                        balanceID=balance_id, claimants=claimants,
                        asset=b.asset, amount=b.amount, ext=ext)),
                ext=_LedgerEntryExt(0))
            src_le = ltx.load(LedgerKey.account(self.source_id))
            res = create_entry_with_possible_sponsorship(
                ltx, header, entry, src_le, ctx)
            if res == SponsorshipResult.LOW_RESERVE:
                self.set_inner_result(
                    rc.CREATE_CLAIMABLE_BALANCE_LOW_RESERVE)
                return False
            if res != SponsorshipResult.SUCCESS:
                self.set_outer_result(
                    OperationResultCode.opTOO_MANY_SPONSORING)
                return False
            ltx.create(entry)
            self.set_inner_result(
                rc.CREATE_CLAIMABLE_BALANCE_SUCCESS, balance_id)
            ltx.commit()
            return True


@register_op(OperationType.CLAIM_CLAIMABLE_BALANCE)
class ClaimClaimableBalanceOpFrame(OperationFrame):

    def do_check_valid(self, header, ledger_version: int) -> bool:
        return True

    def do_apply(self, ltx_outer, header_outer, ctx) -> bool:
        b = self.body
        rc = ClaimClaimableBalanceResultCode
        with LedgerTxn(ltx_outer) as ltx:
            header = ltx.load_header()
            key = LedgerKey.claimable_balance(b.balanceID)
            le = ltx.load(key)
            if le is None:
                self.set_inner_result(
                    rc.CLAIM_CLAIMABLE_BALANCE_DOES_NOT_EXIST)
                return False
            cb: ClaimableBalanceEntry = le.data.value
            claimant = None
            for c in cb.claimants:
                if c.value.destination.to_bytes() == \
                        self.source_id.to_bytes():
                    claimant = c.value
                    break
            if claimant is None or not test_predicate(
                    claimant.predicate, header.scpValue.closeTime):
                self.set_inner_result(
                    rc.CLAIM_CLAIMABLE_BALANCE_CANNOT_CLAIM)
                return False

            # credit the claimant
            native = cb.asset.disc == AssetType.ASSET_TYPE_NATIVE
            if native:
                src_le = ltx.load(LedgerKey.account(self.source_id))
                if not tx_utils.add_balance_account(
                        header, src_le.data.value, cb.amount):
                    self.set_inner_result(
                        rc.CLAIM_CLAIMABLE_BALANCE_LINE_FULL)
                    return False
            else:
                issuer = tx_utils.asset_issuer(cb.asset)
                if issuer.to_bytes() != self.source_id.to_bytes():
                    tl_le = tx_utils.load_trustline(ltx, self.source_id,
                                                    cb.asset)
                    if tl_le is None:
                        self.set_inner_result(
                            rc.CLAIM_CLAIMABLE_BALANCE_NO_TRUST)
                        return False
                    tl = tl_le.data.value
                    if not tx_utils.is_authorized(tl):
                        self.set_inner_result(
                            rc.CLAIM_CLAIMABLE_BALANCE_NOT_AUTHORIZED)
                        return False
                    if not tx_utils.add_balance_trustline(tl, cb.amount):
                        self.set_inner_result(
                            rc.CLAIM_CLAIMABLE_BALANCE_LINE_FULL)
                        return False

            remove_entry_with_possible_sponsorship(
                ltx, header, le,
                ltx.load(LedgerKey.account(self.source_id)))
            ltx.erase(key)
            self.set_inner_result(rc.CLAIM_CLAIMABLE_BALANCE_SUCCESS)
            ltx.commit()
            return True


@register_op(OperationType.CLAWBACK_CLAIMABLE_BALANCE)
class ClawbackClaimableBalanceOpFrame(OperationFrame):

    def do_check_valid(self, header, ledger_version: int) -> bool:
        return True

    def do_apply(self, ltx_outer, header_outer, ctx) -> bool:
        b = self.body
        rc = ClawbackClaimableBalanceResultCode
        with LedgerTxn(ltx_outer) as ltx:
            header = ltx.load_header()
            key = LedgerKey.claimable_balance(b.balanceID)
            le = ltx.load(key)
            if le is None:
                self.set_inner_result(
                    rc.CLAWBACK_CLAIMABLE_BALANCE_DOES_NOT_EXIST)
                return False
            cb: ClaimableBalanceEntry = le.data.value
            issuer = tx_utils.asset_issuer(cb.asset)
            if issuer is None or \
                    issuer.to_bytes() != self.source_id.to_bytes():
                self.set_inner_result(
                    rc.CLAWBACK_CLAIMABLE_BALANCE_NOT_ISSUER)
                return False
            flags = cb.ext.value.flags if cb.ext.disc == 1 else 0
            if not (flags & CLAIMABLE_BALANCE_CLAWBACK_ENABLED_FLAG):
                self.set_inner_result(
                    rc.CLAWBACK_CLAIMABLE_BALANCE_NOT_CLAWBACK_ENABLED)
                return False
            remove_entry_with_possible_sponsorship(
                ltx, header, le,
                ltx.load(LedgerKey.account(self.source_id)))
            ltx.erase(key)
            self.set_inner_result(rc.CLAWBACK_CLAIMABLE_BALANCE_SUCCESS)
            ltx.commit()
            return True
