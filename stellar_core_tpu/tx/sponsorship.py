"""Sponsorship accounting and reserve-checked entry lifecycle.

Reference: transactions/SponsorshipUtils.{h,cpp} — every subentry/account/
claimable-balance creation goes through `create_entry_with_possible_
sponsorship`, which decides who pays the base-reserve (owner or the active
sponsor from a BeginSponsoringFutureReserves scope), bumps numSubEntries /
numSponsoring / numSponsored, and enforces the reserve floor and count
limits. Removal reverses it.

Design difference from the reference: the active-sponsorship scopes are NOT
modelled as internal ledger entries (reference: LedgerTxn SPONSORSHIP
internal types); they live on the per-transaction `ApplyContext`, because
ops that fail never commit their LedgerTxn, which gives the same rollback
semantics with far less machinery.
"""

from __future__ import annotations

from enum import IntEnum
from typing import Dict, List, Optional

from ..util.checks import releaseAssert
from ..xdr.ledger_entries import (AccountEntry, AccountEntryExtensionV1,
                                  AccountEntryExtensionV2,
                                  AccountEntryExtensionV3, LedgerEntry,
                                  LedgerEntryExtensionV1, LedgerEntryType,
                                  Liabilities, TrustLineAsset)
from ..xdr.ledger import LedgerHeader
from ..xdr.types import AccountID, PublicKey
from . import tx_utils

ACCOUNT_SUBENTRY_LIMIT = 1000
MAX_SIGNERS = 20


class SponsorshipResult(IntEnum):
    SUCCESS = 0
    LOW_RESERVE = -1
    TOO_MANY_SUBENTRIES = -2
    TOO_MANY_SPONSORING = -3
    TOO_MANY_SPONSORED = -4


class ApplyContext:
    """Per-transaction apply state shared by its operations: the active
    sponsorship scopes (sponsored account id bytes -> sponsor AccountID)
    plus identifiers ops need for hash-derived ids."""

    def __init__(self, network_id: bytes = b"\x00" * 32,
                 tx_source_id: Optional[AccountID] = None,
                 tx_seq_num: int = 0):
        self.network_id = network_id
        self.tx_source_id = tx_source_id
        self.tx_seq_num = tx_seq_num
        self.op_index = 0
        self.active_sponsorships: Dict[bytes, AccountID] = {}
        # Soroban apply state (set by TransactionFrame for contract txs)
        self.soroban_data = None
        self.fee_source_id: Optional[AccountID] = tx_source_id
        self.tx_size_bytes = 0
        self.verify = None
        self.soroban_events = []
        self.soroban_return_value = None
        self.soroban_diagnostic_events = []
        self.soroban_diagnostics_in_success = True

    def sponsor_for(self, account_id: AccountID) -> Optional[AccountID]:
        return self.active_sponsorships.get(account_id.to_bytes())


# ------------------------------------------------------- account extensions --

def ensure_account_ext_v1(acc: AccountEntry) -> AccountEntryExtensionV1:
    if acc.ext.disc == 0:
        acc.ext = type(acc.ext)(1, AccountEntryExtensionV1(
            liabilities=Liabilities(buying=0, selling=0)))
    return acc.ext.value


def ensure_account_ext_v2(acc: AccountEntry) -> AccountEntryExtensionV2:
    v1 = ensure_account_ext_v1(acc)
    if v1.ext.disc == 0:
        v2 = AccountEntryExtensionV2(
            numSponsored=0, numSponsoring=0,
            signerSponsoringIDs=[None] * len(acc.signers))
        v1.ext = type(v1.ext)(2, v2)
    v2 = v1.ext.value
    # keep the parallel signer-sponsor array sized with signers
    while len(v2.signerSponsoringIDs) < len(acc.signers):
        v2.signerSponsoringIDs.append(None)
    return v2


def ensure_account_ext_v3(acc: AccountEntry) -> AccountEntryExtensionV3:
    v2 = ensure_account_ext_v2(acc)
    if v2.ext.disc == 0:
        v2.ext = type(v2.ext)(3, AccountEntryExtensionV3(
            seqLedger=0, seqTime=0))
    return v2.ext.value


def num_sponsoring(acc: AccountEntry) -> int:
    if acc.ext.disc == 1 and acc.ext.value.ext.disc == 2:
        return acc.ext.value.ext.value.numSponsoring
    return 0


def num_sponsored(acc: AccountEntry) -> int:
    if acc.ext.disc == 1 and acc.ext.value.ext.disc == 2:
        return acc.ext.value.ext.value.numSponsored
    return 0


def account_seq_time(acc: AccountEntry) -> int:
    if (acc.ext.disc == 1 and acc.ext.value.ext.disc == 2
            and acc.ext.value.ext.value.ext.disc == 3):
        return acc.ext.value.ext.value.ext.value.seqTime
    return 0


def account_seq_ledger(acc: AccountEntry) -> int:
    if (acc.ext.disc == 1 and acc.ext.value.ext.disc == 2
            and acc.ext.value.ext.value.ext.disc == 3):
        return acc.ext.value.ext.value.ext.value.seqLedger
    return 0


# -------------------------------------------------------- entry sponsorship --

def is_sponsored(entry: LedgerEntry) -> bool:
    return entry.ext.disc == 1 and entry.ext.value.sponsoringID is not None


def get_sponsoring_id(entry: LedgerEntry) -> Optional[AccountID]:
    if entry.ext.disc == 1:
        return entry.ext.value.sponsoringID
    return None


def set_sponsoring_id(entry: LedgerEntry,
                      sponsor: Optional[AccountID]) -> None:
    if sponsor is None:
        if entry.ext.disc == 1:
            entry.ext.value.sponsoringID = None
        return
    if entry.ext.disc == 0:
        entry.ext = type(entry.ext)(1, LedgerEntryExtensionV1(
            sponsoringID=sponsor))
    else:
        entry.ext.value.sponsoringID = sponsor


def reserve_multiplier(entry: LedgerEntry) -> int:
    """How many base reserves the entry costs (reference:
    SponsorshipUtils computeMultiplier)."""
    t = entry.data.disc
    if t == LedgerEntryType.ACCOUNT:
        return 2
    if t == LedgerEntryType.CLAIMABLE_BALANCE:
        return len(entry.data.value.claimants)
    if t == LedgerEntryType.TRUSTLINE:
        tla: TrustLineAsset = entry.data.value.asset
        from ..xdr.ledger_entries import AssetType
        return 2 if tla.disc == AssetType.ASSET_TYPE_POOL_SHARE else 1
    if t in (LedgerEntryType.OFFER, LedgerEntryType.DATA):
        return 1
    releaseAssert(False, f"no reserve multiplier for {t!r}")


def _is_subentry(entry: LedgerEntry) -> bool:
    return entry.data.disc in (LedgerEntryType.TRUSTLINE,
                               LedgerEntryType.OFFER,
                               LedgerEntryType.DATA)


def _subentry_count(entry: LedgerEntry) -> int:
    """Pool-share trustlines count as 2 subentries (reference:
    ChangeTrustOpFrame / SponsorshipUtils)."""
    if entry.data.disc == LedgerEntryType.TRUSTLINE:
        from ..xdr.ledger_entries import AssetType
        if entry.data.value.asset.disc == AssetType.ASSET_TYPE_POOL_SHARE:
            return 2
    return 1


def _available_for_reserve(header: LedgerHeader, acc: AccountEntry,
                           extra_reserves: int) -> bool:
    """Can `acc` afford `extra_reserves` more base reserves on top of its
    current minimum balance + selling liabilities?"""
    needed = (tx_utils.min_balance(header, acc)
              + extra_reserves * header.baseReserve
              + tx_utils.selling_liabilities_account(acc))
    return acc.balance >= needed


def create_entry_with_possible_sponsorship(
        ltx, header: LedgerHeader, entry: LedgerEntry,
        owner_le: Optional[LedgerEntry],
        ctx: Optional[ApplyContext]) -> SponsorshipResult:
    """Reserve- and count-check the creation of `entry`, mutating the
    owner (and sponsor) accounts. Caller still calls ltx.create(entry).

    owner_le: the account LedgerEntry that owns the new entry (None only
    for claimable balances, which have no owning account after creation).
    """
    owner_acc: Optional[AccountEntry] = \
        owner_le.data.value if owner_le is not None else None
    mult = reserve_multiplier(entry)

    sponsor_id = None
    if ctx is not None:
        if entry.data.disc == LedgerEntryType.ACCOUNT:
            sponsor_id = ctx.sponsor_for(entry.data.value.accountID)
        elif entry.data.disc == LedgerEntryType.CLAIMABLE_BALANCE:
            # the creating op's source is "owner" for scope lookup
            if owner_acc is not None:
                sponsor_id = ctx.sponsor_for(owner_acc.accountID)
        elif owner_acc is not None:
            sponsor_id = ctx.sponsor_for(owner_acc.accountID)

    if entry.data.disc == LedgerEntryType.CLAIMABLE_BALANCE \
            and sponsor_id is None and owner_acc is not None:
        # claimable balances are always sponsored by their creator
        sponsor_id = owner_acc.accountID

    if sponsor_id is not None:
        from ..xdr.ledger_entries import LedgerKey
        sponsor_le = ltx.load(LedgerKey.account(sponsor_id))
        releaseAssert(sponsor_le is not None, "sponsor account must exist")
        sponsor_acc: AccountEntry = sponsor_le.data.value
        sp_v2 = ensure_account_ext_v2(sponsor_acc)
        if sp_v2.numSponsoring > 0xFFFFFFFF - mult:
            return SponsorshipResult.TOO_MANY_SPONSORING
        if not _available_for_reserve(header, sponsor_acc, mult):
            return SponsorshipResult.LOW_RESERVE
        if owner_acc is not None and \
                entry.data.disc != LedgerEntryType.ACCOUNT and \
                entry.data.disc != LedgerEntryType.CLAIMABLE_BALANCE:
            own_v2 = ensure_account_ext_v2(owner_acc)
            if own_v2.numSponsored > 0xFFFFFFFF - mult:
                return SponsorshipResult.TOO_MANY_SPONSORED
            own_v2.numSponsored += mult
        elif entry.data.disc == LedgerEntryType.ACCOUNT:
            new_acc: AccountEntry = entry.data.value
            nv2 = ensure_account_ext_v2(new_acc)
            nv2.numSponsored += mult
        sp_v2.numSponsoring += mult
        set_sponsoring_id(entry, sponsor_id)
    else:
        releaseAssert(owner_acc is not None,
                      "unsponsored entry needs an owner for the reserve")
        if entry.data.disc != LedgerEntryType.ACCOUNT and \
                not _available_for_reserve(header, owner_acc, mult):
            return SponsorshipResult.LOW_RESERVE

    if owner_acc is not None and _is_subentry(entry):
        cnt = _subentry_count(entry)
        if owner_acc.numSubEntries + cnt > ACCOUNT_SUBENTRY_LIMIT:
            return SponsorshipResult.TOO_MANY_SUBENTRIES
        owner_acc.numSubEntries += cnt
    return SponsorshipResult.SUCCESS


def remove_entry_with_possible_sponsorship(
        ltx, header: LedgerHeader, entry: LedgerEntry,
        owner_le: Optional[LedgerEntry]) -> None:
    """Reverse of create: decrement counts on owner and sponsor. Caller
    erases the entry afterwards."""
    mult = reserve_multiplier(entry)
    sponsor_id = get_sponsoring_id(entry)
    if sponsor_id is not None:
        from ..xdr.ledger_entries import LedgerKey
        sponsor_le = ltx.load(LedgerKey.account(sponsor_id))
        if sponsor_le is not None:
            sp_acc: AccountEntry = sponsor_le.data.value
            v2 = ensure_account_ext_v2(sp_acc)
            v2.numSponsoring = max(0, v2.numSponsoring - mult)
        if owner_le is not None and \
                entry.data.disc != LedgerEntryType.CLAIMABLE_BALANCE:
            own_acc: AccountEntry = owner_le.data.value
            v2 = ensure_account_ext_v2(own_acc)
            v2.numSponsored = max(0, v2.numSponsored - mult)
    if owner_le is not None and _is_subentry(entry):
        owner_le.data.value.numSubEntries -= _subentry_count(entry)


# -------------------------------------------------------- signer sponsorship --

def create_signer_with_possible_sponsorship(
        ltx, header: LedgerHeader, owner_le: LedgerEntry,
        ctx: Optional[ApplyContext]) -> SponsorshipResult:
    """Reserve/count accounting for adding one signer to owner (the
    caller inserts into acc.signers and the parallel sponsoring array)."""
    owner_acc: AccountEntry = owner_le.data.value
    sponsor_id = ctx.sponsor_for(owner_acc.accountID) if ctx else None
    if sponsor_id is not None and \
            sponsor_id.to_bytes() != owner_acc.accountID.to_bytes():
        from ..xdr.ledger_entries import LedgerKey
        sponsor_le = ltx.load(LedgerKey.account(sponsor_id))
        releaseAssert(sponsor_le is not None, "sponsor account must exist")
        sp_acc: AccountEntry = sponsor_le.data.value
        sp_v2 = ensure_account_ext_v2(sp_acc)
        if sp_v2.numSponsoring >= 0xFFFFFFFF:
            return SponsorshipResult.TOO_MANY_SPONSORING
        if not _available_for_reserve(header, sp_acc, 1):
            return SponsorshipResult.LOW_RESERVE
        own_v2 = ensure_account_ext_v2(owner_acc)
        if own_v2.numSponsored >= 0xFFFFFFFF:
            return SponsorshipResult.TOO_MANY_SPONSORED
        own_v2.numSponsored += 1
        sp_v2.numSponsoring += 1
        # caller records sponsor_id in signerSponsoringIDs at insert index
    else:
        if not _available_for_reserve(header, owner_acc, 1):
            return SponsorshipResult.LOW_RESERVE
        sponsor_id = None
    if owner_acc.numSubEntries + 1 > ACCOUNT_SUBENTRY_LIMIT:
        return SponsorshipResult.TOO_MANY_SUBENTRIES
    owner_acc.numSubEntries += 1
    return SponsorshipResult.SUCCESS


def remove_signer_sponsorship(ltx, owner_le: LedgerEntry,
                              signer_index: int) -> None:
    """Undo counts for removing signer at `signer_index` (caller pops from
    both parallel arrays afterwards)."""
    owner_acc: AccountEntry = owner_le.data.value
    sponsor_id = None
    if owner_acc.ext.disc == 1 and owner_acc.ext.value.ext.disc == 2:
        ids = owner_acc.ext.value.ext.value.signerSponsoringIDs
        if signer_index < len(ids):
            sponsor_id = ids[signer_index]
    if sponsor_id is not None:
        from ..xdr.ledger_entries import LedgerKey
        sponsor_le = ltx.load(LedgerKey.account(sponsor_id))
        if sponsor_le is not None:
            v2 = ensure_account_ext_v2(sponsor_le.data.value)
            v2.numSponsoring = max(0, v2.numSponsoring - 1)
        own_v2 = ensure_account_ext_v2(owner_acc)
        own_v2.numSponsored = max(0, own_v2.numSponsored - 1)
    owner_acc.numSubEntries -= 1
