"""Exchange arithmetic for the order book.

Reference: transactions/OfferExchange.cpp (exchangeV10 family) and the
bigDivide helpers in util/types.cpp. Python's arbitrary-precision ints
replace the reference's uint128 machinery; every result is still checked
into int64 like the reference's bigDivide overflow contract.

All semantics are value-preserving: the ledger must compute the exact
same traded amounts as the reference or consensus diverges.
"""

from __future__ import annotations

from enum import IntEnum
from typing import NamedTuple

from ..util.checks import releaseAssert
from ..xdr.ledger_entries import Price

INT64_MAX = 2**63 - 1


class Rounding(IntEnum):
    ROUND_DOWN = 0
    ROUND_UP = 1


class RoundingType(IntEnum):
    NORMAL = 0
    PATH_PAYMENT_STRICT_SEND = 1
    PATH_PAYMENT_STRICT_RECEIVE = 2


def big_divide(a: int, b: int, c: int, rounding: Rounding) -> int:
    """(a * b) / c with explicit rounding; raises on int64 overflow
    (reference: util/types.cpp bigDivideOrThrow)."""
    releaseAssert(c > 0, "bigDivide by non-positive")
    x = a * b
    if rounding == Rounding.ROUND_DOWN:
        res = x // c
    else:
        res = (x + c - 1) // c
    if res > INT64_MAX or res < 0:
        raise OverflowError("bigDivide overflow")
    return res


def big_divide_128(value: int, c: int, rounding: Rounding) -> int:
    return big_divide(value, 1, c, rounding)


class ExchangeResultV10(NamedTuple):
    num_wheat_received: int
    num_sheep_send: int
    wheat_stays: bool


def _offer_value(price_n: int, price_d: int, max_send: int,
                 max_receive: int) -> int:
    return min(max_send * price_n, max_receive * price_d)


def exchange_v10_without_price_error_thresholds(
        price: Price, max_wheat_send: int, max_wheat_receive: int,
        max_sheep_send: int, max_sheep_receive: int,
        round_type: RoundingType) -> ExchangeResultV10:
    wheat_value = _offer_value(price.n, price.d,
                               max_wheat_send, max_sheep_receive)
    sheep_value = _offer_value(price.d, price.n,
                               max_sheep_send, max_wheat_receive)
    wheat_stays = wheat_value > sheep_value

    if wheat_stays:
        if round_type == RoundingType.PATH_PAYMENT_STRICT_SEND:
            wheat_receive = sheep_value // price.n
            sheep_send = min(max_sheep_send, max_sheep_receive)
        elif price.n > price.d or \
                round_type == RoundingType.PATH_PAYMENT_STRICT_RECEIVE:
            wheat_receive = sheep_value // price.n
            sheep_send = big_divide(wheat_receive, price.n, price.d,
                                    Rounding.ROUND_UP)
        else:
            sheep_send = sheep_value // price.d
            wheat_receive = big_divide(sheep_send, price.d, price.n,
                                       Rounding.ROUND_DOWN)
    else:
        if price.n > price.d:
            wheat_receive = wheat_value // price.n
            sheep_send = big_divide(wheat_receive, price.n, price.d,
                                    Rounding.ROUND_DOWN)
        else:
            sheep_send = wheat_value // price.d
            wheat_receive = big_divide(sheep_send, price.d, price.n,
                                       Rounding.ROUND_UP)

    releaseAssert(0 <= wheat_receive <= min(max_wheat_receive,
                                            max_wheat_send),
                  "wheatReceive out of bounds")
    releaseAssert(0 <= sheep_send <= min(max_sheep_receive, max_sheep_send),
                  "sheepSend out of bounds")
    return ExchangeResultV10(wheat_receive, sheep_send, wheat_stays)


def check_price_error_bound(price: Price, wheat_receive: int,
                            sheep_send: int, can_favor_wheat: bool) -> bool:
    """Both sides get a price within 1% of the crossed price
    (reference: OfferExchange.cpp checkPriceErrorBound)."""
    lhs = 100 * price.n * wheat_receive
    rhs = 100 * price.d * sheep_send
    if can_favor_wheat and rhs > lhs:
        return True
    return abs(lhs - rhs) <= price.n * wheat_receive


def apply_price_error_thresholds(
        price: Price, wheat_receive: int, sheep_send: int,
        wheat_stays: bool, round_type: RoundingType) -> ExchangeResultV10:
    if wheat_receive > 0 and sheep_send > 0:
        wheat_value = wheat_receive * price.n
        sheep_value = sheep_send * price.d
        if wheat_stays:
            releaseAssert(sheep_value >= wheat_value,
                          "favored sheep when wheat stays")
        else:
            releaseAssert(sheep_value <= wheat_value,
                          "favored wheat when sheep stays")
        if round_type == RoundingType.NORMAL:
            if not check_price_error_bound(price, wheat_receive, sheep_send,
                                           False):
                wheat_receive = 0
                sheep_send = 0
        else:
            releaseAssert(
                check_price_error_bound(price, wheat_receive, sheep_send,
                                        True),
                "exceeded price error bound")
    else:
        # one side rounds to zero: no trade for NORMAL / STRICT_RECEIVE;
        # STRICT_SEND may send sheep for no wheat (reference comment)
        if round_type != RoundingType.PATH_PAYMENT_STRICT_SEND:
            wheat_receive = 0
            sheep_send = 0
    return ExchangeResultV10(wheat_receive, sheep_send, wheat_stays)


def exchange_v10(price: Price, max_wheat_send: int, max_wheat_receive: int,
                 max_sheep_send: int, max_sheep_receive: int,
                 round_type: RoundingType) -> ExchangeResultV10:
    before = exchange_v10_without_price_error_thresholds(
        price, max_wheat_send, max_wheat_receive, max_sheep_send,
        max_sheep_receive, round_type)
    return apply_price_error_thresholds(
        price, before.num_wheat_received, before.num_sheep_send,
        before.wheat_stays, round_type)


def adjust_offer_amount(price: Price, max_wheat_send: int,
                        max_sheep_receive: int) -> int:
    """Largest executable offer amount (reference: adjustOffer)."""
    res = exchange_v10(price, max_wheat_send, INT64_MAX, INT64_MAX,
                       max_sheep_receive, RoundingType.NORMAL)
    return res.num_wheat_received


def offer_selling_liabilities(offer_entry) -> int:
    """reference: TransactionUtils.cpp:926-941 getOfferSellingLiabilities"""
    res = exchange_v10_without_price_error_thresholds(
        offer_entry.price, offer_entry.amount, INT64_MAX, INT64_MAX,
        INT64_MAX, RoundingType.NORMAL)
    return res.num_wheat_received


def offer_buying_liabilities(offer_entry) -> int:
    """reference: TransactionUtils.cpp:902-916 getOfferBuyingLiabilities"""
    res = exchange_v10_without_price_error_thresholds(
        offer_entry.price, offer_entry.amount, INT64_MAX, INT64_MAX,
        INT64_MAX, RoundingType.NORMAL)
    return res.num_sheep_send
