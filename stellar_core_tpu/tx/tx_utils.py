"""Shared ledger-mutation helpers.

Reference: transactions/TransactionUtils.{h,cpp} — account/trustline
loading, balance changes with liability clamps, reserve math, threshold
accessors, sequence-number rules. Money is int64 stroops throughout;
all arithmetic is checked against the int64 range like the reference's
util/types.h addBalance helpers.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from ..util.checks import releaseAssert
from ..xdr.ledger_entries import (AccountEntry, AccountFlags, Asset,
                                  AssetType, LedgerEntry, LedgerEntryType,
                                  LedgerKey, ThresholdIndexes,
                                  TrustLineAsset, TrustLineEntry,
                                  TrustLineFlags, _LedgerEntryData)
from ..xdr.ledger import LedgerHeader
from ..xdr.types import PublicKey, SignerKey, SignerKeyType

INT64_MAX = 2**63 - 1
INT64_MIN = -(2**63)

# protocol constants (reference: LedgerManager::GENESIS_* and header)
GENESIS_LEDGER_BASE_FEE = 100
GENESIS_LEDGER_BASE_RESERVE = 100_000_000


def in_int64(v: int) -> bool:
    return INT64_MIN <= v <= INT64_MAX


# ------------------------------------------------------------- thresholds --

def threshold(account: AccountEntry, idx: ThresholdIndexes) -> int:
    return account.thresholds[idx]


def get_signers_with_master(
        account: AccountEntry) -> List[Tuple[SignerKey, int]]:
    """All signers incl. the implicit master key at masterWeight."""
    out: List[Tuple[SignerKey, int]] = []
    mw = account.thresholds[ThresholdIndexes.THRESHOLD_MASTER_WEIGHT]
    if mw > 0:
        out.append((SignerKey(SignerKeyType.SIGNER_KEY_TYPE_ED25519,
                              account.accountID.value), mw))
    for s in account.signers:
        out.append((s.key, s.weight))
    return out


# ---------------------------------------------------------------- reserve --

def min_balance(header: LedgerHeader, account: AccountEntry) -> int:
    """(2 + numSubEntries + numSponsoring - numSponsored) * baseReserve
    (reference: LedgerTxnHeader::getMinBalance / getAvailableBalance)."""
    sponsoring = sponsored = 0
    ext = account.ext
    if ext.disc == 1 and ext.value.ext.disc == 2:
        v2 = ext.value.ext.value
        sponsoring, sponsored = v2.numSponsoring, v2.numSponsored
    count = 2 + account.numSubEntries + sponsoring - sponsored
    return count * header.baseReserve


def available_balance(header: LedgerHeader, account: AccountEntry) -> int:
    liab = selling_liabilities_account(account)
    return account.balance - min_balance(header, account) - liab


def header_flags(header: LedgerHeader) -> int:
    """LedgerHeader ext-v1 flags (reference: getHeaderFlags) — the
    DISABLE_LIQUIDITY_POOL_* bits voted in via LEDGER_UPGRADE_FLAGS."""
    return header.ext.value.flags if header.ext.disc == 1 else 0


def selling_liabilities_account(account: AccountEntry) -> int:
    if account.ext.disc == 1:
        return account.ext.value.liabilities.selling
    return 0


def buying_liabilities_account(account: AccountEntry) -> int:
    if account.ext.disc == 1:
        return account.ext.value.liabilities.buying
    return 0


# ---------------------------------------------------------------- balance --

def add_balance_account(header: LedgerHeader, account: AccountEntry,
                        delta: int) -> bool:
    """Clamped balance change; False (and no change) if it would break
    the reserve floor, liabilities, or int64."""
    new = account.balance + delta
    if not in_int64(new):
        return False
    if delta < 0:
        if new < min_balance(header, account) + \
                selling_liabilities_account(account):
            return False
    else:
        if new > INT64_MAX - buying_liabilities_account(account):
            return False
    account.balance = new
    return True


def add_balance_trustline(tl: TrustLineEntry, delta: int) -> bool:
    new = tl.balance + delta
    if not in_int64(new) or new < 0:
        return False
    if delta < 0:
        if new < _tl_selling_liabilities(tl):
            return False
    else:
        if new > tl.limit - _tl_buying_liabilities(tl):
            return False
    tl.balance = new
    return True


def _tl_selling_liabilities(tl: TrustLineEntry) -> int:
    if tl.ext.disc == 1:
        return tl.ext.value.liabilities.selling
    return 0


def _tl_buying_liabilities(tl: TrustLineEntry) -> int:
    if tl.ext.disc == 1:
        return tl.ext.value.liabilities.buying
    return 0


def max_receive_trustline(tl: TrustLineEntry) -> int:
    return tl.limit - tl.balance - _tl_buying_liabilities(tl)


def is_authorized(tl: TrustLineEntry) -> bool:
    return bool(tl.flags & TrustLineFlags.AUTHORIZED_FLAG)


def is_authorized_to_maintain_liabilities(tl: TrustLineEntry) -> bool:
    return bool(tl.flags & (
        TrustLineFlags.AUTHORIZED_FLAG |
        TrustLineFlags.AUTHORIZED_TO_MAINTAIN_LIABILITIES_FLAG))


def is_string_valid(s: bytes) -> bool:
    """No control characters (reference: util/types.cpp isStringValid)."""
    return all(c >= 0x20 and c != 0x7F for c in s)


# ----------------------------------------------------------------- assets --

def is_asset_valid(asset: Asset) -> bool:
    """Reference util/types.cpp isAssetValid: code chars must be
    [a-zA-Z0-9], zero-padded at the tail only; ALPHANUM4 codes are 1-4
    chars, ALPHANUM12 codes must be >4 chars."""
    if asset.disc == AssetType.ASSET_TYPE_NATIVE:
        return True
    code = asset.value.assetCode
    body = code.rstrip(b"\x00")
    if not body or b"\x00" in body:
        return False
    if asset.disc == AssetType.ASSET_TYPE_CREDIT_ALPHANUM12 and len(body) <= 4:
        return False
    return all(chr(c).isalnum() and c < 128 for c in body)


def asset_issuer(asset: Asset) -> Optional[PublicKey]:
    if asset.disc == AssetType.ASSET_TYPE_NATIVE:
        return None
    return asset.value.issuer


# ---------------------------------------------------------------- loaders --

def load_account(ltx, account_id: PublicKey) -> Optional[LedgerEntry]:
    # LedgerKey.account is interned with memoized bytes — no per-load
    # key serialization cost
    return ltx.load(LedgerKey.account(account_id))


def load_trustline(ltx, account_id: PublicKey,
                   asset: Asset) -> Optional[LedgerEntry]:
    tla = TrustLineAsset.from_asset(asset)
    return ltx.load(LedgerKey.trust_line(account_id, tla))


def account_entry(le: LedgerEntry) -> AccountEntry:
    releaseAssert(le.data.disc == LedgerEntryType.ACCOUNT, "not an account")
    return le.data.value


def make_account_ledger_entry(account_id: PublicKey, balance: int,
                              seq_num: int) -> LedgerEntry:
    ae = AccountEntry(accountID=account_id, balance=balance,
                      seqNum=seq_num,
                      thresholds=bytes([1, 0, 0, 0]))
    return LedgerEntry(lastModifiedLedgerSeq=0,
                       data=_LedgerEntryData(LedgerEntryType.ACCOUNT, ae))


# --------------------------------------------------------------- seqnums --

def starting_sequence_number(ledger_seq: int) -> int:
    """New accounts start at ledgerSeq << 32 (reference:
    getStartingSequenceNumber)."""
    return ledger_seq << 32


def is_bad_seq(account: AccountEntry, tx_seq: int) -> bool:
    return tx_seq <= account.seqNum or tx_seq > INT64_MAX
