"""Hash-keyed flood-propagation tracking (the mesh observatory core).

Every transaction frame and SCP envelope already carries a stable
hash (tx contents hash / sha256 of the flooded message), so each node
can record first-seen / send / recv / admitted / externalized instants
keyed by that hash with NO wire-format change — the Dapper insight
(PAPERS.md, Sigelman et al. 2010) applied to a gossip mesh: the
message id IS the trace id.

Always-on cost: one dict upsert per flood event into a bounded stamp
map — the same policy `ledger.transaction.e2e` uses (TTL prune past a
size threshold, `tracing.stamps.dropped` counts evictions), so a
never-externalized flood cannot grow memory. While a flight-recorder
trace is on, the overlay ALSO emits `flood.send`/`flood.recv`
instants carrying the hash; `util/tracemerge.py` stitches those into
cross-node flow chains.

Duplicate accounting answers ROADMAP item 3's question — how much of
the wire path is redundant delivery: `overlay.flood.unique` vs
`overlay.flood.duplicate` counters (metrics route + Prometheus),
per-peer `duplicates` on the `peers` route, and a redundancy ratio in
`report()` (surfaced by `clusterstatus` and the TPSM/TPSMT bench
artifacts as the before-picture for pull-mode flooding).
"""

from __future__ import annotations

import time
from typing import Dict, Optional


class _Stamp:
    __slots__ = ("first_seen", "recvs", "sends", "admitted",
                 "externalized")

    def __init__(self, now: float):
        self.first_seen = now
        self.recvs = 0
        self.sends = 0
        self.admitted: Optional[float] = None
        self.externalized: Optional[float] = None


class PropagationTracker:
    # mirror of Herder.TX_E2E_STAMP_TTL_SECONDS / _TX_E2E_PRUNE_THRESHOLD:
    # stamps older than the TTL are dropped once the map crosses the
    # threshold (banned / never-externalized floods must not accumulate)
    STAMP_TTL_SECONDS = 300.0
    PRUNE_THRESHOLD = 10_000

    def __init__(self, metrics=None):
        self._stamps: Dict[bytes, _Stamp] = {}
        self.unique = 0
        self.duplicates = 0
        if metrics is not None:
            self._dropped_counter = metrics.new_counter(
                "tracing.stamps.dropped")
            self._uniq_counter = metrics.new_counter(
                "overlay.flood.unique")
            self._dup_counter = metrics.new_counter(
                "overlay.flood.duplicate")
        else:
            self._dropped_counter = None
            self._uniq_counter = None
            self._dup_counter = None

    # ------------------------------------------------------------ stamps --
    def _get(self, h: bytes, now: float) -> _Stamp:
        st = self._stamps.get(h)
        if st is None:
            st = self._stamps[h] = _Stamp(now)
            if len(self._stamps) > self.PRUNE_THRESHOLD:
                self._prune_front(now)
        return st

    def on_recv(self, h: bytes, duplicate: Optional[bool] = None,
                now: Optional[float] = None) -> bool:
        """Record a delivery of hash `h`. `duplicate` overrides the
        stamp-based detection when the caller has an authority (the
        floodgate's dedup record for SCP messages); by default a
        delivery is a duplicate if this node already received or
        locally admitted the message. Returns the duplicate verdict."""
        if now is None:
            now = time.perf_counter()
        st = self._get(h, now)
        if duplicate is None:
            duplicate = st.recvs > 0 or st.admitted is not None
        st.recvs += 1
        if duplicate:
            self.duplicates += 1
            if self._dup_counter is not None:
                self._dup_counter.inc()
        else:
            self.unique += 1
            if self._uniq_counter is not None:
                self._uniq_counter.inc()
        return duplicate

    def on_send(self, h: bytes, n_peers: int = 1,
                now: Optional[float] = None) -> None:
        if now is None:
            now = time.perf_counter()
        self._get(h, now).sends += n_peers

    def on_admitted(self, h: bytes,
                    now: Optional[float] = None) -> None:
        if now is None:
            now = time.perf_counter()
        st = self._get(h, now)
        if st.admitted is None:
            st.admitted = now

    def on_externalized(self, h: bytes,
                        now: Optional[float] = None) -> None:
        """Update-only: a node that never saw the flood (catchup
        replay) must not grow the map with externalize-only stamps."""
        st = self._stamps.get(h)
        if st is not None and st.externalized is None:
            st.externalized = now if now is not None \
                else time.perf_counter()

    # ----------------------------------------------------------- hygiene --
    def _prune_front(self, now: float) -> None:
        """Entries are inserted with a monotonic first_seen, so the
        dict's insertion order IS first_seen order: scan from the
        front and stop at the first in-TTL entry — O(evicted), not a
        full map scan per flood event on the always-on hot path."""
        cutoff = now - self.STAMP_TTL_SECONDS
        stale = []
        for h, st in self._stamps.items():
            if st.first_seen >= cutoff:
                break
            stale.append(h)
        for h in stale:
            del self._stamps[h]
        if stale and self._dropped_counter is not None:
            self._dropped_counter.inc(len(stale))

    def clear(self) -> None:
        """`clearmetrics` hook: bench legs sharing a process start each
        measured window from a clean slate."""
        self._stamps.clear()
        self.unique = 0
        self.duplicates = 0

    def __len__(self) -> int:
        return len(self._stamps)

    # ------------------------------------------------------------ report --
    def report(self) -> dict:
        """Flood-redundancy snapshot (clusterstatus route, bench
        artifacts): duplicate_ratio is redundant deliveries per unique
        message — the number pull-mode flooding must drive toward 0."""
        total = self.unique + self.duplicates
        return {
            "unique": self.unique,
            "duplicates": self.duplicates,
            "duplicate_ratio": round(
                self.duplicates / max(1, self.unique), 4),
            "redundancy": round(total / max(1, self.unique), 4),
            "tracked": len(self._stamps),
            "dropped": self._dropped_counter.count
            if self._dropped_counter is not None else 0,
        }
