"""Pull-mode transaction flooding.

Reference: src/overlay/TxAdvertQueue.{h,cpp} + TxDemandsManager —
instead of pushing full transactions, peers advertise tx hashes
(FLOOD_ADVERT); the receiver queues unknown hashes and demands bodies
(FLOOD_DEMAND); the advertiser answers with TRANSACTION messages.
"""

from __future__ import annotations

from collections import OrderedDict, deque
from typing import Deque, List, Set

from ..util.logging import get_logger
from ..xdr.overlay import (FloodAdvert, FloodDemand, MessageType,
                           StellarMessage, MAX_TX_ADVERT_VECTOR,
                           MAX_TX_DEMAND_VECTOR)

log = get_logger("Overlay")


class TxAdvertQueue:
    """Per-peer outgoing advert batching + incoming advert tracking."""

    def __init__(self, config):
        self._outgoing: List[bytes] = []
        self._incoming: Deque[bytes] = deque()
        self._seen_adverts: OrderedDict = OrderedDict()
        self._max_cache = config.MAX_ADVERT_CACHE_SIZE

    # ------------------------------------------------------------- outgoing --
    def queue_advert(self, tx_hash: bytes) -> StellarMessage | None:
        """Queue a hash for advertising; returns a FLOOD_ADVERT message
        to send now only when the batch is full. The flush cadence
        (cooldown-gated immediate send vs timer) is the manager's call —
        it owns the clock."""
        self._outgoing.append(tx_hash)
        if len(self._outgoing) >= MAX_TX_ADVERT_VECTOR:
            return self.flush_advert()
        return None

    def pending(self) -> bool:
        return bool(self._outgoing)

    def flush_advert(self) -> StellarMessage | None:
        if not self._outgoing:
            return None
        batch, self._outgoing = self._outgoing, []
        return StellarMessage(MessageType.FLOOD_ADVERT,
                              FloodAdvert(txHashes=batch))

    # ------------------------------------------------------------- incoming --
    def recv_advert(self, tx_hashes, known_fn) -> List[bytes]:
        """Track advertised hashes; returns those we should demand."""
        demand = []
        for h in tx_hashes:
            h = bytes(h)
            if h in self._seen_adverts:
                continue
            self._seen_adverts[h] = True
            while len(self._seen_adverts) > self._max_cache:
                self._seen_adverts.popitem(last=False)
            if not known_fn(h):
                demand.append(h)
        return demand

    @staticmethod
    def make_demand(tx_hashes: List[bytes]) -> StellarMessage:
        return StellarMessage(
            MessageType.FLOOD_DEMAND,
            FloodDemand(txHashes=tx_hashes[:MAX_TX_DEMAND_VECTOR]))
