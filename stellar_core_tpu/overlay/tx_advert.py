"""Pull-mode transaction flooding.

Reference: src/overlay/TxAdvertQueue.{h,cpp} + TxDemandsManager —
instead of pushing full transactions, peers advertise tx hashes
(FLOOD_ADVERT); the receiver queues unknown hashes and demands bodies
(FLOOD_DEMAND); the advertiser answers with TRANSACTION messages.
`TxDemandsManager` is the manager-level single-flight table: each
hash is demanded from exactly ONE peer at a time, however many peers
advertise it.
"""

from __future__ import annotations

from collections import OrderedDict, deque
from typing import Deque, Dict, List, Optional, Set

from ..util.logging import get_logger
from ..xdr.overlay import (FloodAdvert, FloodDemand, MessageType,
                           StellarMessage, MAX_TX_ADVERT_VECTOR,
                           MAX_TX_DEMAND_VECTOR)

log = get_logger("Overlay")


class TxAdvertQueue:
    """Per-peer outgoing advert batching + incoming advert tracking."""

    def __init__(self, config):
        self._outgoing: List[bytes] = []
        self._incoming: Deque[bytes] = deque()
        self._seen_adverts: OrderedDict = OrderedDict()
        self._max_cache = config.MAX_ADVERT_CACHE_SIZE

    # ------------------------------------------------------------- outgoing --
    def queue_advert(self, tx_hash: bytes) -> StellarMessage | None:
        """Queue a hash for advertising; returns a FLOOD_ADVERT message
        to send now only when the batch is full. The flush cadence
        (cooldown-gated immediate send vs timer) is the manager's call —
        it owns the clock."""
        self._outgoing.append(tx_hash)
        if len(self._outgoing) >= MAX_TX_ADVERT_VECTOR:
            return self.flush_advert()
        return None

    def pending(self) -> bool:
        return bool(self._outgoing)

    def flush_advert(self) -> StellarMessage | None:
        if not self._outgoing:
            return None
        batch, self._outgoing = self._outgoing, []
        return StellarMessage(MessageType.FLOOD_ADVERT,
                              FloodAdvert(txHashes=batch))

    # ------------------------------------------------------------- incoming --
    def recv_advert(self, tx_hashes, known_fn) -> List[bytes]:
        """Track advertised hashes; returns those we should demand."""
        demand = []
        for h in tx_hashes:
            h = bytes(h)
            if h in self._seen_adverts:
                continue
            self._seen_adverts[h] = True
            while len(self._seen_adverts) > self._max_cache:
                self._seen_adverts.popitem(last=False)
            if not known_fn(h):
                demand.append(h)
        return demand

    @staticmethod
    def make_demand(tx_hashes: List[bytes]) -> StellarMessage:
        return StellarMessage(
            MessageType.FLOOD_DEMAND,
            FloodDemand(txHashes=tx_hashes[:MAX_TX_DEMAND_VECTOR]))


class _Demand:
    """One outstanding single-flight demand: who currently owes us the
    body, when we asked, how many attempts so far, and which OTHER
    peers advertised the hash (the retry rotation order)."""

    __slots__ = ("peer_key", "t", "attempts", "backups")

    def __init__(self, peer_key: int, now: float):
        self.peer_key = peer_key
        self.t = now
        self.attempts = 1
        self.backups: List[int] = []


class TxDemandsManager:
    """Single-flight outstanding-demand table (ISSUE 12 tentpole,
    prong 2; reference: TxDemandsManager).

    The per-peer `TxAdvertQueue` dedups adverts per LINK; this table
    dedups demands per NODE: when two peers advertise the same hash
    before the body arrives, the second (and every later) advertiser
    is recorded as a backup instead of being demanded too — each hash
    is in flight from exactly one peer at a time, which is the lever
    that pushes real-socket duplicate_ratio below 1.0 (every extra
    concurrent demand used to buy a guaranteed duplicate body).
    A peer that lets a demand time out is rotated out: the retry goes
    to the next backup advertiser (falling back to any other live
    peer when no advertiser remains), with per-peer
    `demand.{sent,fulfilled,timeout,retry}` accounting kept by the
    OverlayManager that drives this table."""

    def __init__(self, max_attempts: int = 3):
        self.max_attempts = max_attempts
        self._outstanding: Dict[bytes, _Demand] = {}

    def __len__(self) -> int:
        return len(self._outstanding)

    def outstanding_from(self, h: bytes) -> Optional[int]:
        d = self._outstanding.get(h)
        return d.peer_key if d is not None else None

    def note_advert(self, h: bytes, peer_key: int, now: float) -> bool:
        """Register an advert for `h` from `peer_key`. True = demand
        it from this peer now (first flight); False = a demand is
        already in flight, the peer was recorded as a backup."""
        d = self._outstanding.get(h)
        if d is None:
            self._outstanding[h] = _Demand(peer_key, now)
            return True
        if peer_key != d.peer_key and peer_key not in d.backups:
            d.backups.append(peer_key)
        return False

    def fulfilled(self, h: bytes) -> Optional[_Demand]:
        """A body for `h` arrived: retire the record (returns it for
        accounting, None when the body was never demanded)."""
        return self._outstanding.pop(h, None)

    def forget(self, h: bytes) -> None:
        self._outstanding.pop(h, None)

    def sweep(self, now: float, period_s: float, backoff_s: float,
              peers_by_key: Dict[int, object], any_peers: List,
              is_known=None):
        """One retry pass: returns `(retries, timeouts)` where
        `retries` maps target peer -> [hashes] to re-demand (records
        already rotated onto the target) and `timeouts` lists the
        peer_keys that let a demand expire (one entry per hash).
        Each attempt waits an extra `backoff_s` step before the next
        (reference: FLOOD_DEMAND_BACKOFF_DELAY_MS)."""
        retries: Dict[int, tuple] = {}
        timeouts: List[int] = []
        for h, d in list(self._outstanding.items()):
            if is_known is not None and is_known(h):
                del self._outstanding[h]
                continue
            if now - d.t < period_s + backoff_s * (d.attempts - 1):
                continue
            timeouts.append(d.peer_key)
            if d.attempts >= self.max_attempts:
                del self._outstanding[h]
                continue
            # rotation: the next LIVE backup advertiser wins; with no
            # advertiser left, any other live peer (round-robin by
            # attempt) keeps the fetch moving
            target = None
            while d.backups:
                cand = d.backups.pop(0)
                if cand in peers_by_key and cand != d.peer_key:
                    target = peers_by_key[cand]
                    break
            if target is None:
                others = [p for p in any_peers
                          if id(p) != d.peer_key]
                if not others:
                    del self._outstanding[h]
                    continue
                target = others[d.attempts % len(others)]
            d.peer_key = id(target)
            d.t = now
            d.attempts += 1
            retries.setdefault(id(target), (target, []))[1].append(h)
        return retries, timeouts
