"""Credit-based flow control for flooded messages.

Reference: src/overlay/FlowControl.{h,cpp} + FlowControlCapacity — each
side grants its peer an initial reading capacity (messages and bytes);
flooded messages (TRANSACTION, SCP_MESSAGE, FLOOD_ADVERT, FLOOD_DEMAND)
consume capacity at the sender and queue when exhausted; the receiver
returns capacity in SEND_MORE_EXTENDED batches after processing.
Non-flood traffic is never throttled.

Outbound queueing is priority-aware and byte-budgeted (ISSUE 20): the
per-peer queue is three drop-priority classes — SCP envelopes (highest:
consensus halts without them), demanded transaction bodies (the peer
explicitly asked), advert/demand gossip (lowest: re-announcable) —
drained strictly in that order, FIFO within a class. Past the total
byte budget (OUTBOUND_QUEUE_BYTE_LIMIT) the enqueue sheds from the
lowest-priority non-empty class first; an SCP envelope is only ever
shed to make room for another SCP envelope, never for tx or gossip.
Shed counts are kept per class for the `peers` route and the
`overlay.flow.drop.*` counters, so a slow or partitioned link is
visible — and bounded — instead of ballooning a healthy node's memory.
"""

from __future__ import annotations

from collections import deque
from typing import Deque, Optional, Tuple

from ..util.logging import get_logger
from ..xdr.overlay import (MessageType, SendMoreExtended, StellarMessage)
from . import wire

log = get_logger("Overlay")

FLOW_CONTROLLED_TYPES = (MessageType.TRANSACTION, MessageType.SCP_MESSAGE,
                         MessageType.FLOOD_ADVERT, MessageType.FLOOD_DEMAND)

# drop-priority classes, highest priority first (lowest index sheds
# LAST). The names are the `overlay.flow.drop.<class>` suffixes and the
# `peers` route's drops keys.
CLASS_SCP = 0
CLASS_TX = 1
CLASS_GOSSIP = 2
CLASS_NAMES = ("scp", "tx", "gossip")


def msg_class(msg: StellarMessage) -> int:
    if msg.disc == MessageType.SCP_MESSAGE:
        return CLASS_SCP
    if msg.disc == MessageType.TRANSACTION:
        return CLASS_TX
    return CLASS_GOSSIP        # FLOOD_ADVERT / FLOOD_DEMAND


def is_flow_controlled(msg: StellarMessage) -> bool:
    return msg.disc in FLOW_CONTROLLED_TYPES


def msg_body_size(msg: StellarMessage, counters=None) -> int:
    # serialize-once (ISSUE 12): byte-level flow accounting sizes each
    # flooded message up to four times on its way through a peer
    # (try_send, queue caps, recv accounting, SEND_MORE bookkeeping) —
    # all hits on the message's cached canonical bytes now
    return len(wire.body_bytes(msg, counters))


class FlowControl:
    """One instance per peer connection, tracking both directions."""

    def __init__(self, config, encode_counters=None, drop_counters=None):
        # the overlay's (hit, miss) encode-cache counter pair: flow
        # control is often the FIRST consumer to serialize an outbound
        # flooded message, so the miss must be charged here for the
        # cache evidence to add up
        self._enc = encode_counters
        # aggregate overlay.flow.drop.<class> counters (one triple
        # shared by every peer; per-peer tallies live in `dropped`)
        self._drop_counters = drop_counters
        # what the remote may still send us before we SEND_MORE
        self.local_capacity_msgs = config.PEER_FLOOD_READING_CAPACITY
        self.local_capacity_bytes = config.PEER_FLOOD_READING_CAPACITY_BYTES
        # what we may still send the remote
        self.remote_capacity_msgs = 0
        self.remote_capacity_bytes = 0
        self.batch_msgs = config.FLOW_CONTROL_SEND_MORE_BATCH_SIZE
        self.batch_bytes = config.FLOW_CONTROL_SEND_MORE_BATCH_SIZE_BYTES
        self._processed_msgs = 0
        self._processed_bytes = 0
        # one FIFO per drop-priority class, drained SCP→tx→gossip
        self._queues: Tuple[Deque[StellarMessage], ...] = (
            deque(), deque(), deque())
        self._queued_bytes = [0, 0, 0]
        # cap on queued TRANSACTION bytes; oldest dropped first
        # (reference: OUTBOUND_TX_QUEUE_BYTE_LIMIT)
        self.tx_queue_byte_limit = config.OUTBOUND_TX_QUEUE_BYTE_LIMIT
        self.dropped_tx_msgs = 0
        # total outbound byte budget across all classes; 0 = unbounded
        self.queue_byte_limit = getattr(
            config, "OUTBOUND_QUEUE_BYTE_LIMIT", 0)
        self.queue_high_water = 0      # max total queued bytes observed
        self.dropped = [0, 0, 0]       # per-class shed counts
        # byte-level accounting off = message counts only (reference:
        # ENABLE_FLOW_CONTROL_BYTES)
        self.bytes_enabled = config.ENABLE_FLOW_CONTROL_BYTES

    # ----------------------------------------------------------- queueing --
    def _drop_oldest(self, cls: int) -> None:
        q = self._queues[cls]
        victim = q.popleft()
        self._queued_bytes[cls] -= msg_body_size(victim, self._enc)
        self.dropped[cls] += 1
        if cls == CLASS_TX:
            self.dropped_tx_msgs += 1
        if self._drop_counters is not None:
            self._drop_counters[cls].inc()

    def _enqueue(self, msg: StellarMessage) -> None:
        cls = msg_class(msg)
        size = msg_body_size(msg, self._enc)
        self._queues[cls].append(msg)
        self._queued_bytes[cls] += size
        # legacy per-class tx cap (reference semantics): oldest tx out
        if cls == CLASS_TX and self.tx_queue_byte_limit > 0:
            while self._queued_bytes[CLASS_TX] > self.tx_queue_byte_limit:
                self._drop_oldest(CLASS_TX)
        # total byte budget: shed from the lowest-priority non-empty
        # class. Never shed a class higher-priority than the incoming
        # message's own — an SCP enqueue may shed old SCP (the budget
        # is then all consensus traffic), but tx/gossip never evict SCP
        if self.queue_byte_limit > 0:
            while sum(self._queued_bytes) > self.queue_byte_limit:
                for shed_cls in (CLASS_GOSSIP, CLASS_TX, CLASS_SCP):
                    if shed_cls >= cls and self._queues[shed_cls]:
                        self._drop_oldest(shed_cls)
                        break
                else:
                    break    # only higher-priority bytes remain
        total = sum(self._queued_bytes)
        if total > self.queue_high_water:
            self.queue_high_water = total

    def _note_dequeued(self, cls: int, msg: StellarMessage) -> None:
        self._queued_bytes[cls] -= msg_body_size(msg, self._enc)

    # ------------------------------------------------------------ sending --
    def initial_send_more(self, config) -> StellarMessage:
        """The capacity grant sent right after AUTH (reference:
        sendSendMore at handshake completion)."""
        return StellarMessage(
            MessageType.SEND_MORE_EXTENDED,
            SendMoreExtended(
                numMessages=config.PEER_FLOOD_READING_CAPACITY,
                numBytes=config.PEER_FLOOD_READING_CAPACITY_BYTES))

    def try_send(self, msg: StellarMessage) -> Optional[StellarMessage]:
        """Returns the message if capacity allows sending now, else
        queues it (priority class, FIFO within) and returns None."""
        if not is_flow_controlled(msg):
            return msg
        if self._queues[msg_class(msg)]:
            # FIFO within a class: never overtake an earlier message of
            # the same priority (slow-link ordering, MAC seq safety)
            self._enqueue(msg)
            return None
        return self._consume_or_queue(msg)

    def _consume_or_queue(self, msg: StellarMessage
                          ) -> Optional[StellarMessage]:
        size = msg_body_size(msg, self._enc)
        if self.remote_capacity_msgs >= 1 and \
                (not self.bytes_enabled or
                 self.remote_capacity_bytes >= size):
            self.remote_capacity_msgs -= 1
            self.remote_capacity_bytes -= size
            return msg
        self._enqueue(msg)
        return None

    def on_send_more(self, num_messages: int, num_bytes: int) -> list:
        """Peer granted capacity; returns queued messages now sendable,
        highest priority class first, FIFO within a class. A class head
        too big for the byte grant blocks only its own class — lower
        classes may still fit (it keeps first claim on the next grant)."""
        self.remote_capacity_msgs += num_messages
        self.remote_capacity_bytes += num_bytes
        out = []
        for cls in (CLASS_SCP, CLASS_TX, CLASS_GOSSIP):
            q = self._queues[cls]
            while q:
                msg = q[0]
                size = msg_body_size(msg, self._enc)
                if self.remote_capacity_msgs >= 1 and \
                        (not self.bytes_enabled or
                         self.remote_capacity_bytes >= size):
                    self.remote_capacity_msgs -= 1
                    self.remote_capacity_bytes -= size
                    sent = q.popleft()
                    self._note_dequeued(cls, sent)
                    out.append(sent)
                else:
                    break
        return out

    # ---------------------------------------------------------- receiving --
    def on_message_received(self, msg: StellarMessage) -> bool:
        """Account an inbound flooded message against the capacity we
        granted; False = peer overflowed its allowance (protocol
        violation, reference: throwIfOutOfSyncRecv)."""
        if not is_flow_controlled(msg):
            return True
        size = msg_body_size(msg, self._enc)
        if self.local_capacity_msgs < 1 or \
                (self.bytes_enabled and self.local_capacity_bytes < size):
            return False
        self.local_capacity_msgs -= 1
        self.local_capacity_bytes -= size
        return True

    def maybe_send_more(self, msg: StellarMessage
                        ) -> Optional[StellarMessage]:
        """After processing an inbound flooded message, possibly return a
        SEND_MORE_EXTENDED replenishing the peer's budget."""
        if not is_flow_controlled(msg):
            return None
        self._processed_msgs += 1
        self._processed_bytes += msg_body_size(msg, self._enc)
        if self._processed_msgs >= self.batch_msgs or \
                self._processed_bytes >= self.batch_bytes:
            n, b = self._processed_msgs, self._processed_bytes
            self._processed_msgs = 0
            self._processed_bytes = 0
            self.local_capacity_msgs += n
            self.local_capacity_bytes += b
            return StellarMessage(
                MessageType.SEND_MORE_EXTENDED,
                SendMoreExtended(numMessages=n, numBytes=b))
        return None

    def outbound_queue_len(self) -> int:
        return sum(len(q) for q in self._queues)

    def queued_bytes(self) -> int:
        return sum(self._queued_bytes)

    def flow_stats(self) -> dict:
        """The `peers` route's per-link backpressure row: live queue
        depth, the budget, the high-water mark against it, and what was
        shed per drop-priority class."""
        return {
            "queued_msgs": self.outbound_queue_len(),
            "queued_bytes": self.queued_bytes(),
            "queue_budget": self.queue_byte_limit,
            "queue_high_water": self.queue_high_water,
            "drops": {CLASS_NAMES[c]: self.dropped[c]
                      for c in (CLASS_SCP, CLASS_TX, CLASS_GOSSIP)},
        }
