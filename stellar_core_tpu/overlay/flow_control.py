"""Credit-based flow control for flooded messages.

Reference: src/overlay/FlowControl.{h,cpp} + FlowControlCapacity — each
side grants its peer an initial reading capacity (messages and bytes);
flooded messages (TRANSACTION, SCP_MESSAGE, FLOOD_ADVERT, FLOOD_DEMAND)
consume capacity at the sender and queue when exhausted; the receiver
returns capacity in SEND_MORE_EXTENDED batches after processing.
Non-flood traffic is never throttled.
"""

from __future__ import annotations

from collections import deque
from typing import Deque, Optional

from ..util.logging import get_logger
from ..xdr.overlay import (MessageType, SendMoreExtended, StellarMessage)
from . import wire

log = get_logger("Overlay")

FLOW_CONTROLLED_TYPES = (MessageType.TRANSACTION, MessageType.SCP_MESSAGE,
                         MessageType.FLOOD_ADVERT, MessageType.FLOOD_DEMAND)


def is_flow_controlled(msg: StellarMessage) -> bool:
    return msg.disc in FLOW_CONTROLLED_TYPES


def msg_body_size(msg: StellarMessage, counters=None) -> int:
    # serialize-once (ISSUE 12): byte-level flow accounting sizes each
    # flooded message up to four times on its way through a peer
    # (try_send, queue caps, recv accounting, SEND_MORE bookkeeping) —
    # all hits on the message's cached canonical bytes now
    return len(wire.body_bytes(msg, counters))


class FlowControl:
    """One instance per peer connection, tracking both directions."""

    def __init__(self, config, encode_counters=None):
        # the overlay's (hit, miss) encode-cache counter pair: flow
        # control is often the FIRST consumer to serialize an outbound
        # flooded message, so the miss must be charged here for the
        # cache evidence to add up
        self._enc = encode_counters
        # what the remote may still send us before we SEND_MORE
        self.local_capacity_msgs = config.PEER_FLOOD_READING_CAPACITY
        self.local_capacity_bytes = config.PEER_FLOOD_READING_CAPACITY_BYTES
        # what we may still send the remote
        self.remote_capacity_msgs = 0
        self.remote_capacity_bytes = 0
        self.batch_msgs = config.FLOW_CONTROL_SEND_MORE_BATCH_SIZE
        self.batch_bytes = config.FLOW_CONTROL_SEND_MORE_BATCH_SIZE_BYTES
        self._processed_msgs = 0
        self._processed_bytes = 0
        self._outbound: Deque[StellarMessage] = deque()
        # cap on queued TRANSACTION bytes; oldest dropped first
        # (reference: OUTBOUND_TX_QUEUE_BYTE_LIMIT)
        self.tx_queue_byte_limit = config.OUTBOUND_TX_QUEUE_BYTE_LIMIT
        self._queued_tx_bytes = 0
        self.dropped_tx_msgs = 0
        # byte-level accounting off = message counts only (reference:
        # ENABLE_FLOW_CONTROL_BYTES)
        self.bytes_enabled = config.ENABLE_FLOW_CONTROL_BYTES

    def _note_queued(self, msg: StellarMessage) -> None:
        if msg.disc != MessageType.TRANSACTION or \
                self.tx_queue_byte_limit <= 0:
            return
        self._queued_tx_bytes += msg_body_size(msg, self._enc)
        while self._queued_tx_bytes > self.tx_queue_byte_limit:
            for k, queued in enumerate(self._outbound):
                if queued.disc == MessageType.TRANSACTION:
                    self._queued_tx_bytes -= msg_body_size(queued, self._enc)
                    del self._outbound[k]
                    self.dropped_tx_msgs += 1
                    break
            else:
                break

    def _note_dequeued(self, msg: StellarMessage) -> None:
        if msg.disc == MessageType.TRANSACTION and \
                self.tx_queue_byte_limit > 0:
            self._queued_tx_bytes -= msg_body_size(msg, self._enc)

    # ------------------------------------------------------------ sending --
    def initial_send_more(self, config) -> StellarMessage:
        """The capacity grant sent right after AUTH (reference:
        sendSendMore at handshake completion)."""
        return StellarMessage(
            MessageType.SEND_MORE_EXTENDED,
            SendMoreExtended(
                numMessages=config.PEER_FLOOD_READING_CAPACITY,
                numBytes=config.PEER_FLOOD_READING_CAPACITY_BYTES))

    def try_send(self, msg: StellarMessage) -> Optional[StellarMessage]:
        """Returns the message if capacity allows sending now, else
        queues it and returns None."""
        if not is_flow_controlled(msg):
            return msg
        if self._outbound:
            self._outbound.append(msg)
            self._note_queued(msg)
            return None
        return self._consume_or_queue(msg)

    def _consume_or_queue(self, msg: StellarMessage
                          ) -> Optional[StellarMessage]:
        size = msg_body_size(msg, self._enc)
        if self.remote_capacity_msgs >= 1 and \
                (not self.bytes_enabled or
                 self.remote_capacity_bytes >= size):
            self.remote_capacity_msgs -= 1
            self.remote_capacity_bytes -= size
            return msg
        self._outbound.append(msg)
        self._note_queued(msg)
        return None

    def on_send_more(self, num_messages: int, num_bytes: int) -> list:
        """Peer granted capacity; returns queued messages now sendable."""
        self.remote_capacity_msgs += num_messages
        self.remote_capacity_bytes += num_bytes
        out = []
        while self._outbound:
            msg = self._outbound[0]
            size = msg_body_size(msg, self._enc)
            if self.remote_capacity_msgs >= 1 and \
                    (not self.bytes_enabled or
                     self.remote_capacity_bytes >= size):
                self.remote_capacity_msgs -= 1
                self.remote_capacity_bytes -= size
                sent = self._outbound.popleft()
                self._note_dequeued(sent)
                out.append(sent)
            else:
                break
        return out

    # ---------------------------------------------------------- receiving --
    def on_message_received(self, msg: StellarMessage) -> bool:
        """Account an inbound flooded message against the capacity we
        granted; False = peer overflowed its allowance (protocol
        violation, reference: throwIfOutOfSyncRecv)."""
        if not is_flow_controlled(msg):
            return True
        size = msg_body_size(msg, self._enc)
        if self.local_capacity_msgs < 1 or \
                (self.bytes_enabled and self.local_capacity_bytes < size):
            return False
        self.local_capacity_msgs -= 1
        self.local_capacity_bytes -= size
        return True

    def maybe_send_more(self, msg: StellarMessage
                        ) -> Optional[StellarMessage]:
        """After processing an inbound flooded message, possibly return a
        SEND_MORE_EXTENDED replenishing the peer's budget."""
        if not is_flow_controlled(msg):
            return None
        self._processed_msgs += 1
        self._processed_bytes += msg_body_size(msg, self._enc)
        if self._processed_msgs >= self.batch_msgs or \
                self._processed_bytes >= self.batch_bytes:
            n, b = self._processed_msgs, self._processed_bytes
            self._processed_msgs = 0
            self._processed_bytes = 0
            self.local_capacity_msgs += n
            self.local_capacity_bytes += b
            return StellarMessage(
                MessageType.SEND_MORE_EXTENDED,
                SendMoreExtended(numMessages=n, numBytes=b))
        return None

    def outbound_queue_len(self) -> int:
        return len(self._outbound)
