"""Serialize-once wire path (ISSUE 12 tentpole, prong 1).

A broadcast used to re-encode the same `StellarMessage` body for every
consumer on the path: once for the floodgate hash, once inside each
peer's HMAC, once inside each peer's `AuthenticatedMessage.to_bytes()`,
and up to three more times per peer inside flow control's
`msg_body_size` — ~25 encodings of one body for an 8-peer fan-out.
The reference pays the same tax (`xdr::msg_to_bytes` per peer inside
`Peer::sendAuthenticatedMessage`); the Clipper lesson already applied
to the verify path (amortize a fixed cost across the batch) applies
verbatim here: the body bytes are identical for every peer, only the
~40 bytes of per-peer sequence + MAC differ.

This module owns the canonical-bytes cache and the frame splice:

- `body_bytes(msg)` returns the canonical XDR encoding of a
  `StellarMessage`, computed at most once per message object and
  cached on the instance (`_wire_body`). Messages on the wire path
  are immutable by convention — they are constructed, flooded, and
  dropped; nothing mutates a message after it has been handed to
  `send_message`/`broadcast_message` (mutating one AFTER a send would
  desynchronize cache and object, which is why the cache lives here,
  at the wire boundary, and not inside `Union.to_bytes`).
- `seed_body(msg, body)` installs the received wire slice as the
  cache on a PARSED message, so the recv→rebroadcast path (SCP
  gossip) never re-encodes either — and the flood hash is computed
  over the bytes actually on the wire, exactly like the reference
  hashing the received xdr blob.
- `flood_hash(msg)` is the floodgate/propagation key, sha256 over the
  cached body (cached itself as `_wire_hash`).
- `assemble_frame(seq, body, mac)` splices the per-peer sequence and
  MAC around the shared body — byte-identical to
  `AuthenticatedMessage(0, _AuthenticatedMessageV0(...)).to_bytes()`
  (pinned by tests/test_wire_path.py frame-parity tests), so
  cross-version peers interoperate: nothing about the wire format
  changes, only how many times we pay to produce it.

Cache-efficiency evidence rides the `overlay.encode.{cache_hit,
cache_miss}` counters (metrics route + Prometheus): pass the
`(hit, miss)` counter pair a caller holds (OverlayManager owns the
shared pair) and one broadcast to N peers shows exactly one miss.
"""

from __future__ import annotations

import struct
from typing import Optional, Tuple

from ..crypto.sha import sha256
from ..xdr.overlay import StellarMessage

# AuthenticatedMessage union discriminant 0 (the only arm) — the
# 4-byte prefix every frame starts with
FRAME_PREFIX = b"\x00\x00\x00\x00"
MAC_LEN = 32
# prefix(4) + sequence(8); the MAC'd region of a frame is everything
# from the sequence to the end of the body: frame[4:-MAC_LEN]
BODY_OFFSET = 12


def body_bytes(msg: StellarMessage,
               counters: Optional[Tuple] = None) -> bytes:
    """Canonical XDR bytes of `msg`, encoded at most once per object.
    `counters` is an optional `(hit_counter, miss_counter)` pair."""
    b = msg.__dict__.get("_wire_body")
    if b is not None:
        if counters is not None:
            counters[0].inc()
        return b
    b = msg.to_bytes()
    msg.__dict__["_wire_body"] = b
    if counters is not None:
        counters[1].inc()
    return b


def seed_body(msg: StellarMessage, body: bytes) -> None:
    """Install the received wire slice as `msg`'s canonical bytes —
    the recv side serialized nothing, so this is neither a cache hit
    nor a miss; it makes every downstream consumer (flood hash,
    flow-control sizing, rebroadcast framing) a hit."""
    if "_wire_body" not in msg.__dict__:
        msg.__dict__["_wire_body"] = body


def flood_hash(msg: StellarMessage,
               counters: Optional[Tuple] = None) -> bytes:
    """Floodgate/propagation key: sha256 over the canonical body,
    computed (and cached) once per message object."""
    h = msg.__dict__.get("_wire_hash")
    if h is None:
        h = sha256(body_bytes(msg, counters))
        msg.__dict__["_wire_hash"] = h
    return h


def assemble_frame(seq: int, body: bytes, mac: bytes) -> bytes:
    """Splice per-peer sequence + MAC around the shared body; byte-
    identical to framing through `AuthenticatedMessage.to_bytes()`."""
    return b"".join((FRAME_PREFIX, struct.pack(">Q", seq), body, mac))
