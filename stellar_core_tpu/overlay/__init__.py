"""P2P overlay (reference: src/overlay — SURVEY.md §1 layer 8)."""

from .loopback import LoopbackPeer, LoopbackPeerConnection
from .manager import OverlayManager
from .peer import Peer, PeerState
from .peer_auth import PeerAuth, PeerRole

__all__ = ["OverlayManager", "Peer", "PeerState", "PeerAuth", "PeerRole",
           "LoopbackPeer", "LoopbackPeerConnection"]
