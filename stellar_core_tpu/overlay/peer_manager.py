"""Peer database + ban list + automatic connection maintenance.

Reference: src/overlay/PeerManager.{h,cpp} (peers table with
nextattempt/numfailures/type and exponential backoff),
RandomPeerSource.{h,cpp} (candidate selection), BanManager.{h,cpp}
(node-id bans), and OverlayManagerImpl::tick (:613 — top up outbound
connections toward TARGET_PEER_CONNECTIONS).
"""

from __future__ import annotations

import random
from enum import IntEnum
from typing import List, Optional, Tuple

from ..util.logging import get_logger
from ..util.timer import VirtualTimer

log = get_logger("Overlay")


class PeerType(IntEnum):
    # reference: PeerManager.h PeerType
    INBOUND = 0
    OUTBOUND = 1
    PREFERRED = 2


# reference: PeerManager::backOff — exponential, capped
MAX_BACKOFF_SECONDS = 24 * 3600
BASE_BACKOFF_SECONDS = 30


class PeerManager:
    def __init__(self, app):
        self.app = app
        self._rng = random.Random(0xBEEF)

    # ------------------------------------------------------------ peer rows --
    def ensure_exists(self, ip: str, port: int,
                      peer_type: PeerType = PeerType.OUTBOUND) -> None:
        db = self.app.database
        row = db.query_one(
            "SELECT 1 FROM peers WHERE ip=? AND port=?", (ip, port))
        if row is None:
            db.execute(
                "INSERT INTO peers (ip, port, nextattempt, numfailures, "
                "type) VALUES (?,?,0,0,?)", (ip, port, int(peer_type)))

    def update_success(self, ip: str, port: int) -> None:
        self.app.database.execute(
            "UPDATE peers SET numfailures=0, nextattempt=0 "
            "WHERE ip=? AND port=?", (ip, port))

    def update_failure(self, ip: str, port: int) -> None:
        now = int(self.app.clock.system_now())
        row = self.app.database.query_one(
            "SELECT numfailures FROM peers WHERE ip=? AND port=?",
            (ip, port))
        failures = (row[0] if row else 0) + 1
        backoff = min(BASE_BACKOFF_SECONDS * (2 ** min(failures, 12)),
                      MAX_BACKOFF_SECONDS)
        # jittered like the reference's randomized backoff
        backoff = self._rng.randint(backoff // 2, backoff)
        self.app.database.execute(
            "UPDATE peers SET numfailures=?, nextattempt=? "
            "WHERE ip=? AND port=?", (failures, now + backoff, ip, port))

    def candidates(self, n: int) -> List[Tuple[str, int]]:
        """Random eligible peers to dial (reference: RandomPeerSource)."""
        now = int(self.app.clock.system_now())
        rows = self.app.database.query_all(
            "SELECT ip, port FROM peers WHERE nextattempt <= ? "
            "ORDER BY type DESC, numfailures ASC LIMIT ?", (now, 4 * n))
        rows = list(rows)
        self._rng.shuffle(rows)
        return [(ip, port) for ip, port in rows[:n]]

    def known_peers(self) -> List[Tuple[str, int, int, int]]:
        return list(self.app.database.query_all(
            "SELECT ip, port, numfailures, type FROM peers"))

    def store_peer_list(self, addresses) -> None:
        """PEERS message payload → db (reference: recvPeers)."""
        for addr in addresses:
            if addr.ip.disc == 0:  # IPv4
                ip = ".".join(str(b) for b in bytes(addr.ip.value))
                if 0 < addr.port < 65536:
                    self.ensure_exists(ip, addr.port)


class BanManager:
    """reference: BanManager.{h,cpp} — node-id ban table consulted at
    auth time and managed over the admin API."""

    def __init__(self, app):
        self.app = app

    def ban_node(self, node_id_raw: bytes) -> None:
        self.app.database.execute(
            "INSERT OR REPLACE INTO ban (nodeid) VALUES (?)",
            (node_id_raw,))

    def unban_node(self, node_id_raw: bytes) -> None:
        self.app.database.execute(
            "DELETE FROM ban WHERE nodeid=?", (node_id_raw,))

    def is_banned(self, node_id_raw: bytes) -> bool:
        return self.app.database.query_one(
            "SELECT 1 FROM ban WHERE nodeid=?", (node_id_raw,)) is not None

    def banned_nodes(self) -> List[bytes]:
        return [bytes(r[0]) for r in self.app.database.query_all(
            "SELECT nodeid FROM ban")]
