"""Overlay manager: peer lifecycle + message routing + flooding.

Reference: src/overlay/OverlayManagerImpl.{h,cpp} (broadcastMessage
:1105, tick :613) and the Peer.cpp dispatch :519-585 for the
application-level message types, which land here via
`Peer.recv_message` → `handle_message`.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ..crypto.sha import sha256
from ..herder.pending_envelopes import RecvState
from ..util import chaos, tracing
from ..util.logging import get_logger
from ..xdr.overlay import (DontHave, MessageType, PeerAddress,
                           StellarMessage)
from ..xdr.scp import SCPQuorumSet
from . import wire
from .floodgate import Floodgate
from .item_fetcher import ItemFetcher
from .peer import Peer, PeerState
from .peer_auth import PeerAuth, PeerRole
from .tx_advert import (MAX_TX_DEMAND_VECTOR, TxAdvertQueue,
                        TxDemandsManager)

log = get_logger("Overlay")


# ratio keys the per-node reports derive from their own counts: a
# cross-node merge must SKIP these (summing ratios is meaningless) and
# re-derive them over the merged totals in finalize_flood_evidence —
# register any new derived key here and it is excluded automatically
DERIVED_EVIDENCE_KEYS = frozenset(
    {"single_flight_efficiency", "hit_ratio"})


def merge_flood_evidence(into: dict, add: dict) -> None:
    """Sum numeric leaves of one node's flood-evidence dict (the
    `demand_report`/`encode_report`/`flood_kind_report` shapes) into a
    cross-node total — nested dicts recursed, bools and
    `DERIVED_EVIDENCE_KEYS` excluded. Shared by bench's in-process
    `_flood_report` and the cluster harness's over-HTTP `flood_report`
    so the two artifact families can't drift."""
    for k, v in (add or {}).items():
        if k in DERIVED_EVIDENCE_KEYS:
            continue
        if isinstance(v, dict):
            merge_flood_evidence(into.setdefault(k, {}), v)
        elif isinstance(v, (int, float)) and not isinstance(v, bool):
            into[k] = into.get(k, 0) + v


def finalize_flood_evidence(demand: dict, encode: dict) -> None:
    """Derive the `DERIVED_EVIDENCE_KEYS` ratios over MERGED totals."""
    d_total = demand.get("sent", 0) + demand.get("suppressed", 0)
    demand["single_flight_efficiency"] = round(
        demand.get("suppressed", 0) / d_total, 4) if d_total else 0.0
    e_total = encode.get("cache_hit", 0) + encode.get("cache_miss", 0)
    encode["hit_ratio"] = round(
        encode.get("cache_hit", 0) / e_total, 4) if e_total else 0.0


def _forge_bad_sig_frames(frame, burst: int, network_id: bytes) -> list:
    """Byzantine flood material: `burst` structurally-valid
    TransactionEnvelopes cloned from a real one with the seqNum bumped —
    each gets a fresh contents hash, so the cloned signature no longer
    verifies. Exactly what a flooder aiming at batch admission would
    send: every frame parses, every signature costs a verify, none can
    ever apply."""
    from ..tx.frame import make_frame
    from ..xdr.transaction import TransactionEnvelope
    from ..xdr.types import EnvelopeType
    env = frame.envelope
    if env.disc != EnvelopeType.ENVELOPE_TYPE_TX:
        return []
    raw = env.to_bytes()
    out = []
    for k in range(burst):
        twin = TransactionEnvelope.from_bytes(raw)
        twin.value.tx.seqNum += k + 1
        out.append(make_frame(twin, network_id))
    return out


class OverlayManager:
    def __init__(self, app):
        self.app = app
        self.peer_auth = PeerAuth(app.config,
                                  lambda: app.clock.system_now())
        self.floodgate = Floodgate()
        self.tx_set_fetcher = ItemFetcher(self, MessageType.GET_TX_SET)
        self.qset_fetcher = ItemFetcher(self, MessageType.GET_SCP_QUORUMSET)
        self._pending: List[Peer] = []
        self._authenticated: List[Peer] = []
        self._advert_queues: Dict[int, TxAdvertQueue] = {}
        # single-flight outstanding-demand table (ISSUE 12): each tx
        # hash is demanded from exactly ONE peer at a time; later
        # advertisers become retry backups (reference: TxDemandsManager)
        self.demands = TxDemandsManager(self.MAX_DEMAND_ATTEMPTS)
        self._tcp_peers: List[Peer] = []
        self._door = None
        self._shutting_down = False
        # batched flood admission (ISSUE 4): TRANSACTION bodies received
        # in one crank buffer here and drain as ONE prevalidated batch
        # through herder.recv_transactions on the next crank's posted
        # actions (only when the coalescing verify service is installed)
        self._tx_recv_buffer: List[object] = []
        self._tx_drain_posted = False
        # drop-reason tallies (reference: Peer::DropReason buckets) —
        # reasons are free text; the tally keys on the stable prefix
        # before any ':' detail so "send error: [Errno 32]…" buckets
        # as one reason, mirrored into overlay.peer.drop.* counters
        self.drop_reasons: Dict[str, int] = {}
        self._dns_cache: Dict[str, object] = {}
        # serialize-once encode-cache evidence + pull-mode demand
        # accounting (ISSUE 12): all on the metrics route + Prometheus
        metrics = getattr(app, "metrics", None)
        if metrics is not None:
            # (hit, miss) pair threaded through overlay/wire.py —
            # one broadcast to N peers must show exactly one miss
            self.encode_counters = (
                metrics.new_counter("overlay.encode.cache_hit"),
                metrics.new_counter("overlay.encode.cache_miss"))
            self._demand_meters = {
                k: metrics.new_meter(f"overlay.demand.{k}")
                for k in ("sent", "fulfilled", "timeout", "retry",
                          "suppressed")}
            # flood dedup verdicts split by kind: which traffic class
            # the duplicate_ratio is made of (SCP push gossip vs tx
            # pull bodies) — the attribution ROADMAP item 3 needs
            self._flood_kind_counters = {
                (kind, dup): metrics.new_counter(
                    "overlay.flood.%s.%s" %
                    ("duplicate" if dup else "unique", kind))
                for kind in ("scp", "tx") for dup in (False, True)}
            # per-class outbound load-shed (ISSUE 20 backpressure):
            # one aggregate triple shared by every peer's FlowControl,
            # indexed by drop-priority class (scp, tx, gossip)
            from .flow_control import CLASS_NAMES
            self.flow_drop_counters = tuple(
                metrics.new_counter(f"overlay.flow.drop.{cls}")
                for cls in CLASS_NAMES)
            # SCP pushes suppressed because the link's floodgate digest
            # says the peer already signaled the envelope — the counter
            # that proves the dups/envelope floor is being attacked
            self._digest_suppressed = metrics.new_meter(
                "overlay.flood.digest.suppressed")
        else:
            self.encode_counters = None
            self._demand_meters = None
            self._flood_kind_counters = None
            self.flow_drop_counters = None
            self._digest_suppressed = None
        from .survey import SurveyManager
        self.survey_manager = SurveyManager(app)
        from .peer_manager import BanManager, PeerManager
        self.peer_manager = PeerManager(app)
        self.ban_manager = BanManager(app)
        self._tick_timer = None
        self._tick_rng = None    # lazy: seeded from config.jitter_seed()
        self._advert_timer = None
        self._advert_timer_armed = False
        self._demand_timer = None
        self._demand_timer_armed = False
        self._last_advert_flush = float("-inf")
        self._wire_herder()

    # -------------------------------------------------------------- wiring --
    def _wire_herder(self) -> None:
        herder = self.app.herder
        herder.broadcast_cb = self._broadcast_scp_envelope
        herder.ledger_closed_cb = self.ledger_closed
        herder.tx_advert_cb = self.advert_transaction
        herder.out_of_sync_cb = self._request_scp_state_from_peers
        herder.pending_envelopes.request_txset = self.tx_set_fetcher.fetch
        herder.pending_envelopes.request_qset = self.qset_fetcher.fetch

    def _request_scp_state(self, peer: Peer) -> None:
        """reference: HerderImpl::getMoreSCPState."""
        peer.send_message(StellarMessage(
            MessageType.GET_SCP_STATE, max(0, self._lcl_seq() - 1)))

    def _request_scp_state_from_peers(self) -> None:
        """Out-of-sync recovery: ask every peer for fresh SCP state."""
        # copy: a failed send can drop the peer mid-iteration
        for peer in list(self._authenticated):
            self._request_scp_state(peer)

    def _broadcast_scp_envelope(self, envelope) -> None:
        self.broadcast_message(
            StellarMessage(MessageType.SCP_MESSAGE, envelope))

    # --------------------------------------------------------------- peers --
    def add_pending_peer(self, peer: Peer) -> None:
        if len(self._pending) >= self.app.config.MAX_PENDING_CONNECTIONS:
            peer.drop("too many pending connections")
            return
        self._pending.append(peer)

    def peer_authenticated(self, peer: Peer) -> None:
        from .peer_auth import PeerRole
        cfg = self.app.config
        if peer in self._pending:
            self._pending.remove(peer)
        if chaos.ENABLED:
            # link-fault seam at admission (ISSUE 20): a reconnect
            # attempted while a `partition`/`flap` window is open on
            # this edge is refused right here — the redial loop keeps
            # knocking and succeeds only once the window heals
            link = chaos.point("overlay.link", None,
                               now=self.app.clock.now(),
                               **peer._chaos_ctx())
            if link is chaos.DROP:
                peer.drop("link down: chaos partition/flap")
                return
        if self.ban_manager.is_banned(peer.peer_id):
            peer.drop("banned")
            return
        # one authenticated connection per node id
        for other in self._authenticated:
            if other.peer_id == peer.peer_id:
                peer.drop("duplicate connection")
                return
        if peer.role == PeerRole.REMOTE_CALLED_US:
            # inbound cap (reference: MAX_ADDITIONAL_PEER_CONNECTIONS —
            # inbound slots on top of the outbound target)
            inbound = sum(1 for p in self._authenticated
                          if p.role == PeerRole.REMOTE_CALLED_US)
            if inbound >= cfg.max_inbound_peer_connections():
                peer.drop("too many inbound connections")
                return
            if cfg.PREFERRED_PEERS_ONLY and \
                    not self._is_preferred(peer):
                # reference: PREFERRED_PEERS_ONLY rejects everyone else
                peer.drop("not a preferred peer")
                return
        self._authenticated.append(peer)
        self._advert_queues[id(peer)] = TxAdvertQueue(self.app.config)
        log.debug("peer authenticated: %r", peer)
        self.tx_set_fetcher.peer_connected()
        self.qset_fetcher.peer_connected()
        # pull the peer's SCP state so consensus started before this
        # connection still reaches us (reference: Peer handshake →
        # sendGetScpState)
        self._request_scp_state(peer)

    # successful resolutions are cached this long; failures are NOT
    # cached at all — a transient resolver error or a DNS record change
    # must not permanently block a preferred peer until restart
    DNS_CACHE_TTL_SECONDS = 300.0

    def _resolve_host(self, host: str):
        """TTL-cached DNS resolution: a hit costs a dict lookup, an
        expired/missing entry re-resolves, and failures are never
        remembered (the next connection attempt retries)."""
        import time as _time
        now = _time.monotonic()
        hit = self._dns_cache.get(host)
        if hit is not None and now < hit[1]:
            return hit[0]
        if host == "localhost":
            ip = "127.0.0.1"
        else:
            try:
                import socket
                ip = socket.gethostbyname(host)
            except OSError:
                self._dns_cache.pop(host, None)
                return None
        self._dns_cache[host] = (ip, now + self.DNS_CACHE_TTL_SECONDS)
        return ip

    def _is_preferred(self, peer: Peer) -> bool:
        """Match a peer against PREFERRED_PEERS host:port entries (best
        effort: the listening port comes from HELLO; the host from the
        socket when there is one)."""
        port = getattr(peer, "remote_listening_port", 0)
        ip = None
        sock = getattr(peer, "sock", None)
        if sock is not None:
            try:
                ip = sock.getpeername()[0]
            except OSError:
                pass
        for entry in self.app.config.PREFERRED_PEERS:
            host, _, p = entry.rpartition(":")
            if not p.isdigit() or int(p) != port:
                continue
            if ip is None or host == ip:
                return True
            # PREFERRED_PEERS may name a DNS host (cached resolution)
            if self._resolve_host(host) == ip:
                return True
        return False

    def record_drop_reason(self, reason: str) -> None:
        key = (reason or "unknown").split(":", 1)[0].strip() or "unknown"
        self.drop_reasons[key] = self.drop_reasons.get(key, 0) + 1
        slug = "-".join("".join(
            c if c.isalnum() else " " for c in key.lower()).split())
        self.app.metrics.new_counter(
            f"overlay.peer.drop.{slug or 'unknown'}").inc()

    def peer_dropped(self, peer: Peer) -> None:
        if peer in self._pending:
            self._pending.remove(peer)
        if peer in self._authenticated:
            self._authenticated.remove(peer)
        self._advert_queues.pop(id(peer), None)
        self.floodgate.forget_peer(peer)
        self.tx_set_fetcher.peer_dropped(peer)
        self.qset_fetcher.peer_dropped(peer)

    def get_authenticated_peers(self) -> List[Peer]:
        return list(self._authenticated)

    def peers_json(self) -> dict:
        def fmt(peers):
            from ..crypto.strkey import StrKey
            return [{
                "id": StrKey.encode_ed25519_public(p.peer_id),
                "ver": p.remote_version,
                "olver": p.remote_overlay_version,
                # per-peer traffic counters (reference: the per-peer
                # metrics PeerSurvey reports — message/byte read+write)
                "messages_received": p.messages_read,
                "messages_sent": p.messages_written,
                "bytes_received": p.bytes_read,
                "bytes_sent": p.bytes_written,
                "bad_sig_drops": p.bad_sig_drops,
                # flood frames shed at admission by the adaptive
                # controller's surge gate (ops/controller.py)
                "shed_drops": p.shed_drops,
                # redundant flood deliveries this peer sent us — the
                # per-link share of the mesh's duplicate traffic
                "duplicates": p.duplicate_messages,
                # single-flight demand accounting per link (ISSUE 12)
                "demand": {"sent": p.demand_sent,
                           "fulfilled": p.demand_fulfilled,
                           "timeout": p.demand_timeout,
                           "retry": p.demand_retry},
                # per-link outbound backpressure (ISSUE 20): queue
                # depth vs its byte budget, high-water mark, per-class
                # shed counts — the evidence a slow link is bounded
                "flow": p.flow.flow_stats(),
            } for p in peers if p.peer_id is not None]
        inbound = [p for p in self._authenticated
                   if p.role == PeerRole.REMOTE_CALLED_US]
        outbound = [p for p in self._authenticated
                    if p.role == PeerRole.WE_CALLED_REMOTE]
        out = {"inbound": fmt(inbound), "outbound": fmt(outbound),
               "drop_reasons": dict(self.drop_reasons)}
        prop = getattr(self.app, "propagation", None)
        if prop is not None:
            # aggregate flood-redundancy snapshot beside the per-peer
            # rows (ROADMAP item 3's flood-duplicate counter surface),
            # extended with the ISSUE 12 wire-path evidence: demand
            # single-flight totals, encode-cache efficiency, and the
            # SCP-vs-tx split of the dedup verdicts
            flood = prop.report()
            flood["demand"] = self.demand_report()
            flood["encode"] = self.encode_report()
            flood["by_kind"] = self.flood_kind_report()
            out["flood"] = flood
        return out

    def demand_report(self) -> dict:
        """Aggregate single-flight demand snapshot (peers route /
        bench + cluster flood sections): `outstanding` is the live
        table size; `suppressed` counts demands single-flight avoided
        (each one used to be a guaranteed duplicate body);
        `single_flight_efficiency` = share of advertised fetches the
        table collapsed into an already-in-flight demand."""
        if self._demand_meters is None:
            return {}
        counts = {k: m.count for k, m in self._demand_meters.items()}
        counts["outstanding"] = len(self.demands)
        total = counts["sent"] + counts["suppressed"]
        counts["single_flight_efficiency"] = round(
            counts["suppressed"] / total, 4) if total else 0.0
        return counts

    def encode_report(self) -> dict:
        """Serialize-once cache snapshot: hits are encodings the wire
        path did NOT perform (hash/HMAC/frame/flow-control consumers
        of an already-cached body)."""
        if self.encode_counters is None:
            return {}
        hit, miss = self.encode_counters
        total = hit.count + miss.count
        return {"cache_hit": hit.count, "cache_miss": miss.count,
                "hit_ratio": round(hit.count / total, 4)
                if total else 0.0}

    def flood_kind_report(self) -> dict:
        """unique/duplicate dedup verdicts split by traffic class."""
        if self._flood_kind_counters is None:
            return {}
        return {kind: {
            "unique": self._flood_kind_counters[(kind, False)].count,
            "duplicates": self._flood_kind_counters[(kind, True)].count,
        } for kind in ("scp", "tx")}

    def reset_peer_counters(self) -> None:
        """`clearmetrics` hook: per-peer message/byte/duplicate
        counters back to zero on every authenticated peer."""
        for p in self._authenticated:
            p.reset_traffic_counters()

    # ------------------------------------------------------- tcp transport --
    def start(self) -> None:
        """Open the listener + dial configured peers (reference:
        OverlayManagerImpl::start); no-op for RUN_STANDALONE."""
        cfg = self.app.config
        if not cfg.mode_auto_starts_overlay():
            return
        from .tcp_peer import PeerDoor, connect_to
        self._door = PeerDoor(self, cfg.PEER_PORT)
        self.app.clock.add_io_poller(self._poll_tcp)
        from .peer_manager import PeerType
        for addr in cfg.KNOWN_PEERS + cfg.PREFERRED_PEERS:
            host, _, port = addr.partition(":")
            self.peer_manager.ensure_exists(
                host, int(port or 11625),
                PeerType.PREFERRED if addr in cfg.PREFERRED_PEERS
                else PeerType.OUTBOUND)
            connect_to(self, host, int(port or 11625))
        self.tick()

    def register_tcp_peer(self, peer) -> None:
        self._tcp_peers.append(peer)

    def _poll_tcp(self) -> int:
        n = self._door.poll() if self._door is not None else 0
        for peer in list(self._tcp_peers):
            n += peer.poll()
            if peer.state == PeerState.CLOSING:
                self._tcp_peers.remove(peer)
        return n

    def _arm_advert_timer(self) -> None:
        """One-shot advert-batch drain, armed only while a batch is
        pending (reference: pull-mode flood cadence — adverts leave on a
        short timer, not one message per transaction). One-shot so an
        idle overlay leaves no timer on the clock: virtual-time tests
        step timer-to-timer and must not land on empty flood ticks."""
        if self._advert_timer_armed or self._shutting_down:
            return
        from ..util.timer import VirtualTimer
        if self._advert_timer is None:
            self._advert_timer = VirtualTimer(self.app.clock)
        self._advert_timer_armed = True
        self._advert_timer.expires_from_now(
            self.app.config.FLOOD_ADVERT_PERIOD_MS / 1000.0)
        self._advert_timer.async_wait(self._advert_timer_fired)

    def _advert_timer_fired(self) -> None:
        self._advert_timer_armed = False
        if self._shutting_down:
            return
        self.flush_adverts()

    MAX_DEMAND_ATTEMPTS = 3

    def _arm_demand_timer(self) -> None:
        """One-shot retry sweep for unanswered FLOOD_DEMANDs (reference:
        TxDemandsManager — a peer that never answers must not strand the
        transaction; re-demand from someone else)."""
        if self._demand_timer_armed or self._shutting_down:
            return
        from ..util.timer import VirtualTimer
        if self._demand_timer is None:
            self._demand_timer = VirtualTimer(self.app.clock)
        self._demand_timer_armed = True
        self._demand_timer.expires_from_now(
            self.app.config.FLOOD_DEMAND_PERIOD_MS / 1000.0)
        self._demand_timer.async_wait(self._demand_timer_fired)

    def _demand_timer_fired(self) -> None:
        self._demand_timer_armed = False
        if self._shutting_down:
            return
        now = self.app.clock.now()
        period = self.app.config.FLOOD_DEMAND_PERIOD_MS / 1000.0
        backoff = self.app.config.FLOOD_DEMAND_BACKOFF_DELAY_MS / 1000.0
        herder = self.app.herder
        peers_by_key = {id(p): p for p in self._authenticated}
        retries, timeouts = self.demands.sweep(
            now, period, backoff, peers_by_key,
            list(self._authenticated),
            is_known=lambda h: herder.tx_queue.get_tx(h) is not None)
        # charge each expiry to the peer that sat on the demand
        for pid in timeouts:
            p = peers_by_key.get(pid)
            if p is not None:
                p.demand_timeout += 1
        if timeouts and self._demand_meters is not None:
            self._demand_meters["timeout"].mark(len(timeouts))
        for target, hashes in retries.values():
            target.demand_retry += len(hashes)
            if self._demand_meters is not None:
                self._demand_meters["retry"].mark(len(hashes))
            self._send_demand(target, hashes, retry=True)
        if len(self.demands):
            self._arm_demand_timer()

    def shutdown(self) -> None:
        self._shutting_down = True
        self._tx_recv_buffer = []
        if self._tick_timer is not None:
            self._tick_timer.cancel()
            self._tick_timer = None
        if self._advert_timer is not None:
            self._advert_timer.cancel()
            self._advert_timer = None
        if self._demand_timer is not None:
            self._demand_timer.cancel()
            self._demand_timer = None
        for p in list(self._authenticated) + list(self._pending):
            p.drop("shutdown")
        if self._door is not None:
            self._door.close()
            self.app.clock.remove_io_poller(self._poll_tcp)
            self._door = None

    # ------------------------------------------------------------ flooding --
    def _lcl_seq(self) -> int:
        return self.app.ledger_manager.get_last_closed_ledger_num()

    def broadcast_message(self, msg: StellarMessage,
                          msg_hash: Optional[bytes] = None) -> int:
        # serialize-once: the flood hash is computed from the body
        # bytes cached on the message (encoded here if this node
        # authored it, seeded from the wire slice if it is relaying),
        # and every peer's frame below splices around that same body
        h = msg_hash if msg_hash is not None \
            else wire.flood_hash(msg, self.encode_counters)
        sent = self.floodgate.broadcast(msg, self._authenticated,
                                        self._lcl_seq(), msg_hash=h)
        if msg.disc == MessageType.SCP_MESSAGE and \
                self._digest_suppressed is not None:
            # per-link digest evidence (ISSUE 20): every authenticated
            # peer the floodgate skipped is one push-gossip duplicate
            # that did NOT go out — the counter duplicate_ratio
            # improvements are judged against
            eligible = sum(1 for p in self._authenticated
                           if p.is_authenticated())
            if eligible > sent:
                self._digest_suppressed.mark(eligible - sent)
        if sent and msg.disc in (MessageType.SCP_MESSAGE,
                                 MessageType.TRANSACTION):
            # hash-keyed propagation stamp (overlay/propagation.py):
            # the send side of the mesh observatory's flood hops.
            # Flooded consensus/tx traffic only — survey relays also
            # broadcast, but have no recv-side stamp and would pollute
            # the flood analytics with send-only entries
            prop = getattr(self.app, "propagation", None)
            if prop is not None:
                prop.on_send(h, sent)
            if tracing.ENABLED:
                rec = self.app.flight_recorder
                if rec.active:
                    rec.instant("flood.send", {
                        "hash": h.hex()[:16], "type": msg.disc.name,
                        "n": sent})
        return sent

    # ------------------------------------------------------------ dispatch --
    def handle_message(self, peer: Peer, msg: StellarMessage) -> None:
        t = msg.disc
        handler = {
            MessageType.GET_TX_SET: self._on_get_tx_set,
            MessageType.TX_SET: self._on_tx_set,
            MessageType.GENERALIZED_TX_SET: self._on_tx_set,
            MessageType.GET_SCP_QUORUMSET: self._on_get_qset,
            MessageType.SCP_QUORUMSET: self._on_qset,
            MessageType.SCP_MESSAGE: self._on_scp_message,
            MessageType.GET_SCP_STATE: self._on_get_scp_state,
            MessageType.TRANSACTION: self._on_transaction,
            MessageType.DONT_HAVE: self._on_dont_have,
            MessageType.FLOOD_ADVERT: self._on_flood_advert,
            MessageType.FLOOD_DEMAND: self._on_flood_demand,
            MessageType.GET_PEERS: self._on_get_peers,
            MessageType.PEERS: self._on_peers,
            MessageType.SURVEY_REQUEST:
                lambda p, m: self.survey_manager.handle_request(p, m),
            MessageType.SURVEY_RESPONSE:
                lambda p, m: self.survey_manager.handle_response(p, m),
        }.get(t)
        if handler is None:
            log.debug("unhandled message type %s from %r", t, peer)
            return
        handler(peer, msg)

    # ------------------------------------------------------- fetch serving --
    def _on_get_tx_set(self, peer, msg) -> None:
        h = bytes(msg.value)
        tx_set = self.app.herder.pending_envelopes.get_tx_set(h)
        if tx_set is None:
            peer.send_message(StellarMessage(
                MessageType.DONT_HAVE,
                DontHave(type=MessageType.TX_SET, reqHash=h)))
            return
        xdr_set = tx_set.to_xdr()
        if tx_set.is_generalized:
            peer.send_message(StellarMessage(
                MessageType.GENERALIZED_TX_SET, xdr_set))
        else:
            peer.send_message(StellarMessage(MessageType.TX_SET, xdr_set))

    def _on_tx_set(self, peer, msg) -> None:
        from ..herder.tx_set import TxSetFrame
        frame = TxSetFrame(msg.value, self.app.config.network_id())
        h = frame.get_contents_hash()
        self.tx_set_fetcher.recv(h)
        self.app.herder.recv_tx_set(h, frame)

    def _on_get_qset(self, peer, msg) -> None:
        h = bytes(msg.value)
        qset = self.app.herder.pending_envelopes.get_qset(h)
        if qset is None:
            peer.send_message(StellarMessage(
                MessageType.DONT_HAVE,
                DontHave(type=MessageType.SCP_QUORUMSET, reqHash=h)))
            return
        peer.send_message(StellarMessage(MessageType.SCP_QUORUMSET, qset))

    def _on_qset(self, peer, msg) -> None:
        qset = msg.value
        h = sha256(qset.to_bytes())
        self.qset_fetcher.recv(h)
        self.app.herder.recv_scp_quorum_set(h, qset)

    def _on_dont_have(self, peer, msg) -> None:
        dh = msg.value
        if dh.type == MessageType.TX_SET:
            self.tx_set_fetcher.dont_have(bytes(dh.reqHash), peer)
        elif dh.type == MessageType.SCP_QUORUMSET:
            self.qset_fetcher.dont_have(bytes(dh.reqHash), peer)

    # ----------------------------------------------------------- consensus --
    def _on_scp_message(self, peer, msg) -> None:
        envelope = msg.value
        # cache seeded from the wire slice on recv: hashing a relayed
        # message re-encodes nothing
        h = wire.flood_hash(msg, self.encode_counters)
        new = self.floodgate.add_record(msg, peer, self._lcl_seq(),
                                        msg_hash=h)
        # propagation stamp + duplicate accounting: the floodgate's
        # dedup record is the authority on whether this delivery was
        # redundant; the duplicate is charged to the delivering peer
        prop = getattr(self.app, "propagation", None)
        if prop is not None:
            prop.on_recv(h, duplicate=not new)
        if not new:
            peer.duplicate_messages += 1
        if self._flood_kind_counters is not None:
            self._flood_kind_counters[("scp", not new)].inc()
        if tracing.ENABLED:
            rec = self.app.flight_recorder
            if rec.active:
                rec.instant("flood.recv", {
                    "hash": h.hex()[:16], "type": "SCP_MESSAGE",
                    "from": peer.peer_id.hex()[:8]
                    if peer.peer_id else "?", "dup": not new})
        if new:
            status = self.app.herder.recv_scp_envelope(envelope)
            # relay gate (ISSUE 12): only envelopes that can still
            # advance consensus somewhere — slot at or above our LCL —
            # are re-flooded. The LCL slot itself must keep relaying
            # (followers one slot behind externalize off our quorum's
            # EXTERNALIZE statements), but strictly-older envelopes
            # inside the remember window are INGESTED (quorum
            # tracking, catchup) without re-flooding: the boot/churn
            # GET_SCP_STATE echoes measured as the largest SCP
            # duplicate source in the cluster harness (a restarted
            # node re-flooded every remembered slot's statements to
            # neighbors that externalized them long ago). A peer that
            # needs history asks for it (GET_SCP_STATE), it does not
            # need us to gossip the past.
            if status != RecvState.ENVELOPE_STATUS_DISCARDED and \
                    envelope.statement.slotIndex >= self._lcl_seq():
                self.broadcast_message(msg, msg_hash=h)

    def _on_get_scp_state(self, peer, msg) -> None:
        """Send our latest SCP state for (and above) the requested seq
        (reference: Peer::recvGetSCPState → Herder::sendSCPStateToPeer)."""
        herder = self.app.herder
        if herder.scp is None:
            return
        from_seq = msg.value
        for slot_index in sorted(herder.scp.known_slots):
            if from_seq and slot_index < from_seq:
                continue
            for env in herder.scp.get_current_state(slot_index):
                m = StellarMessage(MessageType.SCP_MESSAGE, env)
                # per-link SCP digest (ISSUE 20): the peer now holds
                # this envelope — a later flood broadcast must not
                # re-push it down this link. Catchup-served state was
                # a guaranteed source of push-gossip duplicates after
                # every partition heal / churn rejoin.
                self.floodgate.note_told(
                    wire.flood_hash(m, self.encode_counters), peer,
                    self._lcl_seq())
                peer.send_message(m)

    # -------------------------------------------------------- transactions --
    def _on_transaction(self, peer, msg) -> None:
        from ..tx.frame import make_frame
        from ..util import chaos
        frame = make_frame(msg.value, self.app.config.network_id())
        h = frame.full_hash()
        # retire the single-flight demand record; fulfillment credit
        # goes to the peer we actually demanded from (a body from
        # anyone else still satisfies the fetch, but is the kind of
        # unsolicited push the demand table exists to make rare)
        rec = self.demands.fulfilled(h)
        if rec is not None:
            if rec.peer_key == id(peer):
                peer.demand_fulfilled += 1
            if self._demand_meters is not None:
                self._demand_meters["fulfilled"].mark()
        # propagation stamp keyed by the tx contents hash (the same
        # key the tx e2e track uses): a body this node already
        # received or admitted is a redundant delivery, charged to the
        # peer that sent it
        prop = getattr(self.app, "propagation", None)
        dup = False
        if prop is not None:
            dup = prop.on_recv(h)
            if dup:
                peer.duplicate_messages += 1
        if self._flood_kind_counters is not None:
            self._flood_kind_counters[("tx", dup)].inc()
        if tracing.ENABLED:
            rec = self.app.flight_recorder
            if rec.active:
                rec.instant("flood.recv", {
                    "hash": h.hex()[:16], "type": "TRANSACTION",
                    "from": peer.peer_id.hex()[:8]
                    if peer.peer_id else "?", "dup": dup})
        frames = [frame]
        if chaos.ENABLED:
            # Byzantine flood seam (ISSUE 7): a `bad_sig_flood` fault
            # here models the sending peer bursting well-formed
            # transactions with INVALID signatures alongside each real
            # body — aimed straight at the verify service's batch
            # admission. Forged from the real frame so everything is
            # structurally valid; attribution stays with the peer the
            # template came from (the flooder).
            cfg = self.app.config
            out = chaos.point(
                "overlay.transaction.recv", frame,
                node=cfg.node_id().hex() if cfg.NODE_SEED is not None
                else "",
                peer=peer.peer_id.hex() if peer.peer_id else "")
            if isinstance(out, chaos.BadSigBurst):
                frames += _forge_bad_sig_frames(
                    frame, out.burst, cfg.network_id())
        # surge shedding (ops/controller.py): drop decisions run HERE,
        # before the batched recv_transactions verify dispatch on
        # either path below — a shed frame costs this node zero device
        # time and zero try_add work. Shed frames are charged to the
        # per-peer `shed_drops` accounting (the `peers` route), not to
        # bad-sig accounting: nothing was verified, so nothing can be
        # called invalid. The roll covers everything the peer actually
        # sent — chaos-forged bad-sig bursts included.
        ctl = getattr(self.app, "controller", None)
        if ctl is not None and ctl.shed_flood > 0.0:
            kept = []
            for f in frames:
                if ctl.roll_flood_shed():
                    peer.shed_drops += 1
                else:
                    kept.append(f)
            frames = kept
            if not frames:
                return
        if self.app.herder.verify_service is None:
            # no batch accelerator: admit synchronously, as before —
            # but still through the bad_sig-reporting batched API, so
            # per-peer flooder accounting (and the drop threshold)
            # works on native-backend nodes too: the multi-process
            # cluster harness runs its chaos legs exactly there
            bad: List[bool] = []
            self.app.herder.recv_transactions(frames, bad_sig=bad)
            for is_bad in bad:
                if is_bad:
                    self.record_bad_sig(peer)
            return
        # coalescing path: buffer the crank's burst of received bodies
        # and admit them as ONE prevalidated batch on the next crank
        # (posted actions run before any further delivery), so a flood
        # burst pays one device dispatch instead of per-signature verify
        for f in frames:
            self._tx_recv_buffer.append((peer, f))
        if not self._tx_drain_posted:
            self._tx_drain_posted = True
            self.app.clock.post(self._drain_recv_transactions)

    def _drain_recv_transactions(self) -> None:
        self._tx_drain_posted = False
        buffered, self._tx_recv_buffer = self._tx_recv_buffer, []
        if not buffered or self._shutting_down:
            return
        from ..main.application import AppState
        if self.app.state == AppState.APP_STOPPING_STATE:
            return   # a crashed/buried node must not keep admitting
        # duplicate bodies (the same tx demanded from two peers before
        # either answered) collapse here; try_add would dedup anyway,
        # but the batch verify should not pay for them twice
        seen = set()
        batch = []
        for peer, f in buffered:
            h = f.full_hash()
            if h in seen:
                continue
            seen.add(h)
            batch.append((peer, f))
        bad_sig: List[bool] = []
        self.app.herder.recv_transactions([f for _, f in batch],
                                          bad_sig=bad_sig)
        # per-peer invalid-signature accounting (ISSUE 7 satellite):
        # the admission batch just told us exactly which envelopes
        # carried signatures that verified False — charge them to the
        # peer that delivered the body
        for (peer, _f), is_bad in zip(batch, bad_sig):
            if is_bad:
                self.record_bad_sig(peer)

    def record_bad_sig(self, peer: Peer, n: int = 1) -> None:
        """Count an invalid-signature transaction against `peer`; past
        PEER_BAD_SIG_DROP_THRESHOLD the peer takes the standard drop
        path (a flooder must not keep monopolizing verify batches).
        Surfaces as the per-peer `bad_sig_drops` field on the `peers`
        route and the aggregate `overlay.peer.drop.bad_sig` counter
        (metrics route + Prometheus)."""
        peer.bad_sig_drops += n
        self.app.metrics.new_counter("overlay.peer.drop.bad_sig").inc(n)
        thr = self.app.config.PEER_BAD_SIG_DROP_THRESHOLD
        if thr > 0 and peer.bad_sig_drops >= thr and \
                peer.state != PeerState.CLOSING:
            peer.drop("bad sig flood")

    def advert_transaction(self, tx_hash: bytes,
                           exclude: Optional[Peer] = None) -> None:
        """Queue the hash on every peer's advert batch (reference:
        TxAdvertQueue batches up to TX_ADVERT_VECTOR hashes per
        FLOOD_ADVERT; flushes ride the flood cadence, not one message
        per transaction). Cadence: a full batch sends at once; an idle
        overlay (no flush within the last period) flushes immediately so
        a lone transaction pays no timer latency; inside the cooldown a
        burst batches until the one-shot timer / ledger close fires."""
        # copy: a failed send can drop the peer mid-iteration
        for p in list(self._authenticated):
            if p is exclude:
                continue
            q = self._advert_queues.get(id(p))
            if q is None:
                continue
            full = q.queue_advert(tx_hash)
            if full is not None:
                p.send_message(full)
        now = self.app.clock.now()
        period = self.app.config.FLOOD_ADVERT_PERIOD_MS / 1000.0
        if now - self._last_advert_flush >= period:
            self.flush_adverts()
        else:
            self._arm_advert_timer()

    def flush_adverts(self) -> None:
        self._last_advert_flush = self.app.clock.now()
        # copy: a failed send can drop the peer mid-iteration
        for p in list(self._authenticated):
            q = self._advert_queues.get(id(p))
            if q is None:
                continue
            flushed = q.flush_advert()
            if flushed is not None:
                p.send_message(flushed)

    def _send_demand(self, peer, hashes: List[bytes],
                     retry: bool = False) -> None:
        """Send FLOOD_DEMANDs with per-peer + aggregate accounting and
        a hash-count trace instant (the demand leg of
        `trace_report.py --flood`'s single-flight efficiency view).
        Chunked to MAX_TX_DEMAND_VECTOR per message: the demands table
        has already stamped EVERY hash as in-flight from this peer, so
        an oversized batch (a retry sweep rotating a large backlog
        onto one survivor) must transmit them all — truncating here
        would leave the tail waiting out a full timeout for a demand
        that never went on the wire."""
        for i in range(0, len(hashes), MAX_TX_DEMAND_VECTOR):
            peer.send_message(TxAdvertQueue.make_demand(
                hashes[i:i + MAX_TX_DEMAND_VECTOR]))
        peer.demand_sent += len(hashes)
        if self._demand_meters is not None:
            self._demand_meters["sent"].mark(len(hashes))
        if tracing.ENABLED:
            rec = self.app.flight_recorder
            if rec.active:
                rec.instant("flood.demand", {
                    "n": len(hashes), "retry": retry,
                    "peer": peer.peer_id.hex()[:8]
                    if peer.peer_id else "?"})

    def _on_flood_advert(self, peer, msg) -> None:
        herder = self.app.herder

        def known(h: bytes) -> bool:
            return herder.tx_queue.get_tx(h) is not None or \
                herder.tx_queue.is_banned(h)

        q = self._advert_queues.get(id(peer))
        if q is None:
            return
        demand = q.recv_advert(msg.value.txHashes, known)
        if not demand:
            return
        # single-flight (ISSUE 12): only hashes with no demand already
        # in flight are demanded from this peer; for the rest the peer
        # is recorded as a retry backup — two peers advertising the
        # same hash used to mean two demands and a guaranteed
        # duplicate body
        now = self.app.clock.now()
        to_send = [h for h in demand
                   if self.demands.note_advert(h, id(peer), now)]
        suppressed = len(demand) - len(to_send)
        if suppressed and self._demand_meters is not None:
            self._demand_meters["suppressed"].mark(suppressed)
        if to_send:
            self._send_demand(peer, to_send)
        self._arm_demand_timer()

    def _on_flood_demand(self, peer, msg) -> None:
        herder = self.app.herder
        prop = getattr(self.app, "propagation", None)
        for h in msg.value.txHashes:
            h = bytes(h)
            tx = herder.tx_queue.get_tx(h)
            if tx is not None:
                # serialize-once: one TRANSACTION wrapper per frame,
                # stashed on it — every peer demanding this body (and
                # every flow-control sizing of it) hits the same
                # cached encoding instead of re-wrapping + re-encoding
                out = getattr(tx, "_flood_msg", None)
                if out is None:
                    out = StellarMessage(MessageType.TRANSACTION,
                                         tx.envelope)
                    tx._flood_msg = out
                peer.send_message(out)
                if prop is not None:
                    prop.on_send(h, 1)
                if tracing.ENABLED:
                    rec = self.app.flight_recorder
                    if rec.active:
                        rec.instant("flood.send", {
                            "hash": h.hex()[:16],
                            "type": "TRANSACTION", "n": 1})

    # ---------------------------------------------------------------- misc --
    def _on_get_peers(self, peer, msg) -> None:
        """Answer with known dialable peers (reference: recvGetPeers →
        sendPeers, up to 100)."""
        from ..xdr.overlay import IPAddrType, PeerAddress, _PeerAddressIp
        out = []
        for ip, port, failures, _t in self.peer_manager.known_peers():
            try:
                packed = bytes(int(x) for x in ip.split("."))
            except ValueError:
                continue
            if len(packed) != 4:
                continue
            out.append(PeerAddress(
                ip=_PeerAddressIp(IPAddrType.IPv4, packed),
                port=port, numFailures=failures))
            if len(out) >= 100:
                break
        peer.send_message(StellarMessage(MessageType.PEERS, out))

    def _on_peers(self, peer, msg) -> None:
        self.peer_manager.store_peer_list(list(msg.value))

    # ---------------------------------------------------------------- tick --
    def tick(self) -> None:
        """Connection maintenance (reference: OverlayManagerImpl::tick
        :613): top up outbound TCP connections toward the target."""
        cfg = self.app.config
        if cfg.RUN_STANDALONE or self._shutting_down:
            return
        if cfg.ARTIFICIALLY_SKIP_CONNECTION_ADJUSTMENT_FOR_TESTING:
            # reference: tests freeze the connection set mid-scenario
            return
        from .peer_auth import PeerRole
        outbound = [p for p in self._authenticated
                    if p.role == PeerRole.WE_CALLED_REMOTE]
        missing = cfg.TARGET_PEER_CONNECTIONS - len(outbound)
        if missing > 0:
            from .tcp_peer import connect_to
            if cfg.PREFERRED_PEERS_ONLY:
                # reference: PREFERRED_PEERS_ONLY — dial nobody else.
                # Dedup against live outbound by (host, port): distinct
                # hosts routinely share the standard port.
                have = set()
                for p in outbound:
                    sock = getattr(p, "sock", None)
                    ip = None
                    if sock is not None:
                        try:
                            ip = sock.getpeername()[0]
                        except OSError:
                            pass
                    have.add((ip, p.remote_listening_port))
                cands = []
                for entry in cfg.PREFERRED_PEERS:
                    host, _, p = entry.rpartition(":")
                    if not p.isdigit():
                        continue
                    resolved = self._resolve_host(host)
                    if (resolved, int(p)) not in have and \
                            (host, int(p)) not in have:
                        cands.append((host, int(p)))
                cands = cands[:missing]
            else:
                cands = self.peer_manager.candidates(missing)
            for ip, port in cands:
                if (ip == "localhost" or ip.startswith("127.")) and \
                        not cfg.ALLOW_LOCALHOST_FOR_TESTING:
                    # reference: localhost peers rejected outside tests
                    log.warning(
                        "skipping localhost peer %s:%d "
                        "(ALLOW_LOCALHOST_FOR_TESTING is off)", ip, port)
                    continue
                connect_to(self, ip, port)
        from ..util.timer import VirtualTimer
        self._tick_timer = VirtualTimer(self.app.clock)
        self._tick_timer.expires_from_now(self.tick_interval())
        self._tick_timer.async_wait(self.tick)

    def tick_interval(self) -> float:
        """Jitter-decorrelated dial-retry period (ISSUE 20): a fixed
        5.0 s re-arm made every node that lost a peer to the same
        partition/flap window redial in LOCKSTEP — a thundering herd
        against the healing listener. Per-node seeded jitter
        (config.jitter_seed(), the PR 5 decorrelation discipline)
        spreads the retries over [3.75, 6.25) s while keeping each
        node's sequence reproducible."""
        if self._tick_rng is None:
            import random
            self._tick_rng = random.Random(
                self.app.config.jitter_seed() ^ 0x7E9C_11A3)
        return 5.0 * (0.75 + 0.5 * self._tick_rng.random())

    # ---------------------------------------------------------- ledger tick --
    def ledger_closed(self, ledger_seq: int) -> None:
        self.floodgate.clear_below(ledger_seq)
        self.flush_adverts()
