"""In-memory peer pair for hermetic multi-node tests.

Reference: src/overlay/test/LoopbackPeer.{h,cpp} — two Peer objects
joined by in-memory queues, with fault-injection knobs: probabilistic
corruption, drops, duplication and reordering (LoopbackPeer.h:36-103).
Delivery is explicit (`deliver_all`/`deliver_one`) or scheduled on the
shared VirtualClock, keeping tests deterministic.
"""

from __future__ import annotations

import random
from collections import deque
from typing import Deque, Optional

from ..util import chaos
from ..util.logging import get_logger
from .peer import Peer, PeerRole

log = get_logger("Overlay")


class LoopbackPeer(Peer):
    def __init__(self, overlay, role: PeerRole):
        super().__init__(overlay, role)
        self.partner: Optional["LoopbackPeer"] = None
        self.out_queue: Deque[bytes] = deque()
        # fault injection (reference: LoopbackPeer.h damage/drop knobs)
        self.damage_prob = 0.0
        self.drop_prob = 0.0
        self.duplicate_prob = 0.0
        self.reorder_prob = 0.0
        self._rng = random.Random(0x5EED)
        self.corrupt_cert = False
        # per-link latency/bandwidth model (ISSUE 7): when latency (or
        # a bandwidth cap) is set, sends schedule delivery on the
        # shared VirtualClock instead of the immediate out_queue — 100
        # nodes × latency costs virtual time only, never wall time.
        # Same-latency messages keep FIFO order (the clock heap breaks
        # ties by schedule sequence).
        self.link_latency_s = 0.0
        self.link_bytes_per_s: Optional[float] = None
        # virtual arrival time of the last scheduled TRANSIT delivery:
        # a link transmits SERIALLY, so a later send never overtakes an
        # earlier one (else a small frame scheduled behind a large one
        # under the bandwidth model — or behind a delay-faulted one —
        # would arrive first and the MAC sequence check would kill the
        # authenticated link)
        self._last_arrival = 0.0
        # same clamp for the FINAL hop when a recv-side delay fault is
        # holding a message: later arrivals queue behind the held one
        self._final_hold = 0.0

    def _link_delay_s(self, nbytes: int) -> float:
        d = self.link_latency_s
        if self.link_bytes_per_s:
            d += nbytes / self.link_bytes_per_s
        return d

    def _schedule_delivery(self, raw: bytes, seconds: float) -> None:
        """Deliver `raw` to the partner `seconds` of VIRTUAL time from
        now — the shared path for the latency model and the chaos
        `delay` fault (docs/SIMULATION.md). Arrivals are clamped FIFO
        per link (serial transmission). The receive-side chaos seam
        still runs at delivery time, so latency and recv faults
        compose."""
        clock = self.app.clock
        arrival = max(clock.now() + seconds, self._last_arrival)
        self._last_arrival = arrival
        clock.schedule_at(arrival,
                          lambda err: self._deliver_to_partner(raw))

    def _send_bytes(self, raw: bytes) -> None:
        if chaos.ENABLED:
            # chaos seam (the scheduled, seeded superset of the
            # probabilistic knobs below): drop / corrupt / reorder /
            # delay / io_error on the send side
            out = chaos.point("overlay.send", raw, transport="loopback",
                              _can_delay=True, now=self.app.clock.now(),
                              **self._chaos_ctx())
            if out is chaos.DROP:
                return
            if isinstance(out, chaos.Shape):
                # slow_link (ISSUE 20): the Shape's latency+bandwidth
                # ride the same virtual-time transit path as the link
                # model — FIFO-clamped, so shaped frames never trip
                # the MAC sequence
                extra = (len(raw) / out.bytes_per_s
                         if out.bytes_per_s else 0.0)
                self._schedule_delivery(raw, out.delay_s + extra)
                return
            if out is chaos.REORDER:
                # deliver this message BEFORE the previously queued one
                self.out_queue.append(raw)
                if len(self.out_queue) > 1:
                    self.out_queue[-1], self.out_queue[-2] = \
                        self.out_queue[-2], self.out_queue[-1]
                return
            if isinstance(out, chaos.Delay):
                # virtual-time delay fault: delivery deferred on the
                # clock (never a wall sleep — the single-process sim
                # would stall every node at once)
                self._schedule_delivery(bytes(out.payload), out.seconds)
                return
            if isinstance(out, (bytes, bytearray)):
                raw = out
        if self._rng.random() < self.drop_prob:
            return
        if self._rng.random() < self.damage_prob and raw:
            i = self._rng.randrange(len(raw))
            raw = raw[:i] + bytes([raw[i] ^ 0xFF]) + raw[i + 1:]
        delay_s = self._link_delay_s(len(raw))
        if delay_s > 0.0 or self._last_arrival > self.app.clock.now():
            # modeled link — or an earlier delayed delivery still in
            # flight (a partial-coverage delay fault): transit rides
            # the clock, FIFO-clamped, so an undelayed send never
            # overtakes a delayed one and trips the MAC sequence
            # check. The queue-order knobs (duplicate/reorder) apply
            # only to undelayed links
            self._schedule_delivery(raw, delay_s)
            return
        self.out_queue.append(raw)
        if self._rng.random() < self.duplicate_prob:
            self.out_queue.append(raw)
        if len(self.out_queue) > 1 and \
                self._rng.random() < self.reorder_prob:
            i = self._rng.randrange(len(self.out_queue) - 1)
            q = list(self.out_queue)
            q[i], q[-1] = q[-1], q[i]
            self.out_queue = deque(q)

    def deliver_one(self) -> bool:
        if not self.out_queue or self.partner is None:
            return False
        raw = self.out_queue.popleft()
        self._deliver_to_partner(raw)
        return True

    def _deliver_to_partner(self, raw: bytes) -> None:
        """Terminal delivery step (immediate queue pump AND scheduled
        latency/delay arrivals): run the receive-side chaos seam, then
        hand the bytes to the partner. The link may have been severed
        (crash/churn) while a delivery was in flight — those bytes are
        gone, like packets to a dead host."""
        if self.partner is None:
            return
        if chaos.ENABLED:
            # receive-side seam: ctx `node` is the RECEIVER
            try:
                out = chaos.point("overlay.recv", raw,
                                  transport="loopback", _can_delay=True,
                                  **self.partner._chaos_ctx())
            except OSError as e:
                # same contract as a TCP recv error: the receiving
                # peer takes the standard drop path; the crank loop
                # never sees the exception (SimulatedCrash, a
                # BaseException, still unwinds to the app boundary)
                self.partner.drop(f"recv error: {e}")
                return
            if out is chaos.DROP:
                return
            if isinstance(out, chaos.Delay):
                # recv-side delay: schedule the FINAL hop directly —
                # re-running the seam at arrival would consume another
                # hit (a prob-1.0 delay spec would defer forever)
                self._schedule_final(bytes(out.payload), out.seconds)
                return
            if isinstance(out, (bytes, bytearray)):
                raw = out
        if self._final_hold > self.app.clock.now():
            # an earlier recv-delayed delivery is still being held:
            # keep the link FIFO past it
            self._schedule_final(raw, 0.0)
            return
        self._deliver_final(raw)

    def _schedule_final(self, raw: bytes, seconds: float) -> None:
        """Schedule the final hop (post-recv-seam), FIFO-clamped
        against other HELD finals — transit ordering was already
        guaranteed when the transit delivery was scheduled."""
        clock = self.app.clock
        arrival = max(clock.now() + seconds, self._final_hold)
        self._final_hold = arrival
        clock.schedule_at(arrival, lambda err: self._deliver_final(raw))

    def _deliver_final(self, raw: bytes) -> None:
        p = self.partner
        if p is not None and p.state.name != "CLOSING":
            p.recv_bytes(raw)

    def deliver_all(self) -> int:
        n = 0
        while self.deliver_one():
            n += 1
        return n

    def _close_transport(self) -> None:
        # queued bytes (e.g. a final ERROR_MSG) still flush to the
        # partner, as a real socket close would after send
        pass


class LoopbackPeerConnection:
    """Wire two applications' overlays together (reference:
    LoopbackPeerConnection in LoopbackPeer.h)."""

    def __init__(self, app_initiator, app_acceptor,
                 latency_s: float = 0.0,
                 bandwidth_bps: Optional[float] = None):
        self.initiator = LoopbackPeer(app_initiator.overlay_manager,
                                      PeerRole.WE_CALLED_REMOTE)
        self.acceptor = LoopbackPeer(app_acceptor.overlay_manager,
                                     PeerRole.REMOTE_CALLED_US)
        # symmetric per-link latency/bandwidth model (virtual time)
        for p in (self.initiator, self.acceptor):
            p.link_latency_s = latency_s
            p.link_bytes_per_s = (bandwidth_bps / 8.0
                                  if bandwidth_bps else None)
        self.initiator.partner = self.acceptor
        self.acceptor.partner = self.initiator
        app_initiator.overlay_manager.add_pending_peer(self.initiator)
        app_acceptor.overlay_manager.add_pending_peer(self.acceptor)
        self.acceptor.connect_handler()
        self.initiator.connect_handler()

    def crank(self, max_rounds: int = 100) -> int:
        """Ping-pong queued bytes until quiescent."""
        total = 0
        for _ in range(max_rounds):
            n = self.initiator.deliver_all() + self.acceptor.deliver_all()
            total += n
            if n == 0:
                break
        return total
