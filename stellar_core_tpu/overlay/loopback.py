"""In-memory peer pair for hermetic multi-node tests.

Reference: src/overlay/test/LoopbackPeer.{h,cpp} — two Peer objects
joined by in-memory queues, with fault-injection knobs: probabilistic
corruption, drops, duplication and reordering (LoopbackPeer.h:36-103).
Delivery is explicit (`deliver_all`/`deliver_one`) or scheduled on the
shared VirtualClock, keeping tests deterministic.
"""

from __future__ import annotations

import random
from collections import deque
from typing import Deque, Optional

from ..util import chaos
from ..util.logging import get_logger
from .peer import Peer, PeerRole

log = get_logger("Overlay")


class LoopbackPeer(Peer):
    def __init__(self, overlay, role: PeerRole):
        super().__init__(overlay, role)
        self.partner: Optional["LoopbackPeer"] = None
        self.out_queue: Deque[bytes] = deque()
        # fault injection (reference: LoopbackPeer.h damage/drop knobs)
        self.damage_prob = 0.0
        self.drop_prob = 0.0
        self.duplicate_prob = 0.0
        self.reorder_prob = 0.0
        self._rng = random.Random(0x5EED)
        self.corrupt_cert = False

    def _send_bytes(self, raw: bytes) -> None:
        if chaos.ENABLED:
            # chaos seam (the scheduled, seeded superset of the
            # probabilistic knobs below): drop / corrupt / reorder /
            # io_error on the send side
            out = chaos.point("overlay.send", raw, transport="loopback",
                              **self._chaos_ctx())
            if out is chaos.DROP:
                return
            if out is chaos.REORDER:
                # deliver this message BEFORE the previously queued one
                self.out_queue.append(raw)
                if len(self.out_queue) > 1:
                    self.out_queue[-1], self.out_queue[-2] = \
                        self.out_queue[-2], self.out_queue[-1]
                return
            if isinstance(out, (bytes, bytearray)):
                raw = out
        if self._rng.random() < self.drop_prob:
            return
        if self._rng.random() < self.damage_prob and raw:
            i = self._rng.randrange(len(raw))
            raw = raw[:i] + bytes([raw[i] ^ 0xFF]) + raw[i + 1:]
        self.out_queue.append(raw)
        if self._rng.random() < self.duplicate_prob:
            self.out_queue.append(raw)
        if len(self.out_queue) > 1 and \
                self._rng.random() < self.reorder_prob:
            i = self._rng.randrange(len(self.out_queue) - 1)
            q = list(self.out_queue)
            q[i], q[-1] = q[-1], q[i]
            self.out_queue = deque(q)

    def deliver_one(self) -> bool:
        if not self.out_queue or self.partner is None:
            return False
        raw = self.out_queue.popleft()
        if chaos.ENABLED:
            # receive-side seam: ctx `node` is the RECEIVER
            try:
                out = chaos.point("overlay.recv", raw,
                                  transport="loopback",
                                  **self.partner._chaos_ctx())
            except OSError as e:
                # same contract as a TCP recv error: the receiving
                # peer takes the standard drop path; the crank loop
                # never sees the exception (SimulatedCrash, a
                # BaseException, still unwinds to the app boundary)
                self.partner.drop(f"recv error: {e}")
                return True
            if out is chaos.DROP:
                return True
            if isinstance(out, (bytes, bytearray)):
                raw = out
        if self.partner.state.name != "CLOSING":
            self.partner.recv_bytes(raw)
        return True

    def deliver_all(self) -> int:
        n = 0
        while self.deliver_one():
            n += 1
        return n

    def _close_transport(self) -> None:
        # queued bytes (e.g. a final ERROR_MSG) still flush to the
        # partner, as a real socket close would after send
        pass


class LoopbackPeerConnection:
    """Wire two applications' overlays together (reference:
    LoopbackPeerConnection in LoopbackPeer.h)."""

    def __init__(self, app_initiator, app_acceptor):
        self.initiator = LoopbackPeer(app_initiator.overlay_manager,
                                      PeerRole.WE_CALLED_REMOTE)
        self.acceptor = LoopbackPeer(app_acceptor.overlay_manager,
                                     PeerRole.REMOTE_CALLED_US)
        self.initiator.partner = self.acceptor
        self.acceptor.partner = self.initiator
        app_initiator.overlay_manager.add_pending_peer(self.initiator)
        app_acceptor.overlay_manager.add_pending_peer(self.acceptor)
        self.acceptor.connect_handler()
        self.initiator.connect_handler()

    def crank(self, max_rounds: int = 100) -> int:
        """Ping-pong queued bytes until quiescent."""
        total = 0
        for _ in range(max_rounds):
            n = self.initiator.deliver_all() + self.acceptor.deliver_all()
            total += n
            if n == 0:
                break
        return total
