"""TCP transport for peers.

Reference: src/overlay/TCPPeer.{h,cpp} + PeerDoor.{h,cpp} — asio sockets
carrying length-prefixed AuthenticatedMessage frames. Here: non-blocking
stdlib sockets polled from the VirtualClock's io-poller hook, keeping
the single-main-thread discipline (docs/architecture.md:24-36). Frames
are 4-byte big-endian length + XDR bytes, matching the reference's
record-marking layout (high bit unused).
"""

from __future__ import annotations

import errno
import socket
import struct
from typing import List, Optional

from ..util import chaos
from ..util.logging import get_logger
from .peer import Peer, PeerState
from .peer_auth import PeerRole

log = get_logger("Overlay")

MAX_FRAME = 32 * 1024 * 1024


class TCPPeer(Peer):
    def __init__(self, overlay, role: PeerRole, sock: socket.socket):
        super().__init__(overlay, role)
        self.sock = sock
        self.sock.setblocking(False)
        try:
            self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        self._rbuf = b""
        self._wbuf = b""

    # ----------------------------------------------------------- transport --
    def _send_bytes(self, raw: bytes) -> None:
        if chaos.ENABLED:
            # chaos seam: io_error raises (OSError — routed through the
            # standard drop path by _send_message), drop loses the
            # frame, corrupt flips one byte before framing; sentinels
            # with no transport meaning (REORDER/FAIL) leave it intact
            out = chaos.point("overlay.send", raw, transport="tcp",
                              **self._chaos_ctx())
            if out is chaos.DROP:
                return
            if isinstance(out, (bytes, bytearray)):
                raw = out
        self._wbuf += struct.pack(">I", len(raw)) + raw
        self._flush()

    def _flush(self) -> int:
        sent = 0
        while self._wbuf:
            try:
                n = self.sock.send(self._wbuf)
            except BlockingIOError:
                break
            except OSError as e:
                self.drop(f"send error: {e}")
                return sent
            if n <= 0:
                break
            self._wbuf = self._wbuf[n:]
            sent += n
        return sent

    def poll(self) -> int:
        """One io-poller pass: flush writes, drain reads, dispatch
        complete frames. Returns work units done."""
        if self.state == PeerState.CLOSING:
            return 0
        work = 1 if self._flush() else 0
        while True:
            try:
                chunk = self.sock.recv(65536)
            except BlockingIOError:
                break
            except OSError as e:
                self.drop(f"recv error: {e}")
                return work
            if not chunk:
                self.drop("connection closed by remote")
                return work
            if chaos.ENABLED:
                # the received chunk is the payload: io_error takes the
                # same drop path a real socket error would, drop loses
                # the chunk, corrupt flips one byte (lands as a framing
                # /MAC failure downstream)
                try:
                    out = chaos.point("overlay.recv", chunk,
                                      transport="tcp",
                                      **self._chaos_ctx())
                except OSError as e:
                    self.drop(f"recv error: {e}")
                    return work
                if out is chaos.DROP:
                    continue
                if isinstance(out, (bytes, bytearray)):
                    chunk = out
            self._rbuf += chunk
            work += 1
        while len(self._rbuf) >= 4:
            (length,) = struct.unpack(">I", self._rbuf[:4])
            if length > MAX_FRAME:
                self.drop("oversized frame")
                return work
            if len(self._rbuf) < 4 + length:
                break
            frame = self._rbuf[4:4 + length]
            self._rbuf = self._rbuf[4 + length:]
            self.recv_bytes(frame)
            work += 1
        return work

    def _close_transport(self) -> None:
        self._flush()
        try:
            self.sock.close()
        except OSError:
            pass


class PeerDoor:
    """Listening socket accepting inbound peers (reference:
    overlay/PeerDoor.{h,cpp})."""

    def __init__(self, overlay, port: int):
        self.overlay = overlay
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind(("127.0.0.1", port))
        self.sock.listen(16)
        self.sock.setblocking(False)
        self.port = self.sock.getsockname()[1]

    def poll(self) -> int:
        n = 0
        while True:
            try:
                conn, _addr = self.sock.accept()
            except BlockingIOError:
                break
            except OSError:
                break
            peer = TCPPeer(self.overlay, PeerRole.REMOTE_CALLED_US, conn)
            self.overlay.add_pending_peer(peer)
            self.overlay.register_tcp_peer(peer)
            peer.connect_handler()
            n += 1
        return n

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


def connect_to(overlay, host: str, port: int) -> Optional[TCPPeer]:
    """Outbound connection (reference: OverlayManagerImpl::connectTo)."""
    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    sock.setblocking(False)
    try:
        sock.connect((host, port))
    except BlockingIOError:
        pass
    except OSError as e:
        if e.errno != errno.EINPROGRESS:
            log.debug("connect to %s:%d failed: %s", host, port, e)
            sock.close()
            return None
    peer = TCPPeer(overlay, PeerRole.WE_CALLED_REMOTE, sock)
    overlay.add_pending_peer(peer)
    overlay.register_tcp_peer(peer)
    peer.connect_handler()
    return peer
