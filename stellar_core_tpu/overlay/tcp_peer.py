"""TCP transport for peers.

Reference: src/overlay/TCPPeer.{h,cpp} + PeerDoor.{h,cpp} — asio sockets
carrying length-prefixed AuthenticatedMessage frames. Here: non-blocking
stdlib sockets polled from the VirtualClock's io-poller hook, keeping
the single-main-thread discipline (docs/architecture.md:24-36). Frames
are 4-byte big-endian length + XDR bytes, matching the reference's
record-marking layout (high bit unused).
"""

from __future__ import annotations

import errno
import socket
import struct
from collections import deque
from typing import List, Optional

from ..util import chaos
from ..util.logging import get_logger
from .peer import Peer, PeerState
from .peer_auth import PeerRole

log = get_logger("Overlay")

MAX_FRAME = 32 * 1024 * 1024


class TCPPeer(Peer):
    def __init__(self, overlay, role: PeerRole, sock: socket.socket):
        super().__init__(overlay, role)
        self.sock = sock
        self.sock.setblocking(False)
        try:
            self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        self._rbuf = b""
        self._wbuf = b""
        # slow_link shaping (ISSUE 20, chaos.Shape at the overlay.send
        # seam): framed segments held until their release time, paced
        # by a byte/second token budget. Strictly FIFO — a shaped
        # frame never overtakes or is overtaken, so the MAC sequence
        # survives any latency/bandwidth shape. Empty (and unpaid for)
        # unless a slow_link spec is live on this edge.
        self._wqueue: deque = deque()     # (release_time, segment)
        self._shape_bps: Optional[float] = None
        self._shape_budget = 0.0
        self._shape_last: Optional[float] = None
        # crank-coalesced writes (ISSUE 12): frames buffered within a
        # crank flush as ONE socket write on the next crank's posted
        # actions — a 50-advert drain costs one syscall-shaped send,
        # not 50. The flush/frame counters make the coalescing ratio
        # observable (metrics route + Prometheus).
        self._flush_posted = False
        self._pending_frames = 0
        metrics = getattr(self.app, "metrics", None)
        if metrics is not None:
            self._flush_counter = metrics.new_counter(
                "overlay.tcp.write.flush")
            self._frames_counter = metrics.new_counter(
                "overlay.tcp.write.frames")
        else:
            self._flush_counter = self._frames_counter = None
        # socket deadlines (reference: Peer::startRecurrentTimer —
        # PEER_AUTHENTICATION_TIMEOUT / PEER_TIMEOUT): a black-holed
        # peer must not pin a connection slot forever. One recurrent
        # VirtualTimer per peer checks connect / handshake / idle
        # deadlines and tears the peer down through the standard drop
        # path on expiry. Loopback peers (virtual-time simulations)
        # carry no timer: their transport cannot black-hole.
        clock = self.app.clock
        self._t0 = clock.now()
        # inbound sockets arrive established; outbound ones are mid
        # non-blocking connect until the first byte moves
        self._established_at = self._t0 \
            if role == PeerRole.REMOTE_CALLED_US else None
        self._last_read = self._t0
        self._last_keepalive = self._t0
        self._deadline_timer = None
        cfg = self.app.config
        deadlines = [d for d in (cfg.PEER_CONNECT_TIMEOUT,
                                 cfg.PEER_AUTHENTICATION_TIMEOUT,
                                 cfg.PEER_TIMEOUT) if d and d > 0]
        if deadlines:
            from ..util.timer import VirtualTimer
            self._check_interval = max(0.1, min(1.0, min(deadlines) / 2))
            self._deadline_timer = VirtualTimer(clock)
            self._arm_deadline_timer()

    def _arm_deadline_timer(self) -> None:
        if self._deadline_timer is None:
            # the keepalive send inside _check_deadlines can itself hit
            # a dead socket and drop the peer (which clears the timer);
            # re-arming after that would dereference None
            return
        self._deadline_timer.expires_from_now(self._check_interval)
        self._deadline_timer.async_wait(self._check_deadlines)

    def _check_deadlines(self) -> None:
        if self.state == PeerState.CLOSING:
            return
        cfg = self.app.config
        now = self.app.clock.now()
        if self._established_at is None:
            if cfg.PEER_CONNECT_TIMEOUT > 0 and \
                    now - self._t0 > cfg.PEER_CONNECT_TIMEOUT:
                self.drop("connect timeout")
                return
        elif self.state != PeerState.GOT_AUTH:
            if cfg.PEER_AUTHENTICATION_TIMEOUT > 0 and \
                    now - self._established_at > \
                    cfg.PEER_AUTHENTICATION_TIMEOUT:
                self.drop("handshake timeout")
                return
        elif cfg.PEER_TIMEOUT > 0:
            idle = now - self._last_read
            if idle > cfg.PEER_TIMEOUT:
                self.drop("idle timeout")
                return
            if idle > cfg.PEER_TIMEOUT / 2 and \
                    now - self._last_keepalive > cfg.PEER_TIMEOUT / 2:
                # keepalive (reference: the recurrent timer PINGS as
                # well as drops, so an idle-but-healthy link generates
                # read traffic instead of being shot): GET_PEERS is
                # non-flood-controlled and elicits a PEERS reply that
                # refreshes _last_read on both ends; a black-holed
                # peer stays silent and still hits the full deadline
                self._last_keepalive = now
                from ..xdr.overlay import MessageType, StellarMessage
                self.send_message(
                    StellarMessage(MessageType.GET_PEERS))
            self._arm_deadline_timer()
            return
        self._arm_deadline_timer()

    # ----------------------------------------------------------- transport --
    def _send_bytes(self, raw: bytes) -> None:
        shape = None
        if chaos.ENABLED:
            # chaos seam: io_error raises (OSError — routed through the
            # standard drop path by _send_message), drop loses the
            # frame, corrupt flips one byte before framing, slow_link
            # returns a Shape (delay + bandwidth) this frame is paced
            # by; sentinels with no transport meaning (REORDER/FAIL)
            # leave it intact
            out = chaos.point("overlay.send", raw, transport="tcp",
                              now=self.app.clock.now(),
                              **self._chaos_ctx())
            if out is chaos.DROP:
                return
            if isinstance(out, chaos.Shape):
                shape = out
            elif isinstance(out, (bytes, bytearray)):
                raw = out
        framed = struct.pack(">I", len(raw)) + raw
        if shape is not None or self._wqueue:
            # shaped path. An unshaped frame arriving while shaped
            # segments are pending queues BEHIND them (release clamped
            # monotonic): FIFO survives the shape window's edges.
            now = self.app.clock.now()
            release = now + (shape.delay_s if shape is not None else 0.0)
            if self._wqueue and release < self._wqueue[-1][0]:
                release = self._wqueue[-1][0]
            self._wqueue.append((release, framed))
            if shape is not None:
                self._shape_bps = shape.bytes_per_s
        else:
            self._wbuf += framed
        self._pending_frames += 1
        # coalesce: don't write per frame — schedule ONE flush for the
        # crank boundary so every frame produced this crank (an advert
        # drain, an SCP broadcast burst, a demand answer batch) leaves
        # in a single buffered send
        if not self._flush_posted:
            self._flush_posted = True
            self.app.clock.post(self._posted_flush)

    def _posted_flush(self) -> None:
        self._flush_posted = False
        if self.state == PeerState.CLOSING:
            return
        self._flush()

    def _drain_shaped(self) -> None:
        """Move shaped segments whose release time has passed into the
        write buffer, paced by the token budget when the shape carries
        a bandwidth. Called from every flush: delivery granularity is
        the io-poll/crank cadence, which is exactly the granularity a
        real kernel-scheduled slow link shows the application."""
        if not self._wqueue:
            return
        now = self.app.clock.now()
        bps = self._shape_bps
        if bps:
            if self._shape_last is not None:
                self._shape_budget += (now - self._shape_last) * bps
            self._shape_last = now
            # cap the accumulated allowance: an idle gap must not bank
            # into a burst that defeats the throttle
            cap = max(bps * 0.25, 65536.0)
            if self._shape_budget > cap:
                self._shape_budget = cap
        while self._wqueue and self._wqueue[0][0] <= now:
            release, seg = self._wqueue[0]
            if bps:
                take = min(len(seg), int(self._shape_budget))
                if take <= 0:
                    break
                self._shape_budget -= take
            else:
                take = len(seg)
            self._wbuf += seg[:take]
            if take == len(seg):
                self._wqueue.popleft()
            else:
                self._wqueue[0] = (release, seg[take:])

    def _flush(self) -> int:
        self._drain_shaped()
        if self._pending_frames:
            if self._flush_counter is not None:
                self._flush_counter.inc()
                self._frames_counter.inc(self._pending_frames)
            self._pending_frames = 0
        sent = 0
        while self._wbuf:
            try:
                n = self.sock.send(self._wbuf)
            except BlockingIOError:
                break
            except OSError as e:
                self.drop(f"send error: {e}")
                return sent
            if n <= 0:
                break
            if self._established_at is None:
                # first byte moved: the non-blocking connect completed
                self._established_at = self.app.clock.now()
            self._wbuf = self._wbuf[n:]
            sent += n
        return sent

    def poll(self) -> int:
        """One io-poller pass: flush writes, drain reads, dispatch
        complete frames. Returns work units done."""
        if self.state == PeerState.CLOSING:
            return 0
        work = 1 if self._flush() else 0
        while True:
            try:
                chunk = self.sock.recv(65536)
            except BlockingIOError:
                break
            except OSError as e:
                self.drop(f"recv error: {e}")
                return work
            if not chunk:
                self.drop("connection closed by remote")
                return work
            now = self.app.clock.now()
            self._last_read = now
            if self._established_at is None:
                self._established_at = now
            if chaos.ENABLED:
                # the received chunk is the payload: io_error takes the
                # same drop path a real socket error would, drop loses
                # the chunk, corrupt flips one byte (lands as a framing
                # /MAC failure downstream)
                try:
                    out = chaos.point("overlay.recv", chunk,
                                      transport="tcp",
                                      **self._chaos_ctx())
                except OSError as e:
                    self.drop(f"recv error: {e}")
                    return work
                if out is chaos.DROP:
                    continue
                if isinstance(out, (bytes, bytearray)):
                    chunk = out
            self._rbuf += chunk
            work += 1
        while len(self._rbuf) >= 4:
            (length,) = struct.unpack(">I", self._rbuf[:4])
            if length > MAX_FRAME:
                self.drop("oversized frame")
                return work
            if len(self._rbuf) < 4 + length:
                break
            frame = self._rbuf[4:4 + length]
            self._rbuf = self._rbuf[4 + length:]
            self.recv_bytes(frame)
            work += 1
        return work

    def _close_transport(self) -> None:
        if self._deadline_timer is not None:
            self._deadline_timer.cancel()
            self._deadline_timer = None
        self._flush()
        try:
            self.sock.close()
        except OSError:
            pass


class PeerDoor:
    """Listening socket accepting inbound peers (reference:
    overlay/PeerDoor.{h,cpp})."""

    def __init__(self, overlay, port: int):
        self.overlay = overlay
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind(("127.0.0.1", port))
        self.sock.listen(16)
        self.sock.setblocking(False)
        self.port = self.sock.getsockname()[1]

    def poll(self) -> int:
        n = 0
        while True:
            try:
                conn, _addr = self.sock.accept()
            except BlockingIOError:
                break
            except OSError:
                break
            peer = TCPPeer(self.overlay, PeerRole.REMOTE_CALLED_US, conn)
            self.overlay.add_pending_peer(peer)
            self.overlay.register_tcp_peer(peer)
            peer.connect_handler()
            n += 1
        return n

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


def connect_to(overlay, host: str, port: int) -> Optional[TCPPeer]:
    """Outbound connection (reference: OverlayManagerImpl::connectTo)."""
    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    sock.setblocking(False)
    try:
        sock.connect((host, port))
    except BlockingIOError:
        pass
    except OSError as e:
        if e.errno != errno.EINPROGRESS:
            log.debug("connect to %s:%d failed: %s", host, port, e)
            sock.close()
            return None
    peer = TCPPeer(overlay, PeerRole.WE_CALLED_REMOTE, sock)
    overlay.add_pending_peer(peer)
    overlay.register_tcp_peer(peer)
    peer.connect_handler()
    return peer
