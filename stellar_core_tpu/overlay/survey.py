"""Network topology survey.

Reference: src/overlay/SurveyManager.{h,cpp} + SurveyMessageLimiter —
an authenticated, encrypted survey protocol relayed over the overlay:
the surveyor signs SURVEY_REQUEST messages naming a surveyed peer and an
ephemeral Curve25519 key; the surveyed node answers with its peer
statistics encrypted to that key; intermediate nodes relay both
directions. Results feed the `surveytopology`/`getsurveyresult` admin
commands and scripts/OverlaySurvey.py-style walkers.

Encryption: sealed-box construction from the primitives in crypto/
(ephemeral X25519 → HKDF stream key + HMAC tag; the reference uses
libsodium's crypto_box_seal — same shape, same key exchange).
"""

from __future__ import annotations

import hashlib
import hmac as _hmac
import os
import struct
from typing import Dict, List, Optional

from ..crypto.curve25519 import Curve25519Public, Curve25519Secret
from ..crypto.keys import PubKeyUtils
from ..crypto.sha import hkdf_expand, hkdf_extract, sha256
from ..util.logging import get_logger
from ..xdr.overlay import (MessageType, PeerStats,
                           SignedSurveyRequestMessage,
                           SignedSurveyResponseMessage, StellarMessage,
                           SurveyMessageCommandType, SurveyRequestMessage,
                           SurveyResponseMessage, SurveyResponseBody,
                           TopologyResponseBody)
from ..xdr.types import Curve25519Public as XdrCurve25519Public
from ..xdr.types import EnvelopeType, PublicKey

log = get_logger("Overlay")


# ------------------------------------------------------------- sealed box --

def _stream(key: bytes, n: int) -> bytes:
    out = b""
    counter = 0
    while len(out) < n:
        out += hashlib.sha256(key + struct.pack(">Q", counter)).digest()
        counter += 1
    return out[:n]


def seal(recipient_pub: bytes, plaintext: bytes) -> bytes:
    eph = Curve25519Secret.random()
    shared = eph.ecdh(Curve25519Public(recipient_pub), local_first=True)
    key = hkdf_expand(shared, b"survey-seal", 64)
    ct = bytes(a ^ b for a, b in zip(plaintext,
                                     _stream(key[:32], len(plaintext))))
    tag = _hmac.new(key[32:], ct, hashlib.sha256).digest()
    return eph.derive_public().key + tag + ct


def unseal(secret: Curve25519Secret, sealed: bytes) -> Optional[bytes]:
    if len(sealed) < 64:
        return None
    eph_pub, tag, ct = sealed[:32], sealed[32:64], sealed[64:]
    # recipient computes the same shared secret with roles flipped
    shared = secret.ecdh(Curve25519Public(eph_pub), local_first=False)
    key = hkdf_expand(shared, b"survey-seal", 64)
    if not _hmac.compare_digest(
            _hmac.new(key[32:], ct, hashlib.sha256).digest(), tag):
        return None
    return bytes(a ^ b for a, b in zip(ct, _stream(key[:32], len(ct))))


def _request_sign_bytes(network_id: bytes,
                        req: SurveyRequestMessage) -> bytes:
    return sha256(network_id
                  + struct.pack(">i", EnvelopeType.ENVELOPE_TYPE_AUTH)
                  + b"survey-req" + req.to_bytes())


def _response_sign_bytes(network_id: bytes,
                         resp: SurveyResponseMessage) -> bytes:
    return sha256(network_id
                  + struct.pack(">i", EnvelopeType.ENVELOPE_TYPE_AUTH)
                  + b"survey-resp" + resp.to_bytes())


class SurveyManager:
    def __init__(self, app):
        self.app = app
        self._secret = Curve25519Secret.random()
        self.results: Dict[bytes, dict] = {}   # surveyed node -> topology
        self._relayed: set = set()

    # -------------------------------------------------------------- start --
    def survey_peer(self, surveyed_raw: bytes) -> None:
        """Send a signed request for one node's topology (reference:
        SurveyManager::addNodeToRunningSurveyBacklog + sendTopologyRequest)."""
        cfg = self.app.config
        req = SurveyRequestMessage(
            surveyorPeerID=PublicKey.ed25519(cfg.node_id()),
            surveyedPeerID=PublicKey.ed25519(surveyed_raw),
            ledgerNum=self.app.ledger_manager.get_last_closed_ledger_num(),
            encryptionKey=XdrCurve25519Public(
                key=self._secret.derive_public().key),
            commandType=SurveyMessageCommandType.SURVEY_TOPOLOGY)
        signed = SignedSurveyRequestMessage(
            requestSignature=cfg.NODE_SEED.sign(
                _request_sign_bytes(cfg.network_id(), req)),
            request=req)
        self.app.overlay_manager.broadcast_message(StellarMessage(
            MessageType.SURVEY_REQUEST, signed))

    # ------------------------------------------------------------- handling --
    def handle_request(self, peer, msg: StellarMessage) -> None:
        signed: SignedSurveyRequestMessage = msg.value
        req = signed.request
        network_id = self.app.config.network_id()
        if not PubKeyUtils.verify_sig(
                bytes(req.surveyorPeerID.value),
                bytes(signed.requestSignature),
                _request_sign_bytes(network_id, req)):
            return
        if bytes(req.surveyedPeerID.value) == self.app.config.node_id():
            self._respond(req)
        else:
            self._relay(msg)

    def _respond(self, req: SurveyRequestMessage) -> None:
        cfg = self.app.config
        body = SurveyResponseBody(
            SurveyMessageCommandType.SURVEY_TOPOLOGY,
            self._topology_body())
        sealed = seal(bytes(req.encryptionKey.key), body.to_bytes())
        resp = SurveyResponseMessage(
            surveyorPeerID=req.surveyorPeerID,
            surveyedPeerID=PublicKey.ed25519(cfg.node_id()),
            ledgerNum=req.ledgerNum,
            commandType=SurveyMessageCommandType.SURVEY_TOPOLOGY,
            encryptedBody=sealed)
        signed = SignedSurveyResponseMessage(
            responseSignature=cfg.NODE_SEED.sign(
                _response_sign_bytes(cfg.network_id(), resp)),
            response=resp)
        self.app.overlay_manager.broadcast_message(StellarMessage(
            MessageType.SURVEY_RESPONSE, signed))

    def _topology_body(self) -> TopologyResponseBody:
        om = self.app.overlay_manager
        from .peer_auth import PeerRole

        def stats(p) -> PeerStats:
            return PeerStats(
                id=PublicKey.ed25519(p.peer_id),
                versionStr=p.remote_version.encode()[:100],
                messagesRead=p.messages_read,
                messagesWritten=p.messages_written,
                bytesRead=p.bytes_read, bytesWritten=p.bytes_written,
                secondsConnected=0, uniqueFloodBytesRecv=0,
                duplicateFloodBytesRecv=0, uniqueFetchBytesRecv=0,
                duplicateFetchBytesRecv=0, uniqueFloodMessageRecv=0,
                duplicateFloodMessageRecv=0, uniqueFetchMessageRecv=0,
                duplicateFetchMessageRecv=0)

        inbound = [stats(p) for p in om.get_authenticated_peers()
                   if p.role == PeerRole.REMOTE_CALLED_US][:25]
        outbound = [stats(p) for p in om.get_authenticated_peers()
                    if p.role == PeerRole.WE_CALLED_REMOTE][:25]
        return TopologyResponseBody(
            inboundPeers=inbound, outboundPeers=outbound,
            totalInboundPeerCount=len(inbound),
            totalOutboundPeerCount=len(outbound))

    def handle_response(self, peer, msg: StellarMessage) -> None:
        signed: SignedSurveyResponseMessage = msg.value
        resp = signed.response
        network_id = self.app.config.network_id()
        if not PubKeyUtils.verify_sig(
                bytes(resp.surveyedPeerID.value),
                bytes(signed.responseSignature),
                _response_sign_bytes(network_id, resp)):
            return
        if bytes(resp.surveyorPeerID.value) == self.app.config.node_id():
            plain = unseal(self._secret, bytes(resp.encryptedBody))
            if plain is None:
                log.debug("survey response failed to unseal")
                return
            body = SurveyResponseBody.from_bytes(plain)
            self.results[bytes(resp.surveyedPeerID.value)] = \
                _topology_json(body.value)
        else:
            self._relay(msg)

    def _relay(self, msg: StellarMessage) -> None:
        h = sha256(msg.to_bytes())
        if h in self._relayed:
            return
        self._relayed.add(h)
        self.app.overlay_manager.broadcast_message(msg)

    def results_json(self) -> dict:
        from ..crypto.strkey import StrKey
        # snapshot: HTTP threads read while the crank thread inserts
        return {StrKey.encode_ed25519_public(k): v
                for k, v in dict(self.results).items()}


def _topology_json(body: TopologyResponseBody) -> dict:
    from ..crypto.strkey import StrKey

    def fmt(peers):
        return [{"nodeId": StrKey.encode_ed25519_public(
                    bytes(p.id.value)),
                 "bytesRead": p.bytesRead,
                 "bytesWritten": p.bytesWritten,
                 "messagesRead": p.messagesRead,
                 "messagesWritten": p.messagesWritten} for p in peers]

    return {
        "inboundPeers": fmt(body.inboundPeers),
        "outboundPeers": fmt(body.outboundPeers),
        "totalInbound": body.totalInboundPeerCount,
        "totalOutbound": body.totalOutboundPeerCount,
    }
