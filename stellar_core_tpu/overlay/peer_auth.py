"""Per-connection authentication material.

Reference: src/overlay/PeerAuth.{h,cpp} — each node keeps one X25519
session keypair and an AuthCert: the session pubkey + expiration signed
by the long-lived Ed25519 node key over
SHA256(networkID ‖ ENVELOPE_TYPE_AUTH ‖ expiration ‖ pubkey). After
HELLO exchange, ECDH + HKDF derive one HMAC-SHA256 key per direction,
bound to both sides' nonces and the caller/callee roles.
"""

from __future__ import annotations

import struct
from enum import Enum
from typing import Dict, Tuple

from ..crypto.curve25519 import (Curve25519Public, Curve25519Secret,
                                 expand_session_key)
from ..crypto.keys import PubKeyUtils
from ..crypto.sha import sha256
from ..xdr.overlay import AuthCert
from ..xdr.types import Curve25519Public as XdrCurve25519Public
from ..xdr.types import EnvelopeType

# reference: PeerAuth.cpp expirationLimit — certs live an hour
CERT_EXPIRATION_SECONDS = 3600


class PeerRole(Enum):
    WE_CALLED_REMOTE = 0
    REMOTE_CALLED_US = 1


def _cert_hash(network_id: bytes, expiration: int, pubkey: bytes) -> bytes:
    # xdr_to_opaque(networkID, ENVELOPE_TYPE_AUTH, expiration, pubkey)
    return sha256(network_id
                  + struct.pack(">i", EnvelopeType.ENVELOPE_TYPE_AUTH)
                  + struct.pack(">Q", expiration) + pubkey)


class PeerAuth:
    def __init__(self, config, now_fn):
        self.config = config
        self.network_id = config.network_id()
        self._now = now_fn
        self._secret = Curve25519Secret.random()
        self._public = self._secret.derive_public()
        self._cert = self._make_cert()
        self._shared_cache: Dict[Tuple[bytes, PeerRole], bytes] = {}

    def _make_cert(self) -> AuthCert:
        expiration = int(self._now()) + CERT_EXPIRATION_SECONDS
        h = _cert_hash(self.network_id, expiration, self._public.key)
        return AuthCert(pubkey=XdrCurve25519Public(key=self._public.key),
                        expiration=expiration,
                        sig=self.config.NODE_SEED.sign(h))

    def get_auth_cert(self) -> AuthCert:
        if self._cert.expiration < int(self._now()) + \
                CERT_EXPIRATION_SECONDS // 2:
            self._cert = self._make_cert()
        return self._cert

    def verify_remote_cert(self, remote_node_raw: bytes,
                           cert: AuthCert) -> bool:
        if cert.expiration < int(self._now()):
            return False
        h = _cert_hash(self.network_id, cert.expiration,
                       bytes(cert.pubkey.key))
        return PubKeyUtils.verify_sig(remote_node_raw, bytes(cert.sig), h)

    # ---------------------------------------------------------------- keys --
    def _shared_key(self, remote_public: bytes, role: PeerRole) -> bytes:
        k = self._shared_cache.get((remote_public, role))
        if k is None:
            k = self._secret.ecdh(
                Curve25519Public(remote_public),
                local_first=(role == PeerRole.WE_CALLED_REMOTE))
            self._shared_cache[(remote_public, role)] = k
        return k

    def get_sending_mac_key(self, remote_public: bytes, local_nonce: bytes,
                            remote_nonce: bytes, role: PeerRole) -> bytes:
        if role == PeerRole.WE_CALLED_REMOTE:
            buf = b"\x00" + local_nonce + remote_nonce   # K_AB, A=local
        else:
            buf = b"\x01" + local_nonce + remote_nonce   # K_BA, B=local
        return expand_session_key(self._shared_key(remote_public, role), buf)

    def get_receiving_mac_key(self, remote_public: bytes,
                              local_nonce: bytes, remote_nonce: bytes,
                              role: PeerRole) -> bytes:
        if role == PeerRole.WE_CALLED_REMOTE:
            buf = b"\x01" + remote_nonce + local_nonce   # K_BA, A=local
        else:
            buf = b"\x00" + remote_nonce + local_nonce   # K_AB, B=local
        return expand_session_key(self._shared_key(remote_public, role), buf)
