"""Anycast fetch of tx sets / quorum sets from peers.

Reference: src/overlay/ItemFetcher.{h,cpp} + Tracker — for each wanted
hash, ask one authenticated peer at a time; on DONT_HAVE or timeout move
to the next; stop when the item arrives (PendingEnvelopes is told by the
overlay manager, which then recycles ready envelopes into the herder).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

from ..util.logging import get_logger
from ..util.timer import VirtualTimer
from ..xdr.overlay import MessageType, StellarMessage

log = get_logger("Overlay")

# reference: MS_TO_WAIT_FOR_FETCH_REPLY
FETCH_REPLY_TIMEOUT = 1.5


class _Tracker:
    def __init__(self, item_hash: bytes, msg_type: MessageType):
        self.item_hash = item_hash
        self.msg_type = msg_type
        self.asked: List[int] = []         # id(peer) already tried
        self.current_peer = None
        self.timer: Optional[VirtualTimer] = None
        self.tries = 0


class ItemFetcher:
    """One instance per item kind (GET_TX_SET / GET_SCP_QUORUMSET)."""

    def __init__(self, overlay, msg_type: MessageType):
        self.overlay = overlay
        self.msg_type = msg_type
        self._trackers: Dict[bytes, _Tracker] = {}

    def fetch(self, item_hash: bytes) -> None:
        if item_hash in self._trackers:
            return
        tracker = _Tracker(item_hash, self.msg_type)
        self._trackers[item_hash] = tracker
        self._try_next_peer(tracker)

    def stop_fetch(self, item_hash: bytes) -> None:
        tracker = self._trackers.pop(item_hash, None)
        if tracker is not None and tracker.timer is not None:
            tracker.timer.cancel()

    def recv(self, item_hash: bytes) -> None:
        """Item arrived (from any peer)."""
        self.stop_fetch(item_hash)

    def dont_have(self, item_hash: bytes, peer) -> None:
        tracker = self._trackers.get(item_hash)
        if tracker is not None and tracker.current_peer is peer:
            self._try_next_peer(tracker)

    def peer_dropped(self, peer) -> None:
        for tracker in list(self._trackers.values()):
            if tracker.current_peer is peer:
                self._try_next_peer(tracker)

    def fetching_count(self) -> int:
        return len(self._trackers)

    def _try_next_peer(self, tracker: _Tracker) -> None:
        if tracker.timer is not None:
            tracker.timer.cancel()
            tracker.timer = None
        peers = [p for p in self.overlay.get_authenticated_peers()
                 if id(p) not in tracker.asked]
        if not peers:
            # everyone asked: start over (reference: tryNextPeer wraps
            # around, envelopes referencing the item may still arrive)
            tracker.asked.clear()
            peers = self.overlay.get_authenticated_peers()
            if not peers:
                # no peers at all: retry when one connects
                tracker.current_peer = None
                return
        peer = peers[0]
        tracker.current_peer = peer
        tracker.asked.append(id(peer))
        tracker.tries += 1
        peer.send_message(StellarMessage(self.msg_type,
                                         tracker.item_hash))
        timer = VirtualTimer(self.overlay.app.clock)
        timer.expires_from_now(FETCH_REPLY_TIMEOUT)
        timer.async_wait(lambda: self._timeout(tracker))
        tracker.timer = timer

    def _timeout(self, tracker: _Tracker) -> None:
        tracker.timer = None
        if tracker.item_hash in self._trackers:
            self._try_next_peer(tracker)

    def peer_connected(self) -> None:
        """A peer authenticated: kick any stalled trackers."""
        for tracker in self._trackers.values():
            if tracker.current_peer is None:
                self._try_next_peer(tracker)
