"""Peer connection state machine.

Reference: src/overlay/Peer.{h,cpp} — handshake
(HELLO → HELLO → AUTH → AUTH, :125,350,907-914,1369-1430), HMAC-framed
`AuthenticatedMessage`s with per-direction sequence numbers
(:690,739-749), and the big message dispatch (:519-585). Transport
(loopback queues or TCP) lives in subclasses via `_send_bytes`;
everything protocol lives here.
"""

from __future__ import annotations

import os
import struct
from enum import Enum
from typing import Callable, Optional

from ..crypto.sha import hmac_sha256, hmac_sha256_verify
from ..util import chaos, tracing
from ..util.logging import get_logger
from ..xdr.overlay import (Auth, AuthenticatedMessage, Error, ErrorCode,
                           Hello, MessageType, StellarMessage,
                           _AuthenticatedMessageV0)
from ..xdr.types import PublicKey
from . import wire
from .flow_control import FlowControl, is_flow_controlled
from .peer_auth import PeerRole

log = get_logger("Overlay")

OVERLAY_VERSION = 29          # current overlay protocol (reference Config)
OVERLAY_MIN_VERSION = 27
VERSION_STR = b"stellar-core-tpu dev"


class PeerState(Enum):
    # reference: Peer.h PeerState
    CONNECTING = 0
    CONNECTED = 1
    GOT_HELLO = 2
    GOT_AUTH = 3
    CLOSING = 4


class Peer:
    def __init__(self, overlay, role: PeerRole):
        self.overlay = overlay
        self.app = overlay.app
        self.role = role
        self.state = PeerState.CONNECTING
        self.peer_id: Optional[bytes] = None     # remote node id (raw)
        self.remote_listening_port = 0
        self.remote_version = ""
        self.remote_overlay_version = 0
        self.local_nonce = os.urandom(32)
        self.remote_nonce: Optional[bytes] = None
        self.remote_pub: Optional[bytes] = None  # remote session X25519
        self.send_mac_key: Optional[bytes] = None
        self.recv_mac_key: Optional[bytes] = None
        self.send_mac_seq = 0
        self.recv_mac_seq = 0
        self.flow = FlowControl(self.app.config,
                                getattr(overlay, "encode_counters",
                                        None),
                                getattr(overlay, "flow_drop_counters",
                                        None))
        self._chaos_held: list = []   # messages held back by a reorder fault
        self.messages_read = 0
        self.messages_written = 0
        self.bytes_read = 0
        self.bytes_written = 0
        # redundant flood deliveries attributed to this peer (a tx or
        # SCP envelope this node had already seen): the per-link share
        # of the mesh's flood redundancy, on the `peers` route
        self.duplicate_messages = 0
        # invalid-signature transactions attributed to this peer
        # (overlay/manager.py batched-admission accounting): past
        # PEER_BAD_SIG_DROP_THRESHOLD the peer is dropped
        self.bad_sig_drops = 0
        # flood frames from this peer dropped by the adaptive
        # controller's surge gate BEFORE verify dispatch
        # (ops/controller.py) — load accounting, not a sanction
        self.shed_drops = 0
        # single-flight demand accounting (ISSUE 12, tx_advert.py
        # TxDemandsManager): FLOOD_DEMANDs we sent this peer, bodies
        # it answered with, demands it let time out, and demands
        # re-routed TO it after another peer timed out — the per-link
        # view of pull-mode flooding on the `peers` route
        self.demand_sent = 0
        self.demand_fulfilled = 0
        self.demand_timeout = 0
        self.demand_retry = 0
        # aggregate overlay.peer.* meters (per-peer counts live on the
        # peer object and surface via the `peers` admin route; the
        # registry meters feed `metrics` + the survey tooling)
        metrics = getattr(self.app, "metrics", None)
        if metrics is not None:
            self._msg_out_meter = metrics.new_meter(
                "overlay.peer.message.sent")
            self._msg_in_meter = metrics.new_meter(
                "overlay.peer.message.received")
            self._byte_out_meter = metrics.new_meter(
                "overlay.peer.byte.sent", "byte")
            self._byte_in_meter = metrics.new_meter(
                "overlay.peer.byte.received", "byte")
        else:
            self._msg_out_meter = self._msg_in_meter = None
            self._byte_out_meter = self._byte_in_meter = None

    def reset_traffic_counters(self) -> None:
        """`clearmetrics` hook: zero the per-peer message/byte/
        duplicate counters so bench legs in one process measure from a
        clean slate. Bad-sig accounting deliberately survives — it
        feeds the PEER_BAD_SIG_DROP_THRESHOLD security drop."""
        self.messages_read = self.messages_written = 0
        self.bytes_read = self.bytes_written = 0
        self.duplicate_messages = 0
        # shed accounting resets with the controller state (the
        # clearmetrics clean-slate contract); bad-sig survives above
        self.shed_drops = 0
        self.demand_sent = self.demand_fulfilled = 0
        self.demand_timeout = self.demand_retry = 0

    # ----------------------------------------------------------- identity --
    def is_authenticated(self) -> bool:
        return self.state == PeerState.GOT_AUTH

    def __repr__(self):
        pid = self.peer_id.hex()[:8] if self.peer_id else "?"
        return f"<Peer {pid} {self.role.name} {self.state.name}>"

    def _chaos_ctx(self) -> dict:
        """Context for chaos injection points: `node` is the local node
        running this peer object, `peer` the remote (when known)."""
        cfg = self.app.config
        return {
            "node": cfg.node_id().hex() if cfg.NODE_SEED is not None
            else "",
            "peer": self.peer_id.hex() if self.peer_id else "",
        }

    # ------------------------------------------------------------ lifecycle --
    def connect_handler(self) -> None:
        """Transport established; the caller speaks first (reference:
        connectHandler → sendHello)."""
        rec = getattr(self.app, "input_recorder", None)
        if rec is not None and rec.active:
            # input log (replay/recorder.py): connections are numbered
            # in establishment order; every recorded frame refers back
            # to its conn id
            rec.record_conn(self)
        self.state = PeerState.CONNECTED
        if self.role == PeerRole.WE_CALLED_REMOTE:
            self.send_hello()

    def drop(self, reason: str = "") -> None:
        if self.state == PeerState.CLOSING:
            return
        rec = getattr(self.app, "input_recorder", None)
        if rec is not None and rec.active:
            # protocol drops re-derive on replay (the PDROP is then an
            # idempotent no-op); driver drops — a crashed partner — only
            # exist in the log
            rec.record_pdrop(self, reason)
        self.state = PeerState.CLOSING
        log.debug("dropping peer %r: %s", self, reason)
        self.overlay.record_drop_reason(reason)
        self.overlay.peer_dropped(self)
        self._close_transport()

    def _close_transport(self) -> None:
        pass

    # ------------------------------------------------------------ sending --
    def send_hello(self) -> None:
        cfg = self.app.config
        lcl = self.app.ledger_manager.get_last_closed_ledger_header()
        hello = Hello(
            ledgerVersion=lcl.ledgerVersion,
            overlayVersion=cfg.OVERLAY_PROTOCOL_VERSION,
            overlayMinVersion=cfg.OVERLAY_PROTOCOL_MIN_VERSION,
            networkID=cfg.network_id(),
            versionStr=(cfg.VERSION_STR.encode()[:100]
                        if cfg.VERSION_STR else VERSION_STR),
            listeningPort=cfg.PEER_PORT,
            peerID=PublicKey.ed25519(cfg.node_id()),
            cert=self.overlay.peer_auth.get_auth_cert(),
            nonce=self.local_nonce)
        self._send_message(StellarMessage(MessageType.HELLO, hello))

    def send_auth(self) -> None:
        self._send_message(StellarMessage(MessageType.AUTH, Auth(flags=0)))

    def send_error_and_drop(self, code: ErrorCode, msg: str) -> None:
        try:
            self._send_message(StellarMessage(
                MessageType.ERROR_MSG,
                Error(code=code, msg=msg.encode()[:100])))
        finally:
            self.drop(msg)

    def send_message(self, msg: StellarMessage) -> None:
        """Public send — flood messages respect flow-control credit."""
        if self.state == PeerState.CLOSING:
            return
        if chaos.ENABLED:
            # link-level chaos seam (ISSUE 20): a `partition` or `flap`
            # spec matching this edge severs the connection outright —
            # the minority side stalls, the jittered redial re-knits
            # the mesh after heal. Checked per send because a link cut
            # is a condition, not an event: the first send inside the
            # window kills the link.
            link = chaos.point("overlay.link", None,
                               now=self.app.clock.now(),
                               **self._chaos_ctx())
            if link is chaos.DROP:
                self.drop("link down: chaos partition/flap")
                return
            # message-level chaos seam, BEFORE the HMAC sequence number
            # is assigned: a dropped or held-back message models a lossy
            # / reordering network without violating the MAC sequence
            # (transport-level loss is the `overlay.send` seam and —
            # correctly — kills the link like a real socket would)
            out = chaos.point("overlay.message", msg,
                              **self._chaos_ctx())
            if out is chaos.DROP:
                return
            if out is chaos.REORDER:
                self._chaos_held.append(msg)
                return
        ready = self.flow.try_send(msg)
        if ready is not None:
            self._send_message(ready)
        if self._chaos_held:
            # flush reorder-held messages AFTER the one just sent — a
            # deterministic one-slot delivery reordering. Deliberately
            # NOT gated on chaos.ENABLED (an empty-list check when
            # disabled): a message held when the engine is uninstalled
            # must still go out on the next send rather than silently
            # degrade the declared reorder into a drop. A reorder on a
            # peer's FINAL send does stay held — schedule reorders
            # mid-stream, not on the last message.
            held, self._chaos_held = self._chaos_held, []
            for m in held:
                ready = self.flow.try_send(m)
                if ready is not None:
                    self._send_message(ready)

    def _send_message(self, msg: StellarMessage) -> None:
        """Frame with sequence + HMAC and hand to the transport.

        Serialize-once (ISSUE 12): the body is encoded at most once
        per message OBJECT — a broadcast to N peers pays one XDR
        encoding, then each peer splices its own ~40 bytes of
        sequence + MAC around the shared body. Byte-identical to
        framing through `AuthenticatedMessage.to_bytes()` (parity
        pinned by tests/test_wire_path.py)."""
        if self.state == PeerState.CLOSING:
            return
        body = wire.body_bytes(msg, self.overlay.encode_counters)
        mac = b"\x00" * wire.MAC_LEN
        seq = 0
        if self.send_mac_key is not None and \
                msg.disc not in (MessageType.HELLO, MessageType.ERROR_MSG):
            seq = self.send_mac_seq
            mac = hmac_sha256(self.send_mac_key,
                              struct.pack(">Q", seq) + body)
            self.send_mac_seq += 1
        raw = wire.assemble_frame(seq, body, mac)
        self.messages_written += 1
        self.bytes_written += len(raw)
        if self._msg_out_meter is not None:
            self._msg_out_meter.mark()
            self._byte_out_meter.mark(len(raw))
        if tracing.ENABLED:
            rec = self.app.flight_recorder
            if rec.active:
                rec.instant("overlay.send", {
                    "type": msg.disc.name, "bytes": len(raw),
                    "peer": self.peer_id.hex()[:8]
                    if self.peer_id else "?"})
        try:
            self._send_bytes(raw)
        except OSError as e:
            # a transport error mid-write tears the peer down through
            # the standard drop path (flow-control state goes with the
            # peer, floodgate/fetchers unsubscribe in peer_dropped) and
            # must never unwind into the caller's scheduler loop
            self.drop(f"send error: {e}")

    def _send_bytes(self, raw: bytes) -> None:
        raise NotImplementedError

    # ----------------------------------------------------------- receiving --
    def recv_bytes(self, raw: bytes) -> None:
        rec = getattr(self.app, "input_recorder", None)
        if rec is not None and rec.active:
            # record BEFORE parsing: a malformed frame must replay as
            # the same malformed bytes (serialize-once — `raw` is the
            # exact wire slice, never re-encoded)
            rec.record_frame(self, raw)
        self.bytes_read += len(raw)
        if self._byte_in_meter is not None:
            self._byte_in_meter.mark(len(raw))
        try:
            amsg = AuthenticatedMessage.from_bytes(raw)
        except Exception as e:
            self.send_error_and_drop(ErrorCode.ERR_DATA,
                                     f"malformed message: {e}")
            return
        self.recv_authenticated_message(amsg.value, frame=raw)

    def recv_authenticated_message(self, v0: _AuthenticatedMessageV0,
                                   frame: Optional[bytes] = None
                                   ) -> None:
        """`frame`, when given, is the exact wire frame `v0` was parsed
        from: the MAC is verified over the received slice
        `frame[4:-32]` (sequence ‖ body as transmitted) instead of
        re-encoding the parsed message — one XDR encoding saved per
        delivery, and strictly more faithful: a corrupted byte the
        parser tolerates (e.g. a flipped padding byte the re-encoding
        would canonicalize away) now still fails the MAC, exactly as
        the reference verifying over the received buffer does."""
        msg = v0.message
        if msg.disc not in (MessageType.HELLO, MessageType.ERROR_MSG):
            if self.recv_mac_key is not None:
                if v0.sequence != self.recv_mac_seq:
                    self.send_error_and_drop(ErrorCode.ERR_AUTH,
                                             "unexpected auth sequence")
                    return
                if not self._verify_frame_mac(v0, frame):
                    rec = getattr(self.app, "input_recorder", None)
                    if rec is not None and rec.active:
                        # MAC keys derive from per-connection random
                        # nonces and ephemeral session keys, so replay
                        # cannot re-verify — the verdict itself is the
                        # recorded input (replay/log.py MACFAIL)
                        rec.record_mac_fail(self)
                    self.send_error_and_drop(ErrorCode.ERR_AUTH,
                                             "unexpected MAC")
                    return
                self.recv_mac_seq += 1
        if frame is not None:
            # the wire slice IS the body's canonical bytes: seed the
            # serialize-once cache so the rebroadcast path (SCP
            # gossip), the flood hash and flow-control sizing never
            # re-encode a message this node merely relays
            wire.seed_body(msg, frame[wire.BODY_OFFSET:-wire.MAC_LEN])
        self.messages_read += 1
        self.recv_message(msg)

    def _verify_frame_mac(self, v0: _AuthenticatedMessageV0,
                          frame: Optional[bytes]) -> bool:
        """Check the frame HMAC. A seam, not just a helper: MAC keys
        derive from per-connection random nonces + ephemeral session
        keys, so a replayed node cannot recompute them — the replay
        peer overrides this to return the verdict recorded live."""
        if frame is not None:
            return hmac_sha256_verify(
                self.recv_mac_key, frame[4:-wire.MAC_LEN],
                frame[-wire.MAC_LEN:])
        return hmac_sha256_verify(
            self.recv_mac_key,
            struct.pack(">Q", v0.sequence) + v0.message.to_bytes(),
            bytes(v0.mac.mac))

    def recv_message(self, msg: StellarMessage) -> None:
        """Dispatch (reference: Peer::recvMessage :519-585). When a
        trace is on, each dispatched message is a span on this thread's
        track — per-peer, per-type — so cross-subsystem causality
        (recv → herder → close) nests under it."""
        if self._msg_in_meter is not None:
            self._msg_in_meter.mark()
        if tracing.ENABLED:
            rec = self.app.flight_recorder
            if rec.active:
                rec.begin("overlay.recv", {
                    "type": msg.disc.name,
                    "peer": self.peer_id.hex()[:8]
                    if self.peer_id else "?"})
                try:
                    self._recv_message(msg)
                finally:
                    rec.end("overlay.recv")
                return
        self._recv_message(msg)

    def _recv_message(self, msg: StellarMessage) -> None:
        t = msg.disc
        # messages legal before full auth
        if self.state != PeerState.GOT_AUTH and t not in (
                MessageType.HELLO, MessageType.AUTH, MessageType.ERROR_MSG):
            self.send_error_and_drop(ErrorCode.ERR_MISC,
                                     "received before auth")
            return
        if t == MessageType.HELLO:
            self._recv_hello(msg.value)
            return
        if t == MessageType.AUTH:
            self._recv_auth()
            return
        if t == MessageType.ERROR_MSG:
            log.debug("peer %r sent error: %s", self, msg.value.msg)
            self.drop(f"remote error: {msg.value.msg}")
            return
        if not self.flow.on_message_received(msg):
            self.send_error_and_drop(ErrorCode.ERR_LOAD,
                                     "flood capacity exceeded")
            return
        if t in (MessageType.SEND_MORE, MessageType.SEND_MORE_EXTENDED):
            self._recv_send_more(msg)
            return
        # everything else is overlay/herder level
        self.overlay.handle_message(self, msg)
        reclaim = self.flow.maybe_send_more(msg)
        if reclaim is not None:
            self._send_message(reclaim)

    # ----------------------------------------------------------- handshake --
    def _recv_hello(self, hello: Hello) -> None:
        if self.state != PeerState.CONNECTED:
            self.send_error_and_drop(ErrorCode.ERR_MISC,
                                     "unexpected HELLO")
            return
        cfg = self.app.config
        if bytes(hello.networkID) != cfg.network_id():
            self.send_error_and_drop(ErrorCode.ERR_CONF,
                                     "wrong network passphrase")
            return
        our_version = cfg.OVERLAY_PROTOCOL_VERSION
        our_min = cfg.OVERLAY_PROTOCOL_MIN_VERSION
        if hello.overlayMinVersion > our_version or \
                hello.overlayVersion < our_min:
            self.send_error_and_drop(ErrorCode.ERR_CONF,
                                     "incompatible overlay version")
            return
        remote_id = bytes(hello.peerID.value)
        if remote_id == cfg.node_id():
            self.send_error_and_drop(ErrorCode.ERR_CONF,
                                     "connecting to self")
            return
        if not self.overlay.peer_auth.verify_remote_cert(
                remote_id, hello.cert):
            self.send_error_and_drop(ErrorCode.ERR_AUTH, "bad auth cert")
            return
        self.peer_id = remote_id
        self.remote_nonce = bytes(hello.nonce)
        self.remote_pub = bytes(hello.cert.pubkey.key)
        self.remote_listening_port = hello.listeningPort
        self.remote_version = bytes(hello.versionStr).decode("utf-8", "replace")
        self.remote_overlay_version = hello.overlayVersion
        pa = self.overlay.peer_auth
        self.send_mac_key = pa.get_sending_mac_key(
            self.remote_pub, self.local_nonce, self.remote_nonce, self.role)
        self.recv_mac_key = pa.get_receiving_mac_key(
            self.remote_pub, self.local_nonce, self.remote_nonce, self.role)
        self.send_mac_seq = 0
        self.recv_mac_seq = 0
        self.state = PeerState.GOT_HELLO
        if self.role == PeerRole.REMOTE_CALLED_US:
            self.send_hello()
        else:
            self.send_auth()

    def _recv_auth(self) -> None:
        if self.state != PeerState.GOT_HELLO:
            self.send_error_and_drop(ErrorCode.ERR_MISC, "unexpected AUTH")
            return
        self.state = PeerState.GOT_AUTH
        if self.role == PeerRole.REMOTE_CALLED_US:
            self.send_auth()
        # grant initial flood capacity (reference: sendSendMore post-auth)
        self._send_message(self.flow.initial_send_more(self.app.config))
        self.overlay.peer_authenticated(self)

    def _recv_send_more(self, msg: StellarMessage) -> None:
        if msg.disc == MessageType.SEND_MORE:
            n, b = msg.value.numMessages, 2**32 - 1
        else:
            n, b = msg.value.numMessages, msg.value.numBytes
        for ready in self.flow.on_send_more(n, b):
            self._send_message(ready)
