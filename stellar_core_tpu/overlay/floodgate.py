"""Flood dedup + rebroadcast bookkeeping.

Reference: src/overlay/Floodgate.{h,cpp} — records which peers already
saw each flooded message (keyed by message hash) so broadcast skips
them; records are GC'd by ledger seq.
"""

from __future__ import annotations

from typing import Dict, Set

from ..crypto.sha import sha256
from ..util.logging import get_logger
from ..xdr.overlay import StellarMessage

log = get_logger("Overlay")


class _FloodRecord:
    __slots__ = ("ledger_seq", "peers_told")

    def __init__(self, ledger_seq: int):
        self.ledger_seq = ledger_seq
        self.peers_told: Set[int] = set()   # id(peer)


def message_hash(msg: StellarMessage) -> bytes:
    return sha256(msg.to_bytes())


class Floodgate:
    def __init__(self):
        self._records: Dict[bytes, _FloodRecord] = {}

    def add_record(self, msg: StellarMessage, from_peer,
                   ledger_seq: int, msg_hash: bytes = None) -> bool:
        """Returns True if the message is new (should be processed +
        forwarded). `msg_hash` lets a caller that already hashed the
        message (propagation tracking) skip the re-hash."""
        h = msg_hash if msg_hash is not None else message_hash(msg)
        rec = self._records.get(h)
        if rec is None:
            rec = self._records[h] = _FloodRecord(ledger_seq)
        new = not rec.peers_told
        if from_peer is not None:
            rec.peers_told.add(id(from_peer))
            new = len(rec.peers_told) == 1
        return new

    def broadcast(self, msg: StellarMessage, peers, ledger_seq: int,
                  msg_hash: bytes = None) -> int:
        """Send to every authenticated peer that hasn't seen it."""
        h = msg_hash if msg_hash is not None else message_hash(msg)
        rec = self._records.get(h)
        if rec is None:
            rec = self._records[h] = _FloodRecord(ledger_seq)
        sent = 0
        for peer in peers:
            if not peer.is_authenticated():
                continue
            if id(peer) in rec.peers_told:
                continue
            rec.peers_told.add(id(peer))
            peer.send_message(msg)
            sent += 1
        return sent

    def clear_below(self, ledger_seq: int) -> None:
        for h in [h for h, r in self._records.items()
                  if r.ledger_seq + 10 < ledger_seq]:
            del self._records[h]

    def forget_peer(self, peer) -> None:
        for rec in self._records.values():
            rec.peers_told.discard(id(peer))
