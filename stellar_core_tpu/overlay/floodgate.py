"""Flood dedup + rebroadcast bookkeeping.

Reference: src/overlay/Floodgate.{h,cpp} — records which peers already
saw each flooded message (keyed by message hash) so broadcast skips
them; records are GC'd by ledger seq.
"""

from __future__ import annotations

from typing import Dict, Set

from ..util.logging import get_logger
from ..xdr.overlay import StellarMessage
from . import wire

log = get_logger("Overlay")


class _FloodRecord:
    __slots__ = ("ledger_seq", "peers_told")

    def __init__(self, ledger_seq: int):
        self.ledger_seq = ledger_seq
        self.peers_told: Set[int] = set()   # id(peer)


def message_hash(msg: StellarMessage) -> bytes:
    """Flood key: sha256 over the message's canonical bytes —
    serialize-once (ISSUE 12): both the body encoding and the hash are
    cached on the message object, so hashing a message that is about
    to be broadcast (or was just received, cache seeded from the wire
    slice) costs nothing beyond the first call."""
    return wire.flood_hash(msg)


class Floodgate:
    def __init__(self):
        self._records: Dict[bytes, _FloodRecord] = {}
        # id(peer) -> hashes whose records name it in peers_told: the
        # disconnect path walks only what the peer actually saw,
        # O(records-told), instead of scanning every live record —
        # O(records × churn) measured in the cluster harness's churn
        # legs (ISSUE 12 satellite)
        self._peer_index: Dict[int, Set[bytes]] = {}

    def _tell(self, rec: _FloodRecord, h: bytes, peer) -> None:
        rec.peers_told.add(id(peer))
        self._peer_index.setdefault(id(peer), set()).add(h)

    def add_record(self, msg: StellarMessage, from_peer,
                   ledger_seq: int, msg_hash: bytes = None) -> bool:
        """Returns True if the message is new (should be processed +
        forwarded). `msg_hash` lets a caller that already hashed the
        message (propagation tracking) skip the re-hash."""
        h = msg_hash if msg_hash is not None else message_hash(msg)
        rec = self._records.get(h)
        if rec is None:
            rec = self._records[h] = _FloodRecord(ledger_seq)
        new = not rec.peers_told
        if from_peer is not None:
            self._tell(rec, h, from_peer)
            new = len(rec.peers_told) == 1
        return new

    def note_told(self, msg_hash: bytes, peer, ledger_seq: int) -> None:
        """Record that `peer` already holds the message with this flood
        hash WITHOUT sending anything — the per-link SCP digest gate
        (ISSUE 20). Used when an envelope reaches a peer outside the
        flood path (a GET_SCP_STATE catchup response): a later
        broadcast of the same envelope must not re-push it down that
        link, which is exactly the push-gossip duplicate the
        dups/envelope floor is made of."""
        rec = self._records.get(msg_hash)
        if rec is None:
            rec = self._records[msg_hash] = _FloodRecord(ledger_seq)
        self._tell(rec, msg_hash, peer)

    def broadcast(self, msg: StellarMessage, peers, ledger_seq: int,
                  msg_hash: bytes = None) -> int:
        """Send to every authenticated peer that hasn't seen it."""
        h = msg_hash if msg_hash is not None else message_hash(msg)
        rec = self._records.get(h)
        if rec is None:
            rec = self._records[h] = _FloodRecord(ledger_seq)
        sent = 0
        for peer in peers:
            if not peer.is_authenticated():
                continue
            if id(peer) in rec.peers_told:
                continue
            self._tell(rec, h, peer)
            peer.send_message(msg)
            sent += 1
        return sent

    def clear_below(self, ledger_seq: int) -> None:
        for h in [h for h, r in self._records.items()
                  if r.ledger_seq + 10 < ledger_seq]:
            rec = self._records.pop(h)
            # keep the per-peer index in lockstep: a long-lived peer's
            # index set must not accumulate hashes of GC'd records
            for pid in rec.peers_told:
                told = self._peer_index.get(pid)
                if told is not None:
                    told.discard(h)

    def forget_peer(self, peer) -> None:
        told = self._peer_index.pop(id(peer), None)
        if not told:
            return
        for h in told:
            rec = self._records.get(h)
            if rec is not None:
                rec.peers_told.discard(id(peer))
