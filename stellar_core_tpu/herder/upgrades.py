"""Network upgrade voting and application.

Reference: src/herder/Upgrades.{h,cpp} — operators schedule parameter changes
(protocol version, base fee, max tx set size, base reserve, flags) for a
given time; validators include matching LedgerUpgrade XDRs in their
StellarValue proposals; externalized upgrades are applied to the ledger
header during closeLedger (Upgrades.cpp:271-316 applyTo).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from ..util.logging import get_logger
from ..xdr.ledger import LedgerHeaderFlags, LedgerUpgrade, LedgerUpgradeType

log = get_logger("Herder")

# All flags an upgrade may set (reference: MASK_LEDGER_HEADER_FLAGS)
MASK_LEDGER_HEADER_FLAGS = (
    LedgerHeaderFlags.DISABLE_LIQUIDITY_POOL_TRADING_FLAG
    | LedgerHeaderFlags.DISABLE_LIQUIDITY_POOL_DEPOSIT_FLAG
    | LedgerHeaderFlags.DISABLE_LIQUIDITY_POOL_WITHDRAWAL_FLAG)


class UpgradeParameters:
    """Operator-scheduled upgrade set (reference:
    Upgrades::UpgradeParameters)."""

    def __init__(self, upgrade_time: int = 0,
                 protocol_version: Optional[int] = None,
                 base_fee: Optional[int] = None,
                 max_tx_set_size: Optional[int] = None,
                 base_reserve: Optional[int] = None,
                 flags: Optional[int] = None):
        self.upgrade_time = upgrade_time
        self.protocol_version = protocol_version
        self.base_fee = base_fee
        self.max_tx_set_size = max_tx_set_size
        self.base_reserve = base_reserve
        self.flags = flags


class Upgrades:
    def __init__(self, params: Optional[UpgradeParameters] = None,
                 current_protocol_version: int = 21):
        self._params = params or UpgradeParameters()
        self.current_protocol_version = current_protocol_version

    def set_parameters(self, params: UpgradeParameters) -> None:
        self._params = params

    def get_parameters(self) -> UpgradeParameters:
        return self._params

    # ------------------------------------------------------------ proposing --
    def create_upgrades_for(self, header, close_time: int
                            ) -> List[LedgerUpgrade]:
        """Upgrades this node votes for, given the LCL header (reference:
        Upgrades::createUpgradesFor)."""
        p = self._params
        out: List[LedgerUpgrade] = []
        if close_time < p.upgrade_time:
            return out
        if (p.protocol_version is not None
                and header.ledgerVersion != p.protocol_version):
            out.append(LedgerUpgrade(
                LedgerUpgradeType.LEDGER_UPGRADE_VERSION,
                p.protocol_version))
        if p.base_fee is not None and header.baseFee != p.base_fee:
            out.append(LedgerUpgrade(
                LedgerUpgradeType.LEDGER_UPGRADE_BASE_FEE, p.base_fee))
        if (p.max_tx_set_size is not None
                and header.maxTxSetSize != p.max_tx_set_size):
            out.append(LedgerUpgrade(
                LedgerUpgradeType.LEDGER_UPGRADE_MAX_TX_SET_SIZE,
                p.max_tx_set_size))
        if p.base_reserve is not None and header.baseReserve != p.base_reserve:
            out.append(LedgerUpgrade(
                LedgerUpgradeType.LEDGER_UPGRADE_BASE_RESERVE,
                p.base_reserve))
        if p.flags is not None and _header_flags(header) != p.flags:
            out.append(LedgerUpgrade(
                LedgerUpgradeType.LEDGER_UPGRADE_FLAGS, p.flags))
        return out

    # ----------------------------------------------------------- validating --
    def is_valid(self, upgrade: LedgerUpgrade, header,
                 nomination: bool, close_time: int = 0) -> bool:
        """Would this node accept the proposed upgrade? During nomination
        the upgrade must match our scheduled parameters; after
        externalization only structural validity matters (reference:
        Upgrades::isValid / isValidForApply)."""
        ok, _ = self._validate(upgrade, header)
        if not ok:
            return False
        if not nomination:
            return True
        p = self._params
        if close_time < p.upgrade_time:
            return False
        t = upgrade.disc
        v = upgrade.value
        if t == LedgerUpgradeType.LEDGER_UPGRADE_VERSION:
            return p.protocol_version == v
        if t == LedgerUpgradeType.LEDGER_UPGRADE_BASE_FEE:
            return p.base_fee == v
        if t == LedgerUpgradeType.LEDGER_UPGRADE_MAX_TX_SET_SIZE:
            return p.max_tx_set_size == v
        if t == LedgerUpgradeType.LEDGER_UPGRADE_BASE_RESERVE:
            return p.base_reserve == v
        if t == LedgerUpgradeType.LEDGER_UPGRADE_FLAGS:
            return p.flags == v
        return False

    def _validate(self, upgrade: LedgerUpgrade, header) -> Tuple[bool, str]:
        t = upgrade.disc
        v = upgrade.value
        if t == LedgerUpgradeType.LEDGER_UPGRADE_VERSION:
            if v > self.current_protocol_version:
                return False, "version not supported"
            if v < header.ledgerVersion:
                return False, "downgrade"
            return True, ""
        if t == LedgerUpgradeType.LEDGER_UPGRADE_BASE_FEE:
            return (v > 0, "base fee must be positive")
        if t == LedgerUpgradeType.LEDGER_UPGRADE_MAX_TX_SET_SIZE:
            return (v > 0, "max tx set size must be positive")
        if t == LedgerUpgradeType.LEDGER_UPGRADE_BASE_RESERVE:
            return (v > 0, "base reserve must be positive")
        if t == LedgerUpgradeType.LEDGER_UPGRADE_FLAGS:
            if header.ledgerVersion < 18:
                return False, "flags upgrade needs protocol 18"
            return ((v & ~MASK_LEDGER_HEADER_FLAGS) == 0, "invalid flags")
        return False, "unknown upgrade type"

    # ------------------------------------------------------------- applying --
    @staticmethod
    def apply_to(upgrade: LedgerUpgrade, header) -> None:
        """Mutate the in-close ledger header (reference:
        Upgrades::applyTo)."""
        t = upgrade.disc
        v = upgrade.value
        if t == LedgerUpgradeType.LEDGER_UPGRADE_VERSION:
            header.ledgerVersion = v
        elif t == LedgerUpgradeType.LEDGER_UPGRADE_BASE_FEE:
            header.baseFee = v
        elif t == LedgerUpgradeType.LEDGER_UPGRADE_MAX_TX_SET_SIZE:
            header.maxTxSetSize = v
        elif t == LedgerUpgradeType.LEDGER_UPGRADE_BASE_RESERVE:
            header.baseReserve = v
        elif t == LedgerUpgradeType.LEDGER_UPGRADE_FLAGS:
            _set_header_flags(header, v)
        else:
            log.warning("ignoring unknown upgrade type %s", t)


def _header_flags(header) -> int:
    if header.ext.disc == 1:
        return header.ext.value.flags
    return 0


def _set_header_flags(header, flags: int) -> None:
    from ..xdr.ledger import LedgerHeaderExtensionV1, _LedgerHeaderExt
    if flags == 0 and header.ext.disc == 0:
        return
    if header.ext.disc == 0:
        header.ext = _LedgerHeaderExt(1, LedgerHeaderExtensionV1())
    header.ext.value.flags = flags
