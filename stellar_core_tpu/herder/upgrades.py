"""Network upgrade voting and application.

Reference: src/herder/Upgrades.{h,cpp} — operators schedule parameter changes
(protocol version, base fee, max tx set size, base reserve, flags) for a
given time; validators include matching LedgerUpgrade XDRs in their
StellarValue proposals; externalized upgrades are applied to the ledger
header during closeLedger (Upgrades.cpp:271-316 applyTo).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from ..util.logging import get_logger
from ..xdr.ledger import LedgerHeaderFlags, LedgerUpgrade, LedgerUpgradeType

log = get_logger("Herder")

# All flags an upgrade may set (reference: MASK_LEDGER_HEADER_FLAGS)
MASK_LEDGER_HEADER_FLAGS = (
    LedgerHeaderFlags.DISABLE_LIQUIDITY_POOL_TRADING_FLAG
    | LedgerHeaderFlags.DISABLE_LIQUIDITY_POOL_DEPOSIT_FLAG
    | LedgerHeaderFlags.DISABLE_LIQUIDITY_POOL_WITHDRAWAL_FLAG)


class UpgradeParameters:
    """Operator-scheduled upgrade set (reference:
    Upgrades::UpgradeParameters)."""

    def __init__(self, upgrade_time: int = 0,
                 protocol_version: Optional[int] = None,
                 base_fee: Optional[int] = None,
                 max_tx_set_size: Optional[int] = None,
                 base_reserve: Optional[int] = None,
                 flags: Optional[int] = None,
                 max_soroban_tx_set_size: Optional[int] = None,
                 config_upgrade_set_key=None):
        self.upgrade_time = upgrade_time
        self.protocol_version = protocol_version
        self.base_fee = base_fee
        self.max_tx_set_size = max_tx_set_size
        self.base_reserve = base_reserve
        self.flags = flags
        self.max_soroban_tx_set_size = max_soroban_tx_set_size
        # ConfigUpgradeSetKey pointing at a published upgrade set
        self.config_upgrade_set_key = config_upgrade_set_key


class ConfigUpgradeSetFrame:
    """A validated Soroban config upgrade set loaded from the ledger
    (reference: herder/Upgrades.cpp ConfigUpgradeSetFrame:1273-1376 —
    the key names a TEMPORARY contract-data entry whose SCV_BYTES value
    deserializes to a ConfigUpgradeSet matching contentHash)."""

    def __init__(self, upgrade_set, key):
        self.upgrade_set = upgrade_set
        self.key = key

    @staticmethod
    def ledger_key(key):
        from ..xdr.contract import (ContractDataDurability, SCAddress,
                                    SCAddressType, SCVal, SCValType)
        from ..xdr.ledger_entries import LedgerKey
        contract = SCAddress(SCAddressType.SC_ADDRESS_TYPE_CONTRACT,
                             key.contractID)
        val = SCVal(SCValType.SCV_BYTES, bytes(key.contentHash))
        return LedgerKey.contract_data(
            contract, val, ContractDataDurability.TEMPORARY)

    @classmethod
    def make_from_key(cls, ltx, key):
        """Load + validate; returns None when missing/expired/corrupt
        (reference: makeFromKey :1273)."""
        from ..crypto.sha import sha256
        from ..soroban.host import ttl_key_for
        from ..xdr.contract import ConfigUpgradeSet, SCValType
        from ..xdr.runtime import XdrError
        lk = cls.ledger_key(key)
        le = ltx.load_without_record(lk)
        if le is None:
            return None
        ttl = ltx.load_without_record(ttl_key_for(lk))
        if ttl is None or \
                ttl.data.value.liveUntilLedgerSeq < ltx.get_header().ledgerSeq:
            return None
        cd = le.data.value
        if cd.val.disc != SCValType.SCV_BYTES:
            return None
        try:
            upgrade_set = ConfigUpgradeSet.from_bytes(bytes(cd.val.value))
        except XdrError:
            return None
        if sha256(upgrade_set.to_bytes()) != bytes(key.contentHash):
            return None
        # entries must be non-empty and strictly sorted by setting id
        ids = [e.disc for e in upgrade_set.updatedEntry]
        if not ids or ids != sorted(set(ids)):
            return None
        for entry in upgrade_set.updatedEntry:
            if not _is_valid_config_entry(entry):
                return None
        return cls(upgrade_set, key)

    def upgrade_needed(self, ltx) -> bool:
        """Any updated entry differing from the live one?"""
        from ..xdr.ledger_entries import LedgerKey
        for entry in self.upgrade_set.updatedEntry:
            live = ltx.load_without_record(
                LedgerKey.config_setting(entry.disc))
            if live is None or live.data.value != entry:
                return True
        return False

    def apply_to(self, ltx) -> None:
        """Overwrite the CONFIG_SETTING entries (reference: applyTo
        :344-358)."""
        from ..xdr.ledger_entries import LedgerKey
        for entry in self.upgrade_set.updatedEntry:
            key = LedgerKey.config_setting(entry.disc)
            live = ltx.load(key)
            if live is None:
                raise RuntimeError(
                    f"config setting {entry.disc!r} missing")
            live.data.value = entry


class Upgrades:
    def __init__(self, params: Optional[UpgradeParameters] = None,
                 current_protocol_version: int = 21):
        self._params = params or UpgradeParameters()
        self.current_protocol_version = current_protocol_version

    def set_parameters(self, params: UpgradeParameters) -> None:
        self._params = params

    def get_parameters(self) -> UpgradeParameters:
        return self._params

    # ------------------------------------------------------------ proposing --
    def create_upgrades_for(self, header, close_time: int,
                            ltx=None) -> List[LedgerUpgrade]:
        """Upgrades this node votes for, given the LCL header (reference:
        Upgrades::createUpgradesFor). `ltx` (when given) enables the
        Soroban config votes, which read CONFIG_SETTING entries."""
        p = self._params
        out: List[LedgerUpgrade] = []
        if close_time < p.upgrade_time:
            return out
        if (p.protocol_version is not None
                and header.ledgerVersion != p.protocol_version):
            out.append(LedgerUpgrade(
                LedgerUpgradeType.LEDGER_UPGRADE_VERSION,
                p.protocol_version))
        if p.base_fee is not None and header.baseFee != p.base_fee:
            out.append(LedgerUpgrade(
                LedgerUpgradeType.LEDGER_UPGRADE_BASE_FEE, p.base_fee))
        if (p.max_tx_set_size is not None
                and header.maxTxSetSize != p.max_tx_set_size):
            out.append(LedgerUpgrade(
                LedgerUpgradeType.LEDGER_UPGRADE_MAX_TX_SET_SIZE,
                p.max_tx_set_size))
        if p.base_reserve is not None and header.baseReserve != p.base_reserve:
            out.append(LedgerUpgrade(
                LedgerUpgradeType.LEDGER_UPGRADE_BASE_RESERVE,
                p.base_reserve))
        if p.flags is not None and _header_flags(header) != p.flags:
            out.append(LedgerUpgrade(
                LedgerUpgradeType.LEDGER_UPGRADE_FLAGS, p.flags))
        if ltx is not None and header.ledgerVersion >= 20:
            if p.max_soroban_tx_set_size is not None and \
                    _soroban_max_tx_count(ltx) != \
                    p.max_soroban_tx_set_size:
                out.append(LedgerUpgrade(
                    LedgerUpgradeType
                    .LEDGER_UPGRADE_MAX_SOROBAN_TX_SET_SIZE,
                    p.max_soroban_tx_set_size))
            if p.config_upgrade_set_key is not None:
                frame = ConfigUpgradeSetFrame.make_from_key(
                    ltx, p.config_upgrade_set_key)
                if frame is not None and frame.upgrade_needed(ltx):
                    out.append(LedgerUpgrade(
                        LedgerUpgradeType.LEDGER_UPGRADE_CONFIG,
                        p.config_upgrade_set_key))
        return out

    # ----------------------------------------------------------- validating --
    def is_valid(self, upgrade: LedgerUpgrade, header,
                 nomination: bool, close_time: int = 0,
                 ltx=None) -> bool:
        """Would this node accept the proposed upgrade? During nomination
        the upgrade must match our scheduled parameters; after
        externalization only structural validity matters (reference:
        Upgrades::isValid / isValidForApply)."""
        ok, _ = self._validate(upgrade, header)
        if not ok:
            return False
        if upgrade.disc == LedgerUpgradeType.LEDGER_UPGRADE_CONFIG \
                and ltx is not None and \
                ConfigUpgradeSetFrame.make_from_key(
                    ltx, upgrade.value) is None:
            # reference: isValidForApply loads + validates the set via
            # the ltx; an unloadable/corrupt set is rejected at ballot
            # time so apply can't crash the close
            return False
        if not nomination:
            return True
        p = self._params
        if close_time < p.upgrade_time:
            return False
        t = upgrade.disc
        v = upgrade.value
        if t == LedgerUpgradeType.LEDGER_UPGRADE_VERSION:
            return p.protocol_version == v
        if t == LedgerUpgradeType.LEDGER_UPGRADE_BASE_FEE:
            return p.base_fee == v
        if t == LedgerUpgradeType.LEDGER_UPGRADE_MAX_TX_SET_SIZE:
            return p.max_tx_set_size == v
        if t == LedgerUpgradeType.LEDGER_UPGRADE_BASE_RESERVE:
            return p.base_reserve == v
        if t == LedgerUpgradeType.LEDGER_UPGRADE_FLAGS:
            return p.flags == v
        if t == LedgerUpgradeType.LEDGER_UPGRADE_MAX_SOROBAN_TX_SET_SIZE:
            return p.max_soroban_tx_set_size == v
        if t == LedgerUpgradeType.LEDGER_UPGRADE_CONFIG:
            return p.config_upgrade_set_key is not None and \
                p.config_upgrade_set_key.to_bytes() == v.to_bytes()
        return False

    def _validate(self, upgrade: LedgerUpgrade, header) -> Tuple[bool, str]:
        t = upgrade.disc
        v = upgrade.value
        if t == LedgerUpgradeType.LEDGER_UPGRADE_VERSION:
            if v > self.current_protocol_version:
                return False, "version not supported"
            if v < header.ledgerVersion:
                return False, "downgrade"
            return True, ""
        if t == LedgerUpgradeType.LEDGER_UPGRADE_BASE_FEE:
            return (v > 0, "base fee must be positive")
        if t == LedgerUpgradeType.LEDGER_UPGRADE_MAX_TX_SET_SIZE:
            return (v > 0, "max tx set size must be positive")
        if t == LedgerUpgradeType.LEDGER_UPGRADE_BASE_RESERVE:
            return (v > 0, "base reserve must be positive")
        if t == LedgerUpgradeType.LEDGER_UPGRADE_FLAGS:
            if header.ledgerVersion < 18:
                return False, "flags upgrade needs protocol 18"
            return ((v & ~MASK_LEDGER_HEADER_FLAGS) == 0, "invalid flags")
        if t == LedgerUpgradeType.LEDGER_UPGRADE_MAX_SOROBAN_TX_SET_SIZE:
            if header.ledgerVersion < 20:
                return False, "soroban upgrade needs protocol 20"
            return True, ""
        if t == LedgerUpgradeType.LEDGER_UPGRADE_CONFIG:
            if header.ledgerVersion < 20:
                return False, "config upgrade needs protocol 20"
            return True, ""
        return False, "unknown upgrade type"

    # ------------------------------------------------------------- applying --
    @staticmethod
    def apply_to(upgrade: LedgerUpgrade, header, ltx=None) -> None:
        """Mutate the in-close ledger header — and, for the Soroban
        upgrade types, the CONFIG_SETTING entries via `ltx` (reference:
        Upgrades::applyTo)."""
        t = upgrade.disc
        v = upgrade.value
        if t == LedgerUpgradeType.LEDGER_UPGRADE_VERSION:
            header.ledgerVersion = v
        elif t == LedgerUpgradeType.LEDGER_UPGRADE_BASE_FEE:
            header.baseFee = v
        elif t == LedgerUpgradeType.LEDGER_UPGRADE_MAX_TX_SET_SIZE:
            header.maxTxSetSize = v
        elif t == LedgerUpgradeType.LEDGER_UPGRADE_BASE_RESERVE:
            header.baseReserve = v
        elif t == LedgerUpgradeType.LEDGER_UPGRADE_FLAGS:
            _set_header_flags(header, v)
        elif t == LedgerUpgradeType \
                .LEDGER_UPGRADE_MAX_SOROBAN_TX_SET_SIZE:
            if ltx is None:
                raise RuntimeError("soroban upgrade needs an ltx")
            _set_soroban_max_tx_count(ltx, v)
        elif t == LedgerUpgradeType.LEDGER_UPGRADE_CONFIG:
            if ltx is None:
                raise RuntimeError("config upgrade needs an ltx")
            frame = ConfigUpgradeSetFrame.make_from_key(ltx, v)
            if frame is None:
                raise RuntimeError(
                    "failed to retrieve valid config upgrade set")
            frame.apply_to(ltx)
        else:
            log.warning("ignoring unknown upgrade type %s", t)


def _header_flags(header) -> int:
    from ..tx.tx_utils import header_flags
    return header_flags(header)


def _set_header_flags(header, flags: int) -> None:
    from ..xdr.ledger import LedgerHeaderExtensionV1, _LedgerHeaderExt
    if flags == 0 and header.ext.disc == 0:
        return
    if header.ext.disc == 0:
        header.ext = _LedgerHeaderExt(1, LedgerHeaderExtensionV1())
    header.ext.value.flags = flags


def _soroban_max_tx_count(ltx) -> Optional[int]:
    from ..xdr.contract import ConfigSettingID
    from ..xdr.ledger_entries import LedgerKey
    le = ltx.load_without_record(LedgerKey.config_setting(
        ConfigSettingID.CONFIG_SETTING_CONTRACT_EXECUTION_LANES))
    return le.data.value.value.ledgerMaxTxCount if le is not None else None


def _set_soroban_max_tx_count(ltx, count: int) -> None:
    """reference: upgradeMaxSorobanTxSetSize (Upgrades.cpp:130-138)."""
    from ..xdr.contract import ConfigSettingID
    from ..xdr.ledger_entries import LedgerKey
    le = ltx.load(LedgerKey.config_setting(
        ConfigSettingID.CONFIG_SETTING_CONTRACT_EXECUTION_LANES))
    if le is None:
        raise RuntimeError("execution-lanes config setting missing")
    le.data.value.value.ledgerMaxTxCount = count


# non-upgradeable internal bookkeeping settings (reference:
# ConfigUpgradeSetFrame::isValid rejects these ids)
from ..xdr.contract import ConfigSettingID as _CSID
_NON_UPGRADEABLE_SETTINGS = frozenset((
    _CSID.CONFIG_SETTING_BUCKETLIST_SIZE_WINDOW,
    _CSID.CONFIG_SETTING_EVICTION_ITERATOR,
))


def _is_valid_config_entry(entry) -> bool:
    """Content sanity for one updated ConfigSettingEntry (reference:
    ConfigUpgradeSetFrame::isValid + SorobanNetworkConfig::isValid —
    internal ids rejected, core limits must stay positive)."""
    from ..xdr.contract import ConfigSettingID
    if int(entry.disc) in _NON_UPGRADEABLE_SETTINGS:
        return False
    v = entry.value
    sid = entry.disc
    if sid == ConfigSettingID.CONFIG_SETTING_CONTRACT_MAX_SIZE_BYTES:
        return v > 0
    if sid == ConfigSettingID.CONFIG_SETTING_CONTRACT_COMPUTE_V0:
        return (v.ledgerMaxInstructions > 0 and v.txMaxInstructions > 0
                and v.txMaxInstructions <= v.ledgerMaxInstructions
                and v.txMemoryLimit > 0)
    if sid == ConfigSettingID.CONFIG_SETTING_CONTRACT_LEDGER_COST_V0:
        return (v.txMaxReadLedgerEntries > 0 and v.txMaxReadBytes > 0
                and v.txMaxWriteBytes > 0)
    if sid == ConfigSettingID.CONFIG_SETTING_CONTRACT_BANDWIDTH_V0:
        return (v.txMaxSizeBytes > 0
                and v.txMaxSizeBytes <= v.ledgerMaxTxsSizeBytes)
    if sid == ConfigSettingID.CONFIG_SETTING_CONTRACT_DATA_KEY_SIZE_BYTES:
        return v > 0
    if sid == ConfigSettingID.CONFIG_SETTING_CONTRACT_DATA_ENTRY_SIZE_BYTES:
        return v > 0
    if sid == ConfigSettingID.CONFIG_SETTING_CONTRACT_EXECUTION_LANES:
        return v.ledgerMaxTxCount > 0
    return True
