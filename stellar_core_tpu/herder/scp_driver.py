"""HerderSCPDriver — binds abstract SCP to the ledger application.

Reference: src/herder/HerderSCPDriver.{h,cpp}: value (de)serialization
and validation against the LCL, candidate combination, envelope
signing/emission, timer plumbing onto the VirtualClock, and the
valueExternalized handoff to ledger close.
"""

from __future__ import annotations

import struct
import time
from typing import Dict, Optional, Set

from ..crypto.sha import sha256
from ..scp import SCPDriver, ValidationLevel
from ..util import tracing
from ..util.logging import get_logger
from ..util.timer import VirtualTimer
from ..xdr.ledger import (LedgerUpgrade, LedgerUpgradeType, StellarValue,
                          StellarValueType)
from ..xdr.scp import SCPEnvelope
from ..xdr.types import EnvelopeType

log = get_logger("Herder")

# reference: Herder.h MAX_TIME_SLIP_SECONDS
MAX_TIME_SLIP_SECONDS = 60


def scp_envelope_sign_bytes(network_id: bytes, statement) -> bytes:
    """xdr_to_opaque(networkID, ENVELOPE_TYPE_SCP, statement)
    (reference: HerderImpl::signEnvelope :2291)."""
    return (network_id + struct.pack(">i", EnvelopeType.ENVELOPE_TYPE_SCP)
            + statement.to_bytes())


def stellar_value_sign_bytes(network_id: bytes, tx_set_hash: bytes,
                             close_time: int) -> bytes:
    """xdr_to_opaque(networkID, ENVELOPE_TYPE_SCPVALUE, txSetHash,
    closeTime) (reference: HerderImpl::verifyStellarValueSignature)."""
    return (network_id
            + struct.pack(">i", EnvelopeType.ENVELOPE_TYPE_SCPVALUE)
            + tx_set_hash + struct.pack(">Q", close_time))


class HerderSCPDriver(SCPDriver):
    def __init__(self, herder):
        self.herder = herder
        self._timers: Dict[tuple, VirtualTimer] = {}

    # ------------------------------------------------------------- wiring --
    @property
    def app_clock(self):
        return self.herder._clock

    def sign_envelope(self, envelope: SCPEnvelope) -> None:
        sk = self.herder.config.NODE_SEED
        envelope.signature = sk.sign(scp_envelope_sign_bytes(
            self.herder.network_id, envelope.statement))

    def emit_envelope(self, envelope: SCPEnvelope) -> None:
        self.herder.emit_envelope(envelope)

    def get_qset(self, qset_hash: bytes):
        return self.herder.pending_envelopes.get_qset(qset_hash)

    # --------------------------------------------------------- validation --
    def validate_value(self, slot_index: int, value: bytes,
                       nomination: bool) -> ValidationLevel:
        try:
            sv = StellarValue.from_bytes(value)
        except Exception:
            return ValidationLevel.kInvalidValue
        lcl = self.herder.ledger_manager.get_last_closed_ledger_header()
        lcl_seq = lcl.ledgerSeq

        # nomination values must be signed by their proposer (reference:
        # validateValueHelper, protocol 18+ behavior)
        if nomination:
            if sv.ext.disc != StellarValueType.STELLAR_VALUE_SIGNED:
                return ValidationLevel.kInvalidValue
            if not self.herder.verify_stellar_value_signature(sv):
                return ValidationLevel.kInvalidValue

        if slot_index != lcl_seq + 1:
            # old or far-future slot: can't fully validate against state
            return ValidationLevel.kMaybeValidValue

        if sv.closeTime <= lcl.scpValue.closeTime:
            return ValidationLevel.kInvalidValue
        now = self.herder._now()
        if sv.closeTime > now + MAX_TIME_SLIP_SECONDS:
            return ValidationLevel.kInvalidValue

        tx_set = self.herder.pending_envelopes.get_tx_set(
            bytes(sv.txSetHash))
        if tx_set is None:
            log.debug("validateValue: unknown txset %s",
                      bytes(sv.txSetHash).hex()[:16])
            return ValidationLevel.kInvalidValue
        if not self.herder.is_tx_set_valid(tx_set):
            return ValidationLevel.kInvalidValue
        if sv.upgrades:
            from ..ledger.ledger_txn import LedgerTxn
            with LedgerTxn(self.herder.ledger_manager.root) as ltx_read:
                for raw in sv.upgrades:
                    try:
                        up = LedgerUpgrade.from_bytes(bytes(raw))
                    except Exception:
                        return ValidationLevel.kInvalidValue
                    if not self.herder.upgrades.is_valid(
                            up, lcl, nomination, sv.closeTime,
                            ltx=ltx_read):
                        return ValidationLevel.kInvalidValue
        return ValidationLevel.kFullyValidatedValue

    def extract_valid_value(self, slot_index: int,
                            value: bytes) -> Optional[bytes]:
        """Strip invalid upgrades from an otherwise-valid value
        (reference: HerderSCPDriver::extractValidValue)."""
        try:
            sv = StellarValue.from_bytes(value)
        except Exception:
            return None
        lcl = self.herder.ledger_manager.get_last_closed_ledger_header()
        tx_set = self.herder.pending_envelopes.get_tx_set(
            bytes(sv.txSetHash))
        if tx_set is None or not self.herder.is_tx_set_valid(tx_set):
            return None
        kept = []
        if sv.upgrades:
            from ..ledger.ledger_txn import LedgerTxn
            with LedgerTxn(self.herder.ledger_manager.root) as ltx_read:
                for raw in sv.upgrades:
                    try:
                        up = LedgerUpgrade.from_bytes(bytes(raw))
                        if self.herder.upgrades.is_valid(
                                up, lcl, True, sv.closeTime, ltx=ltx_read):
                            kept.append(raw)
                    except Exception:
                        pass
        sv.upgrades = kept
        return sv.to_bytes()

    # -------------------------------------------------------- combination --
    def combine_candidates(self, slot_index: int,
                           candidates: Set[bytes]) -> Optional[bytes]:
        """Aggregate upgrades (max per type), pick the best tx set
        (reference: HerderSCPDriver::combineCandidates :615)."""
        lcl = self.herder.ledger_manager.get_last_closed_ledger_header()
        lcl_hash = self.herder.ledger_manager.get_last_closed_ledger_hash()
        upgrades: Dict[int, LedgerUpgrade] = {}
        candidates_hash = bytes(32)
        values = []
        for raw in sorted(candidates):
            sv = StellarValue.from_bytes(raw)
            values.append(sv)
            candidates_hash = bytes(
                a ^ b for a, b in zip(candidates_hash, sha256(raw)))
            for uraw in sv.upgrades:
                up = LedgerUpgrade.from_bytes(bytes(uraw))
                t = up.disc
                cur = upgrades.get(t)
                if cur is None or up.value > cur.value:
                    upgrades[t] = up

        best = None
        best_txset = None
        for sv in values:
            tx_set = self.herder.pending_envelopes.get_tx_set(
                bytes(sv.txSetHash))
            if tx_set is None:
                continue
            applicable = self.herder.applicable_for(tx_set)
            if applicable is None or \
                    tx_set.previous_ledger_hash() != lcl_hash:
                continue
            if best is None or self._tx_set_less(
                    best_txset, applicable, bytes(best.txSetHash),
                    bytes(sv.txSetHash), candidates_hash):
                best = sv
                best_txset = applicable
        if best is None:
            raise RuntimeError("no usable candidate transaction set")

        comp = StellarValue.from_bytes(best.to_bytes())
        comp.upgrades = [upgrades[t].to_bytes() for t in sorted(upgrades)]
        # the composite is STELLAR_VALUE_BASIC (reference:
        # combineCandidates strips the nomination signature): only
        # nomination values are signed, and the externalized header
        # must not depend on WHICH proposer's candidate won the slot —
        # chaos-convergence runs diff header bytes across runs
        from ..xdr.ledger import _StellarValueExt
        comp.ext = _StellarValueExt(StellarValueType.STELLAR_VALUE_BASIC)
        return comp.to_bytes()

    @staticmethod
    def _tx_set_less(l_app, r_app, lh: bytes, rh: bytes,
                     mix: bytes) -> bool:
        """compareTxSets: by op count, then total fees, then hash^mix."""
        if l_app is None:
            return r_app is not None
        if r_app is None:
            return False
        if l_app.size_op() != r_app.size_op():
            return l_app.size_op() < r_app.size_op()
        l_fees = sum(t.inclusion_fee() for t in l_app.txs)
        r_fees = sum(t.inclusion_fee() for t in r_app.txs)
        if l_fees != r_fees:
            return l_fees < r_fees
        lx = bytes(a ^ b for a, b in zip(lh, mix))
        rx = bytes(a ^ b for a, b in zip(rh, mix))
        return lx < rx

    # -------------------------------------------------------------- timers --
    def setup_timer(self, slot_index: int, timer_id: int,
                    timeout_seconds: float, cb) -> None:
        key = (slot_index, timer_id)
        old = self._timers.pop(key, None)
        if old is not None:
            old.cancel()
        if cb is None:
            return
        timer = VirtualTimer(self.app_clock)
        timer.expires_from_now(timeout_seconds)

        def fire():
            self._timers.pop(key, None)
            cb()

        timer.async_wait(fire)
        self._timers[key] = timer

    def cancel_timers_below(self, slot_index: int) -> None:
        for key in [k for k in self._timers if k[0] <= slot_index]:
            self._timers.pop(key).cancel()

    def cancel_all_timers(self) -> None:
        """Shutdown: a pending ballot/nomination timer must not fire
        into a dead app."""
        for timer in self._timers.values():
            timer.cancel()
        self._timers.clear()

    # ------------------------------------------------------- notifications --
    def value_externalized(self, slot_index: int, value: bytes) -> None:
        self._slot_phase(slot_index, "externalize")
        self.cancel_timers_below(slot_index)
        self.herder.value_externalized_from_scp(slot_index, value)

    def nominating_value(self, slot_index: int, value: bytes) -> None:
        log.debug("nominating value for slot %d", slot_index)

    # ------------------------------------------------- slot phase timeline --
    # Per-slot consensus timeline (mesh observatory): the SCP seams the
    # kernel already exposes map 1:1 onto the phase transitions —
    # slot_activated = nomination begins, started_ballot_protocol = the
    # first ballot (prepare), accepted_commit = the PREPARE→CONFIRM
    # flip, value_externalized = CONFIRM→EXTERNALIZE. Each transition
    # closes the previous phase into a `scp.slot.<phase>` timer
    # (metrics route + Prometheus) and, while tracing, rides the
    # flight recorder as per-slot async spans — one
    # nominate→prepare→confirm lane per node in the merged trace.
    _SLOT_PHASES = ("nominate", "prepare", "confirm", "externalize")

    def slot_activated(self, slot_index: int) -> None:
        self._slot_phase(slot_index, "nominate")

    def started_ballot_protocol(self, slot_index: int, ballot) -> None:
        self._slot_phase(slot_index, "prepare")

    def accepted_commit(self, slot_index: int, ballot) -> None:
        # fires on the PREPARE→CONFIRM flip and again on every later
        # commit/high update within CONFIRM; only the first counts
        self._slot_phase(slot_index, "confirm")

    def _slot_phase(self, slot_index: int, phase: str) -> None:
        herder = self.herder
        tl = herder.slot_timelines.get(slot_index)
        if tl is None:
            if len(herder.slot_timelines) >= herder.SLOT_TIMELINE_MAX:
                # bounded like the SCP slot map itself: oldest first
                for k in sorted(herder.slot_timelines)[
                        :len(herder.slot_timelines)
                        - herder.SLOT_TIMELINE_MAX + 1]:
                    del herder.slot_timelines[k]
            tl = herder.slot_timelines[slot_index] = {}
        if phase in tl:
            return
        now = time.perf_counter()
        rec = None
        if tracing.ENABLED:
            rec = herder.perf.tracer
            if rec is not None and not rec.active:
                rec = None
        prev = tl.get("_open")
        if prev is not None:
            if herder._metrics is not None:
                herder._metrics.timer("scp", "slot", prev).update(
                    now - tl[prev])
            if rec is not None:
                rec.async_end("scp.slot." + prev, "slot%d" % slot_index,
                              {"slot": slot_index})
        tl[phase] = now
        if phase == "externalize":
            tl["_open"] = None
            if herder._metrics is not None and "nominate" in tl:
                herder._metrics.timer("scp", "slot", "total").update(
                    now - tl["nominate"])
        else:
            tl["_open"] = phase
            if rec is not None:
                rec.async_begin("scp.slot." + phase,
                                "slot%d" % slot_index,
                                {"slot": slot_index})
