"""Transaction sets.

Reference: src/herder/TxSetFrame.{h,cpp} and TxSetUtils.{h,cpp}.

Two representations, as in the reference:
- `TxSetFrame` — the wire/hash form (GeneralizedTransactionSet XDR from
  protocol 20, legacy TransactionSet before); contents-hashed, immutable.
- `ApplicableTxSet` — the validated, per-tx-base-fee-annotated form the
  ledger close consumes (reference: ApplicableTxSetFrame).

Apply order (reference TxSetFrame.cpp:550-599 getTxsInApplyOrder): txs of one
source account stay in seqnum order; inter-account order is deterministic yet
unpredictable — sort by SHA256(txSetHash ‖ txFullHash).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from ..crypto.sha import sha256
from ..tx.frame import TransactionFrame, make_frame
from ..util.logging import get_logger
from ..xdr.ledger import (GeneralizedTransactionSet, TransactionPhase,
                          TransactionSet, TransactionSetV1, TxSetComponent,
                          TxSetComponentType)
from .surge_pricing import (GENERIC_LANE, SurgePricingLaneConfig,
                            surge_pricing_filter)

log = get_logger("Herder")

# From protocol 20 the wire form is GeneralizedTransactionSet
FIRST_GENERALIZED_TX_SET_PROTOCOL = 20


class TxSetFrame:
    """Immutable wire-form tx set, identified by its contents hash
    (reference: TxSetXDRFrame)."""

    def __init__(self, xdr_set, network_id: bytes):
        self._xdr = xdr_set
        self._generalized = isinstance(xdr_set, GeneralizedTransactionSet)
        self.network_id = network_id
        self._hash = sha256(xdr_set.to_bytes())

    @property
    def is_generalized(self) -> bool:
        return self._generalized

    def get_contents_hash(self) -> bytes:
        return self._hash

    def previous_ledger_hash(self) -> bytes:
        if self._generalized:
            return self._xdr.value.previousLedgerHash
        return self._xdr.previousLedgerHash

    def to_xdr(self):
        return self._xdr

    def to_bytes(self) -> bytes:
        return self._xdr.to_bytes()

    def size_tx_total(self) -> int:
        return len(list(self._iter_envelopes()))

    def size_op_total(self) -> int:
        n = 0
        for frame, _ in self._frames_with_base_fee():
            n += max(1, frame.num_operations())
        return n

    def _iter_envelopes(self):
        if not self._generalized:
            for env in self._xdr.txs:
                yield env
            return
        for phase in self._xdr.value.phases:
            for comp in phase.value:
                yield from comp.value.txs

    def _frames_with_base_fee(self) -> List[Tuple[TransactionFrame,
                                                  Optional[int]]]:
        out = []
        if not self._generalized:
            for env in self._xdr.txs:
                out.append((make_frame(env, self.network_id), None))
            return out
        for phase in self._xdr.value.phases:
            for comp in phase.value:
                bf = comp.value.baseFee
                for env in comp.value.txs:
                    out.append((make_frame(env, self.network_id), bf))
        return out

    def prepare_for_apply(self, lcl_header) -> Optional["ApplicableTxSet"]:
        """Parse + structurally validate against the LCL; returns None on
        malformed sets (reference: TxSetXDRFrame::prepareForApply)."""
        try:
            frames = self._frames_with_base_fee()
        except Exception:
            log.warning("malformed tx set %s", self._hash.hex()[:16])
            return None
        return ApplicableTxSet(self, frames, lcl_header)


class ApplicableTxSet:
    """Validated form consumed by closeLedger (reference:
    ApplicableTxSetFrame)."""

    def __init__(self, frame: TxSetFrame,
                 frames_with_base_fee: Sequence[Tuple[TransactionFrame,
                                                      Optional[int]]],
                 lcl_header):
        self._frame = frame
        self._txs = list(frames_with_base_fee)
        self._lcl_header = lcl_header
        self._base_fee_by_hash = {t.full_hash(): bf for t, bf in self._txs}

    def get_contents_hash(self) -> bytes:
        return self._frame.get_contents_hash()

    def to_wire(self) -> TxSetFrame:
        return self._frame

    @property
    def txs(self) -> List[TransactionFrame]:
        return [t for t, _ in self._txs]

    def base_fee_for(self, tx: TransactionFrame) -> Optional[int]:
        """Per-op base fee override from the discounted component; None
        means the tx pays its own bid (legacy sets: lcl base fee
        semantics handled by TransactionFrame)."""
        h = tx.full_hash()
        if h not in self._base_fee_by_hash:
            raise KeyError(f"tx {h.hex()[:16]} not in this tx set")
        return self._base_fee_by_hash[h]

    def size_tx(self) -> int:
        return len(self._txs)

    def size_op(self) -> int:
        return sum(max(1, t.num_operations()) for t, _ in self._txs)

    # ------------------------------------------------------------ validity --
    def check_valid(self, ltx_parent, verify=None) -> bool:
        """Full semantic validation (reference:
        ApplicableTxSetFrame::checkValid): prev-hash links the LCL, no
        duplicates, per-account seqnum chains, each tx checkValid, size
        within the header limit."""
        header = self._lcl_header
        if self._frame.previous_ledger_hash() != _header_hash(header):
            log.debug("tx set prev hash mismatch")
            return False
        if self._frame.is_generalized:
            if header.ledgerVersion < FIRST_GENERALIZED_TX_SET_PROTOCOL:
                return False
        # maxTxSetSize counts operations from protocol 11 on, txs before
        # (reference: TxSetFrame size() + FIRST_PROTOCOL_SUPPORTING_
        # OPERATION_LIMITS); applies to generalized sets too
        size = self.size_op() if header.ledgerVersion >= 11 \
            else self.size_tx()
        if size > header.maxTxSetSize:
            return False
        seen = set()
        for t, _ in self._txs:
            h = t.full_hash()
            if h in seen:
                return False
            seen.add(h)
        return self._check_tx_chains(ltx_parent, verify)

    def _check_tx_chains(self, ltx_parent, verify) -> bool:
        _, dropped = walk_tx_chains(self._txs_only(), ltx_parent, verify,
                                    stop_on_first=True)
        return not dropped

    def _txs_only(self) -> List[TransactionFrame]:
        return [t for t, _ in self._txs]

    # --------------------------------------------------------- apply order --
    def get_txs_in_apply_order(self) -> List[TransactionFrame]:
        """Reference TxSetFrame.cpp:550-599: per-account seqnum order kept,
        inter-account order by hash mix with the set hash."""
        set_hash = self.get_contents_hash()
        by_acct: Dict[bytes, List[TransactionFrame]] = {}
        for t, _ in self._txs:
            by_acct.setdefault(t.source_id.to_bytes(), []).append(t)
        for txs in by_acct.values():
            txs.sort(key=lambda t: t.seq_num)
        # each account's next tx is a "head"; repeatedly take the head
        # with the smallest mixed hash
        heads = []
        for acct, txs in by_acct.items():
            heads.append((sha256(set_hash + txs[0].full_hash()), acct, 0))
        out: List[TransactionFrame] = []
        import heapq
        heapq.heapify(heads)
        while heads:
            _, acct, idx = heapq.heappop(heads)
            txs = by_acct[acct]
            out.append(txs[idx])
            if idx + 1 < len(txs):
                heapq.heappush(
                    heads,
                    (sha256(set_hash + txs[idx + 1].full_hash()), acct,
                     idx + 1))
        return out


def _header_hash(header) -> bytes:
    return sha256(header.to_bytes())


def walk_tx_chains(txs: Sequence[TransactionFrame], ltx_parent, verify,
                   stop_on_first: bool = False
                   ) -> Tuple[List[TransactionFrame],
                              List[TransactionFrame]]:
    """Per-account seqnum-chain validation walk shared by txset
    checkValid and the proposer's trim (reference: TxSetUtils —
    checkValidInternal and trimInvalid ride the same chain logic).
    Only the first tx of a chain is checked against the live account
    seqnum; accepted txs consume their seqnum so followers must be
    contiguous. Returns (kept, dropped); with stop_on_first the walk
    aborts at the first invalid tx (validation mode)."""
    from ..ledger.ledger_txn import LedgerTxn
    from ..tx.signature_checker import default_verify
    verify = verify or default_verify
    by_acct: Dict[bytes, List[TransactionFrame]] = {}
    for t in txs:
        by_acct.setdefault(t.source_id.to_bytes(), []).append(t)
    kept: List[TransactionFrame] = []
    dropped: List[TransactionFrame] = []
    with LedgerTxn(ltx_parent) as ltx:
        for chain in by_acct.values():
            chain.sort(key=lambda t: t.seq_num)
            for t in chain:
                if t.check_valid(ltx, current=0, verify=verify):
                    t._process_seq_num(ltx)
                    kept.append(t)
                else:
                    dropped.append(t)
                    if stop_on_first:
                        ltx.rollback()
                        return kept, dropped
        ltx.rollback()
    return kept, dropped


def trim_invalid(txs: Sequence[TransactionFrame], ltx_root, verify=None
                 ) -> Tuple[List[TransactionFrame],
                            List[TransactionFrame]]:
    """Split candidates into (valid, invalid) against the LCL state in
    `ltx_root` (reference: TxSetUtils::trimInvalid,
    herder/TxSetUtils.cpp:200 — run on the proposer's queue snapshot
    before surge pricing so a stale-invalid tx can never reach a
    nominated set; the herder bans the invalid remainder)."""
    return walk_tx_chains(txs, ltx_root, verify)


def make_tx_set_from_transactions(
        txs: Sequence[TransactionFrame],
        lcl_header,
        network_id: bytes,
        lane_config: Optional[SurgePricingLaneConfig] = None,
) -> Tuple[TxSetFrame, ApplicableTxSet, List[TransactionFrame]]:
    """Build a tx set from candidate txs with surge pricing applied
    (reference: makeTxSetFromTransactions). Returns (wire frame,
    applicable set, excluded txs — surge-priced-out, still queueable).
    Proposers run trim_invalid on the candidates first (the reference's
    makeFromTransactions does the trim internally and reports invalids
    through an out-param; here the herder owns that step and bans the
    remainder)."""
    if lane_config is None:
        lane_config = SurgePricingLaneConfig([lcl_header.maxTxSetSize])
    included, base_fees = surge_pricing_filter(txs, lane_config)
    excluded = [t for t in txs if t not in included]

    prev_hash = _header_hash(lcl_header)
    if lcl_header.ledgerVersion >= FIRST_GENERALIZED_TX_SET_PROTOCOL:
        xdr_set = _build_generalized(included, base_fees, lane_config,
                                     prev_hash, lcl_header)
    else:
        envs = [t.envelope for t in _sort_for_contents(included)]
        xdr_set = TransactionSet(previousLedgerHash=prev_hash, txs=envs)
    frame = TxSetFrame(xdr_set, network_id)
    applicable = frame.prepare_for_apply(lcl_header)
    assert applicable is not None
    return frame, applicable, excluded


def _sort_for_contents(txs: Sequence[TransactionFrame]
                       ) -> List[TransactionFrame]:
    """Canonical in-set order: by full hash (reference:
    TxSetUtils::sortTxsInHashOrder)."""
    return sorted(txs, key=lambda t: t.full_hash())


def _build_generalized(included, base_fees, lane_config, prev_hash,
                       lcl_header) -> GeneralizedTransactionSet:
    # one component per distinct base fee (reference:
    # TxSetFrame::makeFromTransactions building per-lane components);
    # surged lanes get their clearing fee, others an absent baseFee.
    comp_txs: Dict[Optional[int], List] = {}
    for t in included:
        lane = lane_config.lane_of(t)
        bf = base_fees.get(lane)
        if bf is not None:
            # clearing fee must never exceed what any included tx bid
            # per op, nor fall below the protocol minimum
            bf = max(lcl_header.baseFee, bf)
        comp_txs.setdefault(bf, []).append(t)
    components = []
    for bf in sorted(comp_txs, key=lambda v: (v is not None, v or 0)):
        envs = [t.envelope for t in _sort_for_contents(comp_txs[bf])]
        comp = TxSetComponent(
            TxSetComponentType.TXSET_COMP_TXS_MAYBE_DISCOUNTED_FEE)
        comp.value.baseFee = bf
        comp.value.txs = envs
        components.append(comp)
    phase_classic = TransactionPhase(0, components)
    phase_soroban = TransactionPhase(0, [])
    v1 = TransactionSetV1(previousLedgerHash=prev_hash,
                          phases=[phase_classic, phase_soroban])
    return GeneralizedTransactionSet(1, v1)
