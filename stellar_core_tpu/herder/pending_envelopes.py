"""Fetch-tracking for SCP envelopes and their referenced artifacts.

Reference: src/herder/PendingEnvelopes.{h,cpp} — an SCP envelope can only
be fed to SCP once every tx set and quorum set its statement references
is locally available; until then it sits in a fetching queue and the
overlay's ItemFetchers anycast GET_TX_SET / GET_SCP_QUORUMSET requests.
The fetch transport is injected (`request_txset` / `request_qset`
callables) so tests and the in-process simulation can satisfy fetches
synchronously.
"""

from __future__ import annotations

from enum import Enum
from typing import Callable, Dict, List, Optional, Set

from ..crypto.sha import sha256
from ..scp import local_node as ln
from ..util.logging import get_logger
from ..xdr.ledger import StellarValue
from ..xdr.scp import SCPEnvelope, SCPQuorumSet, SCPStatementType

log = get_logger("Herder")

# reference: Herder.h MAX_SLOTS_TO_REMEMBER
MAX_SLOTS_TO_REMEMBER = 12


class RecvState(Enum):
    # reference: Herder::EnvelopeStatus
    ENVELOPE_STATUS_DISCARDED = 0
    ENVELOPE_STATUS_FETCHING = 1
    ENVELOPE_STATUS_READY = 2
    ENVELOPE_STATUS_PROCESSED = 3


def _statement_txset_hashes(st) -> Set[bytes]:
    """Every txSetHash referenced by the statement's StellarValues
    (reference: getTxSetHashes/getStellarValues)."""
    values: List[bytes] = []
    t = st.pledges.disc
    pl = st.pledges.value
    if t == SCPStatementType.SCP_ST_NOMINATE:
        values.extend(bytes(v) for v in pl.votes)
        values.extend(bytes(v) for v in pl.accepted)
    elif t == SCPStatementType.SCP_ST_PREPARE:
        if pl.ballot.counter != 0:
            values.append(bytes(pl.ballot.value))
        if pl.prepared is not None:
            values.append(bytes(pl.prepared.value))
        if pl.preparedPrime is not None:
            values.append(bytes(pl.preparedPrime.value))
    elif t == SCPStatementType.SCP_ST_CONFIRM:
        values.append(bytes(pl.ballot.value))
    else:
        values.append(bytes(pl.commit.value))
    out = set()
    for raw in values:
        try:
            sv = StellarValue.from_bytes(raw)
        except Exception:
            continue
        out.add(bytes(sv.txSetHash))
    return out


def _statement_qset_hash(st) -> Optional[bytes]:
    t = st.pledges.disc
    if t == SCPStatementType.SCP_ST_EXTERNALIZE:
        return None  # externalize acts as its own singleton qset
    return bytes(st.pledges.value.quorumSetHash)


class PendingEnvelopes:
    def __init__(self, network_id: bytes,
                 request_txset: Optional[Callable[[bytes], None]] = None,
                 request_qset: Optional[Callable[[bytes], None]] = None):
        self.network_id = network_id
        self._txsets: Dict[bytes, object] = {}     # hash -> TxSetFrame
        self._qsets: Dict[bytes, SCPQuorumSet] = {}
        self._fetching: Dict[int, List[SCPEnvelope]] = {}
        self._ready: Dict[int, List[SCPEnvelope]] = {}
        self._processed: Dict[int, Set[bytes]] = {}
        self._discarded: Dict[int, Set[bytes]] = {}
        self.request_txset = request_txset or (lambda h: None)
        self.request_qset = request_qset or (lambda h: None)

    # ------------------------------------------------------------- caches --
    def add_tx_set(self, tx_set_hash: bytes, tx_set) -> None:
        self._txsets[tx_set_hash] = tx_set
        self._recheck_fetching()

    def add_scp_quorum_set(self, qset_hash: bytes,
                           qset: SCPQuorumSet) -> None:
        self._qsets[qset_hash] = qset
        self._recheck_fetching()

    def get_tx_set(self, tx_set_hash: bytes):
        return self._txsets.get(tx_set_hash)

    def get_qset(self, qset_hash: bytes) -> Optional[SCPQuorumSet]:
        return self._qsets.get(qset_hash)

    def put_local_qset(self, qset: SCPQuorumSet) -> None:
        self._qsets[ln.qset_hash(qset)] = qset

    # -------------------------------------------------------------- state --
    def _missing_for(self, env: SCPEnvelope) -> Set[bytes]:
        st = env.statement
        missing = {h for h in _statement_txset_hashes(st)
                   if h not in self._txsets}
        qh = _statement_qset_hash(st)
        if qh is not None and qh not in self._qsets:
            missing.add(qh)
        return missing

    def recv_scp_envelope(self, env: SCPEnvelope) -> RecvState:
        """Classify an incoming envelope (reference:
        PendingEnvelopes::recvSCPEnvelope)."""
        slot = env.statement.slotIndex
        eh = sha256(env.to_bytes())
        if eh in self._discarded.get(slot, set()):
            return RecvState.ENVELOPE_STATUS_DISCARDED
        if eh in self._processed.get(slot, set()):
            return RecvState.ENVELOPE_STATUS_PROCESSED
        missing = self._missing_for(env)
        if not missing:
            self._ready.setdefault(slot, []).append(env)
            self._processed.setdefault(slot, set()).add(eh)
            return RecvState.ENVELOPE_STATUS_READY
        st = env.statement
        qh = _statement_qset_hash(st)
        for h in missing:
            if h == qh:
                self.request_qset(h)
            else:
                self.request_txset(h)
        self._fetching.setdefault(slot, []).append(env)
        return RecvState.ENVELOPE_STATUS_FETCHING

    def _recheck_fetching(self) -> None:
        for slot, envs in list(self._fetching.items()):
            still = []
            for env in envs:
                if not self._missing_for(env):
                    eh = sha256(env.to_bytes())
                    if eh not in self._processed.get(slot, set()):
                        self._ready.setdefault(slot, []).append(env)
                        self._processed.setdefault(slot, set()).add(eh)
                else:
                    still.append(env)
            if still:
                self._fetching[slot] = still
            else:
                self._fetching.pop(slot, None)

    def pop_ready(self, slot: int) -> List[SCPEnvelope]:
        return self._ready.pop(slot, [])

    def has_ready(self) -> bool:
        return any(self._ready.values())

    def ready_slots(self) -> List[int]:
        return sorted(self._ready)

    # ---------------------------------------------------------------- gc --
    def slot_closed(self, closed_slot: int,
                    max_slots: int = MAX_SLOTS_TO_REMEMBER) -> None:
        """Drop state for slots too old to matter (reference:
        eraseBelow via MAX_SLOTS_TO_REMEMBER; the herder passes its
        configured window)."""
        low = closed_slot - max_slots + 1
        for d in (self._fetching, self._ready, self._processed,
                  self._discarded):
            for s in [s for s in d if s < low]:
                del d[s]

    def discard_slot(self, slot: int) -> None:
        self._fetching.pop(slot, None)
        self._ready.pop(slot, None)
