"""Transitive-quorum tracker.

Maintains, for every node reachable through quorum-set references from the
local node, how far away it is (in qset hops) and through which immediate
validators it is reached. Reference: src/herder/QuorumTracker.{h,cpp} —
`QuorumTracker::expand` (incremental) and `rebuild` (full BFS), consumed by
`HerderImpl::isNodeDefinitelyInQuorum` and the `quorum` HTTP endpoint's
"transitive" section.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Set

from ..xdr.scp import SCPQuorumSet


def _qset_nodes(qset: SCPQuorumSet) -> Set[bytes]:
    """All node ids referenced (recursively) by a quorum set."""
    out: Set[bytes] = set()
    for v in qset.validators:
        out.add(bytes(v.value))
    for inner in qset.innerSets:
        out |= _qset_nodes(inner)
    return out


@dataclass
class NodeInfo:
    """What we know about one node in the transitive quorum."""
    qset: Optional[SCPQuorumSet] = None
    distance: int = 0
    # local-qset validators through which this node is reachable
    closest_validators: Set[bytes] = field(default_factory=set)


class QuorumTracker:
    """Tracks the transitive closure of quorum-set references starting at
    the local node's quorum set."""

    def __init__(self, local_node_id: bytes, local_qset: SCPQuorumSet):
        self._local_id = local_node_id
        self._local_qset = local_qset
        self._quorum: Dict[bytes, NodeInfo] = {}
        self.rebuild(lambda _: None)

    # ------------------------------------------------------------ queries --
    def is_node_definitely_in_quorum(self, node_id: bytes) -> bool:
        return node_id in self._quorum

    @property
    def quorum_map(self) -> Dict[bytes, NodeInfo]:
        return self._quorum

    def set_local_qset(self, qset: SCPQuorumSet,
                       lookup: Callable[[bytes], Optional[SCPQuorumSet]]
                       ) -> None:
        self._local_qset = qset
        self.rebuild(lookup)

    # ------------------------------------------------------------ updates --
    def expand(self, node_id: bytes, qset: SCPQuorumSet) -> bool:
        """Incrementally record `node_id`'s quorum set. Returns False when
        the update cannot be applied incrementally (unknown node, or a
        conflicting qset already recorded) — caller should `rebuild`."""
        info = self._quorum.get(node_id)
        if info is None:
            return False  # not reachable as far as we know: needs rebuild
        if info.qset is not None:
            return info.qset is qset or info.qset == qset
        new_nodes = _qset_nodes(qset)
        # refuse to shorten an existing node's distance incrementally —
        # descendants computed from the longer path would go stale
        # (reference handles inconsistencies by forcing a rebuild)
        for nid in new_nodes:
            sub = self._quorum.get(nid)
            if sub is not None and info.distance + 1 < sub.distance:
                return False
        info.qset = qset
        for nid in new_nodes:
            sub = self._quorum.get(nid)
            if sub is None:
                self._quorum[nid] = NodeInfo(
                    qset=None, distance=info.distance + 1,
                    closest_validators=set(info.closest_validators))
            else:
                # union of reach paths ("reachable through" semantics)
                sub.closest_validators |= info.closest_validators
        return True

    def rebuild(self, lookup: Callable[[bytes], Optional[SCPQuorumSet]]
                ) -> None:
        """Full BFS from the local qset, resolving qsets via `lookup`."""
        self._quorum = {self._local_id: NodeInfo(qset=self._local_qset,
                                                 distance=0)}
        frontier = deque()
        for nid in _qset_nodes(self._local_qset):
            info = self._quorum.get(nid)
            if info is None:
                self._quorum[nid] = NodeInfo(distance=1,
                                             closest_validators={nid})
                frontier.append(nid)
            else:
                info.closest_validators.add(nid)
        while frontier:
            nid = frontier.popleft()
            info = self._quorum[nid]
            qset = info.qset if info.qset is not None else lookup(nid)
            if qset is None:
                continue
            info.qset = qset
            for sub in _qset_nodes(qset):
                known = self._quorum.get(sub)
                if known is None:
                    self._quorum[sub] = NodeInfo(
                        distance=info.distance + 1,
                        closest_validators=set(info.closest_validators))
                    frontier.append(sub)
                else:
                    known.closest_validators |= info.closest_validators
                    if info.distance + 1 < known.distance:
                        known.distance = info.distance + 1

    # --------------------------------------------------------- inspection --
    def transitive_json(self) -> dict:
        from ..crypto.strkey import StrKey
        nodes = []
        for nid, info in sorted(self._quorum.items()):
            nodes.append({
                "node": StrKey.encode_ed25519_public(nid),
                "distance": info.distance,
                "heard_qset": info.qset is not None,
            })
        return {"node_count": len(self._quorum), "nodes": nodes}
