from .tx_set import (ApplicableTxSet, TxSetFrame, make_tx_set_from_transactions)
from .tx_queue import TransactionQueue, AddResult
from .upgrades import Upgrades
from .surge_pricing import SurgePricingLaneConfig, surge_pricing_filter

__all__ = [
    "ApplicableTxSet", "TxSetFrame", "make_tx_set_from_transactions",
    "TransactionQueue", "AddResult", "Upgrades",
    "SurgePricingLaneConfig", "surge_pricing_filter",
]
