"""Pending-transaction queue.

Reference: src/herder/TransactionQueue.{h,cpp} — the pool of candidate txs
between submission and inclusion. Lifecycle (TransactionQueue.h:35-59):
`try_add` admits after full validation; `shift` runs at every ledger close,
ageing every queued tx and banning sources whose txs sat for `pending_depth`
ledgers; banned hashes stay banned for `ban_depth` ledgers; `remove_applied`
drops included txs.

Capacity is op-counted: `pool_ledger_multiplier × maxTxSetSize`; when full,
the lowest-fee-rate tx is evicted (and banned) to make room for a
better-paying one (reference: TxQueueLimiter).
"""

from __future__ import annotations

from enum import Enum
from typing import Dict, List, Optional

from ..util.logging import get_logger
from .surge_pricing import fee_rate_cmp

log = get_logger("Herder")

# reference: TransactionQueue ctor args in HerderImpl.cpp
DEFAULT_PENDING_DEPTH = 4
DEFAULT_BAN_DEPTH = 10
DEFAULT_POOL_LEDGER_MULTIPLIER = 2
# fee-bump replacement must pay >= 10x the fee rate of what it replaces
# (reference: FEE_MULTIPLIER in TransactionQueue.cpp)
FEE_MULTIPLIER = 10


class AddResult(Enum):
    ADD_STATUS_PENDING = 0
    ADD_STATUS_DUPLICATE = 1
    ADD_STATUS_ERROR = 2
    ADD_STATUS_TRY_AGAIN_LATER = 3
    ADD_STATUS_FILTERED = 4


class _QueuedTx:
    __slots__ = ("tx", "age", "ops", "fee")

    def __init__(self, tx):
        self.tx = tx
        self.age = 0
        # cached for the eviction scan (avoids re-deriving per compare)
        self.ops = max(1, tx.num_operations())
        self.fee = tx.inclusion_fee()


class TransactionQueue:
    def __init__(self, pending_depth: int = DEFAULT_PENDING_DEPTH,
                 ban_depth: int = DEFAULT_BAN_DEPTH,
                 pool_ledger_multiplier: int = DEFAULT_POOL_LEDGER_MULTIPLIER,
                 metrics=None, limit_source_account: bool = False):
        self.pending_depth = pending_depth
        self.ban_depth = ban_depth
        self.pool_ledger_multiplier = pool_ledger_multiplier
        # at most one queued tx per source account (reference:
        # LIMIT_TX_QUEUE_SOURCE_ACCOUNT) — replace-by-fee still allowed
        self.limit_source_account = limit_source_account
        self._by_account: Dict[bytes, List[_QueuedTx]] = {}
        self._by_hash: Dict[bytes, _QueuedTx] = {}
        # ban generations: index 0 = banned this ledger
        self._banned: List[set] = [set() for _ in range(ban_depth)]
        self._total_ops = 0     # incremental size_ops (O(1) admission)
        self._metrics = metrics
        if metrics is not None:
            self._size_gauge = metrics.counter("herder", "pending-txs", "sum")
        else:
            self._size_gauge = None

    # ------------------------------------------------------------- queries --
    def size_ops(self) -> int:
        return self._total_ops

    def size_txs(self) -> int:
        return len(self._by_hash)

    def is_banned(self, tx_hash: bytes) -> bool:
        return any(tx_hash in gen for gen in self._banned)

    def is_pending(self, tx_hash: bytes) -> bool:
        """Already queued? (what try_add reports as DUPLICATE — the
        flood-admission path asks first to skip signature work for
        redundant deliveries)"""
        return tx_hash in self._by_hash

    def get_tx(self, tx_hash: bytes):
        """Queued tx by hash, or None (reference: getTx)."""
        q = self._by_hash.get(tx_hash)
        return q.tx if q is not None else None

    def get_transactions(self) -> List[object]:
        """All queued txs, candidates for the next tx set (reference:
        getTransactions)."""
        return [q.tx for q in self._by_hash.values()]

    # ----------------------------------------------------------- admission --
    def try_add(self, tx, ltx_root, max_queue_ops: int,
                verify=None) -> AddResult:
        """Admit a tx after validation (reference: TransactionQueue::tryAdd
        → canAdd → TransactionFrame::checkValid)."""
        h = tx.full_hash()
        if self.is_banned(h):
            return AddResult.ADD_STATUS_TRY_AGAIN_LATER
        if h in self._by_hash:
            return AddResult.ADD_STATUS_DUPLICATE
        acct = tx.source_id.to_bytes()
        chain = self._by_account.get(acct, [])
        replacing: Optional[_QueuedTx] = None
        for q in chain:
            if q.tx.seq_num == tx.seq_num:
                # replace-by-fee: must bid >= FEE_MULTIPLIER x the old rate
                old = q.tx
                if fee_rate_cmp(tx.inclusion_fee(),
                                max(1, tx.num_operations()),
                                FEE_MULTIPLIER * old.inclusion_fee(),
                                max(1, old.num_operations())) < 0:
                    return AddResult.ADD_STATUS_ERROR
                replacing = q
                break
        if self.limit_source_account and chain and replacing is None:
            return AddResult.ADD_STATUS_TRY_AGAIN_LATER
        # full validation against current ledger state; chained txs from
        # the same account validate with predecessors' seqnums consumed
        from ..ledger.ledger_txn import LedgerTxn
        from ..tx.signature_checker import default_verify
        verify = verify or default_verify
        with LedgerTxn(ltx_root) as ltx:
            for q in chain:
                if q.tx.seq_num < tx.seq_num and q is not replacing:
                    q.tx._process_seq_num(ltx)
            ok = tx.check_valid(ltx, verify=verify)
            ltx.rollback()
        if not ok:
            return AddResult.ADD_STATUS_ERROR
        # capacity: the replaced tx's ops are already freed (it can't be
        # picked for eviction and doesn't count against the limit), but it
        # is only dropped once admission is certain
        new_ops = max(1, tx.num_operations())
        freed = replacing.ops if replacing else 0
        need = self.size_ops() - freed + new_ops - max_queue_ops
        if need > 0:
            # two-phase eviction (reference: TxQueueLimiter::canAddTx
            # evaluates the whole eviction set before dropping anything):
            # nothing is evicted or banned unless the newcomer actually
            # gets admitted
            import functools
            candidates = sorted(
                (q for q in self._by_hash.values() if q is not replacing),
                key=functools.cmp_to_key(
                    lambda a, b: fee_rate_cmp(a.fee, a.ops, b.fee, b.ops)))
            evict = []
            for q in candidates:
                if need <= 0:
                    break
                if fee_rate_cmp(tx.inclusion_fee(), new_ops,
                                q.fee, q.ops) <= 0:
                    return AddResult.ADD_STATUS_TRY_AGAIN_LATER
                evict.append(q)
                need -= q.ops
            if need > 0:
                return AddResult.ADD_STATUS_TRY_AGAIN_LATER
            for q in evict:
                self._drop(q, ban=True)
        if replacing is not None:
            self._drop(replacing, ban=True)
        q = _QueuedTx(tx)
        self._by_hash[h] = q
        self._total_ops += q.ops
        self._by_account.setdefault(acct, []).append(q)
        self._by_account[acct].sort(key=lambda e: e.tx.seq_num)
        self._update_size_gauge()
        return AddResult.ADD_STATUS_PENDING

    def _drop(self, q: _QueuedTx, ban: bool) -> None:
        h = q.tx.full_hash()
        if self._by_hash.pop(h, None) is not None:
            self._total_ops -= q.ops
        acct = q.tx.source_id.to_bytes()
        chain = self._by_account.get(acct)
        if chain is not None:
            self._by_account[acct] = [e for e in chain if e is not q]
            if not self._by_account[acct]:
                del self._by_account[acct]
        if ban:
            self._banned[0].add(h)
        self._update_size_gauge()

    def _update_size_gauge(self) -> None:
        if self._size_gauge is not None:
            self._size_gauge.set_count(len(self._by_hash))

    # ------------------------------------------------------------ lifecycle --
    def remove_applied(self, txs) -> None:
        """Drop txs included in a closed ledger; also drop queued txs made
        invalid by consumed seqnums (reference: removeApplied)."""
        applied_hashes = {t.full_hash() for t in txs}
        max_seq_by_acct: Dict[bytes, int] = {}
        for t in txs:
            a = t.source_id.to_bytes()
            max_seq_by_acct[a] = max(max_seq_by_acct.get(a, 0), t.seq_num)
        for h in list(self._by_hash):
            q = self._by_hash.get(h)
            if q is None:
                continue
            if h in applied_hashes:
                self._drop(q, ban=False)
                continue
            a = q.tx.source_id.to_bytes()
            if a in max_seq_by_acct and q.tx.seq_num <= max_seq_by_acct[a]:
                self._drop(q, ban=False)

    def ban(self, txs) -> None:
        for t in txs:
            h = t.full_hash()
            self._banned[0].add(h)
            q = self._by_hash.get(h)
            if q is not None:
                self._drop(q, ban=False)

    def shift(self) -> None:
        """Per-ledger-close ageing (reference: TransactionQueue::shift):
        rotate ban generations, age queued txs, ban the too-old."""
        self._banned.pop()
        self._banned.insert(0, set())
        to_ban = []
        for q in self._by_hash.values():
            q.age += 1
            if q.age >= self.pending_depth:
                to_ban.append(q)
        for q in to_ban:
            self._drop(q, ban=True)
            log.debug("banned aged-out tx %s", q.tx.full_hash().hex()[:16])
