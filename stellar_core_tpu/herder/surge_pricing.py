"""Surge pricing — multi-lane fee-rate prioritisation.

Reference: src/herder/SurgePricingUtils.{h,cpp} — lane 0 is the generic lane
whose limit every tx counts against; extra lanes (e.g. DEX-op txs) have their
own sub-limits. Selection pops the highest fee-rate txs that still fit their
lane(s); the "clearing" fee rate per lane is the lowest included rate when a
lane overflowed, and absent otherwise.

Fee-rate comparison is exact rational comparison fee_a/ops_a vs fee_b/ops_b
(reference: SurgePricingUtils.cpp feeRate3WayCompare), tie-broken by full
hash for determinism.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple

GENERIC_LANE = 0


def fee_rate_cmp(fee_a: int, ops_a: int, fee_b: int, ops_b: int) -> int:
    """3-way compare of fee rates as exact rationals
    (reference: feeRate3WayCompare)."""
    lhs = fee_a * ops_b
    rhs = fee_b * ops_a
    return (lhs > rhs) - (lhs < rhs)


def compute_per_op_fee(fee: int, ops: int, rounding_up: bool) -> int:
    ops = max(1, ops)
    if rounding_up:
        return -(-fee // ops)
    return fee // ops


class SurgePricingLaneConfig:
    """Lane limits + classifier. `lane_of(tx)` returns the lane index;
    `limits[lane]` is the op-count capacity of that lane; limits[0] is the
    total capacity (reference: DexLimitingLaneConfig)."""

    def __init__(self, limits: Sequence[int],
                 lane_of: Optional[Callable[[object], int]] = None):
        assert len(limits) >= 1
        self.limits = list(limits)
        self._lane_of = lane_of or (lambda tx: GENERIC_LANE)

    def lane_of(self, tx) -> int:
        lane = self._lane_of(tx)
        assert 0 <= lane < len(self.limits)
        return lane


def surge_pricing_filter(
        txs: Sequence[object],
        config: SurgePricingLaneConfig,
) -> Tuple[List[object], Dict[int, Optional[int]]]:
    """Pick the highest-paying txs that fit the lane limits, visiting
    each ACCOUNT's txs in seqnum order (reference:
    SurgePricingPriorityQueue::popTopTxs over per-account TxStacks —
    a stack's priority is its NEXT tx's fee rate, and a stack whose
    next tx doesn't fit is dropped whole, since the rest of the chain
    would be seqnum-gapped and invalid).

    Returns (included txs, {lane: clearing base_fee or None}). The
    clearing fee is set for a lane iff at least one tx was excluded from
    it (or from the generic capacity while the tx was in that lane)."""
    import heapq
    from fractions import Fraction

    by_acct: Dict[bytes, List[object]] = {}
    for tx in txs:
        by_acct.setdefault(tx.source_id.to_bytes(), []).append(tx)

    def head_key(tx):
        # max fee rate first; hash ascending tie-break (deterministic,
        # reference: TxStackComparator's hash tie-break)
        return (-Fraction(tx.inclusion_fee(),
                          max(1, tx.num_operations())), tx.full_hash())

    heads = []
    for acct, chain in by_acct.items():
        chain.sort(key=lambda t: t.seq_num)
        # duplicate seqnums (e.g. a replace-by-fee race in the queue)
        # can't both apply: keep the best-paying per seqnum so the
        # emitted set stays chain-valid
        dedup: List[object] = []
        for t in chain:
            if dedup and dedup[-1].seq_num == t.seq_num:
                if fee_rate_cmp(t.inclusion_fee(),
                                max(1, t.num_operations()),
                                dedup[-1].inclusion_fee(),
                                max(1, dedup[-1].num_operations())) > 0:
                    dedup[-1] = t
            else:
                dedup.append(t)
        by_acct[acct] = dedup
        heapq.heappush(heads, (*head_key(dedup[0]), acct, 0))

    remaining = list(config.limits)
    included: List[object] = []
    lane_overflowed: Dict[int, bool] = {}
    lane_min_rate: Dict[int, Tuple[int, int]] = {}

    while heads:
        _, _, acct, idx = heapq.heappop(heads)
        tx = by_acct[acct][idx]
        lane = config.lane_of(tx)
        ops = max(1, tx.num_operations())
        fits_generic = remaining[GENERIC_LANE] >= ops
        fits_lane = (lane == GENERIC_LANE or remaining[lane] >= ops)
        if fits_generic and fits_lane:
            remaining[GENERIC_LANE] -= ops
            if lane != GENERIC_LANE:
                remaining[lane] -= ops
            included.append(tx)
            r = (tx.inclusion_fee(), ops)
            cur = lane_min_rate.get(lane)
            if cur is None or fee_rate_cmp(r[0], r[1], cur[0], cur[1]) < 0:
                lane_min_rate[lane] = r
            if idx + 1 < len(by_acct[acct]):
                nxt = by_acct[acct][idx + 1]
                heapq.heappush(heads, (*head_key(nxt), acct, idx + 1))
        else:
            # the whole remaining chain of this account is excluded:
            # an excluded tx surges its own lane; if it failed on
            # generic capacity it surges every lane (reference:
            # popTopTxs hadTxNotFittingLane semantics)
            if not fits_generic:
                for ln in range(len(config.limits)):
                    lane_overflowed[ln] = True
            else:
                lane_overflowed[lane] = True

    base_fees: Dict[int, Optional[int]] = {}
    for lane in range(len(config.limits)):
        if lane_overflowed.get(lane) and lane in lane_min_rate:
            fee, ops = lane_min_rate[lane]
            base_fees[lane] = compute_per_op_fee(fee, ops, rounding_up=False)
        else:
            base_fees[lane] = None
    return included, base_fees


