"""Herder — drives ledger close from transaction submission.

Reference: src/herder/HerderImpl.{h,cpp}. This class owns the
TransactionQueue and the Upgrades table and turns queue contents into tx
sets (`triggerNextLedger`, HerderImpl.cpp:1266) and externalized values
into `LedgerManager::closeLedger` calls (`valueExternalized` :380).

In RUN_STANDALONE/MANUAL_CLOSE mode (milestone M1, SURVEY.md §7 step 4)
there is no SCP: `trigger_next_ledger` externalizes its own proposal
immediately, exactly like the reference's standalone manual-close path
(Herder::setInSyncAndTriggerNextLedger via the `manualclose` command).
The SCP binding (HerderSCPDriver) layers on top without changing this
pipeline.
"""

from __future__ import annotations

import time
from enum import Enum
from typing import List, Optional

from ..ledger.ledger_manager import LedgerCloseData, LedgerManager
from ..util import chaos, tracing
from ..util.logging import get_logger
from ..xdr.ledger import StellarValue, StellarValueType, _StellarValueExt
from .tx_queue import AddResult, TransactionQueue
from .tx_set import make_tx_set_from_transactions, trim_invalid
from .upgrades import Upgrades

log = get_logger("Herder")

# reference: Herder.h MAX_SCP_TIMEOUT_SECONDS etc.
MAX_TIME_SLIP_SECONDS = 60
# reference: Herder.h LEDGER_VALIDITY_BRACKET — max slots ahead of LCL we
# accept envelopes for
LEDGER_VALIDITY_BRACKET = 100
# reference: Herder.h CONSENSUS_STUCK_TIMEOUT_SECONDS
CONSENSUS_STUCK_TIMEOUT_SECONDS = 35.0
# reference: out-of-sync recovery cadence (HerderImpl::outOfSyncRecovery)
OUT_OF_SYNC_RECOVERY_TIMER_SECONDS = 10.0

# slot phase timelines kept in memory (mesh observatory): enough for
# MAX_SLOTS_TO_REMEMBER-scale introspection, bounded regardless
SLOT_TIMELINE_MAX = 64


class HerderState(Enum):
    # reference: Herder.h State
    HERDER_BOOTING_STATE = 0
    HERDER_SYNCING_STATE = 1
    HERDER_TRACKING_NETWORK_STATE = 2


class Herder:
    SLOT_TIMELINE_MAX = SLOT_TIMELINE_MAX

    def __init__(self, config, ledger_manager: LedgerManager,
                 metrics=None, verify=None, batch_verifier=None,
                 verify_service=None):
        self.batch_verifier = batch_verifier
        # coalescing verify service (ops/verify_service.py): the live
        # per-signature paths — SCP envelopes, StellarValue signatures,
        # batched flood admission — route through it when present
        self.verify_service = verify_service
        self.config = config
        self.ledger_manager = ledger_manager
        self.network_id = config.network_id()
        self.upgrades = Upgrades(
            current_protocol_version=config.LEDGER_PROTOCOL_VERSION)
        self.tx_queue = TransactionQueue(
            pending_depth=config.TRANSACTION_QUEUE_PENDING_DEPTH,
            ban_depth=config.TRANSACTION_QUEUE_BAN_DEPTH,
            pool_ledger_multiplier=config.TRANSACTION_QUEUE_SIZE_MULTIPLIER,
            metrics=metrics,
            limit_source_account=config.LIMIT_TX_QUEUE_SOURCE_ACCOUNT)
        self.state = HerderState.HERDER_BOOTING_STATE
        self._verify = verify
        self._metrics = metrics
        self._clock = None  # set by Application
        # budgeted flood lanes (reference: FLOOD_TX_PERIOD_MS et al.);
        # bounded deques — overload drops the OLDEST adverts, which are
        # the ones peers least need (their txs age out of the queue)
        from collections import deque
        self._flood_classic = deque(maxlen=50_000)
        self._flood_soroban = deque(maxlen=50_000)
        self._flood_timer = None
        self._flood_last_drain: dict = {}
        if metrics is not None:
            self._tx_recv_meter = metrics.meter("herder", "tx", "received")
            self._tx_accept_meter = metrics.meter("herder", "tx", "accepted")
            # tx end-to-end latency: first-seen (submit/flood recv) →
            # externalized in a closed ledger, on THIS node's clock
            self.tx_e2e_timer = metrics.timer("ledger", "transaction",
                                              "e2e")
        else:
            self._tx_recv_meter = self._tx_accept_meter = None
            self.tx_e2e_timer = None
        # tx hash -> perf_counter at first acceptance; consumed by
        # _ledger_closed for the e2e timer + trace track, pruned so
        # never-externalized txs cannot grow it without bound
        self._tx_submit_times: dict = {}
        # hash-keyed propagation tracker (overlay/propagation.py), set
        # by Application; admission/externalize stamps land here so the
        # mesh observatory sees the full flood→admit→externalize path
        self.propagation = None
        # adaptive control plane (ops/controller.py), set by
        # Application: the tx-submit surge gate consults its shed
        # probability before any validation work is paid
        self.controller = None
        # per-slot consensus phase timeline (herder/scp_driver.py):
        # slot -> {phase: perf_counter, "_open": phase|None}, bounded
        self.slot_timelines: dict = {}

        # SCP binding (reference: HerderImpl owns SCP + PendingEnvelopes +
        # HerderSCPDriver); live whenever the node has an identity.
        from .pending_envelopes import PendingEnvelopes
        from .scp_driver import HerderSCPDriver
        self.pending_envelopes = PendingEnvelopes(self.network_id)
        self.scp = None
        self.scp_driver = None
        self.broadcast_cb = None      # set by overlay manager / simulation
        self.ledger_closed_cb = None  # set by overlay manager
        self.tx_advert_cb = None      # set by overlay manager
        self._tx_sets_for_slot = {}   # slot -> proposed TxSetFrame
        self._buffered_values = {}    # slot -> (StellarValue, tx_set)
        self._applicable_cache = {}   # txset hash -> (lcl seq, applicable)
        self._batch_pv_cache = {}     # txset hash -> (lcl seq, lazy pv)
        self._tx_set_valid_cache = {}  # (lcl hash, txset hash) -> bool
        self.trigger_timer = None
        self.catchup_manager = None   # set by Application
        self.out_of_sync_cb = None    # set by overlay manager
        from ..util.perf import default_registry
        self.perf = default_registry  # per-app registry set by Application
        self._tracking_timer = None
        if config.NODE_SEED is not None:
            from ..scp import SCP
            qset = config.QUORUM_SET.to_scp_quorum_set()
            from ..scp.quorum_set_utils import normalize_qset
            normalize_qset(qset)
            self.scp_driver = HerderSCPDriver(self)
            self.scp = SCP(self.scp_driver, config.node_id(),
                           config.NODE_IS_VALIDATOR, qset)
            self.pending_envelopes.put_local_qset(qset)
            from .quorum_tracker import QuorumTracker
            self.quorum_tracker = QuorumTracker(config.node_id(), qset)
        else:
            self.quorum_tracker = None

    # ------------------------------------------------------------ lifecycle --
    def start(self) -> None:
        """reference: Herder::start / bootstrap for FORCE_SCP."""
        self.state = HerderState.HERDER_TRACKING_NETWORK_STATE
        if self._tracks_network():
            self._arm_tracking_timer()

    def set_clock(self, clock) -> None:
        self._clock = clock

    def _now(self) -> int:
        if self._clock is not None:
            return int(self._clock.system_now())
        return int(time.time())

    def _next_close_time(self, lcl_header) -> int:
        """closeTime for the next proposed value. With
        ARTIFICIALLY_SET_CLOSE_TIME_FOR_TESTING (reference: Config.h)
        the clock drops out entirely — closeTime advances exactly one
        second per ledger from the configured base, so header bytes are
        reproducible run-to-run regardless of consensus timing
        (chaos-convergence scenarios diff header hashes across runs)."""
        fixed = self.config.ARTIFICIALLY_SET_CLOSE_TIME_FOR_TESTING
        if fixed:
            return max(int(fixed), lcl_header.scpValue.closeTime + 1)
        return max(self._now(), lcl_header.scpValue.closeTime + 1)

    # ----------------------------------------------------------- submission --
    def recv_transaction(self, tx, verify=None) -> AddResult:
        """Admit a tx to the pending queue (reference:
        Herder::recvTransaction :523). `verify` overrides the
        per-signature backend for this admission (the batched flood
        path passes a PrevalidatedVerifier seeded by one device
        batch)."""
        if verify is None and self.controller is not None and \
                self.controller.roll_tx_shed():
            # surge shedding (ops/controller.py): an overloaded node
            # turns direct submissions away BEFORE paying signature
            # verification or queue work — TRY_AGAIN_LATER is the
            # honest good-enough-answer-now (Tail at Scale). Only the
            # direct-submit path rolls here: flood admission sheds at
            # the overlay seam, upstream of the batched verify
            # dispatch, and arrives with a prevalidated `verify`.
            return AddResult.ADD_STATUS_TRY_AGAIN_LATER
        if self._tx_recv_meter is not None:
            self._tx_recv_meter.mark()
        max_ops = (self.config.TRANSACTION_QUEUE_SIZE_MULTIPLIER
                   * self._max_tx_set_ops())
        res = self.tx_queue.try_add(tx, self.ledger_manager.root, max_ops,
                                    verify=verify if verify is not None
                                    else self._verify)
        if res == AddResult.ADD_STATUS_PENDING:
            if self._tx_accept_meter is not None:
                self._tx_accept_meter.mark()
            h = tx.full_hash()
            if self.propagation is not None:
                # admission stamp on the propagation timeline (also
                # first-seen for a locally-submitted tx)
                self.propagation.on_admitted(h)
            if h not in self._tx_submit_times:
                self._tx_submit_times[h] = time.perf_counter()
                if tracing.ENABLED:
                    rec = self.perf.tracer
                    if rec is not None and rec.active:
                        # async track: begin here, end at externalize —
                        # possibly a different thread
                        rec.async_begin("tx.e2e", h.hex()[:16])
            # flood the acceptance (reference: recvTransaction →
            # OverlayManager broadcast, pull-mode advert) — rate-limited
            # per lane when FLOOD_*_PERIOD_MS is set
            if self.tx_advert_cb is not None:
                self._advert_or_queue(tx)
        return res

    def recv_transactions(self, frames,
                          bad_sig: Optional[List[bool]] = None
                          ) -> List[AddResult]:
        """Batched flood admission (ISSUE 4): the overlay collects the
        burst of TRANSACTION bodies received in one crank and admits
        them here as ONE prevalidated batch — every envelope signature
        of the burst goes through the coalescing verify service in a
        single device dispatch, and the per-tx try_add validation
        consumes the results via a PrevalidatedVerifier (misses fall
        back to the sync path, exact semantics). The service writes the
        results through the verify cache, so close-time re-verification
        of these txs is free.

        `bad_sig`, when given, receives one bool per frame: True iff
        the frame carried source-key envelope signatures and at least
        one verified False — the overlay's per-peer flooder accounting
        (ISSUE 7 satellite). Filled on the service path AND, since the
        multi-process harness runs native-backend nodes, on the
        serviceless path (per-signature verify, results prevalidated
        into try_add so nothing verifies twice)."""
        verify = self._verify
        svc = self.verify_service
        if frames and (svc is not None or bad_sig is not None):
            from ..tx.signature_checker import (PrevalidatedVerifier,
                                                collect_signature_tuples,
                                                default_verify)
            # envelope signatures only, like the txset prevalidator:
            # try_add's check_valid never verifies soroban auth
            # entries. On the serviceless path, skip frames try_add
            # will dedupe/ban anyway — with real-wire duplicate ratios
            # >1.5, most flood deliveries carry nothing to verify (a
            # duplicate with a bad signature is still not charged:
            # the FIRST delivery already was)
            if svc is None:
                per_frame = [
                    [] if self.tx_queue.is_pending(h := f.full_hash())
                    or self.tx_queue.is_banned(h)
                    else collect_signature_tuples([f]) for f in frames]
            else:
                per_frame = [collect_signature_tuples([f])
                             for f in frames]
            tuples = [t for ts in per_frame for t in ts]
            results: list = []
            if tuples:
                if svc is not None:
                    futures = svc.submit_many(tuples)
                    results = [f.result() for f in futures]
                else:
                    sync_verify = self._verify or default_verify
                    results = [sync_verify(p, s, m)
                               for p, s, m in tuples]
                pv = PrevalidatedVerifier(
                    fallback=self._verify or default_verify)
                pv.add_results(tuples, results)
                verify = pv
            if bad_sig is not None:
                # the contract is one bool per frame even when nothing
                # needed verifying (all duplicates / no signatures) —
                # the overlay's zip-based per-peer accounting must
                # never silently truncate
                it = iter(results)
                for ts in per_frame:
                    rs = [next(it) for _ in ts]
                    bad_sig.append(bool(ts) and not all(rs))
        return [self.recv_transaction(f, verify=verify) for f in frames]

    def _advert_or_queue(self, tx) -> None:
        """Advert now, or queue into the lane's budgeted flood drain
        (reference: TransactionQueue::broadcast — opsToFloodLedger =
        FLOOD_OP_RATE_PER_LEDGER * maxOps, drained every
        FLOOD_TX_PERIOD_MS; soroban rides its own lane)."""
        soroban = tx.is_soroban()
        period = (self.config.FLOOD_SOROBAN_TX_PERIOD_MS if soroban
                  else self.config.FLOOD_TX_PERIOD_MS)
        if period <= 0 or self._clock is None:
            self.tx_advert_cb(tx.full_hash())
            return
        lane = self._flood_soroban if soroban else self._flood_classic
        # a fresh lane's clock starts at first enqueue: the first drain
        # also waits the lane's full period
        self._flood_last_drain.setdefault(soroban, self._clock.now())
        lane.append((tx.full_hash(), max(1, tx.num_operations())))
        if self._flood_timer is None:
            self._arm_flood_timer()

    def _lane_due(self, soroban: bool, period_ms: float) -> bool:
        last = self._flood_last_drain.get(soroban)
        now = self._clock.now()
        if last is not None and (now - last) * 1000.0 < period_ms * 0.999:
            return False
        self._flood_last_drain[soroban] = now
        return True

    def _flood_budget(self, soroban: bool, period_ms: float) -> int:
        rate = (self.config.FLOOD_SOROBAN_RATE_PER_LEDGER if soroban
                else self.config.FLOOD_OP_RATE_PER_LEDGER)
        per_ledger = rate * self._max_tx_set_ops()
        ledger_s = max(0.001, self.config.EXPECTED_LEDGER_CLOSE_TIME)
        return max(1, int(per_ledger * (period_ms / 1000.0) / ledger_s))

    def _arm_flood_timer(self) -> None:
        from ..util.timer import VirtualTimer
        period = min(p for p in (self.config.FLOOD_TX_PERIOD_MS,
                                 self.config.FLOOD_SOROBAN_TX_PERIOD_MS)
                     if p > 0)
        t = VirtualTimer(self._clock)
        t.expires_from_now(period / 1000.0)
        t.async_wait(self._drain_floods)
        self._flood_timer = t

    def _drain_floods(self) -> None:
        self._flood_timer = None
        for soroban, lane, period in (
                (False, self._flood_classic,
                 self.config.FLOOD_TX_PERIOD_MS),
                (True, self._flood_soroban,
                 self.config.FLOOD_SOROBAN_TX_PERIOD_MS)):
            if not lane or period <= 0:
                continue
            # the shared timer fires at min(period); each lane drains
            # only when ITS OWN period has elapsed, else the slower
            # lane would flood at a multiple of its configured rate
            if not self._lane_due(soroban, period):
                continue
            budget = self._flood_budget(soroban, period)
            while lane and budget > 0:
                h, ops = lane.popleft()
                budget -= ops
                self.tx_advert_cb(h)
        if self._flood_classic or self._flood_soroban:
            self._arm_flood_timer()

    def _max_tx_set_ops(self) -> int:
        return self.ledger_manager.get_last_closed_ledger_header().maxTxSetSize

    # -------------------------------------------------------------- closing --
    def trigger_next_ledger(self) -> None:
        """Build a proposal from the queue (reference:
        Herder::triggerNextLedger :1266). Standalone mode externalizes it
        directly; under SCP this is where nomination starts."""
        lcl_header = self.ledger_manager.get_last_closed_ledger_header()
        next_seq = lcl_header.ledgerSeq + 1
        candidates, invalid = trim_invalid(
            self.tx_queue.get_transactions(), self.ledger_manager.root,
            verify=self._verify)
        if invalid:
            # reference: Herder::triggerNextLedger bans trimInvalid's
            # output so stale txs stop being re-validated every trigger
            self.tx_queue.ban(invalid)
        frame, applicable, excluded = make_tx_set_from_transactions(
            candidates, lcl_header, self.network_id)

        close_time = self._next_close_time(lcl_header)
        upgrade_steps = self._propose_upgrades(lcl_header, close_time)
        value = StellarValue(
            txSetHash=frame.get_contents_hash(),
            closeTime=close_time,
            upgrades=[u.to_bytes() for u in upgrade_steps],
            ext=_StellarValueExt(StellarValueType.STELLAR_VALUE_BASIC))
        self.externalize_value(next_seq, value, applicable)
        # manual/standalone close is a synchronous contract: the caller
        # (admin `manualclose`, tests) reads close artifacts the moment
        # this returns, so join the deferred completion tail. The
        # SCP-driven path keeps the pipeline — the next close's own
        # barrier gates it instead.
        self.ledger_manager.join_completion()

    def _propose_upgrades(self, lcl_header, close_time: int):
        """Vote upgrades against current ledger state (the Soroban
        config votes read CONFIG_SETTING entries)."""
        from ..ledger.ledger_txn import LedgerTxn
        with LedgerTxn(self.ledger_manager.root) as ltx_read:
            return self.upgrades.create_upgrades_for(
                lcl_header, close_time, ltx=ltx_read)

    def externalize_value(self, ledger_seq: int, value: StellarValue,
                          tx_set) -> None:
        """Apply an agreed value (reference: Herder::valueExternalized
        :380 → LedgerManager::valueExternalized)."""
        lcd = LedgerCloseData(ledger_seq, tx_set, value)
        kwargs = {}
        if self._verify is not None:
            kwargs["verify"] = self._verify
        self.ledger_manager.close_ledger(lcd, **kwargs)
        self._ledger_closed(tx_set)

    def _ledger_closed(self, tx_set) -> None:
        """Queue maintenance after close (reference:
        TransactionQueue::removeApplied + shift, called from
        HerderImpl::updateTransactionQueue)."""
        self._record_tx_e2e(tx_set)
        self.tx_queue.remove_applied(tx_set.txs)
        self.tx_queue.shift()
        if self.ledger_closed_cb is not None:
            self.ledger_closed_cb(
                self.ledger_manager.get_last_closed_ledger_num())

    # how long a first-seen stamp may outlive its tx before the prune
    # sweep drops it (banned / evicted txs never externalize)
    TX_E2E_STAMP_TTL_SECONDS = 300.0
    _TX_E2E_PRUNE_THRESHOLD = 10_000

    def _record_tx_e2e(self, tx_set) -> None:
        """Close the submit→externalize latency loop for every tx in
        the just-applied set: one `ledger.transaction.e2e` timer sample
        plus (when tracing) the async-track end event."""
        now = time.perf_counter()
        if self.propagation is not None and len(self.propagation):
            # propagation stamps are independent of the e2e submit
            # times (clearmetrics may have dropped those mid-flood);
            # update-only, so nodes that never saw the flood (catchup
            # replay) record nothing
            for tx in tx_set.txs:
                self.propagation.on_externalized(tx.full_hash(), now)
        if not self._tx_submit_times:
            return
        seq = self.ledger_manager.get_last_closed_ledger_num()
        rec = None
        if tracing.ENABLED:
            rec = self.perf.tracer
            if rec is not None and not rec.active:
                rec = None
        for tx in tx_set.txs:
            t0 = self._tx_submit_times.pop(tx.full_hash(), None)
            if t0 is None:
                continue
            if self.tx_e2e_timer is not None:
                self.tx_e2e_timer.update(now - t0)
            if rec is not None:
                rec.async_end("tx.e2e", tx.full_hash().hex()[:16],
                              {"seq": seq})
        if len(self._tx_submit_times) > self._TX_E2E_PRUNE_THRESHOLD:
            cutoff = now - self.TX_E2E_STAMP_TTL_SECONDS
            for h in [h for h, t in self._tx_submit_times.items()
                      if t < cutoff]:
                del self._tx_submit_times[h]

    # ------------------------------------------------- SCP-driven consensus --
    # reference: HerderImpl binds SCP↔overlay↔ledger; the methods below are
    # that binding. The standalone manual-close path above bypasses them.

    def bootstrap(self) -> None:
        """FORCE_SCP startup: start proposing on the next slot
        (reference: HerderImpl::bootstrap :814-822)."""
        assert self.scp is not None
        self.state = HerderState.HERDER_TRACKING_NETWORK_STATE
        if self._tracks_network():
            self._arm_tracking_timer()
        self._arm_trigger_timer(0.0)

    def emit_envelope(self, envelope) -> None:
        if tracing.ENABLED:
            rec = self.perf.tracer
            if rec is not None and rec.active:
                rec.instant("scp.envelope.emit", {
                    "slot": envelope.statement.slotIndex,
                    "type": envelope.statement.pledges.disc.name})
        if chaos.ENABLED:
            # Byzantine equivocation seam (ISSUE 7): an `equivocate`
            # fault makes this node sign and flood TWO conflicting SCP
            # envelopes for the same slot — the original plus a twin
            # whose values differ (Mazières 2015: exactly the
            # ill-behaved node SCP's quorum intersection must survive).
            # The equivocator's OWN SCP state machine only ever saw the
            # original; honest peers receive both.
            out = chaos.point(
                "scp.emit", envelope,
                node=self.config.node_id().hex()
                if self.config.NODE_SEED is not None else "",
                slot=envelope.statement.slotIndex)
            if out is chaos.DROP:
                # silent validator: the statement was produced (local
                # SCP state advanced) but never leaves the node
                return
            if out is chaos.EQUIVOCATE and self.broadcast_cb is not None:
                twin = self._equivocate_envelope(envelope)
                if twin is not None:
                    self.broadcast_cb(envelope)
                    self.broadcast_cb(twin)
                    return
        if self.broadcast_cb is not None:
            self.broadcast_cb(envelope)

    def _equivocate_envelope(self, envelope):
        """Forge the conflicting twin of `envelope`: same node, same
        slot, same statement type, every carried consensus value warped
        (closeTime+1, nomination values re-signed with this node's own
        key so they pass proposer-signature validation) and the
        envelope re-signed. Returns None if the statement carries no
        warpable value."""
        from ..xdr.ledger import StellarValueType
        from ..xdr.scp import SCPEnvelope, SCPStatementType
        from ..xdr.types import PublicKey
        from .scp_driver import (scp_envelope_sign_bytes,
                                 stellar_value_sign_bytes)
        sk = self.config.NODE_SEED
        if sk is None:
            return None

        def warp(raw: bytes) -> bytes:
            sv = StellarValue.from_bytes(bytes(raw))
            sv.closeTime += 1
            if sv.ext.disc == StellarValueType.STELLAR_VALUE_SIGNED:
                # a nomination value must carry a valid proposer
                # signature — the equivocator signs its forged value
                # like any proposal of its own
                lcs = sv.ext.value
                lcs.nodeID = PublicKey.ed25519(self.config.node_id())
                lcs.signature = sk.sign(stellar_value_sign_bytes(
                    self.network_id, bytes(sv.txSetHash), sv.closeTime))
            return sv.to_bytes()

        env = SCPEnvelope.from_bytes(envelope.to_bytes())
        t = env.statement.pledges.disc
        p = env.statement.pledges.value
        try:
            if t == SCPStatementType.SCP_ST_NOMINATE:
                if not p.votes and not p.accepted:
                    return None
                p.votes = [warp(v) for v in p.votes]
                p.accepted = [warp(v) for v in p.accepted]
            elif t == SCPStatementType.SCP_ST_PREPARE:
                p.ballot.value = warp(p.ballot.value)
                if p.prepared is not None:
                    p.prepared.value = warp(p.prepared.value)
                if p.preparedPrime is not None:
                    p.preparedPrime.value = warp(p.preparedPrime.value)
            elif t == SCPStatementType.SCP_ST_CONFIRM:
                p.ballot.value = warp(p.ballot.value)
            elif t == SCPStatementType.SCP_ST_EXTERNALIZE:
                p.commit.value = warp(p.commit.value)
            else:
                return None
        except Exception:
            # a value that isn't a StellarValue (foreign test driver):
            # nothing meaningful to equivocate about
            return None
        env.signature = sk.sign(scp_envelope_sign_bytes(
            self.network_id, env.statement))
        return env

    def verify_envelope(self, envelope) -> bool:
        """reference: HerderImpl::verifyEnvelope :2272 — done here, not in
        SCP. With the coalescing verify service installed, the verify
        rides the shared micro-batch queue (cache probe + write-through
        keep semantics identical to verify_sig)."""
        from .scp_driver import scp_envelope_sign_bytes
        node_raw = bytes(envelope.statement.nodeID.value)
        sig = bytes(envelope.signature)
        msg = scp_envelope_sign_bytes(self.network_id, envelope.statement)
        if self.verify_service is not None:
            return self.verify_service.verify(node_raw, sig, msg)
        from ..crypto.keys import PubKeyUtils
        return PubKeyUtils.verify_sig(node_raw, sig, msg)

    def recv_scp_envelope(self, envelope):
        """Verify, classify, and (when ready) feed SCP (reference:
        HerderImpl::recvSCPEnvelope :690)."""
        targs = None
        if tracing.ENABLED:
            targs = {"slot": envelope.statement.slotIndex,
                     "type": envelope.statement.pledges.disc.name}
        with self.perf.zone("herder.recvSCPEnvelope", targs=targs):
            return self._recv_scp_envelope(envelope)

    def _recv_scp_envelope(self, envelope):
        from .pending_envelopes import RecvState
        node_id = getattr(envelope.statement, "nodeID", None)
        if node_id is not None and self.config.NODE_SEED is not None \
                and bytes(node_id.value) == self.config.node_id():
            # reference: ENVELOPE_STATUS_SKIPPED_SELF — our own
            # statements enter SCP on the emit path, never from the
            # network. Critical after a churn restart: peers echo the
            # node's PRE-CRASH statements back, and ingesting them
            # would outrank the fresh ballot protocol's own state
            # ("moved to a bad state" on the next self-emit).
            return RecvState.ENVELOPE_STATUS_DISCARDED
        if not self.verify_envelope(envelope):
            return RecvState.ENVELOPE_STATUS_DISCARDED
        slot = envelope.statement.slotIndex
        lcl_seq = self.ledger_manager.get_last_closed_ledger_num()
        # reference: accept only slots within the validity window
        if slot <= max(0, lcl_seq -
                       self.config.MAX_SLOTS_TO_REMEMBER) or \
                slot > lcl_seq + LEDGER_VALIDITY_BRACKET:
            return RecvState.ENVELOPE_STATUS_DISCARDED
        status = self.pending_envelopes.recv_scp_envelope(envelope)
        if status == RecvState.ENVELOPE_STATUS_READY:
            self.process_scp_queue()
        return status

    def process_scp_queue(self) -> None:
        for slot in self.pending_envelopes.ready_slots():
            for env in self.pending_envelopes.pop_ready(slot):
                self.scp.receive_envelope(env)
                # after receive: a rebuild's qset lookup then sees this
                # envelope as the node's latest message
                self._update_quorum_tracker(env)

    def _update_quorum_tracker(self, env) -> None:
        """Track the transitive quorum from processed envelopes (reference:
        HerderImpl::updateTransitiveQuorum via QuorumTracker::expand, with
        full rebuild on inconsistency)."""
        if self.quorum_tracker is None:
            return
        from .pending_envelopes import _statement_qset_hash
        qh = _statement_qset_hash(env.statement)
        if qh is None:
            return
        qset = self.pending_envelopes.get_qset(qh)
        if qset is None:
            return
        node = bytes(env.statement.nodeID.value)
        if not self.quorum_tracker.expand(node, qset):
            self.quorum_tracker.rebuild(self._lookup_node_qset)

    def _lookup_node_qset(self, node_id: bytes):
        """Best-known quorum set of a node, from its latest SCP statement."""
        if self.scp is None:
            return None
        env = self.scp.get_latest_message(node_id)
        if env is None:
            return None
        from .pending_envelopes import _statement_qset_hash
        qh = _statement_qset_hash(env.statement)
        return self.pending_envelopes.get_qset(qh) if qh else None

    def recv_tx_set(self, tx_set_hash: bytes, tx_set) -> None:
        self.pending_envelopes.add_tx_set(tx_set_hash, tx_set)
        self.process_scp_queue()

    def recv_scp_quorum_set(self, qset_hash: bytes, qset) -> None:
        self.pending_envelopes.add_scp_quorum_set(qset_hash, qset)
        self.process_scp_queue()

    # ------------------------------------------------------ value plumbing --
    def make_stellar_value(self, tx_set_hash: bytes, close_time: int,
                           upgrade_steps) -> StellarValue:
        """Signed StellarValue (reference: HerderImpl::makeStellarValue)."""
        from ..xdr.ledger import LedgerCloseValueSignature
        from ..xdr.types import PublicKey
        from .scp_driver import stellar_value_sign_bytes
        sk = self.config.NODE_SEED
        sig = sk.sign(stellar_value_sign_bytes(
            self.network_id, tx_set_hash, close_time))
        return StellarValue(
            txSetHash=tx_set_hash, closeTime=close_time,
            upgrades=[u.to_bytes() for u in upgrade_steps],
            ext=_StellarValueExt(
                StellarValueType.STELLAR_VALUE_SIGNED,
                LedgerCloseValueSignature(
                    nodeID=PublicKey.ed25519(self.config.node_id()),
                    signature=sig)))

    def verify_stellar_value_signature(self, sv: StellarValue) -> bool:
        from .scp_driver import stellar_value_sign_bytes
        lcs = sv.ext.value
        pub = bytes(lcs.nodeID.value)
        sig = bytes(lcs.signature)
        msg = stellar_value_sign_bytes(self.network_id,
                                       bytes(sv.txSetHash), sv.closeTime)
        if self.verify_service is not None:
            return self.verify_service.verify(pub, sig, msg)
        from ..crypto.keys import PubKeyUtils
        return PubKeyUtils.verify_sig(pub, sig, msg)

    def applicable_for(self, tx_set_frame):
        """Prepared ApplicableTxSet for a wire frame against the LCL,
        memoized by contents hash."""
        h = tx_set_frame.get_contents_hash()
        cached = self._applicable_cache.get(h)
        lcl = self.ledger_manager.get_last_closed_ledger_header()
        if cached is not None and cached[0] == lcl.ledgerSeq:
            return cached[1]
        applicable = tx_set_frame.prepare_for_apply(lcl)
        # drop stale entries so the cache tracks only the live ledger
        for k in [k for k, (seq, _) in self._applicable_cache.items()
                  if seq < lcl.ledgerSeq]:
            del self._applicable_cache[k]
        self._applicable_cache[h] = (lcl.ledgerSeq, applicable)
        return applicable

    def is_tx_set_valid(self, tx_set_frame) -> bool:
        """Validity of a proposed txset against the LCL, memoized by
        (LCL hash, txset hash) like the reference's TxSetValidityKey
        cache (herder/HerderSCPDriver.cpp checkAndCacheTxSetValid):
        a quorum's worth of SCP envelopes all naming the same set must
        validate it once, not once per envelope."""
        h = tx_set_frame.get_contents_hash()
        lcl_hash = self.ledger_manager.get_last_closed_ledger_hash()
        key = (lcl_hash, h)
        cached = self._tx_set_valid_cache.get(key)
        if cached is not None:
            return cached
        valid = self._check_tx_set_valid(tx_set_frame)
        if len(self._tx_set_valid_cache) >= 1000:
            self._tx_set_valid_cache.clear()
        self._tx_set_valid_cache[key] = valid
        return valid

    def _check_tx_set_valid(self, tx_set_frame) -> bool:
        applicable = self.applicable_for(tx_set_frame)
        if applicable is None:
            return False
        verify = self._verify
        if self.batch_verifier is not None:
            # one device batch for the whole proposed set; per-signature
            # results seed the lookup the per-tx checkValid consumes
            # (reference collection point: txset validation,
            # herder/TxSetUtils.cpp:200 — SURVEY.md §3.2). Lazy: the
            # batch dispatches only when check_valid reaches its first
            # signature (structurally invalid sets never pay for crypto)
            # and is memoized per (txset hash, lcl) so a quorum's worth
            # of envelopes re-validating the same set verify once.
            h = tx_set_frame.get_contents_hash()
            lcl_seq = self.ledger_manager.get_last_closed_ledger_num()
            cached = self._batch_pv_cache.get(h)
            if cached is None or cached[0] != lcl_seq:
                lazy = _LazyBatchPrevalidator(self.batch_verifier,
                                              applicable, verify)
                for k in [k for k, (seq, _) in
                          self._batch_pv_cache.items() if seq < lcl_seq]:
                    del self._batch_pv_cache[k]
                cached = (lcl_seq, lazy)
                self._batch_pv_cache[h] = cached
            verify = cached[1]
        kwargs = {"verify": verify} if verify else {}
        return applicable.check_valid(self.ledger_manager.root, **kwargs)

    # ---------------------------------------------------------- triggering --
    def trigger_next_ledger_scp(self) -> None:
        """Propose the next slot's value through SCP (reference:
        HerderImpl::triggerNextLedger :1266)."""
        assert self.scp is not None
        lcl_header = self.ledger_manager.get_last_closed_ledger_header()
        slot = lcl_header.ledgerSeq + 1
        candidates, invalid = trim_invalid(
            self.tx_queue.get_transactions(), self.ledger_manager.root,
            verify=self._verify)
        if invalid:
            self.tx_queue.ban(invalid)
        frame, applicable, _ = make_tx_set_from_transactions(
            candidates, lcl_header, self.network_id)
        h = frame.get_contents_hash()
        self.pending_envelopes.add_tx_set(h, frame)
        self._tx_sets_for_slot[slot] = frame
        if tracing.ENABLED:
            rec = self.perf.tracer
            if rec is not None and rec.active:
                # the txset hop of the tx e2e pipeline: submit → queue
                # → TXSET → apply → externalize
                rec.instant("herder.txset.proposed",
                            {"slot": slot, "txs": applicable.size_tx()})
        # trim_invalid above IS a full per-tx validation pass against
        # this LCL, so seed the validity cache: our own proposal must
        # not be re-validated tx-by-tx when SCP hands it back
        # (reference: the trimmed makeFromTransactions output feeds the
        # same TxSetValidityKey cache its checkValid would)
        self._applicable_cache[h] = (lcl_header.ledgerSeq, applicable)
        self._tx_set_valid_cache[(
            self.ledger_manager.get_last_closed_ledger_hash(), h)] = True

        close_time = self._next_close_time(lcl_header)
        upgrade_steps = self._propose_upgrades(lcl_header, close_time)
        sv = self.make_stellar_value(frame.get_contents_hash(), close_time,
                                     upgrade_steps)
        prev_value = lcl_header.scpValue.to_bytes()
        self.scp.nominate(slot, sv.to_bytes(), prev_value)

    def _arm_trigger_timer(self, delay: float) -> None:
        if self._clock is None:
            return
        from ..util.timer import VirtualTimer
        if self.trigger_timer is not None:
            self.trigger_timer.cancel()
        self.trigger_timer = VirtualTimer(self._clock)
        self.trigger_timer.expires_from_now(delay)
        self.trigger_timer.async_wait(self.trigger_next_ledger_scp)

    # ------------------------------------------------------- externalizing --
    def value_externalized_from_scp(self, slot: int, value: bytes) -> None:
        """SCP agreed on `value` for `slot` (reference:
        HerderImpl::valueExternalized :380 → processExternalized)."""
        if tracing.ENABLED:
            rec = self.perf.tracer
            if rec is not None and rec.active:
                rec.instant("scp.externalize", {"slot": slot})
        sv = StellarValue.from_bytes(value)
        tx_set = self.pending_envelopes.get_tx_set(bytes(sv.txSetHash))
        if tx_set is None:
            log.error("externalized value with unknown txset for slot %d",
                      slot)
            return
        lcl_seq = self.ledger_manager.get_last_closed_ledger_num()
        if slot <= lcl_seq:
            return  # already closed (restart / catchup overlap)
        self._buffered_values[slot] = (sv, tx_set)
        self._apply_buffered()

    def _apply_buffered(self) -> None:
        self._drain_buffered()
        # a remaining gap means we can't follow the network; hand off to
        # the catchup manager (reference: CatchupManagerImpl)
        if self._buffered_values and self.catchup_manager is not None:
            lcl = self.ledger_manager.get_last_closed_ledger_num()
            if min(self._buffered_values) > lcl + 1:
                self.catchup_manager.maybe_trigger_catchup()

    def _drain_buffered(self) -> None:
        applied = 0
        while True:
            lcl = self.ledger_manager.get_last_closed_ledger_num()
            # drop stale entries (a node can land past buffered slots,
            # e.g. after a catchup clamped to the archive's tip)
            for slot in [s for s in self._buffered_values if s <= lcl]:
                del self._buffered_values[slot]
                self._tx_sets_for_slot.pop(slot, None)
            next_seq = lcl + 1
            buffered = self._buffered_values.pop(next_seq, None)
            if buffered is None:
                break
            sv, tx_set = buffered
            applicable = self.applicable_for(tx_set)
            self.externalize_value(next_seq, sv, applicable)
            applied += 1
            self._persist_scp_history(next_seq)
            self._tx_sets_for_slot.pop(next_seq, None)
            self.pending_envelopes.slot_closed(
                next_seq, self.config.MAX_SLOTS_TO_REMEMBER)
            if self.scp is not None:
                self.scp.purge_slots(
                    max(1, next_seq + 1 -
                        self.config.MAX_SLOTS_TO_REMEMBER))
                if self.config.NODE_IS_VALIDATOR and \
                        not self.config.MANUAL_CLOSE:
                    self._arm_trigger_timer(
                        self.config.EXPECTED_LEDGER_CLOSE_TIME)
        if applied:
            self.state = HerderState.HERDER_TRACKING_NETWORK_STATE
            if self._tracks_network():
                self._arm_tracking_timer()

    # --------------------------------------------------- sync state machine --
    def _tracks_network(self) -> bool:
        """Whether the consensus-stuck watchdog applies: only when
        following a live network, not standalone/manual-close."""
        return self.scp is not None and not self.config.MANUAL_CLOSE \
            and not self.config.RUN_STANDALONE
    def _arm_tracking_timer(self, delay: float =
                            CONSENSUS_STUCK_TIMEOUT_SECONDS) -> None:
        """Consensus-stuck watchdog (reference: herder/readme.md:23-40,
        trackingConsensusTimer): no externalize within the timeout drops
        us to SYNCING and starts periodic recovery."""
        if self._clock is None:
            return
        from ..util.timer import VirtualTimer
        if self._tracking_timer is not None:
            self._tracking_timer.cancel()
        self._tracking_timer = VirtualTimer(self._clock)
        self._tracking_timer.expires_from_now(delay)
        self._tracking_timer.async_wait(self._lost_sync)

    def _lost_sync(self) -> None:
        """reference: HerderImpl::lostSync :181 + outOfSyncRecovery
        :432 — ask peers for SCP state and keep retrying."""
        self.state = HerderState.HERDER_SYNCING_STATE
        log.warning("lost consensus sync; starting recovery")
        if self.out_of_sync_cb is not None:
            self.out_of_sync_cb()
        if self.catchup_manager is not None and self._buffered_values:
            self.catchup_manager.maybe_trigger_catchup()
        self._arm_tracking_timer(OUT_OF_SYNC_RECOVERY_TIMER_SECONDS)

    def _persist_scp_history(self, slot: int) -> None:
        """Store the slot's externalizing envelopes + quorum sets
        (reference: herder/HerderPersistence — scphistory/scpquorums
        tables, republished in checkpoint scp files)."""
        db = self.ledger_manager.db
        if db is None or self.scp is None:
            return
        from ..scp import local_node as ln
        for env in self.scp.get_externalizing_state(slot):
            db.execute(
                "INSERT INTO scphistory (nodeid, ledgerseq, envelope) "
                "VALUES (?,?,?)",
                (ln.node_key(env.statement.nodeID), slot, env.to_bytes()))
        qset = self.scp.local_node.qset
        db.execute(
            "INSERT OR REPLACE INTO scpquorums "
            "(qsethash, lastledgerseq, qset) VALUES (?,?,?)",
            (ln.qset_hash(qset), slot, qset.to_bytes()))

    def reset_observability(self) -> None:
        """`clearmetrics` hook: drop the hash-keyed stamp dicts (tx
        e2e submit times, slot timelines) so bench legs sharing one
        process measure each window from a clean slate. The herder
        owns this invariant — remote callers must not reach into the
        stamp bookkeeping directly."""
        self._tx_submit_times.clear()
        self.slot_timelines.clear()

    def shutdown(self) -> None:
        if self.trigger_timer is not None:
            self.trigger_timer.cancel()
            self.trigger_timer = None
        if self._tracking_timer is not None:
            self._tracking_timer.cancel()
            self._tracking_timer = None
        if self._flood_timer is not None:
            self._flood_timer.cancel()
            self._flood_timer = None
        if self.scp_driver is not None:
            # pending ballot timers must not fire into a dead app (the
            # chaos crash path shuts nodes down mid-consensus)
            self.scp_driver.cancel_all_timers()
        if self.verify_service is not None:
            # cancel the deadline timer and drop pending verifies: a
            # killed node loses in-flight work, and sync callers always
            # resolved their futures before returning
            self.verify_service.abandon()

    # ----------------------------------------------------------- inspection --
    def get_state(self) -> HerderState:
        return self.state

    def quorum_json(self, analyze: bool = False) -> dict:
        if self.scp is None:
            return {"node": "none", "qset": {}}
        from ..crypto.strkey import StrKey
        out = {
            "node": StrKey.encode_ed25519_public(self.config.node_id()),
            "qset": _qset_json(self.scp.local_node.qset),
        }
        if self.quorum_tracker is not None:
            out["transitive"] = self.quorum_tracker.transitive_json()
            if analyze and self.config.QUORUM_INTERSECTION_CHECKER:
                out["transitive"]["intersection"] = \
                    self.check_quorum_intersection()
        return out

    def check_quorum_intersection(self, max_calls: int = 200_000) -> dict:
        """Run the branch-and-bound intersection checker over the
        transitive quorum map (reference:
        HerderImpl::checkAndMaybeReanalyzeQuorumMap →
        QuorumIntersectionChecker::create/run).  The default call bound
        keeps the admin route's worst case to a few seconds — this runs
        on the request path, so an adversarially-shaped quorum map must
        hit the bound and report "interrupted" rather than stall the
        node (the reference offloads to a thread; here the org-collapse
        + orbit reductions do the heavy lifting and the bound is the
        backstop)."""
        from ..crypto.strkey import StrKey
        from .quorum_intersection import (QICInterrupted,
                                          QuorumIntersectionChecker)
        qmap = {nid: info.qset
                for nid, info in self.quorum_tracker.quorum_map.items()
                if info.qset is not None}
        # call bound AND wall-clock budget: the route must answer in
        # bounded time no matter how the map is shaped
        checker = QuorumIntersectionChecker(qmap, max_calls=max_calls,
                                            max_seconds=5.0)
        try:
            ok = checker.network_enjoys_quorum_intersection()
        except QICInterrupted:
            return {"intersection": None, "status": "interrupted",
                    "node_count": len(qmap), "calls": checker.calls}
        out = {"intersection": ok, "node_count": len(qmap),
               "calls": checker.calls,
               "last_check_ledger":
                   self.ledger_manager.get_last_closed_ledger_num()}
        if not ok and checker.potential_split is not None:
            a, b = checker.potential_split
            out["potential_split"] = [
                sorted(StrKey.encode_ed25519_public(n) for n in a),
                sorted(StrKey.encode_ed25519_public(n) for n in b)]
        return out


class _LazyBatchPrevalidator:
    """Per-txset lazy device batch: dispatches the batch verify the first
    time a signature is actually checked, then serves per-signature
    lookups; misses fall back to the sync path (exact semantics)."""

    def __init__(self, batch_verifier, applicable, fallback):
        from ..tx.signature_checker import default_verify
        self._batch_verifier = batch_verifier
        self._applicable = applicable
        self._fallback = fallback or default_verify
        self._pv = None

    def __call__(self, pub: bytes, sig: bytes, msg: bytes) -> bool:
        if self._pv is None:
            from ..crypto.keys import probe_verify_cache, seed_verify_cache
            from ..tx.signature_checker import (PrevalidatedVerifier,
                                                collect_signature_tuples)
            pv = PrevalidatedVerifier(fallback=self._fallback)
            # envelope signatures only: check_valid never verifies auth
            # entries (those are consumed by catchup's apply-time batch)
            tuples = collect_signature_tuples(self._applicable.txs)
            # the verify cache already holds every signature this node
            # admitted through the live path (flood admission / HTTP
            # submit write through it), so only the cache MISSES ride
            # the device batch — a fully-admitted txset dispatches
            # nothing
            cached, missing = [], []
            for t in tuples:
                hit = probe_verify_cache(*t)
                (missing if hit is None else cached).append(
                    (t, hit))
            if cached:
                pv.add_results([t for t, _ in cached],
                               [ok for _, ok in cached])
            if missing:
                miss_tuples = [t for t, _ in missing]
                try:
                    results = self._batch_verifier.verify_tuples(
                        miss_tuples)
                    pv.add_results(miss_tuples, results)
                    # write-through (ISSUE 4 satellite): apply-time
                    # re-verification of the externalized set hits the
                    # cache instead of re-verifying natively
                    for (p, s, m), ok in zip(miss_tuples, results):
                        seed_verify_cache(p, s, m, ok)
                except Exception:
                    # device verifier down: accept/reject semantics are
                    # identical on the native path, so validation
                    # continues per-signature through the fallback
                    log.warning("batch verifier failed; falling back to "
                                "native per-signature verify",
                                exc_info=True)
            self._pv = pv
            self._applicable = None   # drop the reference once consumed
        return self._pv(pub, sig, msg)


def _qset_json(qset) -> dict:
    from ..crypto.strkey import StrKey
    return {
        "t": qset.threshold,
        "v": [StrKey.encode_ed25519_public(bytes(v.value))
              for v in qset.validators],
        "i": [_qset_json(s) for s in qset.innerSets],
    }
