"""Herder — drives ledger close from transaction submission.

Reference: src/herder/HerderImpl.{h,cpp}. This class owns the
TransactionQueue and the Upgrades table and turns queue contents into tx
sets (`triggerNextLedger`, HerderImpl.cpp:1266) and externalized values
into `LedgerManager::closeLedger` calls (`valueExternalized` :380).

In RUN_STANDALONE/MANUAL_CLOSE mode (milestone M1, SURVEY.md §7 step 4)
there is no SCP: `trigger_next_ledger` externalizes its own proposal
immediately, exactly like the reference's standalone manual-close path
(Herder::setInSyncAndTriggerNextLedger via the `manualclose` command).
The SCP binding (HerderSCPDriver) layers on top without changing this
pipeline.
"""

from __future__ import annotations

import time
from enum import Enum
from typing import List, Optional

from ..ledger.ledger_manager import LedgerCloseData, LedgerManager
from ..util.logging import get_logger
from ..xdr.ledger import StellarValue, StellarValueType, _StellarValueExt
from .tx_queue import AddResult, TransactionQueue
from .tx_set import make_tx_set_from_transactions
from .upgrades import Upgrades

log = get_logger("Herder")

# reference: Herder.h MAX_SCP_TIMEOUT_SECONDS etc.
MAX_TIME_SLIP_SECONDS = 60


class HerderState(Enum):
    # reference: Herder.h State
    HERDER_BOOTING_STATE = 0
    HERDER_SYNCING_STATE = 1
    HERDER_TRACKING_NETWORK_STATE = 2


class Herder:
    def __init__(self, config, ledger_manager: LedgerManager,
                 metrics=None, verify=None):
        self.config = config
        self.ledger_manager = ledger_manager
        self.network_id = config.network_id()
        self.upgrades = Upgrades(
            current_protocol_version=config.LEDGER_PROTOCOL_VERSION)
        self.tx_queue = TransactionQueue(
            pending_depth=config.TRANSACTION_QUEUE_PENDING_DEPTH,
            ban_depth=config.TRANSACTION_QUEUE_BAN_DEPTH,
            pool_ledger_multiplier=config.TRANSACTION_QUEUE_SIZE_MULTIPLIER,
            metrics=metrics)
        self.state = HerderState.HERDER_BOOTING_STATE
        self._verify = verify
        self._metrics = metrics
        self._clock = None  # set by Application
        if metrics is not None:
            self._tx_recv_meter = metrics.meter("herder", "tx", "received")
            self._tx_accept_meter = metrics.meter("herder", "tx", "accepted")
        else:
            self._tx_recv_meter = self._tx_accept_meter = None

    # ------------------------------------------------------------ lifecycle --
    def start(self) -> None:
        """reference: Herder::start / bootstrap for FORCE_SCP."""
        self.state = HerderState.HERDER_TRACKING_NETWORK_STATE

    def set_clock(self, clock) -> None:
        self._clock = clock

    def _now(self) -> int:
        if self._clock is not None:
            return int(self._clock.system_now())
        return int(time.time())

    # ----------------------------------------------------------- submission --
    def recv_transaction(self, tx) -> AddResult:
        """Admit a tx to the pending queue (reference:
        Herder::recvTransaction :523)."""
        if self._tx_recv_meter is not None:
            self._tx_recv_meter.mark()
        max_ops = (self.config.TRANSACTION_QUEUE_SIZE_MULTIPLIER
                   * self._max_tx_set_ops())
        res = self.tx_queue.try_add(tx, self.ledger_manager.root, max_ops,
                                    verify=self._verify)
        if res == AddResult.ADD_STATUS_PENDING \
                and self._tx_accept_meter is not None:
            self._tx_accept_meter.mark()
        return res

    def _max_tx_set_ops(self) -> int:
        return self.ledger_manager.get_last_closed_ledger_header().maxTxSetSize

    # -------------------------------------------------------------- closing --
    def trigger_next_ledger(self) -> None:
        """Build a proposal from the queue (reference:
        Herder::triggerNextLedger :1266). Standalone mode externalizes it
        directly; under SCP this is where nomination starts."""
        lcl_header = self.ledger_manager.get_last_closed_ledger_header()
        next_seq = lcl_header.ledgerSeq + 1
        candidates = self.tx_queue.get_transactions()
        frame, applicable, excluded = make_tx_set_from_transactions(
            candidates, lcl_header, self.network_id)

        close_time = max(self._now(), lcl_header.scpValue.closeTime + 1)
        upgrade_steps = self.upgrades.create_upgrades_for(
            lcl_header, close_time)
        value = StellarValue(
            txSetHash=frame.get_contents_hash(),
            closeTime=close_time,
            upgrades=[u.to_bytes() for u in upgrade_steps],
            ext=_StellarValueExt(StellarValueType.STELLAR_VALUE_BASIC))
        self.externalize_value(next_seq, value, applicable)

    def externalize_value(self, ledger_seq: int, value: StellarValue,
                          tx_set) -> None:
        """Apply an agreed value (reference: Herder::valueExternalized
        :380 → LedgerManager::valueExternalized)."""
        lcd = LedgerCloseData(ledger_seq, tx_set, value)
        kwargs = {}
        if self._verify is not None:
            kwargs["verify"] = self._verify
        self.ledger_manager.close_ledger(lcd, **kwargs)
        self._ledger_closed(tx_set)

    def _ledger_closed(self, tx_set) -> None:
        """Queue maintenance after close (reference:
        TransactionQueue::removeApplied + shift, called from
        HerderImpl::updateTransactionQueue)."""
        self.tx_queue.remove_applied(tx_set.txs)
        self.tx_queue.shift()

    # ----------------------------------------------------------- inspection --
    def get_state(self) -> HerderState:
        return self.state
