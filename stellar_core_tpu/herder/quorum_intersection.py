"""Quorum intersection analysis.

Reference: src/herder/QuorumIntersectionChecker.{h,cpp} — decides
whether every pair of quorums of the known network overlaps, and if not
produces a disjoint quorum pair as the counterexample. The reference
uses a tailored branch-and-bound SAT-style search; this implementation
enumerates minimal quorums by fixpoint contraction over node subsets
with the same worst-case-exponential bound, which is fine at the
network sizes the admin `quorum` endpoint analyzes.
"""

from __future__ import annotations

from itertools import combinations
from typing import Dict, List, Optional, Set, Tuple

from ..scp import local_node as ln
from ..xdr.scp import SCPQuorumSet


class QuorumIntersectionChecker:
    def __init__(self, qmap: Dict[bytes, SCPQuorumSet]):
        """qmap: node id → that node's quorum set."""
        self.qmap = qmap
        self.nodes = sorted(qmap)
        self.potential_split: Optional[Tuple[Set[bytes], Set[bytes]]] = None

    def _is_quorum(self, subset: Set[bytes]) -> bool:
        """Every member's qset has a slice inside the subset."""
        if not subset:
            return False
        return all(ln.is_quorum_slice(self.qmap[n], subset)
                   for n in subset if n in self.qmap)

    def _contract(self, subset: Set[bytes]) -> Set[bytes]:
        """Largest quorum contained in subset (fixpoint removal of nodes
        whose slice requirement fails)."""
        cur = set(subset)
        while True:
            keep = {n for n in cur
                    if n in self.qmap and
                    ln.is_quorum_slice(self.qmap[n], cur)}
            if keep == cur:
                return cur
            cur = keep

    def network_enjoys_quorum_intersection(self) -> bool:
        """True iff all quorums pairwise intersect (reference:
        networkEnjoysQuorumIntersection)."""
        whole = self._contract(set(self.nodes))
        if not whole:
            return True  # no quorums at all
        # search complements: a split exists iff some quorum's
        # complement also contains a quorum
        minimal = self._minimal_quorums(whole)
        for q in minimal:
            rest = whole - q
            other = self._contract(rest)
            if other and self._is_quorum(other):
                self.potential_split = (q, other)
                return False
        return True

    def _minimal_quorums(self, universe: Set[bytes]) -> List[Set[bytes]]:
        """All minimal quorums within the universe (pruned subset
        enumeration, smallest first)."""
        found: List[Set[bytes]] = []
        nodes = sorted(universe)
        if len(nodes) > 20:  # enumeration guard; reference B&B has the
            # same exponential worst case, just a better constant
            nodes = nodes[:20]
        for size in range(1, len(nodes) + 1):
            for combo in combinations(nodes, size):
                s = set(combo)
                if any(m <= s for m in found):
                    continue
                if self._is_quorum(s):
                    found.append(s)
        return found
