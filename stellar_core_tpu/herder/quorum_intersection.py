"""Quorum intersection analysis.

Reference: src/herder/QuorumIntersectionChecker.{h,cpp} — decides
whether every pair of quorums of the known network overlaps, and if not
produces a disjoint quorum pair as the counterexample.

Same algorithm family as the reference's MinQuorumEnumerator
(QuorumIntersectionCheckerImpl.cpp:60-260): a branch-and-bound search
over (committed, remaining) node splits restricted to one strongly
connected component of the dependency graph, with the reference's early
exits —

  1. |committed| > |SCC|/2: other branches will find the min-quorum
     inside the complement instead;
  2. the perimeter holds no quorum extending `committed`;
  3. `committed` contracts to a quorum: terminal — if minimal, check
     its SCC-complement for a disjoint quorum.

Differences from the reference (deliberate): node sets are Python int
bitmasks (arbitrary-width, popcount via int.bit_count) instead of a
custom BitSet, and the split-node heuristic (max in-degree within the
remaining perimeter) breaks ties deterministically instead of by
coin-flip, so analyses are reproducible across runs.

Interruptibility matches the reference: set `interrupt_flag` from any
thread (or pass max_calls) and the search raises QICInterrupted.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..scp import local_node as ln
from ..xdr.scp import SCPQuorumSet
from ..xdr.types import PublicKey


class QICInterrupted(Exception):
    """Search interrupted (reference: InterruptedException)."""


class _QBitSet:
    """One quorum set compiled to index space: threshold over a
    validator mask + inner sets."""

    __slots__ = ("threshold", "vmask", "inner")

    def __init__(self, threshold: int, vmask: int,
                 inner: List["_QBitSet"]):
        self.threshold = threshold
        self.vmask = vmask
        self.inner = inner

    def satisfied_by(self, mask: int) -> bool:
        need = self.threshold - (self.vmask & mask).bit_count()
        if need <= 0:
            return True
        for q in self.inner:
            if q.satisfied_by(mask):
                need -= 1
                if need <= 0:
                    return True
        return False

    def successors(self) -> int:
        m = self.vmask
        for q in self.inner:
            m |= q.successors()
        return m


def _collapse_organizations(qmap: Dict[bytes, SCPQuorumSet]):
    """Organization-level reduction (the fbas-analysis 'merge by org'
    preprocessing; the reference reaches the same scale through C++
    constants — BitSet + a ~10^9-call budget — which a Python checker
    replaces with this exact reduction):

    a group M of k validators is collapsible to one org-node when
      - all members publish the same quorum set, and
      - every appearance of any member, in every distinct qset, is a
        leaf subtree whose validators are exactly M (same threshold t
        everywhere, no inner sets), and
      - 2t > k (two disjoint sets can never both activate the org).

    Then disjoint quorums exist in the full graph iff they exist in the
    collapsed graph (org active in a quorum ⟺ ≥t members present; 2t>k
    forces each org onto one side).  Crucially the collapsed quorums are
    SMALL relative to the collapsed SCC, so the half-SCC bound prunes.
    Returns (new_qmap, expansion) where expansion maps synthetic org ids
    to (members_tuple, t); empty expansion = nothing collapsed."""
    from ..crypto.sha import sha256

    uniq: Dict[bytes, SCPQuorumSet] = {}
    for qs in qmap.values():
        uniq.setdefault(qs.to_bytes(), qs)

    # every appearance context of each node: "leaf:<members,thr>" or a
    # disqualifying marker
    appearances: Dict[bytes, set] = {}

    def walk(qs: SCPQuorumSet):
        vkeys = tuple(sorted(ln.node_key(v) for v in qs.validators))
        is_leaf = not qs.innerSets
        for vk in vkeys:
            if is_leaf:
                appearances.setdefault(vk, set()).add(
                    ("leaf", vkeys, qs.threshold))
            else:
                appearances.setdefault(vk, set()).add(("mixed",))
        for s in qs.innerSets:
            walk(s)

    for qs in uniq.values():
        walk(qs)

    # candidate classes: group by own-qset bytes + the single leaf shape
    groups: Dict[tuple, list] = {}
    for nid, qs in qmap.items():
        ctx = appearances.get(nid, set())
        if len(ctx) != 1:
            continue
        (tag, *rest) = next(iter(ctx))
        if tag != "leaf":
            continue
        members, thr = rest
        if set(members) - set(qmap):
            continue                 # leaf mixes in unknown nodes
        groups.setdefault((members, thr, qs.to_bytes()), []).append(nid)

    collapses: Dict[tuple, tuple] = {}   # members -> (org_id, thr)
    expansion: Dict[bytes, tuple] = {}
    for (members, thr, _qb), nids in groups.items():
        k = len(members)
        if k < 2 or sorted(nids) != list(members):
            continue                 # not the whole leaf, or singleton
        if 2 * thr <= k:
            continue                 # an org two sides could share
        org_id = sha256(b"org:" + b"".join(members))
        collapses[members] = (org_id, thr)
        expansion[org_id] = (members, thr)
    if not collapses:
        return qmap, {}

    def rewrite(qs: SCPQuorumSet) -> SCPQuorumSet:
        vkeys = tuple(sorted(ln.node_key(v) for v in qs.validators))
        if not qs.innerSets and vkeys in collapses:
            org_id, _thr = collapses[vkeys]
            return SCPQuorumSet(
                threshold=1,
                validators=[PublicKey.ed25519(org_id)], innerSets=[])
        return SCPQuorumSet(
            threshold=qs.threshold,
            validators=list(qs.validators),
            innerSets=[rewrite(s) for s in qs.innerSets])

    collapsed_members = {m for ms in collapses for m in ms}
    new_qmap: Dict[bytes, SCPQuorumSet] = {}
    for nid, qs in qmap.items():
        if nid in collapsed_members:
            continue
        new_qmap[nid] = rewrite(qs)
    for members, (org_id, _thr) in collapses.items():
        new_qmap[org_id] = rewrite(qmap[members[0]])
    return new_qmap, expansion


class QuorumIntersectionChecker:
    """Drop-in API: construct with {node id bytes: SCPQuorumSet}, call
    network_enjoys_quorum_intersection(); potential_split holds the
    counterexample pair when it returns False."""

    def __init__(self, qmap: Dict[bytes, SCPQuorumSet],
                 interrupt_flag: Optional[list] = None,
                 max_calls: int = 0, max_seconds: float = 0.0,
                 _collapse: bool = True):
        self._expansion: Dict[bytes, tuple] = {}
        if _collapse and qmap:
            qmap2, expansion = _collapse_organizations(qmap)
            if expansion:
                qmap = qmap2
                self._expansion = expansion
        self.qmap = qmap
        self.nodes = sorted(qmap)
        self._idx = {n: i for i, n in enumerate(self.nodes)}
        # nodes sharing a quorum set (the pubnet norm: org members and
        # often whole tiers publish identical qsets) share ONE compiled
        # _QBitSet, letting contraction evaluate it once per pass
        self._compile_cache: Dict[bytes, _QBitSet] = {}
        self._qsets: List[Optional[_QBitSet]] = []
        for n in self.nodes:
            qs = qmap[n]
            key = qs.to_bytes()
            q = self._compile_cache.get(key)
            if q is None:
                q = self._compile_cache[key] = self._compile(qs)
            self._qsets.append(q)
        self._succ: List[int] = [
            (q.successors() if q is not None else 0) | (1 << i)
            for i, q in enumerate(self._qsets)]
        self._siblings: List[int] = self._sibling_classes()
        self.potential_split: Optional[Tuple[set, set]] = None
        # cooperative interruption: a one-element list so callers can
        # flip it from another thread; max_calls bounds the search size
        self.interrupt_flag = interrupt_flag if interrupt_flag is not None \
            else [False]
        self.max_calls = max_calls
        self.max_seconds = max_seconds
        self._deadline = 0.0
        self.calls = 0

    # ------------------------------------------------------------ compile --
    def _compile(self, qset: SCPQuorumSet) -> _QBitSet:
        vmask = 0
        for v in qset.validators:
            i = self._idx.get(ln.node_key(v))
            if i is not None:
                vmask |= 1 << i
        inner = [self._compile(q) for q in qset.innerSets]
        return _QBitSet(qset.threshold, vmask, inner)

    def _sibling_classes(self) -> List[int]:
        """For each node, the bitmask of nodes interchangeable with it:
        the transposition swapping the two nodes is verified to be an
        automorphism of the whole configuration (every distinct quorum
        set maps to itself as a structural multiset, and both nodes
        publish the same qset — transpositions compose, so the relation
        is an equivalence).  Used for sound symmetry pruning: in the
        branch that EXCLUDES a node, its unexplored siblings may be
        excluded too, since any solution using a sibling maps to one
        using the node itself, which the include-branch explores.
        (Orbit symmetry; after org collapse this typically groups the
        whole symmetric top tier.)"""
        n = len(self.nodes)
        uniq = list({id(q): q for q in self._qsets if q is not None
                     }.values())

        def canon(q: _QBitSet, bi: int, bj: int):
            """Structural key of σ(q) where σ swaps bits bi/bj
            (bi == bj == 0 → identity)."""
            vm = q.vmask
            if bi:
                t = (bj if vm & bi else 0) | (bi if vm & bj else 0)
                vm = (vm & ~(bi | bj)) | t
            return (q.threshold, vm,
                    tuple(sorted(canon(s, bi, bj) for s in q.inner)))

        ident = {id(q): canon(q, 0, 0) for q in uniq}

        def swappable(i: int, j: int) -> bool:
            if self._qsets[i] is not self._qsets[j]:
                return False
            bi, bj = 1 << i, 1 << j
            return all(canon(q, bi, bj) == ident[id(q)] for q in uniq)

        # group candidates by shared qset object, then verify pairwise
        # against a class representative (transpositions compose, so one
        # representative check suffices per class)
        by_qset: Dict[int, List[int]] = {}
        for i, q in enumerate(self._qsets):
            by_qset.setdefault(id(q), []).append(i)
        masks = [1 << i for i in range(n)]
        for members in by_qset.values():
            classes: List[List[int]] = []
            for i in members:
                for cls in classes:
                    if swappable(cls[0], i):
                        cls.append(i)
                        break
                else:
                    classes.append([i])
            for cls in classes:
                m = 0
                for i in cls:
                    m |= 1 << i
                for i in cls:
                    masks[i] = m
        return masks

    # ----------------------------------------------------------- quorum ops --
    def _is_slice_sat(self, i: int, mask: int) -> bool:
        q = self._qsets[i]
        return q is not None and q.satisfied_by(mask)

    def _contract(self, mask: int) -> int:
        """Maximal quorum inside mask (reference:
        contractToMaximalQuorum): fixpoint-drop members whose slice
        requirement fails within the set.  Nodes sharing a compiled
        qset are evaluated once per pass."""
        qsets = self._qsets
        while mask:
            keep = 0
            cache: Dict[int, bool] = {}
            m = mask
            while m:
                low = m & -m
                q = qsets[low.bit_length() - 1]
                if q is not None:
                    qid = id(q)
                    s = cache.get(qid)
                    if s is None:
                        s = cache[qid] = q.satisfied_by(mask)
                    if s:
                        keep |= low
                m ^= low
            if keep == mask:
                return mask
            mask = keep
        return 0

    def _is_minimal_quorum(self, mask: int) -> bool:
        """mask is a quorum none of whose single-node removals still
        contains a quorum (reference: isMinimalQuorum)."""
        m = mask
        while m:
            low = m & -m
            if self._contract(mask ^ low):
                return False
            m ^= low
        return True

    def _mask_to_set(self, mask: int) -> set:
        """Counterexample sets expand collapsed org-nodes back to t
        concrete members (any t suffice to activate the org)."""
        out = set()
        for i in range(len(self.nodes)):
            if not mask >> i & 1:
                continue
            nid = self.nodes[i]
            exp = self._expansion.get(nid)
            if exp is None:
                out.add(nid)
            else:
                members, t = exp
                out.update(members[:t])
        return out

    # -------------------------------------------------------------- search --
    def network_enjoys_quorum_intersection(self) -> bool:
        """True iff all quorums pairwise intersect (reference:
        networkEnjoysQuorumIntersection): split the graph into SCCs, fail
        fast if two SCCs each hold a quorum, then run the enumerator on
        the (single) quorum-bearing SCC."""
        n = len(self.nodes)
        if n == 0:
            return True
        if self.max_seconds:
            import time
            self._deadline = time.monotonic() + self.max_seconds
        sccs = self._tarjan_sccs()
        quorum_sccs = []
        for scc in sccs:
            q = self._contract(scc)
            if q:
                quorum_sccs.append((scc, q))
        if not quorum_sccs:
            return True
        if len(quorum_sccs) > 1:
            # two node-disjoint SCCs each containing a quorum: split
            self.potential_split = (
                self._mask_to_set(quorum_sccs[0][1]),
                self._mask_to_set(quorum_sccs[1][1]))
            return False
        scan_scc = quorum_sccs[0][0]
        return not self._any_min_quorum_has_disjoint_quorum(
            0, scan_scc, scan_scc)

    def _any_min_quorum_has_disjoint_quorum(self, committed: int,
                                            remaining: int,
                                            scan_scc: int) -> bool:
        """reference: MinQuorumEnumerator::anyMinQuorumHasDisjointQuorum
        (iterative deepening done by explicit recursion; the branch
        excluding the split node runs first, exactly as the reference)."""
        self.calls += 1
        if self.interrupt_flag[0] or \
                (self.max_calls and self.calls > self.max_calls):
            raise QICInterrupted(
                f"quorum intersection search interrupted after "
                f"{self.calls} calls")
        if self._deadline and not self.calls & 0x3FF:
            import time
            if time.monotonic() > self._deadline:
                raise QICInterrupted(
                    f"quorum intersection search hit the "
                    f"{self.max_seconds}s time budget "
                    f"({self.calls} calls)")

        # early exit 1: committed beyond half the SCC
        if committed.bit_count() > scan_scc.bit_count() // 2:
            return False

        # early exit 3: committed contracts to a quorum — terminal
        committed_quorum = self._contract(committed)
        if committed_quorum:
            if self._is_minimal_quorum(committed_quorum):
                disj = self._contract(scan_scc & ~committed_quorum)
                if disj:
                    self.potential_split = (
                        self._mask_to_set(committed_quorum),
                        self._mask_to_set(disj))
                    return True
            return False

        # early exit 2: no quorum in the perimeter extends committed
        perimeter = committed | remaining
        extension = self._contract(perimeter)
        if not extension or (committed & ~extension):
            return False

        if not remaining:
            return False

        split = self._pick_split_node(remaining)
        # symmetry pruning: excluding `split` also excludes its
        # interchangeable siblings (see _sibling_classes) — any solution
        # using a sibling is the automorphic image of one using `split`,
        # which the include-branch covers
        sibs = self._siblings[split] & remaining
        if self._any_min_quorum_has_disjoint_quorum(
                committed, remaining & ~sibs, scan_scc):
            return True
        return self._any_min_quorum_has_disjoint_quorum(
            committed | (1 << split), remaining ^ (1 << split), scan_scc)

    def _pick_split_node(self, remaining: int) -> int:
        """Max in-degree within the remaining set (reference:
        pickSplitNode), deterministic first-max tie-break."""
        indeg: Dict[int, int] = {}
        m = remaining
        while m:
            low = m & -m
            i = low.bit_length() - 1
            avail = self._succ[i] & remaining
            a = avail
            while a:
                al = a & -a
                j = al.bit_length() - 1
                indeg[j] = indeg.get(j, 0) + 1
                a ^= al
            m ^= low
        best = remaining.bit_length() - 1
        best_deg = -1
        for j in sorted(indeg):
            if indeg[j] > best_deg:
                best, best_deg = j, indeg[j]
        return best

    # ---------------------------------------------------------------- SCCs --
    def _tarjan_sccs(self) -> List[int]:
        """Tarjan's SCCs over the successor graph, as bitmasks
        (reference: TarjanSCCCalculator.cpp); iterative to survive
        pubnet-sized graphs without hitting the recursion limit."""
        n = len(self.nodes)
        index = [-1] * n
        lowlink = [0] * n
        on_stack = [False] * n
        stack: List[int] = []
        sccs: List[int] = []
        counter = [0]

        for root in range(n):
            if index[root] != -1:
                continue
            work = [(root, 0)]
            while work:
                v, pi = work[-1]
                if pi == 0:
                    index[v] = lowlink[v] = counter[0]
                    counter[0] += 1
                    stack.append(v)
                    on_stack[v] = True
                recurse = False
                succ = self._succ[v]
                # iterate successor indices starting at pi
                m = succ >> pi
                w = pi
                while m:
                    if m & 1:
                        if index[w] == -1:
                            work[-1] = (v, w + 1)
                            work.append((w, 0))
                            recurse = True
                            break
                        if on_stack[w]:
                            lowlink[v] = min(lowlink[v], index[w])
                    m >>= 1
                    w += 1
                if recurse:
                    continue
                work.pop()
                if work:
                    parent = work[-1][0]
                    lowlink[parent] = min(lowlink[parent], lowlink[v])
                if lowlink[v] == index[v]:
                    mask = 0
                    while True:
                        u = stack.pop()
                        on_stack[u] = False
                        mask |= 1 << u
                        if u == v:
                            break
                    sccs.append(mask)
        return sccs
