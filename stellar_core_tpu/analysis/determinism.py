"""Pass 1 — determinism reachability.

Nondeterminism sources are flagged when the function containing them
is *reachable from a consensus root* through the call graph — the
upgrade over the retired directory-list grep, which a wall-clock read
in a ``util/`` helper imported into ``ledger/`` provably escaped.

Roots (the functions whose output every validator must reproduce
bit-for-bit given the same inputs):

- ``LedgerManager.close_ledger`` / ``_close_ledger``  (ledger close)
- ``Slot.process_envelope``                           (SCP slot processing)
- ``TransactionFrame.apply``                          (tx apply)
- ``merge_buckets``                                   (bucket merge)

Source kinds and their severities:

- ``wallclock`` (time.time / datetime.now):  flagged when reachable.
- ``random`` (module-level random.*, os.urandom, np.random, secrets,
  uuid1/4, unseeded ``random.Random()``): flagged when reachable.
  Seeded ``random.Random(seed)`` instances are deterministic and pass.
- ``set-iter``: iteration over a set literal / ``set(...)`` /
  set-comprehension in reachable code — Python set order is
  hash-seed-dependent, so anything it feeds (hashing, XDR
  serialization, tx ordering) varies run to run. ``sorted(set(...))``
  does not match.
- ``sleep`` (time.sleep): flagged EVERYWHERE in the package, not just
  reachable code — a real sleep under a VirtualClock simulation
  blocks every simulated node at once (the old
  ``_SIM_REACHABLE_CHAOS_PATHS`` lint, strengthened from a file list
  to the whole tree). Legitimate uses (REAL_TIME idle waits,
  config-gated test knobs) carry allowlist justifications.
- ``monotonic`` (+ wallclock): flagged in *strict modules* regardless
  of reachability — ops/controller.py must replay decisions from
  sample timestamps alone (ISSUE 11), so even perf_counter is banned
  there.

Allowlist keys: ``determinism:<module>:<qualname>:<source>``.
"""

from __future__ import annotations

from typing import List

from .astgraph import Finding, PackageIndex

# consensus roots: (module suffix, qualname)
ROOTS = (
    ("ledger.ledger_manager", "LedgerManager.close_ledger"),
    ("ledger.ledger_manager", "LedgerManager._close_ledger"),
    ("scp.slot", "Slot.process_envelope"),
    ("tx.frame", "TransactionFrame.apply"),
    ("bucket.bucket", "merge_buckets"),
)

# modules whose own timing reads must come from telemetry samples or
# recorded inputs, never any clock — monotonic/perf_counter included.
# ops.controller: decisions must replay from sample `t` alone
# (ISSUE 11). The replay subsystem (ISSUE 18): a wallclock/random
# read in the recorder or the replay driver would make two replays of
# the same log legally diverge, which is the one thing it exists to
# forbid — every timestamp must come from the VirtualClock via the
# log, every random choice from recorded bytes.
STRICT_MODULES = ("ops.controller", "replay.log", "replay.recorder",
                  "replay.replayer", "replay.scenario")

_REACHABLE_KINDS = ("wallclock", "random", "set-iter")

_HINTS = {
    "wallclock": "close results must not depend on when they run — "
                 "take time from the VirtualClock / the externalized "
                 "StellarValue closeTime",
    "random": "use the seeded helpers in util/rand.py (or a "
              "random.Random(seed) instance) so every validator draws "
              "the same sequence",
    "set-iter": "set order is hash-seed-dependent; sort before "
                "iterating (sorted(...)) or use an ordered container",
    "sleep": "real sleeps block every simulated node at once — ride "
             "the VirtualClock (chaos.Delay / schedule_at) instead",
    "monotonic": "the adaptive controller must replay decisions from "
                 "sample `t` alone; no clock reads of its own",
}


def run(index: PackageIndex) -> List[Finding]:
    findings: List[Finding] = []
    root_keys = []
    for mod, qual in ROOTS:
        key = index.find_func(mod, qual)
        if key is None:
            findings.append(Finding(
                pass_name="determinism",
                key=f"determinism:root-missing:{mod}:{qual}",
                path=index.pkg_root, lineno=0,
                message=f"consensus root {mod}.{qual} not found — the "
                        "analyzer's root list drifted from the code",
                hint="update ROOTS in analysis/determinism.py"))
            continue
        root_keys.append(key)
    parents = index.reachable_from(root_keys)

    for key, fn in sorted(index.funcs.items()):
        reachable = key in parents
        strict = any(fn.module == m or fn.module.endswith("." + m)
                     for m in STRICT_MODULES)
        for occ in fn.nondet:
            flag = False
            kind = occ.kind
            if kind in _REACHABLE_KINDS and reachable:
                flag = True
            elif kind == "sleep":
                flag = True          # package-wide, allowlist the rest
            elif strict and kind in ("wallclock", "monotonic",
                                     "random"):
                flag = True
                if kind == "wallclock":
                    kind = "monotonic"  # strict-module hint applies
            if not flag:
                continue
            chain = index.chain(parents, key) if reachable else []
            findings.append(Finding(
                pass_name="determinism",
                key=f"determinism:{fn.module}:{fn.qualname}:{occ.source}",
                path=fn.path, lineno=occ.lineno,
                message=f"{occ.source} in {fn.module}.{fn.qualname}"
                        + (" (reachable from consensus root)"
                           if reachable else
                           (" (strict module)" if strict else "")),
                hint=_HINTS[kind], chain=chain))
    return _dedupe(findings)


def _dedupe(findings: List[Finding]) -> List[Finding]:
    seen = {}
    for f in findings:
        k = (f.key, f.lineno)
        if k not in seen:
            seen[k] = f
    return list(seen.values())
