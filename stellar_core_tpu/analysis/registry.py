"""Pass 3 — registry cross-checks: chaos seams, metrics, config knobs.

Three registries that historically drift silently, checked in BOTH
directions so either side going stale fails with the missing name:

- **Chaos seams.** Names fired at ``chaos.point("...")`` call sites
  (plus the ``CLOSE_CRASH_POINTS`` tuple, fired dynamically by the
  close path) vs names referenced by ``FaultSpec("...")``
  constructions in the package, tests, scripts and docs/CHAOS.md.
  A referenced-but-never-fired seam is a typo in a test — it would
  silently inject nothing — and always fails. A fired-but-never-
  referenced seam is dead instrumentation (allowlistable:
  ``seam:<name>``).
- **Metrics.** Names emitted through the MetricsRegistry (parts-style
  ``metrics.counter("a", "b")`` and ``new_*("a.b")``) vs dotted names
  documented in docs/OBSERVABILITY.md. Dynamic parts (loop variables)
  become ``*`` wildcards; doc-side ``{a,b}`` brace alternation and
  ``<placeholder>`` forms expand/normalize the same way. Emitted-but-
  undocumented is allowlistable (``metric:<name>``); documented-but-
  not-emitted always fails (the doc promises a metric nothing
  produces).
- **Config knobs.** UPPER_SNAKE ``self.X = ...`` assignments in
  ``Config.__init__`` vs backticked knob names inside markdown tables
  in docs/. Undocumented knob: allowlistable (``knob:<NAME>``);
  documented-but-nonexistent knob always fails.
"""

from __future__ import annotations

import ast
import glob
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from .astgraph import Finding, PackageIndex, _dotted

_METRIC_METHODS = {"counter", "meter", "timer", "histogram"}
_METRIC_NEW = {"new_counter", "new_meter", "new_timer", "new_histogram"}
_METRIC_RECV = re.compile(r"(^|\.)_?metrics$")
_BACKTICK_DOTTED = re.compile(r"`([a-z0-9_*{},<>-]+(?:\.[A-Za-z0-9_*{},<>-]+)+)`")
_KNOB_RE = re.compile(r"`([A-Z][A-Z0-9_]{2,})`")
_SELF_KNOB = re.compile(r"^[A-Z][A-Z0-9_]{2,}$")
_FAULTSPEC_RE = re.compile(r"FaultSpec\(\s*[\"']([a-z0-9_.*-]+)[\"']")
# real seam names are dotted (overlay.send) — the chaos engine's own
# unit tests fire synthetic dotless points ("p", "io") that are not
# registry members
_SEAM_NAME = re.compile(r"^[a-z0-9_-]+(\.[a-zA-Z0-9_*-]+)+$")
_DOC_SEAM_RE = re.compile(r"`([a-z0-9_-]+(?:\.[a-zA-Z0-9_*-]+)+)`")


def run(index: PackageIndex, repo_root: str) -> List[Finding]:
    findings: List[Finding] = []
    findings.extend(_check_seams(index, repo_root))
    findings.extend(_check_metrics(index, repo_root))
    findings.extend(_check_knobs(index, repo_root))
    return findings


# ----------------------------------------------------------------- seams --

def _check_seams(index: PackageIndex, repo_root: str) -> List[Finding]:
    fired: Dict[str, Tuple[str, int]] = {}
    for mod, tree in index.module_trees.items():
        path = index.modules[mod]
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                dotted = _dotted(node.func) or ""
                if dotted.endswith("chaos.point") or dotted == "point":
                    if node.args and isinstance(node.args[0], ast.Constant) \
                            and isinstance(node.args[0].value, str) \
                            and _SEAM_NAME.match(node.args[0].value):
                        fired.setdefault(node.args[0].value,
                                         (path, node.lineno))
            # CLOSE_CRASH_POINTS-style registries of dynamically fired
            # seam names: a module-level UPPER_SNAKE *_POINTS tuple
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and \
                            t.id.endswith("_POINTS") and \
                            isinstance(node.value, (ast.Tuple, ast.List)):
                        for elt in node.value.elts:
                            if isinstance(elt, ast.Constant) and \
                                    isinstance(elt.value, str) and \
                                    _SEAM_NAME.match(elt.value):
                                fired.setdefault(elt.value,
                                                 (path, elt.lineno))

    # strict refs (code/test FaultSpec + JSON schedules) participate in
    # BOTH directions; doc backticks are soft: they count as coverage
    # for a fired seam, but a dotted name appearing in CHAOS.md prose
    # (`chaos.ENABLED`, `time.sleep`, placeholder examples) is not
    # itself a claim that a point exists, so it never flags.
    refs: Dict[str, Tuple[str, int]] = {}
    soft_refs: Dict[str, Tuple[str, int]] = {}
    scan_files = []
    for sub in ("tests", "scripts"):
        scan_files.extend(glob.glob(os.path.join(repo_root, sub, "*.py")))
    scan_files.extend(index.modules.values())
    chaos_md = os.path.join(repo_root, "docs", "CHAOS.md")
    if os.path.isfile(chaos_md):
        scan_files.append(chaos_md)
    for path in scan_files:
        try:
            with open(path, encoding="utf-8") as fh:
                lines = fh.read().splitlines()
        except OSError:
            continue
        is_doc = path.endswith(".md")
        if is_doc:
            # docs/CHAOS.md's seam tables count as coverage —
            # `ledger.close.crash.*` covers the crash matrix
            for i, line in enumerate(lines, 1):
                for m in _DOC_SEAM_RE.finditer(line):
                    if _SEAM_NAME.match(m.group(1)):
                        soft_refs.setdefault(m.group(1), (path, i))
            continue
        # whole-text scan: FaultSpec( often breaks the line before the
        # seam-name literal, so a per-line regex misses it
        text = "\n".join(lines)
        for m in _FAULTSPEC_RE.finditer(text):
            if _SEAM_NAME.match(m.group(1)):
                refs.setdefault(m.group(1),
                                (path, text.count("\n", 0, m.start()) + 1))
        # JSON schedules: {"point": "overlay.send", ...}
        for m in re.finditer(r"[\"']point[\"']\s*:\s*"
                             r"[\"']([a-z0-9_.-]+)[\"']", text):
            if _SEAM_NAME.match(m.group(1)):
                refs.setdefault(m.group(1),
                                (path, text.count("\n", 0, m.start()) + 1))

    findings = []
    for name, (path, line) in sorted(refs.items()):
        if name in fired:
            continue
        if any(_seam_glob(name, f) for f in fired):
            continue
        findings.append(Finding(
            pass_name="registry", key=f"seamref:{name}",
            path=path, lineno=line,
            message=f"FaultSpec references seam {name!r} but no "
                    "chaos.point call site fires it",
            hint="fix the seam-name typo, or instrument the seam — a "
                 "spec naming a nonexistent point silently injects "
                 "nothing (fired seams: see analysis/registry.py)"))
    all_refs = {**soft_refs, **refs}
    for name, (path, line) in sorted(fired.items()):
        if name in all_refs or any(_seam_glob(r, name) for r in all_refs):
            continue
        findings.append(Finding(
            pass_name="registry", key=f"seam:{name}",
            path=path, lineno=line,
            message=f"chaos seam {name!r} is fired here but no test/"
                    "scenario references it",
            hint="add a FaultSpec exercising the seam (or allowlist "
                 f"'seam:{name}' with why it is covered elsewhere)"))
    return findings


def _seam_glob(pattern: str, name: str) -> bool:
    if "*" not in pattern:
        return pattern == name
    return re.fullmatch(pattern.replace(".", r"\.").replace("*", ".+"),
                        name) is not None


# --------------------------------------------------------------- metrics --

def _const_name(arg: ast.expr) -> Optional[str]:
    """Metric name from a literal, f-string or %-format expression;
    dynamic pieces become '*' (``f"overlay.demand.{k}"`` →
    ``overlay.demand.*``)."""
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value
    if isinstance(arg, ast.JoinedStr):
        out = ""
        for v in arg.values:
            if isinstance(v, ast.Constant):
                out += str(v.value)
            else:
                out += "*"
        return out
    if isinstance(arg, ast.BinOp) and isinstance(arg.op, ast.Mod) and \
            isinstance(arg.left, ast.Constant) and \
            isinstance(arg.left.value, str):
        return re.sub(r"%[sdifr]", "*", arg.left.value)
    return None


def _metric_parts(node: ast.Call) -> Optional[str]:
    """Dotted name from a parts-style or new_* metric call; dynamic
    parts become '*' wildcards."""
    dotted = _dotted(node.func) or ""
    recv, _, method = dotted.rpartition(".")
    if method in _METRIC_NEW:
        if node.args:
            return _const_name(node.args[0])
        return None
    if method in _METRIC_METHODS and recv and _METRIC_RECV.search(recv):
        if not node.args:
            return None
        parts = []
        for a in node.args:
            if isinstance(a, ast.Constant) and isinstance(a.value, str):
                parts.append(a.value)
            else:
                parts.append("*")
        return ".".join(parts)
    return None


def _norm_doc_metric(raw: str) -> List[str]:
    """Expand `{a,b}` alternation, normalize `<placeholder>` to '*'."""
    raw = re.sub(r"<[^>]+>", "*", raw)
    out = [""]
    i = 0
    while i < len(raw):
        c = raw[i]
        if c == "{":
            j = raw.index("}", i)
            alts = raw[i + 1:j].split(",")
            out = [p + a for p in out for a in alts]
            i = j + 1
        else:
            out = [p + c for p in out]
            i += 1
    return out


def _metric_match(a: str, b: str) -> bool:
    """Segment-wise match where '*' matches one or more segments on
    either side (dynamic parts can expand to dotted suffixes)."""
    pa, pb = a.split("."), b.split(".")
    if "*" not in a and "*" not in b:
        return a == b
    if len(pa) != len(pb):
        # allow a trailing-or-embedded '*' to absorb length skew
        if not ("*" in pa or "*" in pb):
            return False
    # greedy regex match both directions; a lone '*' segment spans one
    # or more segments, an embedded '*' (device*) spans within one
    def rx(parts):
        return "".join(
            (r"[^\s`]+" if p == "*" else
             re.escape(p).replace(r"\*", r"[^.\s`]*")) + (r"\." if k <
             len(parts) - 1 else "")
            for k, p in enumerate(parts))
    return re.fullmatch(rx(pa), b) is not None or \
        re.fullmatch(rx(pb), a) is not None


def _check_metrics(index: PackageIndex, repo_root: str) -> List[Finding]:
    emitted: Dict[str, Tuple[str, int]] = {}
    for mod, tree in index.module_trees.items():
        path = index.modules[mod]
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                name = _metric_parts(node)
                if name:
                    emitted.setdefault(name, (path, node.lineno))

    # strict doc claims live in metric TABLES (header row contains
    # "metric"); backticked dotted names in prose are soft coverage —
    # they satisfy the emitted→documented direction but a prose
    # mention of `bench.py` or a trace-zone name is not a claim that
    # a registry metric exists.
    obs = os.path.join(repo_root, "docs", "OBSERVABILITY.md")
    documented: Dict[str, Tuple[str, int]] = {}
    soft_doc: Dict[str, Tuple[str, int]] = {}
    if os.path.isfile(obs):
        with open(obs, encoding="utf-8") as fh:
            lines = fh.read().splitlines()
        in_metric_table = False
        for i, line in enumerate(lines, 1):
            stripped = line.lstrip()
            name_cell = ""
            if stripped.startswith("|"):
                prev = lines[i - 2].lstrip() if i >= 2 else ""
                if not prev.startswith("|"):
                    in_metric_table = "metric" in stripped.lower()
                    continue
                if in_metric_table:
                    # the metric NAME is the first cell; description
                    # cells mention related dotted identifiers freely
                    name_cell = stripped.strip("|").split("|")[0]
            else:
                in_metric_table = False
            cell_names = set(_BACKTICK_DOTTED.findall(name_cell))
            for m in _BACKTICK_DOTTED.finditer(line):
                strict = in_metric_table and m.group(1) in cell_names
                target = documented if strict else soft_doc
                for name in _norm_doc_metric(m.group(1)):
                    target.setdefault(name, (obs, i))

    findings = []
    all_doc = {**soft_doc, **documented}
    for name, (path, line) in sorted(emitted.items()):
        if any(_metric_match(name, d) for d in all_doc):
            continue
        findings.append(Finding(
            pass_name="registry", key=f"metric:{name}",
            path=path, lineno=line,
            message=f"metric {name!r} is emitted here but not "
                    "documented in docs/OBSERVABILITY.md",
            hint="add it to the metrics tables in OBSERVABILITY.md "
                 f"(or allowlist 'metric:{name}' with why not)"))
    for name, (path, line) in sorted(documented.items()):
        if any(_metric_match(name, e) for e in emitted):
            continue
        findings.append(Finding(
            pass_name="registry", key=f"metricdoc:{name}",
            path=path, lineno=line,
            message=f"docs/OBSERVABILITY.md documents metric {name!r} "
                    "but nothing emits it",
            hint="remove the stale doc row or fix the emission name — "
                 "a documented metric that never appears misleads "
                 "operators"))
    return findings


# ----------------------------------------------------------------- knobs --

def _config_knobs(index: PackageIndex) -> Dict[str, Tuple[str, int]]:
    knobs: Dict[str, Tuple[str, int]] = {}
    for mod, tree in index.module_trees.items():
        if not (mod == "main.config" or mod.endswith(".main.config")):
            continue
        path = index.modules[mod]
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef) and node.name == "Config":
                for item in node.body:
                    if isinstance(item, ast.FunctionDef) and \
                            item.name == "__init__":
                        for sub in ast.walk(item):
                            targets = []
                            if isinstance(sub, ast.Assign):
                                targets = sub.targets
                            elif isinstance(sub, ast.AnnAssign):
                                targets = [sub.target]
                            for t in targets:
                                if isinstance(t, ast.Attribute) and \
                                        isinstance(t.value, ast.Name) \
                                        and t.value.id == "self" and \
                                        _SELF_KNOB.match(t.attr):
                                    knobs.setdefault(
                                        t.attr, (path, sub.lineno))
    return knobs


def _doc_knobs(repo_root: str) -> Dict[str, Tuple[str, int]]:
    """Backticked UPPER_SNAKE names in markdown *knob tables* under
    docs/ — a table whose header row mentions "knob". Prose mentions
    and non-knob tables (chaos kinds, env vars) are not entries."""
    out: Dict[str, Tuple[str, int]] = {}
    for path in sorted(glob.glob(os.path.join(repo_root, "docs",
                                              "*.md"))):
        with open(path, encoding="utf-8") as fh:
            lines = fh.read().splitlines()
        in_knob_table = False
        for i, line in enumerate(lines, 1):
            stripped = line.lstrip()
            if not stripped.startswith("|"):
                in_knob_table = False
                continue
            prev = lines[i - 2].lstrip() if i >= 2 else ""
            if not prev.startswith("|"):
                # header row of a fresh table
                in_knob_table = "knob" in stripped.lower()
                continue
            if in_knob_table:
                # knob name = first cell; description cells mention
                # other UPPER_SNAKE identifiers (states, env vars)
                first = stripped.strip("|").split("|")[0]
                for m in _KNOB_RE.finditer(first):
                    out.setdefault(m.group(1), (path, i))
    return out


def _check_knobs(index: PackageIndex, repo_root: str) -> List[Finding]:
    knobs = _config_knobs(index)
    doc = _doc_knobs(repo_root)
    findings = []
    for name, (path, line) in sorted(knobs.items()):
        if name in doc:
            continue
        findings.append(Finding(
            pass_name="registry", key=f"knob:{name}",
            path=path, lineno=line,
            message=f"config knob {name} has no row in any docs/ "
                    "knob table",
            hint="add it to the table in docs/CONFIG.md (or allowlist "
                 f"'knob:{name}' with why it is intentionally "
                 "undocumented)"))
    for name, (path, line) in sorted(doc.items()):
        if name in knobs:
            continue
        findings.append(Finding(
            pass_name="registry", key=f"knobdoc:{name}",
            path=path, lineno=line,
            message=f"docs table references config knob {name} which "
                    "main/config.py does not define",
            hint="fix the name or drop the stale row — operators "
                 "setting it get a silent no-op"))
    return findings
