"""Allowlist: every suppression carries a one-line justification.

Format (stellar_core_tpu/analysis/ALLOWLIST, one entry per line):

    <finding-key>  # <why this is not a bug>

Blank lines and lines starting with ``#`` are comments. An entry with
no justification after ``#`` is itself a finding (silent suppressions
are not acceptable — ISSUE 15), and an entry that matched nothing in
the current run is a finding too, so the allowlist can only shrink or
stay justified, never rot.

Keys are stable (module + qualname + source / attr / name — never
line numbers), so a reformat does not invalidate entries. A trailing
``*`` in a key segment-wise matches any suffix, for families like
``determinism:util.timer:VirtualClock.crank:*``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Tuple

from .astgraph import Finding


@dataclass
class Allowlist:
    path: str
    entries: Dict[str, str]          # key -> justification


def load_allowlist(path: str) -> Allowlist:
    entries: Dict[str, str] = {}
    with open(path, encoding="utf-8") as fh:
        for raw in fh:
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            key, _, just = line.partition("#")
            entries[key.strip()] = just.strip()
    return Allowlist(path=path, entries=entries)


def _matches(entry_key: str, finding_key: str) -> bool:
    if entry_key == finding_key:
        return True
    if entry_key.endswith("*"):
        return finding_key.startswith(entry_key[:-1])
    return False


def apply_allowlist(findings: List[Finding], allow: Allowlist,
                    ) -> Tuple[List[Finding], List[Finding],
                               List[Finding]]:
    """(live findings, suppressed findings, allowlist-meta findings)."""
    live: List[Finding] = []
    suppressed: List[Finding] = []
    used: set = set()
    for f in findings:
        hit = None
        for key, just in allow.entries.items():
            if _matches(key, f.key):
                hit = (key, just)
                break
        if hit is None:
            live.append(f)
            continue
        used.add(hit[0])
        suppressed.append(f)
    meta: List[Finding] = []
    for key, just in allow.entries.items():
        if not just:
            meta.append(Finding(
                pass_name="allowlist", key=f"allowlist:unjustified:{key}",
                path=allow.path, lineno=0,
                message=f"allowlist entry {key!r} has no justification",
                hint="append '# <one-line reason>' — silent "
                     "suppressions are not acceptable"))
        elif key not in used:
            meta.append(Finding(
                pass_name="allowlist", key=f"allowlist:unused:{key}",
                path=allow.path, lineno=0,
                message=f"allowlist entry {key!r} matched no finding "
                        "in this run",
                hint="the suppressed code is gone or renamed — delete "
                     "the entry so the allowlist cannot rot"))
    return live, suppressed, meta
