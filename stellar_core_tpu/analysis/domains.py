"""Pass 2 — thread-domain propagation and cross-domain write check.

Entry points declare their domain (``# thread-domain: <name>`` on the
``def`` line, or a ``@threads.entry("<name>")`` decorator); the pass
propagates domains through the typed call graph:

- ``call`` edges: the caller's domains flow into the callee — a
  function called from both an HTTP handler and crank code runs in
  {http, crank}.
- ``post`` edges (``clock.post(cb)`` / ``VirtualTimer.async_wait`` /
  ``schedule_at``): the callback lands back on the crank loop, so it
  gets {crank} regardless of who scheduled it — this is exactly why
  routing work through post() makes it safe.
- ``spawn`` edges (``threading.Thread(target=f)``,
  ``CloseCompletionQueue.submit``): the target runs on its own worker
  thread — it gets its declared domain, or a generated
  ``thread:<name>`` domain when undeclared.

Functions never touched by propagation default to {crank} (the single
logical main thread), and crank flows onward through their calls.

The check: every attribute key (``Class.attr``) written from two or
more domains where at least one write is *unprotected* — not under a
lock-ish ``with`` (name matching lock/cond/mutex/sem) and not in
``__init__`` — is a finding. This is the PR 8 bug class (admin HTTP
commands racing the crank loop's drain swap) caught at analysis time.

Allowlist keys: ``domain:<module>:<Class.attr>``.
"""

from __future__ import annotations

from typing import Dict, List, Set

from .astgraph import CALL, POST, SPAWN, Finding, PackageIndex

CRANK = "crank"

_HINT = ("route the write through clock.post(...) so it runs on the "
         "crank loop, hold the owning lock at every write site, or "
         "allowlist with a justification if the attribute is "
         "genuinely single-writer")


def propagate(index: PackageIndex) -> Dict[str, Set[str]]:
    """Fixpoint domain sets per function key."""
    domains: Dict[str, Set[str]] = {k: set() for k in index.funcs}

    # seed: declarations + spawn targets
    for key, fn in index.funcs.items():
        if fn.declared_domain:
            domains[key].add(fn.declared_domain)
        for edge in fn.calls:
            if edge.kind == SPAWN:
                for t in edge.targets:
                    tfn = index.funcs.get(t)
                    if tfn is None:
                        continue
                    domains[t].add(tfn.declared_domain
                                   or f"thread:{tfn.name}")

    def flow() -> None:
        changed = True
        while changed:
            changed = False
            for key, fn in index.funcs.items():
                src = domains[key]
                for edge in fn.calls:
                    if edge.kind == CALL:
                        add = src
                    elif edge.kind == POST:
                        add = {CRANK}
                    else:
                        continue
                    if not add:
                        continue
                    for t in edge.targets:
                        if t in domains and not add <= domains[t]:
                            domains[t] |= add
                            changed = True

    flow()
    # untouched functions run on the main logical thread; crank then
    # flows onward through their call edges
    for key in domains:
        if not domains[key]:
            domains[key].add(CRANK)
    flow()
    return domains


def run(index: PackageIndex) -> List[Finding]:
    domains = propagate(index)

    # group attribute writes by (module, Class.attr)
    writes: Dict[tuple, list] = {}
    for key, fn in index.funcs.items():
        for w in fn.writes:
            writes.setdefault((fn.module, w.attr_key), []).append(
                (key, w))

    findings: List[Finding] = []
    for (mod, attr_key), sites in sorted(writes.items()):
        touched: Set[str] = set()
        for fkey, _w in sites:
            touched |= domains[fkey]
        if len(touched) < 2:
            continue
        unprotected = [(fkey, w) for fkey, w in sites if not w.protected]
        if not unprotected:
            continue
        fkey, w = unprotected[0]
        fn = index.funcs[fkey]
        by_site = ", ".join(
            f"{index.funcs[fk].qualname}:{ww.lineno}"
            f"[{'/'.join(sorted(domains[fk]))}"
            f"{'' if ww.protected else ' UNPROTECTED'}]"
            for fk, ww in sites)
        findings.append(Finding(
            pass_name="domains",
            key=f"domain:{mod}:{attr_key}",
            path=fn.path, lineno=w.lineno,
            message=f"{attr_key} written from domains "
                    f"{sorted(touched)} with unprotected write in "
                    f"{fn.qualname} (via {w.via}); sites: {by_site}",
            hint=_HINT))
    return findings
