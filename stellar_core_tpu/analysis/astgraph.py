"""Package index + name-based call graph for the analysis passes.

One parse of every ``.py`` under the package root builds:

- a per-module import table (aliases resolved to canonical dotted
  names, relative imports resolved against the package);
- a ``FuncInfo`` per function/method (incl. nested defs and lambdas
  handed to ``post``-like schedulers), carrying its call edges,
  nondeterminism occurrences, attribute writes, and declared thread
  domain;
- global name tables the resolver uses for CHA-style resolution:
  ``self.foo()`` binds to the enclosing class's ``foo`` when it has
  one, otherwise (and for ``obj.foo()``) to every package method named
  ``foo`` — deliberately over-approximate, because a missed edge is a
  silently-missed finding while a spurious edge costs one allowlist
  review. A stoplist of builtin-collection method names keeps the
  over-approximation from smearing the graph through ``.append`` /
  ``.get`` / ``.items``.

Edges are typed, because the thread-domain pass treats them
differently: ``call`` propagates the caller's domains, ``post``
reroutes the callback to the crank domain (that is the whole point of
``clock.post``), and ``spawn`` seeds the target with its own declared
worker domain.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

# ---------------------------------------------------------------- model --

CALL = "call"      # plain call: caller's domains flow into callee
POST = "post"      # callback scheduled onto the crank loop
SPAWN = "spawn"    # callback runs on its own worker thread

# attr-call names never resolved globally (builtin collection/IO noise);
# self.X() still resolves within the class
_GENERIC_METHODS = frozenset((
    "get", "set", "put", "add", "pop", "popleft", "append", "appendleft",
    "extend", "clear", "update", "remove", "discard", "insert", "keys",
    "values", "items", "copy", "join", "split", "rsplit", "strip",
    "read", "write", "open", "close", "encode", "decode", "wait",
    "notify", "notify_all", "acquire", "release", "start", "stop",
    "run", "send", "recv", "connect", "accept", "flush", "sort",
    "count", "index", "format", "match", "search", "group", "exists",
    "mkdir", "load", "loads", "dump", "dumps", "hexdigest", "digest",
    "info", "debug", "warning", "error", "exception", "result",
    "cancel", "done", "is_set", "setdefault", "total_seconds", "lower",
    "upper", "startswith", "endswith", "to_bytes", "from_bytes",
))

# cross-object calls resolve only when the name is this selective
_MAX_GLOBAL_CANDIDATES = 8

# receiver-method mutators: self.X.append(...) is a write to self.X
_MUTATORS = frozenset((
    "append", "appendleft", "extend", "pop", "popleft", "clear",
    "update", "add", "remove", "discard", "insert", "setdefault",
    "push", "put",
))

_LOCKISH = re.compile(r"(lock|cond|mutex|sem)", re.IGNORECASE)
_DOMAIN_COMMENT = re.compile(r"#\s*thread-domain:\s*([A-Za-z0-9_-]+)")


@dataclass
class Occurrence:
    """One nondeterminism source occurrence inside a function body."""
    kind: str        # wallclock | monotonic | sleep | random | set-iter
    source: str      # canonical dotted name, e.g. time.time
    lineno: int


@dataclass
class AttrWrite:
    attr_key: str    # "Class.attr"
    lineno: int
    protected: bool  # lexically under a lock-ish `with`, or in __init__
    via: str         # assign | augassign | subscript | mutator:<name>


@dataclass
class CallEdge:
    kind: str                 # CALL | POST | SPAWN
    targets: Set[str]         # resolved FuncInfo keys
    text: str                 # source-ish callee text for evidence
    lineno: int


@dataclass
class FuncInfo:
    key: str                  # "module:qualname" (module pkg-relative)
    module: str               # pkg-relative dotted module, e.g. util.timer
    qualname: str             # "Class.method" / "func" / "outer.inner"
    name: str
    class_name: Optional[str]
    path: str
    lineno: int
    declared_domain: Optional[str] = None
    calls: List[CallEdge] = field(default_factory=list)
    nondet: List[Occurrence] = field(default_factory=list)
    writes: List[AttrWrite] = field(default_factory=list)


@dataclass
class Finding:
    pass_name: str   # determinism | domains | registry | allowlist
    key: str         # stable allowlist key
    path: str
    lineno: int
    message: str
    hint: str
    chain: List[str] = field(default_factory=list)

    def to_json(self) -> dict:
        return {"pass": self.pass_name, "key": self.key,
                "path": self.path, "line": self.lineno,
                "message": self.message, "hint": self.hint,
                "chain": self.chain}

    def render(self) -> str:
        loc = f"{self.path}:{self.lineno}"
        out = f"[{self.pass_name}] {loc}: {self.message}\n    hint: {self.hint}"
        if self.chain:
            out += "\n    via:  " + " -> ".join(self.chain)
        return out


class PackageIndex:
    def __init__(self, pkg_root: str, pkg_name: str):
        self.pkg_root = pkg_root
        self.pkg_name = pkg_name
        self.modules: Dict[str, str] = {}            # rel module -> path
        self.module_trees: Dict[str, ast.Module] = {}
        self.module_sources: Dict[str, List[str]] = {}
        self.funcs: Dict[str, FuncInfo] = {}
        self.funcs_by_name: Dict[str, Set[str]] = {}
        self.class_methods: Dict[Tuple[str, str], Set[str]] = {}
        self.classes: Dict[str, Set[str]] = {}       # class name -> modules

    # -- lookups used by the passes -------------------------------------
    def find_func(self, module_suffix: str, qualname: str) -> Optional[str]:
        for key, fn in self.funcs.items():
            if fn.qualname == qualname and (
                    fn.module == module_suffix
                    or fn.module.endswith("." + module_suffix)):
                return key
        return None

    def reachable_from(self, roots: List[str],
                       kinds: Tuple[str, ...] = (CALL, POST, SPAWN),
                       ) -> Dict[str, Optional[str]]:
        """BFS over typed edges; returns {key: parent_key} for the
        evidence chain (roots map to None)."""
        parents: Dict[str, Optional[str]] = {}
        frontier = []
        for r in roots:
            if r in self.funcs and r not in parents:
                parents[r] = None
                frontier.append(r)
        while frontier:
            cur = frontier.pop()
            for edge in self.funcs[cur].calls:
                if edge.kind not in kinds:
                    continue
                for t in edge.targets:
                    if t in self.funcs and t not in parents:
                        parents[t] = cur
                        frontier.append(t)
        return parents

    def chain(self, parents: Dict[str, Optional[str]], key: str,
              ) -> List[str]:
        out = []
        cur: Optional[str] = key
        seen = set()
        while cur is not None and cur not in seen:
            seen.add(cur)
            fn = self.funcs[cur]
            out.append(f"{fn.module}.{fn.qualname}")
            cur = parents.get(cur)
        return list(reversed(out))


# ------------------------------------------------------------- building --

def build_index(pkg_root: str) -> PackageIndex:
    pkg_name = os.path.basename(os.path.normpath(pkg_root))
    index = PackageIndex(pkg_root, pkg_name)
    for base, _dirs, files in os.walk(pkg_root):
        _dirs[:] = [d for d in _dirs if d != "__pycache__"]
        for f in sorted(files):
            if not f.endswith(".py"):
                continue
            path = os.path.join(base, f)
            rel = os.path.relpath(path, pkg_root)
            mod = rel[:-3].replace(os.sep, ".")
            if mod.endswith(".__init__"):
                mod = mod[: -len(".__init__")] or "__init__"
            with open(path, encoding="utf-8") as fh:
                src = fh.read()
            try:
                tree = ast.parse(src, filename=path)
            except SyntaxError as e:
                raise RuntimeError(f"analysis parse failure {path}: {e}")
            index.modules[mod] = path
            index.module_trees[mod] = tree
            index.module_sources[mod] = src.splitlines()
    for mod in index.modules:
        _index_module(index, mod)
    return index


def _index_module(index: PackageIndex, mod: str) -> None:
    tree = index.module_trees[mod]
    path = index.modules[mod]
    imports = _import_table(index, mod, tree)
    # first sweep: register every def so the resolver sees the whole
    # module before edges are extracted
    visitor = _ModuleVisitor(index, mod, path, imports)
    visitor.register(tree)
    visitor.extract(tree)


def _import_table(index: PackageIndex, mod: str,
                  tree: ast.Module) -> Dict[str, str]:
    """alias -> canonical dotted name (module or module.symbol)."""
    table: Dict[str, str] = {}
    pkg_parts = mod.split(".")[:-1] if mod != "__init__" else []
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname:
                    table[a.asname] = a.name
                else:
                    head = a.name.split(".")[0]
                    table[head] = head
        elif isinstance(node, ast.ImportFrom):
            if node.level:
                base_parts = pkg_parts[: len(pkg_parts) - (node.level - 1)] \
                    if node.level > 1 else list(pkg_parts)
                base = ".".join(base_parts)
                src = base + ("." + node.module if node.module else "")
                src = src.strip(".")
            else:
                src = node.module or ""
            for a in node.names:
                if a.name == "*":
                    continue
                table[a.asname or a.name] = (src + "." + a.name) \
                    if src else a.name
    return table


class _ModuleVisitor:
    def __init__(self, index: PackageIndex, mod: str, path: str,
                 imports: Dict[str, str]):
        self.index = index
        self.mod = mod
        self.path = path
        self.imports = imports
        self.src_lines = index.module_sources[mod]
        self.local_funcs: Dict[str, str] = {}   # plain name -> key
        self.local_classes: Set[str] = set()

    # -- pass A: register defs ------------------------------------------
    def register(self, tree: ast.Module) -> None:
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._register_func(node, qual=node.name, cls=None)
            elif isinstance(node, ast.ClassDef):
                self.local_classes.add(node.name)
                self.index.classes.setdefault(node.name, set()).add(self.mod)
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        self._register_func(
                            item, qual=f"{node.name}.{item.name}",
                            cls=node.name)

    def _register_func(self, node, qual: str, cls: Optional[str]) -> str:
        key = f"{self.mod}:{qual}"
        fn = FuncInfo(key=key, module=self.mod, qualname=qual,
                      name=node.name if hasattr(node, "name")
                      else qual.rsplit(".", 1)[-1],
                      class_name=cls, path=self.path, lineno=node.lineno,
                      declared_domain=self._declared_domain(node))
        self.index.funcs[key] = fn
        self.index.funcs_by_name.setdefault(fn.name, set()).add(key)
        if cls:
            self.index.class_methods.setdefault(
                (cls, fn.name), set()).add(key)
        if cls is None:
            self.local_funcs[fn.name] = key
        return key

    def _declared_domain(self, node) -> Optional[str]:
        # decorator form: @threads.entry("http") / @entry("http")
        for dec in getattr(node, "decorator_list", ()):
            if isinstance(dec, ast.Call) and dec.args:
                name = _dotted(dec.func) or ""
                if name.split(".")[-1] in ("entry", "domain") and \
                        isinstance(dec.args[0], ast.Constant) and \
                        isinstance(dec.args[0].value, str):
                    return dec.args[0].value
        # structured comment on the def line or the line above
        for ln in (node.lineno, node.lineno - 1):
            if 1 <= ln <= len(self.src_lines):
                m = _DOMAIN_COMMENT.search(self.src_lines[ln - 1])
                if m:
                    return m.group(1)
        return None

    # -- pass B: extract edges/occurrences/writes -----------------------
    def extract(self, tree: ast.Module) -> None:
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._extract_func(node, qual=node.name, cls=None)
            elif isinstance(node, ast.ClassDef):
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        self._extract_func(
                            item, qual=f"{node.name}.{item.name}",
                            cls=node.name)

    def _extract_func(self, node, qual: str, cls: Optional[str]) -> None:
        key = f"{self.mod}:{qual}"
        fn = self.index.funcs.get(key)
        if fn is None:
            return
        body = _BodyVisitor(self, fn, cls)
        for stmt in node.body:
            body.visit(stmt)

    # -- resolution ------------------------------------------------------
    def resolve_callee(self, node: ast.expr,
                       cls: Optional[str]) -> Tuple[Set[str], str]:
        """Resolve a callee expression to FuncInfo keys + display text."""
        text = _dotted(node) or "<dynamic>"
        targets: Set[str] = set()
        if isinstance(node, ast.Name):
            name = node.id
            if name in self.local_funcs:
                targets.add(self.local_funcs[name])
            elif name in self.local_classes:
                targets |= self.index.class_methods.get(
                    (name, "__init__"), set())
            elif name in self.imports:
                targets |= self._resolve_canonical(self.imports[name])
        elif isinstance(node, ast.Attribute):
            attr = node.attr
            canon = self._canonical(text)
            if canon:
                resolved = self._resolve_canonical(canon)
                if resolved:
                    return resolved, text
            recv_is_self = isinstance(node.value, ast.Name) \
                and node.value.id == "self"
            if recv_is_self and cls:
                hit = self.index.class_methods.get((cls, attr), set())
                if hit:
                    return hit, text
            if attr not in _GENERIC_METHODS:
                cands: Set[str] = set()
                for k in self.index.funcs_by_name.get(attr, ()):  # methods+funcs
                    if self.index.funcs[k].class_name is not None \
                            or recv_is_self:
                        cands.add(k)
                if cands and (recv_is_self
                              or len(cands) <= _MAX_GLOBAL_CANDIDATES):
                    targets |= cands
        return targets, text

    def _canonical(self, dotted: Optional[str]) -> Optional[str]:
        """Rewrite the leading alias of a dotted name via the import
        table: `_time.sleep` -> `time.sleep`, `chaos.point` ->
        `<pkg>.util.chaos.point`."""
        if not dotted:
            return None
        head, _, rest = dotted.partition(".")
        if head == "self":
            return None
        base = self.imports.get(head)
        if base is None:
            return None
        return base + ("." + rest if rest else "")

    def _resolve_canonical(self, canon: str) -> Set[str]:
        """Canonical dotted name -> package FuncInfo keys (if it names
        a function/method of an in-package module). Relative imports
        resolve pkg-relative (module names are keyed that way), so both
        `pkg.util.foo.bar` and `util.foo.bar` shapes are accepted —
        stdlib heads like `time.` fall out because they never match a
        module prefix."""
        pkg = self.index.pkg_name + "."
        rel = canon[len(pkg):] if canon.startswith(pkg) else canon
        # longest module prefix that exists, remainder is the qualname
        parts = rel.split(".")
        for cut in range(len(parts) - 1, 0, -1):
            mod = ".".join(parts[:cut])
            if mod in self.index.modules:
                qual = ".".join(parts[cut:])
                key = f"{mod}:{qual}"
                if key in self.index.funcs:
                    return {key}
                # a class: constructor
                init = f"{mod}:{qual}.__init__"
                if init in self.index.funcs:
                    return {init}
                return set()
        return set()


class _BodyVisitor(ast.NodeVisitor):
    """Single-function body walk: edges, nondet occurrences, writes."""

    def __init__(self, owner: _ModuleVisitor, fn: FuncInfo,
                 cls: Optional[str]):
        self.o = owner
        self.fn = fn
        self.cls = cls
        self.with_depth = 0        # inside any lock-ish `with`
        self._nested_seq = 0

    # -- helpers ---------------------------------------------------------
    def _protected(self) -> bool:
        return self.with_depth > 0 or self.fn.name == "__init__"

    def _callback_targets(self, arg: ast.expr) -> Set[str]:
        """Resolve a callback argument (name, self.method, partial,
        lambda, nested def reference) to FuncInfo keys."""
        if isinstance(arg, ast.Lambda):
            return {self._spawn_lambda(arg)}
        if isinstance(arg, ast.Call):
            callee = _dotted(arg.func) or ""
            if callee.split(".")[-1] == "partial" and arg.args:
                return self._callback_targets(arg.args[0])
            return set()
        targets, _ = self.o.resolve_callee(arg, self.cls)
        return targets

    def _spawn_lambda(self, node: ast.Lambda) -> str:
        self._nested_seq += 1
        qual = f"{self.fn.qualname}.<lambda@{node.lineno}>"
        key = f"{self.o.mod}:{qual}"
        sub = FuncInfo(key=key, module=self.o.mod, qualname=qual,
                       name=f"<lambda@{node.lineno}>",
                       class_name=self.cls, path=self.fn.path,
                       lineno=node.lineno)
        self.o.index.funcs[key] = sub
        body = _BodyVisitor(self.o, sub, self.cls)
        body.visit(node.body)
        return key

    def _add_edge(self, kind: str, targets: Set[str], text: str,
                  lineno: int) -> None:
        if targets:
            self.fn.calls.append(CallEdge(kind, targets, text, lineno))

    # -- nested defs: own FuncInfo, CALL edge when referenced -----------
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        qual = f"{self.fn.qualname}.{node.name}"
        key = self.o._register_func(node, qual=qual, cls=self.cls)
        # re-key: nested defs are locally referable by bare name
        self.o.local_funcs.setdefault(node.name, key)
        sub = _BodyVisitor(self.o, self.o.index.funcs[key], self.cls)
        for stmt in node.body:
            sub.visit(stmt)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda) -> None:
        # plain lambda: body runs wherever it is eventually called;
        # keep it attached to the enclosing function via a CALL edge
        key = self._spawn_lambda(node)
        self._add_edge(CALL, {key}, "<lambda>", node.lineno)

    # -- with: lock detection -------------------------------------------
    def visit_With(self, node: ast.With) -> None:
        lockish = any(_LOCKISH.search(_dotted(item.context_expr) or
                                      _dotted(getattr(item.context_expr,
                                                      "func", None)) or "")
                      for item in node.items)
        if lockish:
            self.with_depth += 1
        for item in node.items:
            self.visit(item.context_expr)
        for stmt in node.body:
            self.visit(stmt)
        if lockish:
            self.with_depth -= 1

    # -- writes ----------------------------------------------------------
    def _record_write(self, target: ast.expr, via: str,
                      lineno: int) -> None:
        # self.attr = / self.attr[k] = / self.attr.append(...)
        node = target
        if isinstance(node, ast.Subscript):
            via = "subscript"
            node = node.value
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and \
                node.value.id == "self" and self.cls:
            self.fn.writes.append(AttrWrite(
                attr_key=f"{self.cls}.{node.attr}", lineno=lineno,
                protected=self._protected(), via=via))

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._record_write(t, "assign", node.lineno)
            if isinstance(t, ast.Tuple):
                for elt in t.elts:
                    self._record_write(elt, "assign", node.lineno)
        self.visit(node.value)
        for t in node.targets:
            if isinstance(t, ast.Subscript):
                self.visit(t.value)
                self.visit(t.slice)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._record_write(node.target, "augassign", node.lineno)
        self.visit(node.value)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._record_write(node.target, "assign", node.lineno)
            self.visit(node.value)

    # -- calls: edges, schedulers, threads, nondet ----------------------
    def visit_Call(self, node: ast.Call) -> None:
        text = _dotted(node.func) or "<dynamic>"
        attr = text.split(".")[-1]
        canon = self.o._canonical(text) or text

        # nondeterminism occurrences (canonical names)
        kind_src = _nondet_kind(canon)
        if kind_src:
            self.fn.nondet.append(Occurrence(kind_src[0], kind_src[1],
                                             node.lineno))
        elif canon == "random.Random" and not node.args:
            # seeded Random(seed) is deterministic; bare Random() is not
            self.fn.nondet.append(Occurrence(
                "random", "random.Random(unseeded)", node.lineno))

        # threading.Thread(target=fn) -> SPAWN edge
        if canon in ("threading.Thread", "Thread") or \
                text.endswith("threading.Thread"):
            for kw in node.keywords:
                if kw.arg == "target":
                    self._add_edge(SPAWN, self._callback_targets(kw.value),
                                   text, node.lineno)

        # scheduler reroutes: callbacks land back on the crank loop
        if attr == "post" and node.args:
            self._add_edge(POST, self._callback_targets(node.args[0]),
                           text, node.lineno)
        elif attr == "async_wait":
            for arg in node.args:
                self._add_edge(POST, self._callback_targets(arg),
                               text, node.lineno)
        elif attr == "schedule_at" and len(node.args) >= 2:
            self._add_edge(POST, self._callback_targets(node.args[1]),
                           text, node.lineno)
        elif attr == "submit" and "completion" in text:
            # CloseCompletionQueue.submit(seq, fn): fn runs on the
            # completion worker (docs/ANALYSIS.md documents this seam)
            if len(node.args) >= 2:
                self._add_edge(SPAWN, self._callback_targets(node.args[1]),
                               text, node.lineno)

        # mutating method call on self.attr -> write
        if attr in _MUTATORS and isinstance(node.func, ast.Attribute):
            self._record_write(node.func.value, f"mutator:{attr}",
                               node.lineno)

        # plain call edge
        targets, text2 = self.o.resolve_callee(node.func, self.cls)
        self._add_edge(CALL, targets, text2, node.lineno)

        if isinstance(node.func, ast.Attribute):
            # chained receivers can hold further calls: a.b(x).c(y)
            self.visit(node.func.value)
        for arg in node.args:
            self.visit(arg)
        for kw in node.keywords:
            self.visit(kw.value)

    # -- set iteration ---------------------------------------------------
    def visit_For(self, node: ast.For) -> None:
        if _is_set_expr(node.iter):
            self.fn.nondet.append(Occurrence(
                "set-iter", "iteration over unordered set", node.lineno))
        self.generic_visit(node)

    def visit_comprehension_node(self, node) -> None:
        for gen in node.generators:
            if _is_set_expr(gen.iter):
                self.fn.nondet.append(Occurrence(
                    "set-iter", "iteration over unordered set",
                    node.lineno))
        self.generic_visit(node)

    visit_ListComp = visit_comprehension_node
    visit_SetComp = visit_comprehension_node
    visit_DictComp = visit_comprehension_node
    visit_GeneratorExp = visit_comprehension_node


# ----------------------------------------------------------- utilities --

def _dotted(node) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _dotted(node.value)
        return f"{base}.{node.attr}" if base else None
    if isinstance(node, ast.Call):
        return _dotted(node.func)
    return None


def _is_set_expr(node: ast.expr) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
            and node.func.id == "set":
        return True
    return False


# canonical nondeterminism sources -> (kind, canonical-name)
_RANDOM_FNS = frozenset((
    "random", "randint", "randrange", "choice", "choices", "sample",
    "shuffle", "getrandbits", "uniform", "gauss", "normalvariate",
    "betavariate", "expovariate", "randbytes", "triangular",
))


def _nondet_kind(canon: str) -> Optional[Tuple[str, str]]:
    if canon in ("time.time", "time.time_ns"):
        return ("wallclock", canon)
    if canon in ("datetime.now", "datetime.utcnow", "datetime.today",
                 "datetime.datetime.now", "datetime.datetime.utcnow",
                 "datetime.datetime.today"):
        return ("wallclock", canon)
    if canon in ("time.monotonic", "time.monotonic_ns",
                 "time.perf_counter", "time.perf_counter_ns"):
        return ("monotonic", canon)
    if canon == "time.sleep":
        return ("sleep", canon)
    if canon == "os.urandom":
        return ("random", canon)
    parts = canon.split(".")
    if parts[0] == "random" and len(parts) == 2 and \
            parts[1] in _RANDOM_FNS:
        return ("random", canon)
    if parts[0] == "secrets":
        return ("random", canon)
    if canon in ("uuid.uuid1", "uuid.uuid4"):
        return ("random", canon)
    if len(parts) >= 3 and parts[0] in ("np", "numpy") and \
            parts[1] == "random":
        return ("random", "numpy.random." + parts[2])
    return None
