"""AST-based static analysis for determinism and thread-domain safety.

Three passes over the whole package (docs/ANALYSIS.md):

- Pass 1 (`determinism`): nondeterminism sources *reachable from
  consensus roots* through the import/call graph — the reachability
  upgrade over the old `tests/test_determinism_lint.py` directory
  greps, which a `util/` helper imported into `ledger/` sailed past.
- Pass 2 (`domains`): declared thread domains propagated through the
  call graph; cross-domain writes to shared attributes without a
  lock / `clock.post(...)` are flagged — the PR 8 bug class
  (admin HTTP commands racing the crank loop) at analysis time.
- Pass 3 (`registry`): chaos seam names, metric names and config
  knobs cross-checked against their documented registries; drift in
  either direction fails with the missing name.

Entry points: ``scripts/analyze.py`` (CLI, --json artifact mode) and
``run_all()`` here (what the tier-1 tests call).
"""

from __future__ import annotations

import os
from typing import List, Optional

from .astgraph import Finding, PackageIndex, build_index
from .allowlist import Allowlist, load_allowlist, apply_allowlist
from . import determinism, domains, registry

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
DEFAULT_ALLOWLIST = os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "ALLOWLIST")


def run_all(pkg_root: Optional[str] = None,
            repo_root: Optional[str] = None,
            allowlist_path: Optional[str] = DEFAULT_ALLOWLIST,
            passes: tuple = ("determinism", "domains", "registry"),
            ) -> "AnalysisResult":
    """Run the selected passes; returns findings after allowlisting."""
    repo_root = repo_root or REPO_ROOT
    pkg_root = pkg_root or os.path.join(repo_root, "stellar_core_tpu")
    index = build_index(pkg_root)
    raw: List[Finding] = []
    if "determinism" in passes:
        raw.extend(determinism.run(index))
    if "domains" in passes:
        raw.extend(domains.run(index))
    if "registry" in passes:
        raw.extend(registry.run(index, repo_root))
    if allowlist_path and os.path.isfile(allowlist_path):
        allow = load_allowlist(allowlist_path)
    else:
        allow = Allowlist(path=allowlist_path or "<none>", entries={})
    findings, suppressed, meta = apply_allowlist(raw, allow)
    return AnalysisResult(index=index, findings=findings + meta,
                          suppressed=suppressed, allowlist=allow)


class AnalysisResult:
    def __init__(self, index: PackageIndex, findings: List[Finding],
                 suppressed: List[Finding], allowlist: Allowlist):
        self.index = index
        self.findings = findings       # live findings incl. allowlist rot
        self.suppressed = suppressed   # true positives with justification
        self.allowlist = allowlist

    def counts(self) -> dict:
        out: dict = {}
        for f in self.findings:
            out[f.pass_name] = out.get(f.pass_name, 0) + 1
        return out

    def to_json(self) -> dict:
        sup: dict = {}
        for f in self.suppressed:
            sup[f.pass_name] = sup.get(f.pass_name, 0) + 1
        return {
            "findings": [f.to_json() for f in self.findings],
            "suppressed": [f.to_json() for f in self.suppressed],
            "counts": self.counts(),
            "suppressed_counts": sup,
            "allowlist_size": len(self.allowlist.entries),
            "modules": len(self.index.modules),
            "functions": len(self.index.funcs),
        }
