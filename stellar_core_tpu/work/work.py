"""Work with children, the scheduler, and the common combinators.

Reference: src/work/Work.{h,cpp} (children + doWork), WorkScheduler
(cranks from the VirtualClock), WorkSequence, BatchWork (bounded
parallelism), ConditionalWork, WorkWithCallback.
"""

from __future__ import annotations

from typing import Callable, Iterator, List, Optional

from ..util.logging import get_logger
from .basic_work import BasicWork, InternalState, RETRY_A_FEW, State

log = get_logger("Work")


class Work(BasicWork):
    """A work with children: runs children first, then its own doWork
    (reference: Work::onRun crankChild logic)."""

    def __init__(self, app, name: str, max_retries: int = RETRY_A_FEW):
        super().__init__(app, name, max_retries)
        self._children: List[BasicWork] = []

    def add_work(self, child: BasicWork) -> BasicWork:
        child.start_work(self.wake_up)
        self._children.append(child)
        return child

    def has_children(self) -> bool:
        return bool(self._children)

    def all_children_successful(self) -> bool:
        return all(c.get_state() == State.WORK_SUCCESS
                   for c in self._children)

    def all_children_done(self) -> bool:
        return all(c.is_done() for c in self._children)

    def any_child_failed(self) -> bool:
        return any(c.get_state() == State.WORK_FAILURE
                   for c in self._children)

    def on_run(self) -> State:
        # crank internally-RUNNING children; RETRYING/WAITING children
        # wake us via their notify callback when they resume
        progressed = False
        for child in self._children:
            if child._state == InternalState.RUNNING:
                child.crank_work()
                progressed = True
        if self.any_child_failed():
            return self.on_child_failure()
        if not self.all_children_done():
            return State.WORK_RUNNING if progressed else State.WORK_WAITING
        return self.do_work()

    def on_child_failure(self) -> State:
        return State.WORK_FAILURE

    def do_work(self) -> State:
        """Own logic once children are done (reference: Work::doWork)."""
        return State.WORK_SUCCESS

    def on_abort(self) -> None:
        for child in self._children:
            child.shutdown()

    def on_reset(self) -> None:
        self._children = []
        self.do_reset()

    def do_reset(self) -> None:
        pass


class WorkScheduler(BasicWork):
    """Root of the work tree, cranked from the clock (reference:
    work/WorkScheduler.{h,cpp})."""

    def __init__(self, app):
        super().__init__(app, "work-scheduler", max_retries=0)
        self._works: List[BasicWork] = []
        self.start_work()
        app.clock.add_io_poller(self._poll)

    def schedule(self, work: BasicWork) -> BasicWork:
        work.start_work()
        self._works.append(work)
        return work

    def _poll(self) -> int:
        n = 0
        for work in list(self._works):
            if work._state == InternalState.RUNNING:
                work.crank_work()
                n += 1
            if work.is_done():
                self._works.remove(work)
        return n

    def on_run(self) -> State:
        return State.WORK_WAITING

    def shutdown(self) -> None:
        for work in self._works:
            work.shutdown()
        self._works = []
        self.app.clock.remove_io_poller(self._poll)
        super().shutdown()


class WorkSequence(BasicWork):
    """Run works strictly in order (reference: work/WorkSequence)."""

    def __init__(self, app, name: str, sequence: List[BasicWork],
                 max_retries: int = 0):
        super().__init__(app, name, max_retries)
        self._sequence = sequence
        self._index = 0

    def on_run(self) -> State:
        if self._index >= len(self._sequence):
            return State.WORK_SUCCESS
        current = self._sequence[self._index]
        if current._state == InternalState.PENDING:
            current.start_work(self.wake_up)
        if current._state == InternalState.RUNNING:
            current.crank_work()
            return State.WORK_RUNNING
        state = current.get_state()
        if state in (State.WORK_WAITING, State.WORK_RUNNING):
            return State.WORK_WAITING  # retrying/waiting child wakes us
        if state == State.WORK_SUCCESS:
            self._index += 1
            return State.WORK_RUNNING
        return State.WORK_FAILURE

    def on_abort(self) -> None:
        if self._index < len(self._sequence):
            self._sequence[self._index].shutdown()


class BatchWork(Work):
    """Yield-based bounded-parallel spawner (reference: work/BatchWork —
    keeps up to MAX_CONCURRENT children in flight from an iterator)."""

    MAX_CONCURRENT = 8

    def __init__(self, app, name: str):
        super().__init__(app, name, max_retries=0)

    def yield_more_work(self) -> Optional[BasicWork]:
        """Return the next child, or None when exhausted."""
        raise NotImplementedError

    def has_next(self) -> bool:
        raise NotImplementedError

    def do_work(self) -> State:
        # children (if any) all succeeded; top up the batch
        while self.has_next() and \
                len([c for c in self._children if not c.is_done()]) \
                < self.MAX_CONCURRENT:
            nxt = self.yield_more_work()
            if nxt is None:
                break
            self.add_work(nxt)
        if self._children and not self.all_children_done():
            return State.WORK_RUNNING
        if self.has_next():
            return State.WORK_RUNNING
        if self.any_child_failed():
            return State.WORK_FAILURE
        return State.WORK_SUCCESS


class ConditionalWork(BasicWork):
    """Gate a work behind a predicate (reference: work/ConditionalWork)."""

    def __init__(self, app, name: str, condition: Callable[[], bool],
                 work: BasicWork):
        super().__init__(app, name, max_retries=0)
        self._condition = condition
        self._work = work
        self._started = False

    def on_run(self) -> State:
        if not self._started:
            if not self._condition():
                return State.WORK_WAITING
            self._work.start_work(self.wake_up)
            self._started = True
        if self._work._state == InternalState.RUNNING:
            self._work.crank_work()
            return State.WORK_RUNNING
        state = self._work.get_state()
        if state in (State.WORK_WAITING, State.WORK_RUNNING):
            return State.WORK_WAITING
        return state

    def on_abort(self) -> None:
        if self._started:
            self._work.shutdown()


class WorkWithCallback(BasicWork):
    def __init__(self, app, name: str, cb: Callable[[], bool]):
        super().__init__(app, name, max_retries=0)
        self._cb = cb

    def on_run(self) -> State:
        try:
            ok = self._cb()
        except Exception as e:
            log.error("callback work %s failed: %s", self.name, e)
            return State.WORK_FAILURE
        return State.WORK_SUCCESS if ok else State.WORK_FAILURE


def run_work_to_completion(app, work: BasicWork,
                           timeout_virtual: float = 600.0) -> State:
    """Test/CLI helper: schedule and crank until done."""
    scheduler = getattr(app, "work_scheduler", None)
    owns = scheduler is None
    if owns:
        scheduler = WorkScheduler(app)
    scheduler.schedule(work)
    deadline = app.clock.now() + timeout_virtual
    while not work.is_done() and app.clock.now() < deadline:
        if app.clock.crank(False) == 0:
            app.clock.crank(True)
    if owns:
        app.clock.remove_io_poller(scheduler._poll)
    return work.get_state()
