"""Async task-DAG framework (reference: src/work)."""

from .basic_work import (BasicWork, RETRY_A_FEW, RETRY_A_LOT, RETRY_NEVER,
                         RETRY_ONCE, State)
from .work import (BatchWork, ConditionalWork, Work, WorkScheduler,
                   WorkSequence, WorkWithCallback, run_work_to_completion)

__all__ = ["BasicWork", "Work", "WorkScheduler", "WorkSequence",
           "BatchWork", "ConditionalWork", "WorkWithCallback", "State",
           "RETRY_NEVER", "RETRY_ONCE", "RETRY_A_FEW", "RETRY_A_LOT",
           "run_work_to_completion"]
