"""Async task-DAG framework: the BasicWork state machine.

Reference: src/work/BasicWork.{h,cpp} — states PENDING → RUNNING ⇄
WAITING → SUCCESS/FAILURE/ABORTED with RETRYING between failures, retry
policies RETRY_NEVER/ONCE/A_FEW/A_LOT with exponential backoff
(BasicWork.h:96-248). Works crank cooperatively: `crank_work` calls
`on_run` which returns the next internal state; WAITING works are woken
by `wakeUp` (timer or event driven).
"""

from __future__ import annotations

from enum import Enum
from typing import Callable, Optional

from ..util.logging import get_logger
from ..util.timer import VirtualTimer

log = get_logger("Work")

RETRY_NEVER = 0
RETRY_ONCE = 1
RETRY_A_FEW = 5
RETRY_A_LOT = 32


class State(Enum):
    # reference: BasicWork::State (public states)
    WORK_RUNNING = 0
    WORK_WAITING = 1
    WORK_SUCCESS = 2
    WORK_FAILURE = 3
    WORK_ABORTED = 4


class InternalState(Enum):
    PENDING = 0
    RUNNING = 1
    WAITING = 2
    RETRYING = 3
    ABORTING = 4
    ABORTED = 5
    SUCCESS = 6
    FAILURE = 7


class BasicWork:
    def __init__(self, app, name: str, max_retries: int = RETRY_A_FEW):
        self.app = app
        self.name = name
        self.max_retries = max_retries
        self._state = InternalState.PENDING
        self._retries = 0
        self._retry_timer: Optional[VirtualTimer] = None
        self._notify_parent: Optional[Callable[[], None]] = None

    # -------------------------------------------------------------- status --
    def get_state(self) -> State:
        s = self._state
        if s in (InternalState.PENDING, InternalState.RUNNING):
            return State.WORK_RUNNING
        if s == InternalState.RETRYING:
            # dormant until the retry timer fires — anything cranking on
            # "is it RUNNING?" must park and wait for the wake notify, or
            # the event loop busy-spins and virtual time never advances
            # to the retry deadline (reference: BasicWork::getState maps
            # RETRYING to WAITING)
            return State.WORK_WAITING
        if s == InternalState.WAITING:
            return State.WORK_WAITING
        if s == InternalState.ABORTING:
            return State.WORK_RUNNING
        if s == InternalState.SUCCESS:
            return State.WORK_SUCCESS
        if s == InternalState.ABORTED:
            return State.WORK_ABORTED
        return State.WORK_FAILURE

    def is_done(self) -> bool:
        return self._state in (InternalState.SUCCESS, InternalState.FAILURE,
                               InternalState.ABORTED)

    def get_status(self) -> str:
        return f"{self.name}: {self._state.name}"

    # ----------------------------------------------------------- lifecycle --
    def start_work(self, notify_parent: Optional[Callable[[], None]] = None
                   ) -> None:
        assert self._state == InternalState.PENDING
        self._notify_parent = notify_parent
        self._retries = 0
        self.on_reset()
        self._state = InternalState.RUNNING

    def ensure_started(self, notify_parent: Optional[Callable[[], None]]
                       = None) -> None:
        """Idempotent start: begin a still-PENDING work, else no-op —
        for owners that lazily crank a child from several code paths."""
        if self._state == InternalState.PENDING:
            self.start_work(notify_parent)

    def crank_work(self) -> None:
        """One step; only meaningful while RUNNING."""
        if self._state != InternalState.RUNNING:
            return
        try:
            next_state = self.on_run()
        except Exception as e:
            log.error("work %s raised: %s", self.name, e)
            next_state = State.WORK_FAILURE
        self._transition(next_state)

    def shutdown(self) -> None:
        if self.is_done():
            return
        if self._retry_timer is not None:
            self._retry_timer.cancel()
            self._retry_timer = None
        self.on_abort()
        self._state = InternalState.ABORTED
        self._notify()

    def wake_up(self) -> None:
        """WAITING → RUNNING (reference: BasicWork::wakeUp)."""
        if self._state == InternalState.WAITING:
            self._state = InternalState.RUNNING
            self._notify()

    # ------------------------------------------------------------ override --
    def on_run(self) -> State:
        raise NotImplementedError

    def on_reset(self) -> None:
        pass

    def on_abort(self) -> None:
        pass

    def on_failure_raise(self) -> None:
        pass

    def on_success(self) -> None:
        pass

    # ------------------------------------------------------------ internal --
    def _transition(self, next_state: State) -> None:
        if next_state == State.WORK_RUNNING:
            self._state = InternalState.RUNNING
            self._notify()
        elif next_state == State.WORK_WAITING:
            self._state = InternalState.WAITING
        elif next_state == State.WORK_SUCCESS:
            self._state = InternalState.SUCCESS
            self.on_success()
            self._notify()
        elif next_state == State.WORK_ABORTED:
            self._state = InternalState.ABORTED
            self._notify()
        else:  # failure: maybe retry
            if self._retries < self.max_retries:
                self._schedule_retry()
            else:
                self._state = InternalState.FAILURE
                self.on_failure_raise()
                self._notify()

    def _schedule_retry(self) -> None:
        self._state = InternalState.RETRYING
        delay = self.get_retry_delay()
        self._retries += 1
        log.debug("work %s retry %d/%d in %.1fs", self.name, self._retries,
                  self.max_retries, delay)
        timer = VirtualTimer(self.app.clock)
        timer.expires_from_now(delay)

        def fire():
            self._retry_timer = None
            if self._state == InternalState.RETRYING:
                self.on_reset()
                self._state = InternalState.RUNNING
                self._notify()

        timer.async_wait(fire)
        self._retry_timer = timer

    def get_retry_delay(self) -> float:
        """Exponential backoff 1,2,4..32s (reference:
        BasicWork::getRetryETA / computeDelay)."""
        return float(min(2 ** self._retries, 32))

    def _notify(self) -> None:
        if self._notify_parent is not None:
            self._notify_parent()
