"""Per-Application input recorder.

Captures, in arrival order with virtual timestamps, everything that can
steer a node: inbound wire frames (the exact serialize-once recv bytes,
hooked at ``Peer.recv_bytes``), connection establishment/teardown,
external transaction injections, recorded admin commands, and the chaos
engine's injected faults (as node-local matched-hit ordinals, via the
chaos observer hook). The recorded config snapshot + NODE_SEED is
enough to rebuild the node; the log is enough to re-drive it
(replay/replayer.py).

Cost contract: with no recorder attached every hook is one
``getattr(app, "input_recorder", None) is None`` check. Recording
itself is append + CRC per input — the serialize-once cache means no
frame is ever re-encoded.
"""

from __future__ import annotations

from typing import Optional

from ..main.config import Config, QuorumSetConfig
from ..util import chaos
from ..util.logging import get_logger
from . import log as rlog

log = get_logger("Replay")

# Transport-level chaos seams fire in the loopback/tcp delivery path,
# which does not exist on replay (recorded frames already embody their
# effects: a dropped frame was never recorded, a corrupted one was
# recorded corrupted). Node-level seams DO fire on replay and need
# their outcomes scripted.
TRANSPORT_POINTS = ("overlay.send", "overlay.recv")

# Admin commands that mutate node state and must be re-driven on
# replay. `tx` is deliberately absent: its envelope is recorded as an
# INJECT at the submission site, bytes-exact. `generateload` IS here:
# the load generator is deterministic (RNG seeded from
# config.jitter_seed(), synchronous submission inside the route), so
# re-driving the command regenerates byte-identical transactions —
# recording its submissions as INJECTs too would replay them twice.
RECORDED_ADMIN = ("manualclose", "generateload", "upgrades",
                  "maintenance", "setcursor", "dropcursor")


def quorum_set_to_json(q: QuorumSetConfig) -> dict:
    return {"threshold": q.threshold,
            "validators": [v.hex() for v in q.validators],
            "inner_sets": [quorum_set_to_json(s) for s in q.inner_sets]}


def quorum_set_from_json(doc: dict) -> QuorumSetConfig:
    return QuorumSetConfig(
        threshold=int(doc.get("threshold", 0)),
        validators=[bytes.fromhex(v) for v in doc.get("validators", [])],
        inner_sets=[quorum_set_from_json(s)
                    for s in doc.get("inner_sets", [])])


def config_snapshot(cfg: Config) -> dict:
    """The reconstruction recipe: NODE_SEED (the node's whole identity
    — session keys and jitter_seed derive from it), the quorum set, and
    every JSON-able knob that differs from a fresh ``Config()``."""
    defaults = Config()
    knobs = {}
    for key, dval in vars(defaults).items():
        if not key.isupper() or key in ("NODE_SEED", "QUORUM_SET"):
            continue
        val = getattr(cfg, key, dval)
        if val == dval:
            continue
        if _jsonable(val):
            knobs[key] = val
        else:
            log.warning("config snapshot: skipping non-JSON knob %s", key)
    doc = {"knobs": knobs,
           "quorum_set": quorum_set_to_json(cfg.QUORUM_SET)}
    if cfg.NODE_SEED is not None:
        doc["node_seed"] = cfg.NODE_SEED.seed.hex()
    return doc


def config_from_snapshot(doc: dict) -> Config:
    from ..crypto.keys import SecretKey
    cfg = Config()
    for key, val in doc.get("knobs", {}).items():
        setattr(cfg, key, val)
    cfg.QUORUM_SET = quorum_set_from_json(doc.get("quorum_set", {}))
    seed = doc.get("node_seed")
    if seed:
        cfg.NODE_SEED = SecretKey.from_seed(bytes.fromhex(seed))
    return cfg


def _jsonable(val) -> bool:
    if isinstance(val, (bool, int, float, str, type(None))):
        return True
    if isinstance(val, (list, tuple)):
        return all(_jsonable(v) for v in val)
    if isinstance(val, dict):
        return all(isinstance(k, str) and _jsonable(v)
                   for k, v in val.items())
    return False


class InputRecorder:
    """Attach as ``app.input_recorder`` and call :meth:`begin`. Hooked
    call sites check ``active`` before paying anything."""

    def __init__(self, app, path: Optional[str] = None,
                 extras: Optional[dict] = None):
        self.app = app
        self.path = path
        # driver-level determinism settings that live outside Config
        # (e.g. {"defer_completion": false}) — the replayer re-applies
        # the ones it knows after building the Application
        self.extras = dict(extras or {})
        self.active = False
        self.node_hex = app.config.node_id().hex() \
            if app.config.NODE_SEED is not None else ""
        self._writer: Optional[rlog.LogWriter] = None
        self._next_conn = 0
        self._chaos_counts: dict = {}
        self.frames = 0
        self.injects = 0
        self.chaos_records = 0
        self.ticks = 0

    # ----------------------------------------------------------- lifecycle --
    def begin(self) -> None:
        stream = None
        if self.path is not None:
            # create-only, same contract as dumptrace: an admin route
            # must never be a truncate-arbitrary-file primitive
            stream = open(self.path, "xb")
        self._writer = rlog.LogWriter(stream)
        self._writer.write_json(rlog.RT_HEADER, {
            "version": 1,
            "node": self.node_hex,
            "config": config_snapshot(self.app.config),
            "extras": self.extras,
        })
        chaos.add_observer(self._on_chaos)
        self.app.clock.crank_hooks.append(self._on_crank)
        self.active = True

    def finish(self, reason: str = "ok") -> dict:
        """Write the END marker and detach. A killed node never gets
        here — that absence (plus any torn tail) is itself recorded
        state the loader reports."""
        if not self.active:
            return {"records": 0, "bytes": 0}
        lm = self.app.ledger_manager
        self._writer.write_json(rlog.RT_END, {
            "ts": self._now(),
            "reason": reason,
            "lcl_seq": lm.get_last_closed_ledger_num(),
            "lcl_hash": lm.get_last_closed_ledger_hash().hex(),
        })
        self.active = False
        chaos.remove_observer(self._on_chaos)
        self._detach_clock()
        out = {"records": self._writer.records, "bytes": self._writer.bytes,
               "frames": self.frames, "injects": self.injects,
               "chaos": self.chaos_records, "ticks": self.ticks}
        if self.path is not None:
            out["path"] = self.path
            self._writer.close()
        return out

    def abort(self) -> None:
        """Detach WITHOUT an END marker — the simulated-kill path
        (Simulation.crash_node). The log ends mid-stream exactly like a
        real ``kill -9`` leaves it; what was flushed is what replays."""
        if not self.active:
            return
        self.active = False
        chaos.remove_observer(self._on_chaos)
        self._detach_clock()

    def _detach_clock(self) -> None:
        hooks = self.app.clock.crank_hooks
        if self._on_crank in hooks:
            hooks.remove(self._on_crank)

    def to_bytes(self) -> bytes:
        return self._writer.to_bytes()

    def to_log(self) -> rlog.InputLog:
        return rlog.InputLog.from_bytes(self.to_bytes())

    def _now(self) -> float:
        return self.app.clock.now()

    # --------------------------------------------------------------- hooks --
    def record_conn(self, peer, late: bool = False) -> int:
        conn = self._next_conn
        self._next_conn += 1
        peer._replay_conn_id = conn
        doc = {"ts": self._now(), "conn": conn, "role": peer.role.name}
        if late:
            # recording started mid-connection: the handshake was not
            # captured, so this conn cannot be faithfully replayed —
            # flagged for the replayer to refuse loudly
            doc["late"] = True
        self._writer.write_json(rlog.RT_CONN, doc)
        return conn

    def record_frame(self, peer, raw: bytes) -> None:
        conn = getattr(peer, "_replay_conn_id", None)
        if conn is None:
            conn = self.record_conn(peer, late=True)
        self._writer.write(rlog.RT_FRAME, rlog.encode_frame_payload(
            self._now(), conn, raw))
        self.frames += 1

    def record_mac_fail(self, peer) -> None:
        conn = getattr(peer, "_replay_conn_id", None)
        if conn is None:
            return
        self._writer.write(rlog.RT_MACFAIL, rlog._U32.pack(conn))

    def record_pdrop(self, peer, reason: str) -> None:
        conn = getattr(peer, "_replay_conn_id", None)
        if conn is None:
            return
        self._writer.write_json(rlog.RT_PDROP, {
            "ts": self._now(), "conn": conn, "reason": reason})

    def record_inject(self, envelopes, direct: bool = False) -> None:
        """External transaction submission. `envelopes` is a list of
        envelope XDR byte strings (or frames carrying ``.envelope``).
        `direct` marks the single-tx ``recv_transaction`` path (admin
        tx route, loadgen) so replay re-enters through the same
        admission gate."""
        raws = []
        for e in envelopes:
            if isinstance(e, (bytes, bytearray)):
                raws.append(bytes(e))
            else:
                raws.append(e.envelope.to_bytes())
        self._writer.write(rlog.RT_INJECT, rlog.encode_inject_payload(
            self._now(), raws, via=1 if direct else 0))
        self.injects += 1

    def record_admin(self, cmd: str, params: dict) -> None:
        if cmd not in RECORDED_ADMIN:
            return
        self._writer.write_json(rlog.RT_ADMIN, {
            "ts": self._now(), "cmd": cmd,
            "params": {k: str(v) for k, v in (params or {}).items()}})

    def _on_crank(self, phase: int, now: float) -> None:
        """Crank-hook (util.timer.VirtualClock.crank_hooks): one TICK
        per phase boundary. This is what serializes intra-instant
        ordering — an input recorded between a crank's START and
        DISPATCH ticks arrived before that crank's timers fired, one
        recorded after its END came from a driver between cranks."""
        self._writer.write(rlog.RT_TICK,
                           rlog.encode_tick_payload(now, phase))
        self.ticks += 1

    # ------------------------------------------------------ chaos observer --
    def _on_chaos(self, point: str, ctx: dict, kind, spec) -> None:
        """Called by the chaos engine on EVERY fire (injected or not):
        node-local matched-hit ordinals must count pass-throughs too, so
        the replayer's scripted engine lands the same fault on the same
        call."""
        if point in TRANSPORT_POINTS:
            return
        if ctx.get("node") != self.node_hex:
            return
        ordinal = self._chaos_counts.get(point, 0)
        self._chaos_counts[point] = ordinal + 1
        if kind is None:
            return
        doc = {"ts": self._now(), "point": point, "ordinal": ordinal,
               "kind": kind}
        if kind == "delay":
            doc["delay_s"] = spec.delay_ms / 1000.0
        elif kind == "bad_sig_flood":
            doc["burst"] = spec.burst
        self._writer.write_json(rlog.RT_CHAOS, doc)
        self.chaos_records += 1
