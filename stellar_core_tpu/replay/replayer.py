"""Replay driver: rebuild a node from its input log and re-drive it.

The replayed node is a full Application on a fresh VirtualClock in
VIRTUAL_TIME mode. The driver never calls ``crank()``: it re-creates
the live run's crank sequence from the log's TICK phase boundaries —
set virtual time to the recorded instant, drain posted actions at each
START, feed the records captured inside that crank at their stream
positions, run io pollers and due timers at each DISPATCH. Timestamps
alone cannot do this: a whole handshake-and-first-close storm shares
the virtual instant t=0, and whether the ledger trigger fired before
or after a given input arrived is exactly the phase sequence the TICK
records carry. Peers are ``ReplayPeer`` stubs:
the handshake replays from recorded HELLO/AUTH frames, sends are
discarded (their trace instants still fire, which is what the
divergence diff compares), and HMAC verdicts come from the log because
the ephemeral session keys cannot be re-derived. Node-level chaos
outcomes replay from recorded (point, node-local ordinal) pairs via
``ReplayChaosEngine``.

What must come out byte-identical across replays of one log — and,
for the header chain and controller decision log, identical to the
live run: see docs/REPLAY.md.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

from ..main.application import Application
from ..main.config import Config
from ..overlay.peer import Peer
from ..overlay.peer_auth import PeerRole
from ..util import chaos, threads
from ..util.logging import get_logger
from ..util.timer import ClockMode, VirtualClock
from . import log as rlog
from .recorder import TRANSPORT_POINTS, config_from_snapshot

log = get_logger("Replay")


class ReplayError(Exception):
    """The log cannot be faithfully replayed (late-start connection,
    unsupported recorded chaos kind at a node seam, ...)."""


class ReplayPeer(Peer):
    """Peer stub for replay: transport is the input log. Outbound
    bytes are counted and discarded — the messages' trace instants and
    flow-control effects (what the divergence diff actually compares)
    happen before ``_send_bytes``."""

    def __init__(self, overlay, role: PeerRole, conn_id: int):
        super().__init__(overlay, role)
        self.conn_id = conn_id
        self.force_mac_fail = False
        self.sent_frames = 0
        self.sent_bytes = 0

    def _send_bytes(self, raw: bytes) -> None:
        self.sent_frames += 1
        self.sent_bytes += len(raw)

    def _verify_frame_mac(self, v0, frame) -> bool:
        # MAC keys derive from per-connection random nonces + ephemeral
        # session keys — unrecoverable on replay. The recorded verdict
        # (a MACFAIL record after the frame) substitutes for the check;
        # the deterministic sequence-number check still runs upstream.
        if self.force_mac_fail:
            self.force_mac_fail = False
            return False
        return True


class ReplayChaosEngine(chaos.ChaosEngine):
    """Scripted chaos: replays recorded fault outcomes at the same
    node-local matched-hit ordinals the live engine chose, using the
    exact counting rule the recorder used (non-transport points whose
    context names this node)."""

    def __init__(self, node_hex: str, events: List[dict]):
        super().__init__(seed=0, schedule=[])
        self.node_hex = node_hex
        self._counts: Dict[str, int] = {}
        self._script = {(d["point"], d["ordinal"]): d for d in events}
        self.replayed = 0

    def fire(self, point: str, payload, ctx: dict):
        if point in TRANSPORT_POINTS or ctx.get("node") != self.node_hex:
            return payload
        ordinal = self._counts.get(point, 0)
        self._counts[point] = ordinal + 1
        doc = self._script.get((point, ordinal))
        if doc is None:
            return payload
        self.replayed += 1
        kind = doc["kind"]
        key = f"chaos.injected.{kind}"
        self.injected[key] = self.injected.get(key, 0) + 1
        self.log.append((point, -1, ordinal, kind))
        if kind == "io_error":
            raise chaos.ChaosError(f"chaos injected io_error at {point}")
        if kind == "crash":
            raise chaos.SimulatedCrash(point, ctx)
        if kind == "churn":
            raise chaos.SimulatedChurn(point, ctx)
        if kind == "drop":
            return chaos.DROP
        if kind == "reorder":
            return chaos.REORDER
        if kind == "fail":
            return chaos.FAIL
        if kind == "hang":
            return chaos.HANG
        if kind == "equivocate":
            return chaos.EQUIVOCATE
        if kind == "bad_sig_flood":
            return chaos.BadSigBurst(int(doc.get("burst", 8)))
        if kind == "delay":
            return chaos.Delay(payload, float(doc.get("delay_s", 0.001)))
        # corrupt/malformed mangle bytes with the live engine's per-spec
        # RNG state, which a single-node replay cannot reconstruct; at
        # transport seams the mangled bytes were recorded anyway, and
        # node seams reject them loudly instead of diverging silently
        raise ReplayError(
            f"unsupported recorded chaos kind {kind!r} at node seam "
            f"{point} (docs/REPLAY.md: what is not captured)")


class ReplayResult:
    """Everything the determinism assertions compare."""

    def __init__(self, node: str):
        self.node = node
        self.crashed = False
        self.crash_point: Optional[str] = None
        self.lcl_seq = 0
        self.lcl_hash = ""
        self.header_chain: List[str] = []      # hashes for seq 2..lcl
        self.decisions: List[dict] = []        # controller decision log
        self.trace: List[tuple] = []           # normalized events
        self.end_matches: Optional[bool] = None  # vs the recorded END
        self.torn_tail = 0
        self.chaos_replayed = 0
        self.frames_fed = 0

    def decisions_json(self) -> str:
        return json.dumps(self.decisions, sort_keys=True)


def normalize_trace(recorder) -> List[tuple]:
    """Project a FlightRecorder buffer onto its deterministic core:
    ``(phase, name, canonical-args-json, correlation-id)``. Timestamps
    are wall-clock (perf_counter) and thread ids are process facts —
    both legally differ between byte-identical runs, so they are
    normalized away; everything else must match event-for-event."""
    out = []
    for ph, name, _ts, _tid, args, cid in list(recorder._buf):
        out.append((ph, name,
                    json.dumps(args, sort_keys=True, default=str)
                    if args is not None else "", cid or ""))
    return out


def first_divergence(a: List[tuple], b: List[tuple],
                     context: int = 8) -> Optional[dict]:
    """Align two normalized traces and pinpoint the first diverging
    event, with the shared evidence chain leading up to it. ``None``
    means byte-identical (same events, same order, same args)."""
    n = min(len(a), len(b))
    for i in range(n):
        if a[i] != b[i]:
            return {
                "index": i,
                "a": list(a[i]),
                "b": list(b[i]),
                "chain": [list(e) for e in a[max(0, i - context):i]],
            }
    if len(a) != len(b):
        longer, which = (a, "a") if len(a) > len(b) else (b, "b")
        return {
            "index": n,
            "a": list(a[n]) if len(a) > n else None,
            "b": list(b[n]) if len(b) > n else None,
            "tail_only_in": which,
            "chain": [list(e) for e in longer[max(0, n - context):n]],
        }
    return None


class NodeReplayer:
    """One replay run. Build → :meth:`run` → :class:`ReplayResult`."""

    def __init__(self, ilog: rlog.InputLog, trace: bool = True,
                 trace_capacity: Optional[int] = None):
        self.ilog = ilog
        self.trace = trace
        self.trace_capacity = trace_capacity
        self.clock = VirtualClock(ClockMode.VIRTUAL_TIME)
        self.cfg = self._build_config()
        self.app: Optional[Application] = None
        self.conns: Dict[int, ReplayPeer] = {}
        self._inputs: List[rlog.LogRecord] = [
            r for r in ilog.records
            if r.rtype in (rlog.RT_CONN, rlog.RT_FRAME, rlog.RT_INJECT,
                           rlog.RT_ADMIN, rlog.RT_PDROP, rlog.RT_TICK)]
        self._next = 0
        self.result = ReplayResult(ilog.node)
        end = ilog.end_record()
        self._end_doc = end.doc if end is not None else None

    def _build_config(self) -> Config:
        cfg = config_from_snapshot(self.ilog.header.get("config", {}))
        # never reattach to the live node's storage: the replayed node
        # rebuilds its whole state from genesis + inputs
        cfg.DATABASE = "sqlite3://:memory:"
        cfg.BUCKET_DIR_PATH = None
        return cfg

    # ------------------------------------------------------------ plumbing --
    def _make_peer(self, rec: rlog.LogRecord) -> None:
        doc = rec.doc or {}
        if doc.get("late"):
            raise ReplayError(
                "connection %d was established before recording "
                "started — its handshake is not in the log" % rec.conn)
        peer = ReplayPeer(self.app.overlay_manager,
                          PeerRole[doc["role"]], rec.conn)
        self.conns[rec.conn] = peer
        self.app.overlay_manager.add_pending_peer(peer)
        peer.connect_handler()

    def _feed(self, rec: rlog.LogRecord) -> None:  # thread-domain: crank
        if rec.rtype == rlog.RT_CONN:
            self._make_peer(rec)
        elif rec.rtype == rlog.RT_FRAME:
            peer = self.conns.get(rec.conn)
            if peer is None:
                raise ReplayError(f"frame for unknown conn {rec.conn}")
            from ..overlay.peer import PeerState
            if peer.state == PeerState.CLOSING:
                return
            if rec.mac_invalid:
                peer.force_mac_fail = True
            peer.recv_bytes(rec.data)
            self.result.frames_fed += 1
        elif rec.rtype == rlog.RT_INJECT:
            self._inject(rec.frames or [],
                         (rec.doc or {}).get("via", 0))
        elif rec.rtype == rlog.RT_ADMIN:
            doc = rec.doc or {}
            self.app.command_handler.handle(doc.get("cmd", ""),
                                            doc.get("params") or {})
        elif rec.rtype == rlog.RT_PDROP:
            peer = self.conns.get(rec.conn)
            if peer is not None:
                peer.drop((rec.doc or {}).get("reason", "replayed drop"))

    def _inject(self, raws: List[bytes], via: int) -> None:
        from ..tx.frame import make_frame
        from ..xdr.transaction import TransactionEnvelope
        frames = []
        net = self.cfg.network_id()
        for raw in raws:
            env = TransactionEnvelope.from_bytes(raw)
            frames.append(make_frame(env, net))
        if via == 1:
            # direct submission path — rolls the surge-shed gate
            # exactly like the live tx route / loadgen did
            for frame in frames:
                self.app.herder.recv_transaction(frame)
        else:
            self.app.herder.recv_transactions(frames)

    # ----------------------------------------------------------------- run --
    def run(self) -> ReplayResult:  # thread-domain: crank
        if threads.CHECK:
            # the replay driver IS the logical main thread — it drives
            # the same phases crank() would, just from the log
            threads.bind("crank")
        ilog = self.ilog
        self.result.torn_tail = ilog.torn_tail
        self.app = Application.create(self.clock, self.cfg)
        extras = ilog.header.get("extras", {})
        if extras.get("defer_completion") is False:
            # the recorded run forced the close-completion tail inline
            # (driver-level determinism setting, not a Config knob)
            self.app.ledger_manager.defer_completion = False
        # connections recorded before the first TICK predate the first
        # crank: the driver wired them before the node started, so they
        # are re-created before start(), in the same order
        while self._next < len(self._inputs) and \
                self._inputs[self._next].rtype == rlog.RT_CONN:
            self._make_peer(self._inputs[self._next])
            self._next += 1
        # the scripted chaos engine installs BEFORE start: the live
        # engine was installed before the node started, so seam fires
        # during genesis close count toward the recorded ordinals
        chaos_events = [r.doc for r in ilog.records
                        if r.rtype == rlog.RT_CHAOS]
        engine = None
        if chaos_events:
            engine = ReplayChaosEngine(ilog.node, chaos_events)
            chaos.install(engine)
        try:
            self.app.start()
            if self.trace:
                self.app.flight_recorder.start(
                    capacity=self.trace_capacity)
            self._drive()
        except chaos.SimulatedCrash as cr:
            self.result.crashed = True
            self.result.crash_point = cr.point
        finally:
            if engine is not None:
                self.result.chaos_replayed = engine.replayed
                chaos.uninstall()
        self._collect()
        self._teardown()
        return self.result

    def _drive(self) -> None:  # thread-domain: crank
        """Re-create the recorded crank sequence. Each TICK boundary
        runs its phase on the replay clock at the recorded instant:
        START drains posted actions, DISPATCH runs the replayed app's
        own io pollers (process/work polls — the live node's ran right
        before its dispatch too) and then fires due timers, JUMP
        advances time mid-crank and fires again. Non-TICK records feed
        at their stream position: between START and DISPATCH that is
        the live action/poller window, after END it is a driver acting
        between cranks — the exact interleaving timestamps can't carry
        because whole handshake storms share one virtual instant."""
        clock = self.clock
        try:
            while self._next < len(self._inputs):
                rec = self._inputs[self._next]
                self._next += 1
                if rec.rtype != rlog.RT_TICK:
                    self._feed(rec)
                    continue
                if rec.ts > clock.now():
                    clock.set_virtual_time(rec.ts)
                if rec.phase == rlog.TICK_START:
                    clock.drain_actions()
                elif rec.phase in (rlog.TICK_DISPATCH, rlog.TICK_JUMP):
                    if rec.phase == rlog.TICK_DISPATCH:
                        clock.poll_io()
                    clock.dispatch_due()
                # TICK_END is a pure boundary marker
        except chaos.SimulatedCrash as cr:
            self.result.crashed = True
            self.result.crash_point = cr.point

    def _collect(self) -> None:
        app, res = self.app, self.result
        lm = app.ledger_manager
        res.lcl_seq = lm.get_last_closed_ledger_num()
        res.lcl_hash = lm.get_last_closed_ledger_hash().hex()
        for seq in range(2, res.lcl_seq + 1):
            row = app.database.query_one(
                "SELECT ledgerhash FROM ledgerheaders WHERE ledgerseq=?",
                (seq,))
            res.header_chain.append(
                bytes(row[0]).hex() if row is not None else "")
        res.decisions = [dict(d) for d in app.controller.decisions]
        if self.trace:
            res.trace = normalize_trace(app.flight_recorder)
        if self._end_doc is not None:
            res.end_matches = (
                res.lcl_seq == int(self._end_doc.get("lcl_seq", -1))
                and res.lcl_hash == self._end_doc.get("lcl_hash", ""))

    def _teardown(self) -> None:
        app = self.app
        if not self.result.crashed:
            try:
                app.shutdown()
                return
            except BaseException:   # noqa: BLE001 — fall through to burial
                log.exception("replay shutdown failed; burying instead")
        # a crashed replay is buried the way Simulation.crash_node
        # buries a crashed node: silence timers, drop completion tails,
        # close storage — never the graceful drain
        from ..main.application import AppState
        app.state = AppState.APP_STOPPING_STATE
        try:
            if app.flight_recorder.active:
                app.flight_recorder.stop()
            app.ledger_manager.discard_pending_completion()
            app.herder.shutdown()
            app.maintainer.stop()
            app.work_scheduler.shutdown()
            app.process_manager.shutdown()
            app.query_service.shutdown()
            app.snapshots.shutdown()
            app.bucket_manager.shutdown()
            app.database.close()
            if app._tmp_bucket_dir is not None:
                app._tmp_bucket_dir.cleanup()
        except BaseException:       # noqa: BLE001 — dead is dead
            log.exception("ignoring error while burying replayed node")


def replay_log(ilog: rlog.InputLog, trace: bool = True,
               trace_capacity: Optional[int] = None) -> ReplayResult:
    """Replay one node's input log end-to-end and return the
    :class:`ReplayResult` carrying everything the determinism
    assertions compare."""
    return NodeReplayer(ilog, trace=trace,
                        trace_capacity=trace_capacity).run()
