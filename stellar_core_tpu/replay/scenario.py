"""The canonical recorded scenario: a 4-node seeded chaos run with
every node's inputs captured for replay.

This is the tier-1 round-trip fixture AND the `bench.py --replay`
workload: record once live, replay each node twice, assert the header
chains and controller decision logs match the live run byte-for-byte
and the two replays' flight-recorder traces are zero-diff.

The chaos schedule is deliberately RESTRICTED to fault classes that
replay faithfully (docs/REPLAY.md, "what is not captured"):

- transport faults (the n1→n2 ``corrupt``) need no scripting — the
  mangled bytes were recorded verbatim at ``recv_bytes`` and the HMAC
  verdict rides a MACFAIL record;
- node-seam faults are limited to kinds the scripted replay engine can
  reproduce from (point, ordinal) alone: ``drop``/``reorder`` on
  ``overlay.message`` and the ``crash`` at a close-phase boundary.
  No ``io_error`` on the device seams (the scenario runs without the
  device stack) and no no-context seams (``history.get`` etc. fire
  without a ``node`` key, so neither side can attribute them).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ..crypto.keys import SecretKey, clear_verify_cache
from ..herder.tx_queue import AddResult
from ..main.config import QuorumSetConfig
from ..simulation.chaos import _crank_with_crashes
from ..simulation.simulation import Simulation
from ..simulation.topologies import _seeds
from ..tx.frame import make_frame
from ..util import chaos
from ..util.chaos import ChaosEngine, FaultSpec
from ..util.logging import get_logger
from ..xdr.ledger_entries import Asset, AssetType, LedgerKey
from ..xdr.transaction import (DecoratedSignature, Memo, MemoType,
                               MuxedAccount, Operation, OperationType,
                               PaymentOp, Preconditions, PreconditionType,
                               Transaction, TransactionEnvelope,
                               TransactionV1Envelope, _OperationBody,
                               _TxExt)
from ..xdr.types import EnvelopeType
from . import log as rlog
from .replayer import normalize_trace

log = get_logger("Replay")

DEFAULT_TARGET = 8
FIRST_LOADED_LEDGER = 3      # ledger 2 closes clean before load starts


def restricted_schedule(node_ids: List[bytes]) -> List[FaultSpec]:
    n1, n2, n3 = (nid.hex() for nid in node_ids[1:4])
    return [
        FaultSpec("overlay.message", "drop", start=30, count=20,
                  match={"node": n1}),
        FaultSpec("overlay.message", "reorder", start=8, count=15,
                  match={"node": n2}),
        # transport corruption INTO node 2: recorded verbatim, the MAC
        # failure verdict rides a MACFAIL record
        FaultSpec("overlay.recv", "corrupt", start=30, count=2,
                  match={"node": n2, "peer": n1}),
        # crash node 3 mid-close: its log ends mid-stream (no END)
        FaultSpec("ledger.close.crash.applyTx", "crash", start=4,
                  count=1, match={"node": n3}),
    ]


class _RecordingRootPayer:
    """simulation/chaos.py's deterministic per-ledger root payment,
    with each node's submission recorded as an INJECT: one identical
    tx to every alive node, fresh frame per node."""

    def __init__(self, sim: Simulation, network_id: bytes):
        self.sim = sim
        self.network_id = network_id
        self.key = SecretKey.from_seed(network_id)
        app = sim.apps()[0]
        from ..ledger.ledger_txn import LedgerTxn
        from ..xdr.types import PublicKey
        with LedgerTxn(app.ledger_manager.root) as ltx:
            le = ltx.load_without_record(LedgerKey.account(
                PublicKey.ed25519(self.key.public_key().raw)))
            self.seq = le.data.value.seqNum
        self.submitted = 0

    def submit_one(self) -> None:
        self.seq += 1
        muxed = MuxedAccount.from_ed25519(self.key.public_key().raw)
        tx = Transaction(
            sourceAccount=muxed, fee=100, seqNum=self.seq,
            cond=Preconditions(PreconditionType.PRECOND_NONE),
            memo=Memo(MemoType.MEMO_NONE),
            operations=[Operation(sourceAccount=None, body=_OperationBody(
                OperationType.PAYMENT, PaymentOp(
                    destination=muxed,
                    asset=Asset(AssetType.ASSET_TYPE_NATIVE),
                    amount=1)))],
            ext=_TxExt(0))
        env = TransactionEnvelope(
            EnvelopeType.ENVELOPE_TYPE_TX,
            TransactionV1Envelope(tx=tx, signatures=[]))
        probe = make_frame(env, self.network_id)
        sig = self.key.sign(probe.contents_hash())
        env.value.signatures = [DecoratedSignature(
            hint=self.key.public_key().hint(), signature=sig)]
        raw = env.to_bytes()
        for app in self.sim.alive_apps():
            rec = getattr(app, "input_recorder", None)
            if rec is not None and rec.active:
                rec.record_inject([raw])
            frame = make_frame(TransactionEnvelope.from_bytes(raw),
                               self.network_id)
            res = app.herder.recv_transactions([frame])[0]
            if res not in (AddResult.ADD_STATUS_PENDING,
                           AddResult.ADD_STATUS_DUPLICATE):
                raise RuntimeError(f"replay scenario tx rejected: {res}")
        self.submitted += 1


class ScenarioResult:
    """The live run's ground truth plus every node's input log."""

    def __init__(self):
        self.node_ids: List[bytes] = []
        self.logs: Dict[str, rlog.InputLog] = {}       # node hex -> log
        self.chains: Dict[str, List[str]] = {}         # survivors only
        self.decisions: Dict[str, list] = {}
        self.traces: Dict[str, list] = {}              # normalized
        self.lcl: Dict[str, tuple] = {}                # (seq, hash hex)
        self.crashed: List[str] = []
        self.target = 0


def run_recorded_scenario(seed: int = 7,
                          target: int = DEFAULT_TARGET,
                          trace: bool = True) -> ScenarioResult:
    """Run the recorded chaos scenario live and return the logs plus
    everything replay must reproduce."""
    # cold process-wide verify cache, exactly like a chaos leg: a warm
    # cache changes which admissions enqueue verifies → chaos ordinals
    clear_verify_cache()

    def configure(cfg):
        cfg.ARTIFICIALLY_SET_CLOSE_TIME_FOR_TESTING = 1
        cfg.ARTIFICIALLY_PESSIMIZE_MERGES_FOR_TESTING = True

    # built by hand rather than topologies.core: recorders must attach
    # BEFORE connections wire, or the handshakes are off-log and every
    # conn is flagged unreplayable
    sim = Simulation()
    seeds = _seeds(4, b"core")
    ids = [s.public_key().raw for s in seeds]
    qset = QuorumSetConfig(threshold=3, validators=ids)
    for s in seeds:
        sim.add_node(s, qset, configure=configure)
    for app in sim.apps():
        # inline close completion: deterministic chaos hit ordinals
        app.ledger_manager.defer_completion = False
    sim.record_all(extras={"defer_completion": False})
    for i in range(4):
        for j in range(i + 1, 4):
            sim.add_pending_connection(ids[i], ids[j])

    res = ScenarioResult()
    res.node_ids = ids
    res.target = target
    engine = ChaosEngine(seed, restricted_schedule(ids))
    chaos.install(engine)
    try:
        sim.start_all_nodes()
        if trace:
            sim.start_tracing()
        crashed: List[bytes] = []
        crashed += _crank_with_crashes(
            sim, lambda: sim.have_alive_externalized(2), timeout=60.0)
        if not sim.have_alive_externalized(2):
            raise RuntimeError("network never closed ledger 2")
        payer = _RecordingRootPayer(sim, sim.apps()[0].config.network_id())
        for seq in range(FIRST_LOADED_LEDGER, target + 1):
            payer.submit_one()
            crashed += _crank_with_crashes(
                sim, lambda s=seq: sim.have_alive_externalized(s),
                timeout=120.0)
            if not sim.have_alive_externalized(seq):
                raise RuntimeError(
                    f"liveness lost: survivors stalled before {seq}")
        res.crashed = [nid.hex() for nid in crashed]
        # orderly END for survivors; the crashed node's recorder was
        # aborted mid-stream by crash_node — its log has no END marker
        sim.finish_recording()
        for nid, app in sim.nodes.items():
            hx = nid.hex()
            rec = app.input_recorder
            res.logs[hx] = rec.to_log()
            if nid in sim.crashed:
                continue
            lm = app.ledger_manager
            res.lcl[hx] = (lm.get_last_closed_ledger_num(),
                           lm.get_last_closed_ledger_hash().hex())
            chain = []
            for seq in range(2, res.lcl[hx][0] + 1):
                row = app.database.query_one(
                    "SELECT ledgerhash FROM ledgerheaders "
                    "WHERE ledgerseq=?", (seq,))
                chain.append(bytes(row[0]).hex() if row else "")
            res.chains[hx] = chain
            res.decisions[hx] = [dict(d) for d in app.controller.decisions]
            if trace:
                res.traces[hx] = normalize_trace(app.flight_recorder)
    finally:
        chaos.uninstall()
        try:
            sim.stop_all_nodes()
        except Exception:       # noqa: BLE001 — teardown best-effort
            log.exception("ignoring scenario teardown error")
    return res
