"""Whole-node deterministic record/replay (ISSUE 18).

A node's externally-visible nondeterminism is its *inputs*: inbound
wire frames, driver/admin injections, and the chaos engine's injected
faults. Everything else — timers, SCP, ledger close — is a pure
function of those inputs on the VirtualClock (the determinism analyzer
proves the consensus paths wall-clock- and iteration-order-clean).
Recording the inputs therefore makes every run an offline unit test:

- ``replay.log``      — the crash-tolerant framed input-log format
- ``replay.recorder`` — per-Application InputRecorder (hooked at
  Peer.recv_bytes / connect_handler / drop, chaos observers, and the
  external tx/admin submission sites)
- ``replay.replayer`` — rebuilds the node from the recorded config
  snapshot and re-feeds the log on a fresh VirtualClock
- ``replay.scenario`` — the recorded 4-node seeded chaos scenario the
  tier-1 round-trip test and ``bench.py --replay`` share

All four modules are in the determinism analyzer's STRICT scope
(analysis/determinism.py): a wall-clock or RNG read anywhere in this
package is a lint finding, because replay-of-a-replay must be
byte-stable. docs/REPLAY.md is the contract.
"""

from .log import (InputLog, LogRecord, LogWriter, RT_ADMIN, RT_CHAOS,
                  RT_CONN, RT_END, RT_FRAME, RT_INJECT, RT_MACFAIL,
                  RT_PDROP)
from .recorder import InputRecorder
from .replayer import ReplayResult, first_divergence, normalize_trace, replay_log

__all__ = [
    "InputLog", "LogRecord", "LogWriter", "InputRecorder",
    "ReplayResult", "replay_log", "normalize_trace", "first_divergence",
    "RT_CONN", "RT_FRAME", "RT_MACFAIL", "RT_INJECT", "RT_ADMIN",
    "RT_CHAOS", "RT_PDROP", "RT_END",
]
