"""Input-log file format: framed, CRC-guarded, torn-tail tolerant.

Layout::

    MAGIC (8 bytes, b"SCTPRL01")
    record*        where record = <u8 type><u32 len><u32 crc32>payload

The CRC covers the payload only. The format is append-only and every
record is flushed as written, so a ``kill -9`` can leave at most one
*torn tail*: a final record whose length field outruns the file or
whose CRC does not match. The loader detects the tear, counts it,
logs it loudly, and returns everything before it — a crashed node's
log still replays up to the tear (docs/REPLAY.md). A tear that is NOT
at EOF is indistinguishable from corruption and is treated the same
way: stop there, loudly.

Record payloads (little-endian):

- ``CONN``    JSON ``{ts, conn, role}`` — a transport established;
  ``conn`` numbers peers in connect order and FRAME records refer to it
- ``FRAME``   ``<d ts><I conn>`` + raw wire frame, verbatim — the exact
  bytes ``Peer.recv_bytes`` saw (serialize-once: no re-encode)
- ``MACFAIL`` ``<I conn>`` — the immediately preceding FRAME on that
  conn failed HMAC verification live; replay (which cannot re-derive
  the ephemeral session MAC keys) must force the same verdict
- ``INJECT``  ``<d ts><u8 via>`` + u32 count + (u32 len + envelope
  bytes)* — an external transaction submission (admin tx route,
  loadgen, a scenario driver), recorded at the submission site. ``via``
  picks the replay admission path: 0 = batched
  ``herder.recv_transactions``, 1 = direct ``herder.recv_transaction``
  (which rolls the controller's surge-shed gate — a different path
  must not replay through the other one)
- ``ADMIN``   JSON ``{ts, cmd, params}`` — a recorded admin command
- ``CHAOS``   JSON ``{ts, point, ordinal, kind, ...}`` — the chaos
  engine injected a fault at this node-local matched-hit ordinal
- ``PDROP``   JSON ``{ts, conn, reason}`` — the peer was dropped
  (protocol drops replay naturally and make this a no-op; driver drops
  like a crashed partner only exist in the log)
- ``END``     JSON ``{ts, reason, lcl_seq, lcl_hash}`` — orderly
  finish marker; absent after a hard kill (that is the torn tail)
- ``TICK``    ``<d ts><u8 phase>`` — a crank phase boundary of the
  node's VirtualClock (phase values = ``util.timer.CRANK_*``). These
  carry the clock-advance and timer-firing order: many inputs share
  one virtual instant (the whole t=0 handshake-and-first-close storm),
  and only the phase sequence says whether a timer fired before or
  after a given input arrived. Records between START and DISPATCH
  happened in that crank's action/poller window; records between END
  and the next START came from a driver running between cranks
"""

from __future__ import annotations

import json
import struct
import zlib
from typing import List, Optional

from ..util.logging import get_logger

log = get_logger("Replay")

MAGIC = b"SCTPRL01"

# The header rides as record type 0 so the frame walker needs no
# special case; it is always the first record.
RT_HEADER = 0
RT_CONN = 1
RT_FRAME = 2
RT_MACFAIL = 3
RT_INJECT = 4
RT_ADMIN = 5
RT_CHAOS = 6
RT_PDROP = 7
RT_END = 8
RT_TICK = 9

# TICK phase wire values — same numbers as util.timer.CRANK_* (the
# recorder writes the hook's phase argument verbatim)
TICK_START = 0
TICK_DISPATCH = 1
TICK_JUMP = 2
TICK_END = 3

_RECORD_HDR = struct.Struct("<BII")
_FRAME_HDR = struct.Struct("<dI")
_TS = struct.Struct("<d")
_U32 = struct.Struct("<I")
_TICK = struct.Struct("<dB")

_NAMES = {RT_HEADER: "HEADER",
          RT_CONN: "CONN", RT_FRAME: "FRAME", RT_MACFAIL: "MACFAIL",
          RT_INJECT: "INJECT", RT_ADMIN: "ADMIN", RT_CHAOS: "CHAOS",
          RT_PDROP: "PDROP", RT_END: "END", RT_TICK: "TICK"}


class LogRecord:
    """One parsed record. ``doc`` holds the JSON payload for JSON
    record types; ``ts``/``conn``/``data``/``frames`` are decoded for
    the binary ones."""

    __slots__ = ("rtype", "ts", "conn", "data", "frames", "doc",
                 "mac_invalid", "phase")

    def __init__(self, rtype: int, ts: float = 0.0, conn: int = 0,
                 data: bytes = b"", frames: Optional[list] = None,
                 doc: Optional[dict] = None, phase: int = 0):
        self.rtype = rtype
        self.ts = ts
        self.conn = conn
        self.data = data
        self.frames = frames
        self.doc = doc
        self.phase = phase
        # set by the loader when a MACFAIL record follows this FRAME
        self.mac_invalid = False

    @property
    def name(self) -> str:
        return _NAMES.get(self.rtype, str(self.rtype))

    def __repr__(self):
        return f"<LogRecord {self.name} ts={self.ts:.6f} conn={self.conn}>"


def encode_record(rtype: int, payload: bytes) -> bytes:
    return _RECORD_HDR.pack(rtype, len(payload),
                            zlib.crc32(payload) & 0xFFFFFFFF) + payload


class LogWriter:
    """Streams records to a binary file object (flushed per record, so
    a kill leaves at most a torn tail) or buffers them in memory when
    constructed without a stream."""

    def __init__(self, stream=None):
        self._stream = stream
        self._chunks: List[bytes] = []
        self.records = 0
        self.bytes = len(MAGIC)
        if stream is not None:
            stream.write(MAGIC)
            stream.flush()
        else:
            self._chunks.append(MAGIC)

    def write(self, rtype: int, payload: bytes) -> None:
        raw = encode_record(rtype, payload)
        if self._stream is not None:
            self._stream.write(raw)
            self._stream.flush()
        else:
            self._chunks.append(raw)
        self.records += 1
        self.bytes += len(raw)

    def write_json(self, rtype: int, doc: dict) -> None:
        self.write(rtype, json.dumps(doc, sort_keys=True).encode())

    def to_bytes(self) -> bytes:
        if self._stream is not None:
            raise ValueError("LogWriter is file-backed; read the file")
        return b"".join(self._chunks)

    def close(self) -> None:
        if self._stream is not None:
            self._stream.close()
            self._stream = None


class InputLog:
    """A parsed input log: header doc + record list + tear accounting."""

    def __init__(self, header: dict, records: List[LogRecord],
                 torn_tail: int = 0, torn_bytes: int = 0):
        self.header = header
        self.records = records
        # count of records lost to a torn/corrupt tail (0 or 1 for a
        # clean kill; >1 only if garbage follows the tear)
        self.torn_tail = torn_tail
        self.torn_bytes = torn_bytes

    @property
    def node(self) -> str:
        return self.header.get("node", "")

    def frames(self) -> List[LogRecord]:
        return [r for r in self.records if r.rtype == RT_FRAME]

    def end_record(self) -> Optional[LogRecord]:
        for r in reversed(self.records):
            if r.rtype == RT_END:
                return r
        return None

    @classmethod
    def from_bytes(cls, data: bytes) -> "InputLog":
        if data[:len(MAGIC)] != MAGIC:
            raise ValueError("not an input log (bad magic)")
        pos = len(MAGIC)
        records: List[LogRecord] = []
        torn = 0
        torn_bytes = 0
        while pos < len(data):
            if pos + _RECORD_HDR.size > len(data):
                torn, torn_bytes = 1, len(data) - pos
                break
            rtype, length, crc = _RECORD_HDR.unpack_from(data, pos)
            body_at = pos + _RECORD_HDR.size
            if body_at + length > len(data):
                torn, torn_bytes = 1, len(data) - pos
                break
            payload = data[body_at:body_at + length]
            if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
                # CRC mismatch: a torn record whose length bytes were
                # already on disk, or mid-file corruption — either way
                # nothing after this point is trustworthy
                torn, torn_bytes = 1, len(data) - pos
                break
            records.append(_decode(rtype, payload))
            pos = body_at + length
        if torn:
            log.warning(
                "input log torn tail: %d undecodable byte(s) dropped "
                "after %d good record(s) — replaying up to the tear",
                torn_bytes, len(records))
        if not records or records[0].rtype != RT_HEADER:
            raise ValueError("input log has no header record")
        header = records.pop(0).doc or {}
        _mark_mac_failures(records)
        return cls(header, records, torn_tail=torn, torn_bytes=torn_bytes)

    @classmethod
    def load(cls, path: str) -> "InputLog":
        with open(path, "rb") as f:
            return cls.from_bytes(f.read())


def _decode(rtype: int, payload: bytes) -> LogRecord:
    if rtype == RT_FRAME:
        ts, conn = _FRAME_HDR.unpack_from(payload)
        return LogRecord(RT_FRAME, ts=ts, conn=conn,
                         data=payload[_FRAME_HDR.size:])
    if rtype == RT_MACFAIL:
        (conn,) = _U32.unpack_from(payload)
        return LogRecord(RT_MACFAIL, conn=conn)
    if rtype == RT_TICK:
        ts, phase = _TICK.unpack_from(payload)
        return LogRecord(RT_TICK, ts=ts, phase=phase)
    if rtype == RT_INJECT:
        (ts,) = _TS.unpack_from(payload)
        pos = _TS.size
        via = payload[pos]
        pos += 1
        (count,) = _U32.unpack_from(payload, pos)
        pos += _U32.size
        frames = []
        for _ in range(count):
            (n,) = _U32.unpack_from(payload, pos)
            pos += _U32.size
            frames.append(payload[pos:pos + n])
            pos += n
        rec = LogRecord(RT_INJECT, ts=ts, frames=frames)
        rec.doc = {"via": via}
        return rec
    # JSON records (header, CONN, ADMIN, CHAOS, PDROP, END)
    doc = json.loads(payload)
    rec = LogRecord(rtype, ts=float(doc.get("ts", 0.0)),
                    conn=int(doc.get("conn", 0)), doc=doc)
    return rec


def _mark_mac_failures(records: List[LogRecord]) -> None:
    """Fold MACFAIL markers onto the FRAME they qualify: the recorder
    writes MACFAIL immediately after the frame whose HMAC check failed
    live, so replay can force the same drop without the session keys."""
    last_frame: dict = {}
    for r in records:
        if r.rtype == RT_FRAME:
            last_frame[r.conn] = r
        elif r.rtype == RT_MACFAIL:
            f = last_frame.get(r.conn)
            if f is not None:
                f.mac_invalid = True


def encode_frame_payload(ts: float, conn: int, raw: bytes) -> bytes:
    return _FRAME_HDR.pack(ts, conn) + raw


def encode_tick_payload(ts: float, phase: int) -> bytes:
    return _TICK.pack(ts, phase)


def encode_inject_payload(ts: float, frames: List[bytes],
                          via: int = 0) -> bytes:
    parts = [_TS.pack(ts), bytes([via]), _U32.pack(len(frames))]
    for raw in frames:
        parts.append(_U32.pack(len(raw)))
        parts.append(raw)
    return b"".join(parts)
