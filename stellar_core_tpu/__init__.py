"""stellar_core_tpu — a TPU-native re-implementation of the stellar-core validator.

A replicated state machine maintaining a cryptographic ledger in consensus with
peers (reference: /root/reference README.md:12-14), rebuilt framework-first:

- The node logic (consensus, ledger, overlay, storage) is deterministic,
  single-logical-thread Python + native C++ components — mirroring the
  reference's single-main-thread + worker-pool architecture
  (docs/architecture.md:24-36).
- The performance hot path — Ed25519 signature verification — has a batch
  TPU backend: a jit+vmap'd JAX kernel (SHA-512 host-side, point decompression
  and double-scalar multiplication over edwards25519 on-device), sharded over a
  `jax.sharding.Mesh` via shard_map for multi-chip data parallelism.
  Selected per-config (`SIGNATURE_VERIFY_BACKEND = "cpu" | "tpu"`), identical
  accept/reject semantics to the strict CPU path.

Layer map (mirrors SURVEY.md §1):
  util/    -> VirtualClock, Scheduler, logging, metrics, caches     (layer 1)
  crypto/  -> keys, hashing, strkey, verify cache + backends        (layer 2)
  ops/     -> JAX/TPU kernels (ed25519 field/point/verify)          (layer 2, TPU)
  parallel/-> mesh/sharding for batch verification                  (layer 2, TPU)
  xdr/     -> XDR codec + protocol types                            (layer 3)
  database/, bucket/ -> persistence                                 (layer 4)
  ledger/, tx/, invariant/ -> ledger state machine                  (layer 5)
  scp/, herder/ -> consensus                                        (layer 7)
  overlay/ -> p2p                                                   (layer 8)
  work/, process/, history/, catchup/ -> history & catchup          (layer 9)
  main/    -> Application, Config, CommandHandler, CommandLine      (layer 10)
  simulation/ -> in-process multi-node networks, LoadGenerator      (layer 11)
"""

__version__ = "0.1.0"
