"""Node self-check.

Reference: main/ApplicationUtils.cpp selfCheck (:487-517) — four phases:
(1) history archive reachability / HAS sanity, (2) bucket↔database
consistency, (3) ledger-header chain integrity in the local DB,
(4) crypto benchmark (SecretKey::benchmarkOpsPerSecond — the hook where
the TPU backend's verifies/sec gets compared to CPU).
"""

from __future__ import annotations

import time
from typing import List, Tuple

from ..crypto.keys import PubKeyUtils, SecretKey
from ..ledger.ledger_manager import ledger_header_hash
from ..util.logging import get_logger
from ..xdr.ledger import LedgerHeader

log = get_logger("default")


def self_check(app, crypto_bench_seconds: float = 0.2,
               max_headers: int = 0) -> Tuple[bool, dict]:
    """max_headers > 0 bounds the header-chain scan to the most recent N
    rows — used by the AUTOMATIC_SELF_CHECK_PERIOD timer so a periodic
    check cannot stall the single-threaded crank loop for an unbounded
    full-table rehash."""
    report = {}
    ok = True

    # 1. history archives configured + writable state
    archives = app.history_manager.archives
    report["archives"] = {
        "configured": len(archives),
        "writable": sum(1 for a in archives if a.has_put()),
    }

    # 2. bucket list hash matches the LCL header
    lcl = app.ledger_manager.get_last_closed_ledger_header()
    bl_hash = app.bucket_manager.snapshot_ledger_hash(lcl.ledgerVersion)
    bucket_ok = bytes(lcl.bucketListHash) == bl_hash
    report["bucket_list_consistent"] = bucket_ok
    ok = ok and bucket_ok

    # 3. header chain in the DB
    if max_headers > 0:
        rows = app.database.query_all(
            "SELECT ledgerseq, ledgerhash, prevhash, data FROM ("
            "SELECT * FROM ledgerheaders ORDER BY ledgerseq DESC LIMIT ?)"
            " ORDER BY ledgerseq", (max_headers,))
    else:
        rows = app.database.query_all(
            "SELECT ledgerseq, ledgerhash, prevhash, data FROM "
            "ledgerheaders ORDER BY ledgerseq")
    chain_ok = True
    prev_hash = None
    prev_seq = None
    for seq, lhash, phash, data in rows:
        header = LedgerHeader.from_bytes(bytes(data))
        if ledger_header_hash(header) != bytes(lhash):
            chain_ok = False
            break
        if prev_seq is not None and seq == prev_seq + 1 and \
                bytes(phash) != prev_hash:
            chain_ok = False
            break
        prev_hash, prev_seq = bytes(lhash), seq
    report["header_chain_ok"] = chain_ok
    report["headers_checked"] = len(rows)
    ok = ok and chain_ok

    # 4. crypto benchmark (reference: benchmarkOpsPerSecond)
    sk = SecretKey.from_seed(b"\x42" * 32)
    msg = b"self-check benchmark message...."
    sig = sk.sign(msg)
    pub = sk.public_key().raw
    n = 0
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < crypto_bench_seconds:
        PubKeyUtils.verify_sig(pub, sig, msg)
        n += 1
    elapsed = time.perf_counter() - t0
    report["verify_per_second_cpu"] = int(n / elapsed)

    # 5. TPU batch-backend benchmark when configured (BASELINE.md
    # procedure: self-check reports verifies/sec for BOTH backends)
    if getattr(app.config, "SIGNATURE_VERIFY_BACKEND", "") == "tpu":
        try:
            import numpy as np
            from ..ops.verifier import TpuBatchVerifier
            nb = 1024
            pubs = np.broadcast_to(
                np.frombuffer(pub, dtype=np.uint8), (nb, 32)).copy()
            sigs = np.broadcast_to(
                np.frombuffer(sig, dtype=np.uint8), (nb, 64)).copy()
            msgs = [msg] * nb
            v = TpuBatchVerifier(perf=getattr(app, "perf", None))
            res = v.verify_batch(pubs, sigs, msgs)   # compile + warm
            if not res.all():
                ok = False
                report["tpu_backend_ok"] = False
            else:
                t0 = time.perf_counter()
                v.verify_batch(pubs, sigs, msgs)
                report["verify_per_second_tpu_batch"] = int(
                    nb / (time.perf_counter() - t0))
                report["tpu_backend_ok"] = True
        except Exception as e:           # noqa: BLE001 — report, not crash
            report["tpu_backend_ok"] = False
            report["tpu_backend_error"] = str(e)
            ok = False

    # 6. coalescing verify service warmup (ISSUE 4): push a small batch
    # of fresh signatures through submit → flush → collect so the
    # service's dispatch path is exercised (and warm) before live
    # traffic needs it, and report its occupancy/queue-wait stats
    svc = getattr(app, "verify_service", None)
    if svc is not None:
        try:
            # size the batch to the device cutoff: a smaller batch
            # would take the native bypass and leave the service's
            # device bucket cold for the first live flush
            n_warm = max(4, getattr(app.batch_verifier,
                                    "_device_min_batch", 4))
            items = []
            for i in range(n_warm):
                # 32-byte messages: the tx-hash hot path (msg32
                # kernel) is what live flood flushes will hit
                m = (b"self-check vs %04d" % i).ljust(32, b".")
                items.append((pub, sk.sign(m), m))
            futs = svc.submit_many(items)
            svc_ok = all(f.result() for f in futs)
            report["verify_service_ok"] = svc_ok
            report["verify_service"] = svc.stats()
            ok = ok and svc_ok
        except Exception as e:           # noqa: BLE001 — report, not crash
            report["verify_service_ok"] = False
            report["verify_service_error"] = str(e)
            ok = False

    # 7. backend supervisor state (ops/backend_supervisor.py): degraded
    # mode (OPEN/HALF_OPEN) is an operational fact, not a check
    # failure — the whole point is that the node keeps validating —
    # but it must be visible in the report the operator reads
    bv = getattr(app, "batch_verifier", None)
    if bv is not None and hasattr(bv, "breaker_state"):
        report["verify_backend"] = bv.status()
        report["verify_backend_degraded"] = bv.state != "CLOSED"

    report["ok"] = ok
    return ok, report
