"""Key-value node state in the `storestate` table.

Reference: src/main/PersistentState.{h,cpp} — enumerated entries keyed by
name, storing the last closed ledger, the history archive state, SCP
state per slot, the DB initialization marker, and rebuild flags.
"""

from __future__ import annotations

from enum import Enum
from typing import Optional


class StateEntry(Enum):
    # reference: PersistentState.h kLastClosedLedger etc.
    LAST_CLOSED_LEDGER = "lastclosedledger"
    HISTORY_ARCHIVE_STATE = "historyarchivestate"
    DATABASE_SCHEMA = "databaseschema"
    NETWORK_PASSPHRASE = "networkpassphrase"
    LEDGER_UPGRADES = "ledgerupgrades"
    REBUILD_LEDGER = "rebuildledger"
    LAST_SCP_DATA = "lastscpdata"     # + slot suffix
    HOT_ARCHIVE_STATE = "hotarchivestate"  # protocol-23 state archival
    # highest ledger whose deferred close-completion segment (tx-history
    # rows, meta) committed; < LCL after a crash mid-completion
    LAST_CLOSE_COMPLETED = "lastclosecompleted"


class PersistentState:
    def __init__(self, db):
        self._db = db

    def get(self, entry: StateEntry, suffix: str = "") -> Optional[str]:
        row = self._db.query_one(
            "SELECT state FROM storestate WHERE statename = ?",
            (entry.value + suffix,))
        return row[0] if row else None

    def set(self, entry: StateEntry, value: str, suffix: str = "") -> None:
        self._db.execute(
            "INSERT OR REPLACE INTO storestate (statename, state) "
            "VALUES (?, ?)", (entry.value + suffix, value))

    def drop(self, entry: StateEntry, suffix: str = "") -> None:
        self._db.execute(
            "DELETE FROM storestate WHERE statename = ?",
            (entry.value + suffix,))

    def has(self, entry: StateEntry, suffix: str = "") -> bool:
        return self.get(entry, suffix) is not None
